"""Chaos engine: deterministic fault injection over simulated networks
(simulation/chaos.py — ISSUE 7).

Tier-1 scenarios run the standard scripted suite on small core-4
topologies and assert the full safety contract after every run: zero
forks among honest survivors (header-chain AND bucket-hash agreement),
convergence after the faults clear (time-to-heal is finite), and — the
determinism contract — the same (topology, scenario, seed) reproduces
identical per-node ledger-hash sequences.  The ``slow`` tier repeats
the key scenarios at 50-validator tiered/org scale (what
tools/chaos_bench.py persists as CHAOS_BENCH_r11.json).
"""
import random

import pytest

from stellar_core_tpu.overlay.peer import LinkChaos
from stellar_core_tpu.simulation import core, hierarchical_quorum
from stellar_core_tpu.simulation.chaos import (
    STANDARD_SCENARIOS, ChaosEngine, run_scenario, run_standard_scenario)


def _core4(tmpdir, **kw):
    return lambda: core(4, persist_dir=str(tmpdir), MANUAL_CLOSE=False, **kw)


def _run(tmpdir, scenario, seed=11, duration=15.0, n=4):
    return run_standard_scenario(_core4(tmpdir), scenario, seed=seed,
                                 n_nodes=n, duration=duration)


# -- the tier-1 scenario suite (core-4) -------------------------------------


def test_partition_heal_no_fork(tmp_path):
    rep = _run(tmp_path / "a", "partition_heal")
    assert rep["fork_check"] == "pass"
    assert rep["counters"]["cut"] > 0, "partition never cut a message"
    assert rep["time_to_heal_s"] < 60.0
    assert rep["ledgers_closed"] >= 5


def test_crash_restore_mid_close(tmp_path):
    rep = _run(tmp_path / "a", "crash_restore")
    assert rep["fork_check"] == "pass"
    # the restarted node rejoined and externalized the convergence
    # target (converged() requires ALL honest nodes, crash victim
    # included, to agree on it)
    assert rep["time_to_heal_s"] < 60.0
    assert rep["ledgers_closed"] >= 5


def test_equivocator_no_fork(tmp_path):
    rep = _run(tmp_path / "a", "equivocator", duration=18.0)
    assert rep["fork_check"] == "pass"
    assert rep["byzantine"] == 1
    assert rep["counters"]["equivocations"] > 0, \
        "equivocator never emitted a conflicting variant"
    assert rep["time_to_heal_s"] < 60.0


def test_stale_replay_discarded(tmp_path):
    rep = _run(tmp_path / "a", "stale_replay", duration=18.0)
    assert rep["fork_check"] == "pass"
    assert rep["counters"]["stale_replayed"] > 0
    assert rep["counters"]["stale_discarded"] > 0, \
        "honest nodes never discarded a stale envelope"


def test_laggard_recovers(tmp_path):
    rep = _run(tmp_path / "a", "laggard")
    assert rep["fork_check"] == "pass"
    assert rep["counters"]["delayed"] > 0
    assert rep["time_to_heal_s"] < 60.0


def test_flaky_links_counters_surface(tmp_path):
    rep = _run(tmp_path / "a", "flaky_links", duration=15.0)
    assert rep["fork_check"] == "pass"
    c = rep["counters"]
    assert c["dropped"] + c["damaged"] + c["duplicated"] > 0, \
        "probabilistic link chaos never fired"
    assert c["reconnects"] > 0, \
        "MAC-stream damage should force link re-dials"


def test_chaos_seed_determinism(tmp_path):
    """The contract the whole engine exists for: same (topology,
    scenario, seed) => identical per-node ledger-hash sequences."""
    fps = [_run(tmp_path / d, "flaky_links", seed=42, duration=12.0)
           ["fingerprint"] for d in ("a", "b")]
    assert fps[0] == fps[1]


def test_different_seeds_diverge(tmp_path):
    """Different chaos seeds must actually produce different runs —
    otherwise the determinism test above proves nothing."""
    a = _run(tmp_path / "a", "flaky_links", seed=1, duration=12.0)
    b = _run(tmp_path / "b", "flaky_links", seed=2, duration=12.0)
    assert a["fingerprint"] != b["fingerprint"]


# -- engine units -----------------------------------------------------------


def test_link_chaos_deterministic_faults():
    """LinkChaos decisions are a pure function of (rng, message seq):
    two identically-seeded links make identical drop/damage/duplicate
    choices over any message stream."""
    outcomes = []
    for _ in range(2):
        rng = random.Random(7)
        chaos = LinkChaos(rng, drop=0.3, damage=0.2, duplicate=0.2)
        row = []
        for _ in range(64):
            if chaos.rng.random() < chaos.drop:
                row.append("drop")
            elif chaos.rng.random() < chaos.duplicate:
                row.append("dup")
            else:
                row.append("pass")
        outcomes.append(row)
    assert outcomes[0] == outcomes[1]
    assert "drop" in outcomes[0] and "dup" in outcomes[0]


def test_loopback_chaos_counters(tmp_path):
    """overlay.chaos.* counters tick in /metrics for every injected
    fault (JSON registry; Prometheus shares the same registry)."""
    sim = core(2)
    a, b = list(sim.nodes)
    app = sim.nodes[a]
    p1, p2 = sim.link_peers(a, b)
    # cut: total loss
    p1.set_chaos(LinkChaos(random.Random(1), cut=True))
    p1.transport_write(b"\x00" * 8)
    assert app.metrics.counter("overlay.chaos.cut").count == 1
    # certain drop
    p1.set_chaos(LinkChaos(random.Random(1), drop=1.0))
    p1.transport_write(b"\x00" * 8)
    assert app.metrics.counter("overlay.chaos.dropped").count == 1
    # certain duplicate + damage
    p1.set_chaos(LinkChaos(random.Random(1), duplicate=1.0, damage=1.0))
    p1.transport_write(b"\x00" * 8)
    assert app.metrics.counter("overlay.chaos.duplicated").count == 1
    assert app.metrics.counter("overlay.chaos.damaged").count == 1
    p1.set_chaos(None)
    snap = app.metrics.snapshot()
    assert snap["overlay.chaos.dropped"]["count"] == 1


def test_legacy_set_damage_still_works():
    sim = core(2)
    a, b = list(sim.nodes)
    p1, _ = sim.link_peers(a, b)
    p1.set_damage(drop=1.0, seed=3)
    p1.transport_write(b"\x00" * 8)
    assert p1.app.metrics.counter("overlay.chaos.dropped").count == 1


def test_partition_is_total_and_heal_restores(tmp_path):
    """Unit-level: partition() cuts exactly the cross-group links and
    heal() restores them (no consensus involved)."""
    sim = core(4)
    ids = list(sim.nodes)
    chaos = ChaosEngine(sim, seed=5)
    chaos.partition([ids[:2], ids[2:]])
    cut = {k for k, pol in chaos.policies.items() if pol.cut}
    assert len(cut) == 4  # 2x2 cross links of the full core-4 mesh
    for (x, y) in cut:
        assert (x in ids[:2]) != (y in ids[:2])
    for p in sim.link_peers(*next(iter(cut))):
        assert p.chaos is not None and p.chaos.cut
    chaos.heal()
    assert not any(pol.cut for pol in chaos.policies.values())
    for p in sim.link_peers(*next(iter(cut))):
        assert p.chaos is None


def test_hierarchical_quorum_topology():
    """Tiered/org builder: org-majority-of-majorities qset on every
    node, sparse connectivity (org meshes + leader mesh + backup
    links) rather than full mesh."""
    sim = hierarchical_quorum(3, 3)
    assert len(sim.nodes) == 9
    app = next(iter(sim.nodes.values()))
    qs = app.config.QUORUM_SET
    assert qs["threshold"] == 3 and not qs["validators"]
    assert len(qs["inner_sets"]) == 3
    assert all(s["threshold"] == 3 for s in qs["inner_sets"])
    # 3 orgs x C(3,2) intra + C(3,2) leader links + 3 backup links
    assert len(sim.topology) == 9 + 3 + 3
    full_mesh = 9 * 8 // 2
    assert len(sim.topology) < full_mesh


def test_run_scenario_rejects_fork_scripts(tmp_path):
    """A scenario that permanently halts a quorum can't converge; the
    runner must fail it loudly rather than report success."""
    sim_factory = _core4(tmp_path / "a")

    def kill_three(c):
        for nid in list(c.sim.nodes)[:3]:
            c.crash(nid)
            # drop the recipe's node_dir so restore in the epilogue
            # cannot resurrect them -> convergence must time out
            c.sim.node_recipes[nid]["node_dir"] = None

    with pytest.raises((AssertionError, Exception)):
        run_scenario(sim_factory, seed=3,
                     events=[(2.0, "kill 3 of 4", kill_three)],
                     duration=6.0, label="kill-quorum",
                     converge_timeout=10.0)


# -- network-scale (slow tier; chaos_bench persists the evidence) -----------


@pytest.mark.slow
@pytest.mark.parametrize("scenario", ["partition_heal", "equivocator"])
def test_tiered50_scenarios(tmp_path, scenario):
    rep = run_standard_scenario(
        lambda: hierarchical_quorum(10, 5, persist_dir=str(tmp_path),
                                    MANUAL_CLOSE=False),
        scenario, seed=11, n_nodes=50, duration=12.0)
    assert rep["fork_check"] == "pass"
    assert rep["nodes"] == 50
    assert rep["fork_comparisons"] > 1000
    assert rep["time_to_heal_s"] < 90.0


@pytest.mark.slow
def test_tiered50_seed_determinism(tmp_path):
    fps = []
    for d in ("a", "b"):
        rep = run_standard_scenario(
            lambda: hierarchical_quorum(10, 5,
                                        persist_dir=str(tmp_path / d),
                                        MANUAL_CLOSE=False),
            "crash_restore", seed=11, n_nodes=50, duration=10.0)
        fps.append(rep["fingerprint"])
    assert fps[0] == fps[1]


def test_standard_scenarios_complete():
    assert set(STANDARD_SCENARIOS) == {
        "partition_heal", "crash_restore", "laggard", "flaky_links",
        "stale_replay", "equivocator"}


def test_slot_bracket_uncaps_when_not_tracking():
    """A node > LEDGER_VALIDITY_BRACKET slots behind must still ingest
    live traffic once it knows it lost sync (the reference's
    maxLedgerSeq only caps while TRACKING) — otherwise a long
    partition/outage wedges it at its stale LCL forever."""
    from stellar_core_tpu.herder.herder import (
        LEDGER_VALIDITY_BRACKET, HerderState)

    sim = core(2)
    sim.start_all_nodes()
    app = next(iter(sim.nodes.values()))
    h = app.herder
    # before the first externalize there is no tracked slot to anchor
    # the upper bound on: a cold node must be able to learn how far
    # behind it is, so no cap applies yet
    _, hi0 = h.scp_slot_bracket()
    assert hi0 > 2 ** 62
    assert sim.close_ledger()
    lo, hi = h.scp_slot_bracket()
    # the cap anchors on the newest slot consensus externalized (the
    # tracked slot), NOT the local LCL: a catching-up node's LCL parks
    # at the restore point while live traffic runs 1000+ slots ahead
    assert hi == max(app.ledger_manager.last_closed_seq(),
                     h._tracking_slot) + LEDGER_VALIDITY_BRACKET
    h.state = HerderState.NOT_TRACKING
    lo2, hi2 = h.scp_slot_bracket()
    assert lo2 == lo
    assert hi2 > 2 ** 62


# -- pipelined-close crash window (ISSUE 11 satellite) -----------------------


def test_crash_in_pipeline_window_recovers_to_durable_lcl(tmp_path):
    """Kill a validator BETWEEN seal and deferred commit (ledger N's
    tail parked on the close-pipeline worker): its durable state is
    N-1, and the restart-from-state path must come back at that LCL —
    the last *durably committed* ledger — then rejoin and converge with
    the survivors without forking."""
    import threading

    sim = core(4, persist_dir=str(tmp_path), MANUAL_CLOSE=False,
               PIPELINED_CLOSE=True, PIPELINED_CLOSE_EAGER_DRAIN=False)
    sim.start_all_nodes()
    victim = list(sim.nodes)[0]
    assert sim.crank_until(lambda: sim.have_all_externalized(3), 60.0)
    vapp = sim.nodes[victim]
    pipeline = vapp.ledger_manager.pipeline
    assert pipeline.enabled and pipeline.stats["tails"] > 0

    # park the victim's NEXT tail: the close seals and the herder keeps
    # going, but the durable commit never lands — the pipeline window
    hold = threading.Event()
    pipeline._hold = hold
    target = vapp.ledger_manager.last_closed_seq() + 1
    assert sim.crank_until(
        lambda: vapp.ledger_manager.last_closed_seq() >= target, 60.0)
    seq_sealed = vapp.ledger_manager.last_closed_seq()
    durable = vapp.database.execute(
        "SELECT MAX(ledgerseq) FROM ledgerheaders").fetchone()[0]
    assert durable == seq_sealed - 1, \
        "expected exactly one sealed-but-uncommitted ledger (depth-1)"

    # crash INSIDE the window: the parked tail must never commit
    pipeline.crash_abandon()
    sim.crash_node(victim)

    restarted = sim.restart_node(victim)
    assert restarted.ledger_manager.last_closed_seq() == seq_sealed - 1, \
        "restart must land on the last DURABLY committed LCL"

    # rejoin under live traffic and converge with the survivors
    goal = max(app.ledger_manager.last_closed_seq()
               for app in sim.alive_nodes().values()) + 2
    assert sim.crank_until(lambda: sim.have_all_externalized(goal),
                           120.0), "crash victim never rejoined"
    sim.assert_no_forks()
    for nid in list(sim.alive_nodes()):
        sim.nodes[nid].stop_node()
