"""Persisted peer DB + bans (ref src/overlay/PeerManager.h,
BanManager.h)."""
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.overlay.peer_manager import BanManager, PeerManager
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock


def _app(db=":memory:", **kw):
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config(DATABASE=db, **kw))
    app.start()
    return app


def test_peer_records_and_backoff():
    app = _app()
    pm = PeerManager(app)
    pm.ensure_exists("127.0.0.1", 1111)
    pm.ensure_exists("127.0.0.1", 2222)
    assert len(pm.peers_to_try(10)) == 2
    # failures push the peer past its backoff window
    pm.on_connect_failure("127.0.0.1", 1111)
    assert pm.peers_to_try(10) == [("127.0.0.1", 2222)]
    # success resets
    pm.on_connect_success("127.0.0.1", 1111)
    assert len(pm.peers_to_try(10)) == 2


def test_failures_back_off_but_never_exclude_forever():
    app = _app()
    pm = PeerManager(app)
    pm.ensure_exists("10.0.0.1", 1)
    for _ in range(12):
        pm.on_connect_failure("10.0.0.1", 1)
    # inside the backoff window: not offered
    assert ("10.0.0.1", 1) not in pm.peers_to_try(10)
    # far in the future the peer becomes connectable again (capped
    # exponential backoff, no permanent exclusion)
    pm._now = lambda: 10**12
    assert ("10.0.0.1", 1) in pm.peers_to_try(10)


def test_bans_persist(tmp_path):
    db = str(tmp_path / "peers.db")
    app = _app(db=db)
    bm = BanManager(app)
    nid = b"\x09" * 32
    bm.ban(nid)
    assert bm.is_banned(nid)
    app.database.close()
    app2 = _app(db=db)
    bm2 = BanManager(app2)
    assert bm2.is_banned(nid)
    bm2.unban(nid)
    assert not bm2.is_banned(nid)


def test_overlay_manager_loads_bans(tmp_path):
    from stellar_core_tpu.overlay.manager import OverlayManager

    db = str(tmp_path / "om.db")
    app = _app(db=db)
    app.overlay_manager = OverlayManager(app)
    nid = b"\x0a" * 32
    app.overlay_manager.ban_peer(nid)
    app.database.close()

    app2 = _app(db=db)
    app2.overlay_manager = OverlayManager(app2)
    assert nid in app2.overlay_manager.banned_peers
    app2.overlay_manager.unban_peer(nid)
    assert not app2.overlay_manager.ban_manager.is_banned(nid)
