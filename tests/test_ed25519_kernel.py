"""Batched ed25519 verify kernel vs the executable spec + CPU backend.

Model: the reference pins verify semantics with libsodium; here the batch
kernel must be bit-identical in accept/reject to crypto/ed25519_ref (the
executable spec) and crypto/ed25519 (the CPU backend) — including tampered
signatures, non-canonical encodings, and s >= L malleability rejects
(SURVEY.md §7 hard parts).

One compiled call covers the whole matrix (single jit, one batch).
"""
import numpy as np
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.crypto import ed25519 as ed
from stellar_core_tpu.crypto import ed25519_ref as ref


def _mk(n):
    pubs, sigs, msgs = [], [], []
    for i in range(n):
        sk = SecretKey(sha256(b"kern%d" % i))
        m = sha256(b"kmsg%d" % i)
        pubs.append(bytearray(sk.public_key().raw))
        sigs.append(bytearray(sk.sign(m)))
        msgs.append(bytearray(m))
    return pubs, sigs, msgs


L = 2**252 + 27742317777372353535851937790883648493


@pytest.fixture(scope="module")
def batch_results():
    from stellar_core_tpu.ops.ed25519_kernel import verify_batch

    pubs, sigs, msgs = _mk(10)
    # case 0: valid (untouched)
    # case 1: tampered signature R
    sigs[1][0] ^= 1
    # case 2: tampered message
    msgs[2][0] ^= 1
    # case 3: tampered pubkey
    pubs[3][0] ^= 1
    # case 4: s >= L (add L to s, still < 2^256 — malleability reject)
    s = int.from_bytes(bytes(sigs[4][32:]), "little") + L
    sigs[4][32:] = s.to_bytes(32, "little")
    # case 5: non-canonical R encoding (y = p, encodes as canonical 0 + high)
    p = 2**255 - 19
    sigs[5][:32] = p.to_bytes(32, "little")
    # case 6: non-canonical pubkey (y >= p)
    pubs[6][:32] = (p + 1).to_bytes(32, "little")
    # case 7: all-zero signature
    sigs[7][:] = bytes(64)
    # case 8: swap of valid sig from another message
    sigs[8] = bytearray(bytes(sigs[9]))
    # case 9: valid (control)

    pk = np.frombuffer(b"".join(bytes(p_) for p_ in pubs), np.uint8
                       ).reshape(10, 32)
    sg = np.frombuffer(b"".join(bytes(s_) for s_ in sigs), np.uint8
                       ).reshape(10, 64)
    mg = np.frombuffer(b"".join(bytes(m_) for m_ in msgs), np.uint8
                       ).reshape(10, 32)
    got = np.asarray(verify_batch(pk, sg, mg))
    want_ref = [ref.verify(bytes(pubs[i]), bytes(sigs[i]), bytes(msgs[i]))
                for i in range(10)]
    want_cpu = [ed.raw_verify(bytes(pubs[i]), bytes(sigs[i]), bytes(msgs[i]))
                for i in range(10)]
    return got, want_ref, want_cpu


def test_kernel_matches_spec(batch_results):
    got, want_ref, _ = batch_results
    assert got.tolist() == want_ref


def test_kernel_matches_cpu_backend(batch_results):
    got, _, want_cpu = batch_results
    assert got.tolist() == want_cpu


def test_expected_accept_pattern(batch_results):
    got, _, _ = batch_results
    # only the untouched cases are valid
    assert got.tolist() == [True] + [False] * 8 + [True]
