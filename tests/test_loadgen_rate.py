"""LoadGenerator rate mode: timer-driven tx/s generation (ref
LoadGenerator.h:28-36 — generateLoad's txRate scheduling; ROADMAP open
item 6).  The timer enqueues generation on the app's fair scheduler, so
sustained load shares the crank with consensus — which is what makes the
soak behaviors (queue aging, ban, rebroadcast) reachable at all.
"""
import pytest

from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.main.http_server import CommandHandler
from stellar_core_tpu.simulation.load_generator import LoadGenerator
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock


def _rate_app(**kw):
    app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                      test_config(**kw))
    app.start()
    app.herder.manual_close()
    return app


def _seed_accounts(app, n):
    handler = CommandHandler(app)
    code, body = handler.handle("generateload",
                                {"mode": "create", "accounts": str(n)})
    assert code == 200, body
    app.herder.manual_close()
    return handler


def test_rate_run_submits_at_rate():
    app = _rate_app()
    handler = _seed_accounts(app, 20)
    code, body = handler.handle(
        "generateload", {"mode": "pay", "rate": "20", "duration": "3"})
    assert code == 200 and body["rate_run"]["running"], body
    lg = app._load_generator
    # crank virtual time through the run; close each virtual second
    for _ in range(8):
        app.crank(block=True)
        app.herder.manual_close()
        if not lg.rate_status()["running"]:
            break
    st = lg.rate_status()
    assert not st["running"]
    # 20 tx/s x 3s, quantized per 1s tick
    assert 40 <= st["submitted"] <= 60, st
    # everything was admitted and applied (rate below capacity)
    assert st["status_counts"] == {"0": st["submitted"]}, st
    assert app.herder.tx_queue.size() == 0
    code, body = handler.handle("generateload", {"mode": "status"})
    assert code == 200 and body["rate_run"]["submitted"] == st["submitted"]


def test_rate_run_stop_route():
    app = _rate_app()
    handler = _seed_accounts(app, 10)
    code, body = handler.handle(
        "generateload", {"mode": "pay", "rate": "5", "duration": "60"})
    assert code == 200 and body["rate_run"]["running"]
    code, body = handler.handle("generateload", {"mode": "stop"})
    assert code == 200 and not body["rate_run"]["running"]
    submitted = body["rate_run"]["submitted"]
    for _ in range(3):
        app.crank(block=True)
    assert app._load_generator.rate_status()["submitted"] == submitted


def test_rate_requires_accounts():
    app = _rate_app()
    handler = CommandHandler(app)
    code, body = handler.handle(
        "generateload", {"mode": "pay", "rate": "5"})
    assert code == 400, body


@pytest.mark.slow
def test_rate_mode_soak_50_closes():
    """>=50-close soak at a rate ABOVE close capacity: the queue must
    fill, age, evict-and-ban, and the node must keep closing at a
    bounded queue size — the sustained-load behaviors rate mode exists
    to reach (ROADMAP item 6)."""
    app = _rate_app(UPGRADE_DESIRED_MAX_TX_SET_SIZE=100)
    handler = _seed_accounts(app, 50)
    seq0 = app.ledger_manager.last_closed_seq()
    # 150 tx/s vs ~100 ops/close at one close per virtual second:
    # sustained overload
    code, body = handler.handle(
        "generateload", {"mode": "pay", "rate": "150", "duration": "60"})
    assert code == 200, body
    lg = app._load_generator
    closes = 0
    max_queue = 0
    max_banned = 0
    while closes < 55:
        app.crank(block=True)
        app.herder.manual_close()
        closes += 1
        max_queue = max(max_queue, app.herder.tx_queue.size())
        max_banned = max(max_banned, sum(
            len(b) for b in app.herder.tx_queue.banned))
    st = lg.rate_status()
    assert app.ledger_manager.last_closed_seq() - seq0 >= 55
    assert st["submitted"] >= 150 * 30  # most of the run happened
    applied = app.database.execute(
        "SELECT COUNT(*) FROM txhistory").fetchone()[0]
    assert applied >= 50 * 50  # sustained application, not a stall
    # overload reached the queue-limiter path: not every submission
    # could stay PENDING
    assert any(k != "0" for k in st["status_counts"]), st
    # the queue stayed bounded by the limiter (multiplier x set size)
    cap = app.config.TRANSACTION_QUEUE_SIZE_MULTIPLIER * 100
    assert 0 < max_queue <= cap + 150
    # ban machinery engaged during the overload transient (evictions);
    # the ring may legitimately drain once rejection throttles arrivals
    assert max_banned > 0, "no tx was ever banned"
