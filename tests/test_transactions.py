"""Transaction frame + op tests (ref models: src/transactions/test/
{TxEnvelopeTests,PaymentTests,ChangeTrustTests,SetOptionsTests,
ManageDataTests,BumpSequenceTests,MergeTests}.cpp)."""
import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.ledger import LedgerTxn
from stellar_core_tpu.transactions import TransactionFrame
from stellar_core_tpu.transactions import utils as U
from stellar_core_tpu.transactions.signature_checker import signature_hint
from stellar_core_tpu.xdr import types as T

from tests.txtest import (
    BASE_FEE, BASE_RESERVE, NETWORK_ID, TestAccount, TestLedger,
)

TC = T.TransactionResultCode


@pytest.fixture()
def ledger():
    return TestLedger()


@pytest.fixture()
def root(ledger):
    return ledger.root()


def op_result_code(result, i=0):
    return result.result.value[i].value.value.type


def test_create_account_and_payment(root, ledger):
    a = root.create("alice", 10 * BASE_RESERVE)
    b = root.create("bob", 10 * BASE_RESERVE)
    assert a.exists() and b.exists()
    start_a, start_b = a.balance(), b.balance()
    env = a.tx([a.op_payment(b.account_id, 1000000)])
    a.apply(env)
    assert a.balance() == start_a - 1000000 - BASE_FEE
    assert b.balance() == start_b + 1000000


def test_create_account_already_exists(root):
    a = root.create("alice", 10 * BASE_RESERVE)
    env = root.tx([root.op_create_account(a.account_id, 10 * BASE_RESERVE)])
    ok, result = root.apply(env, expect_success=False)
    assert not ok
    assert result.result.type == TC.txFAILED
    assert op_result_code(result) == \
        T.CreateAccountResultCode.CREATE_ACCOUNT_ALREADY_EXIST


def test_create_account_low_reserve(root):
    dest = SecretKey(sha256(b"lowres")).public_key().raw
    env = root.tx([root.op_create_account(dest, 1)])
    ok, result = root.apply(env, expect_success=False)
    assert op_result_code(result) == \
        T.CreateAccountResultCode.CREATE_ACCOUNT_LOW_RESERVE


def test_payment_underfunded(root):
    a = root.create("alice", 3 * BASE_RESERVE)
    b = root.create("bob", 3 * BASE_RESERVE)
    env = a.tx([a.op_payment(b.account_id, 10 * BASE_RESERVE)])
    ok, result = a.apply(env, expect_success=False)
    assert op_result_code(result) == \
        T.PaymentResultCode.PAYMENT_UNDERFUNDED


def test_payment_no_destination(root):
    a = root.create("alice", 10 * BASE_RESERVE)
    ghost = SecretKey(sha256(b"ghost")).public_key().raw
    env = a.tx([a.op_payment(ghost, 100)])
    ok, result = a.apply(env, expect_success=False)
    assert op_result_code(result) == \
        T.PaymentResultCode.PAYMENT_NO_DESTINATION


def test_seqnum_progression_and_bad_seq(root):
    a = root.create("alice", 100 * BASE_RESERVE)
    # new accounts start at ledgerSeq << 32 (ref TransactionUtils.cpp:984)
    start = a.loaded_seq()
    assert start == root.ledger.header().ledgerSeq << 32
    a.apply(a.tx([a.op_bump_seq(0)]))  # no-op bump
    assert a.loaded_seq() == start + 1
    # replay same seq -> bad seq at checkValid
    env = a.tx([a.op_bump_seq(0)], seq=start + 1)
    res = a.check_valid(env)
    assert res.code == TC.txBAD_SEQ


def test_check_valid_rejects_insufficient_fee(root):
    a = root.create("alice", 100 * BASE_RESERVE)
    env = a.tx([a.op_bump_seq(0)], fee=BASE_FEE - 1)
    assert a.check_valid(env).code == TC.txINSUFFICIENT_FEE


def test_check_valid_rejects_bad_signature(root, ledger):
    a = root.create("alice", 100 * BASE_RESERVE)
    mallory = SecretKey(sha256(b"mallory"))
    env = a.tx([a.op_bump_seq(0)])
    # replace the signature with mallory's
    bad = TestAccount(ledger, mallory)
    env2 = bad.tx([a.op_bump_seq(0)])
    env_tampered = T.TransactionEnvelope.make(
        T.EnvelopeType.ENVELOPE_TYPE_TX,
        T.TransactionV1Envelope.make(
            tx=env.value.tx, signatures=env2.value.signatures))
    assert a.check_valid(env_tampered).code == TC.txBAD_AUTH


def test_check_valid_rejects_unused_extra_signature(root, ledger):
    a = root.create("alice", 100 * BASE_RESERVE)
    stranger = SecretKey(sha256(b"stranger"))
    env = a.tx([a.op_bump_seq(0)], extra_signers=[stranger])
    assert a.check_valid(env).code == TC.txBAD_AUTH_EXTRA


def test_time_bounds(root):
    a = root.create("alice", 100 * BASE_RESERVE)
    close_time = root.ledger.header().scpValue.closeTime
    tb = T.TimeBounds.make(minTime=close_time + 100, maxTime=0)
    cond = T.Preconditions.make(T.PreconditionType.PRECOND_TIME, tb)
    env = a.tx([a.op_bump_seq(0)], cond=cond)
    assert a.check_valid(env).code == TC.txTOO_EARLY
    tb2 = T.TimeBounds.make(minTime=0, maxTime=max(1, close_time - 100))
    cond2 = T.Preconditions.make(T.PreconditionType.PRECOND_TIME, tb2)
    env2 = a.tx([a.op_bump_seq(0)], cond=cond2)
    assert a.check_valid(env2).code == TC.txTOO_LATE


def test_fee_charged_and_fee_pool(root, ledger):
    a = root.create("alice", 100 * BASE_RESERVE)
    pool_before = ledger.header().feePool
    a.apply(a.tx([a.op_bump_seq(0)]))
    assert ledger.header().feePool == pool_before + BASE_FEE


def test_trustline_payment_flow(root):
    issuer = root.create("issuer", 100 * BASE_RESERVE)
    alice = root.create("alice2", 100 * BASE_RESERVE)
    usd = U.make_asset(b"USD", issuer.account_id)
    alice.apply(alice.tx([alice.op_change_trust(usd)]))
    # issuer pays alice 500 USD (issuing)
    issuer.apply(issuer.tx([issuer.op_payment(
        alice.account_id, 500, asset=usd)]))
    # alice pays back 200
    alice.apply(alice.tx([alice.op_payment(
        issuer.account_id, 200, asset=usd)]))
    with LedgerTxn(root.ledger.root_txn) as ltx:
        tl = ltx.load_trustline(alice.account_id, usd)
        ltx.rollback()
    assert tl.data.value.balance == 300


def test_payment_no_trust(root):
    issuer = root.create("issuer", 100 * BASE_RESERVE)
    alice = root.create("alice3", 100 * BASE_RESERVE)
    usd = U.make_asset(b"USD", issuer.account_id)
    env = issuer.tx([issuer.op_payment(alice.account_id, 500, asset=usd)])
    ok, result = issuer.apply(env, expect_success=False)
    assert op_result_code(result) == T.PaymentResultCode.PAYMENT_NO_TRUST


def test_change_trust_delete(root):
    issuer = root.create("issuer", 100 * BASE_RESERVE)
    alice = root.create("alice4", 100 * BASE_RESERVE)
    usd = U.make_asset(b"USD", issuer.account_id)
    alice.apply(alice.tx([alice.op_change_trust(usd)]))
    sub_before = _subentries(root, alice)
    alice.apply(alice.tx([alice.op_change_trust(usd, limit=0)]))
    assert _subentries(root, alice) == sub_before - 1


def _subentries(root, who):
    with LedgerTxn(root.ledger.root_txn) as ltx:
        e = ltx.load_account(who.account_id)
        ltx.rollback()
    return e.data.value.numSubEntries


def test_manage_data_create_update_delete(root):
    a = root.create("alice5", 100 * BASE_RESERVE)
    a.apply(a.tx([a.op_manage_data(b"k1", b"v1")]))
    assert _subentries(root, a) == 1
    a.apply(a.tx([a.op_manage_data(b"k1", b"v2")]))
    with LedgerTxn(root.ledger.root_txn) as ltx:
        d = ltx.load_data(a.account_id, b"k1")
        ltx.rollback()
    assert d.data.value.dataValue == b"v2"
    a.apply(a.tx([a.op_manage_data(b"k1", None)]))
    assert _subentries(root, a) == 0


def test_set_options_add_signer_multisig(root, ledger):
    a = root.create("alice6", 100 * BASE_RESERVE)
    cosigner = SecretKey(sha256(b"cosigner"))
    signer = T.Signer.make(
        key=T.SignerKey.make(T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                             cosigner.public_key().raw),
        weight=1)
    a.apply(a.tx([a.op_set_options(signer=signer, low=1, med=2, high=2,
                                   master_weight=1)]))
    # now a alone passes the tx-level LOW check but fails the payment op's
    # MED threshold -> txFAILED with opBAD_AUTH (tx-level weight shortfall
    # would instead be txBAD_AUTH, tested separately)
    b = root.create("bob6", 100 * BASE_RESERVE)
    env = a.tx([a.op_payment(b.account_id, 1000)])
    assert a.check_valid(env).code == TC.txFAILED
    # with the cosigner it passes
    env2 = a.tx([a.op_payment(b.account_id, 1000)],
                extra_signers=[cosigner])
    assert a.check_valid(env2).ok
    a.apply(env2)


def test_account_merge(root):
    a = root.create("alice7", 100 * BASE_RESERVE)
    b = root.create("bob7", 100 * BASE_RESERVE)
    bal_a, bal_b = a.balance(), b.balance()
    env = a.tx([a.op_merge(b.account_id)])
    ok, result = a.apply(env)
    assert not a.exists()
    assert b.balance() == bal_b + bal_a - BASE_FEE


def test_all_or_nothing_apply(root):
    a = root.create("alice8", 100 * BASE_RESERVE)
    b = root.create("bob8", 100 * BASE_RESERVE)
    bal_b = b.balance()
    ghost = SecretKey(sha256(b"ghost8")).public_key().raw
    # first op succeeds, second fails -> nothing applied
    start = a.loaded_seq()
    env = a.tx([
        a.op_payment(b.account_id, 1000),
        a.op_payment(ghost, 1000),
    ])
    ok, result = a.apply(env, expect_success=False)
    assert not ok
    assert result.result.type == TC.txFAILED
    assert b.balance() == bal_b  # rolled back
    # fee still charged, seq still bumped
    assert a.loaded_seq() == start + 1


def test_credit_self_payment_is_noop(root):
    """Regression (review finding): a credit-asset self-payment must not
    mint — src and dest share one trustline."""
    issuer = root.create("issuer9", 100 * BASE_RESERVE)
    alice = root.create("alice9", 100 * BASE_RESERVE)
    usd = U.make_asset(b"USD", issuer.account_id)
    alice.apply(alice.tx([alice.op_change_trust(usd)]))
    issuer.apply(issuer.tx([issuer.op_payment(
        alice.account_id, 500, asset=usd)]))
    # self-payment: balance must stay exactly 500
    alice.apply(alice.tx([alice.op_payment(
        alice.account_id, 300, asset=usd)]))
    with LedgerTxn(root.ledger.root_txn) as ltx:
        tl = ltx.load_trustline(alice.account_id, usd)
        ltx.rollback()
    assert tl.data.value.balance == 500


def test_apply_bad_seq_after_sibling_bump(root):
    """Regression (ADVICE r4 high): a tx overtaken by an earlier tx in the
    same set (BUMP_SEQUENCE on its own source) must fail cleanly at apply
    with txBAD_SEQ — NOT crash — and must NOT consume its seqnum
    (ref commonValid re-runs isBadSeq when applying,
    TransactionFrame.cpp:1135-1148; cv==kInvalid skips processSeqNum
    :1770-1772)."""
    a = root.create("aseq", 100 * BASE_RESERVE)
    start = a.loaded_seq()
    env2 = a.tx([a.op_bump_seq(0)], seq=start + 2)  # built before the bump
    a.apply(a.tx([a.op_bump_seq(start + 10)], seq=start + 1))
    assert a.loaded_seq() == start + 10
    ok, result = a.apply(env2, expect_success=False)
    assert not ok
    assert result.result.type == TC.txBAD_SEQ
    assert a.loaded_seq() == start + 10  # not consumed


def test_apply_min_seq_ledger_gap_consumes_seq(root):
    """Regression (ADVICE r4 medium): minSeqLedgerGap is enforced at apply
    too (ref isTooEarlyForAccount from commonValid :1152), and the failing
    tx STILL consumes its sequence number (cv==kInvalidUpdateSeqNum)."""
    a = root.create("agap", 100 * BASE_RESERVE)
    start = a.loaded_seq()
    a.apply(a.tx([a.op_bump_seq(0)]))  # stamps seqLedger via v3 ext
    assert a.loaded_seq() == start + 1
    cond = T.Preconditions.make(
        T.PreconditionType.PRECOND_V2,
        T.PreconditionsV2.make(
            timeBounds=None, ledgerBounds=None, minSeqNum=None,
            minSeqAge=0, minSeqLedgerGap=100, extraSigners=[]))
    env = a.tx([a.op_bump_seq(0)], cond=cond, seq=start + 2)
    ok, result = a.apply(env, expect_success=False)
    assert not ok
    assert result.result.type == TC.txBAD_MIN_SEQ_AGE_OR_GAP
    assert a.loaded_seq() == start + 2  # consumed despite the failure


def test_apply_partial_op_bad_auth_results(root):
    """Regression (ADVICE r4 medium): in a multi-op tx failed by ONE op's
    bad signature, only that op gets opBAD_AUTH; ops whose signatures
    passed keep the default-initialized opINNER success result
    (ref OperationFrame::checkSignature :194 + markResultFailed
    :1063-1067)."""
    a = root.create("amix", 100 * BASE_RESERVE)
    b = root.create("bmix", 100 * BASE_RESERVE)
    # op1: a pays b (signed); op2: sourced by b, b did NOT sign
    op2 = a.op_payment(a.account_id, 1000)
    op2 = op2._replace(sourceAccount=T.muxed_account(b.account_id))
    env = a.tx([a.op_payment(b.account_id, 1000), op2])
    ok, result = a.apply(env, expect_success=False)
    assert not ok
    assert result.result.type == TC.txFAILED
    ops = result.result.value
    OC = T.OperationResultCode
    assert ops[0].type == OC.opINNER
    assert ops[0].value.type == T.OperationType.PAYMENT
    assert ops[0].value.value.type == \
        T.PaymentResultCode.PAYMENT_SUCCESS
    assert ops[1].type == OC.opBAD_AUTH


def test_fee_bump_underpriced_inner_applies(root):
    """Regression (r5 review): a fee-bump wrapping an inner tx whose own
    fee is below the min fee must still APPLY successfully — the outer
    source paid (ref FeeBumpTransactionFrame::apply -> mInnerTx->apply
    with chargeFee=false)."""
    a = root.create("afb", 100 * BASE_RESERVE)
    b = root.create("bfb", 100 * BASE_RESERVE)
    inner = a.tx([a.op_payment(b.account_id, 1000)], fee=1)
    fb = a.fee_bump(inner, fee_source=b)
    ok, result = b.apply(fb)
    assert ok
    assert result.result.type == TC.txFEE_BUMP_INNER_SUCCESS


def test_disabled_master_key_does_not_consume_signature(root, ledger):
    """Regression (r5 review): a master key disabled with weight 0 must
    NOT match (and consume) its signature — the reference omits it from
    the signer set entirely (TransactionFrame::checkSignature :306-310),
    so an extra master-key signature on a signer-authorized tx is
    txBAD_AUTH_EXTRA, not txSUCCESS."""
    a = root.create("a0m", 100 * BASE_RESERVE)
    cosigner = SecretKey(sha256(b"cosigner0m"))
    signer = T.Signer.make(
        key=T.SignerKey.make(T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                             cosigner.public_key().raw),
        weight=10)
    a.apply(a.tx([a.op_set_options(signer=signer, master_weight=0)]))
    # signed by BOTH the cosigner (sufficient) and the disabled master
    env = a.tx([a.op_bump_seq(0)], extra_signers=[cosigner])
    res = a.check_valid(env)
    assert res.code == TC.txBAD_AUTH_EXTRA
    # cosigner alone is fine
    env2 = a.tx([a.op_bump_seq(0)])
    env2 = T.TransactionEnvelope.make(
        T.EnvelopeType.ENVELOPE_TYPE_TX,
        T.TransactionV1Envelope.make(
            tx=env2.value.tx,
            signatures=[s for s in a.tx(
                [a.op_bump_seq(0)],
                extra_signers=[cosigner]).value.signatures[1:]]))
    assert a.check_valid(env2).ok
