"""VirtualClock / Scheduler / work-system / metrics tests
(ref test models: src/util/test/TimerTests.cpp, SchedulerTests.cpp,
src/work/test/WorkTests.cpp)."""
import pytest

from stellar_core_tpu.utils import (
    ActionType, ClockMode, MetricsRegistry, Scheduler, VirtualClock,
    VirtualTimer,
)
from stellar_core_tpu.work import (
    BasicWork, BatchWork, State, Work, WorkScheduler, WorkSequence,
    WorkWithCallback,
)


# -- clock ------------------------------------------------------------------


def test_virtual_time_advances_to_deadline():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    fired = []
    t = VirtualTimer(clock)
    t.expires_from_now(5.0)
    t.async_wait(lambda: fired.append(clock.now()))
    assert clock.now() == 0.0
    clock.crank(block=True)
    assert fired == [5.0]
    assert clock.now() == 5.0


def test_timer_ordering_and_cancel():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    order = []
    t1, t2, t3 = (VirtualTimer(clock) for _ in range(3))
    t1.expires_from_now(3.0)
    t1.async_wait(lambda: order.append("t1"))
    t2.expires_from_now(1.0)
    t2.async_wait(lambda: order.append("t2"))
    t3.expires_from_now(2.0)
    t3.async_wait(lambda: order.append("t3"), lambda: order.append("c3"))
    t3.cancel()
    while clock.crank(block=True):
        pass
    assert order == ["c3", "t2", "t1"]


def test_crank_until_predicate():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    state = []

    def arm(delay):
        t = VirtualTimer(clock)
        t.expires_from_now(delay)
        t.async_wait(lambda: state.append(delay))
        return t

    timers = [arm(d) for d in (1, 2, 30)]
    assert clock.crank_until(lambda: len(state) == 2, timeout=10)
    assert clock.now() < 30


def test_timer_callbacks_can_rearm():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    count = []

    def tick():
        count.append(clock.now())
        if len(count) < 3:
            t = VirtualTimer(clock)
            t.expires_from_now(1.0)
            t.async_wait(tick)

    t = VirtualTimer(clock)
    t.expires_from_now(1.0)
    t.async_wait(tick)
    clock.crank_until(lambda: len(count) == 3, timeout=10)
    assert count == [1.0, 2.0, 3.0]


# -- scheduler --------------------------------------------------------------


def test_scheduler_fairness():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    sched = Scheduler(clock)
    ran = []
    for i in range(3):
        sched.enqueue("a", lambda i=i: ran.append(("a", i)))
    sched.enqueue("b", lambda: ran.append(("b", 0)))
    while sched.run_one():
        pass
    # queue b (never served) must run before queue a drains fully
    assert ("b", 0) in ran[:2]
    assert len(ran) == 4


def test_scheduler_sheds_droppable_after_window():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    sched = Scheduler(clock, latency_window=5.0)
    ran = []
    sched.enqueue("q", lambda: ran.append("d"), ActionType.DROPPABLE)
    sched.enqueue("q", lambda: ran.append("n"), ActionType.NORMAL)
    clock.set_current_virtual_time(10.0)
    while sched.run_one():
        pass
    assert ran == ["n"]
    assert sched.stats_dropped == 1


# -- metrics ----------------------------------------------------------------


def test_metrics_registry():
    reg = MetricsRegistry()
    reg.counter("ledger.ledger.count").inc(5)
    t = reg.timer("ledger.ledger.close")
    for v in (0.001, 0.002, 0.003):
        t.update(v)
    snap = reg.snapshot()
    assert snap["ledger.ledger.count"]["count"] == 5
    assert snap["ledger.ledger.close"]["count"] == 3
    assert 0.001 <= snap["ledger.ledger.close"]["p50"] <= 0.003
    with pytest.raises(AssertionError):
        reg.meter("ledger.ledger.count")  # type clash


# -- work system ------------------------------------------------------------


class CountdownWork(BasicWork):
    def __init__(self, name, n, fail_at=None):
        super().__init__(name, max_retries=0)
        self.n = n
        self.fail_at = fail_at

    def on_run(self):
        self.n -= 1
        if self.fail_at is not None and self.n == self.fail_at:
            return State.FAILURE
        return State.SUCCESS if self.n <= 0 else State.RUNNING


def test_work_scheduler_runs_to_success():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    ws = WorkScheduler(clock)
    w = ws.schedule(CountdownWork("w", 5))
    assert ws.crank_all()
    assert w.state == State.SUCCESS


def test_work_sequence_ordering_and_failure():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    ws = WorkScheduler(clock)
    ran = []
    seq = WorkSequence("seq", [
        WorkWithCallback("a", lambda: (ran.append("a"), True)[1]),
        WorkWithCallback("b", lambda: (ran.append("b"), False)[1]),
        WorkWithCallback("c", lambda: (ran.append("c"), True)[1]),
    ])
    seq.start()
    while not seq.done:
        seq.crank()
    assert seq.state == State.FAILURE
    assert ran == ["a", "b"]  # c never runs after b fails


def test_retry_then_success():
    class FlakyWork(BasicWork):
        def __init__(self):
            super().__init__("flaky", max_retries=2)
            self.attempts = 0

        def on_run(self):
            self.attempts += 1
            return State.SUCCESS if self.attempts == 3 else State.FAILURE

    w = FlakyWork()
    w.start()
    while not w.done:
        w.crank()
    assert w.state == State.SUCCESS
    assert w.attempts == 3


def test_batch_work_bounded_parallelism():
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    peak = [0]

    works = [CountdownWork(f"w{i}", 3) for i in range(10)]

    class Tracking(BatchWork):
        def on_run(self):
            live = sum(1 for c in self.children if not c.done)
            peak[0] = max(peak[0], live)
            return super().on_run()

    b = Tracking("batch", iter(works), batch_size=3)
    b.start()
    for _ in range(200):
        if b.done:
            break
        b.crank()
    assert b.state == State.SUCCESS
    assert peak[0] <= 3
    assert all(w.state == State.SUCCESS for w in works)


def test_timer_cancel_and_rearm_uses_new_deadline():
    """Regression (review finding): cancel + re-arm must not fire at the
    stale (earlier) deadline."""
    clock = VirtualClock(ClockMode.VIRTUAL_TIME)
    fired = []
    t = VirtualTimer(clock)
    t.expires_from_now(5.0)
    t.async_wait(lambda: fired.append(("old", clock.now())))
    t.cancel()
    t.expires_from_now(100.0)
    t.async_wait(lambda: fired.append(("new", clock.now())))
    while clock.crank(block=True):
        pass
    assert fired == [("new", 100.0)]
