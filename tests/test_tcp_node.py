"""Two OS processes converge over real TCP + the admin HTTP API
(VERDICT r2 next-round task #9 done-gate: two processes converge over
localhost; a curl-submitted payment is admitted).

Each node runs `python -m stellar_core_tpu --conf <toml> run` with a
2-of-2 quorum, real sockets on localhost, and the admin HTTP endpoint;
the test drives them purely through HTTP like an operator would."""
import base64
import json
import os
import signal
import subprocess
import sys
import time
import urllib.parse
import urllib.request

import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.crypto.strkey import (
    encode_ed25519_public_key, encode_ed25519_seed,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    p = s.getsockname()[1]
    s.close()
    return p


def _http(port, path, timeout=2.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}/{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _wait_http(port, deadline=30.0):
    t0 = time.time()
    while time.time() - t0 < deadline:
        try:
            return _http(port, "info")
        except Exception:
            time.sleep(0.25)
    raise TimeoutError(f"admin endpoint :{port} never came up")


@pytest.mark.slow
def test_two_processes_converge_and_accept_tx(tmp_path):
    seeds = [sha256(b"tcp-node-%d" % i) for i in range(2)]
    sks = [SecretKey(s) for s in seeds]
    ids = [sk.public_key().raw for sk in sks]
    peer_ports = [_free_port(), _free_port()]
    http_ports = [_free_port(), _free_port()]

    procs = []
    logs = []
    for i in range(2):
        conf = tmp_path / f"node{i}.toml"
        validators = "".join(
            f'"{encode_ed25519_public_key(x)}", ' for x in ids)
        conf.write_text(f"""
network_passphrase = "tcp process test net"
node_seed = "{encode_ed25519_seed(seeds[i])}"
peer_port = {peer_ports[i]}
http_port = {http_ports[i]}
known_peers = [{f'"127.0.0.1:{peer_ports[1 - i]}"' if i == 1 else ''}]
manual_close = false
artificially_accelerate_time_for_testing = true
exp_ledger_timespan_seconds = 1.0
invariant_checks = [".*"]
crypto_backend = "cpu"
scp_tally_backend = "host"

[quorum_set]
threshold = 2
validators = [{validators}]
""")
        env = dict(os.environ, PYTHONPATH=REPO, JAX_PLATFORMS="cpu")
        # log to files, never PIPE: an unread pipe fills at ~64KB and
        # blocks the node mid-write, freezing consensus
        log = open(tmp_path / f"node{i}.log", "w")
        logs.append(log)
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "stellar_core_tpu",
             "--conf", str(conf), "run"],
            cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT))
    try:
        for port in http_ports:
            _wait_http(port)

        # wait until both nodes close ledgers together
        def heights():
            return [_http(p, "info")["info"]["ledger"]["num"]
                    for p in http_ports]

        t0 = time.time()
        while time.time() - t0 < 150:
            try:
                h = heights()
                if min(h) >= 3:
                    break
            except Exception:
                pass
            time.sleep(0.5)
        else:
            pytest.fail(f"nodes never converged: {heights()}")

        # submit a payment from the network root via the HTTP tx endpoint
        from stellar_core_tpu.main.config import Config
        from .txtest import TestAccount

        class _RemoteAccount(TestAccount):
            def __init__(self, secret, passphrase):
                self.secret = secret
                self.account_id = secret.public_key().raw
                self._passphrase = passphrase
                self._seq = 0

            def network_id(self):
                return sha256(self._passphrase)

            def next_seq(self):
                self._seq += 1
                return self._seq

        root = _RemoteAccount(SecretKey(sha256(b"tcp process test net")),
                              b"tcp process test net")
        dest = SecretKey(sha256(b"tcp-dest"))
        env = root.tx([root.op_create_account(
            dest.public_key().raw, 10**9)])
        from stellar_core_tpu.xdr import types as T

        blob = base64.b64encode(
            T.TransactionEnvelope.encode(env)).decode()
        res = _http(http_ports[0], "tx?blob=" +
                    urllib.parse.quote(blob))
        assert res["status"] == "PENDING", res

        # the tx floods to node 1 and both apply it
        t0 = time.time()
        applied = False
        while time.time() - t0 < 150:
            infos = [_http(p, "info")["info"] for p in http_ports]
            if all(i["pending_txs"] == 0 for i in infos) and \
                    min(i["ledger"]["num"] for i in infos) >= 4:
                applied = True
                break
            time.sleep(0.5)
        assert applied, "payment never applied on both nodes"

        # hashes agree at the shared height
        h = min(_http(p, "info")["info"]["ledger"]["num"]
                for p in http_ports)
        # (fetch again at equal height to compare)
        hashes = set()
        for p in http_ports:
            info = _http(p, "info")["info"]["ledger"]
            if info["num"] == h:
                hashes.add(info["hash"])
        assert len(hashes) <= 1

        # metrics + quorum endpoints respond
        m = _http(http_ports[0], "metrics")
        assert "metrics" in m
        q = _http(http_ports[0], "quorum")
        assert q["qset"]["threshold"] == 2
    finally:
        for p in procs:
            p.send_signal(signal.SIGINT)
        for p in procs:
            try:
                p.wait(timeout=5)
            except subprocess.TimeoutExpired:
                p.kill()
        for log in logs:
            log.close()
