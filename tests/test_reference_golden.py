"""Parity against the REFERENCE's committed tx-meta baseline corpus
(/root/reference/test-tx-meta-baseline-current/*.json — the BASELINE.md
correctness gate: "bit-identical TxResults vs test-tx-meta-baseline-
current").

Each baseline file maps a Catch2 section path (e.g. "create account|
protocol version 19|Success") to the 64-bit SipHash-2-4 of every
NORMALIZED TransactionMeta recorded while that section ran (ref
src/test/test.cpp:620 recordOrCheckGlobalTestTxMetadata;
src/util/MetaUtils.cpp normalizeMeta; shortHash seeded from the file's
"!rng seed" via ShortHash.cpp seed()).

Reproducing a value requires replaying the reference test's exact
fixtures — which ARE deterministic: test network passphrase
"(V) (;,,;) (V)" (test.cpp), root key seeded by the network id, named
accounts seeded by the name '.'-padded to 32 bytes (TxTests.cpp:574),
genesis base fee 100 / base reserve 100000000 / maxTxSetSize 50 / total
coins 10^18 (LedgerManagerImpl.cpp:88-93, Config.cpp:197-199), fee =
100 * ops, and closes that keep closeTime at 0 (TxTests closeLedger
reuses the last close time).  This file replays a set of scenarios
through the REAL close path and asserts hash equality at protocol 19.

Reproducibility notes for the rest of the corpus (VERDICT r4 task #7):
scenarios whose fixtures use Catch2's PRNG (SecretKey::
pseudoRandomForTesting, rng-seeded amounts) or TestMarket state are
keyed to Catch2 internals and need those exact streams; everything
fixture-deterministic (named accounts + constant amounts) is
reconstructible the same way as the scenarios below.
"""
import base64
import json
import os

import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.crypto.shorthash import siphash24
from stellar_core_tpu.herder.tx_set import TxSetFrame
from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

REFERENCE_DIR = "/root/reference/test-tx-meta-baseline-current"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_DIR),
    reason="reference baseline corpus not mounted")

TEST_PASSPHRASE = "(V) (;,,;) (V)"  # ref test.cpp getTestConfig


def load_baseline(name):
    with open(os.path.join(REFERENCE_DIR, name)) as f:
        return json.load(f)


def shorthash_key(seed: int) -> bytes:
    """ref ShortHash.cpp seed(): key[i] = byte of (seed >> (i % 4))."""
    return bytes((seed >> (i % 4)) & 0xFF for i in range(16))


# -- meta normalization (ref src/util/MetaUtils.cpp) ------------------------

_TYPE_ORDER = {  # STATE first, then CREATED, UPDATED, REMOVED
    T.LedgerEntryChangeType.LEDGER_ENTRY_STATE: 0,
    T.LedgerEntryChangeType.LEDGER_ENTRY_CREATED: 1,
    T.LedgerEntryChangeType.LEDGER_ENTRY_UPDATED: 2,
    T.LedgerEntryChangeType.LEDGER_ENTRY_REMOVED: 3,
}


def _change_key(change) -> bytes:
    if change.type == T.LedgerEntryChangeType.LEDGER_ENTRY_REMOVED:
        return key_bytes(change.value)
    return key_bytes(entry_to_key(change.value))


def _sorted_changes(changes):
    return sorted(changes, key=lambda c: (
        _change_key(c), _TYPE_ORDER[c.type],
        sha256(T.LedgerEntryChange.encode(c))))


def normalize_meta(meta):
    """Sorted-changes copy of a TransactionMeta (v2)."""
    assert meta.type == 2
    v2 = meta.value
    ops = [T.OperationMeta.make(changes=_sorted_changes(om.changes))
           for om in v2.operations]
    return T.TransactionMeta.make(2, T.TransactionMetaV2.make(
        txChangesBefore=_sorted_changes(v2.txChangesBefore),
        operations=ops,
        txChangesAfter=_sorted_changes(v2.txChangesAfter)))


def meta_hash_b64(meta, rng_seed: int) -> str:
    h = siphash24(shorthash_key(rng_seed),
                  T.TransactionMeta.encode(normalize_meta(meta)))
    # the corpus stores each uint64 base64'd in big-endian byte order
    # (ref test.cpp saveTestTxMeta :815)
    return base64.b64encode(h.to_bytes(8, "big")).decode()


# -- reference test fixtures ------------------------------------------------

def named_account_seed(name: str) -> bytes:
    """ref txtest::getAccount: the name '.'-padded to 32 bytes IS the
    ed25519 seed."""
    return (name + "." * 32)[:32].encode()


class RefHarness:
    """A node configured exactly like the reference's createTestApplication
    + getTestConfig, applying txs one per close with closeTime pinned at 0
    (ref txtest::closeLedger reusing the last close time)."""

    def __init__(self):
        self.app = Application(
            VirtualClock(ClockMode.VIRTUAL_TIME),
            test_config(
                NETWORK_PASSPHRASE=TEST_PASSPHRASE,
                TESTING_UPGRADE_RESERVE=100000000,
                TESTING_UPGRADE_MAX_TX_SET_SIZE=50,
            ))
        self.app.start()
        self.root_sk = SecretKey(self.app.config.network_id())
        self.base_reserve = 100000000
        self.txfee = 100
        self.seqs = {}  # account raw pubkey -> last seq consumed

    def min_balance(self, entries: int) -> int:
        return (2 + entries) * self.base_reserve

    def _next_seq(self, pub: bytes) -> int:
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        if pub not in self.seqs:
            with LedgerTxn(self.app.ledger_manager.root) as ltx:
                e = ltx.load_account(pub)
                self.seqs[pub] = e.data.value.seqNum
                ltx.rollback()
        self.seqs[pub] += 1
        return self.seqs[pub]

    def tx(self, sk: SecretKey, ops, seq=None, extra_signers=(),
           fee=None):
        """transactionFromOperationsV1: fee = ops * 100, no memo/bounds.
        ``extra_signers`` mirrors TestAccount::tx + addSignature."""
        pub = sk.public_key().raw
        tx = T.Transaction.make(
            sourceAccount=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, pub),
            fee=len(ops) * self.txfee if fee is None else fee,
            seqNum=self._next_seq(pub) if seq is None else seq,
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.Memo.make(T.MemoType.MEMO_NONE),
            operations=ops,
            ext=T.Transaction.fields[6][1].make(0))
        payload = T.TransactionSignaturePayload.make(
            networkId=self.app.config.network_id(),
            taggedTransaction=T.TransactionSignaturePayload
            .fields[1][1].make(T.EnvelopeType.ENVELOPE_TYPE_TX, tx))
        h = sha256(T.TransactionSignaturePayload.encode(payload))
        sigs = []
        for signer in (sk, *extra_signers):
            spub = signer.public_key().raw
            sigs.append(T.DecoratedSignature.make(
                hint=spub[-4:], signature=signer.sign(h)))
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=tx, signatures=sigs))

    # -- op builders (ref TxTests.cpp op factories) ------------------------

    def _op(self, body_type, body_value=None, source: bytes = None):
        return T.Operation.make(
            sourceAccount=(None if source is None else T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, source)),
            body=T.OperationBody.make(body_type, body_value))

    def op_bump_seq(self, to: int, source=None):
        return self._op(T.OperationType.BUMP_SEQUENCE,
                        T.BumpSequenceOp.make(bumpTo=to), source)

    def op_merge(self, dest_pub: bytes, source=None):
        return self._op(T.OperationType.ACCOUNT_MERGE,
                        T.MuxedAccount.make(
                            T.CryptoKeyType.KEY_TYPE_ED25519, dest_pub),
                        source)

    def op_inflation(self, source=None):
        return self._op(T.OperationType.INFLATION, None, source)

    def op_change_trust(self, asset, limit: int, source=None):
        return self._op(
            T.OperationType.CHANGE_TRUST,
            T.ChangeTrustOp.make(
                line=T.ChangeTrustAsset.make(asset.type, asset.value),
                limit=limit), source)

    def op_manage_data(self, name: bytes, value, source=None):
        return self._op(T.OperationType.MANAGE_DATA,
                        T.ManageDataOp.make(dataName=name, dataValue=value),
                        source)

    def op_set_options(self, source=None, **kw):
        return self._op(T.OperationType.SET_OPTIONS, T.SetOptionsOp.make(
            inflationDest=kw.get("inflation_dest"),
            clearFlags=kw.get("clear_flags"),
            setFlags=kw.get("set_flags"),
            masterWeight=kw.get("master_weight"),
            lowThreshold=kw.get("low"),
            medThreshold=kw.get("med"),
            highThreshold=kw.get("high"),
            homeDomain=kw.get("home_domain"),
            signer=kw.get("signer")), source)

    def op_manage_sell_offer(self, selling, buying, amount: int,
                             price_n: int, price_d: int, offer_id: int = 0,
                             source=None):
        return self._op(T.OperationType.MANAGE_SELL_OFFER,
                        T.ManageSellOfferOp.make(
                            selling=selling, buying=buying, amount=amount,
                            price=T.Price.make(n=price_n, d=price_d),
                            offerID=offer_id), source)

    def asset(self, issuer_pub: bytes, code: bytes):
        """makeAsset: 4-char alphanum asset."""
        return T.Asset.make(
            T.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
            T.AlphaNum4.make(assetCode=code.ljust(4, b"\x00"),
                             issuer=T.account_id(issuer_pub)))

    def native(self):
        return T.Asset.make(T.AssetType.ASSET_TYPE_NATIVE)

    def op_create_account(self, dest_pub: bytes, balance: int,
                          source=None):
        return self._op(T.OperationType.CREATE_ACCOUNT,
                        T.CreateAccountOp.make(
                            destination=T.account_id(dest_pub),
                            startingBalance=balance), source)

    def op_payment(self, dest_pub: bytes, amount: int, asset=None,
                   source=None):
        return self._op(
            T.OperationType.PAYMENT,
            T.PaymentOp.make(
                destination=T.MuxedAccount.make(
                    T.CryptoKeyType.KEY_TYPE_ED25519, dest_pub),
                asset=(asset if asset is not None else
                       T.Asset.make(T.AssetType.ASSET_TYPE_NATIVE)),
                amount=amount), source)

    def close_empty(self, close_time=None):
        """txtest::closeLedger(app) / closeLedgerOn with no txs."""
        lm = self.app.ledger_manager
        prev = lm.last_closed_header()
        xdr_set = T.TransactionSet.make(
            previousLedgerHash=lm.last_closed_hash(), txs=[])
        tx_set = TxSetFrame.make_from_wire(
            self.app.config.network_id(), xdr_set)
        sv = T.StellarValue.make(
            txSetHash=tx_set.contents_hash(),
            closeTime=(prev.scpValue.closeTime if close_time is None
                       else close_time),
            upgrades=[],
            ext=T.StellarValue.fields[3][1].make(
                T.StellarValueType.STELLAR_VALUE_BASIC))
        from stellar_core_tpu.herder.herder import LedgerCloseData

        lm.close_ledger(LedgerCloseData(lm.last_closed_seq() + 1,
                                        tx_set, sv))

    def apply_tx(self, env):
        """One tx in its own close, closeTime = last close time (stays 0);
        returns (tx_result, TransactionMeta) from the real close path."""
        lm = self.app.ledger_manager
        seq = lm.last_closed_seq() + 1
        prev = lm.last_closed_header()
        xdr_set = T.TransactionSet.make(
            previousLedgerHash=lm.last_closed_hash(), txs=[env])
        tx_set = TxSetFrame.make_from_wire(
            self.app.config.network_id(), xdr_set)
        sv = T.StellarValue.make(
            txSetHash=tx_set.contents_hash(),
            closeTime=prev.scpValue.closeTime,
            upgrades=[],
            ext=T.StellarValue.fields[3][1].make(
                T.StellarValueType.STELLAR_VALUE_BASIC))
        from stellar_core_tpu.herder.herder import LedgerCloseData

        lm.close_ledger(LedgerCloseData(seq, tx_set, sv))
        cur = self.app.database.cursor()
        row = cur.execute(
            "SELECT txresult, txmeta FROM txhistory WHERE ledgerseq=? "
            "ORDER BY txindex", (seq,)).fetchall()
        assert len(row) == 1
        result = T.TransactionResultPair.decode(row[0][0])
        meta = T.TransactionMeta.decode(row[0][1])
        return result, meta


# -- scenarios --------------------------------------------------------------

class TestCreateAccountBaselines:
    """create account|protocol version 19|... scenarios from
    CreateAccountTests.cpp, replayed step-for-step."""

    def test_success(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        b_sk = SecretKey(named_account_seed("B"))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        got = meta_hash_b64(meta, seed)
        want = d["create account|protocol version 19|Success"]
        assert got == want[0], f"meta hash {got} != reference {want[0]}"

    def test_success_account_already_exists(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        b_sk = SecretKey(named_account_seed("B"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.CreateAccountResultCode.CREATE_ACCOUNT_ALREADY_EXIST
        got = meta_hash_b64(meta, seed)
        want = d["create account|protocol version 19|Success|"
                 "Account already exists"]
        assert got == want[0]

    def test_not_enough_funds(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        gateway_payment = h.min_balance(2) + 10 * h.txfee + 1
        gate_sk = SecretKey(named_account_seed("gate"))
        _, meta1 = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            gate_sk.public_key().raw, gateway_payment)]))
        res, meta2 = h.apply_tx(h.tx(gate_sk, [h.op_create_account(
            SecretKey(named_account_seed("B")).public_key().raw,
            gateway_payment)]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.CreateAccountResultCode.CREATE_ACCOUNT_UNDERFUNDED
        want = d["create account|protocol version 19|"
                 "Not enough funds (source)"]
        assert [meta_hash_b64(meta1, seed),
                meta_hash_b64(meta2, seed)] == want


def assert_section(d, key, metas):
    """Assert the section's recorded hash list equals our replayed metas."""
    seed = d["!rng seed"]
    got = [meta_hash_b64(m, seed) for m in metas]
    assert got == d[key], f"{key}: {got} != {d[key]}"


INT64_MAX = 2**63 - 1


class TestBumpSequenceBaselines:
    """bump sequence|protocol version 19|... (BumpSequenceTests.cpp:26-101).
    Fixture: A and B created with minBalance(0)+1000."""

    def _fixture(self):
        h = RefHarness()
        a = SecretKey(named_account_seed("A"))
        b = SecretKey(named_account_seed("B"))
        for sk in (a, b):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, h.min_balance(0) + 1000)]))
        return h, a, b

    def _seq(self, h, sk):
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            e = ltx.load_account(sk.public_key().raw)
            ltx.rollback()
        return e.data.value.seqNum

    def test_small_bump(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        new_seq = self._seq(h, a) + 2
        _, meta = h.apply_tx(h.tx(a, [h.op_bump_seq(new_seq)]))
        assert self._seq(h, a) == new_seq
        assert_section(
            d, "bump sequence|protocol version 19|test success|small bump",
            [meta])

    def test_large_bump_and_int64_max(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        _, meta = h.apply_tx(h.tx(a, [h.op_bump_seq(INT64_MAX)]))
        assert self._seq(h, a) == INT64_MAX
        assert_section(
            d, "bump sequence|protocol version 19|test success|large bump",
            [meta])
        # SequenceNumber::min() == 0 -> txBAD_SEQ, recorded anyway
        res, meta2 = h.apply_tx(h.tx(
            a, [h.op_payment(h.root_sk.public_key().raw, 1)], seq=0))
        assert res.result.result.type == T.TransactionResultCode.txBAD_SEQ
        assert_section(
            d, "bump sequence|protocol version 19|test success|large bump|"
               "no more tx when INT64_MAX is reached", [meta2])

    def test_backward_jump_noop(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        old = self._seq(h, a)
        _, meta = h.apply_tx(h.tx(a, [h.op_bump_seq(1)]))
        assert self._seq(h, a) == old + 1
        assert_section(
            d, "bump sequence|protocol version 19|test success|"
               "backward jump (no-op)", [meta])

    def test_bad_seq(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        res1, m1 = h.apply_tx(h.tx(a, [h.op_bump_seq(-1)]))
        res2, m2 = h.apply_tx(h.tx(a, [h.op_bump_seq(-(2**63))]))
        for res in (res1, res2):
            op = res.result.result.value[0]
            assert op.value.value.type == \
                T.BumpSequenceResultCode.BUMP_SEQUENCE_BAD_SEQ
        assert_section(
            d, "bump sequence|protocol version 19|test success|bad seq",
            [m1, m2])

    def test_seqnum_equals_starting_sequence(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        ledger_seq = h.app.ledger_manager.last_closed_seq() + 2
        new_seq = (ledger_seq << 32) - 1
        _, m1 = h.apply_tx(h.tx(a, [h.op_bump_seq(new_seq)]))
        assert self._seq(h, a) == new_seq
        res, m2 = h.apply_tx(h.tx(
            a, [h.op_payment(h.root_sk.public_key().raw, 1)]))
        assert res.result.result.type == T.TransactionResultCode.txBAD_SEQ
        assert_section(
            d, "bump sequence|protocol version 19|"
               "seqnum equals starting sequence", [m1, m2])


class TestMergeBaselines:
    """merge|protocol version 19|... (MergeTests.cpp:35-175).
    Fixture: A (2*minBalance), B (minBalance), gate (minBalance) where
    minBalance = getLastMinBalance(5) + 20*txfee."""

    def _fixture(self):
        h = RefHarness()
        min_bal = h.min_balance(5) + 20 * h.txfee
        a1 = SecretKey(named_account_seed("A"))
        b1 = SecretKey(named_account_seed("B"))
        gate = SecretKey(named_account_seed("gate"))
        for sk, bal in ((a1, 2 * min_bal), (b1, min_bal), (gate, min_bal)):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, bal)]))
        return h, a1, b1

    def test_merge_into_self(self):
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        res, meta = h.apply_tx(h.tx(a1, [h.op_merge(a1.public_key().raw)]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.AccountMergeResultCode.ACCOUNT_MERGE_MALFORMED
        assert_section(d, "merge|protocol version 19|merge into self",
                       [meta])

    def test_merge_into_non_existent(self):
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        c = SecretKey(named_account_seed("C"))
        res, meta = h.apply_tx(h.tx(a1, [h.op_merge(c.public_key().raw)]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.AccountMergeResultCode.ACCOUNT_MERGE_NO_ACCOUNT
        assert_section(
            d, "merge|protocol version 19|merge into non existent account",
            [meta])

    def test_with_create_seqnum_too_far(self):
        """merge+create+merge in one tx: the re-merge hits
        SEQNUM_TOO_FAR at protocol >= 10 (the account was just recreated
        with a starting seqnum beyond the current ledger)."""
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        create_balance = h.min_balance(1)
        apub, bpub = a1.public_key().raw, b1.public_key().raw
        env = h.tx(a1, [
            h.op_merge(bpub, source=apub),
            h.op_create_account(apub, create_balance, source=bpub),
            h.op_merge(bpub, source=apub),
        ], extra_signers=[b1])
        res, meta = h.apply_tx(env)
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        ops = res.result.result.value
        assert ops[2].value.value.type == \
            T.AccountMergeResultCode.ACCOUNT_MERGE_SEQNUM_TOO_FAR
        assert_section(d, "merge|protocol version 19|with create", [meta])

    def test_merge_create_merge_back(self):
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        create_balance = h.min_balance(1)
        apub, bpub = a1.public_key().raw, b1.public_key().raw
        env = h.tx(a1, [
            h.op_merge(bpub, source=apub),
            h.op_create_account(apub, create_balance, source=bpub),
            h.op_merge(apub, source=bpub),
        ], extra_signers=[b1])
        res, meta = h.apply_tx(env)
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            e = ltx.load_account(apub)
            assert ltx.load_account(bpub) is None
            ltx.rollback()
        # recreated with the starting seqnum of the applying ledger (5)
        assert e.data.value.seqNum == 5 << 32
        assert_section(
            d, "merge|protocol version 19|merge, create, merge back",
            [meta])


class TestPaymentBaselines:
    """payment|protocol version 19|... (PaymentTests.cpp:39-230,1890).
    Fixture: A (minBalance2), gate + gate2 (minBalance2+morePayment)."""

    def _fixture(self):
        h = RefHarness()
        min_balance2 = h.min_balance(2) + 10 * h.txfee
        payment_amount = min_balance2
        more_payment = payment_amount // 2
        gateway_payment = min_balance2 + more_payment
        a1 = SecretKey(named_account_seed("A"))
        gate = SecretKey(named_account_seed("gate"))
        gate2 = SecretKey(named_account_seed("gate2"))
        for sk, bal in ((a1, payment_amount), (gate, gateway_payment),
                        (gate2, gateway_payment)):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, bal)]))
        return h, a1, more_payment

    def test_send_xlm_to_existing_account(self):
        d = load_baseline("PaymentTests.json")
        h, a1, more_payment = self._fixture()
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_payment(
            a1.public_key().raw, more_payment)]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        assert_section(
            d, "payment|protocol version 19|send XLM to an existing account",
            [meta])

    def test_send_xlm_no_destination(self):
        d = load_baseline("PaymentTests.json")
        h, a1, _ = self._fixture()
        b = SecretKey(named_account_seed("B"))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_payment(
            b.public_key().raw, h.min_balance(0))]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.PaymentResultCode.PAYMENT_NO_DESTINATION
        assert_section(
            d, "payment|protocol version 19|"
               "send XLM to a new account (no destination)", [meta])

    def test_dest_amount_too_big(self):
        d = load_baseline("PaymentTests.json")
        h, a1, _ = self._fixture()
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_payment(
            a1.public_key().raw, INT64_MAX)]))
        op = res.result.result.value[0]
        assert op.value.value.type == T.PaymentResultCode.PAYMENT_LINE_FULL
        assert_section(
            d, "payment|protocol version 19|"
               "dest amount too big for native asset", [meta])


class TestInflationBaselines:
    """inflation|protocol version 19|not supported
    (InflationTests.cpp:684-689): INFLATION returns opNOT_SUPPORTED at
    protocol >= 12."""

    def test_not_supported(self):
        d = load_baseline("InflationTests.json")
        h = RefHarness()
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_inflation()]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.type == T.OperationResultCode.opNOT_SUPPORTED
        assert_section(
            d, "inflation|protocol version 19|not supported", [meta])


class TestChangeTrustBaselines:
    """change trust|protocol version 19|... (ChangeTrustTests.cpp:24-95).
    Fixture: gw created with minBalance2; idr = gw's IDR."""

    def _fixture(self):
        h = RefHarness()
        gw = SecretKey(named_account_seed("gw"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            gw.public_key().raw, h.min_balance(2))]))
        return h, gw

    def test_basic_tests(self):
        d = load_baseline("ChangeTrustTests.json")
        h, gw = self._fixture()
        idr = h.asset(gw.public_key().raw, b"IDR")
        root = h.root_sk
        rpub = root.public_key().raw
        metas = []
        CC = T.ChangeTrustResultCode

        def step(env, expect=None):
            res, meta = h.apply_tx(env)
            if expect is not None:
                op = res.result.result.value[0]
                assert op.value.value.type == expect, (
                    f"got {op.value.value.type}, want {expect}")
            metas.append(meta)

        step(h.tx(root, [h.op_change_trust(idr, 0)]),
             CC.CHANGE_TRUST_INVALID_LIMIT)
        step(h.tx(root, [h.op_change_trust(idr, 100)]),
             CC.CHANGE_TRUST_SUCCESS)
        step(h.tx(gw, [h.op_payment(rpub, 90, asset=idr)]))
        step(h.tx(root, [h.op_change_trust(idr, 89)]),
             CC.CHANGE_TRUST_INVALID_LIMIT)
        step(h.tx(root, [h.op_change_trust(idr, 0)]),
             CC.CHANGE_TRUST_INVALID_LIMIT)
        step(h.tx(root, [h.op_change_trust(idr, 90)]),
             CC.CHANGE_TRUST_SUCCESS)
        step(h.tx(root, [h.op_payment(gw.public_key().raw, 90, asset=idr)]))
        step(h.tx(root, [h.op_change_trust(idr, 0)]),
             CC.CHANGE_TRUST_SUCCESS)
        assert_section(d, "change trust|protocol version 19|basic tests",
                       metas)

    def test_issuer_does_not_exist_new_trust_line(self):
        d = load_baseline("ChangeTrustTests.json")
        h, gw = self._fixture()
        missing = SecretKey(named_account_seed("non-existing"))
        usd = h.asset(missing.public_key().raw, b"IDR")
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_change_trust(
            usd, 100)]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.ChangeTrustResultCode.CHANGE_TRUST_NO_ISSUER
        assert_section(
            d, "change trust|protocol version 19|issuer does not exist|"
               "new trust line", [meta])


class TestManageDataBaselines:
    """manage data|protocol version 19|create data with native buying
    liabilities (ManageDataTests.cpp:146-161)."""

    def test_create_data_native_buying_liabilities(self):
        d = load_baseline("ManageDataTests.json")
        h = RefHarness()
        # top-level fixture (parent key): gw with minBalance(3)-100, then
        # the versioned top-level manageData sequence
        # (ManageDataTests.cpp:83-101 for_versions_from({2,4}))
        gw = SecretKey(named_account_seed("gw"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            gw.public_key().raw, h.min_balance(3) - 100)]))
        value = bytes(range(64))
        value2 = bytes((n + 3) & 0xFF for n in range(64))
        MD = T.ManageDataResultCode
        for name, val, expect in (
                (b"test", value, MD.MANAGE_DATA_SUCCESS),
                (b"test2", value, MD.MANAGE_DATA_SUCCESS),
                (b"test3", value, MD.MANAGE_DATA_LOW_RESERVE),
                (b"test", value2, MD.MANAGE_DATA_SUCCESS),
                (b"test", None, MD.MANAGE_DATA_SUCCESS),
                (b"test3", value, MD.MANAGE_DATA_SUCCESS),
                (b"test4", None, MD.MANAGE_DATA_NAME_NOT_FOUND)):
            res, _ = h.apply_tx(h.tx(gw, [h.op_manage_data(name, val)]))
            op = res.result.result.value[0]
            assert op.value.value.type == expect
        # section fixture (counts toward THIS key): acc1 + its offer
        acc1 = SecretKey(named_account_seed("acc1"))
        apub = acc1.public_key().raw
        metas = []
        _, m1 = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            apub, h.min_balance(2) + 2 * h.txfee + 500 - 1)]))
        metas.append(m1)
        cur1 = h.asset(apub, b"CUR1")
        res, m2 = h.apply_tx(h.tx(acc1, [h.op_manage_sell_offer(
            cur1, h.native(), 500, 1, 1)]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        metas.append(m2)
        value = bytes(range(64))
        res, m3 = h.apply_tx(h.tx(acc1, [h.op_manage_data(b"test", value)]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        metas.append(m3)
        assert_section(
            d, "manage data|protocol version 19|"
               "create data with native buying liabilities", metas)


class TestSetOptionsBaselines:
    """set options|protocol version 19|... (SetOptionsTests.cpp:30-120,
    581-609).  Fixture: A created with minBalance(0)+1000."""

    def _fixture(self):
        h = RefHarness()
        a1 = SecretKey(named_account_seed("A"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            a1.public_key().raw, h.min_balance(0) + 1000)]))
        return h, a1

    def test_cant_set_and_clear_same_flag(self):
        d = load_baseline("SetOptionsTests.json")
        h, a1 = self._fixture()
        res, meta = h.apply_tx(h.tx(a1, [h.op_set_options(
            set_flags=1, clear_flags=1)]))  # AUTH_REQUIRED_FLAG
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.SetOptionsResultCode.SET_OPTIONS_BAD_FLAGS
        assert_section(
            d, "set options|protocol version 19|flags|"
               "Can't set and clear same flag", [meta])

    def test_bad_weight_for_master_key(self):
        d = load_baseline("SetOptionsTests.json")
        h, a1 = self._fixture()
        res, meta = h.apply_tx(h.tx(a1, [h.op_set_options(
            master_weight=256)]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.SetOptionsResultCode.SET_OPTIONS_THRESHOLD_OUT_OF_RANGE
        assert_section(
            d, "set options|protocol version 19|Signers|"
               "bad weight for master key", [meta])

    def test_signers_insufficient_balance(self):
        d = load_baseline("SetOptionsTests.json")
        h, a1 = self._fixture()
        s1 = SecretKey(named_account_seed("S1"))
        signer = T.Signer.make(
            key=T.SignerKey.make(T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                                 s1.public_key().raw),
            weight=1)
        res, meta = h.apply_tx(h.tx(a1, [h.op_set_options(
            master_weight=100, low=1, med=10, high=100, signer=signer)]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.SetOptionsResultCode.SET_OPTIONS_LOW_RESERVE
        assert_section(
            d, "set options|protocol version 19|Signers|"
               "insufficient balance", [meta])


class TestTxResultsBaselines:
    """txresults|protocol version 19|... (TxResultsTests.cpp:58-355).
    Fixture: one empty close at 2016-01-01, then a..e (reserve*100),
    g (minBalance0); f never created."""

    def _fixture(self):
        h = RefHarness()
        h.close_empty(close_time=1451606400)  # getTestDate(1, 1, 2016)
        start = h.base_reserve * 100
        accs = {}
        for name in ("a", "b", "c", "d", "e"):
            accs[name] = SecretKey(named_account_seed(name))
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                accs[name].public_key().raw, start)]))
        accs["g"] = SecretKey(named_account_seed("g"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            accs["g"].public_key().raw, h.min_balance(0))]))
        return h, accs, start

    def test_create_account_normal(self):
        d = load_baseline("TxResultsTests.json")
        h, accs, start = self._fixture()
        f = SecretKey(named_account_seed("f"))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            f.public_key().raw, start)]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        assert_section(
            d, "txresults|protocol version 19|create account|normal",
            [meta])

    def test_merge_account_normal(self):
        d = load_baseline("TxResultsTests.json")
        h, accs, start = self._fixture()
        res, meta = h.apply_tx(h.tx(accs["a"], [
            h.op_payment(accs["b"].public_key().raw, 1000),
            h.op_merge(h.root_sk.public_key().raw)]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        ops = res.result.result.value
        assert ops[1].value.value.value == start - 1200  # merged balance
        assert_section(
            d, "txresults|protocol version 19|merge account|normal",
            [meta])


class TestEndSponsoringBaselines:
    """confirm and clear sponsor|protocol version 19|not sponsored
    (EndSponsoringFutureReservesTests.cpp): the recorded meta is the
    fixture create; the raw-apply differential (checkValid passes, apply
    fails NOT_SPONSORED) is asserted against our frames directly."""

    def test_not_sponsored(self):
        d = load_baseline("EndSponsoringFutureReservesTests.json")
        h = RefHarness()
        a1 = SecretKey(named_account_seed("a1"))
        _, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            a1.public_key().raw, h.min_balance(0))]))
        assert_section(
            d, "confirm and clear sponsor|protocol version 19|not sponsored",
            [meta])
        # differential: END_SPONSORING with no begin -> checkValid OK,
        # apply fails with NOT_SPONSORED (uncommitted, like the reference)
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.transactions.frame import TransactionFrame

        env = h.tx(h.root_sk, [h._op(
            T.OperationType.END_SPONSORING_FUTURE_RESERVES)])
        frame = TransactionFrame(h.app.config.network_id(), env)
        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            assert frame.check_valid(ltx).ok
            ok, result, _ = frame.apply(ltx)
            ltx.rollback()
        assert not ok
        assert result.result.type == T.TransactionResultCode.txFAILED
        op = result.result.value[0]
        assert op.value.value.type == \
            T.EndSponsoringFutureReservesResultCode.\
            END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED


class TestClawbackBaselines:
    """clawback|protocol version 19|... (ClawbackTests.cpp:18-80).
    Fixture: A1 + gw with minBalance3; idr = gw's IDR."""

    def _fixture(self):
        h = RefHarness()
        a1 = SecretKey(named_account_seed("A1"))
        gw = SecretKey(named_account_seed("gw"))
        for sk in (a1, gw):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, h.min_balance(3))]))
        return h, a1, gw

    def test_all_version_errors(self):
        d = load_baseline("ClawbackTests.json")
        h, a1, gw = self._fixture()
        # allowTrust with TRUSTLINE_CLAWBACK_ENABLED_FLAG (4) is MALFORMED
        op = h._op(T.OperationType.ALLOW_TRUST, T.AllowTrustOp.make(
            trustor=T.account_id(a1.public_key().raw),
            asset=T.AssetCode.make(
                T.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4, b"IDR\x00"),
            authorize=4))
        res, meta = h.apply_tx(h.tx(gw, [op]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.AllowTrustResultCode.ALLOW_TRUST_MALFORMED
        assert_section(
            d, "clawback|protocol version 19|all version errors", [meta])

    def test_from_v17_basic(self):
        d = load_baseline("ClawbackTests.json")
        h, a1, gw = self._fixture()
        idr = h.asset(gw.public_key().raw, b"IDR")
        apub = a1.public_key().raw
        # from-V17 setup (parent key): clawback+revocable flags, trust, pay
        h.apply_tx(h.tx(gw, [h.op_set_options(set_flags=0x8 | 0x2)]))
        h.apply_tx(h.tx(a1, [h.op_change_trust(idr, 1000)]))
        h.apply_tx(h.tx(gw, [h.op_payment(apub, 100, asset=idr)]))
        claw = h._op(T.OperationType.CLAWBACK, T.ClawbackOp.make(
            asset=idr,
            from_=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, apub),
            amount=75))
        res, meta = h.apply_tx(h.tx(gw, [claw]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.ClawbackResultCode.CLAWBACK_SUCCESS
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            tl = ltx.load_trustline(apub, idr)
            ltx.rollback()
        assert tl.data.value.balance == 25
        assert_section(
            d, "clawback|protocol version 19|from V17|basic test", [meta])


class TestSetTrustLineFlagsBaselines:
    """set trustline flags|protocol version 19|errors|no trust
    (SetTrustLineFlagsTests.cpp:1-60,303-307)."""

    def test_errors_no_trust(self):
        d = load_baseline("SetTrustLineFlagsTests.json")
        h = RefHarness()
        gw = SecretKey(named_account_seed("gw"))
        a1 = SecretKey(named_account_seed("A1"))
        a2 = SecretKey(named_account_seed("A2"))
        for sk, bal in ((gw, h.min_balance(4)),
                        (a1, h.min_balance(4) + 10000),
                        (a2, h.min_balance(4))):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, bal)]))
        idr = h.asset(gw.public_key().raw, b"IDR")
        h.apply_tx(h.tx(gw, [h.op_set_options(set_flags=0x2)]))  # REVOCABLE
        h.apply_tx(h.tx(a1, [h.op_change_trust(idr, INT64_MAX)]))
        # leaf: setTrustLineFlags on a2 who has NO trustline
        op = h._op(T.OperationType.SET_TRUST_LINE_FLAGS,
                   T.SetTrustLineFlagsOp.make(
                       trustor=T.account_id(a2.public_key().raw),
                       asset=idr, clearFlags=0, setFlags=0))
        res, meta = h.apply_tx(h.tx(gw, [op]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.SetTrustLineFlagsResultCode.SET_TRUST_LINE_FLAGS_NO_TRUST_LINE
        assert_section(
            d, "set trustline flags|protocol version 19|errors|no trust",
            [meta])


class TestAllowTrustBaselines:
    """authorized to maintain liabilities|protocol version 19|allow trust|
    AUTHORIZED_FLAG and AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG can't be
    used together (AllowTrustTests.cpp:80-305)."""

    def test_auth_flags_cant_be_used_together(self):
        d = load_baseline("AllowTrustTests.json")
        h = RefHarness()
        gw = SecretKey(named_account_seed("gw"))
        a1 = SecretKey(named_account_seed("A1"))
        a2 = SecretKey(named_account_seed("A2"))
        gpub, apub = gw.public_key().raw, a1.public_key().raw
        for sk, bal in ((gw, h.min_balance(4)),
                        (a1, h.min_balance(4) + 10000),
                        (a2, h.min_balance(4))):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, bal)]))
        # AUTH_REQUIRED | AUTH_REVOCABLE
        h.apply_tx(h.tx(gw, [h.op_set_options(set_flags=0x1 | 0x2)]))
        usd = h.asset(gpub, b"USD")
        idr = h.asset(gpub, b"IDR")

        def allow(asset_code, authorize, expect=None):
            op = h._op(T.OperationType.ALLOW_TRUST, T.AllowTrustOp.make(
                trustor=T.account_id(apub),
                asset=T.AssetCode.make(
                    T.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
                    asset_code.ljust(4, b"\x00")),
                authorize=authorize))
            res, meta = h.apply_tx(h.tx(gw, [op]))
            opr = res.result.result.value[0]
            if expect is not None:
                assert opr.value.value.type == expect
            return meta

        h.apply_tx(h.tx(a1, [h.op_change_trust(usd, INT64_MAX)]))
        allow(b"USD", 1, T.AllowTrustResultCode.ALLOW_TRUST_SUCCESS)
        h.apply_tx(h.tx(a1, [h.op_change_trust(idr, INT64_MAX)]))
        allow(b"IDR", 1, T.AllowTrustResultCode.ALLOW_TRUST_SUCCESS)
        h.apply_tx(h.tx(gw, [h.op_payment(apub, 20000, asset=usd)]))
        h.apply_tx(h.tx(gw, [h.op_payment(apub, 20000, asset=idr)]))
        res, _ = h.apply_tx(h.tx(a1, [h.op_manage_sell_offer(
            usd, idr, 1000, 1, 1)]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        # leaf: authorize = AUTHORIZED | AUTHORIZED_TO_MAINTAIN (1|2)
        meta = allow(b"IDR", 3,
                     T.AllowTrustResultCode.ALLOW_TRUST_MALFORMED)
        assert_section(
            d, "authorized to maintain liabilities|protocol version 19|"
               "allow trust|AUTHORIZED_FLAG and "
               "AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG can't be used "
               "together", [meta])


class TestBeginSponsoringBaselines:
    """sponsor future reserves|protocol version 19|...
    (BeginSponsoringFutureReservesTests.cpp:76-190).  The recorded meta
    per leaf is the fixture create; the begin/end sandwich itself is
    raw-applied (uncommitted fee-less apply) and asserted as a
    differential."""

    def _fixture(self):
        h = RefHarness()
        a1 = SecretKey(named_account_seed("a1"))
        _, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            a1.public_key().raw, h.min_balance(0))]))
        return h, a1, meta

    def _raw_apply(self, h, env):
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.transactions.frame import TransactionFrame

        frame = TransactionFrame(h.app.config.network_id(), env)
        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            assert frame.check_valid(ltx).ok
            ok, result, _ = frame.apply(ltx)
            ltx.rollback()
        return ok, result

    def test_success(self):
        d = load_baseline("BeginSponsoringFutureReservesTests.json")
        h, a1, meta = self._fixture()
        apub = a1.public_key().raw
        begin = h._op(T.OperationType.BEGIN_SPONSORING_FUTURE_RESERVES,
                      T.BeginSponsoringFutureReservesOp.make(
                          sponsoredID=T.account_id(apub)))
        end = h._op(T.OperationType.END_SPONSORING_FUTURE_RESERVES,
                    source=apub)
        ok, result = self._raw_apply(
            h, h.tx(h.root_sk, [begin, end], extra_signers=[a1]))
        assert ok
        assert_section(
            d, "sponsor future reserves|protocol version 19|success",
            [meta])

    def test_bad_sponsorship(self):
        d = load_baseline("BeginSponsoringFutureReservesTests.json")
        h, a1, meta = self._fixture()
        begin = h._op(T.OperationType.BEGIN_SPONSORING_FUTURE_RESERVES,
                      T.BeginSponsoringFutureReservesOp.make(
                          sponsoredID=T.account_id(a1.public_key().raw)))
        ok, result = self._raw_apply(h, h.tx(h.root_sk, [begin]))
        assert not ok
        assert result.result.type == \
            T.TransactionResultCode.txBAD_SPONSORSHIP
        assert_section(
            d, "sponsor future reserves|protocol version 19|bad sponsorship",
            [meta])

    def test_already_sponsored(self):
        d = load_baseline("BeginSponsoringFutureReservesTests.json")
        h, a1, meta = self._fixture()
        apub = a1.public_key().raw
        begin = h._op(T.OperationType.BEGIN_SPONSORING_FUTURE_RESERVES,
                      T.BeginSponsoringFutureReservesOp.make(
                          sponsoredID=T.account_id(apub)))
        begin2 = h._op(T.OperationType.BEGIN_SPONSORING_FUTURE_RESERVES,
                       T.BeginSponsoringFutureReservesOp.make(
                           sponsoredID=T.account_id(apub)))
        ok, result = self._raw_apply(h, h.tx(h.root_sk, [begin, begin2]))
        assert not ok
        assert result.result.type == T.TransactionResultCode.txFAILED
        ops = result.result.value
        BS = T.BeginSponsoringFutureReservesResultCode
        assert ops[0].value.value.type == \
            BS.BEGIN_SPONSORING_FUTURE_RESERVES_SUCCESS
        assert ops[1].value.value.type == \
            BS.BEGIN_SPONSORING_FUTURE_RESERVES_ALREADY_SPONSORED
        assert_section(
            d, "sponsor future reserves|protocol version 19|"
               "already sponsored", [meta])


class TestFeeBumpBaselines:
    """fee bump transactions|protocol version 19|...
    (FeeBumpTransactionTests.cpp:64-264).  Each leaf's recorded meta is
    the fixture create; the fee-bump checkValid behaviors are asserted as
    differentials against our FeeBumpTransactionFrame."""

    def _fee_bump_env(self, h, fee_source, source, dest_pub, outer_fee,
                      inner_fee, amount, outer_signers, seq=None):
        """ref feeBumpUnsigned + sign()s, signer list explicit."""
        inner_tx = T.Transaction.make(
            sourceAccount=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519,
                source.public_key().raw),
            fee=inner_fee,
            seqNum=h._next_seq(source.public_key().raw)
            if seq is None else seq,
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.Memo.make(T.MemoType.MEMO_NONE),
            operations=[h.op_payment(dest_pub, amount)],
            ext=T.Transaction.fields[6][1].make(0))
        net = h.app.config.network_id()
        inner_payload = T.TransactionSignaturePayload.make(
            networkId=net,
            taggedTransaction=T.TransactionSignaturePayload
            .fields[1][1].make(T.EnvelopeType.ENVELOPE_TYPE_TX, inner_tx))
        inner_sig = T.DecoratedSignature.make(
            hint=source.public_key().raw[-4:],
            signature=source.sign(sha256(
                T.TransactionSignaturePayload.encode(inner_payload))))
        fb = T.FeeBumpTransaction.make(
            feeSource=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519,
                fee_source.public_key().raw),
            fee=outer_fee,
            innerTx=T.FeeBumpTransaction.fields[2][1].make(
                T.EnvelopeType.ENVELOPE_TYPE_TX,
                T.TransactionV1Envelope.make(
                    tx=inner_tx, signatures=[inner_sig])),
            ext=T.FeeBumpTransaction.fields[3][1].make(0))
        outer_payload = T.TransactionSignaturePayload.make(
            networkId=net,
            taggedTransaction=T.TransactionSignaturePayload
            .fields[1][1].make(
                T.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, fb))
        outer_hash = sha256(
            T.TransactionSignaturePayload.encode(outer_payload))
        sigs = [T.DecoratedSignature.make(
            hint=sk.public_key().raw[-4:], signature=sk.sign(outer_hash))
            for sk in outer_signers]
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
            T.FeeBumpTransactionEnvelope.make(tx=fb, signatures=sigs))

    def _check(self, h, env):
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.transactions.fee_bump import \
            FeeBumpTransactionFrame

        frame = FeeBumpTransactionFrame(h.app.config.network_id(), env)
        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            res = frame.check_valid(ltx)
            ltx.rollback()
        return res

    def test_fee_processing(self):
        d = load_baseline("FeeBumpTransactionTests.json")
        h = RefHarness()
        acc = SecretKey(named_account_seed("A"))
        _, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            acc.public_key().raw, 2 * h.base_reserve + 2 * h.txfee)]))
        assert_section(
            d, "fee bump transactions|protocol version 19|fee processing",
            [meta])
        # differential: processFeeSeqNum charges the OUTER source 2*fee
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.transactions.fee_bump import \
            FeeBumpTransactionFrame

        env = self._fee_bump_env(h, acc, h.root_sk,
                                 h.root_sk.public_key().raw,
                                 2 * h.txfee, h.txfee, 1, [acc])
        frame = FeeBumpTransactionFrame(h.app.config.network_id(), env)
        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            before = ltx.load_account(
                acc.public_key().raw).data.value.balance
            frame.process_fee_seq_num(ltx, base_fee=h.txfee)
            after = ltx.load_account(
                acc.public_key().raw).data.value.balance
            ltx.rollback()
        assert before == after + 2 * h.txfee

    def test_validity_bad_signature_order(self):
        """Outer signature taken over the envelope BEFORE the inner
        signature was attached -> txBAD_AUTH (ref :139-155)."""
        d = load_baseline("FeeBumpTransactionTests.json")
        h = RefHarness()
        acc = SecretKey(named_account_seed("A"))
        _, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            acc.public_key().raw, 2 * h.base_reserve)]))
        assert_section(
            d, "fee bump transactions|protocol version 19|validity|"
               "bad signatures, signature invalid", [meta])
        # build with wrong-order signing: outer signature over fb whose
        # inner has NO signatures yet
        net = h.app.config.network_id()
        inner_tx = T.Transaction.make(
            sourceAccount=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519,
                h.root_sk.public_key().raw),
            fee=h.txfee, seqNum=h._next_seq(h.root_sk.public_key().raw),
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.Memo.make(T.MemoType.MEMO_NONE),
            operations=[h.op_payment(h.root_sk.public_key().raw, 1)],
            ext=T.Transaction.fields[6][1].make(0))
        fb_unsigned = T.FeeBumpTransaction.make(
            feeSource=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, acc.public_key().raw),
            fee=2 * h.txfee,
            innerTx=T.FeeBumpTransaction.fields[2][1].make(
                T.EnvelopeType.ENVELOPE_TYPE_TX,
                T.TransactionV1Envelope.make(tx=inner_tx, signatures=[])),
            ext=T.FeeBumpTransaction.fields[3][1].make(0))
        outer_payload = T.TransactionSignaturePayload.make(
            networkId=net,
            taggedTransaction=T.TransactionSignaturePayload
            .fields[1][1].make(
                T.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP, fb_unsigned))
        outer_sig = T.DecoratedSignature.make(
            hint=acc.public_key().raw[-4:],
            signature=acc.sign(sha256(
                T.TransactionSignaturePayload.encode(outer_payload))))
        # now sign the inner (mutating what the outer signature covered)
        inner_payload = T.TransactionSignaturePayload.make(
            networkId=net,
            taggedTransaction=T.TransactionSignaturePayload
            .fields[1][1].make(T.EnvelopeType.ENVELOPE_TYPE_TX, inner_tx))
        inner_sig = T.DecoratedSignature.make(
            hint=h.root_sk.public_key().raw[-4:],
            signature=h.root_sk.sign(sha256(
                T.TransactionSignaturePayload.encode(inner_payload))))
        fb = fb_unsigned._replace(
            innerTx=T.FeeBumpTransaction.fields[2][1].make(
                T.EnvelopeType.ENVELOPE_TYPE_TX,
                T.TransactionV1Envelope.make(
                    tx=inner_tx, signatures=[inner_sig])))
        env = T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP,
            T.FeeBumpTransactionEnvelope.make(tx=fb,
                                              signatures=[outer_sig]))
        res = self._check(h, env)
        assert res.code == T.TransactionResultCode.txBAD_AUTH


class TestManageBuyOfferBaselines:
    """manage buy offer failure modes|protocol version 19|negative offerID
    (ManageBuyOfferTests.cpp:1-30,340-353)."""

    def test_negative_offer_id(self):
        d = load_baseline("ManageBuyOfferTests.json")
        h = RefHarness()
        i1 = SecretKey(named_account_seed("issuer1"))
        i2 = SecretKey(named_account_seed("issuer2"))
        for sk in (i1, i2):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, h.min_balance(0) + 100 * h.txfee)]))
        cur1 = h.asset(i1.public_key().raw, b"CUR1")
        op = h._op(T.OperationType.MANAGE_BUY_OFFER,
                   T.ManageBuyOfferOp.make(
                       selling=cur1, buying=h.native(), buyAmount=1,
                       price=T.Price.make(n=1, d=1), offerID=-1))
        res, meta = h.apply_tx(h.tx(i1, [op]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.ManageBuyOfferResultCode.MANAGE_BUY_OFFER_MALFORMED
        assert_section(
            d, "manage buy offer failure modes|protocol version 19|"
               "negative offerID", [meta])


class TestClaimableBalanceBaselines:
    """claimableBalance|protocol version 19|invalid asset
    (ClaimableBalanceTests.cpp:900-951)."""

    def test_invalid_asset(self):
        d = load_baseline("ClaimableBalanceTests.json")
        h = RefHarness()
        acc1 = SecretKey(named_account_seed("acc1"))
        acc2 = SecretKey(named_account_seed("acc2"))
        issuer = SecretKey(named_account_seed("issuer"))
        for sk in (acc1, acc2, issuer):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, h.min_balance(3))]))
        usd = h.asset(issuer.public_key().raw, b"USD")
        h.apply_tx(h.tx(acc2, [h.op_change_trust(usd, INT64_MAX)]))

        def simple_pred(levels):
            if levels == 0:
                return T.ClaimPredicate.make(
                    T.ClaimPredicateType
                    .CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME, INT64_MAX)
            nxt = simple_pred(levels - 1)
            return T.ClaimPredicate.make(
                T.ClaimPredicateType.CLAIM_PREDICATE_OR, [nxt, nxt])

        bad_usd = T.Asset.make(
            T.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
            T.AlphaNum4.make(assetCode=b"\x00SD\x00",
                             issuer=T.account_id(issuer.public_key().raw)))
        claimant = T.Claimant.make(
            T.ClaimantType.CLAIMANT_TYPE_V0,
            T.Claimant.arms[T.ClaimantType.CLAIMANT_TYPE_V0][1].make(
                destination=T.account_id(acc2.public_key().raw),
                predicate=simple_pred(3)))
        op = h._op(T.OperationType.CREATE_CLAIMABLE_BALANCE,
                   T.CreateClaimableBalanceOp.make(
                       asset=bad_usd, amount=100, claimants=[claimant]))
        res, meta = h.apply_tx(h.tx(acc1, [op]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.CreateClaimableBalanceResultCode.\
            CREATE_CLAIMABLE_BALANCE_MALFORMED
        assert_section(
            d, "claimableBalance|protocol version 19|invalid asset", [meta])


class TestRevokeSponsorshipBaselines:
    """update sponsorship|protocol version 19|entry is not sponsored|
    account is not sponsored|account (RevokeSponsorshipTests.cpp:53-74)."""

    def test_account_not_sponsored(self):
        d = load_baseline("RevokeSponsorshipTests.json")
        h = RefHarness()
        a1 = SecretKey(named_account_seed("a1"))
        apub = a1.public_key().raw
        _, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            apub, h.min_balance(1))]))
        assert_section(
            d, "update sponsorship|protocol version 19|"
               "entry is not sponsored|account is not sponsored|account",
            [meta])
        # differential: revoking the (non-)sponsorship of one's own
        # account entry is a success no-op
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.transactions.frame import TransactionFrame

        key = T.LedgerKey.make(
            T.LedgerEntryType.ACCOUNT,
            T.LedgerKey.arms[T.LedgerEntryType.ACCOUNT][1].make(
                accountID=T.account_id(apub)))
        op = h._op(T.OperationType.REVOKE_SPONSORSHIP,
                   T.RevokeSponsorshipOp.make(
                       T.RevokeSponsorshipType
                       .REVOKE_SPONSORSHIP_LEDGER_ENTRY, key))
        frame = TransactionFrame(h.app.config.network_id(),
                                 h.tx(a1, [op]))
        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            assert frame.check_valid(ltx).ok
            ok, result, _ = frame.apply(ltx)
            ltx.rollback()
        assert ok, result


class TestOfferBaselines:
    """create offer|protocol version 19|create offer errors|create offer
    without account (OfferTests.cpp:95-141): a manage-offer tx from a
    NONEXISTENT account fails txNO_ACCOUNT; the reference records its
    (empty-changes) meta via applyCheck with fee processing skipped."""

    def test_create_offer_without_account(self):
        d = load_baseline("OfferTests.json")
        h = RefHarness()
        issuer = SecretKey(named_account_seed("issuer"))
        min_balance2 = h.min_balance(2) + 20 * h.txfee
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            issuer.public_key().raw, min_balance2 * 10)]))
        idr = h.asset(issuer.public_key().raw, b"IDR")
        usd = h.asset(issuer.public_key().raw, b"USD")
        a1 = SecretKey(named_account_seed("a1"))  # never created
        env = h.tx(a1, [h.op_manage_sell_offer(idr, usd, 100, 1, 1)],
                   seq=1)
        # mirror applyCheck's txNO_ACCOUNT branch: empty close to advance
        # the ledger, then apply WITHOUT fee processing, committed
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.transactions.frame import TransactionFrame

        h.close_empty()
        frame = TransactionFrame(h.app.config.network_id(), env)
        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            ok, result, meta = frame.apply(ltx)
            ltx.commit()
        assert not ok
        assert result.result.type == T.TransactionResultCode.txNO_ACCOUNT
        assert_section(
            d, "create offer|protocol version 19|create offer errors|"
               "create offer without account", [meta])


class TestClawbackClaimableBalanceBaselines:
    """clawbackClaimableBalance|protocol version 19|basic test
    (ClawbackClaimableBalanceTests.cpp:1-71)."""

    def test_basic(self):
        d = load_baseline("ClawbackClaimableBalanceTests.json")
        h = RefHarness()
        a1 = SecretKey(named_account_seed("A1"))
        gw = SecretKey(named_account_seed("gw"))
        apub, gpub = a1.public_key().raw, gw.public_key().raw
        for sk in (a1, gw):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, h.min_balance(4))]))
        idr = h.asset(gpub, b"IDR")
        # v17+ setup (parent key): clawback-enabled + revocable, trust, pay
        h.apply_tx(h.tx(gw, [h.op_set_options(set_flags=0x8 | 0x2)]))
        h.apply_tx(h.tx(a1, [h.op_change_trust(idr, 1000)]))
        h.apply_tx(h.tx(gw, [h.op_payment(apub, 100, asset=idr)]))
        metas = []
        claimant = T.Claimant.make(
            T.ClaimantType.CLAIMANT_TYPE_V0,
            T.Claimant.arms[T.ClaimantType.CLAIMANT_TYPE_V0][1].make(
                destination=T.account_id(gpub),
                predicate=T.ClaimPredicate.make(
                    T.ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL)))
        res, m1 = h.apply_tx(h.tx(a1, [h._op(
            T.OperationType.CREATE_CLAIMABLE_BALANCE,
            T.CreateClaimableBalanceOp.make(
                asset=idr, amount=99, claimants=[claimant]))]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.CreateClaimableBalanceResultCode.\
            CREATE_CLAIMABLE_BALANCE_SUCCESS
        balance_id = opr.value.value.value
        metas.append(m1)
        CB = T.ClawbackClaimableBalanceResultCode
        res, m2 = h.apply_tx(h.tx(gw, [h._op(
            T.OperationType.CLAWBACK_CLAIMABLE_BALANCE,
            T.ClawbackClaimableBalanceOp.make(balanceID=balance_id))]))
        assert res.result.result.value[0].value.value.type == \
            CB.CLAWBACK_CLAIMABLE_BALANCE_SUCCESS
        metas.append(m2)
        res, m3 = h.apply_tx(h.tx(gw, [h._op(
            T.OperationType.CLAIM_CLAIMABLE_BALANCE,
            T.ClaimClaimableBalanceOp.make(balanceID=balance_id))]))
        assert res.result.result.value[0].value.value.type == \
            T.ClaimClaimableBalanceResultCode.\
            CLAIM_CLAIMABLE_BALANCE_DOES_NOT_EXIST
        metas.append(m3)
        res, m4 = h.apply_tx(h.tx(gw, [h._op(
            T.OperationType.CLAWBACK_CLAIMABLE_BALANCE,
            T.ClawbackClaimableBalanceOp.make(balanceID=balance_id))]))
        assert res.result.result.value[0].value.value.type == \
            CB.CLAWBACK_CLAIMABLE_BALANCE_DOES_NOT_EXIST
        metas.append(m4)
        assert_section(
            d, "clawbackClaimableBalance|protocol version 19|basic test",
            metas)


class TestPathPaymentBaselines:
    """pathpayment|protocol version 19|issuer missing|path payment middle
    issuer missing (PathPaymentTests.cpp:663-712)."""

    def test_middle_issuer_missing(self):
        d = load_baseline("PathPaymentTests.json")
        h = RefHarness()
        gate = SecretKey(named_account_seed("gate"))
        gate2 = SecretKey(named_account_seed("gate2"))
        min_balance2 = h.min_balance(2) + 10 * h.txfee
        min_balance3 = h.min_balance(3) + 10 * h.txfee
        gateway_payment = min_balance2 + min_balance3 // 2
        for sk in (gate, gate2):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, gateway_payment)]))
        idr = h.asset(gate.public_key().raw, b"IDR")
        usd = h.asset(gate2.public_key().raw, b"USD")
        # section fixture (parent key): source/destination + trusts + pay
        src = SecretKey(named_account_seed("source"))
        dst = SecretKey(named_account_seed("destination"))
        min_balance1 = h.min_balance(1) + 10 * h.txfee
        for sk in (src, dst):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, min_balance1)]))
        h.apply_tx(h.tx(src, [h.op_change_trust(idr, 20)]))
        h.apply_tx(h.tx(dst, [h.op_change_trust(usd, 20)]))
        h.apply_tx(h.tx(gate, [h.op_payment(
            src.public_key().raw, 10, asset=idr)]))
        # leaf: strict-receive through a path whose middle issuer is gone
        btc = h.asset(SecretKey(
            named_account_seed("missing")).public_key().raw, b"BTC")
        op = h._op(T.OperationType.PATH_PAYMENT_STRICT_RECEIVE,
                   T.PathPaymentStrictReceiveOp.make(
                       sendAsset=idr, sendMax=11,
                       destination=T.MuxedAccount.make(
                           T.CryptoKeyType.KEY_TYPE_ED25519,
                           dst.public_key().raw),
                       destAsset=usd, destAmount=11, path=[btc]))
        res, meta = h.apply_tx(h.tx(src, [op]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.PathPaymentStrictReceiveResultCode.\
            PATH_PAYMENT_STRICT_RECEIVE_TOO_FEW_OFFERS
        assert_section(
            d, "pathpayment|protocol version 19|issuer missing|"
               "path payment middle issuer missing", [meta])


class TestPathPaymentStrictSendBaselines:
    """pathpayment strict send|protocol version 19|issuer missing|path
    payment middle issuer missing (PathPaymentStrictSendTests.cpp:563-612)."""

    def test_middle_issuer_missing(self):
        d = load_baseline("PathPaymentStrictSendTests.json")
        h = RefHarness()
        gate = SecretKey(named_account_seed("gate1"))
        gate2 = SecretKey(named_account_seed("gate2"))
        min_balance5 = h.min_balance(5) + 10 * h.txfee
        for sk in (gate, gate2):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, min_balance5)]))
        idr = h.asset(gate.public_key().raw, b"IDR")
        usd = h.asset(gate2.public_key().raw, b"USD")
        src = SecretKey(named_account_seed("source"))
        dst = SecretKey(named_account_seed("destination"))
        min_balance1 = h.min_balance(1) + 10 * h.txfee
        for sk in (src, dst):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, min_balance1)]))
        h.apply_tx(h.tx(src, [h.op_change_trust(idr, 20)]))
        h.apply_tx(h.tx(dst, [h.op_change_trust(usd, 20)]))
        h.apply_tx(h.tx(gate, [h.op_payment(
            src.public_key().raw, 10, asset=idr)]))
        btc = h.asset(SecretKey(
            named_account_seed("missing")).public_key().raw, b"BTC")
        op = h._op(T.OperationType.PATH_PAYMENT_STRICT_SEND,
                   T.PathPaymentStrictSendOp.make(
                       sendAsset=idr, sendAmount=10,
                       destination=T.MuxedAccount.make(
                           T.CryptoKeyType.KEY_TYPE_ED25519,
                           dst.public_key().raw),
                       destAsset=usd, destMin=10, path=[btc]))
        res, meta = h.apply_tx(h.tx(src, [op]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.PathPaymentStrictSendResultCode.\
            PATH_PAYMENT_STRICT_SEND_TOO_FEW_OFFERS
        assert_section(
            d, "pathpayment strict send|protocol version 19|"
               "issuer missing|path payment middle issuer missing",
            [meta])


class TestTxEnvelopeBaselines:
    """txenvelope|protocol version 19|batching|empty batch
    (TxEnvelopeTests.cpp:1680-1696): a zero-op tx with fee 1000 fails
    txMISSING_OPERATION at apply and still records its (empty) meta."""

    def test_empty_batch(self):
        d = load_baseline("TxEnvelopeTests.json")
        h = RefHarness()
        env = h.tx(h.root_sk, [], fee=1000)
        res, meta = h.apply_tx(env)
        assert res.result.result.type == \
            T.TransactionResultCode.txMISSING_OPERATION
        assert_section(
            d, "txenvelope|protocol version 19|batching|empty batch",
            [meta])


class TestLiquidityPoolWithdrawBaselines:
    """liquidity pool withdraw|protocol version 19|malformed
    (LiquidityPoolWithdrawTests.cpp:1-45)."""

    def test_malformed(self):
        d = load_baseline("LiquidityPoolWithdrawTests.json")
        h = RefHarness()
        acc1 = SecretKey(named_account_seed("acc1"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            acc1.public_key().raw, h.min_balance(10))]))
        LW = T.LiquidityPoolWithdrawResultCode
        metas = []
        for amount, min_a, min_b in ((0, 1, 1), (1, -1, 1), (1, 1, -1)):
            op = h._op(T.OperationType.LIQUIDITY_POOL_WITHDRAW,
                       T.LiquidityPoolWithdrawOp.make(
                           liquidityPoolID=b"\x00" * 32,
                           amount=amount, minAmountA=min_a,
                           minAmountB=min_b))
            res, meta = h.apply_tx(h.tx(acc1, [op]))
            opr = res.result.result.value[0]
            assert opr.value.value.type == \
                LW.LIQUIDITY_POOL_WITHDRAW_MALFORMED
            metas.append(meta)
        assert_section(
            d, "liquidity pool withdraw|protocol version 19|malformed",
            metas)


class TestLiquidityPoolDepositBaselines:
    """liquidity pool deposit|protocol version 19|validity checks
    (LiquidityPoolDepositTests.cpp:45-95): 13 MALFORMED deposits from the
    root account (no fixture)."""

    def test_validity_checks(self):
        d = load_baseline("LiquidityPoolDepositTests.json")
        h = RefHarness()
        LD = T.LiquidityPoolDepositResultCode
        cases = [
            (0, 100, (1, 1), (1, 1)), (-1, 100, (1, 1), (1, 1)),
            (100, 0, (1, 1), (1, 1)), (100, -1, (1, 1), (1, 1)),
            (100, 100, (0, 1), (1, 1)), (100, 100, (-1, 1), (1, 1)),
            (100, 100, (1, 0), (1, 1)), (100, 100, (1, -1), (1, 1)),
            (100, 100, (1, 1), (0, 1)), (100, 100, (1, 1), (-1, 1)),
            (100, 100, (1, 1), (1, 0)), (100, 100, (1, 1), (1, -1)),
            (100, 100, (2, 1), (1, 1)),
        ]
        metas = []
        for max_a, max_b, min_p, max_p in cases:
            op = h._op(T.OperationType.LIQUIDITY_POOL_DEPOSIT,
                       T.LiquidityPoolDepositOp.make(
                           liquidityPoolID=b"\x00" * 32,
                           maxAmountA=max_a, maxAmountB=max_b,
                           minPrice=T.Price.make(n=min_p[0], d=min_p[1]),
                           maxPrice=T.Price.make(n=max_p[0], d=max_p[1])))
            res, meta = h.apply_tx(h.tx(h.root_sk, [op]))
            opr = res.result.result.value[0]
            assert opr.value.value.type == \
                LD.LIQUIDITY_POOL_DEPOSIT_MALFORMED, (max_a, max_b)
            metas.append(meta)
        assert_section(
            d, "liquidity pool deposit|protocol version 19|"
               "validity checks", metas)


class TestLiquidityPoolTradeBaselines:
    """liquidity pool trade|protocol version 19|CUR1, CUR2|payment through
    a pool that the sender participates in|strict receive
    (LiquidityPoolTradeTests.cpp:410-435, 1203-1206): a real pool deposit
    followed by a strict-receive path payment routed through the pool."""

    def test_sender_participates_strict_receive(self):
        d = load_baseline("LiquidityPoolTradeTests.json")
        h = RefHarness()
        rpub = h.root_sk.public_key().raw
        cur1 = h.asset(rpub, b"CUR1")
        cur2 = h.asset(rpub, b"CUR2")
        params = T.LiquidityPoolConstantProductParameters.make(
            assetA=cur1, assetB=cur2, fee=T.LIQUIDITY_POOL_FEE_V18)
        lp_params = T.LiquidityPoolParameters.make(
            T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT, params)
        share12 = T.ChangeTrustAsset.make(
            T.AssetType.ASSET_TYPE_POOL_SHARE, lp_params)
        pool12 = sha256(T.LiquidityPoolParameters.encode(lp_params))
        a1 = SecretKey(named_account_seed("a1"))
        a2 = SecretKey(named_account_seed("a2"))
        apub, a2pub = a1.public_key().raw, a2.public_key().raw
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            apub, h.min_balance(10))]))
        h.apply_tx(h.tx(a1, [h.op_change_trust(cur1, INT64_MAX)]))
        h.apply_tx(h.tx(a1, [h.op_change_trust(cur2, INT64_MAX)]))
        h.apply_tx(h.tx(a1, [h.op_change_trust(share12, INT64_MAX)]))
        h.apply_tx(h.tx(h.root_sk, [h.op_payment(apub, 10000,
                                                 asset=cur1)]))
        h.apply_tx(h.tx(h.root_sk, [h.op_payment(apub, 10000,
                                                 asset=cur2)]))
        dep = h._op(T.OperationType.LIQUIDITY_POOL_DEPOSIT,
                    T.LiquidityPoolDepositOp.make(
                        liquidityPoolID=pool12,
                        maxAmountA=1000, maxAmountB=1000,
                        minPrice=T.Price.make(n=1, d=2**31 - 1),
                        maxPrice=T.Price.make(n=2**31 - 1, d=1)))
        res, _ = h.apply_tx(h.tx(a1, [dep]))
        assert res.result.result.value[0].value.value.type == \
            T.LiquidityPoolDepositResultCode.LIQUIDITY_POOL_DEPOSIT_SUCCESS
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            a2pub, h.min_balance(10))]))
        h.apply_tx(h.tx(a2, [h.op_change_trust(cur2, INT64_MAX)]))
        # leaf: strict receive cur1 -> cur2 through the pool
        op = h._op(T.OperationType.PATH_PAYMENT_STRICT_RECEIVE,
                   T.PathPaymentStrictReceiveOp.make(
                       sendAsset=cur1, sendMax=10,
                       destination=T.MuxedAccount.make(
                           T.CryptoKeyType.KEY_TYPE_ED25519, a2pub),
                       destAsset=cur2, destAmount=9, path=[]))
        res, meta = h.apply_tx(h.tx(a1, [op]))
        opr = res.result.result.value[0]
        assert opr.value.value.type == \
            T.PathPaymentStrictReceiveResultCode.\
            PATH_PAYMENT_STRICT_RECEIVE_SUCCESS
        assert_section(
            d, "liquidity pool trade|protocol version 19|CUR1, CUR2|"
               "payment through a pool that the sender participates in|"
               "strict receive", [meta])


class TestTxEnvelopeAltSignatureBaselines:
    """txenvelope|protocol version 19|alternative signatures|hash x|
    single signature|merge source account before payment|merge op source
    account (TxEnvelopeTests.cpp:738-1013): a 3-op multi-source
    SetOptions installing HASH_X signers, signed by root+a1+b1 — the
    recorded leaf meta — followed (unrecorded) by the strict-order
    merge+payment close the section exists for."""

    def test_hash_x_merge_op_source(self):
        d = load_baseline("TxEnvelopeTests.json")
        h = RefHarness()
        payment_amount = h.base_reserve * 10
        a1 = SecretKey(named_account_seed("A"))
        b1 = SecretKey(named_account_seed("b1"))
        apub, bpub = a1.public_key().raw, b1.public_key().raw
        rpub = h.root_sk.public_key().raw
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            apub, payment_amount)]))
        # parent-section fixture
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            bpub, payment_amount)]))
        h.apply_tx(h.tx(a1, [h.op_payment(bpub, 1000)]))
        # x with embedded NULs (the section's point)
        x = bytes([97, 98, 99, 0, 100, 101, 102, 0,
                   0, 0, 103, 104, 105, 106, 107, 108,
                   65, 66, 67, 0, 68, 69, 70, 0,
                   0, 0, 71, 72, 73, 74, 75, 76])
        hx = sha256(x)
        signer_key = T.SignerKey.make(
            T.SignerKeyType.SIGNER_KEY_TYPE_HASH_X, hx)
        signer = T.Signer.make(key=signer_key, weight=1)
        # tx/op construction order fixes the local seq bookkeeping:
        # txMerge consumes b1's next seq, payment tx consumes a1's
        merge_env = h.tx(b1, [h.op_merge(apub)])
        pay_env_seq = h._next_seq(apub)  # a1.tx(...) in the reference
        # leaf: the signer-installing tx (root tx source; a1/b1 op
        # sources; signed by all three)
        set_signer_env = h.tx(h.root_sk, [
            h.op_set_options(signer=signer),
            h.op_set_options(signer=signer, source=apub),
            h.op_set_options(signer=signer, source=bpub),
        ], extra_signers=[a1, b1])
        res, meta = h.apply_tx(set_signer_env)
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        assert_section(
            d, "txenvelope|protocol version 19|alternative signatures|"
               "hash x|single signature|merge source account before "
               "payment|merge op source account", [meta])
        # differential follow-through (unrecorded in the corpus): the
        # hash-x-signed payment whose OP source (b1) was merged away
        # fails txFAILED with opBAD_AUTH from the signature probe (see
        # the assertion below)
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn
        from stellar_core_tpu.transactions.frame import TransactionFrame

        pay_tx = T.Transaction.make(
            sourceAccount=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, apub),
            fee=2 * h.txfee, seqNum=pay_env_seq,
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.Memo.make(T.MemoType.MEMO_NONE),
            operations=[
                h.op_payment(rpub, 110, source=bpub),
                h.op_payment(apub, 101, source=rpub)],
            ext=T.Transaction.fields[6][1].make(0))
        pay_env = T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=pay_tx, signatures=[
                T.DecoratedSignature.make(hint=hx[-4:], signature=x)]))
        _, _ = h.apply_tx(merge_env)  # b1 merged into a1
        frame = TransactionFrame(h.app.config.network_id(), pay_env)
        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            ok, result, _ = frame.apply(ltx)
            ltx.rollback()
        assert not ok
        assert result.result.type == T.TransactionResultCode.txFAILED
        ops = result.result.value
        # reference: the probe's checkSignatureNoAccount finds no
        # matching master-key signature (the tx is hash-x-signed) and
        # fails the op with opBAD_AUTH (SignatureChecker returns false
        # even at neededWeight 0 when nothing matches)
        assert ops[0].type == T.OperationResultCode.opBAD_AUTH
