"""Parity against the REFERENCE's committed tx-meta baseline corpus
(/root/reference/test-tx-meta-baseline-current/*.json — the BASELINE.md
correctness gate: "bit-identical TxResults vs test-tx-meta-baseline-
current").

Each baseline file maps a Catch2 section path (e.g. "create account|
protocol version 19|Success") to the 64-bit SipHash-2-4 of every
NORMALIZED TransactionMeta recorded while that section ran (ref
src/test/test.cpp:620 recordOrCheckGlobalTestTxMetadata;
src/util/MetaUtils.cpp normalizeMeta; shortHash seeded from the file's
"!rng seed" via ShortHash.cpp seed()).

Reproducing a value requires replaying the reference test's exact
fixtures — which ARE deterministic: test network passphrase
"(V) (;,,;) (V)" (test.cpp), root key seeded by the network id, named
accounts seeded by the name '.'-padded to 32 bytes (TxTests.cpp:574),
genesis base fee 100 / base reserve 100000000 / maxTxSetSize 50 / total
coins 10^18 (LedgerManagerImpl.cpp:88-93, Config.cpp:197-199), fee =
100 * ops, and closes that keep closeTime at 0 (TxTests closeLedger
reuses the last close time).  This file replays a set of scenarios
through the REAL close path and asserts hash equality at protocol 19.

Reproducibility notes for the rest of the corpus (VERDICT r4 task #7):
scenarios whose fixtures use Catch2's PRNG (SecretKey::
pseudoRandomForTesting, rng-seeded amounts) or TestMarket state are
keyed to Catch2 internals and need those exact streams; everything
fixture-deterministic (named accounts + constant amounts) is
reconstructible the same way as the scenarios below.
"""
import base64
import json
import os

import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.crypto.shorthash import siphash24
from stellar_core_tpu.herder.tx_set import TxSetFrame
from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

REFERENCE_DIR = "/root/reference/test-tx-meta-baseline-current"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_DIR),
    reason="reference baseline corpus not mounted")

TEST_PASSPHRASE = "(V) (;,,;) (V)"  # ref test.cpp getTestConfig


def load_baseline(name):
    with open(os.path.join(REFERENCE_DIR, name)) as f:
        return json.load(f)


def shorthash_key(seed: int) -> bytes:
    """ref ShortHash.cpp seed(): key[i] = byte of (seed >> (i % 4))."""
    return bytes((seed >> (i % 4)) & 0xFF for i in range(16))


# -- meta normalization (ref src/util/MetaUtils.cpp) ------------------------

_TYPE_ORDER = {  # STATE first, then CREATED, UPDATED, REMOVED
    T.LedgerEntryChangeType.LEDGER_ENTRY_STATE: 0,
    T.LedgerEntryChangeType.LEDGER_ENTRY_CREATED: 1,
    T.LedgerEntryChangeType.LEDGER_ENTRY_UPDATED: 2,
    T.LedgerEntryChangeType.LEDGER_ENTRY_REMOVED: 3,
}


def _change_key(change) -> bytes:
    if change.type == T.LedgerEntryChangeType.LEDGER_ENTRY_REMOVED:
        return key_bytes(change.value)
    return key_bytes(entry_to_key(change.value))


def _sorted_changes(changes):
    return sorted(changes, key=lambda c: (
        _change_key(c), _TYPE_ORDER[c.type],
        sha256(T.LedgerEntryChange.encode(c))))


def normalize_meta(meta):
    """Sorted-changes copy of a TransactionMeta (v2)."""
    assert meta.type == 2
    v2 = meta.value
    ops = [T.OperationMeta.make(changes=_sorted_changes(om.changes))
           for om in v2.operations]
    return T.TransactionMeta.make(2, T.TransactionMetaV2.make(
        txChangesBefore=_sorted_changes(v2.txChangesBefore),
        operations=ops,
        txChangesAfter=_sorted_changes(v2.txChangesAfter)))


def meta_hash_b64(meta, rng_seed: int) -> str:
    h = siphash24(shorthash_key(rng_seed),
                  T.TransactionMeta.encode(normalize_meta(meta)))
    # the corpus stores each uint64 base64'd in big-endian byte order
    # (ref test.cpp saveTestTxMeta :815)
    return base64.b64encode(h.to_bytes(8, "big")).decode()


# -- reference test fixtures ------------------------------------------------

def named_account_seed(name: str) -> bytes:
    """ref txtest::getAccount: the name '.'-padded to 32 bytes IS the
    ed25519 seed."""
    return (name + "." * 32)[:32].encode()


class RefHarness:
    """A node configured exactly like the reference's createTestApplication
    + getTestConfig, applying txs one per close with closeTime pinned at 0
    (ref txtest::closeLedger reusing the last close time)."""

    def __init__(self):
        self.app = Application(
            VirtualClock(ClockMode.VIRTUAL_TIME),
            test_config(
                NETWORK_PASSPHRASE=TEST_PASSPHRASE,
                TESTING_UPGRADE_RESERVE=100000000,
                TESTING_UPGRADE_MAX_TX_SET_SIZE=50,
            ))
        self.app.start()
        self.root_sk = SecretKey(self.app.config.network_id())
        self.base_reserve = 100000000
        self.txfee = 100
        self.seqs = {}  # account raw pubkey -> last seq consumed

    def min_balance(self, entries: int) -> int:
        return (2 + entries) * self.base_reserve

    def _next_seq(self, pub: bytes) -> int:
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        if pub not in self.seqs:
            with LedgerTxn(self.app.ledger_manager.root) as ltx:
                e = ltx.load_account(pub)
                self.seqs[pub] = e.data.value.seqNum
                ltx.rollback()
        self.seqs[pub] += 1
        return self.seqs[pub]

    def tx(self, sk: SecretKey, ops, seq=None, extra_signers=()):
        """transactionFromOperationsV1: fee = ops * 100, no memo/bounds.
        ``extra_signers`` mirrors TestAccount::tx + addSignature."""
        pub = sk.public_key().raw
        tx = T.Transaction.make(
            sourceAccount=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, pub),
            fee=len(ops) * self.txfee,
            seqNum=self._next_seq(pub) if seq is None else seq,
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.Memo.make(T.MemoType.MEMO_NONE),
            operations=ops,
            ext=T.Transaction.fields[6][1].make(0))
        payload = T.TransactionSignaturePayload.make(
            networkId=self.app.config.network_id(),
            taggedTransaction=T.TransactionSignaturePayload
            .fields[1][1].make(T.EnvelopeType.ENVELOPE_TYPE_TX, tx))
        h = sha256(T.TransactionSignaturePayload.encode(payload))
        sigs = []
        for signer in (sk, *extra_signers):
            spub = signer.public_key().raw
            sigs.append(T.DecoratedSignature.make(
                hint=spub[-4:], signature=signer.sign(h)))
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=tx, signatures=sigs))

    # -- op builders (ref TxTests.cpp op factories) ------------------------

    def _op(self, body_type, body_value=None, source: bytes = None):
        return T.Operation.make(
            sourceAccount=(None if source is None else T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, source)),
            body=T.OperationBody.make(body_type, body_value))

    def op_bump_seq(self, to: int, source=None):
        return self._op(T.OperationType.BUMP_SEQUENCE,
                        T.BumpSequenceOp.make(bumpTo=to), source)

    def op_merge(self, dest_pub: bytes, source=None):
        return self._op(T.OperationType.ACCOUNT_MERGE,
                        T.MuxedAccount.make(
                            T.CryptoKeyType.KEY_TYPE_ED25519, dest_pub),
                        source)

    def op_inflation(self, source=None):
        return self._op(T.OperationType.INFLATION, None, source)

    def op_change_trust(self, asset, limit: int, source=None):
        return self._op(
            T.OperationType.CHANGE_TRUST,
            T.ChangeTrustOp.make(
                line=T.ChangeTrustAsset.make(asset.type, asset.value),
                limit=limit), source)

    def op_manage_data(self, name: bytes, value, source=None):
        return self._op(T.OperationType.MANAGE_DATA,
                        T.ManageDataOp.make(dataName=name, dataValue=value),
                        source)

    def op_set_options(self, source=None, **kw):
        return self._op(T.OperationType.SET_OPTIONS, T.SetOptionsOp.make(
            inflationDest=kw.get("inflation_dest"),
            clearFlags=kw.get("clear_flags"),
            setFlags=kw.get("set_flags"),
            masterWeight=kw.get("master_weight"),
            lowThreshold=kw.get("low"),
            medThreshold=kw.get("med"),
            highThreshold=kw.get("high"),
            homeDomain=kw.get("home_domain"),
            signer=kw.get("signer")), source)

    def op_manage_sell_offer(self, selling, buying, amount: int,
                             price_n: int, price_d: int, offer_id: int = 0,
                             source=None):
        return self._op(T.OperationType.MANAGE_SELL_OFFER,
                        T.ManageSellOfferOp.make(
                            selling=selling, buying=buying, amount=amount,
                            price=T.Price.make(n=price_n, d=price_d),
                            offerID=offer_id), source)

    def asset(self, issuer_pub: bytes, code: bytes):
        """makeAsset: 4-char alphanum asset."""
        return T.Asset.make(
            T.AssetType.ASSET_TYPE_CREDIT_ALPHANUM4,
            T.AlphaNum4.make(assetCode=code.ljust(4, b"\x00"),
                             issuer=T.account_id(issuer_pub)))

    def native(self):
        return T.Asset.make(T.AssetType.ASSET_TYPE_NATIVE)

    def op_create_account(self, dest_pub: bytes, balance: int,
                          source=None):
        return self._op(T.OperationType.CREATE_ACCOUNT,
                        T.CreateAccountOp.make(
                            destination=T.account_id(dest_pub),
                            startingBalance=balance), source)

    def op_payment(self, dest_pub: bytes, amount: int, asset=None):
        return T.Operation.make(
            sourceAccount=None,
            body=T.Operation.fields[1][1].make(
                T.OperationType.PAYMENT,
                T.PaymentOp.make(
                    destination=T.MuxedAccount.make(
                        T.CryptoKeyType.KEY_TYPE_ED25519, dest_pub),
                    asset=(asset if asset is not None else
                           T.Asset.make(T.AssetType.ASSET_TYPE_NATIVE)),
                    amount=amount)))

    def close_empty(self, close_time=None):
        """txtest::closeLedger(app) / closeLedgerOn with no txs."""
        lm = self.app.ledger_manager
        prev = lm.last_closed_header()
        xdr_set = T.TransactionSet.make(
            previousLedgerHash=lm.last_closed_hash(), txs=[])
        tx_set = TxSetFrame.make_from_wire(
            self.app.config.network_id(), xdr_set)
        sv = T.StellarValue.make(
            txSetHash=tx_set.contents_hash(),
            closeTime=(prev.scpValue.closeTime if close_time is None
                       else close_time),
            upgrades=[],
            ext=T.StellarValue.fields[3][1].make(
                T.StellarValueType.STELLAR_VALUE_BASIC))
        from stellar_core_tpu.herder.herder import LedgerCloseData

        lm.close_ledger(LedgerCloseData(lm.last_closed_seq() + 1,
                                        tx_set, sv))

    def apply_tx(self, env):
        """One tx in its own close, closeTime = last close time (stays 0);
        returns (tx_result, TransactionMeta) from the real close path."""
        lm = self.app.ledger_manager
        seq = lm.last_closed_seq() + 1
        prev = lm.last_closed_header()
        xdr_set = T.TransactionSet.make(
            previousLedgerHash=lm.last_closed_hash(), txs=[env])
        tx_set = TxSetFrame.make_from_wire(
            self.app.config.network_id(), xdr_set)
        sv = T.StellarValue.make(
            txSetHash=tx_set.contents_hash(),
            closeTime=prev.scpValue.closeTime,
            upgrades=[],
            ext=T.StellarValue.fields[3][1].make(
                T.StellarValueType.STELLAR_VALUE_BASIC))
        from stellar_core_tpu.herder.herder import LedgerCloseData

        lm.close_ledger(LedgerCloseData(seq, tx_set, sv))
        cur = self.app.database.cursor()
        row = cur.execute(
            "SELECT txresult, txmeta FROM txhistory WHERE ledgerseq=? "
            "ORDER BY txindex", (seq,)).fetchall()
        assert len(row) == 1
        result = T.TransactionResultPair.decode(row[0][0])
        meta = T.TransactionMeta.decode(row[0][1])
        return result, meta


# -- scenarios --------------------------------------------------------------

class TestCreateAccountBaselines:
    """create account|protocol version 19|... scenarios from
    CreateAccountTests.cpp, replayed step-for-step."""

    def test_success(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        b_sk = SecretKey(named_account_seed("B"))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        got = meta_hash_b64(meta, seed)
        want = d["create account|protocol version 19|Success"]
        assert got == want[0], f"meta hash {got} != reference {want[0]}"

    def test_success_account_already_exists(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        b_sk = SecretKey(named_account_seed("B"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.CreateAccountResultCode.CREATE_ACCOUNT_ALREADY_EXIST
        got = meta_hash_b64(meta, seed)
        want = d["create account|protocol version 19|Success|"
                 "Account already exists"]
        assert got == want[0]

    def test_not_enough_funds(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        gateway_payment = h.min_balance(2) + 10 * h.txfee + 1
        gate_sk = SecretKey(named_account_seed("gate"))
        _, meta1 = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            gate_sk.public_key().raw, gateway_payment)]))
        res, meta2 = h.apply_tx(h.tx(gate_sk, [h.op_create_account(
            SecretKey(named_account_seed("B")).public_key().raw,
            gateway_payment)]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.CreateAccountResultCode.CREATE_ACCOUNT_UNDERFUNDED
        want = d["create account|protocol version 19|"
                 "Not enough funds (source)"]
        assert [meta_hash_b64(meta1, seed),
                meta_hash_b64(meta2, seed)] == want


def assert_section(d, key, metas):
    """Assert the section's recorded hash list equals our replayed metas."""
    seed = d["!rng seed"]
    got = [meta_hash_b64(m, seed) for m in metas]
    assert got == d[key], f"{key}: {got} != {d[key]}"


INT64_MAX = 2**63 - 1


class TestBumpSequenceBaselines:
    """bump sequence|protocol version 19|... (BumpSequenceTests.cpp:26-101).
    Fixture: A and B created with minBalance(0)+1000."""

    def _fixture(self):
        h = RefHarness()
        a = SecretKey(named_account_seed("A"))
        b = SecretKey(named_account_seed("B"))
        for sk in (a, b):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, h.min_balance(0) + 1000)]))
        return h, a, b

    def _seq(self, h, sk):
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            e = ltx.load_account(sk.public_key().raw)
            ltx.rollback()
        return e.data.value.seqNum

    def test_small_bump(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        new_seq = self._seq(h, a) + 2
        _, meta = h.apply_tx(h.tx(a, [h.op_bump_seq(new_seq)]))
        assert self._seq(h, a) == new_seq
        assert_section(
            d, "bump sequence|protocol version 19|test success|small bump",
            [meta])

    def test_large_bump_and_int64_max(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        _, meta = h.apply_tx(h.tx(a, [h.op_bump_seq(INT64_MAX)]))
        assert self._seq(h, a) == INT64_MAX
        assert_section(
            d, "bump sequence|protocol version 19|test success|large bump",
            [meta])
        # SequenceNumber::min() == 0 -> txBAD_SEQ, recorded anyway
        res, meta2 = h.apply_tx(h.tx(
            a, [h.op_payment(h.root_sk.public_key().raw, 1)], seq=0))
        assert res.result.result.type == T.TransactionResultCode.txBAD_SEQ
        assert_section(
            d, "bump sequence|protocol version 19|test success|large bump|"
               "no more tx when INT64_MAX is reached", [meta2])

    def test_backward_jump_noop(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        old = self._seq(h, a)
        _, meta = h.apply_tx(h.tx(a, [h.op_bump_seq(1)]))
        assert self._seq(h, a) == old + 1
        assert_section(
            d, "bump sequence|protocol version 19|test success|"
               "backward jump (no-op)", [meta])

    def test_bad_seq(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        res1, m1 = h.apply_tx(h.tx(a, [h.op_bump_seq(-1)]))
        res2, m2 = h.apply_tx(h.tx(a, [h.op_bump_seq(-(2**63))]))
        for res in (res1, res2):
            op = res.result.result.value[0]
            assert op.value.value.type == \
                T.BumpSequenceResultCode.BUMP_SEQUENCE_BAD_SEQ
        assert_section(
            d, "bump sequence|protocol version 19|test success|bad seq",
            [m1, m2])

    def test_seqnum_equals_starting_sequence(self):
        d = load_baseline("BumpSequenceTests.json")
        h, a, b = self._fixture()
        ledger_seq = h.app.ledger_manager.last_closed_seq() + 2
        new_seq = (ledger_seq << 32) - 1
        _, m1 = h.apply_tx(h.tx(a, [h.op_bump_seq(new_seq)]))
        assert self._seq(h, a) == new_seq
        res, m2 = h.apply_tx(h.tx(
            a, [h.op_payment(h.root_sk.public_key().raw, 1)]))
        assert res.result.result.type == T.TransactionResultCode.txBAD_SEQ
        assert_section(
            d, "bump sequence|protocol version 19|"
               "seqnum equals starting sequence", [m1, m2])


class TestMergeBaselines:
    """merge|protocol version 19|... (MergeTests.cpp:35-175).
    Fixture: A (2*minBalance), B (minBalance), gate (minBalance) where
    minBalance = getLastMinBalance(5) + 20*txfee."""

    def _fixture(self):
        h = RefHarness()
        min_bal = h.min_balance(5) + 20 * h.txfee
        a1 = SecretKey(named_account_seed("A"))
        b1 = SecretKey(named_account_seed("B"))
        gate = SecretKey(named_account_seed("gate"))
        for sk, bal in ((a1, 2 * min_bal), (b1, min_bal), (gate, min_bal)):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, bal)]))
        return h, a1, b1

    def test_merge_into_self(self):
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        res, meta = h.apply_tx(h.tx(a1, [h.op_merge(a1.public_key().raw)]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.AccountMergeResultCode.ACCOUNT_MERGE_MALFORMED
        assert_section(d, "merge|protocol version 19|merge into self",
                       [meta])

    def test_merge_into_non_existent(self):
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        c = SecretKey(named_account_seed("C"))
        res, meta = h.apply_tx(h.tx(a1, [h.op_merge(c.public_key().raw)]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.AccountMergeResultCode.ACCOUNT_MERGE_NO_ACCOUNT
        assert_section(
            d, "merge|protocol version 19|merge into non existent account",
            [meta])

    def test_with_create_seqnum_too_far(self):
        """merge+create+merge in one tx: the re-merge hits
        SEQNUM_TOO_FAR at protocol >= 10 (the account was just recreated
        with a starting seqnum beyond the current ledger)."""
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        create_balance = h.min_balance(1)
        apub, bpub = a1.public_key().raw, b1.public_key().raw
        env = h.tx(a1, [
            h.op_merge(bpub, source=apub),
            h.op_create_account(apub, create_balance, source=bpub),
            h.op_merge(bpub, source=apub),
        ], extra_signers=[b1])
        res, meta = h.apply_tx(env)
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        ops = res.result.result.value
        assert ops[2].value.value.type == \
            T.AccountMergeResultCode.ACCOUNT_MERGE_SEQNUM_TOO_FAR
        assert_section(d, "merge|protocol version 19|with create", [meta])

    def test_merge_create_merge_back(self):
        d = load_baseline("MergeTests.json")
        h, a1, b1 = self._fixture()
        create_balance = h.min_balance(1)
        apub, bpub = a1.public_key().raw, b1.public_key().raw
        env = h.tx(a1, [
            h.op_merge(bpub, source=apub),
            h.op_create_account(apub, create_balance, source=bpub),
            h.op_merge(apub, source=bpub),
        ], extra_signers=[b1])
        res, meta = h.apply_tx(env)
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        with LedgerTxn(h.app.ledger_manager.root) as ltx:
            e = ltx.load_account(apub)
            assert ltx.load_account(bpub) is None
            ltx.rollback()
        # recreated with the starting seqnum of the applying ledger (5)
        assert e.data.value.seqNum == 5 << 32
        assert_section(
            d, "merge|protocol version 19|merge, create, merge back",
            [meta])


class TestPaymentBaselines:
    """payment|protocol version 19|... (PaymentTests.cpp:39-230,1890).
    Fixture: A (minBalance2), gate + gate2 (minBalance2+morePayment)."""

    def _fixture(self):
        h = RefHarness()
        min_balance2 = h.min_balance(2) + 10 * h.txfee
        payment_amount = min_balance2
        more_payment = payment_amount // 2
        gateway_payment = min_balance2 + more_payment
        a1 = SecretKey(named_account_seed("A"))
        gate = SecretKey(named_account_seed("gate"))
        gate2 = SecretKey(named_account_seed("gate2"))
        for sk, bal in ((a1, payment_amount), (gate, gateway_payment),
                        (gate2, gateway_payment)):
            h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
                sk.public_key().raw, bal)]))
        return h, a1, more_payment

    def test_send_xlm_to_existing_account(self):
        d = load_baseline("PaymentTests.json")
        h, a1, more_payment = self._fixture()
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_payment(
            a1.public_key().raw, more_payment)]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        assert_section(
            d, "payment|protocol version 19|send XLM to an existing account",
            [meta])

    def test_send_xlm_no_destination(self):
        d = load_baseline("PaymentTests.json")
        h, a1, _ = self._fixture()
        b = SecretKey(named_account_seed("B"))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_payment(
            b.public_key().raw, h.min_balance(0))]))
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.PaymentResultCode.PAYMENT_NO_DESTINATION
        assert_section(
            d, "payment|protocol version 19|"
               "send XLM to a new account (no destination)", [meta])

    def test_dest_amount_too_big(self):
        d = load_baseline("PaymentTests.json")
        h, a1, _ = self._fixture()
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_payment(
            a1.public_key().raw, INT64_MAX)]))
        op = res.result.result.value[0]
        assert op.value.value.type == T.PaymentResultCode.PAYMENT_LINE_FULL
        assert_section(
            d, "payment|protocol version 19|"
               "dest amount too big for native asset", [meta])


class TestInflationBaselines:
    """inflation|protocol version 19|not supported
    (InflationTests.cpp:684-689): INFLATION returns opNOT_SUPPORTED at
    protocol >= 12."""

    def test_not_supported(self):
        d = load_baseline("InflationTests.json")
        h = RefHarness()
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_inflation()]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.type == T.OperationResultCode.opNOT_SUPPORTED
        assert_section(
            d, "inflation|protocol version 19|not supported", [meta])
