"""Parity against the REFERENCE's committed tx-meta baseline corpus
(/root/reference/test-tx-meta-baseline-current/*.json — the BASELINE.md
correctness gate: "bit-identical TxResults vs test-tx-meta-baseline-
current").

Each baseline file maps a Catch2 section path (e.g. "create account|
protocol version 19|Success") to the 64-bit SipHash-2-4 of every
NORMALIZED TransactionMeta recorded while that section ran (ref
src/test/test.cpp:620 recordOrCheckGlobalTestTxMetadata;
src/util/MetaUtils.cpp normalizeMeta; shortHash seeded from the file's
"!rng seed" via ShortHash.cpp seed()).

Reproducing a value requires replaying the reference test's exact
fixtures — which ARE deterministic: test network passphrase
"(V) (;,,;) (V)" (test.cpp), root key seeded by the network id, named
accounts seeded by the name '.'-padded to 32 bytes (TxTests.cpp:574),
genesis base fee 100 / base reserve 100000000 / maxTxSetSize 50 / total
coins 10^18 (LedgerManagerImpl.cpp:88-93, Config.cpp:197-199), fee =
100 * ops, and closes that keep closeTime at 0 (TxTests closeLedger
reuses the last close time).  This file replays a set of scenarios
through the REAL close path and asserts hash equality at protocol 19.

Reproducibility notes for the rest of the corpus (VERDICT r4 task #7):
scenarios whose fixtures use Catch2's PRNG (SecretKey::
pseudoRandomForTesting, rng-seeded amounts) or TestMarket state are
keyed to Catch2 internals and need those exact streams; everything
fixture-deterministic (named accounts + constant amounts) is
reconstructible the same way as the scenarios below.
"""
import base64
import json
import os

import pytest

from stellar_core_tpu.crypto import SecretKey, sha256
from stellar_core_tpu.crypto.shorthash import siphash24
from stellar_core_tpu.herder.tx_set import TxSetFrame
from stellar_core_tpu.ledger.ledger_txn import entry_to_key, key_bytes
from stellar_core_tpu.main import Application, test_config
from stellar_core_tpu.utils.clock import ClockMode, VirtualClock
from stellar_core_tpu.xdr import types as T

REFERENCE_DIR = "/root/reference/test-tx-meta-baseline-current"

pytestmark = pytest.mark.skipif(
    not os.path.isdir(REFERENCE_DIR),
    reason="reference baseline corpus not mounted")

TEST_PASSPHRASE = "(V) (;,,;) (V)"  # ref test.cpp getTestConfig


def load_baseline(name):
    with open(os.path.join(REFERENCE_DIR, name)) as f:
        return json.load(f)


def shorthash_key(seed: int) -> bytes:
    """ref ShortHash.cpp seed(): key[i] = byte of (seed >> (i % 4))."""
    return bytes((seed >> (i % 4)) & 0xFF for i in range(16))


# -- meta normalization (ref src/util/MetaUtils.cpp) ------------------------

_TYPE_ORDER = {  # STATE first, then CREATED, UPDATED, REMOVED
    T.LedgerEntryChangeType.LEDGER_ENTRY_STATE: 0,
    T.LedgerEntryChangeType.LEDGER_ENTRY_CREATED: 1,
    T.LedgerEntryChangeType.LEDGER_ENTRY_UPDATED: 2,
    T.LedgerEntryChangeType.LEDGER_ENTRY_REMOVED: 3,
}


def _change_key(change) -> bytes:
    if change.type == T.LedgerEntryChangeType.LEDGER_ENTRY_REMOVED:
        return key_bytes(change.value)
    return key_bytes(entry_to_key(change.value))


def _sorted_changes(changes):
    return sorted(changes, key=lambda c: (
        _change_key(c), _TYPE_ORDER[c.type],
        sha256(T.LedgerEntryChange.encode(c))))


def normalize_meta(meta):
    """Sorted-changes copy of a TransactionMeta (v2)."""
    assert meta.type == 2
    v2 = meta.value
    ops = [T.OperationMeta.make(changes=_sorted_changes(om.changes))
           for om in v2.operations]
    return T.TransactionMeta.make(2, T.TransactionMetaV2.make(
        txChangesBefore=_sorted_changes(v2.txChangesBefore),
        operations=ops,
        txChangesAfter=_sorted_changes(v2.txChangesAfter)))


def meta_hash_b64(meta, rng_seed: int) -> str:
    h = siphash24(shorthash_key(rng_seed),
                  T.TransactionMeta.encode(normalize_meta(meta)))
    # the corpus stores each uint64 base64'd in big-endian byte order
    # (ref test.cpp saveTestTxMeta :815)
    return base64.b64encode(h.to_bytes(8, "big")).decode()


# -- reference test fixtures ------------------------------------------------

def named_account_seed(name: str) -> bytes:
    """ref txtest::getAccount: the name '.'-padded to 32 bytes IS the
    ed25519 seed."""
    return (name + "." * 32)[:32].encode()


class RefHarness:
    """A node configured exactly like the reference's createTestApplication
    + getTestConfig, applying txs one per close with closeTime pinned at 0
    (ref txtest::closeLedger reusing the last close time)."""

    def __init__(self):
        self.app = Application(
            VirtualClock(ClockMode.VIRTUAL_TIME),
            test_config(
                NETWORK_PASSPHRASE=TEST_PASSPHRASE,
                TESTING_UPGRADE_RESERVE=100000000,
                TESTING_UPGRADE_MAX_TX_SET_SIZE=50,
            ))
        self.app.start()
        self.root_sk = SecretKey(self.app.config.network_id())
        self.base_reserve = 100000000
        self.txfee = 100
        self.seqs = {}  # account raw pubkey -> last seq consumed

    def min_balance(self, entries: int) -> int:
        return (2 + entries) * self.base_reserve

    def _next_seq(self, pub: bytes) -> int:
        from stellar_core_tpu.ledger.ledger_txn import LedgerTxn

        if pub not in self.seqs:
            with LedgerTxn(self.app.ledger_manager.root) as ltx:
                e = ltx.load_account(pub)
                self.seqs[pub] = e.data.value.seqNum
                ltx.rollback()
        self.seqs[pub] += 1
        return self.seqs[pub]

    def tx(self, sk: SecretKey, ops):
        """transactionFromOperationsV1: fee = ops * 100, no memo/bounds."""
        pub = sk.public_key().raw
        tx = T.Transaction.make(
            sourceAccount=T.MuxedAccount.make(
                T.CryptoKeyType.KEY_TYPE_ED25519, pub),
            fee=len(ops) * self.txfee,
            seqNum=self._next_seq(pub),
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.Memo.make(T.MemoType.MEMO_NONE),
            operations=ops,
            ext=T.Transaction.fields[6][1].make(0))
        payload = T.TransactionSignaturePayload.make(
            networkId=self.app.config.network_id(),
            taggedTransaction=T.TransactionSignaturePayload
            .fields[1][1].make(T.EnvelopeType.ENVELOPE_TYPE_TX, tx))
        sig = sk.sign(sha256(T.TransactionSignaturePayload.encode(payload)))
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=tx, signatures=[
                T.DecoratedSignature.make(hint=pub[-4:], signature=sig)]))

    def op_create_account(self, dest_pub: bytes, balance: int):
        return T.Operation.make(
            sourceAccount=None,
            body=T.Operation.fields[1][1].make(
                T.OperationType.CREATE_ACCOUNT,
                T.CreateAccountOp.make(
                    destination=T.account_id(dest_pub),
                    startingBalance=balance)))

    def op_payment(self, dest_pub: bytes, amount: int, asset=None):
        return T.Operation.make(
            sourceAccount=None,
            body=T.Operation.fields[1][1].make(
                T.OperationType.PAYMENT,
                T.PaymentOp.make(
                    destination=T.MuxedAccount.make(
                        T.CryptoKeyType.KEY_TYPE_ED25519, dest_pub),
                    asset=(asset if asset is not None else
                           T.Asset.make(T.AssetType.ASSET_TYPE_NATIVE)),
                    amount=amount)))

    def apply_tx(self, env):
        """One tx in its own close, closeTime = last close time (stays 0);
        returns (tx_result, TransactionMeta) from the real close path."""
        lm = self.app.ledger_manager
        seq = lm.last_closed_seq() + 1
        prev = lm.last_closed_header()
        xdr_set = T.TransactionSet.make(
            previousLedgerHash=lm.last_closed_hash(), txs=[env])
        tx_set = TxSetFrame.make_from_wire(
            self.app.config.network_id(), xdr_set)
        sv = T.StellarValue.make(
            txSetHash=tx_set.contents_hash(),
            closeTime=prev.scpValue.closeTime,
            upgrades=[],
            ext=T.StellarValue.fields[3][1].make(
                T.StellarValueType.STELLAR_VALUE_BASIC))
        from stellar_core_tpu.herder.herder import LedgerCloseData

        lm.close_ledger(LedgerCloseData(seq, tx_set, sv))
        cur = self.app.database.cursor()
        row = cur.execute(
            "SELECT txresult, txmeta FROM txhistory WHERE ledgerseq=? "
            "ORDER BY txindex", (seq,)).fetchall()
        assert len(row) == 1
        result = T.TransactionResultPair.decode(row[0][0])
        meta = T.TransactionMeta.decode(row[0][1])
        return result, meta


# -- scenarios --------------------------------------------------------------

class TestCreateAccountBaselines:
    """create account|protocol version 19|... scenarios from
    CreateAccountTests.cpp, replayed step-for-step."""

    def test_success(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        b_sk = SecretKey(named_account_seed("B"))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        assert res.result.result.type == T.TransactionResultCode.txSUCCESS
        got = meta_hash_b64(meta, seed)
        want = d["create account|protocol version 19|Success"]
        assert got == want[0], f"meta hash {got} != reference {want[0]}"

    def test_success_account_already_exists(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        b_sk = SecretKey(named_account_seed("B"))
        h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        res, meta = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            b_sk.public_key().raw, h.min_balance(0))]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.CreateAccountResultCode.CREATE_ACCOUNT_ALREADY_EXIST
        got = meta_hash_b64(meta, seed)
        want = d["create account|protocol version 19|Success|"
                 "Account already exists"]
        assert got == want[0]

    def test_not_enough_funds(self):
        d = load_baseline("CreateAccountTests.json")
        seed = d["!rng seed"]
        h = RefHarness()
        gateway_payment = h.min_balance(2) + 10 * h.txfee + 1
        gate_sk = SecretKey(named_account_seed("gate"))
        _, meta1 = h.apply_tx(h.tx(h.root_sk, [h.op_create_account(
            gate_sk.public_key().raw, gateway_payment)]))
        res, meta2 = h.apply_tx(h.tx(gate_sk, [h.op_create_account(
            SecretKey(named_account_seed("B")).public_key().raw,
            gateway_payment)]))
        assert res.result.result.type == T.TransactionResultCode.txFAILED
        op = res.result.result.value[0]
        assert op.value.value.type == \
            T.CreateAccountResultCode.CREATE_ACCOUNT_UNDERFUNDED
        want = d["create account|protocol version 19|"
                 "Not enough funds (source)"]
        assert [meta_hash_b64(meta1, seed),
                meta_hash_b64(meta2, seed)] == want
