"""Mesh + sharding layer for the device tier (SURVEY.md §2.17 P4-P6).

Owns every ``jax.sharding`` decision in the framework so models and
benches share one layout vocabulary:

- ``data_parallel_mesh`` — the 1-D ``data`` mesh the admission pipeline
  runs on (DP over signature batches, validator-parallel tallies);
- ``dp``/``replicated`` — the two shardings the pipeline uses;

The reference scales by flooding whole validators over TCP
(src/overlay); the TPU-native analog shards work *within* a validator
across the mesh and keeps the overlay for inter-validator traffic
(SURVEY.md §5.8).
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

DATA_AXIS = "data"


def data_parallel_mesh(n_devices: Optional[int] = None,
                       devices=None) -> Mesh:
    """1-D mesh over ``n_devices`` (default: all) on axis ``data``."""
    devs = list(devices if devices is not None else jax.devices())
    if n_devices is not None:
        if len(devs) < n_devices:
            raise ValueError(
                f"need {n_devices} devices, have {len(devs)}")
        devs = devs[:n_devices]
    return Mesh(np.array(devs), (DATA_AXIS,))


def dp(mesh: Mesh) -> NamedSharding:
    """Leading axis split across ``data`` (signature batches, validator
    axes)."""
    return NamedSharding(mesh, PartitionSpec(DATA_AXIS))


def replicated(mesh: Mesh) -> NamedSharding:
    """Fully replicated (small quorum tensors, statement matrices)."""
    return NamedSharding(mesh, PartitionSpec())
