"""Parallel transaction-apply subsystem (ref protocol-20 parallel
Soroban apply, SURVEY.md §2.8/§2.17; Block-STM, Gelashvili et al.,
PPoPP 2022 — adapted to *declared* footprints instead of optimistic
re-execution).

Three layers, one module each:

- ``footprint``  — per-transaction declared read/write footprints over
  canonical LedgerKey bytes, with order-book access declared *by
  asset-pair* and plan-time materialization of everything a DEX
  crossing can touch (resting offers, their sellers, trustlines,
  sponsors, the pair's liquidity pool);
- ``planner``    — conflict graph over the canonical apply order +
  union-find clustering: any two txs sharing a write key, a book pair,
  or the offer-id pool land in one cluster, intra-cluster order
  preserved;
- ``executor``   — each cluster runs against its own child
  ``LedgerTxn`` over a shared immutable snapshot on a worker pool; a
  speculation guard turns any undeclared access into a
  ``FootprintEscape`` that aborts the whole parallel attempt and
  replays the set sequentially (the always-correct fallback).  Cluster
  deltas merge in canonical order, so header/bucket hashes AND meta
  bytes are bit-identical to sequential apply; the GIL-releasing
  native work (xdrpack meta/result serialization) overlaps across
  clusters.

Kill switch: config ``PARALLEL_APPLY = false`` (or env
``PARALLEL_APPLY=0``); aborts surface as the ``apply.parallel.abort``
counter and in ``ledger.apply.cluster`` spans.
"""
from .executor import FootprintEscape, ParallelApplyManager  # noqa: F401
from .footprint import TxFootprint, footprint_for  # noqa: F401
from .planner import ApplyPlan, plan_parallel_apply  # noqa: F401
