"""Dispatch layer for the native GIL-free apply kernel.

Bridges the cluster executor to ``native/apply_kernel.cpp``: decides
per transaction whether its structure fits the kernel's op strip
(``frame_kernel_shape``, consumed by the footprint pass so the planner
can tag whole clusters), packs a kernel-eligible cluster's snapshot
entries / order-book rows / tx descriptors into canonical XDR bytes,
invokes the kernel (which releases the GIL for its whole run), and
re-wraps the kernel's outputs — packed entry deltas, pre-encoded
TransactionMeta / TransactionResult bytes — into the ``ClusterResult``
shape the merge/hash/commit phases already consume.

Parity contract: the kernel implements success paths only.  Any
structural mismatch, unexpected entry state, failing check or
arithmetic divergence comes back as a ``KernelDecline`` and the caller
runs the unchanged Python reference apply for that cluster — identical
bytes either way, which tests/test_native_apply.py holds across
workloads, worker counts and hash seeds.

Signature checking stays host-side ON PURPOSE: verdicts are already
batch-verified (and cached) before the apply phase, so the dispatcher
replays the master-key check the reference performs for a one-signer
account — hint match + cached verdict — and declines anything richer
(extra signers, non-master weights are state the kernel also guards).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..ledger.ledger_txn import _OFFER_PREFIX, account_key_bytes
from ..ledger.packed import LazyUnion, PackedEntry
from ..xdr import types as T

OT = T.OperationType


class KernelDecline(Exception):
    """The kernel cannot apply this cluster; Python apply takes it."""


def _screen_account(snapshot, account_id: bytes, idx: int) -> None:
    """Pre-pack host screen for the account entries every kernel tx
    MUST touch (tx source; payment destination).  The kernel's own
    parse raises the same refusals, but only AFTER the cluster's whole
    snapshot/book encode has been paid — and these shapes (extra
    signers, an inflation destination) persist across closes, so
    without the screen a cluster carrying such an account re-pays the
    pack cost on every close just to hear the same "no".  The decoded
    snapshot entry is already in hand: a few attribute reads decline
    the cluster before any encoding.  The kernel's parse stays the
    authority for every other shape."""
    e = snapshot.store.get(account_key_bytes(account_id))
    if e is not None:
        acc = e.data.value
        if acc.signers or acc.inflationDest is not None:
            raise KernelDecline(
                f"tx {idx}: unsupported account shape (host screen)")


#: protocol constants the C kernel hardcodes (apply_kernel.cpp) paired
#: with their Python source of truth — asserted before every dispatch
#: so a constant drift disables the kernel instead of risking a fork
def _constants_in_lockstep() -> bool:
    from ..transactions import utils as U

    return (U.MAX_OFFERS_TO_CROSS == 1000
            and U.ACCOUNT_SUBENTRY_LIMIT == 1000
            and U.INT64_MAX == 2**63 - 1
            and int(T.AUTHORIZED_FLAG) == 1
            and int(T.PASSIVE_FLAG) == 1)


def kernel_module():
    """The _applykernel extension, or None (build attempted once; the
    native package serializes loading under its own lock)."""
    from ..native import get_apply_kernel

    return get_apply_kernel()


def frame_kernel_shape(frame) -> Optional[tuple]:
    """Structural (state-free) kernel eligibility of one frame; returns
    a shape descriptor consumed by ``run_cluster_native`` or None.

    Pure function of the transaction — safe to compute at plan time
    (including nomination-time preplans) and cache on the footprint.
    """
    from ..transactions import utils as U
    from ..transactions.frame import TransactionFrame

    if type(frame) is not TransactionFrame:
        return None  # fee bumps carry a second fee source
    tx = frame.tx
    if len(tx.operations) != 1:
        return None
    if tx.cond.type != T.PreconditionType.PRECOND_NONE:
        return None  # time/ledger bounds + v2 preconditions stay host-side
    if len(frame.signatures) != 1:
        return None  # multisig evaluation stays host-side
    op = tx.operations[0]
    if op.sourceAccount is not None:
        return None
    body = op.body
    if body.type == OT.PAYMENT:
        b = body.value
        if b.asset.type != T.AssetType.ASSET_TYPE_NATIVE:
            return None  # credit payments keep the trustline reference path
        return ("payment", U.muxed_to_account_id(b.destination), b.amount)
    if body.type == OT.MANAGE_SELL_OFFER:
        b = body.value
        if b.offerID != 0 or b.amount <= 0:
            return None  # modify/delete keep the reference path
        return ("offer", T.Asset.encode(b.selling),
                T.Asset.encode(b.buying), b.amount, b.price.n, b.price.d)
    return None


def _signature_ok(frame, verify) -> bool:
    """The reference's master-key signature consume for a one-signer
    envelope: hint match + (cached) ed25519 verdict."""
    if verify is None:
        from ..crypto import verify_sig as verify
    ds = frame.signatures[0]
    pub = frame.source_account_id()
    return ds.hint == pub[-4:] and verify(pub, ds.signature,
                                          frame.full_hash())


def _tx_tuple(frame, shape) -> tuple:
    if shape[0] == "payment":
        return (int(OT.PAYMENT), frame.full_hash(),
                frame.source_account_id(), frame.seq_num(), frame.tx.fee,
                frame.fee_charged, shape[1], shape[2])
    return (int(OT.MANAGE_SELL_OFFER), frame.full_hash(),
            frame.source_account_id(), frame.seq_num(), frame.tx.fee,
            frame.fee_charged, shape[1], shape[2], shape[3], shape[4],
            shape[5])


def _kernel_ready(snapshot):
    """Shared dispatch gates; returns the kernel module or raises."""
    mod = kernel_module()
    if mod is None:
        raise KernelDecline("kernel unavailable")
    if not _constants_in_lockstep():
        raise KernelDecline("protocol constant drift")
    if snapshot.header.ledgerVersion != 19:
        # the kernel mirrors protocol-19 semantics; older gated
        # behaviors (check order, liability rules) stay host-side
        raise KernelDecline(
            f"protocol version {snapshot.header.ledgerVersion} "
            f"not kernel-backed")
    return mod


def _screen_cluster(cluster, snapshot, apply_order, verify):
    """Host-side per-tx gates (shape, clean master-key signature,
    supported account shapes) — cheap, run BEFORE any encoding is
    paid.  Returns the cluster's frames."""
    frames = [apply_order[i] for i in cluster.indices]
    for idx, frame, shape in zip(cluster.indices, frames,
                                 cluster.shapes):
        if shape is None:
            raise KernelDecline(f"tx {idx} not kernel-shaped")
        if not _signature_ok(frame, verify):
            # a failing signature is a FAILURE result, not a success —
            # the reference path owns every non-success outcome
            raise KernelDecline(f"tx {idx} signature not clean")
        _screen_account(snapshot, frame.source_account_id(), idx)
        if shape[0] == "payment":
            _screen_account(snapshot, shape[1], idx)
    return frames


def _pack_inputs(snapshot, keys, pairs):
    """(entries, books) kernel tables over a declared key/pair set."""
    entries = []
    for kb in sorted(keys):
        e = snapshot.store[kb]
        entries.append((kb, None if e is None else T.LedgerEntry.encode(e)))
    books = []
    for pair in sorted(pairs):
        directions = snapshot.books[pair]
        for direction in sorted(directions):
            books.append((direction[0], direction[1],
                          [kb for _, _, kb in directions[direction]]))
    return entries, books


def _fill_records(res, indices, frames, records) -> None:
    """Wrap kernel (meta, result) byte pairs into the ClusterResult
    record shape the merge/hash/commit phases consume."""
    from ..utils import tracing

    inner_union = T.TransactionResult.fields[1][1]
    ext_v0 = T.TransactionResult.fields[2][1].make(0)
    with tracing.stopwatch() as sw:
        for idx, frame, (meta_b, result_b) in zip(indices, frames,
                                                  records):
            pair_b = frame.full_hash() + result_b
            env_b = T.TransactionEnvelope.encode(frame.envelope)
            # TransactionResult is a struct: rebuild its cheap scalar
            # fields eagerly (feeCharged i64 leads the encoding, ext v0
            # trails) and keep only the result union lazy
            result = T.TransactionResult.make(
                feeCharged=frame.fee_charged,
                result=LazyUnion(inner_union, result_b[8:-4]),
                ext=ext_v0)
            res.records[idx] = (
                True,
                result,
                LazyUnion(T.TransactionMeta, meta_b),
                meta_b, pair_b, env_b,
            )
    res.encode_seconds += sw.seconds


def run_cluster_native(cluster, snapshot, apply_order, verify,
                       result_cls):
    """Apply one kernel-eligible cluster natively.

    Returns a populated ``result_cls`` (the executor's ClusterResult)
    or raises ``KernelDecline`` — the caller then runs the Python
    reference apply for the cluster.  Never mutates shared state: the
    kernel works on copies, so a decline discards everything.
    """
    mod = _kernel_ready(snapshot)
    header = snapshot.header
    frames = _screen_cluster(cluster, snapshot, apply_order, verify)

    params = (header.ledgerSeq, header.scpValue.closeTime, header.baseFee,
              header.baseReserve, snapshot.idpool0)
    entries, books = _pack_inputs(snapshot, cluster.keys, cluster.pairs)
    txs = [_tx_tuple(frame, shape)
           for frame, shape in zip(frames, cluster.shapes)]

    out = mod.apply_cluster(params, entries, books, txs)
    if not out[0]:
        _, reason, tx_index = out
        raise KernelDecline(f"kernel declined tx {tx_index}: {reason}")
    _, deltas, records, idpool_final = out

    from .executor import _is_fresh_offer_key

    res = result_cls(cluster.cluster_id)
    res.native = "hit"
    declared = cluster.writes
    for kb, eb in deltas:
        # write-side guard, mirroring the executor's _post_check: every
        # kernel write must be a declared write or a fresh offer id
        if kb not in declared and not _is_fresh_offer_key(
                kb, snapshot.idpool0):
            raise KernelDecline(f"kernel wrote undeclared key {kb.hex()}")
        res.delta[kb] = None if eb is None else PackedEntry(eb)
        if kb.startswith(_OFFER_PREFIX):
            res.okeys.add(kb)
    if idpool_final != snapshot.idpool0:
        if not cluster.writes_header:
            raise KernelDecline("kernel allocated ids without the token")
        res.header = header._replace(idPool=idpool_final)
    _fill_records(res, cluster.indices, frames, records)
    return res


def run_clusters_native_batched(clusters, snapshot, apply_order, verify,
                                result_cls):
    """Apply MANY kernel-eligible clusters in ONE encode + ONE
    GIL-released ``apply_cluster`` crossing (ROADMAP 2d: a 1000-payment
    close plans hundreds of 2-tx clusters, and per-cluster dispatch
    pays the FFI/encode toll hundreds of times).

    Sound because batchable clusters are disjoint by construction (the
    planner merges any key/book/id-pool conflict into one cluster) and
    none writes the header (id-pool allocators are excluded by the
    caller): applying their transactions back-to-back over the merged
    snapshot table is exactly per-cluster application.  Outputs are
    split back per cluster — deltas by the declared-key ownership map,
    records by tx index.  Any decline rejects the WHOLE batch; the
    caller retries per cluster so one poisoned cluster cannot drag its
    batchmates onto the Python path.
    """
    mod = _kernel_ready(snapshot)
    header = snapshot.header
    clusters = sorted(clusters, key=lambda c: c.cluster_id)
    owner: dict = {}
    all_keys: set = set()
    all_pairs: set = set()
    txs = []
    frames_of = {}
    for cluster in clusters:
        if cluster.writes_header:
            raise KernelDecline(
                f"cluster {cluster.cluster_id} allocates offer ids; "
                f"not batchable")
        frames = _screen_cluster(cluster, snapshot, apply_order, verify)
        frames_of[cluster.cluster_id] = frames
        for kb in cluster.keys:
            owner[kb] = cluster
        all_keys |= cluster.keys
        all_pairs |= cluster.pairs
        for frame, shape in zip(frames, cluster.shapes):
            txs.append(_tx_tuple(frame, shape))

    params = (header.ledgerSeq, header.scpValue.closeTime, header.baseFee,
              header.baseReserve, snapshot.idpool0)
    entries, books = _pack_inputs(snapshot, all_keys, all_pairs)
    out = mod.apply_cluster(params, entries, books, txs)
    if not out[0]:
        _, reason, tx_index = out
        raise KernelDecline(
            f"kernel declined batched tx {tx_index}: {reason}")
    _, deltas, records, idpool_final = out
    if idpool_final != snapshot.idpool0:
        raise KernelDecline("batched kernel allocated offer ids")

    results = {}
    for c in clusters:
        res = result_cls(c.cluster_id)
        res.native = "hit"
        results[c.cluster_id] = res
    for kb, eb in deltas:
        cluster = owner.get(kb)
        # no fresh-offer exemption here: id-pool allocators never batch,
        # so every write must belong to exactly one declared key set
        if cluster is None or kb not in cluster.writes:
            raise KernelDecline(
                f"batched kernel wrote undeclared key {kb.hex()}")
        res = results[cluster.cluster_id]
        res.delta[kb] = None if eb is None else PackedEntry(eb)
        if kb.startswith(_OFFER_PREFIX):
            res.okeys.add(kb)
    pos = 0
    for cluster in clusters:
        frames = frames_of[cluster.cluster_id]
        n = len(frames)
        _fill_records(results[cluster.cluster_id], cluster.indices,
                      frames, records[pos:pos + n])
        pos += n
    return [results[c.cluster_id] for c in clusters]
