"""Dispatch layer for the native GIL-free apply kernel.

Bridges the cluster executor to ``native/apply_kernel.cpp``: decides
per transaction whether its structure fits the kernel's op strip
(``frame_kernel_shape``, consumed by the footprint pass so the planner
can tag whole clusters), packs a kernel-eligible cluster's snapshot
entries / order-book rows / tx descriptors into canonical XDR bytes,
invokes the kernel (which releases the GIL for its whole run), and
re-wraps the kernel's outputs — packed entry deltas, pre-encoded
TransactionMeta / TransactionResult bytes — into the ``ClusterResult``
shape the merge/hash/commit phases already consume.

The kernel-shaped strip (ISSUE 13 kernel-complete apply): native AND
credit payments, CHANGE_TRUST create/update/delete over classic
assets, MANAGE_SELL_OFFER create/modify/delete (offerID 0 and !=0),
and PATH_PAYMENT strict-send/strict-receive over declared hop pairs
(per-hop pool descriptors ride the shape so the kernel can quote a
LIVE constant-product pool on the hop in-kernel — book-vs-pool
arbitration mirrors ``convert_with_offers_and_pools``; pool
deposit/withdraw stay host-side, and ``NATIVE_POOL_QUOTE=0`` restores
the old decline-if-live behavior via a host screen).

Beyond per-cluster apply, ``run_fee_phase_native`` batches the whole
fee/seqnum phase of a close into ONE GIL-released ``charge_fees``
kernel call: apply-ordered tx descriptors + the packed source-account
snapshot go in, per-tx pre-encoded ``feeProcessing``
LedgerEntryChanges plus packed account deltas come out.  Any
unsupported account shape declines the WHOLE fee batch back to the
per-tx ``frame.process_fee_seq_num`` loop — bytes identical either
way.

Parity contract: the kernel implements success paths only.  Any
structural mismatch, unexpected entry state, failing check or
arithmetic divergence comes back as a ``KernelDecline`` and the caller
runs the unchanged Python reference apply for that cluster — identical
bytes either way, which tests/test_native_apply.py holds across
workloads, worker counts and hash seeds.

Signature checking stays host-side ON PURPOSE: verdicts are already
batch-verified (and cached) before the apply phase, so the dispatcher
replays the master-key check the reference performs for a one-signer
account — hint match + cached verdict — and declines anything richer
(extra signers, non-master weights are state the kernel also guards).
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..ledger.ledger_txn import _OFFER_PREFIX, account_key_bytes
from ..ledger.packed import LazyUnion, PackedEntry
from ..xdr import types as T

OT = T.OperationType


def _reason_slug(msg: str) -> str:
    """Stable metric-label slug of a decline reason (the kernel's
    ``need()`` strings are the taxonomy; host-side raises ride along)."""
    import re

    return re.sub(r"[^a-z0-9]+", "_", msg.lower()).strip("_")[:48] or \
        "unknown"


class KernelDecline(Exception):
    """The kernel cannot apply this cluster; Python apply takes it.

    Carries the decline taxonomy: ``op`` is the kernel-shape kind of
    the offending tx (``payment`` / ``offer`` / ``trust`` / ``pathpay``,
    or ``cluster`` for whole-cluster refusals) and ``code`` the reason
    slug — together they feed the ``apply.native.decline.<op>.<code>``
    metric breakout, so a decline storm names the exact coverage gap
    instead of bumping one opaque counter."""

    def __init__(self, msg: str, op: str = "cluster",
                 code: Optional[str] = None):
        super().__init__(msg)
        self.op = op
        self.code = code if code is not None else _reason_slug(msg)


def _screen_account(snapshot, account_id: bytes, idx: int) -> None:
    """Pre-pack host screen for the account entries every kernel tx
    MUST touch (tx source; payment destination).  The kernel's own
    parse raises the same refusals, but only AFTER the cluster's whole
    snapshot/book encode has been paid — and these shapes (extra
    signers, an inflation destination) persist across closes, so
    without the screen a cluster carrying such an account re-pays the
    pack cost on every close just to hear the same "no".  The decoded
    snapshot entry is already in hand: a few attribute reads decline
    the cluster before any encoding.  The kernel's parse stays the
    authority for every other shape."""
    e = snapshot.store.get(account_key_bytes(account_id))
    if e is not None:
        acc = e.data.value
        if acc.signers or acc.inflationDest is not None:
            raise KernelDecline(
                f"tx {idx}: unsupported account shape (host screen)",
                code="unsupported_account_shape")


#: protocol constants the C kernel hardcodes (apply_kernel.cpp) paired
#: with their Python source of truth — asserted before every dispatch
#: so a constant drift disables the kernel instead of risking a fork
#: (the full manifest lives in tools/lint/lockstep.json; detlint's
#: native-lockstep gate diffs both sides statically)
def _constants_in_lockstep() -> bool:
    from ..transactions import utils as U

    return (U.MAX_OFFERS_TO_CROSS == 1000
            and U.ACCOUNT_SUBENTRY_LIMIT == 1000
            and U.MAX_PATH_HOPS == 6
            and U.INT64_MAX == 2**63 - 1
            and int(T.AUTHORIZED_FLAG) == 1
            and int(T.AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG) == 2
            and int(T.TRUSTLINE_CLAWBACK_ENABLED_FLAG) == 4
            and int(T.AUTH_REQUIRED_FLAG) == 1
            and int(T.AUTH_CLAWBACK_ENABLED_FLAG) == 8
            and int(T.PASSIVE_FLAG) == 1
            and int(OT.CHANGE_TRUST) == 6
            and int(OT.PATH_PAYMENT_STRICT_RECEIVE) == 2
            and int(OT.PATH_PAYMENT_STRICT_SEND) == 13
            and int(T.LedgerEntryType.LIQUIDITY_POOL) == 5
            and int(T.ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL) == 2
            and T.LIQUIDITY_POOL_FEE_V18 == 30)


def kernel_module():
    """The _applykernel extension, or None (build attempted once; the
    native package serializes loading under its own lock)."""
    from ..native import get_apply_kernel

    return get_apply_kernel()


def frame_kernel_shape(frame) -> Optional[tuple]:
    """Structural (state-free) kernel eligibility of one frame; returns
    a shape descriptor consumed by ``run_cluster_native`` or None.

    Pure function of the transaction — safe to compute at plan time
    (including nomination-time preplans) and cache on the footprint.
    Structurally-MALFORMED bodies return None on purpose: malformed is
    a FAILURE result, and the Python reference owns every non-success
    outcome.
    """
    from ..transactions import utils as U
    from ..transactions.frame import TransactionFrame

    if type(frame) is not TransactionFrame:
        return None  # fee bumps carry a second fee source
    tx = frame.tx
    if len(tx.operations) != 1:
        return None
    if tx.cond.type != T.PreconditionType.PRECOND_NONE:
        return None  # time/ledger bounds + v2 preconditions stay host-side
    if len(frame.signatures) != 1:
        return None  # multisig evaluation stays host-side
    op = tx.operations[0]
    if op.sourceAccount is not None:
        return None
    body = op.body
    if body.type == OT.PAYMENT:
        b = body.value
        if not U.is_asset_valid(b.asset) or b.amount <= 0:
            return None
        return ("payment", U.muxed_to_account_id(b.destination), b.amount,
                T.Asset.encode(b.asset))
    if body.type == OT.MANAGE_SELL_OFFER:
        b = body.value
        if b.amount < 0 or b.offerID < 0 or \
                (b.amount == 0 and b.offerID == 0):
            return None  # malformed keeps the reference path
        if b.price.n <= 0 or b.price.d <= 0:
            return None
        return ("offer", T.Asset.encode(b.selling),
                T.Asset.encode(b.buying), b.amount, b.price.n, b.price.d,
                b.offerID)
    if body.type == OT.CHANGE_TRUST:
        b = body.value
        line = b.line
        if line.type in (T.AssetType.ASSET_TYPE_NATIVE,
                         T.AssetType.ASSET_TYPE_POOL_SHARE):
            return None  # native is malformed; pool shares stay host-side
        asset = T.Asset.make(line.type, line.value)
        if not U.is_asset_valid(asset) or b.limit < 0:
            return None
        if U.asset_issuer(asset) == frame.source_account_id():
            return None  # SELF_NOT_ALLOWED is a failure result
        return ("trust", T.Asset.encode(asset), b.limit)
    if body.type in (OT.PATH_PAYMENT_STRICT_SEND,
                     OT.PATH_PAYMENT_STRICT_RECEIVE):
        b = body.value
        strict_send = body.type == OT.PATH_PAYMENT_STRICT_SEND
        chain = [b.sendAsset, *b.path, b.destAsset]
        if len(chain) - 1 > U.MAX_PATH_HOPS:
            return None
        for a in chain:
            if not U.is_asset_valid(a):
                return None
        if strict_send:
            if b.sendAmount <= 0 or b.destMin <= 0:
                return None
            amount, amount2 = b.sendAmount, b.destMin
        else:
            if b.destAmount <= 0 or b.sendMax <= 0:
                return None
            amount, amount2 = b.sendMax, b.destAmount
        hops = _path_hops(chain)
        return ("pathpay", U.muxed_to_account_id(b.destination),
                int(body.type), T.Asset.encode(b.sendAsset), amount,
                T.Asset.encode(b.destAsset), amount2, hops)
    return None


def _path_hops(chain) -> tuple:
    """The effective conversion steps of a path-payment chain: adjacent
    equal assets collapse (exactly the reference's ``assets_equal``
    skip), and each hop carries its pair's liquidity-pool key so the
    kernel can quote a LIVE pool against a DECLARED key (and the
    ``NATIVE_POOL_QUOTE=0`` host screen can probe the same key)."""
    from ..transactions import liquidity_pool as LP
    from ..transactions import utils as U

    hops = []
    for i in range(len(chain) - 1):
        if U.assets_equal(chain[i], chain[i + 1]):
            continue
        hops.append((T.Asset.encode(chain[i]),
                     T.Asset.encode(chain[i + 1]),
                     LP.pair_pool_key_bytes(chain[i], chain[i + 1])))
    return tuple(hops)


def _signature_ok(frame, verify) -> bool:
    """The reference's master-key signature consume for a one-signer
    envelope: hint match + (cached) ed25519 verdict."""
    if verify is None:
        from ..crypto import verify_sig as verify
    ds = frame.signatures[0]
    pub = frame.source_account_id()
    return ds.hint == pub[-4:] and verify(pub, ds.signature,
                                          frame.full_hash())


def _tx_tuple(frame, shape) -> tuple:
    head = (frame.full_hash(), frame.source_account_id(),
            frame.seq_num(), frame.tx.fee, frame.fee_charged)
    kind = shape[0]
    if kind == "payment":
        # (dest, amount, asset)
        return (int(OT.PAYMENT), *head, shape[1], shape[2], shape[3])
    if kind == "offer":
        # (selling, buying, amount, price_n, price_d, offer_id)
        return (int(OT.MANAGE_SELL_OFFER), *head, shape[1], shape[2],
                shape[3], shape[4], shape[5], shape[6])
    if kind == "trust":
        # (line asset, limit)
        return (int(OT.CHANGE_TRUST), *head, shape[1], shape[2])
    # pathpay: (dest, op, send_asset, amount, dest_asset, amount2, hops)
    return (shape[2], *head, shape[1], shape[3], shape[4], shape[5],
            shape[6], shape[7])


def _kernel_ready(snapshot):
    """Shared dispatch gates; returns the kernel module or raises."""
    mod = kernel_module()
    if mod is None:
        raise KernelDecline("kernel unavailable")
    if not _constants_in_lockstep():
        raise KernelDecline("protocol constant drift")
    if snapshot.header.ledgerVersion != 19:
        # the kernel mirrors protocol-19 semantics; older gated
        # behaviors (check order, liability rules) stay host-side
        raise KernelDecline(
            f"protocol version {snapshot.header.ledgerVersion} "
            f"not kernel-backed")
    return mod


def _screen_cluster(cluster, snapshot, apply_order, verify):
    """Host-side per-tx gates (shape, clean master-key signature,
    supported account shapes) — cheap, run BEFORE any encoding is
    paid.  Returns the cluster's frames."""
    frames = [apply_order[i] for i in cluster.indices]
    for idx, frame, shape in zip(cluster.indices, frames,
                                 cluster.shapes):
        if shape is None:
            raise KernelDecline(f"tx {idx} not kernel-shaped",
                                code="not_kernel_shaped")
        if not _signature_ok(frame, verify):
            # a failing signature is a FAILURE result, not a success —
            # the reference path owns every non-success outcome
            raise KernelDecline(f"tx {idx} signature not clean",
                                op=shape[0], code="signature_not_clean")
        _screen_account(snapshot, frame.source_account_id(), idx)
        if shape[0] in ("payment", "pathpay"):
            # destination accounts are touched by every payment-shaped
            # apply; screen their persistent unsupported shapes too
            _screen_account(snapshot, shape[1], idx)
        if shape[0] == "pathpay" and not getattr(snapshot, "pool_quote",
                                                 True):
            # NATIVE_POOL_QUOTE=0 kill switch: restore the pre-r16
            # decline-if-live-pool behavior so the Python reference
            # adjudicates every pool-backed hop
            for _, _, pool_kb in shape[7]:
                if snapshot.store.get(pool_kb) is not None:
                    raise KernelDecline(
                        f"tx {idx}: liquidity pool on hop "
                        f"(pool quoting off)", op="pathpay",
                        code="liquidity_pool_on_hop")
    return frames


def _shape_kinds(clusters) -> "List[str]":
    """Kernel-shape kind of every tx across ``clusters`` in dispatch
    order — the map from a kernel decline's tx_index back to the op
    family for the decline-taxonomy metrics."""
    kinds: List[str] = []
    for cluster in clusters:
        kinds.extend(s[0] if s is not None else "cluster"
                     for s in cluster.shapes)
    return kinds


def _kernel_declined(kinds, reason, tx_index, batched=False):
    what = "batched tx" if batched else "tx"
    op = kinds[tx_index] if 0 <= tx_index < len(kinds) else "cluster"
    return KernelDecline(f"kernel declined {what} {tx_index}: {reason}",
                         op=op, code=_reason_slug(reason))


def _kind_counts(cluster) -> dict:
    """tx count per kernel-shape kind — feeds the per-op-type
    ``apply.native.hit.<op>`` attribution on a kernel hit."""
    counts: dict = {}
    for s in cluster.shapes:
        kind = s[0] if s is not None else "cluster"
        counts[kind] = counts.get(kind, 0) + 1
    return counts


def _pack_inputs(snapshot, keys, pairs):
    """(entries, books) kernel tables over a declared key/pair set."""
    entries = []
    for kb in sorted(keys):
        e = snapshot.store[kb]
        entries.append((kb, None if e is None else T.LedgerEntry.encode(e)))
    books = []
    for pair in sorted(pairs):
        directions = snapshot.books[pair]
        for direction in sorted(directions):
            books.append((direction[0], direction[1],
                          [kb for _, _, kb in directions[direction]]))
    return entries, books


def _fill_records(res, indices, frames, records) -> None:
    """Wrap kernel (meta, result) byte pairs into the ClusterResult
    record shape the merge/hash/commit phases consume."""
    from ..utils import tracing

    inner_union = T.TransactionResult.fields[1][1]
    ext_v0 = T.TransactionResult.fields[2][1].make(0)
    with tracing.stopwatch() as sw:
        for idx, frame, (meta_b, result_b) in zip(indices, frames,
                                                  records):
            pair_b = frame.full_hash() + result_b
            env_b = T.TransactionEnvelope.encode(frame.envelope)
            # TransactionResult is a struct: rebuild its cheap scalar
            # fields eagerly (feeCharged i64 leads the encoding, ext v0
            # trails) and keep only the result union lazy
            result = T.TransactionResult.make(
                feeCharged=frame.fee_charged,
                result=LazyUnion(inner_union, result_b[8:-4]),
                ext=ext_v0)
            res.records[idx] = (
                True,
                result,
                LazyUnion(T.TransactionMeta, meta_b),
                meta_b, pair_b, env_b,
            )
    res.encode_seconds += sw.seconds


def run_cluster_native(cluster, snapshot, apply_order, verify,
                       result_cls):
    """Apply one kernel-eligible cluster natively.

    Returns a populated ``result_cls`` (the executor's ClusterResult)
    or raises ``KernelDecline`` — the caller then runs the Python
    reference apply for the cluster.  Never mutates shared state: the
    kernel works on copies, so a decline discards everything.
    """
    mod = _kernel_ready(snapshot)
    header = snapshot.header
    frames = _screen_cluster(cluster, snapshot, apply_order, verify)

    params = (header.ledgerSeq, header.scpValue.closeTime, header.baseFee,
              header.baseReserve, snapshot.idpool0)
    entries, books = _pack_inputs(snapshot, cluster.keys, cluster.pairs)
    txs = [_tx_tuple(frame, shape)
           for frame, shape in zip(frames, cluster.shapes)]

    out = mod.apply_cluster(params, entries, books, txs)
    if not out[0]:
        _, reason, tx_index = out
        raise _kernel_declined(_shape_kinds([cluster]), reason, tx_index)
    _, deltas, records, idpool_final = out

    from .executor import _is_fresh_offer_key

    res = result_cls(cluster.cluster_id)
    res.native = "hit"
    res.op_kinds = _kind_counts(cluster)
    declared = cluster.writes
    for kb, eb in deltas:
        # write-side guard, mirroring the executor's _post_check: every
        # kernel write must be a declared write or a fresh offer id
        if kb not in declared and not _is_fresh_offer_key(
                kb, snapshot.idpool0):
            # fixed code: the key hex must not leak into the metric
            # name (unbounded counter cardinality in a decline storm)
            raise KernelDecline(f"kernel wrote undeclared key {kb.hex()}",
                                code="undeclared_write")
        res.delta[kb] = None if eb is None else PackedEntry(eb)
        if kb.startswith(_OFFER_PREFIX):
            res.okeys.add(kb)
    if idpool_final != snapshot.idpool0:
        if not cluster.writes_header:
            raise KernelDecline("kernel allocated ids without the token")
        res.header = header._replace(idPool=idpool_final)
    _fill_records(res, cluster.indices, frames, records)
    return res


def run_clusters_native_batched(clusters, snapshot, apply_order, verify,
                                result_cls):
    """Apply MANY kernel-eligible clusters in ONE encode + ONE
    GIL-released ``apply_cluster`` crossing (ROADMAP 2d: a 1000-payment
    close plans hundreds of 2-tx clusters, and per-cluster dispatch
    pays the FFI/encode toll hundreds of times).

    Sound because batchable clusters are disjoint by construction (the
    planner merges any key/book/id-pool conflict into one cluster) and
    none writes the header (id-pool allocators are excluded by the
    caller): applying their transactions back-to-back over the merged
    snapshot table is exactly per-cluster application.  Outputs are
    split back per cluster — deltas by the declared-key ownership map,
    records by tx index.  Any decline rejects the WHOLE batch; the
    caller retries per cluster so one poisoned cluster cannot drag its
    batchmates onto the Python path.
    """
    mod = _kernel_ready(snapshot)
    header = snapshot.header
    clusters = sorted(clusters, key=lambda c: c.cluster_id)
    owner: dict = {}
    all_keys: set = set()
    all_pairs: set = set()
    txs = []
    frames_of = {}
    for cluster in clusters:
        if cluster.writes_header:
            raise KernelDecline(
                f"cluster {cluster.cluster_id} allocates offer ids; "
                f"not batchable")
        frames = _screen_cluster(cluster, snapshot, apply_order, verify)
        frames_of[cluster.cluster_id] = frames
        for kb in cluster.keys:
            owner[kb] = cluster
        all_keys |= cluster.keys
        all_pairs |= cluster.pairs
        for frame, shape in zip(frames, cluster.shapes):
            txs.append(_tx_tuple(frame, shape))

    params = (header.ledgerSeq, header.scpValue.closeTime, header.baseFee,
              header.baseReserve, snapshot.idpool0)
    entries, books = _pack_inputs(snapshot, all_keys, all_pairs)
    out = mod.apply_cluster(params, entries, books, txs)
    if not out[0]:
        _, reason, tx_index = out
        raise _kernel_declined(_shape_kinds(clusters), reason, tx_index,
                               batched=True)
    _, deltas, records, idpool_final = out
    if idpool_final != snapshot.idpool0:
        raise KernelDecline("batched kernel allocated offer ids")

    results = {}
    for c in clusters:
        res = result_cls(c.cluster_id)
        res.native = "hit"
        res.op_kinds = _kind_counts(c)
        results[c.cluster_id] = res
    for kb, eb in deltas:
        cluster = owner.get(kb)
        # no fresh-offer exemption here: id-pool allocators never batch,
        # so every write must belong to exactly one declared key set
        if cluster is None or kb not in cluster.writes:
            raise KernelDecline(
                f"batched kernel wrote undeclared key {kb.hex()}",
                code="undeclared_write")
        res = results[cluster.cluster_id]
        res.delta[kb] = None if eb is None else PackedEntry(eb)
        if kb.startswith(_OFFER_PREFIX):
            res.okeys.add(kb)
    pos = 0
    for cluster in clusters:
        frames = frames_of[cluster.cluster_id]
        n = len(frames)
        _fill_records(results[cluster.cluster_id], cluster.indices,
                      frames, records[pos:pos + n])
        pos += n
    return [results[c.cluster_id] for c in clusters]


def run_fee_phase_native(ltx, apply_order, base_fee):
    """Charge the WHOLE fee/seqnum phase in one GIL-released kernel
    call (apply_kernel.cpp ``charge_fees`` — the batched twin of the
    per-tx ``frame.process_fee_seq_num`` loop).

    On success: sets ``frame.fee_charged`` on every frame, installs the
    packed post-charge account images + the feePool bump into ``ltx``,
    and returns the per-tx ``feeProcessing`` LedgerEntryChanges (each a
    pre-encoded ``[STATE, UPDATED]`` pair riding ``LazyUnion``) in the
    exact shape the Python loop returns.  Raises ``KernelDecline`` with
    ``ltx`` untouched otherwise — any unsupported account shape
    declines the whole batch to the reference loop (bytes identical
    either way; tests/test_native_fee.py holds the parity)."""
    from ..transactions import utils as U
    from ..transactions.frame import TransactionFrame

    mod = kernel_module()
    if mod is None:
        raise KernelDecline("kernel unavailable", op="fee")
    if not _constants_in_lockstep():
        raise KernelDecline("protocol constant drift", op="fee")
    header = ltx.header()
    if header.ledgerVersion != 19:
        raise KernelDecline(
            f"protocol version {header.ledgerVersion} not kernel-backed",
            op="fee")

    acct_idx: dict = {}
    acct_keys: List[bytes] = []
    accounts: List[bytes] = []
    fee_txs: List[tuple] = []
    for idx, frame in enumerate(apply_order):
        if type(frame) is not TransactionFrame:
            # fee bumps charge a second fee source; reference loop owns
            raise KernelDecline(f"tx {idx} not kernel-shaped", op="fee",
                                code="not_kernel_shaped")
        src = frame.source_account_id()
        i = acct_idx.get(src)
        if i is None:
            kb = account_key_bytes(src)
            entry = ltx.get(kb)
            if entry is None:
                # the reference raises "fee source vanished" — a halt,
                # not a success path; keep it on the Python loop
                raise KernelDecline(f"tx {idx} fee source missing",
                                    op="fee", code="fee_source_missing")
            i = acct_idx[src] = len(accounts)
            acct_keys.append(kb)
            accounts.append(T.LedgerEntry.encode(entry))
        fee_txs.append((i, frame.get_full_fee(), frame.num_operations()))

    # base_fee None means "no vote: charge the full fee"; an
    # INT64_MAX stride makes the kernel's min() pick full_fee exactly
    bf = U.INT64_MAX if base_fee is None else base_fee
    out = mod.charge_fees((header.ledgerSeq, bf), accounts, fee_txs)
    if not out[0]:
        raise KernelDecline(f"kernel declined fee batch: {out[1]}",
                            op="fee", code=_reason_slug(out[1]))
    _, rows, finals, fee_pool_delta = out

    fee_changes = []
    for frame, (charged, state_b, upd_b) in zip(apply_order, rows):
        frame.fee_charged = charged
        fee_changes.append([LazyUnion(T.LedgerEntryChange, state_b),
                            LazyUnion(T.LedgerEntryChange, upd_b)])
    # the merge is the executor's delta-install idiom: packed images
    # land in the close ltx, materialized only if someone reads them
    for kb, eb in zip(acct_keys, finals):
        ltx._delta[kb] = PackedEntry(eb)
    ltx.set_header(header._replace(
        feePool=header.feePool + fee_pool_delta))
    return fee_changes
