"""Bit-identical concurrent executor for planned apply clusters.

Execution model
---------------
The close's main thread snapshots every declared footprint key (plus
the materialized order books) out of the open close ``LedgerTxn`` into
one immutable ``ApplySnapshot``, then runs each cluster as a task on
the worker pool.  A cluster applies its transactions — in canonical
order — through an ordinary ``LedgerTxn`` whose parent is a
``ClusterView``: a read-only window onto the snapshot restricted to
the cluster's declared footprint.

The speculation guard IS the ``ClusterView``: any ``get`` outside the
declared key set, any ``best_offer`` on an undeclared pair, and any
scan the plan did not materialize raises ``FootprintEscape``.  The
executor then abandons the whole parallel attempt (no cluster delta
ever reaches the close LedgerTxn) and the caller replays the set
sequentially — the always-correct fallback — while the
``apply.parallel.abort`` counter and the close's ``ledger.apply.*``
spans record the event.

Bit-identity argument (why merged results equal sequential apply):
clusters are closed under declared write/read conflicts, so a tx's
reads either hit pre-apply state (identical to what sequential apply
would serve, because no other cluster may write them) or intra-cluster
writes (applied in canonical order).  Undeclared accesses cannot
silently diverge — they escape.  Header mutation (offer-id
allocation) is confined to the single cluster holding the id-pool
token.  Cluster deltas are disjoint by construction, so merging them
in cluster order reproduces the sequential delta exactly; meta STATE
entries read through the same chain and match byte-for-byte.

The GIL note: transaction apply is host Python, so clusters time-slice
one interpreter — the wall-clock win comes from overlapping the
GIL-releasing native work (xdrpack meta/result/envelope serialization
done eagerly inside each worker) with other clusters' Python, and from
the close path consuming those pre-encoded bytes instead of
re-encoding (tx history rows, result-set hashing).
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional, Tuple

from ..ledger.ledger_txn import LedgerTxn, VIRTUAL_PREFIX, _OFFER_PREFIX
from ..xdr import types as T
from .planner import ApplyPlan, plan_parallel_apply


class FootprintEscape(Exception):
    """A cluster touched state outside its declared footprint."""


_MISS = object()


class ApplySnapshot:
    """Immutable pre-apply state shared by every cluster (built on the
    main thread, read-only afterwards — no locking needed)."""

    __slots__ = ("store", "header", "books", "idpool0", "pool_quote")

    def __init__(self, store: Dict[bytes, object], header, books,
                 idpool0: int, pool_quote: bool = True):
        self.store = store
        self.header = header
        # pair token -> {direction (selling, buying): sorted
        #               [(Fraction, offerID, kb)]}
        self.books = books
        self.idpool0 = idpool0
        # NATIVE_POOL_QUOTE kill switch: False restores the pre-r16
        # decline-if-live-pool host screen (native_apply._screen_cluster)
        self.pool_quote = pool_quote


def _is_fresh_offer_key(kb: bytes, idpool0: int) -> bool:
    """Offer keys minted this close carry ids beyond the pre-apply
    idPool — they cannot exist in pre-state nor belong to any other
    cluster (only the id-pool cluster allocates)."""
    if len(kb) != 48 or not kb.startswith(_OFFER_PREFIX):
        return False
    offer_id = int.from_bytes(kb[40:48], "big", signed=True)
    return offer_id > idpool0


class ClusterView:
    """Read-only LedgerTxn parent enforcing one cluster's footprint.

    Implements the parent surface LedgerTxn fall-through uses: ``get``
    / ``header`` plus the root query hooks (``_best_offer``,
    ``_offers_by_seller``, ``_entries_by_key_prefix``)."""

    __slots__ = ("snapshot", "store", "pairs", "abort", "_child")

    def __init__(self, snapshot: ApplySnapshot, cluster, abort):
        self.snapshot = snapshot
        # pre-restricted store: declared-key reads are ONE dict lookup
        # (this get underlies every entry load in the cluster's apply
        # loop — the speculation guard must not tax the hot path)
        self.store = {kb: snapshot.store[kb] for kb in cluster.keys}
        self.pairs = cluster.pairs
        self.abort = abort
        self._child = None  # LedgerTxn child-tracking protocol

    # -- reads --------------------------------------------------------------

    def get(self, kb: bytes):
        v = self.store.get(kb, _MISS)
        if v is not _MISS:
            return v
        if kb.startswith(VIRTUAL_PREFIX):
            return None  # sponsorship bookkeeping never exists pre-tx
        if _is_fresh_offer_key(kb, self.snapshot.idpool0):
            return None  # created this close by THIS cluster
        raise FootprintEscape(f"undeclared key access: {kb.hex()}")

    def header(self):
        return self.snapshot.header

    # -- root query hooks (LedgerTxn.best_offer / offers_by_account /
    #    entries_by_key_prefix end their layer walk here) ------------------

    def _best_offer(self, selling: bytes, buying: bytes,
                    overrides, worse_than=None):
        from .footprint import pair_token

        pair = pair_token(selling, buying)
        if pair not in self.pairs:
            raise FootprintEscape(
                "undeclared order-book scan: "
                f"{selling.hex()[:16]}/{buying.hex()[:16]}")
        candidates = []
        for price, oid, kb in self.snapshot.books[pair][(selling, buying)]:
            if kb in overrides:
                continue
            key = (price, oid)
            if worse_than is not None and key <= worse_than:
                continue
            candidates.append((*key, kb))
            break  # book rows are sorted: first unshadowed row wins…
        # …but an override may still beat it
        for kb, e in sorted(overrides.items()):
            if e is None:
                continue
            o = e.data.value
            if (T.Asset.encode(o.selling) != selling
                    or T.Asset.encode(o.buying) != buying):
                continue
            from fractions import Fraction

            key = (Fraction(o.price.n, o.price.d), o.offerID)
            if worse_than is not None and key <= worse_than:
                continue
            candidates.append((*key, kb))
        if not candidates:
            return None
        candidates.sort()
        kb = candidates[0][2]
        e = overrides.get(kb)
        if e is None:
            e = self.get(kb)
        return e

    def _offers_by_seller(self, sellerid: bytes):
        # no plan materializes per-seller offer lists today (the ops
        # that scan them are imprecise and close sequentially), so any
        # scan reaching a worker MUST abort — silently serving an empty
        # list would be a wrong-state apply, not an escape
        raise FootprintEscape(
            f"undeclared offer scan for seller {sellerid.hex()[:16]}")

    def _entries_by_key_prefix(self, prefix: bytes):
        raise FootprintEscape(
            f"undeclared prefix scan: {prefix.hex()[:16]}")


class ClusterResult:
    """What one cluster task brings back to the main thread."""

    __slots__ = ("cluster_id", "records", "delta", "okeys", "vkeys",
                 "header", "op_costs", "span_seconds", "encode_seconds",
                 "native", "batched", "op_kinds", "native_op",
                 "native_code")

    def __init__(self, cluster_id: int):
        self.cluster_id = cluster_id
        # tx index -> (ok, result, meta, meta_bytes, pair_bytes, env_bytes)
        self.records: Dict[int, tuple] = {}
        self.delta: Dict[bytes, object] = {}
        self.okeys: set = set()
        self.vkeys: set = set()
        self.header = None
        self.op_costs: Dict[str, List[float]] = {}
        self.span_seconds = 0.0
        self.encode_seconds = 0.0
        # native-kernel outcome: "hit" (applied by the kernel),
        # "decline:<reason>" (kernel refused, Python applied), or None
        # (kernel never attempted)
        self.native: Optional[str] = None
        # applied as part of a multi-cluster batched kernel crossing
        # (ROADMAP 2d amortized dispatch)
        self.batched = False
        # taxonomy: tx count per kernel-shape kind on a hit, and the
        # (op family, reason slug) of a decline — both feed the
        # per-op-type apply.native.* metric breakout
        self.op_kinds: Dict[str, int] = {}
        self.native_op: Optional[str] = None
        self.native_code: Optional[str] = None


class ParallelApplyManager:
    """Owns the apply worker pool + per-session counters; one per
    Application (mirrors the PR-1 bucket-merge pool pattern)."""

    def __init__(self, app):
        self.app = app
        cfg = app.config
        self.workers = int(getattr(cfg, "PARALLEL_APPLY_WORKERS", 0) or 0)
        parallel_on = bool(getattr(cfg, "PARALLEL_APPLY", False))
        # NATIVE_APPLY=0 is the kernel kill switch: clusters then always
        # run the Python reference apply.  NATIVE_APPLY_INLINE engages
        # the planner+kernel WITHOUT a worker pool (workers 0/1): the
        # kernel is faster even sequentially, and the single-cluster
        # fast path needs no pool at all.
        self.native_wanted = bool(getattr(cfg, "NATIVE_APPLY", True))
        pool_on = parallel_on and self.workers >= 2
        if self.native_wanted and (pool_on or parallel_on and
                                   getattr(cfg, "NATIVE_APPLY_INLINE",
                                           False)):
            # probe (and build, once per process — the .so is cached)
            # up front: a host whose kernel cannot build must not pay
            # the single-cluster planning/snapshot overhead for
            # guaranteed declines
            from .native_apply import kernel_module

            if kernel_module() is None:
                self.native_wanted = False
        inline_native = parallel_on and self.native_wanted and \
            bool(getattr(cfg, "NATIVE_APPLY_INLINE", False))
        self.enabled = pool_on or inline_native
        # opt-in post-apply invariant pass over kernel-applied cluster
        # deltas (ROADMAP 2e): configuring INVARIANT_CHECKS engages it,
        # so chaos runs with INVARIANT_CHECKS=[".*"] cover the native
        # path too.  Packed deltas decode lazily inside the checkers —
        # an operator who opts out of checkers never pays the decode.
        self.native_invariants = bool(
            self.enabled and self.native_wanted
            and getattr(app, "invariants", None) is not None
            and app.invariants.invariants)
        if (self.enabled and self.native_wanted
                and getattr(cfg, "INVARIANT_CHECKS", None)):
            from ..utils.logging import get_logger

            get_logger("Ledger").info(
                "native apply kernel on: INVARIANT_CHECKS %s run per-op "
                "on Python-applied clusters and as a post-apply "
                "cluster-delta pass on kernel-applied clusters "
                "(NATIVE_APPLY=0 to run every checker per-op on every "
                "tx)", cfg.INVARIANT_CHECKS)
        self.executor = None
        if pool_on:
            from concurrent.futures import ThreadPoolExecutor

            self.executor = ThreadPoolExecutor(
                max_workers=self.workers, thread_name_prefix="tx-apply")
        # session counters (main-thread only: updated after joins)
        self.stats = {
            "parallel_closes": 0,
            "sequential_closes": 0,
            "aborts": 0,
            "unplanned": 0,
            "preplan_hits": 0,
            "native_hits": 0,      # clusters applied by the kernel
            "native_declines": 0,  # kernel refused -> Python fallback
            "native_off": 0,       # clusters never offered to the kernel
            "batched_clusters": 0,  # kernel hits via batched crossings
            "escapes": [],  # last few escape reasons, newest last
            "native_decline_reasons": [],  # newest last, bounded
        }
        self.last_plan_stats: dict = {}
        # nomination-time plan cache: the plan is a pure function of
        # (tx set, LCL state) — the fee phase moves balances only, never
        # key sets, sponsors or the order book — so the herder can plan
        # while building its proposal and the close just looks it up.
        # Keyed (contents_hash, prev_ledger_hash); externalized foreign
        # sets miss and plan inside the close.  Main-crank-thread only.
        from collections import OrderedDict

        self._plan_cache: "OrderedDict" = OrderedDict()

    def shutdown(self) -> None:
        if self.executor is not None:
            self.executor.shutdown(wait=True)
        path = getattr(self.app.config, "PARALLEL_APPLY_STATS_FILE", None)
        if path:
            self._append_stats_line(path)

    def _append_stats_line(self, path: str) -> None:
        import json

        line = {k: v for k, v in self.stats.items()
                if k not in ("escapes", "native_decline_reasons")}
        line["escape_reasons"] = list(self.stats["escapes"])[-8:]
        line["native_decline_reasons"] = \
            list(self.stats["native_decline_reasons"])[-8:]
        line["workers"] = self.workers
        line["native"] = self.native_wanted
        try:
            with open(path, "a", encoding="utf-8") as f:
                f.write(json.dumps(line) + "\n")
        except OSError:
            pass

    # -- planning -----------------------------------------------------------

    def preplan(self, tx_set, root) -> None:
        """Nomination-time planning (herder.trigger_next_ledger): plan
        the node's own proposal against the LCL so the close can skip
        the planning cost when this exact set externalizes."""
        if not self.enabled:
            return
        key = (tx_set.contents_hash(), tx_set.previous_ledger_hash)
        if key in self._plan_cache:
            return
        apply_order = tx_set.txs_in_apply_order()
        if len(apply_order) < 2:
            return
        with LedgerTxn(root) as ltx:
            plan, stats = plan_parallel_apply(
                apply_order, ltx,
                allow_single_native=self.native_wanted)
            ltx.rollback()
        self._plan_cache[key] = (plan, stats)
        while len(self._plan_cache) > 4:
            self._plan_cache.popitem(last=False)

    def plan(self, tx_set, apply_order, ltx) -> Optional[ApplyPlan]:
        cached = self._plan_cache.pop(
            (tx_set.contents_hash(), tx_set.previous_ledger_hash), None)
        if cached is not None:
            plan, stats = cached
            self.stats["preplan_hits"] += 1
            stats = dict(stats, preplanned=True)
        else:
            plan, stats = plan_parallel_apply(
                apply_order, ltx,
                allow_single_native=self.native_wanted)
        self.last_plan_stats = stats
        if plan is None:
            self.stats["unplanned"] += 1
        return plan

    # -- execution ----------------------------------------------------------

    def execute(self, plan: ApplyPlan, ltx, apply_order, verify,
                invariant_check) -> Optional[List[tuple]]:
        """Run the plan; on success merge cluster deltas into ``ltx``
        (canonical cluster order) and return the per-tx records in
        apply order: ``(ok, result, meta, meta_bytes, pair_bytes,
        env_bytes)``.  Returns None on abort — ``ltx`` untouched."""
        tracer = self.app.tracer
        metrics = self.app.metrics
        snapshot = self._build_snapshot(plan, ltx)
        abort = threading.Event()
        parent_token = tracer.current_id()
        # pack clusters into a bounded number of tasks (round-robin by
        # cluster id — deterministic): a 1000-payment close can plan
        # hundreds of two-tx clusters, and one future per cluster would
        # drown the win in submit/teardown overhead.  A single-cluster
        # plan (the kernel's adversarial-ring fast path) and the
        # pool-less native-inline mode run on the close thread instead:
        # one task's pool round-trip buys nothing.
        inline = self.executor is None or len(plan.clusters) == 1
        if inline:
            groups: List[List] = [list(plan.clusters)]
        else:
            n_tasks = min(len(plan.clusters), self.workers * 2)
            groups = [[] for _ in range(n_tasks)]
            for cluster in plan.clusters:
                groups[cluster.cluster_id % n_tasks].append(cluster)
        results: List[Optional[ClusterResult]] = []
        escape: Optional[str] = None

        def _collect(run_group):
            nonlocal escape
            try:
                results.extend(run_group())
            except FootprintEscape as e:
                abort.set()
                escape = escape or str(e)
                results.append(None)
            except Exception as e:  # never let a parallel-only failure
                # kill a close the sequential path would survive; real
                # apply errors (invariant violations…) re-raise there
                abort.set()
                escape = escape or f"worker exception: {e!r}"
                results.append(None)

        if inline:
            for group in groups:
                _collect(lambda g=group: self._run_task(
                    g, snapshot, apply_order, verify, invariant_check,
                    abort, tracer, parent_token))
        else:
            futures = [self.executor.submit(
                self._run_task, group, snapshot, apply_order, verify,
                invariant_check, abort, tracer, parent_token)
                for group in groups]
            for fut in futures:
                _collect(fut.result)
        # a second header writer is a planner invariant violation —
        # detect it BEFORE any delta reaches the close LedgerTxn
        if sum(1 for r in results
               if r is not None and r.header is not None) > 1:
            abort.set()
            escape = escape or "two clusters wrote the header"
        if abort.is_set() or any(r is None for r in results):
            self.stats["aborts"] += 1
            if escape is not None:
                self.stats["escapes"].append(escape)
                del self.stats["escapes"][:-32]
            metrics.counter("apply.parallel.abort").inc()
            from ..utils.logging import get_logger

            get_logger("Ledger").warning(
                "parallel apply aborted (%s); replaying sequentially",
                escape or "worker failure")
            return None

        # merge per-op cost attribution into the close's collector
        from ..utils import tracing

        collector = tracing.op_collector()
        if collector is not None:
            merged: Dict[str, List[float]] = {}
            for res in results:
                for name, (secs, count) in res.op_costs.items():
                    slot = merged.setdefault(name, [0.0, 0])
                    slot[0] += secs
                    slot[1] += count
            for name in sorted(merged):
                secs, count = merged[name]
                collector.add_many(name, secs, int(count))

        # canonical-order merge: cluster deltas are disjoint by
        # construction, so update order cannot change the outcome — but
        # keep it deterministic anyway
        header = None
        for res in sorted(results, key=lambda r: r.cluster_id):
            ltx._delta.update(res.delta)
            ltx._okeys |= res.okeys
            ltx._vkeys |= res.vkeys
            if res.header is not None:
                header = res.header
        if header is not None:
            ltx.set_header(header)

        records: List[tuple] = [None] * len(apply_order)
        for res in results:
            for idx, rec in res.records.items():
                records[idx] = rec
        self.stats["parallel_closes"] += 1
        metrics.counter("apply.parallel.close").inc()
        # native-kernel accounting (main thread, after joins)
        for res in results:
            if res.native == "hit":
                self.stats["native_hits"] += 1
                metrics.counter("apply.native.hit").inc()
                # per-op-type hit attribution (tx-granular: a cluster
                # may mix op families); bounded family — past the cap
                # new kinds collapse into apply.native.hit.other
                for kind in sorted(res.op_kinds):
                    metrics.counter(metrics.bounded_name(
                        "apply.native.hit", str(kind), cap=24)).inc(
                            res.op_kinds[kind])
                if res.batched:
                    self.stats["batched_clusters"] += 1
                    metrics.counter("apply.native.batched_clusters").inc()
            elif res.native is not None:
                self.stats["native_declines"] += 1
                metrics.counter("apply.native.decline").inc()
                # reason x op-type breakout: a decline storm names its
                # exact coverage gap in /metrics instead of hiding
                # behind one opaque counter.  Bounded family: an
                # adversarial op mix can mint unbounded (op, reason)
                # combinations — past the cap they collapse into
                # apply.native.decline.other
                metrics.counter(metrics.bounded_name(
                    "apply.native.decline",
                    f"{res.native_op or 'cluster'}."
                    f"{res.native_code or 'unknown'}", cap=48)).inc()
                self.stats["native_decline_reasons"].append(
                    res.native[len("decline:"):])
                del self.stats["native_decline_reasons"][:-32]
            else:
                self.stats["native_off"] += 1
                metrics.counter("apply.native.fallback").inc()
        encode_ms = sum(r.encode_seconds for r in results) * 1000.0
        self.last_plan_stats = dict(self.last_plan_stats,
                                    native_encode_ms=round(encode_ms, 3))
        return records

    # -- internals ----------------------------------------------------------

    def _build_snapshot(self, plan: ApplyPlan, ltx) -> ApplySnapshot:
        store: Dict[bytes, object] = {}
        for cluster in plan.clusters:
            for kb in cluster.keys:
                if kb not in store:
                    store[kb] = ltx.get(kb)
        books = {pair: mat.offers
                 for pair, mat in plan.context.books.items()}
        header = ltx.header()
        pool_quote = bool(getattr(self.app.config, "NATIVE_POOL_QUOTE",
                                  True))
        return ApplySnapshot(store, header, books, header.idPool,
                             pool_quote)

    def _run_task(self, clusters, snapshot, apply_order, verify,
                  invariant_check, abort, tracer,
                  parent_token) -> List["ClusterResult"]:
        """Worker-side: one task runs its packed clusters back to back.

        Runs of kernel-eligible, non-id-pool clusters are coalesced
        into ONE batched kernel crossing (one encode, one GIL release)
        instead of one call per 2-tx cluster — the amortized-dispatch
        half of ROADMAP 2d.  Everything else goes through the
        per-cluster path unchanged."""
        results: List["ClusterResult"] = []
        batch: List = []

        def run_one(cluster):
            return self._run_cluster(cluster, snapshot, apply_order,
                                     verify, invariant_check, abort,
                                     tracer, parent_token)

        def flush():
            if len(batch) >= 2:
                results.extend(self._run_cluster_batch(
                    list(batch), snapshot, apply_order, verify,
                    invariant_check, abort, tracer, parent_token))
            elif batch:
                results.append(run_one(batch[0]))
            batch.clear()

        for cluster in clusters:
            if self.native_wanted and cluster.kernel_ok and \
                    not cluster.writes_header:
                batch.append(cluster)
            else:
                flush()
                results.append(run_one(cluster))
        flush()
        return results

    def _run_cluster_batch(self, batch, snapshot, apply_order, verify,
                           invariant_check, abort, tracer,
                           parent_token) -> List["ClusterResult"]:
        """One GIL-released kernel crossing for a run of disjoint
        kernel-eligible clusters; on any decline, retry per cluster so
        one poisoned cluster cannot drag its batchmates off the kernel."""
        from .native_apply import (
            KernelDecline, run_clusters_native_batched)

        if abort.is_set():
            raise FootprintEscape("aborted by another cluster")
        total_txs = sum(len(c.indices) for c in batch)
        with tracer.span("ledger.apply.cluster.native.batch",
                         parent=parent_token, clusters=len(batch),
                         txs=total_txs, outcome="hit") as nspan:
            try:
                batch_results = run_clusters_native_batched(
                    batch, snapshot, apply_order, verify, ClusterResult)
            except KernelDecline as e:
                if nspan.args is not None:
                    nspan.args["outcome"] = "decline"
                    nspan.args["reason"] = str(e)
                batch_results = None
        if batch_results is None:
            return [self._run_cluster(c, snapshot, apply_order, verify,
                                      invariant_check, abort, tracer,
                                      parent_token)
                    for c in batch]
        ordered = sorted(batch, key=lambda c: c.cluster_id)
        for cluster, res in zip(ordered, batch_results):
            if self.native_invariants:
                self._check_native_invariants(cluster, snapshot, res)
            # metrics attribution only: apportion the crossing's wall
            # time across its clusters by tx count
            share = nspan.seconds * len(cluster.indices) / total_txs
            res.op_costs = {"native_kernel": [share,
                                              len(cluster.indices)]}
            res.span_seconds = share
            res.batched = True
        return batch_results

    def _run_cluster(self, cluster, snapshot,
                     apply_order, verify, invariant_check, abort,
                     tracer, parent_token) -> ClusterResult:
        """Apply one cluster — native kernel first when eligible, the
        Python reference apply otherwise (and on any kernel decline) —
        pre-encoding meta/result/envelope bytes and post-checking the
        written keys."""
        from ..utils import tracing

        decline_reason = None
        decline_op = decline_code = None
        native_res = None
        if self.native_wanted and cluster.kernel_ok:
            from .native_apply import KernelDecline, run_cluster_native

            with tracer.span("ledger.apply.cluster.native",
                             parent=parent_token,
                             cluster=cluster.cluster_id,
                             txs=len(cluster.indices),
                             outcome="hit") as nspan:
                try:
                    native_res = run_cluster_native(
                        cluster, snapshot, apply_order, verify,
                        ClusterResult)
                except KernelDecline as e:
                    decline_reason = str(e)
                    decline_op, decline_code = e.op, e.code
                    if nspan.args is not None:
                        nspan.args["outcome"] = "decline"
                        nspan.args["reason"] = decline_reason
            if native_res is not None:
                if self.native_invariants:
                    self._check_native_invariants(cluster, snapshot,
                                                  native_res)
                native_res.op_costs = {"native_kernel": [
                    nspan.seconds, len(cluster.indices)]}
                native_res.span_seconds = nspan.seconds
                return native_res

        res = ClusterResult(cluster.cluster_id)
        if decline_reason is not None:
            res.native = f"decline:{decline_reason}"
            res.native_op = decline_op
            res.native_code = decline_code
        view = ClusterView(snapshot, cluster, abort)
        with tracer.span("ledger.apply.cluster", parent=parent_token,
                         cluster=cluster.cluster_id,
                         txs=len(cluster.indices)) as span, \
                tracing.collect_op_costs() as op_costs:
            cluster_ltx = LedgerTxn(view)
            for idx in cluster.indices:
                if abort.is_set():
                    # another cluster escaped: the attempt is doomed,
                    # stop burning GIL time on results that get discarded
                    raise FootprintEscape("aborted by another cluster")
                frame = apply_order[idx]
                ok, result, meta = frame.apply(
                    cluster_ltx, verify=verify,
                    invariant_check=invariant_check)
                with tracing.stopwatch() as sw:
                    pair = frame.result_pair(result)
                    pair_bytes = T.TransactionResultPair.encode(pair)
                    meta_bytes = T.TransactionMeta.encode(meta)
                    env_bytes = T.TransactionEnvelope.encode(frame.envelope)
                res.encode_seconds += sw.seconds
                res.records[idx] = (ok, result, meta, meta_bytes,
                                    pair_bytes, env_bytes)
            self._post_check(cluster, snapshot, cluster_ltx)
            res.delta = cluster_ltx._delta
            res.okeys = cluster_ltx._okeys
            res.vkeys = cluster_ltx._vkeys
            res.header = cluster_ltx._header
            res.op_costs = op_costs.costs
        res.span_seconds = span.seconds
        return res

    def _check_native_invariants(self, cluster, snapshot, res) -> None:
        """Post-apply invariant pass over one kernel-applied cluster's
        delta (ROADMAP 2e): rebuild the layer shape the checkers expect
        — a LedgerTxn whose parent is the cluster's footprint view —
        seed it with the kernel's packed delta (entries decode lazily on
        first checker touch), and run every configured checker once at
        cluster granularity.

        A violation raises through the worker's escape machinery: the
        parallel attempt aborts and the sequential replay re-runs the
        same transactions through the Python reference apply with
        per-op checkers — which either reproduces the violation (a real
        bug: the close crashes, safety-first) or proves the kernel
        diverged (the replay's bytes win)."""
        from ..invariant.manager import InvariantDoesNotHold

        view = ClusterView(snapshot, cluster, None)
        ltx = LedgerTxn(view)
        ltx._delta = dict(res.delta)
        ltx._okeys = set(res.okeys)
        if res.header is not None:
            ltx.set_header(res.header)
        try:
            self.app.invariants.check_on_tx_apply(ltx, None, True)
        except InvariantDoesNotHold as e:
            self.app.metrics.counter("apply.native.invariant-fail").inc()
            raise FootprintEscape(
                f"native cluster invariant: {e}") from e

    @staticmethod
    def _post_check(cluster, snapshot, cluster_ltx) -> None:
        """Write-side guard: every written key must be declared (or a
        fresh offer id), and only the id-pool cluster may touch the
        header."""
        for kb in cluster_ltx._delta:
            if kb in cluster.writes or kb.startswith(VIRTUAL_PREFIX):
                continue
            if _is_fresh_offer_key(kb, snapshot.idpool0):
                continue
            if kb in cluster.keys:
                # declared read written to: safe for THIS cluster's view
                # but the planner treated it as read-only for conflict
                # closure — another cluster may read it.  Escape.
                raise FootprintEscape(
                    f"write to read-declared key: {kb.hex()}")
            raise FootprintEscape(f"undeclared write: {kb.hex()}")
        if cluster_ltx._header is not None and not cluster.writes_header:
            raise FootprintEscape("undeclared header write")
