"""Conflict-cluster scheduler: footprints -> deterministic clusters.

Two transactions conflict when one's declared WRITE set intersects the
other's declared read-or-write set, when they touch the same order-book
pair, or when both may allocate from the offer-id pool (a global
header counter whose values are consensus-visible).  Conflicts are
closed transitively with union-find over the canonical apply order;
each resulting cluster preserves intra-cluster canonical order and the
clusters themselves are emitted in ascending first-tx order, so the
whole plan is a pure function of (tx set, ledger state) — no
iteration-order dependence, no randomness.

``plan_parallel_apply`` returns ``None`` when the set cannot be
parallelized (an imprecise footprint, or fewer than two clusters):
the caller then runs the ordinary sequential loop.
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

from .footprint import PlanContext, TxFootprint, footprint_for

#: conflict token for offer-id-pool allocation (header.idPool)
IDPOOL_TOKEN = ("header", "idpool")


class Cluster:
    """One parallel unit: canonical-order tx indices + merged footprint."""

    __slots__ = ("cluster_id", "indices", "keys", "writes", "pairs",
                 "writes_header", "kernel_ok", "shapes")

    def __init__(self, cluster_id: int):
        self.cluster_id = cluster_id
        self.indices: List[int] = []
        self.keys: Set[bytes] = set()    # reads | writes
        self.writes: Set[bytes] = set()
        self.pairs: Set[Tuple[bytes, bytes]] = set()
        self.writes_header = False
        # every member tx is kernel-shaped: the executor may hand the
        # whole cluster to the native apply kernel (state-level checks
        # happen inside the kernel, which declines back to Python);
        # ``shapes`` holds the per-tx kernel descriptors, parallel to
        # ``indices`` (entries are None for non-eligible txs)
        self.kernel_ok = True
        self.shapes: List[Optional[tuple]] = []


class ApplyPlan:
    __slots__ = ("clusters", "footprints", "context", "stats")

    def __init__(self, clusters: List[Cluster],
                 footprints: List[TxFootprint],
                 context: PlanContext, stats: dict):
        self.clusters = clusters
        self.footprints = footprints
        self.context = context
        self.stats = stats


class _UnionFind:
    def __init__(self, n: int):
        self.parent = list(range(n))

    def find(self, i: int) -> int:
        p = self.parent
        while p[i] != i:
            p[i] = p[p[i]]
            i = p[i]
        return i

    def union(self, a: int, b: int) -> None:
        ra, rb = self.find(a), self.find(b)
        if ra != rb:
            # smaller index wins: keeps representatives canonical
            if ra < rb:
                self.parent[rb] = ra
            else:
                self.parent[ra] = rb


def plan_parallel_apply(apply_order, ltx, allow_single_native: bool = False
                        ) -> Tuple[Optional[ApplyPlan], dict]:
    """Footprint every tx, build the conflict graph, emit clusters.

    ``ltx`` is the open close LedgerTxn (post-fee state) — used for
    plan-time materialization only; never retained by worker threads.
    Returns ``(plan, stats)``; ``plan`` is None (with no side effects)
    when the set has an imprecise footprint or collapses into a single
    cluster — ``stats["unplanned"]`` then says why.

    ``allow_single_native``: emit a one-cluster plan anyway when that
    cluster is kernel-eligible — the executor applies it INLINE through
    the native kernel (the adversarial-ring case turns from planner
    refusal into a native fast path; a kernel decline still lands on
    the ordinary sequential loop).
    """
    n = len(apply_order)
    ctx = PlanContext(ltx)
    footprints: List[TxFootprint] = []
    for i, frame in enumerate(apply_order):
        fp = footprint_for(i, frame, ctx)
        if not fp.precise:
            return None, {"txs": n, "clusters": 0,
                          "unplanned": fp.reason, "tx_index": i}
        footprints.append(fp)

    uf = _UnionFind(n)
    # token -> representative of the merged group holding its write;
    # readers seen before any writer wait in readers_pending
    writer_of: Dict[object, int] = {}
    readers_pending: Dict[object, List[int]] = {}
    conflict_edges = 0

    def declare_write(i: int, token) -> None:
        nonlocal conflict_edges
        w = writer_of.get(token)
        if w is not None and uf.find(w) != uf.find(i):
            uf.union(i, w)
            conflict_edges += 1
        for r in readers_pending.pop(token, ()):
            if uf.find(r) != uf.find(i):
                uf.union(i, r)
                conflict_edges += 1
        writer_of[token] = uf.find(i)

    def declare_read(i: int, token) -> None:
        nonlocal conflict_edges
        w = writer_of.get(token)
        if w is not None:
            if uf.find(w) != uf.find(i):
                uf.union(i, w)
                conflict_edges += 1
        else:
            readers_pending.setdefault(token, []).append(i)

    pair_rep: Dict[Tuple[bytes, bytes], int] = {}
    for i, fp in enumerate(footprints):
        for kb in sorted(fp.writes):
            declare_write(i, kb)
        for pair in sorted(fp.book_pairs):
            declare_write(i, ("book", pair))
            pair_rep.setdefault(pair, i)
        if fp.allocates_offer_ids:
            declare_write(i, IDPOOL_TOKEN)
        for kb in sorted(fp.reads - fp.writes):
            declare_read(i, kb)
    # each materialized book joins the conflict graph ONCE, through the
    # first tx touching its pair: every resting offer / seller /
    # trustline / sponsor key the book reaches merges any tx that
    # declared it into the pair's group (a payment crediting a resting
    # seller must not run concurrently with crossings consuming that
    # seller's offer)
    for pair in sorted(pair_rep):
        rep = pair_rep[pair]
        mat = ctx.books[pair]
        for kb in sorted(mat.keys):
            declare_write(rep, kb)
        for kb in sorted(mat.read_keys):
            declare_read(rep, kb)

    by_root: Dict[int, Cluster] = {}
    clusters: List[Cluster] = []
    for i in range(n):
        root = uf.find(i)
        cluster = by_root.get(root)
        if cluster is None:
            cluster = Cluster(len(clusters))
            by_root[root] = cluster
            clusters.append(cluster)
        cluster.indices.append(i)
        fp = footprints[i]
        cluster.keys |= fp.all_keys()
        cluster.writes |= fp.writes
        cluster.pairs |= fp.book_pairs
        cluster.writes_header |= fp.allocates_offer_ids
        cluster.kernel_ok &= fp.kernel_shape is not None
        cluster.shapes.append(fp.kernel_shape)
    for cluster in clusters:
        for pair in cluster.pairs:
            mat = ctx.books[pair]
            cluster.keys |= mat.keys
            cluster.keys |= mat.read_keys
            cluster.writes |= mat.keys

    widths = [len(c.indices) for c in clusters]
    stats = {
        "txs": n,
        "clusters": len(clusters),
        "max_width": max(widths) if widths else 0,
        "singletons": sum(1 for w in widths if w == 1),
        "conflict_edges": conflict_edges,
        "conflict_rate": round(1.0 - len(clusters) / n, 4) if n else 0.0,
        "book_pairs": len(ctx.books),
        "kernel_clusters": sum(1 for c in clusters if c.kernel_ok),
    }
    if len(clusters) < 2:
        if allow_single_native and clusters and clusters[0].kernel_ok:
            stats["single_native"] = True
            return ApplyPlan(clusters, footprints, ctx, stats), stats
        stats["unplanned"] = "single cluster"
        return None, stats
    return ApplyPlan(clusters, footprints, ctx, stats), stats
