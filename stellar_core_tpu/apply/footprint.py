"""Declared per-transaction footprints for parallel apply.

A footprint is the set of canonical LedgerKey byte strings a
transaction may READ or WRITE during apply, plus three structured
conflict tokens the key space cannot express:

- order-book pairs (``book_pairs``): DEX ops touch arbitrary resting
  offers of an asset pair; the pair itself is the conflict unit and the
  planner materializes every resting offer (and its seller's entries)
  into concrete keys at plan time;
- offer-id allocation (``allocates_offer_ids``): creating a resting
  offer consumes ``header.idPool`` — a global counter whose values are
  consensus-visible, so all allocating txs serialize into one cluster.

Ops whose access pattern cannot be declared (trustline-flag revocation
pulling offers and redeeming pool shares by prefix scan) mark the
footprint ``precise = False``; the planner then refuses to parallelize
the whole set — the always-correct sequential path applies it.

Everything here runs on the MAIN thread at plan time, against the open
close ``LedgerTxn`` (post-fee state), so SQL access and root caches
need no locking.
"""
from __future__ import annotations

from fractions import Fraction
from typing import Dict, List, Optional, Set, Tuple

from ..ledger.ledger_txn import (
    LedgerTxnRoot, account_key_bytes, key_bytes, trustline_key,
)
from ..xdr import types as T

OT = T.OperationType

#: op types served by a dedicated handler below; anything else is
#: imprecise by default (NotSupported placeholders write nothing, but
#: new op types must OPT IN to parallel apply by declaring a handler)
_IMPRECISE = "imprecise"


class TxFootprint:
    """Declared footprint of one transaction frame."""

    __slots__ = ("index", "reads", "writes", "book_pairs",
                 "allocates_offer_ids", "precise", "reason",
                 "kernel_shape")

    def __init__(self, index: int):
        self.index = index
        self.reads: Set[bytes] = set()
        self.writes: Set[bytes] = set()
        # unordered pairs of canonical XDR Asset encodings
        self.book_pairs: Set[Tuple[bytes, bytes]] = set()
        self.allocates_offer_ids = False
        self.precise = True
        self.reason = ""
        # native-apply eligibility: the structural (state-free) kernel
        # shape of the frame, or None — a pure function of the tx, so
        # nomination-time preplans carry it too (apply/native_apply.py)
        self.kernel_shape: Optional[tuple] = None

    def all_keys(self) -> Set[bytes]:
        return self.reads | self.writes

    def mark_imprecise(self, reason: str) -> None:
        self.precise = False
        self.reason = reason


def pair_token(asset_a: bytes, asset_b: bytes) -> Tuple[bytes, bytes]:
    """Canonical unordered book-pair token over encoded assets."""
    return (asset_a, asset_b) if asset_a <= asset_b else (asset_b, asset_a)


class BookMaterialization:
    """Plan-time expansion of one order-book pair: every resting offer
    in both directions, ready to serve a cluster's ``best_offer`` scans
    without touching SQL from worker threads."""

    __slots__ = ("pair", "offers", "keys", "read_keys", "assets")

    def __init__(self, pair: Tuple[bytes, bytes]):
        self.pair = pair
        # direction (selling, buying) -> sorted [(Fraction, offerID, kb)]
        self.offers: Dict[Tuple[bytes, bytes], List[tuple]] = {}
        self.keys: Set[bytes] = set()       # write keys (offers, sellers…)
        self.read_keys: Set[bytes] = set()  # issuer accounts
        self.assets: List[object] = []      # the two decoded Asset values


class PlanContext:
    """Shared memoization across one close's footprint pass."""

    def __init__(self, ltx):
        self.ltx = ltx
        self.books: Dict[Tuple[bytes, bytes], BookMaterialization] = {}

    # -- order-book expansion ---------------------------------------------

    def book(self, selling, buying) -> BookMaterialization:
        """Materialize (once) the order book for the unordered pair of
        Asset values ``selling``/``buying``."""
        from ..transactions import liquidity_pool as LP
        from ..transactions import utils as U

        sb = T.Asset.encode(selling)
        bb = T.Asset.encode(buying)
        pair = pair_token(sb, bb)
        mat = self.books.get(pair)
        if mat is not None:
            return mat
        mat = BookMaterialization(pair)
        mat.assets = [selling, buying]
        overrides, root = self.ltx._collect_offer_overrides()
        for direction in ((sb, bb), (bb, sb)):
            rows: List[tuple] = []
            if isinstance(root, LedgerTxnRoot):
                for kb, entry in root._offers_by_pair(*direction):
                    if kb in overrides:
                        continue
                    o = entry.data.value
                    rows.append((Fraction(o.price.n, o.price.d),
                                 o.offerID, kb))
                    self._declare_offer(mat, entry)
            for kb, entry in sorted(overrides.items()):
                if entry is None:
                    continue
                o = entry.data.value
                if (T.Asset.encode(o.selling),
                        T.Asset.encode(o.buying)) != direction:
                    continue
                rows.append((Fraction(o.price.n, o.price.d), o.offerID, kb))
                self._declare_offer(mat, entry)
            rows.sort()
            mat.offers[direction] = rows
        # the pair's liquidity pool (path payments quote it on each hop;
        # the native kernel probes the same key for its decline-if-live
        # pool guard — pair_pool_key_bytes is the one derivation)
        mat.keys.add(LP.pair_pool_key_bytes(selling, buying))
        # issuer accounts: crossing checks their existence
        for asset in (selling, buying):
            issuer = None if U.is_native(asset) else U.asset_issuer(asset)
            if issuer is not None:
                mat.read_keys.add(account_key_bytes(issuer))
        self.books[pair] = mat
        return mat

    def _declare_offer(self, mat: BookMaterialization, entry) -> None:
        """One resting offer's full reach: the offer itself, its
        seller's account and trustlines for both legs (crossing settles
        balances on the seller side), and the offer's sponsor (erasing
        a consumed offer credits the sponsor's numSponsoring)."""
        from ..ledger.ledger_txn import entry_to_key
        from ..transactions import sponsorship as SP
        from ..transactions import utils as U

        o = entry.data.value
        seller = o.sellerID.value
        mat.keys.add(key_bytes(entry_to_key(entry)))
        mat.keys.add(account_key_bytes(seller))
        for asset in (o.selling, o.buying):
            if not U.is_native(asset):
                mat.keys.add(key_bytes(trustline_key(
                    seller, U.to_trustline_asset(asset))))
        sponsor = SP.entry_sponsor(entry)
        if sponsor is not None:
            mat.keys.add(account_key_bytes(sponsor))


def _tl_kb(account_id: bytes, asset) -> Optional[bytes]:
    from ..transactions import utils as U

    if U.is_native(asset):
        return None
    return key_bytes(trustline_key(account_id, U.to_trustline_asset(asset)))


def _issuer_kb(asset) -> Optional[bytes]:
    from ..transactions import utils as U

    issuer = None if U.is_native(asset) else U.asset_issuer(asset)
    return None if issuer is None else account_key_bytes(issuer)


def _cb_kb(balance_id: bytes) -> bytes:
    LE = T.LedgerEntryType
    return key_bytes(T.LedgerKey.make(
        LE.CLAIMABLE_BALANCE,
        T.LedgerKey.arms[LE.CLAIMABLE_BALANCE][1].make(
            balanceID=balance_id)))


def _offer_kb(seller_id: bytes, offer_id: int) -> bytes:
    LE = T.LedgerEntryType
    return key_bytes(T.LedgerKey.make(
        LE.OFFER, T.LedgerKey.arms[LE.OFFER][1].make(
            sellerID=T.account_id(seller_id), offerID=offer_id)))


def _data_kb(account_id: bytes, name) -> bytes:
    LE = T.LedgerEntryType
    return key_bytes(T.LedgerKey.make(
        LE.DATA, T.LedgerKey.arms[LE.DATA][1].make(
            accountID=T.account_id(account_id), dataName=name)))


# -- per-op handlers ----------------------------------------------------------
# Each handler(fp, opf, ctx) adds the op's declared keys to the
# footprint.  The table is module-level on purpose: tests monkeypatch
# entries to force under-declared footprints (the escape-abort path).

def _fp_create_account(fp, opf, ctx):
    fp.writes.add(account_key_bytes(opf.body.destination.value))


def _fp_payment(fp, opf, ctx):
    from ..transactions import utils as U

    b = opf.body
    dest = U.muxed_to_account_id(b.destination)
    src = opf.source_account_id()
    fp.writes.add(account_key_bytes(dest))
    for aid in (src, dest):
        kb = _tl_kb(aid, b.asset)
        if kb is not None:
            fp.writes.add(kb)


def _fp_account_merge(fp, opf, ctx):
    from ..transactions import utils as U

    fp.writes.add(account_key_bytes(U.muxed_to_account_id(opf.body)))


def _fp_change_trust(fp, opf, ctx):
    from ..transactions import liquidity_pool as LP

    line = opf.body.line
    src = opf.source_account_id()
    if line.type == T.AssetType.ASSET_TYPE_POOL_SHARE:
        params = line.value
        pool_id = LP.pool_id_from_params(params)
        fp.writes.add(key_bytes(LP.pool_share_trustline_key(src, pool_id)))
        fp.writes.add(key_bytes(LP.pool_key(pool_id)))
        cp = params.value
        for a in (cp.assetA, cp.assetB):
            kb = _tl_kb(src, a)
            if kb is not None:
                fp.writes.add(kb)
            ik = _issuer_kb(a)
            if ik is not None:
                fp.reads.add(ik)
        return
    asset = T.Asset.make(line.type, line.value)
    kb = _tl_kb(src, asset)
    if kb is not None:
        fp.writes.add(kb)
    ik = _issuer_kb(asset)
    if ik is not None:
        fp.reads.add(ik)


def _fp_manage_offer(fp, opf, ctx):
    src = opf.source_account_id()
    selling, buying, amount, _price, offer_id = opf._params()
    for asset in (selling, buying):
        kb = _tl_kb(src, asset)
        if kb is not None:
            fp.writes.add(kb)
        ik = _issuer_kb(asset)
        if ik is not None:
            fp.reads.add(ik)
    if offer_id:
        fp.writes.add(_offer_kb(src, offer_id))
        # modify/delete releases the LOADED offer's liabilities, whose
        # assets may differ from the op's declared pair: without the
        # resting offer's own trustline reach the release is an
        # undeclared write (worker escape / kernel decline) every time
        existing = ctx.ltx.get(_offer_kb(src, offer_id))
        if existing is not None:
            o = existing.data.value
            for asset in (o.selling, o.buying):
                kb = _tl_kb(src, asset)
                if kb is not None:
                    fp.writes.add(kb)
    if amount != 0:
        # the pair's materialized reach (resting offers, sellers,
        # trustlines, pool, sponsors) is attached ONCE per pair by the
        # planner — not unioned into every DEX tx's own key set, which
        # would make sponsor expansion O(txs x book)
        mat = ctx.book(selling, buying)
        fp.book_pairs.add(mat.pair)
        if offer_id == 0:
            fp.allocates_offer_ids = True


def _fp_path_payment(fp, opf, ctx):
    from ..transactions import utils as U

    b = opf.body
    src = opf.source_account_id()
    dest = U.muxed_to_account_id(b.destination)
    fp.writes.add(account_key_bytes(dest))
    chain = [b.sendAsset, *b.path, b.destAsset]
    for kb in (_tl_kb(src, b.sendAsset), _tl_kb(dest, b.destAsset)):
        if kb is not None:
            fp.writes.add(kb)
    for asset in chain:
        ik = _issuer_kb(asset)
        if ik is not None:
            fp.reads.add(ik)
    for i in range(len(chain) - 1):
        if U.assets_equal(chain[i], chain[i + 1]):
            continue
        mat = ctx.book(chain[i], chain[i + 1])
        fp.book_pairs.add(mat.pair)


def _fp_source_only(fp, opf, ctx):
    pass  # tx/op source accounts are declared for every tx


def _fp_manage_data(fp, opf, ctx):
    fp.writes.add(_data_kb(opf.source_account_id(), opf.body.dataName))


def _fp_clawback(fp, opf, ctx):
    from ..transactions import utils as U

    b = opf.body
    kb = _tl_kb(U.muxed_to_account_id(b.from_), b.asset)
    if kb is not None:
        fp.writes.add(kb)
    fp.writes.add(account_key_bytes(U.muxed_to_account_id(b.from_)))


def _fp_create_cb(fp, opf, ctx):
    b = opf.body
    fp.writes.add(_cb_kb(opf.balance_id()))
    src = opf.source_account_id()
    kb = _tl_kb(src, b.asset)
    if kb is not None:
        fp.writes.add(kb)
    ik = _issuer_kb(b.asset)
    if ik is not None:
        fp.reads.add(ik)
    for cl in b.claimants:
        fp.reads.add(account_key_bytes(cl.value.destination.value))


def _fp_claim_cb(fp, opf, ctx):
    fp.writes.add(_cb_kb(opf.body.balanceID))
    src = opf.source_account_id()
    entry = ctx.ltx.get(_cb_kb(opf.body.balanceID))
    if entry is not None:
        asset = entry.data.value.asset
        kb = _tl_kb(src, asset)
        if kb is not None:
            fp.writes.add(kb)
        ik = _issuer_kb(asset)
        if ik is not None:
            fp.reads.add(ik)


def _fp_clawback_cb(fp, opf, ctx):
    fp.writes.add(_cb_kb(opf.body.balanceID))


def _fp_begin_sponsoring(fp, opf, ctx):
    fp.reads.add(account_key_bytes(opf.body.sponsoredID.value))


def _fp_revoke_sponsorship(fp, opf, ctx):
    b = opf.body
    RS = T.RevokeSponsorshipType
    if b.type == RS.REVOKE_SPONSORSHIP_LEDGER_ENTRY:
        lk = b.value
        fp.writes.add(key_bytes(lk))
        # the owner account's counts move with the sponsorship
        owner = getattr(lk.value, "accountID", None) or \
            getattr(lk.value, "sellerID", None)
        if owner is not None:
            fp.writes.add(account_key_bytes(owner.value))
    else:
        fp.writes.add(account_key_bytes(b.value.accountID.value))


def _fp_pool_op(fp, opf, ctx):
    from ..transactions import liquidity_pool as LP

    b = opf.body
    pool_id = b.liquidityPoolID
    src = opf.source_account_id()
    fp.writes.add(key_bytes(LP.pool_key(pool_id)))
    fp.writes.add(key_bytes(LP.pool_share_trustline_key(src, pool_id)))
    pool = ctx.ltx.get(key_bytes(LP.pool_key(pool_id)))
    if pool is not None:
        cp = pool.data.value.body.value
        for a in (cp.params.assetA, cp.params.assetB):
            kb = _tl_kb(src, a)
            if kb is not None:
                fp.writes.add(kb)
            ik = _issuer_kb(a)
            if ik is not None:
                fp.reads.add(ik)


def _fp_imprecise(reason: str):
    def handler(fp, opf, ctx):
        fp.mark_imprecise(reason)
    return handler


#: OperationType -> handler.  Module-level and mutable BY DESIGN: the
#: adversarial escape tests patch entries to under-declare footprints.
OP_FOOTPRINTS = {
    OT.CREATE_ACCOUNT: _fp_create_account,
    OT.PAYMENT: _fp_payment,
    OT.ACCOUNT_MERGE: _fp_account_merge,
    OT.CHANGE_TRUST: _fp_change_trust,
    OT.MANAGE_SELL_OFFER: _fp_manage_offer,
    OT.MANAGE_BUY_OFFER: _fp_manage_offer,
    OT.CREATE_PASSIVE_SELL_OFFER: _fp_manage_offer,
    OT.PATH_PAYMENT_STRICT_RECEIVE: _fp_path_payment,
    OT.PATH_PAYMENT_STRICT_SEND: _fp_path_payment,
    OT.SET_OPTIONS: _fp_source_only,
    OT.BUMP_SEQUENCE: _fp_source_only,
    OT.INFLATION: _fp_source_only,
    OT.MANAGE_DATA: _fp_manage_data,
    OT.CLAWBACK: _fp_clawback,
    OT.CREATE_CLAIMABLE_BALANCE: _fp_create_cb,
    OT.CLAIM_CLAIMABLE_BALANCE: _fp_claim_cb,
    OT.CLAWBACK_CLAIMABLE_BALANCE: _fp_clawback_cb,
    OT.BEGIN_SPONSORING_FUTURE_RESERVES: _fp_begin_sponsoring,
    OT.END_SPONSORING_FUTURE_RESERVES: _fp_source_only,
    OT.REVOKE_SPONSORSHIP: _fp_revoke_sponsorship,
    OT.LIQUIDITY_POOL_DEPOSIT: _fp_pool_op,
    OT.LIQUIDITY_POOL_WITHDRAW: _fp_pool_op,
    # trustline-flag revocation pulls the trustor's whole offer list and
    # prefix-scans pool-share trustlines — undeclarable; sequential only
    OT.ALLOW_TRUST: _fp_imprecise("allow_trust offer pull"),
    OT.SET_TRUST_LINE_FLAGS: _fp_imprecise("set_trust_line_flags pull"),
}


def footprint_for(index: int, frame, ctx: PlanContext) -> TxFootprint:
    """Full declared footprint of one frame (fee-bump aware)."""
    from .native_apply import frame_kernel_shape

    fp = TxFootprint(index)
    fp.kernel_shape = frame_kernel_shape(frame)
    fp.writes.add(account_key_bytes(frame.source_account_id()))
    fee_src = getattr(frame, "fee_source_id", None)
    if fee_src is not None:
        fp.writes.add(account_key_bytes(fee_src()))
    for opf in frame.op_frames:
        fp.writes.add(account_key_bytes(opf.source_account_id()))
        handler = OP_FOOTPRINTS.get(opf.op.body.type)
        if handler is None:
            fp.mark_imprecise(f"no handler for op type {opf.op.body.type}")
            return fp
        try:
            handler(fp, opf, ctx)
        except Exception as e:  # malformed body: let sequential apply fail it
            fp.mark_imprecise(f"footprint error: {e!r}")
            return fp
        if not fp.precise:
            return fp
    _expand_sponsors(fp, ctx)
    return fp


def _expand_sponsors(fp: TxFootprint, ctx: PlanContext) -> None:
    """Removing or resizing a sponsored entry credits its sponsor's
    ``numSponsoring`` — an undeclared account write unless expanded
    here.  One round suffices: sponsors are accounts, and touching a
    sponsor's counters never cascades further."""
    from ..transactions import sponsorship as SP

    extra: Set[bytes] = set()
    for kb in sorted(fp.all_keys()):
        entry = ctx.ltx.get(kb)
        if entry is None or kb.startswith(b"\xff"):
            continue
        sponsor = SP.entry_sponsor(entry)
        if sponsor is not None:
            extra.add(account_key_bytes(sponsor))
        if entry.data.type == T.LedgerEntryType.ACCOUNT:
            for sid in SP.signer_sponsoring_ids(entry.data.value):
                if sid is not None:
                    extra.add(account_key_bytes(sid.value))
    fp.writes |= extra
