"""Async work FSM framework (ref src/work — SURVEY.md §2.9)."""
from .work import (  # noqa: F401
    BasicWork, BatchWork, ConditionalWork, State, Work, WorkScheduler,
    WorkSequence, WorkWithCallback,
)
