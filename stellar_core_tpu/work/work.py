"""Async work system: finite-state machines for long multi-step tasks
(ref src/work — BasicWork state diagram at src/work/BasicWork.h:15-60).

States: WAITING / RUNNING / SUCCESS / FAILURE / ABORTED, with retry edges.
``Work`` composes children; ``WorkScheduler`` is the app-attached root that
cranks on the main thread; ``BatchWork`` runs a bounded-parallel iterator;
``WorkSequence`` chains works in order.
"""
from __future__ import annotations

from enum import Enum
from typing import Callable, Dict, Iterator, List, Optional


class State(Enum):
    WAITING = 0
    RUNNING = 1
    SUCCESS = 2
    FAILURE = 3
    ABORTED = 4


class BasicWork:
    """Subclass and implement on_run() -> State (RUNNING to be rescheduled,
    WAITING to block on a child/event, SUCCESS/FAILURE when done)."""

    RETRY_NEVER = 0
    RETRY_ONCE = 1
    RETRY_A_FEW = 5
    RETRY_FOREVER = 2**31

    def __init__(self, name: str, max_retries: int = RETRY_A_FEW):
        self.name = name
        self.max_retries = max_retries
        self.state = State.WAITING
        self.retries = 0
        self._aborting = False

    # -- subclass surface ---------------------------------------------------

    def on_run(self) -> State:
        raise NotImplementedError

    def on_reset(self) -> None:
        pass

    def on_success(self) -> None:
        pass

    def on_failure_retry(self) -> None:
        pass

    def on_failure_raise(self) -> None:
        pass

    def on_abort(self) -> bool:
        """Return True when abort cleanup is complete."""
        return True

    # -- engine -------------------------------------------------------------

    def start(self) -> None:
        self.state = State.RUNNING
        self.retries = 0
        self.on_reset()

    def crank(self) -> State:
        if self.state not in (State.RUNNING, State.WAITING):
            return self.state
        if self._aborting:
            if self.on_abort():
                self.state = State.ABORTED
            return self.state
        nxt = self.on_run()
        if nxt == State.FAILURE and self.retries < self.max_retries:
            self.retries += 1
            self.on_failure_retry()
            self.on_reset()
            self.state = State.RUNNING
            return self.state
        self.state = nxt
        if nxt == State.SUCCESS:
            self.on_success()
        elif nxt == State.FAILURE:
            self.on_failure_raise()
        return self.state

    def abort(self) -> None:
        if self.state in (State.RUNNING, State.WAITING):
            self._aborting = True

    @property
    def done(self) -> bool:
        return self.state in (State.SUCCESS, State.FAILURE, State.ABORTED)


class Work(BasicWork):
    """A work with children: runs children to completion before itself
    (ref src/work/Work.h).  Subclasses implement do_work() which may add
    children via add_work()."""

    def __init__(self, name: str, max_retries: int = BasicWork.RETRY_A_FEW):
        super().__init__(name, max_retries)
        self.children: List[BasicWork] = []

    def add_work(self, w: BasicWork) -> BasicWork:
        w.start()
        self.children.append(w)
        return w

    def on_reset(self) -> None:
        self.children.clear()
        self.do_reset()

    def do_reset(self) -> None:
        pass

    def do_work(self) -> State:
        raise NotImplementedError

    def on_run(self) -> State:
        # crank one non-done child first (round robin)
        any_failed = False
        all_done = True
        for c in self.children:
            if not c.done:
                c.crank()
            if not c.done:
                all_done = False
            elif c.state in (State.FAILURE, State.ABORTED):
                any_failed = True
        if any_failed:
            return State.FAILURE
        if not all_done:
            return State.RUNNING
        return self.do_work()


class WorkSequence(BasicWork):
    """Execute a list of works strictly in order (ref WorkSequence)."""

    def __init__(self, name: str, steps: List[BasicWork]):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self.steps = steps
        self._idx = 0

    def on_reset(self) -> None:
        self._idx = 0
        for s in self.steps:
            s.start()

    def on_run(self) -> State:
        while self._idx < len(self.steps):
            cur = self.steps[self._idx]
            if not cur.done:
                cur.crank()
            if not cur.done:
                return State.RUNNING
            if cur.state != State.SUCCESS:
                return State.FAILURE
            self._idx += 1
        return State.SUCCESS


class BatchWork(Work):
    """Bounded-parallelism iterator (ref src/work/BatchWork.h:19): yields
    works from ``iterator`` keeping at most ``batch_size`` in flight."""

    def __init__(self, name: str, iterator: Iterator[BasicWork],
                 batch_size: int = 8):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self._iter = iterator
        self.batch_size = batch_size
        self._exhausted = False

    def do_reset(self) -> None:
        self._exhausted = False

    def do_work(self) -> State:
        # drop finished children, top up to batch_size
        self.children = [c for c in self.children if not c.done]
        while not self._exhausted and len(self.children) < self.batch_size:
            try:
                nxt = next(self._iter)
            except StopIteration:
                self._exhausted = True
                break
            self.add_work(nxt)
        if self.children:
            return State.RUNNING
        return State.SUCCESS

    def on_run(self) -> State:
        for c in self.children:
            if not c.done:
                c.crank()
        for c in self.children:
            if c.done and c.state in (State.FAILURE, State.ABORTED):
                return State.FAILURE
        return self.do_work()


class WorkWithCallback(BasicWork):
    def __init__(self, name: str, fn: Callable[[], bool]):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self.fn = fn

    def on_run(self) -> State:
        return State.SUCCESS if self.fn() else State.FAILURE


class ConditionalWork(BasicWork):
    """Waits for a condition, then runs the wrapped work."""

    def __init__(self, name: str, condition: Callable[[], bool],
                 work: BasicWork):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self.condition = condition
        self.work = work
        self._started = False

    def on_run(self) -> State:
        if not self._started:
            if not self.condition():
                return State.RUNNING
            self.work.start()
            self._started = True
        self.work.crank()
        if not self.work.done:
            return State.RUNNING
        return self.work.state


class WorkScheduler(Work):
    """App-attached root work cranked from the main loop
    (ref src/work/WorkScheduler.h:20-48)."""

    def __init__(self, clock):
        super().__init__("work-scheduler",
                         max_retries=BasicWork.RETRY_NEVER)
        self.clock = clock
        self.state = State.RUNNING

    def do_work(self) -> State:
        return State.RUNNING  # the root never finishes

    def schedule(self, w: BasicWork) -> BasicWork:
        return self.add_work(w)

    def crank_all(self, max_cranks: int = 100_000) -> bool:
        """Crank until all scheduled works are done (test helper); bounded
        so stuck works can't hang the caller."""

        def all_done():
            return all(c.done for c in self.children)

        for _ in range(max_cranks):
            if all_done():
                break
            self.crank()
            self.clock.crank(block=False)
            if all(c.state == State.WAITING for c in self.children
                   if not c.done):
                break  # blocked on external events with none pending
        return all_done()
