"""Async work system: finite-state machines for long multi-step tasks
(ref src/work — BasicWork state diagram at src/work/BasicWork.h:15-60).

States: WAITING / RUNNING / SUCCESS / FAILURE / ABORTED, with retry edges.
``Work`` composes children; ``WorkScheduler`` is the app-attached root that
cranks on the main thread; ``BatchWork`` runs a bounded-parallel iterator;
``WorkSequence`` chains works in order.

Since r17 the system is a REAL parallel DAG (ref the reference running
works on ApplicationImpl's worker threads):

- ``WorkerPool`` is a shared thread pool the scheduler owns;
- ``ThreadedWork`` runs its blocking part (``on_io``) on that pool while
  the FSM keeps cranking on the main thread — a ``BatchWork`` over
  ``ThreadedWork`` children therefore keeps ``batch_size`` transfers
  genuinely in flight at once (catchup's archive fetch/verify fan-out);
- failed works retry with exponential clock-based backoff
  (``retry_backoff`` + a ``clock``) instead of hot-spinning the archive;
- ``abort()`` propagates down the DAG: parents drive children to
  ABORTED (cancelling queued pool dispatches) before finishing, and a
  failed ``BatchWork`` aborts its in-flight siblings instead of
  orphaning their futures.
"""
from __future__ import annotations

from enum import Enum
from typing import Callable, Iterator, List, Optional


class State(Enum):
    WAITING = 0
    RUNNING = 1
    SUCCESS = 2
    FAILURE = 3
    ABORTED = 4


class WorkerPool:
    """The scheduler-owned thread pool ThreadedWorks dispatch their
    blocking part to (ref ApplicationImpl's worker io_contexts).  Threads
    spawn lazily, so idle apps (50-validator sims) pay nothing."""

    def __init__(self, max_workers: int = 4):
        from concurrent.futures import ThreadPoolExecutor

        self.max_workers = max(1, int(max_workers))
        self._ex = ThreadPoolExecutor(
            max_workers=self.max_workers, thread_name_prefix="work-pool")

    def submit(self, fn, *args):
        return self._ex.submit(fn, *args)

    def shutdown(self, wait: bool = True) -> None:
        self._ex.shutdown(wait=wait)


class BasicWork:
    """Subclass and implement on_run() -> State (RUNNING to be rescheduled,
    WAITING to block on a child/event, SUCCESS/FAILURE when done).

    ``retry_backoff`` > 0 with a ``clock`` makes failure retries wait
    ``retry_backoff * 2**(retries-1)`` (capped at MAX_RETRY_BACKOFF)
    clock-seconds before re-running — deterministic under VirtualClock,
    wall-clock on live nodes (ref BasicWork::getRetryETA)."""

    RETRY_NEVER = 0
    RETRY_ONCE = 1
    RETRY_A_FEW = 5
    RETRY_FOREVER = 2**31
    MAX_RETRY_BACKOFF = 30.0

    def __init__(self, name: str, max_retries: int = RETRY_A_FEW,
                 clock=None, retry_backoff: float = 0.0):
        self.name = name
        self.max_retries = max_retries
        self.state = State.WAITING
        self.retries = 0
        self.clock = clock
        self.retry_backoff = retry_backoff
        self._retry_at: Optional[float] = None
        self._aborting = False

    # -- subclass surface ---------------------------------------------------

    def on_run(self) -> State:
        raise NotImplementedError

    def on_reset(self) -> None:
        pass

    def on_success(self) -> None:
        pass

    def on_failure_retry(self) -> None:
        pass

    def on_failure_raise(self) -> None:
        pass

    def on_abort(self) -> bool:
        """Return True when abort cleanup is complete."""
        return True

    # -- engine -------------------------------------------------------------

    def start(self) -> None:
        self.state = State.RUNNING
        self.retries = 0
        self._retry_at = None
        self._aborting = False
        self.on_reset()

    def crank(self) -> State:
        if self.state not in (State.RUNNING, State.WAITING):
            return self.state
        if self._aborting:
            if self.on_abort():
                self.state = State.ABORTED
            return self.state
        if self._retry_at is not None:
            if self.clock is not None and \
                    self.clock.now() < self._retry_at:
                return self.state  # backing off before the retry
            self._retry_at = None
        nxt = self.on_run()
        if nxt == State.FAILURE and self.retries < self.max_retries:
            self.retries += 1
            self.on_failure_retry()
            self.on_reset()
            if self.retry_backoff > 0.0 and self.clock is not None:
                self._retry_at = self.clock.now() + min(
                    self.retry_backoff * (2 ** (self.retries - 1)),
                    self.MAX_RETRY_BACKOFF)
            self.state = State.RUNNING
            return self.state
        self.state = nxt
        if nxt == State.SUCCESS:
            self.on_success()
        elif nxt == State.FAILURE:
            self.on_failure_raise()
        return self.state

    def abort(self) -> None:
        if self.state in (State.RUNNING, State.WAITING):
            self._aborting = True

    @property
    def done(self) -> bool:
        return self.state in (State.SUCCESS, State.FAILURE, State.ABORTED)


class ThreadedWork(BasicWork):
    """A work whose blocking part runs on the scheduler's WorkerPool:
    ``on_io()`` executes on a pool thread (file/network I/O, hashing),
    ``on_complete(result)`` back on the cranking thread.  With no pool
    (or a non-thread-safe transport) the work degrades to inline
    execution — same FSM, zero concurrency.

    on_io must not touch main-thread state: everything it reads should be
    captured in __init__, everything it produces returned (the FSM hands
    it to on_complete on the cranking side)."""

    POLL_GRACE = 0.001  # seconds a crank waits on an in-flight future

    def __init__(self, name: str, pool: Optional[WorkerPool] = None,
                 max_retries: int = BasicWork.RETRY_A_FEW,
                 clock=None, retry_backoff: float = 0.0):
        super().__init__(name, max_retries, clock=clock,
                         retry_backoff=retry_backoff)
        self.pool = pool
        self._future = None

    def on_io(self):
        """Worker thread.  Raise to fail the attempt."""
        raise NotImplementedError

    def on_complete(self, result) -> State:
        """Cranking thread, with on_io's return value."""
        return State.SUCCESS

    def on_io_error(self, exc: BaseException) -> None:
        """Cranking thread, before the FAILURE/retry edge."""
        pass

    def on_reset(self) -> None:
        self._future = None

    def on_run(self) -> State:
        if self.pool is None:
            try:
                result = self.on_io()
            except Exception as e:
                self.on_io_error(e)
                return State.FAILURE
            return self.on_complete(result)
        if self._future is None:
            self._future = self.pool.submit(self.on_io)
            return State.RUNNING
        from concurrent.futures import TimeoutError as _FutTimeout

        fut = self._future
        try:
            # a short grace wait instead of a pure poll: tight crank
            # loops make real progress, while sibling futures keep
            # running on the other pool threads in the meantime
            result = fut.result(timeout=self.POLL_GRACE)
        except (_FutTimeout, TimeoutError):
            return State.RUNNING
        except Exception as e:
            self._future = None
            self.on_io_error(e)
            return State.FAILURE
        self._future = None
        # a future that completed leaves the work one decision to make;
        # re-dispatch (multi-round works) happens via RUNNING + next crank
        return self.on_complete(result)

    def on_abort(self) -> bool:
        fut = self._future
        if fut is None:
            return True
        if fut.cancel():
            self._future = None
            return True
        # already running on the pool thread: wait for it to finish
        # (Python threads can't be interrupted); discard the result
        if fut.done():
            self._future = None
            return True
        return False


class Work(BasicWork):
    """A work with children: runs children to completion before itself
    (ref src/work/Work.h).  Subclasses implement do_work() which may add
    children via add_work().  abort() propagates to every child."""

    def __init__(self, name: str, max_retries: int = BasicWork.RETRY_A_FEW,
                 clock=None, retry_backoff: float = 0.0):
        super().__init__(name, max_retries, clock=clock,
                         retry_backoff=retry_backoff)
        self.children: List[BasicWork] = []

    def add_work(self, w: BasicWork) -> BasicWork:
        w.start()
        self.children.append(w)
        return w

    def on_reset(self) -> None:
        self.children.clear()
        self.do_reset()

    def do_reset(self) -> None:
        pass

    def do_work(self) -> State:
        raise NotImplementedError

    def on_run(self) -> State:
        any_failed = False
        all_done = True
        for c in self.children:
            if not c.done:
                c.crank()
            if not c.done:
                all_done = False
            elif c.state in (State.FAILURE, State.ABORTED):
                any_failed = True
        if any_failed:
            # drive the surviving children down before surfacing the
            # failure — in-flight pool futures must not be orphaned
            if not self._abort_children():
                return State.RUNNING
            return State.FAILURE
        if not all_done:
            return State.RUNNING
        return self.do_work()

    def _abort_children(self) -> bool:
        """Abort + crank every non-done child; True when all are done."""
        alive = False
        for c in self.children:
            if not c.done:
                c.abort()
                c.crank()
            if not c.done:
                alive = True
        return not alive

    def on_abort(self) -> bool:
        return self._abort_children()


class WorkSequence(BasicWork):
    """Execute a list of works strictly in order (ref WorkSequence)."""

    def __init__(self, name: str, steps: List[BasicWork]):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self.steps = steps
        self._idx = 0

    def on_reset(self) -> None:
        self._idx = 0
        for s in self.steps:
            s.start()

    def on_run(self) -> State:
        while self._idx < len(self.steps):
            cur = self.steps[self._idx]
            if not cur.done:
                cur.crank()
            if not cur.done:
                return State.RUNNING
            if cur.state != State.SUCCESS:
                return State.FAILURE
            self._idx += 1
        return State.SUCCESS

    def on_abort(self) -> bool:
        if self._idx >= len(self.steps):
            return True
        cur = self.steps[self._idx]
        if not cur.done:
            cur.abort()
            cur.crank()
        return cur.done


class BatchWork(Work):
    """Bounded-parallelism iterator (ref src/work/BatchWork.h:19): yields
    works from ``iterator`` keeping at most ``batch_size`` in flight.
    With ThreadedWork children the batch is the archive-transfer fan-out:
    batch_size concurrent downloads, each with its own retry/backoff."""

    def __init__(self, name: str, iterator: Iterator[BasicWork],
                 batch_size: int = 8):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self._iter = iterator
        self.batch_size = batch_size
        self._exhausted = False

    def do_reset(self) -> None:
        self._exhausted = False

    def do_work(self) -> State:
        # drop finished children, top up to batch_size
        self.children = [c for c in self.children if not c.done]
        while not self._exhausted and len(self.children) < self.batch_size:
            try:
                nxt = next(self._iter)
            except StopIteration:
                self._exhausted = True
                break
            self.add_work(nxt)
        if self.children:
            return State.RUNNING
        return State.SUCCESS

    def on_run(self) -> State:
        for c in self.children:
            if not c.done:
                c.crank()
        for c in self.children:
            if c.done and c.state in (State.FAILURE, State.ABORTED):
                # one child exhausted its retries: stop spawning and
                # abort the in-flight siblings before failing the batch
                self._exhausted = True
                if not self._abort_children():
                    return State.RUNNING
                return State.FAILURE
        return self.do_work()

    def on_abort(self) -> bool:
        self._exhausted = True
        return self._abort_children()


class WorkWithCallback(BasicWork):
    def __init__(self, name: str, fn: Callable[[], bool]):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self.fn = fn

    def on_run(self) -> State:
        return State.SUCCESS if self.fn() else State.FAILURE


class ConditionalWork(BasicWork):
    """Waits for a condition, then runs the wrapped work."""

    def __init__(self, name: str, condition: Callable[[], bool],
                 work: BasicWork):
        super().__init__(name, max_retries=BasicWork.RETRY_NEVER)
        self.condition = condition
        self.work = work
        self._started = False

    def on_run(self) -> State:
        if not self._started:
            if not self.condition():
                return State.RUNNING
            self.work.start()
            self._started = True
        self.work.crank()
        if not self.work.done:
            return State.RUNNING
        return self.work.state

    def on_abort(self) -> bool:
        if not self._started:
            return True
        if not self.work.done:
            self.work.abort()
            self.work.crank()
        return self.work.done


class WorkScheduler(Work):
    """App-attached root work cranked from the main loop
    (ref src/work/WorkScheduler.h:20-48).  Owns the WorkerPool that
    ThreadedWorks under it dispatch to."""

    def __init__(self, clock, worker_pool: Optional[WorkerPool] = None):
        super().__init__("work-scheduler",
                         max_retries=BasicWork.RETRY_NEVER)
        self.clock = clock
        self.worker_pool = worker_pool
        self.state = State.RUNNING

    def do_work(self) -> State:
        return State.RUNNING  # the root never finishes

    def on_run(self) -> State:
        # unlike Work, the root outlives failed children: a failed
        # catchup attempt must not kill the scheduler (or abort its
        # unrelated siblings) — callers observe per-work state instead
        for c in self.children:
            if not c.done:
                c.crank()
        return State.RUNNING

    def schedule(self, w: BasicWork) -> BasicWork:
        return self.add_work(w)

    def shutdown(self) -> None:
        """Abort scheduled works, then stop the pool (node teardown)."""
        for _ in range(1000):
            alive = [c for c in self.children if not c.done]
            if not alive:
                break
            for c in alive:
                c.abort()
                c.crank()
        if self.worker_pool is not None:
            self.worker_pool.shutdown(wait=True)

    def crank_all(self, max_cranks: int = 100_000) -> bool:
        """Crank until all scheduled works are done (test helper); bounded
        so stuck works can't hang the caller."""

        def all_done():
            return all(c.done for c in self.children)

        for _ in range(max_cranks):
            if all_done():
                break
            self.crank()
            self.clock.crank(block=False)
            if all(c.state == State.WAITING for c in self.children
                   if not c.done):
                break  # blocked on external events with none pending
        return all_done()
