"""In-process multi-node simulation (ref src/simulation — SURVEY.md §4.2)."""
from .simulation import Simulation, core, cycle, pair  # noqa: F401
