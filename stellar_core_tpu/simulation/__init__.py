"""In-process multi-node simulation (ref src/simulation — SURVEY.md §4.2)."""
from .simulation import (  # noqa: F401
    Simulation, core, cycle, hierarchical_quorum, pair,
)
