"""LoadGenerator: the standard synthetic load source for benchmarks and
soak tests (ref src/simulation/LoadGenerator.h:28-36 — modes CREATE / PAY;
the reference drives it via the test-build 'generateload' HTTP endpoint).

CREATE seeds n accounts; PAY builds single-op payment transactions between
them.  Accounts are written straight into the ledger root (bulk-seeding
like the reference's createAccounts batches); payments are real signed
envelopes that flow through whatever admission path the caller uses.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..crypto import SecretKey, sha256
from ..ledger.ledger_txn import LedgerTxn
from ..transactions import utils as U
from ..transactions.signature_checker import signature_hint
from ..xdr import types as T

CREATE = "create"
PAY = "pay"

# rate mode: generation quantum (seconds of virtual/real clock per tick)
RATE_TICK_SECONDS = 1.0


class LoadGenerator:
    def __init__(self, app):
        self.app = app
        self.network_id = app.config.network_id()
        self.accounts: List[SecretKey] = []
        self._seqs = {}
        self._rate_timer = None
        self._rate_state: Optional[dict] = None
        # payment destination graph: "ring" (i pays i+1; one conflict
        # component), "pairs" (2j <-> 2j+1; disjoint account pairs),
        # "credit" (pairs graph, but payments move the LOAD credit
        # asset over trustlines — setup_dex() first), or "pool" (pairs
        # graph, path payments routed through LIVE constant-product
        # pools — setup_pool() first)
        self.payment_pattern = "ring"

    # -- deterministic account derivation -----------------------------------

    @staticmethod
    def account_key(i: int, prefix: bytes = b"loadgen") -> SecretKey:
        """The i-th generator account key, derived purely from the index
        (ref LoadGenerator::findAccount — accounts are a deterministic
        function of their ordinal, so a restarted node can regenerate
        load against accounts created before the restart without
        re-creating them)."""
        return SecretKey(sha256(prefix + b"-%d" % i))

    def restore_accounts(self, prefix: bytes = b"loadgen",
                         limit: int = 100_000) -> int:
        """Rebuild the account pool after a process restart by probing the
        ledger for consecutively-derived accounts until one is absent.
        Returns how many accounts were recovered."""
        from ..ledger.ledger_txn import entry_to_key, key_bytes

        root = self.app.ledger_manager.root
        found = []
        for i in range(limit):
            sk = self.account_key(i, prefix)
            kb = key_bytes(entry_to_key(
                U.make_account_entry(sk.public_key().raw, 0)))
            if root.get(kb) is None:
                break
            found.append(sk)
        self.accounts = found
        self._seqs = {}
        return len(found)

    # -- CREATE mode --------------------------------------------------------

    def create_accounts(self, n: int, balance: int = 10**9,
                        prefix: bytes = b"loadgen") -> List[SecretKey]:
        """Seed n funded accounts directly into the ledger root (bulk;
        the per-tx path would be n CreateAccount ops)."""
        root = self.app.ledger_manager.root
        new = [self.account_key(i, prefix) for i in range(n)]
        with LedgerTxn(root) as ltx:
            for sk in new:
                ltx.put(U.make_account_entry(
                    sk.public_key().raw, balance, seq_num=0))
            ltx.commit()
        self.accounts.extend(new)
        return new

    # -- PAY mode -----------------------------------------------------------

    def _next_seq(self, sk: SecretKey) -> int:
        k = sk.public_key().raw
        if k not in self._seqs:
            root = self.app.ledger_manager.root
            with LedgerTxn(root) as ltx:
                e = ltx.load_account(k)
                ltx.rollback()
            if e is None:
                # account not on-ledger yet (e.g. a seeding stage called
                # before its close): the envelope will be rejected at
                # admission — do NOT cache, or the eventual real seqnum
                # (ledgerSeq<<32) would never be read and every retry
                # would be a sequence gap
                return 1
            self._seqs[k] = e.data.value.seqNum
        self._seqs[k] += 1
        return self._seqs[k]

    def payment_envelope(self, src: SecretKey, dest: bytes, amount: int,
                         fee: int = 100, asset=None):
        op = T.Operation.make(
            sourceAccount=None,
            body=T.OperationBody.make(
                T.OperationType.PAYMENT,
                T.PaymentOp.make(destination=T.muxed_account(dest),
                                 asset=asset if asset is not None
                                 else U.asset_native(),
                                 amount=amount)))
        return self._sign_tx(src, [op], fee)

    def _payment_dest(self, accts: List[SecretKey], i: int,
                      dest_accounts: Optional[List[SecretKey]] = None
                      ) -> bytes:
        """Destination for payment i: ``ring`` (each account pays its
        successor — one fully-connected conflict component, the
        parallel-apply worst case) or ``pairs`` (2j <-> 2j+1 — disjoint
        account pairs, the independent-users shape real traffic
        approximates and conflict clustering can spread).

        ``dest_accounts``: draw destinations from a DIFFERENT pool than
        the sources (payment i -> dest_accounts[i]) — the recipients-
        aren't-senders shape, where admission never pre-warms the
        destination entries and the close's prefetch does real work."""
        if dest_accounts is not None:
            return dest_accounts[i % len(dest_accounts)].public_key().raw
        k = len(accts)
        if self.payment_pattern in ("pairs", "credit"):
            j = i % k
            p = j ^ 1
            if p >= k:
                p = j  # odd pool tail: self-payment, still pair-local
            return accts[p].public_key().raw
        return accts[(i + 1) % k].public_key().raw

    def generate_payments(self, n: int,
                          accounts: Optional[List[SecretKey]] = None,
                          dest_accounts: Optional[List[SecretKey]] = None
                          ) -> List:
        """n one-op payments round-robin across the account pool
        (destination graph per ``payment_pattern``; sequence numbers
        tracked per source)."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        if self.payment_pattern == "pool":
            assert getattr(self, "pool_ids", None), \
                "setup_pool() first for payment_pattern='pool'"
            return self.generate_pool_payments(n, accounts=accounts)
        asset = None
        if self.payment_pattern == "credit":
            assert getattr(self, "dex_asset", None) is not None, \
                "setup_dex() first for payment_pattern='credit'"
            asset = self.dex_asset
        out = []
        k = len(accts)
        for i in range(n):
            src = accts[i % k]
            dest = self._payment_dest(accts, i, dest_accounts)
            out.append(self.payment_envelope(src, dest, 1 + (i % 1000),
                                             asset=asset))
        return out

    # -- PRETEND mode -------------------------------------------------------

    def pretend_envelope(self, src: SecretKey, op_count: int = 1,
                         fee: int = 100):
        """SetOptions no-op-shaped txs sized like real traffic (ref
        LoadGenerator::pretendTransaction :721 — inflationDest=self,
        16-char homeDomain, first op padded with an extra signer)."""
        pub = src.public_key().raw
        ops = []
        for i in range(op_count):
            home = b"*" * (24 if i == 0 else 16)
            signer = None
            if i == 0:
                signer = T.Signer.make(
                    key=T.SignerKey.make(
                        T.SignerKeyType.SIGNER_KEY_TYPE_ED25519,
                        bytes(32)),
                    weight=0)  # weight 0 = delete-if-present no-op
            ops.append(T.Operation.make(
                sourceAccount=None,
                body=T.OperationBody.make(
                    T.OperationType.SET_OPTIONS,
                    T.SetOptionsOp.make(
                        inflationDest=T.account_id(pub),
                        clearFlags=None, setFlags=None,
                        masterWeight=None, lowThreshold=None,
                        medThreshold=None, highThreshold=None,
                        homeDomain=home, signer=signer))))
        return self._sign_tx(src, ops, fee * max(1, op_count))

    def generate_pretend(self, n: int, op_count: int = 1,
                         accounts: Optional[List[SecretKey]] = None
                         ) -> List:
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        return [self.pretend_envelope(accts[i % len(accts)], op_count)
                for i in range(n)]

    # -- MIXED_TXS mode -----------------------------------------------------

    def _derive_dex(self) -> None:
        """One derivation for both seeding paths (bulk setup_dex and the
        staged HTTP envelopes) so they can never diverge."""
        issuer = SecretKey(sha256(b"loadgen-dex-issuer"))
        self.dex_issuer = issuer
        self.dex_asset = U.make_asset(b"LOAD", issuer.public_key().raw)

    def setup_dex(self, accounts: Optional[List[SecretKey]] = None,
                  credit: int = 10**7) -> None:
        """Seed the DEX leg of MIXED_TXS: a LOAD-asset issuer plus a
        funded trustline for every generator account (bulk-written like
        create_accounts; the per-tx path would be changeTrust+payment)."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        root = self.app.ledger_manager.root
        self._derive_dex()
        issuer = self.dex_issuer
        with LedgerTxn(root) as ltx:
            if ltx.load_account(issuer.public_key().raw) is None:
                ltx.put(U.make_account_entry(
                    issuer.public_key().raw, 10**9, seq_num=0))
            for sk in accts:
                pub = sk.public_key().raw
                if ltx.load_trustline(pub, self.dex_asset) is None:
                    ltx.put(U.make_trustline_entry(
                        pub, self.dex_asset, balance=credit,
                        limit=U.INT64_MAX))
                    e = ltx.load_account(pub)
                    acc = e.data.value
                    ltx.put(e._replace(data=T.LedgerEntryData.make(
                        T.LedgerEntryType.ACCOUNT,
                        acc._replace(
                            numSubEntries=acc.numSubEntries + 1))))
            ltx.commit()

    def offer_envelope(self, src: SecretKey, amount: int,
                       price_n: int, price_d: int, fee: int = 100,
                       selling=None, buying=None, offer_id: int = 0):
        """ManageSellOffer envelope; default shape sells native for the
        LOAD asset as a NEW offer (ref manageOfferTransaction), but any
        pair / offerID works — offer_id != 0 with amount > 0 is a
        modify, with amount == 0 a delete."""
        op = T.Operation.make(
            sourceAccount=None,
            body=T.OperationBody.make(
                T.OperationType.MANAGE_SELL_OFFER,
                T.ManageSellOfferOp.make(
                    selling=selling if selling is not None
                    else U.asset_native(),
                    buying=buying if buying is not None
                    else self.dex_asset,
                    amount=amount,
                    price=T.Price.make(n=price_n, d=price_d),
                    offerID=offer_id)))
        return self._sign_tx(src, [op], fee)

    def changetrust_envelope(self, src: SecretKey, asset,
                             limit: int = U.INT64_MAX, fee: int = 100):
        """ChangeTrust envelope over a classic asset (create when no
        line exists, limit update when one does, delete at limit=0)."""
        op = T.Operation.make(
            sourceAccount=None,
            body=T.OperationBody.make(
                T.OperationType.CHANGE_TRUST,
                T.ChangeTrustOp.make(
                    line=T.ChangeTrustAsset.make(asset.type, asset.value),
                    limit=limit)))
        return self._sign_tx(src, [op], fee)

    def generate_mixed(self, n: int, dex_percent: int = 50,
                       accounts: Optional[List[SecretKey]] = None,
                       dest_accounts: Optional[List[SecretKey]] = None
                       ) -> List:
        """Payments + DEX offers at ``dex_percent`` (ref MIXED_TXS
        :308-318; deterministic pseudo-mix instead of the reference's
        PRNG so benches are reproducible)."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        assert getattr(self, "dex_asset", None) is not None, \
            "setup_dex() first"
        out = []
        k = len(accts)
        for i in range(n):
            src = accts[i % k]
            if (i * 7919 + 13) % 100 < dex_percent:
                # prices spread so offers rarely cross (book grows like
                # the reference's synthetic DEX load)
                out.append(self.offer_envelope(
                    src, 10 + i % 90, 100 + (i % 50), 100))
            else:
                dest = self._payment_dest(accts, i, dest_accounts)
                out.append(self.payment_envelope(src, dest,
                                                 1 + (i % 1000)))
        return out

    # -- CREDIT mode (ISSUE 13: credit-heavy realistic traffic) -------------

    def _derive_credit2(self) -> None:
        issuer2 = SecretKey(sha256(b"loadgen-credit-issuer2"))
        self.credit2_issuer = issuer2
        self.credit2_asset = U.make_asset(b"CRD2",
                                          issuer2.public_key().raw)

    def setup_credit(self, accounts: Optional[List[SecretKey]] = None,
                     credit: int = 10**7) -> None:
        """Seed the credit-mix workload: the LOAD issuer + funded
        trustlines (setup_dex) plus a SECOND issuer/asset (CRD2) whose
        trustlines the workload creates and resizes through real
        changeTrust transactions — the trustline create/update kernel
        surface."""
        self.setup_dex(accounts=accounts, credit=credit)
        self._derive_credit2()
        root = self.app.ledger_manager.root
        with LedgerTxn(root) as ltx:
            if ltx.load_account(self.credit2_issuer.public_key().raw) \
                    is None:
                ltx.put(U.make_account_entry(
                    self.credit2_issuer.public_key().raw, 10**9,
                    seq_num=0))
            ltx.commit()

    def create_credit_issuer_envelopes(self) -> List:
        """Stage A of TX-BASED credit-mix seeding (the HTTP
        generateload path, state-commitment-safe): create the LOAD and
        CRD2 issuers from the network root — their own close, so later
        trustlines cannot race them."""
        root = self.root_key()
        self._derive_dex()
        self._derive_credit2()
        envs = []
        for issuer in (self.dex_issuer, self.credit2_issuer):
            envs.append(self._sign_tx(root, [T.Operation.make(
                sourceAccount=None,
                body=T.OperationBody.make(
                    T.OperationType.CREATE_ACCOUNT,
                    T.CreateAccountOp.make(
                        destination=T.account_id(
                            issuer.public_key().raw),
                        startingBalance=10**9)))], 100))
        return envs

    def generate_credit_mix(self, n: int, trust_pct: int = 10,
                            accounts: Optional[List[SecretKey]] = None
                            ) -> List:
        """Credit-heavy close shape: LOAD-asset payments over disjoint
        account pairs, salted with ``trust_pct``% changeTrust ops on the
        CRD2 asset (first touch creates the line, later touches resize
        its limit) — the credit/trustline op families real Stellar
        traffic is dominated by, in conflict shapes the planner can
        spread.  Deterministic pseudo-mix like generate_mixed."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        assert getattr(self, "credit2_asset", None) is not None, \
            "setup_credit() first"
        prev_pattern = self.payment_pattern
        self.payment_pattern = "credit"
        out = []
        k = len(accts)
        try:
            for i in range(n):
                src = accts[i % k]
                if (i * 7919 + 13) % 100 < trust_pct:
                    # vary the limit so repeat touches are real updates
                    limit = U.INT64_MAX - (i % 5)
                    out.append(self.changetrust_envelope(
                        src, self.credit2_asset, limit))
                else:
                    dest = self._payment_dest(accts, i, None)
                    out.append(self.payment_envelope(
                        src, dest, 1 + (i % 1000),
                        asset=self.dex_asset))
        finally:
            self.payment_pattern = prev_pattern
        return out

    # -- PATHPAY mode (ISSUE 13: multi-hop conversion chains) ---------------

    def _derive_path(self, hops: int, makers: int):
        """Deterministic issuers/assets/makers of the ``hops``-hop
        chain (shared by the bulk seeder and the tx-based stages)."""
        assert 1 <= hops <= 3, "path workloads support 1-3 hops"
        names = [b"PATHA", b"PATHB", b"PATHC"][:hops]
        issuers = [SecretKey(sha256(b"loadgen-path-issuer-" + nm))
                   for nm in names]
        assets = [U.make_asset(nm, sk.public_key().raw)
                  for nm, sk in zip(names, issuers)]
        maker_keys = [self.account_key(j, b"pathmaker")
                      for j in range(makers)]
        self.path_issuers = issuers
        self.path_assets = assets
        self.path_makers = maker_keys
        return issuers, assets, maker_keys

    def path_stage_envelopes(self, stage: int, hops: int = 2,
                             makers: int = 8,
                             maker_credit: int = 10**12,
                             offer_amount: int = 10**10) -> List:
        """TX-BASED pathpay seeding for the HTTP generateload path —
        four stages, one ledger close between each (the returned
        envelopes must all be admitted before advancing):

        0. network root creates the hop issuers + maker accounts;
        1. trustlines: makers trust every hop asset, every generator
           account trusts the FINAL asset (the recipients);
        2. issuers fund the makers in their asset;
        3. makers post the hop offers (selling hop asset i for the
           previous chain asset, native first) — the seeded books.
        """
        issuers, assets, maker_keys = self._derive_path(hops, makers)
        root = self.root_key()
        if stage == 0:
            ops = [T.Operation.make(
                sourceAccount=None,
                body=T.OperationBody.make(
                    T.OperationType.CREATE_ACCOUNT,
                    T.CreateAccountOp.make(
                        destination=T.account_id(sk.public_key().raw),
                        startingBalance=10**9)))
                for sk in (*issuers, *maker_keys)]
            return [self._sign_tx(root, ops, 100 * len(ops))]
        if stage == 1:
            envs = []
            for mk in maker_keys:
                for asset in assets:
                    envs.append(self.changetrust_envelope(mk, asset))
            final = assets[-1]
            for sk in self.accounts:
                envs.append(self.changetrust_envelope(sk, final))
            return envs
        if stage == 2:
            envs = []
            for issuer, asset in zip(issuers, assets):
                ops = [T.Operation.make(
                    sourceAccount=None,
                    body=T.OperationBody.make(
                        T.OperationType.PAYMENT,
                        T.PaymentOp.make(
                            destination=T.muxed_account(
                                mk.public_key().raw),
                            asset=asset, amount=maker_credit)))
                    for mk in maker_keys]
                envs.append(self._sign_tx(issuer, ops, 100 * len(ops)))
            return envs
        assert stage == 3, f"unknown path stage {stage}"
        return self._maker_offer_envelopes(assets, maker_keys,
                                           offer_amount)

    def _maker_offer_envelopes(self, assets, maker_keys,
                               offer_amount: int) -> List:
        """The seeded hop books: each maker sells hop asset i for the
        previous chain asset (native first) at 1:1, deep amounts so
        thousands of small path payments shave offers without
        exhausting a book.  One builder for BOTH seeding paths (bulk
        setup_path and the tx-based HTTP stages) so the two workloads
        can never drift apart."""
        envs = []
        chain_buy = [U.asset_native(), *assets[:-1]]
        for mk in maker_keys:
            for selling, buying in zip(assets, chain_buy):
                envs.append(self.offer_envelope(
                    mk, offer_amount, 1, 1, selling=selling,
                    buying=buying))
        return envs

    def setup_path(self, hops: int = 2, makers: int = 8,
                   maker_credit: int = 10**15,
                   offer_amount: int = 10**12) -> List:
        """Seed ``hops``-hop path-payment books: one issuer+asset per
        chain step (PATHA, PATHB, PATHC...), maker accounts holding
        deep balances in every step asset, and trustlines in the FINAL
        asset for every generator account (they are the recipients).

        Seeding writes accounts/trustlines in bulk (perf-rig style,
        like setup_dex) but returns the market-maker OFFER envelopes
        for the caller to admit + close: resting offers carry
        liabilities and consume offer ids, so they must flow through
        the real close path to keep the id pool and reserve accounting
        consistent.  Chain: native -> PATHA [-> PATHB ...] -> final."""
        assert self.accounts, "CREATE accounts first"
        issuers, assets, maker_keys = self._derive_path(hops, makers)
        root = self.app.ledger_manager.root
        with LedgerTxn(root) as ltx:
            for sk in issuers:
                if ltx.load_account(sk.public_key().raw) is None:
                    ltx.put(U.make_account_entry(
                        sk.public_key().raw, 10**9, seq_num=0))
            for mk in maker_keys:
                pub = mk.public_key().raw
                if ltx.load_account(pub) is None:
                    ltx.put(U.make_account_entry(pub, 10**9, seq_num=0))
                subentries = 0
                for asset in assets:
                    if ltx.load_trustline(pub, asset) is None:
                        ltx.put(U.make_trustline_entry(
                            pub, asset, balance=maker_credit,
                            limit=U.INT64_MAX))
                        subentries += 1
                if subentries:
                    e = ltx.load_account(pub)
                    acc = e.data.value
                    ltx.put(e._replace(data=T.LedgerEntryData.make(
                        T.LedgerEntryType.ACCOUNT,
                        acc._replace(numSubEntries=acc.numSubEntries
                                     + subentries))))
            final = assets[-1]
            for sk in self.accounts:
                pub = sk.public_key().raw
                if ltx.load_trustline(pub, final) is None:
                    ltx.put(U.make_trustline_entry(
                        pub, final, balance=0, limit=U.INT64_MAX))
                    e = ltx.load_account(pub)
                    acc = e.data.value
                    ltx.put(e._replace(data=T.LedgerEntryData.make(
                        T.LedgerEntryType.ACCOUNT,
                        acc._replace(
                            numSubEntries=acc.numSubEntries + 1))))
            ltx.commit()
        return self._maker_offer_envelopes(assets, maker_keys,
                                           offer_amount)

    def path_payment_envelope(self, src: SecretKey, dest: bytes,
                              amount: int, strict_send: bool = True,
                              fee: int = 100):
        """One path payment over the seeded chain: native in, the final
        path asset out, intermediate assets as the declared path."""
        assets = self.path_assets
        path = assets[:-1]
        dest_asset = assets[-1]
        if strict_send:
            body = T.PathPaymentStrictSendOp.make(
                sendAsset=U.asset_native(), sendAmount=amount,
                destination=T.muxed_account(dest), destAsset=dest_asset,
                destMin=1, path=path)
            op_type = T.OperationType.PATH_PAYMENT_STRICT_SEND
        else:
            body = T.PathPaymentStrictReceiveOp.make(
                sendAsset=U.asset_native(), sendMax=4 * amount + 100,
                destination=T.muxed_account(dest), destAsset=dest_asset,
                destAmount=amount, path=path)
            op_type = T.OperationType.PATH_PAYMENT_STRICT_RECEIVE
        op = T.Operation.make(
            sourceAccount=None,
            body=T.OperationBody.make(op_type, body))
        return self._sign_tx(src, [op], fee)

    def generate_path_payments(self, n: int,
                               accounts: Optional[List[SecretKey]] = None
                               ) -> List:
        """n path payments over the seeded books, alternating
        strict-send / strict-receive, destinations on the disjoint
        pairs graph (sources are never makers, so self-crossing cannot
        fire)."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        assert getattr(self, "path_assets", None) is not None, \
            "setup_path() first"
        out = []
        k = len(accts)
        for i in range(n):
            src = accts[i % k]
            j = i % k
            p = j ^ 1
            if p >= k:
                p = j
            dest = accts[p].public_key().raw
            out.append(self.path_payment_envelope(
                src, dest, 1 + (i % 500), strict_send=(i % 2 == 0)))
        return out

    # -- POOL mode (path payments through LIVE liquidity pools) -------------

    def setup_pool(self, hops: int = 2, reserve: int = 10**12) -> None:
        """Seed ``hops``-hop LIVE constant-product pools: one pool per
        chain hop pair (native<->PATHA [, PATHA<->PATHB ...]) with deep
        equal reserves, plus final-asset trustlines for every generator
        account (the recipients).  NO maker books: the pools are the
        only venue on each hop, so every path payment crosses them (the
        empty book walk loses the book-vs-pool arbitration and the
        constant-product quote adjudicates).  Bulk-seeded perf-rig
        style like setup_path; flips ``payment_pattern`` to "pool"."""
        from ..transactions import liquidity_pool as LP

        assert self.accounts, "CREATE accounts first"
        issuers, assets, _ = self._derive_path(hops, makers=0)
        root = self.app.ledger_manager.root
        pool_ids = []
        cp_type = T.LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT
        with LedgerTxn(root) as ltx:
            for sk in issuers:
                if ltx.load_account(sk.public_key().raw) is None:
                    ltx.put(U.make_account_entry(
                        sk.public_key().raw, 10**9, seq_num=0))
            final = assets[-1]
            for sk in self.accounts:
                pub = sk.public_key().raw
                if ltx.load_trustline(pub, final) is None:
                    ltx.put(U.make_trustline_entry(
                        pub, final, balance=0, limit=U.INT64_MAX))
                    e = ltx.load_account(pub)
                    acc = e.data.value
                    ltx.put(e._replace(data=T.LedgerEntryData.make(
                        T.LedgerEntryType.ACCOUNT,
                        acc._replace(
                            numSubEntries=acc.numSubEntries + 1))))
            chain = [U.asset_native(), *assets]
            for x, y in zip(chain, chain[1:]):
                a, b = ((x, y) if LP.compare_assets(x, y) < 0
                        else (y, x))
                params = T.LiquidityPoolParameters.make(
                    cp_type,
                    T.LiquidityPoolConstantProductParameters.make(
                        assetA=a, assetB=b,
                        fee=T.LIQUIDITY_POOL_FEE_V18))
                pool_id = LP.pool_id_from_params(params)
                cp = T.LiquidityPoolEntry.fields[1][1].arms[
                    cp_type][1].make(
                    params=params.value, reserveA=reserve,
                    reserveB=reserve, totalPoolShares=reserve,
                    poolSharesTrustLineCount=1)
                lp = T.LiquidityPoolEntry.make(
                    liquidityPoolID=pool_id,
                    body=T.LiquidityPoolEntry.fields[1][1].make(
                        cp_type, cp))
                ltx.put(U.wrap_entry(
                    T.LedgerEntryType.LIQUIDITY_POOL, lp))
                pool_ids.append(pool_id)
            ltx.commit()
        self.pool_ids = pool_ids
        self.payment_pattern = "pool"

    def generate_pool_payments(self, n: int,
                               accounts: Optional[List[SecretKey]] = None
                               ) -> List:
        """n path payments routed through the seeded pools — the same
        alternating strict-send / strict-receive mix as the book
        workload, but amounts start at 10: the 30bps constant-product
        fee must never round a hop's output to zero (a zero-output
        quote is a FAILED path payment, which the success-only kernel
        declines — poisoning its whole cluster off the fast path)."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        assert getattr(self, "pool_ids", None), "setup_pool() first"
        out = []
        k = len(accts)
        for i in range(n):
            src = accts[i % k]
            j = i % k
            p = j ^ 1
            if p >= k:
                p = j
            dest = accts[p].public_key().raw
            out.append(self.path_payment_envelope(
                src, dest, 10 + (i % 500), strict_send=(i % 2 == 0)))
        return out

    # -- RATE mode (timer-driven tx/s; ref LoadGenerator.h:28-36) -----------

    def start_rate_run(self, mode: str = PAY, rate: float = 10.0,
                       duration: float = 10.0, dex_percent: int = 50,
                       op_count: int = 1) -> dict:
        """Sustain ``rate`` tx/s for ``duration`` clock-seconds (the
        reference's generateLoad txRate scheduling): a VirtualTimer ticks
        every RATE_TICK_SECONDS and ENQUEUES the generation work on the
        app's fair scheduler (utils/scheduler.py, queue "loadgen"), so
        sustained load shares the crank with consensus instead of
        preempting it — the soak shape that makes queue aging, banning
        and rebroadcast reachable.  Returns the initial status dict."""
        from ..utils.clock import VirtualTimer

        assert mode in (PAY, "pretend", "mixed"), mode
        assert self.accounts, "CREATE accounts first"
        if mode == "mixed":
            assert getattr(self, "dex_asset", None) is not None, \
                "setup_dex() first"
        self.stop_rate_run()
        clock = self.app.clock
        self._rate_state = {
            "mode": mode, "rate": float(rate),
            "deadline": clock.now() + float(duration),
            "dex_percent": int(dex_percent), "op_count": int(op_count),
            "submitted": 0, "status_counts": {}, "ticks": 0,
            "cursor": 0, "carry": 0.0, "last": clock.now(),
            "running": True, "stopped": False,
        }
        self._rate_timer = VirtualTimer(clock, owner=self.app)
        self._arm_rate_tick()
        return self.rate_status()

    def stop_rate_run(self) -> None:
        if self._rate_timer is not None:
            self._rate_timer.cancel()
            self._rate_timer = None
        if self._rate_state is not None:
            self._rate_state["running"] = False
            # an operator stop also voids batches already enqueued on
            # the scheduler (deadline expiry does NOT: the final tick's
            # batch covers the run's last second and must submit)
            self._rate_state["stopped"] = True

    def rate_status(self) -> dict:
        st = self._rate_state
        if st is None:
            return {"running": False}
        return {"running": st["running"], "mode": st["mode"],
                "rate": st["rate"], "ticks": st["ticks"],
                "submitted": st["submitted"],
                "status_counts": {str(k): v for k, v
                                  in st["status_counts"].items()},
                "remaining_seconds": round(
                    max(0.0, st["deadline"] - self.app.clock.now()), 3)}

    def _arm_rate_tick(self) -> None:
        t = self._rate_timer
        t.expires_from_now(RATE_TICK_SECONDS)
        t.async_wait(self._rate_tick)

    def _rate_tick(self) -> None:
        st = self._rate_state
        if st is None or not st["running"]:
            return
        clock = self.app.clock
        now = clock.now()
        want = st["rate"] * (now - st["last"]) + st["carry"]
        n = int(want)
        st["carry"] = want - n
        st["last"] = now
        st["ticks"] += 1
        if n > 0:
            # generation/submission runs as a fair-scheduled action, not
            # inside the timer callback; the batch binds ITS run's state
            # so a stop/start can never replay it against the new run
            self.app.scheduler.enqueue(
                "loadgen", lambda st=st, n=n: self._rate_generate(st, n))
        if now < st["deadline"]:
            self._arm_rate_tick()
        else:
            st["running"] = False
            self._rate_timer = None

    def _rate_generate(self, st: dict, n: int) -> None:
        from ..herder.tx_queue import TransactionQueue

        if st["stopped"]:
            return
        accts = self.accounts
        k = len(accts)
        for _ in range(n):
            i = st["cursor"]
            st["cursor"] += 1
            src = accts[i % k]
            if st["mode"] == "pretend":
                env = self.pretend_envelope(src, st["op_count"])
            elif st["mode"] == "mixed" and \
                    (i * 7919 + 13) % 100 < st["dex_percent"]:
                env = self.offer_envelope(
                    src, 10 + i % 90, 100 + (i % 50), 100)
            else:
                dest = accts[(i + 1) % k].public_key().raw
                # fee spread: sustained overload must exercise the
                # fee-rate eviction (and ban) path, which uniform fees
                # never trigger
                env = self.payment_envelope(src, dest, 1 + (i % 1000),
                                            fee=100 + (i % 16) * 25)
            r = self.app.herder.recv_transaction(env)
            st["submitted"] += 1
            st["status_counts"][r] = st["status_counts"].get(r, 0) + 1
            if r not in (TransactionQueue.ADD_STATUS_PENDING,
                         TransactionQueue.ADD_STATUS_DUPLICATE):
                # the queue did not take it: the cached seqnum must roll
                # back or every later tx from this source is a seq gap
                pub = src.public_key().raw
                if pub in self._seqs:
                    self._seqs[pub] -= 1

    # -- shared signing -----------------------------------------------------

    def _sign_tx(self, src: SecretKey, ops, fee: int):
        tx = T.Transaction.make(
            sourceAccount=T.muxed_account(src.public_key().raw),
            fee=fee,
            seqNum=self._next_seq(src),
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.MEMO_NONE_VALUE,
            operations=ops,
            ext=T.Transaction.fields[6][1].make(0))
        payload = T.TransactionSignaturePayload.make(
            networkId=self.network_id,
            taggedTransaction=T.TransactionSignaturePayload.fields[1][1]
            .make(T.EnvelopeType.ENVELOPE_TYPE_TX, tx))
        h = sha256(T.TransactionSignaturePayload.encode(payload))
        sig = T.DecoratedSignature.make(
            hint=signature_hint(src.public_key().raw),
            signature=src.sign(h))
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=tx, signatures=[sig]))

    # -- tx-based seeding (state-commitment-safe) ---------------------------

    def root_key(self) -> SecretKey:
        """The network root account key (standalone networks seed the
        genesis balance at SecretKey(network_id), like the reference's
        TestAccount::createRoot)."""
        return SecretKey(self.network_id)

    def create_account_envelopes(self, n: int, balance: int = 10**9,
                                 prefix: bytes = b"loadgen",
                                 batch: int = 100) -> List:
        """CreateAccount transactions from the network root, ``batch``
        ops per tx (ref LoadGenerator::createAccounts — REAL txs, so the
        bucket-list commitment covers the seeded accounts; the bulk
        create_accounts() writer is for in-process perf rigs only and
        leaves the SQL tier ahead of the buckets)."""
        root = self.root_key()
        new = [self.account_key(i, prefix) for i in range(n)]
        envs = []
        for i in range(0, len(new), batch):
            chunk = new[i:i + batch]
            ops = [T.Operation.make(
                sourceAccount=None,
                body=T.OperationBody.make(
                    T.OperationType.CREATE_ACCOUNT,
                    T.CreateAccountOp.make(
                        destination=T.account_id(sk.public_key().raw),
                        startingBalance=balance)))
                for sk in chunk]
            envs.append(self._sign_tx(root, ops, 100 * len(ops)))
        self.accounts.extend(new)
        return envs

    def create_dex_issuer_envelope(self) -> List:
        """Stage A of DEX seeding: create the LOAD issuer (its OWN close
        — apply order is hash-shuffled, so trustlines in the same ledger
        could apply before the issuer exists and fail NO_ISSUER)."""
        root = self.root_key()
        self._derive_dex()
        issuer = self.dex_issuer
        return [self._sign_tx(root, [T.Operation.make(
            sourceAccount=None,
            body=T.OperationBody.make(
                T.OperationType.CREATE_ACCOUNT,
                T.CreateAccountOp.make(
                    destination=T.account_id(issuer.public_key().raw),
                    startingBalance=10**9)))], 100)]

    def setup_dex_envelopes(self, credit: int = 10**7,
                            accounts: Optional[List[SecretKey]] = None
                            ) -> List:
        """Stage B of DEX seeding: one changeTrust per account (each
        account signs its own; run AFTER the issuer-create tx closed)."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        assert getattr(self, "dex_asset", None) is not None, \
            "create_dex_issuer_envelope first"
        envs = []
        for sk in accts:
            envs.append(self._sign_tx(sk, [T.Operation.make(
                sourceAccount=None,
                body=T.OperationBody.make(
                    T.OperationType.CHANGE_TRUST,
                    T.ChangeTrustOp.make(
                        line=T.ChangeTrustAsset.make(
                            self.dex_asset.type, self.dex_asset.value),
                        limit=U.INT64_MAX)))], 100))
        return envs

    def fund_dex_envelopes(self, credit: int = 10**7, batch: int = 100,
                           accounts: Optional[List[SecretKey]] = None
                           ) -> List:
        """Issuer payments funding every trustline (run AFTER the
        setup_dex_envelopes txs have closed)."""
        accts = accounts or self.accounts
        envs = []
        for i in range(0, len(accts), batch):
            chunk = accts[i:i + batch]
            ops = [T.Operation.make(
                sourceAccount=None,
                body=T.OperationBody.make(
                    T.OperationType.PAYMENT,
                    T.PaymentOp.make(
                        destination=T.muxed_account(sk.public_key().raw),
                        asset=self.dex_asset, amount=credit)))
                for sk in chunk]
            envs.append(self._sign_tx(self.dex_issuer, ops,
                                      100 * len(ops)))
        return envs
