"""LoadGenerator: the standard synthetic load source for benchmarks and
soak tests (ref src/simulation/LoadGenerator.h:28-36 — modes CREATE / PAY;
the reference drives it via the test-build 'generateload' HTTP endpoint).

CREATE seeds n accounts; PAY builds single-op payment transactions between
them.  Accounts are written straight into the ledger root (bulk-seeding
like the reference's createAccounts batches); payments are real signed
envelopes that flow through whatever admission path the caller uses.
"""
from __future__ import annotations

from typing import List, Optional, Tuple

from ..crypto import SecretKey, sha256
from ..ledger.ledger_txn import LedgerTxn
from ..transactions import utils as U
from ..transactions.signature_checker import signature_hint
from ..xdr import types as T

CREATE = "create"
PAY = "pay"


class LoadGenerator:
    def __init__(self, app):
        self.app = app
        self.network_id = app.config.network_id()
        self.accounts: List[SecretKey] = []
        self._seqs = {}

    # -- CREATE mode --------------------------------------------------------

    def create_accounts(self, n: int, balance: int = 10**9,
                        prefix: bytes = b"loadgen") -> List[SecretKey]:
        """Seed n funded accounts directly into the ledger root (bulk;
        the per-tx path would be n CreateAccount ops)."""
        root = self.app.ledger_manager.root
        new = [SecretKey(sha256(prefix + b"-%d" % i)) for i in range(n)]
        with LedgerTxn(root) as ltx:
            for sk in new:
                ltx.put(U.make_account_entry(
                    sk.public_key().raw, balance, seq_num=0))
            ltx.commit()
        self.accounts.extend(new)
        return new

    # -- PAY mode -----------------------------------------------------------

    def _next_seq(self, sk: SecretKey) -> int:
        k = sk.public_key().raw
        if k not in self._seqs:
            root = self.app.ledger_manager.root
            with LedgerTxn(root) as ltx:
                e = ltx.load_account(k)
                ltx.rollback()
            self._seqs[k] = e.data.value.seqNum if e else 0
        self._seqs[k] += 1
        return self._seqs[k]

    def payment_envelope(self, src: SecretKey, dest: bytes, amount: int,
                         fee: int = 100):
        tx = T.Transaction.make(
            sourceAccount=T.muxed_account(src.public_key().raw),
            fee=fee,
            seqNum=self._next_seq(src),
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.MEMO_NONE_VALUE,
            operations=[T.Operation.make(
                sourceAccount=None,
                body=T.OperationBody.make(
                    T.OperationType.PAYMENT,
                    T.PaymentOp.make(destination=T.muxed_account(dest),
                                     asset=U.asset_native(),
                                     amount=amount)))],
            ext=T.Transaction.fields[6][1].make(0))
        payload = T.TransactionSignaturePayload.make(
            networkId=self.network_id,
            taggedTransaction=T.TransactionSignaturePayload.fields[1][1]
            .make(T.EnvelopeType.ENVELOPE_TYPE_TX, tx))
        h = sha256(T.TransactionSignaturePayload.encode(payload))
        sig = T.DecoratedSignature.make(
            hint=signature_hint(src.public_key().raw),
            signature=src.sign(h))
        return T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=tx, signatures=[sig]))

    def generate_payments(self, n: int,
                          accounts: Optional[List[SecretKey]] = None
                          ) -> List:
        """n one-op payments round-robin across the account pool (each
        account pays its successor; sequence numbers tracked per source)."""
        accts = accounts or self.accounts
        assert accts, "CREATE accounts first"
        out = []
        k = len(accts)
        for i in range(n):
            src = accts[i % k]
            dest = accts[(i + 1) % k].public_key().raw
            out.append(self.payment_envelope(src, dest, 1 + (i % 1000)))
        return out
