"""Simulation: N full Applications in one process sharing a VirtualClock,
wired over loopback links — whole consensus networks run deterministically
at accelerated time (ref src/simulation/Simulation.h:29, Topologies.h;
SURVEY.md §4.2: "how multi-node is tested without a cluster").

This harness is also the TPU-mesh multi-validator driver: each node's
admission batches dispatch to the shared device, validators map onto mesh
slices (SURVEY.md §2.17 P4).
"""
from __future__ import annotations

from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import SecretKey, sha256
from ..main.application import Application
from ..main.config import Config
from ..overlay.manager import OverlayManager
from ..overlay.peer import make_loopback_pair
from ..utils.clock import ClockMode, VirtualClock


class Simulation:
    OVER_LOOPBACK = 0

    def __init__(self, mode: int = OVER_LOOPBACK,
                 network_passphrase: str = "test simulation network"):
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.network_passphrase = network_passphrase
        self.nodes: Dict[bytes, Application] = {}
        self.node_seeds: Dict[bytes, bytes] = {}

    # -- topology construction ---------------------------------------------

    def add_node(self, seed: bytes, qset_spec: dict,
                 **config_kw) -> Application:
        """qset_spec: {"threshold": t, "validators": [node ids]}."""
        cfg = Config(
            NETWORK_PASSPHRASE=self.network_passphrase,
            NODE_SEED=seed,
            QUORUM_SET=qset_spec,
            RUN_STANDALONE=False,
            MANUAL_CLOSE=config_kw.pop("MANUAL_CLOSE", True),
            ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True,
            INVARIANT_CHECKS=[".*"],
            # sim topologies use deliberately small/unsafe quorums
            # (ref getTestConfig setting UNSAFE_QUORUM)
            UNSAFE_QUORUM=config_kw.pop("UNSAFE_QUORUM", True),
            **config_kw,
        )
        app = Application(self.clock, cfg)
        app.overlay_manager = OverlayManager(app)
        self.nodes[cfg.node_id()] = app
        self.node_seeds[cfg.node_id()] = seed
        return app

    def add_connection(self, a: bytes, b: bytes) -> None:
        make_loopback_pair(self.nodes[a], self.nodes[b])

    def start_all_nodes(self) -> None:
        for app in self.nodes.values():
            app.start()

    # -- driving ------------------------------------------------------------

    def crank(self, block: bool = False) -> int:
        return self.clock.crank(block)

    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 100.0) -> bool:
        return self.clock.crank_until(pred, timeout)

    def crank_for(self, seconds: float) -> None:
        deadline = self.clock.now() + seconds
        while self.clock.now() < deadline:
            if self.clock.crank(block=True) == 0 and \
                    self.clock.next_deadline() is None:
                break

    def have_all_externalized(self, seq: int) -> bool:
        return all(
            app.ledger_manager.last_closed_seq() >= seq
            for app in self.nodes.values())

    def trigger_all(self) -> None:
        """Manual-close mode: every validator proposes for the next slot."""
        for app in self.nodes.values():
            app.herder.trigger_next_ledger()

    def close_ledger(self, timeout: float = 60.0) -> bool:
        """One consensus round across the whole network."""
        target = max(app.ledger_manager.last_closed_seq()
                     for app in self.nodes.values()) + 1
        self.trigger_all()
        return self.crank_until(
            lambda: self.have_all_externalized(target), timeout)

    # -- assertions ----------------------------------------------------------

    def ledger_hashes(self, seq: Optional[int] = None) -> List[bytes]:
        return [app.ledger_manager.last_closed_hash()
                for app in self.nodes.values()]

    def assert_in_sync(self) -> None:
        hashes = self.ledger_hashes()
        assert len(set(hashes)) == 1, [h.hex()[:8] for h in hashes]


# -- canned topologies (ref src/simulation/Topologies.h:12-80) ---------------

def _seeds(n: int) -> List[bytes]:
    return [sha256(b"sim-node-%d" % i) for i in range(n)]


def _ids(seeds: List[bytes]) -> List[bytes]:
    return [SecretKey(s).public_key().raw for s in seeds]


def core(n: int, threshold: Optional[int] = None,
         passphrase: str = "test simulation network") -> Simulation:
    """Fully-connected core-N: every validator trusts all N with the given
    threshold (default 2f+1; ref Topologies::core)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n)
    ids = _ids(seeds)
    thr = threshold if threshold is not None else n - (n - 1) // 3
    qset = {"threshold": thr, "validators": ids}
    for s in seeds:
        sim.add_node(s, qset)
    for i in range(n):
        for j in range(i + 1, n):
            sim.add_connection(ids[i], ids[j])
    return sim


def pair(passphrase: str = "test simulation network") -> Simulation:
    return core(2, threshold=2, passphrase=passphrase)


def cycle(n: int, passphrase: str = "test simulation network") -> Simulation:
    """Ring: each node trusts itself + both neighbors (2-of-3)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n)
    ids = _ids(seeds)
    for i, s in enumerate(seeds):
        neighbors = [ids[i], ids[(i - 1) % n], ids[(i + 1) % n]]
        sim.add_node(s, {"threshold": 2, "validators": neighbors})
    for i in range(n):
        sim.add_connection(ids[i], ids[(i + 1) % n])
    return sim
