"""Simulation: N full Applications in one process sharing a VirtualClock,
wired over loopback links — whole consensus networks run deterministically
at accelerated time (ref src/simulation/Simulation.h:29, Topologies.h;
SURVEY.md §4.2: "how multi-node is tested without a cluster").

This harness is also the TPU-mesh multi-validator driver: each node's
admission batches dispatch to the shared device, validators map onto mesh
slices (SURVEY.md §2.17 P4).

Chaos support (simulation/chaos.py drives these seams):
- every loopback link is registered in ``links`` so fault injection can
  find both directions of any pair;
- nodes may run with on-disk state (``node_dir``) so ``crash_node`` /
  ``restart_node`` model a full process kill + restart-from-state;
- ``header_chain`` / ``assert_no_forks`` are the safety oracle: honest
  survivors must agree on every closed header (bucket hash included).
"""
from __future__ import annotations

import os
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import SecretKey, sha256
from ..main.application import Application
from ..main.config import Config
from ..overlay.manager import OverlayManager
from ..overlay.peer import make_loopback_pair
from ..utils.clock import ClockMode, VirtualClock
from ..xdr import types as T, xdr_sha256


class Simulation:
    OVER_LOOPBACK = 0

    def __init__(self, mode: int = OVER_LOOPBACK,
                 network_passphrase: str = "test simulation network"):
        self.clock = VirtualClock(ClockMode.VIRTUAL_TIME)
        self.network_passphrase = network_passphrase
        self.nodes: Dict[bytes, Application] = {}
        self.node_seeds: Dict[bytes, bytes] = {}
        # rebuild recipes for restart-from-state (chaos kill-restore)
        self.node_recipes: Dict[bytes, dict] = {}
        # intended adjacency (survives crashes; restart re-wires from it)
        self.topology: List[Tuple[bytes, bytes]] = []
        # live loopback pairs: (a, b) -> (peer at a, peer at b)
        self.links: Dict[Tuple[bytes, bytes], tuple] = {}
        self.crashed: Dict[bytes, bool] = {}
        # network observatory (attach_observatory); restart_node
        # re-attaches it to rebuilt Applications
        self.observatory = None

    # -- topology construction ---------------------------------------------

    def add_node(self, seed: bytes, qset_spec: dict,
                 node_dir: Optional[str] = None,
                 **config_kw) -> Application:
        """qset_spec: {"threshold": t, "validators": [node ids],
        "inner_sets": [...]}.  ``node_dir`` gives the node on-disk state
        (SQLite DB + bucket store) so it can be killed and restarted
        from state by the chaos engine."""
        recipe = {"seed": seed, "qset_spec": qset_spec,
                  "node_dir": node_dir, "config_kw": dict(config_kw)}
        cfg = self._build_config(recipe)
        app = self._build_app(cfg)
        self.nodes[cfg.node_id()] = app
        self.node_seeds[cfg.node_id()] = seed
        self.node_recipes[cfg.node_id()] = recipe
        return app

    def _build_config(self, recipe: dict) -> Config:
        config_kw = dict(recipe["config_kw"])
        node_dir = recipe["node_dir"]
        if node_dir is not None:
            os.makedirs(os.path.join(node_dir, "buckets"), exist_ok=True)
            config_kw.setdefault(
                "DATABASE", os.path.join(node_dir, "node.db"))
            config_kw.setdefault(
                "BUCKET_DIR_PATH_REAL", os.path.join(node_dir, "buckets"))
        # sims default the close pipeline OFF: a 50-validator network
        # in one process would own 50 tail workers for no modelled
        # benefit, and the scripted chaos wall-cost budget predates it.
        # Pipeline-specific sim tests (the chaos pipeline-window
        # kill-restore) opt in per node via config_kw, and the core-4
        # chaos smoke tier runs PIPELINED_CLOSE=True wholesale
        # (tools/chaos_bench.py) so the overlap contract is
        # chaos-tested.
        config_kw.setdefault("PIPELINED_CLOSE", False)
        # no per-node 1 Hz vitals timers at simulation scale (50 nodes
        # = 50 timers per virtual second); vitals tests opt in
        config_kw.setdefault("VITALS_ENABLED", False)
        return Config(
            NETWORK_PASSPHRASE=self.network_passphrase,
            NODE_SEED=recipe["seed"],
            QUORUM_SET=recipe["qset_spec"],
            RUN_STANDALONE=False,
            MANUAL_CLOSE=config_kw.pop("MANUAL_CLOSE", True),
            ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True,
            INVARIANT_CHECKS=config_kw.pop("INVARIANT_CHECKS", [".*"]),
            # sim topologies use deliberately small/unsafe quorums
            # (ref getTestConfig setting UNSAFE_QUORUM)
            UNSAFE_QUORUM=config_kw.pop("UNSAFE_QUORUM", True),
            **config_kw,
        )

    def _build_app(self, cfg: Config) -> Application:
        app = Application(self.clock, cfg)
        app.overlay_manager = OverlayManager(app)
        if self.observatory is not None:
            app._observatory = self.observatory
        return app

    def add_connection(self, a: bytes, b: bytes) -> None:
        if (a, b) not in self.topology and (b, a) not in self.topology:
            self.topology.append((a, b))
        self._wire(a, b)

    def _wire(self, a: bytes, b: bytes) -> None:
        p1, p2 = make_loopback_pair(self.nodes[a], self.nodes[b])
        self.links[(a, b)] = (p1, p2)

    def link_peers(self, a: bytes, b: bytes) -> list:
        """Both LoopbackPeer ends of the (a, b) link, either key order."""
        pair = self.links.get((a, b)) or self.links.get((b, a))
        return list(pair) if pair is not None else []

    def start_all_nodes(self) -> None:
        for app in self.nodes.values():
            app.start()

    # -- crash / restart (the chaos kill-restore seam) -----------------------

    def crash_node(self, node_id: bytes) -> None:
        """Kill one validator mid-flight: close its links (both ends),
        tear down its subsystems, cancel its timers on the shared clock.
        On-disk state survives for ``restart_node``."""
        app = self.nodes[node_id]
        for key in [k for k in self.links if node_id in k]:
            p_a, p_b = self.links.pop(key)
            for p in (p_a, p_b):
                if p.app is not app:
                    p.close("peer crashed")
        app.stop_node()
        self.crashed[node_id] = True

    def restart_node(self, node_id: bytes) -> Application:
        """Rebuild the crashed node from its on-disk state (the
        restart-from-state path: load-last-known-ledger, hash-verified
        bucket restore, SCP state re-ingest) and re-wire its topology
        links to the surviving nodes."""
        recipe = self.node_recipes[node_id]
        assert recipe["node_dir"] is not None, \
            "restart_node needs a node_dir-backed node"
        app = self._build_app(self._build_config(recipe))
        self.nodes[node_id] = app
        self.crashed.pop(node_id, None)
        app.start()
        for a, b in self.topology:
            if node_id not in (a, b):
                continue
            other = b if a == node_id else a
            if self.crashed.get(other) or other not in self.nodes:
                continue
            self._wire(a, b)
        return app

    def alive_nodes(self) -> Dict[bytes, Application]:
        return {nid: app for nid, app in self.nodes.items()
                if not self.crashed.get(nid)}

    # -- observability rigs ---------------------------------------------------

    def attach_observatory(self):
        """Create (or return) the fleet-level NetworkObservatory and hang
        it off every node as ``app._observatory`` so each node's
        ``network-observatory`` admin endpoint serves the merged view.
        Nodes rebuilt by ``restart_node`` re-attach automatically."""
        if self.observatory is None:
            from .observatory import NetworkObservatory

            self.observatory = NetworkObservatory(self)
        for app in self.nodes.values():
            app._observatory = self.observatory
        return self.observatory

    def enable_crank_profiler(self):
        """Arm the shared clock's wall-attribution profiler (fresh run:
        re-enabling restarts the measurement window)."""
        from ..utils.clock import CrankProfiler

        self.clock.profiler = CrankProfiler()
        self._profiler_v0 = self.clock.now()
        return self.clock.profiler

    def crank_report(self) -> Optional[dict]:
        """The profiler's bucket report over the window since
        ``enable_crank_profiler``, with wall-per-virtual-second."""
        prof = self.clock.profiler
        if prof is None:
            return None
        return prof.report(
            virtual_elapsed=self.clock.now() - self._profiler_v0)

    # -- driving ------------------------------------------------------------

    def crank(self, block: bool = False) -> int:
        return self.clock.crank(block)

    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 100.0) -> bool:
        return self.clock.crank_until(pred, timeout)

    def crank_for(self, seconds: float) -> None:
        deadline = self.clock.now() + seconds
        while self.clock.now() < deadline:
            if self.clock.crank(block=True) == 0 and \
                    self.clock.next_deadline() is None:
                break

    def have_all_externalized(self, seq: int) -> bool:
        return all(
            app.ledger_manager.last_closed_seq() >= seq
            for app in self.alive_nodes().values())

    def trigger_all(self) -> None:
        """Manual-close mode: every validator proposes for the next slot."""
        for app in self.alive_nodes().values():
            app.herder.trigger_next_ledger()

    def close_ledger(self, timeout: float = 60.0) -> bool:
        """One consensus round across the whole network."""
        target = max(app.ledger_manager.last_closed_seq()
                     for app in self.alive_nodes().values()) + 1
        self.trigger_all()
        return self.crank_until(
            lambda: self.have_all_externalized(target), timeout)

    # -- assertions ----------------------------------------------------------

    def ledger_hashes(self, seq: Optional[int] = None) -> List[bytes]:
        return [app.ledger_manager.last_closed_hash()
                for app in self.alive_nodes().values()]

    def assert_in_sync(self) -> None:
        hashes = self.ledger_hashes()
        assert len(set(hashes)) == 1, [h.hex()[:8] for h in hashes]

    def header_chain(self, node_id: bytes) -> Dict[int, tuple]:
        """seq -> (header hash, bucketListHash) for every ledger the node
        has closed, read from its persisted header rows — the fork
        oracle's raw material."""
        app = self.nodes[node_id]
        out: Dict[int, tuple] = {}
        for seq, data in app.database.execute(
                "SELECT ledgerseq, data FROM ledgerheaders "
                "ORDER BY ledgerseq").fetchall():
            hdr = T.LedgerHeader.decode(data)
            out[seq] = (xdr_sha256(T.LedgerHeader, hdr),
                        hdr.bucketListHash)
        return out

    def assert_no_forks(self, node_ids: Optional[List[bytes]] = None
                        ) -> int:
        """Every pair of (honest, alive) nodes must agree on the header
        hash AND bucket-list hash of every ledger seq both have closed.
        Returns the number of (seq) comparisons made; raises
        AssertionError on the first divergence — a fork."""
        if node_ids is None:
            node_ids = list(self.alive_nodes())
        chains = {nid: self.header_chain(nid) for nid in node_ids}
        compared = 0
        ids = list(chains)
        for i in range(len(ids)):
            for j in range(i + 1, len(ids)):
                a, b = ids[i], ids[j]
                for seq in chains[a].keys() & chains[b].keys():
                    ha, ba = chains[a][seq]
                    hb, bb = chains[b][seq]
                    assert ha == hb, (
                        f"FORK: header divergence at seq {seq} between "
                        f"{a.hex()[:8]} ({ha.hex()[:8]}) and "
                        f"{b.hex()[:8]} ({hb.hex()[:8]})")
                    assert ba == bb, (
                        f"FORK: bucket-hash divergence at seq {seq} "
                        f"between {a.hex()[:8]} and {b.hex()[:8]}")
                    compared += 1
        return compared


# -- canned topologies (ref src/simulation/Topologies.h:12-80) ---------------

def _seeds(n: int) -> List[bytes]:
    return [sha256(b"sim-node-%d" % i) for i in range(n)]


def _ids(seeds: List[bytes]) -> List[bytes]:
    return [SecretKey(s).public_key().raw for s in seeds]


def _node_dir(base: Optional[str], i: int) -> Optional[str]:
    return None if base is None else os.path.join(base, f"node{i:03d}")


def core(n: int, threshold: Optional[int] = None,
         passphrase: str = "test simulation network",
         persist_dir: Optional[str] = None, **config_kw) -> Simulation:
    """Fully-connected core-N: every validator trusts all N with the given
    threshold (default 2f+1; ref Topologies::core)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n)
    ids = _ids(seeds)
    thr = threshold if threshold is not None else n - (n - 1) // 3
    qset = {"threshold": thr, "validators": ids}
    for i, s in enumerate(seeds):
        sim.add_node(s, qset, node_dir=_node_dir(persist_dir, i),
                     **config_kw)
    for i in range(n):
        for j in range(i + 1, n):
            sim.add_connection(ids[i], ids[j])
    return sim


def pair(passphrase: str = "test simulation network") -> Simulation:
    return core(2, threshold=2, passphrase=passphrase)


def cycle(n: int, passphrase: str = "test simulation network") -> Simulation:
    """Ring: each node trusts itself + both neighbors (2-of-3)."""
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n)
    ids = _ids(seeds)
    for i, s in enumerate(seeds):
        neighbors = [ids[i], ids[(i - 1) % n], ids[(i + 1) % n]]
        sim.add_node(s, {"threshold": 2, "validators": neighbors})
    for i in range(n):
        sim.add_connection(ids[i], ids[(i + 1) % n])
    return sim


def hierarchical_quorum(n_orgs: int, per_org: int = 5,
                        passphrase: str = "test simulation network",
                        persist_dir: Optional[str] = None,
                        **config_kw) -> Simulation:
    """Tiered/org topology (ref Topologies::hierarchicalQuorum): the
    network is ``n_orgs`` organizations of ``per_org`` validators each.

    Quorum structure (same symmetric qset on every validator): the
    top level requires a byzantine-safe majority of ORGS (inner sets),
    each org an internal 2f+1 of its members — the two-tier shape real
    networks (and the reference's hierarchicalQuorum) use.

    Connectivity is deliberately sparser than core-N so partitions mean
    something: full mesh inside each org, a full mesh between org
    leaders (member 0), plus each org's member 1 linked to the NEXT
    org's leader so losing one leader cannot isolate an org.
    """
    assert n_orgs >= 2 and per_org >= 1
    n = n_orgs * per_org
    sim = Simulation(network_passphrase=passphrase)
    seeds = _seeds(n)
    ids = _ids(seeds)
    orgs = [ids[o * per_org:(o + 1) * per_org] for o in range(n_orgs)]
    org_sets = [
        {"threshold": per_org - (per_org - 1) // 3, "validators": members}
        for members in orgs]
    qset = {"threshold": n_orgs - (n_orgs - 1) // 3,
            "validators": [], "inner_sets": org_sets}
    for i, s in enumerate(seeds):
        sim.add_node(s, qset, node_dir=_node_dir(persist_dir, i),
                     **config_kw)
    for o, members in enumerate(orgs):
        for i in range(per_org):
            for j in range(i + 1, per_org):
                sim.add_connection(members[i], members[j])
        next_org = orgs[(o + 1) % n_orgs]
        if per_org >= 2:
            sim.add_connection(members[1], next_org[0])
    leaders = [members[0] for members in orgs]
    for i in range(n_orgs):
        for j in range(i + 1, n_orgs):
            sim.add_connection(leaders[i], leaders[j])
    return sim
