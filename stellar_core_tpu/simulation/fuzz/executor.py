"""Schedule executor: compile the IR against the chaos engine and run
it under the full oracle stack.

Every run goes through ``simulation.chaos.run_scenario`` and therefore
inherits the whole safety contract: no forks among honest survivors
(header chain + bucket hash), two-ledger convergence after heal within
the schedule's ``converge_timeout``, time-to-heal, INVARIANT_CHECKS
(sim nodes run ``[".*"]``; a violation raises out of the close), the
unfired-script oracle, and — with traffic phases — loadgen admission
accounting.  On top of that the executor adds the fuzzer's own two:

- ``failure_fingerprint`` — a deterministic hash over the failure
  class + per-node externalize record + first divergence, computed
  from the forensics dump (itself byte-stable across same-seed
  reruns).  The persisted repro's replay-identity check compares THIS,
  so "reproduces" means the same failure at the same slots, not just
  any red run.
- ``novelty`` — a quantized signature over what the run DID (ledgers
  closed, chaos counter profile, heal time bucket, traffic statuses,
  topology/event shape): the corpus-retention signal that keeps the
  campaign spending budget on interleavings it hasn't seen.
"""
from __future__ import annotations

import json
import os
import tempfile
from typing import Callable, Dict, List, Optional

from ...crypto import sha256
from ..chaos import ScenarioFailure, run_scenario
from . import schedule as S


def _compile_events(sched: dict, ids: List[bytes]) -> List[tuple]:
    """IR events -> run_scenario (t, label, fn(chaos)) triples."""
    out = []
    for ev in sched.get("events", []):
        kind = ev["kind"]
        t = float(ev["t"])
        if kind == "partition":
            groups = [[ids[i] for i in g] for g in ev["groups"]]
            fn = lambda c, g=groups: c.partition(g)
        elif kind == "heal":
            fn = lambda c: c.heal()
        elif kind == "clear_links":
            fn = lambda c: c.clear_links()
        elif kind == "flaky":
            victims = [ids[i] for i in ev.get("victims", [])]

            def fn(c, vs=victims, ev=ev):
                for v in vs:
                    for a, b in c.sim.topology:
                        if v in (a, b):
                            c.set_link(
                                a, b, drop=float(ev.get("drop", 0.0)),
                                damage=float(ev.get("damage", 0.0)),
                                duplicate=float(
                                    ev.get("duplicate", 0.0)))
        elif kind == "lag":
            fn = lambda c, v=ids[ev["victim"]], ev=ev: c.lag(
                v, float(ev.get("latency", 1.0)))
        elif kind == "unlag":
            fn = lambda c, v=ids[ev["victim"]]: c.lag(v, 0.0)
        elif kind == "crash":
            fn = lambda c, v=ids[ev["victim"]]: c.crash(v)
        elif kind == "restore":
            fn = lambda c, v=ids[ev["victim"]]: c.restore(v)
        elif kind == "equivocate":
            fn = lambda c, v=ids[ev["victim"]]: c.equivocate(v)
        elif kind == "silence":
            # selective forwarding: the victim keeps emitting its own
            # SCP traffic but relays nothing (the Byzantine-bridge leg
            # of the induced-fork recipe)
            def fn(c, v=ids[ev["victim"]]):
                c.byzantine.add(v)
                c.sim.nodes[v].overlay_manager.broadcast_message = \
                    lambda *a, **kw: None
        elif kind == "capture_scp":
            fn = lambda c, v=ids[ev["victim"]]: c.capture_scp(v)
        elif kind == "replay_stale":
            def fn(c, a=ids[ev["attacker"]], ev=ev):
                lcl = c.sim.nodes[a].ledger_manager.last_closed_seq()
                c.replay_stale(
                    a, max_age_slot=max(1, lcl - int(ev.get("age", 2))),
                    limit=int(ev.get("limit", 64)))
        else:  # pragma: no cover - validate_schedule rejects these
            raise S.ScheduleError(f"unknown event kind {kind!r}")
        out.append((t, f"{kind} {_ev_brief(ev)}".strip(), fn))
    return out


def _ev_brief(ev: dict) -> str:
    parts = [f"{k}={ev[k]}" for k in sorted(ev)
             if k not in ("kind", "t", "groups", "victims")]
    return " ".join(parts)


def _canon(doc) -> bytes:
    return json.dumps(doc, sort_keys=True,
                      separators=(",", ":")).encode()


def failure_fingerprint(failure_class: str,
                        forensics: Optional[dict]) -> str:
    """Deterministic identity of one failure: the class plus the
    divergence shape and per-node externalize record from the
    forensics dump.  Pure function of run state — a same-seed rerun
    reproduces it byte-for-byte."""
    material = {
        "class": failure_class,
        "divergence": (forensics or {}).get("divergence"),
        "first": (forensics or {}).get("first_divergence"),
        "externalized": (forensics or {}).get("per_node_externalized"),
    }
    return sha256(_canon(material)).hex()


def novelty_signature(sched: dict, result: dict) -> str:
    """Quantized behavior signature for corpus retention.  Buckets are
    coarse on purpose: two runs differing only in microsecond timing
    should collide, two runs exercising different fault/traffic
    machinery should not."""
    rep = result.get("report") or {}
    counters = rep.get("counters") or {}
    traffic = rep.get("traffic") or {}
    material = {
        "topology": sched["topology"],
        "kinds": sorted({e["kind"] for e in sched.get("events", [])}),
        "class": result.get("failure_class"),
        "ledgers": (rep.get("ledgers_closed") or 0) // 4,
        "heal_bucket": int(float(rep.get("time_to_heal_s") or 0.0) / 5),
        "counter_profile": sorted(
            k for k, v in counters.items() if v > 0),
        "traffic_statuses": sorted(
            (traffic.get("status_totals") or {}).items()),
        "banned": (traffic.get("queue") or {}).get("banned", 0) > 0,
    }
    return sha256(_canon(material)).hex()[:16]


def run_schedule(sched: dict, persist_dir: Optional[str] = None,
                 forensics_dir: Optional[str] = None) -> dict:
    """Execute one schedule under the full oracle stack.

    Returns a classified result dict:
    ``{"ok", "schedule_id", "failure_class", "failure_fingerprint",
    "fingerprint", "novelty", "report"|"error"}`` — never raises for
    an oracle failure (the campaign loop and ddmin need red runs as
    DATA); programming errors inside the fuzzer itself still raise.
    """
    S.validate_schedule(sched)
    sid = S.schedule_id(sched)
    ids = S.node_ids(sched["topology"])
    label = f"fuzz-{sid}"

    def _run(workdir: str) -> dict:
        fdir = forensics_dir or workdir
        make_sim = S.topology_factory(sched["topology"], workdir)
        events = _compile_events(sched, ids)
        try:
            rep = run_scenario(
                make_sim, int(sched["seed"]), events,
                float(sched["duration"]), label,
                converge_timeout=float(
                    sched.get("converge_timeout", 120.0)),
                forensics_dir=fdir,
                traffic=sched.get("traffic") or None)
        except ScenarioFailure as e:
            forensics = None
            if e.forensics_path and os.path.exists(e.forensics_path):
                with open(e.forensics_path, "r", encoding="utf-8") as f:
                    forensics = json.load(f)
            res = {
                "ok": False, "schedule_id": sid,
                "failure_class": e.failure_class,
                "failure_fingerprint": failure_fingerprint(
                    e.failure_class, forensics),
                "fingerprint": None,
                "error": str(e).splitlines()[0][:400],
            }
            res["novelty"] = novelty_signature(sched, res)
            return res
        except Exception as e:  # invariant violations, close crashes
            cls = f"crash:{type(e).__name__}"
            res = {
                "ok": False, "schedule_id": sid,
                "failure_class": cls,
                "failure_fingerprint": sha256(
                    cls.encode() + str(e)[:500].encode()).hex(),
                "fingerprint": None,
                "error": str(e).splitlines()[0][:400] if str(e)
                else type(e).__name__,
            }
            res["novelty"] = novelty_signature(sched, res)
            return res
        res = {
            "ok": True, "schedule_id": sid,
            "failure_class": None, "failure_fingerprint": None,
            "fingerprint": rep["fingerprint"],
            "report": {
                "ledgers_closed": rep["ledgers_closed"],
                "virtual_elapsed_s": rep["virtual_elapsed_s"],
                "time_to_heal_s": rep["time_to_heal_s"],
                "counters": rep["counters"],
                "fork_comparisons": rep["fork_comparisons"],
                "traffic": rep.get("traffic"),
            },
        }
        res["novelty"] = novelty_signature(sched, res)
        return res

    if persist_dir is not None:
        return _run(persist_dir)
    with tempfile.TemporaryDirectory(prefix="fuzz-sched-") as d:
        return _run(d)
