"""ddmin repro minimization: shrink a failing schedule to its
essential events, then shrink parameters, re-running the oracle stack
at every step.

The matcher is the failure CLASS (``ScenarioFailure.failure_class`` or
``crash:<ExcType>``), not the full failure fingerprint: a smaller
schedule legitimately forks at a different slot, but it must keep
failing the SAME oracle to count as the same bug.  The final minimized
schedule is re-run to record ITS fingerprint, and that pair (schedule,
expected class + fingerprint) is what ``write_repro`` persists to
``traces/`` — replaying the artifact must reproduce the exact
fingerprint, deterministically (``tools/fuzz_repro`` checks it).
"""
from __future__ import annotations

import json
import os
import re
from typing import Callable, Dict, List, Optional, Tuple

from . import schedule as S
from .executor import run_schedule

REPRO_SCHEMA = 1


# ---------------------------------------------------------------------------
# ddmin over (event, traffic-phase) atoms
# ---------------------------------------------------------------------------

def _atoms(sched: dict) -> List[tuple]:
    return ([("e", i) for i in range(len(sched.get("events", [])))]
            + [("p", i) for i in range(len(sched.get("traffic", [])))])


def _build(sched: dict, atoms: List[tuple]) -> dict:
    keep_e = {i for k, i in atoms if k == "e"}
    keep_p = {i for k, i in atoms if k == "p"}
    out = dict(sched)
    out["events"] = [e for i, e in enumerate(sched.get("events", []))
                     if i in keep_e]
    out["traffic"] = [p for i, p in enumerate(sched.get("traffic", []))
                      if i in keep_p]
    return out


class _Oracle:
    """Budgeted, memoized reproduces-the-class test."""

    def __init__(self, target_class: str, run: Callable[[dict], dict],
                 max_runs: int):
        self.target_class = target_class
        self.run = run
        self.max_runs = max_runs
        self.runs = 0
        self.cache: Dict[str, bool] = {}

    def __call__(self, sched: dict) -> bool:
        try:
            S.validate_schedule(sched)
        except S.ScheduleError:
            return False
        sid = S.schedule_id(sched)
        hit = self.cache.get(sid)
        if hit is not None:
            return hit
        if self.runs >= self.max_runs:
            return False  # budget exhausted: treat as non-reproducing
        self.runs += 1
        res = self.run(sched)
        ok = res.get("failure_class") == self.target_class
        self.cache[sid] = ok
        return ok


def _ddmin(atoms: List[tuple], test: Callable[[List[tuple]], bool]
           ) -> List[tuple]:
    """Classic Zeller/Hildebrandt ddmin to a 1-minimal atom subset."""
    n = 2
    while len(atoms) >= 2:
        chunk = max(1, len(atoms) // n)
        reduced = False
        for start in range(0, len(atoms), chunk):
            complement = atoms[:start] + atoms[start + chunk:]
            if complement and test(complement):
                atoms = complement
                n = max(n - 1, 2)
                reduced = True
                break
        if not reduced:
            if n >= len(atoms):
                break
            n = min(len(atoms), n * 2)
    return atoms


# ---------------------------------------------------------------------------
# parameter shrinking
# ---------------------------------------------------------------------------

def _shrink_candidates(sched: dict):
    """Yield (description, candidate) parameter-shrunk variants, each a
    single independent change (accepted shrinks re-enter the loop)."""
    # 1. duration down to the last event + slack
    tmax = max([e["t"] for e in sched.get("events", [])]
               + [p["t"] + p["duration"]
                  for p in sched.get("traffic", [])] + [2.0])
    short = round(tmax + 3.0, 1)
    if short < sched["duration"]:
        cand = dict(sched)
        cand["duration"] = short
        yield ("duration", cand)
    # 2. traffic rates halved, phases shortened
    for i, p in enumerate(sched.get("traffic", [])):
        if p["rate"] > 1.0:
            cand = dict(sched)
            cand["traffic"] = list(sched["traffic"])
            cand["traffic"][i] = dict(p, rate=round(p["rate"] / 2, 1))
            yield (f"traffic[{i}].rate", cand)
        if p["duration"] > 2.0:
            cand = dict(sched)
            cand["traffic"] = list(sched["traffic"])
            cand["traffic"][i] = dict(
                p, duration=round(p["duration"] / 2, 1))
            yield (f"traffic[{i}].duration", cand)
    # 3. victim-set shrinking: drop one member of any list param
    for i, e in enumerate(sched.get("events", [])):
        if e["kind"] == "partition":
            for gi, g in enumerate(e["groups"]):
                if len(g) <= 1:
                    continue
                for vi in range(len(g)):
                    cand = dict(sched)
                    cand["events"] = list(sched["events"])
                    groups = [list(x) for x in e["groups"]]
                    groups[gi] = g[:vi] + g[vi + 1:]
                    cand["events"][i] = dict(e, groups=groups)
                    yield (f"events[{i}].groups[{gi}]", cand)
        elif e["kind"] == "flaky" and len(e.get("victims", [])) > 1:
            for vi in range(len(e["victims"])):
                cand = dict(sched)
                cand["events"] = list(sched["events"])
                cand["events"][i] = dict(
                    e, victims=(e["victims"][:vi]
                                + e["victims"][vi + 1:]))
                yield (f"events[{i}].victims", cand)
    # 4. validator-count shrinking (keep every referenced index valid)
    topo = sched["topology"]
    refs = _max_node_ref(sched)
    if topo["kind"] == "core" and topo["n"] > max(3, refs + 1):
        cand = dict(sched)
        cand["topology"] = dict(topo, n=topo["n"] - 1)
        thr = topo.get("threshold")
        if thr is not None and thr > topo["n"] - 1:
            cand["topology"]["threshold"] = topo["n"] - 1
        yield ("topology.n", cand)
    if topo["kind"] == "tiered" and topo["n_orgs"] > 2 \
            and (topo["n_orgs"] - 1) * topo["per_org"] > refs:
        cand = dict(sched)
        cand["topology"] = dict(topo, n_orgs=topo["n_orgs"] - 1)
        yield ("topology.n_orgs", cand)


def _max_node_ref(sched: dict) -> int:
    refs = [-1]
    for e in sched.get("events", []):
        for k in ("victim", "attacker"):
            if k in e:
                refs.append(int(e[k]))
        for g in e.get("groups", []):
            refs.extend(int(v) for v in g)
        refs.extend(int(v) for v in e.get("victims", []))
    return max(refs)


# ---------------------------------------------------------------------------
# the minimizer
# ---------------------------------------------------------------------------

def minimize_schedule(sched: dict, target_class: Optional[str] = None,
                      run: Callable[[dict], dict] = run_schedule,
                      max_runs: int = 48,
                      log: Optional[Callable[[str], None]] = None
                      ) -> Tuple[dict, dict]:
    """Shrink ``sched`` to a 1-minimal failing schedule.

    Returns ``(minimized, stats)`` where stats records the run budget
    spent and the atom counts before/after.  Raises ``ValueError``
    when the input schedule does not fail at all (nothing to
    minimize)."""
    say = log or (lambda s: None)
    first = run(sched)
    if first.get("ok"):
        raise ValueError(
            f"schedule {S.schedule_id(sched)} passes its oracles — "
            f"nothing to minimize")
    target = target_class or first["failure_class"]
    oracle = _Oracle(target, run, max_runs)
    oracle.cache[S.schedule_id(sched)] = \
        first["failure_class"] == target
    atoms0 = _atoms(sched)

    say(f"[ddmin] {len(atoms0)} atoms, class {target!r}")
    atoms = _ddmin(atoms0, lambda a: oracle(_build(sched, a)))
    cur = _build(sched, atoms)

    # parameter shrinking to fixpoint (budget-capped by the oracle)
    changed = True
    while changed and oracle.runs < max_runs:
        changed = False
        for what, cand in _shrink_candidates(cur):
            if oracle(cand):
                say(f"[shrink] {what}")
                cur = cand
                changed = True
                break

    # record the minimized schedule's OWN failure identity (the repro
    # artifact's replay-identity contract)
    final = run(cur)
    stats = {
        "target_class": target,
        "oracle_runs": oracle.runs + 2,
        "atoms_before": len(atoms0),
        "atoms_after": len(_atoms(cur)),
        "reproduces": final.get("failure_class") == target,
        "final_result": {k: final.get(k) for k in
                         ("failure_class", "failure_fingerprint",
                          "schedule_id", "error")},
    }
    return cur, stats


# ---------------------------------------------------------------------------
# repro artifacts (traces/)
# ---------------------------------------------------------------------------

def _slug(s: str) -> str:
    return re.sub(r"[^A-Za-z0-9_-]+", "-", s)[:40]


def write_repro(sched: dict, result: dict,
                out_dir: str = "traces",
                minimized_from: Optional[str] = None) -> str:
    """Persist one runnable repro artifact.  ``result`` must be the
    schedule's own (failing) run result: its class + fingerprint are
    the expectation ``tools.fuzz_repro`` replays against."""
    assert not result.get("ok"), "repro artifacts are for failures"
    doc = {
        "fuzz_repro_schema": REPRO_SCHEMA,
        "schedule": sched,
        "expect": {
            "failure_class": result["failure_class"],
            "failure_fingerprint": result["failure_fingerprint"],
        },
        "minimized_from": minimized_from,
    }
    os.makedirs(out_dir, exist_ok=True)
    name = (f"FUZZ_REPRO_{_slug(result['failure_class'])}_"
            f"{S.schedule_id(sched)}.json")
    path = os.path.join(out_dir, name)
    with open(path, "w", encoding="utf-8") as f:
        json.dump(doc, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def verify_repro(doc: dict,
                 run: Callable[[dict], dict] = run_schedule) -> dict:
    """Replay one repro doc and check replay identity.  Returns
    ``{"reproduced": bool, "expected": ..., "got": ...}``."""
    if doc.get("fuzz_repro_schema") != REPRO_SCHEMA:
        raise S.ScheduleError(
            f"unknown fuzz_repro_schema "
            f"{doc.get('fuzz_repro_schema')!r}")
    sched = doc.get("schedule")
    S.validate_schedule(sched)
    expect = doc.get("expect") or {}
    res = run(sched)
    got = {"failure_class": res.get("failure_class"),
           "failure_fingerprint": res.get("failure_fingerprint")}
    return {
        "reproduced": (not res.get("ok")
                       and got["failure_class"]
                       == expect.get("failure_class")
                       and got["failure_fingerprint"]
                       == expect.get("failure_fingerprint")),
        "expected": expect,
        "got": got,
        "error": res.get("error"),
    }
