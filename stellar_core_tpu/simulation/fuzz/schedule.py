"""Fault-schedule IR: a serializable chaos scenario, pure in one seed.

A schedule is plain JSON composing the chaos primitives (partition,
heal, crash/restore, lag, flaky links, Byzantine equivocation,
selective-forwarding silence, stale replay) with loadgen traffic
phases — exactly the vocabulary ``run_scenario`` executes, with node
references as INDICES into the topology's deterministic node order so
a schedule is meaningful without building a sim.

Everything the generator emits derives from one integer seed: the
topology (sampled from the core-N / tiered-org grid, up to the
100+-validator fleet), the event kinds, their times, victims and
parameters, and the traffic phases.  ``canonical_bytes`` is the
determinism contract's byte form: same seed => identical bytes
(asserted across PYTHONHASHSEED values by tests/test_fuzz_schedule.py).
"""
from __future__ import annotations

import json
import os
import random
from typing import Callable, Dict, List, Optional

from ...crypto import sha256

SCHEMA_VERSION = 1

# repro/schedule files larger than this are rejected unparsed: a fuzz
# artifact is a few KB of events, never megabytes (oversized-input
# hardening mirrors fuzzing.py's XDR harness limits)
MAX_SCHEDULE_BYTES = 256 * 1024

EVENT_KINDS = ("partition", "heal", "flaky", "clear_links", "lag",
               "unlag", "crash", "restore", "equivocate", "silence",
               "capture_scp", "replay_stale")

TRAFFIC_MODES = ("pay", "pretend", "mixed")

# generation profiles: how big a network and how long a window the
# campaign budget affords (the fuzz smoke stays on the small grid; the
# bench's fleet profile reaches the 100-validator tier)
PROFILES = {
    "smoke": {"topologies": [
        {"kind": "core", "n": 4},
        {"kind": "tiered", "n_orgs": 3, "per_org": 3},
    ], "duration": (12.0, 18.0), "max_events": 4, "traffic_max": 1},
    "default": {"topologies": [
        {"kind": "core", "n": 4},
        {"kind": "core", "n": 7},
        {"kind": "tiered", "n_orgs": 3, "per_org": 3},
        {"kind": "tiered", "n_orgs": 5, "per_org": 4},
    ], "duration": (14.0, 22.0), "max_events": 6, "traffic_max": 2},
    "fleet": {"topologies": [
        {"kind": "tiered", "n_orgs": 10, "per_org": 5},
        {"kind": "tiered", "n_orgs": 20, "per_org": 5},
        {"kind": "tiered", "n_orgs": 25, "per_org": 4},
    ], "duration": (10.0, 14.0), "max_events": 4, "traffic_max": 1},
}


class ScheduleError(ValueError):
    """A schedule (or repro file) failed validation."""


# ---------------------------------------------------------------------------
# canonical form + persistence
# ---------------------------------------------------------------------------

def canonical_bytes(sched: dict) -> bytes:
    """The schedule's canonical byte form: sorted keys, minimal
    separators, trailing newline — byte-identical across processes and
    PYTHONHASHSEED values (json.dumps(sort_keys=True) is insertion-
    order-free)."""
    return (json.dumps(sched, sort_keys=True,
                       separators=(",", ":")) + "\n").encode()


def schedule_id(sched: dict) -> str:
    return sha256(canonical_bytes(sched)).hex()[:16]


def save_schedule(sched: dict, path: str) -> str:
    validate_schedule(sched)
    os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
    with open(path, "wb") as f:
        f.write(canonical_bytes(sched))
    return path


def load_schedule(path: str) -> dict:
    """Load + validate one schedule/repro file; corrupted or oversized
    inputs raise ``ScheduleError`` (never a raw parse traceback — the
    repro tool's operator sees WHAT was wrong with the file)."""
    try:
        size = os.path.getsize(path)
    except OSError as e:
        raise ScheduleError(f"unreadable schedule file: {e}") from None
    if size > MAX_SCHEDULE_BYTES:
        raise ScheduleError(
            f"oversized schedule file: {size} bytes > "
            f"{MAX_SCHEDULE_BYTES} cap")
    with open(path, "rb") as f:
        raw = f.read()
    try:
        doc = json.loads(raw.decode("utf-8"))
    except (UnicodeDecodeError, json.JSONDecodeError) as e:
        raise ScheduleError(f"corrupted schedule file: {e}") from None
    sched = doc.get("schedule", doc) if isinstance(doc, dict) else doc
    validate_schedule(sched)
    return doc


# ---------------------------------------------------------------------------
# validation
# ---------------------------------------------------------------------------

def topology_size(topo: dict) -> int:
    if topo["kind"] == "core":
        return int(topo["n"])
    return int(topo["n_orgs"]) * int(topo["per_org"])


def _check(cond: bool, what: str) -> None:
    if not cond:
        raise ScheduleError(what)


def validate_schedule(sched: dict) -> None:
    _check(isinstance(sched, dict), "schedule must be a JSON object")
    _check(sched.get("fuzz_schema") == SCHEMA_VERSION,
           f"unknown fuzz_schema {sched.get('fuzz_schema')!r} "
           f"(expected {SCHEMA_VERSION})")
    _check(isinstance(sched.get("seed"), int), "seed must be an int")
    topo = sched.get("topology")
    _check(isinstance(topo, dict), "topology must be an object")
    kind = topo.get("kind")
    _check(kind in ("core", "tiered"), f"unknown topology kind {kind!r}")
    if kind == "core":
        _check(isinstance(topo.get("n"), int) and 2 <= topo["n"] <= 256,
               "core topology needs 2 <= n <= 256")
        thr = topo.get("threshold")
        _check(thr is None or (isinstance(thr, int)
                               and 1 <= thr <= topo["n"]),
               "core threshold out of range")
    else:
        _check(isinstance(topo.get("n_orgs"), int)
               and isinstance(topo.get("per_org"), int)
               and 2 <= topo["n_orgs"] <= 64
               and 1 <= topo["per_org"] <= 16,
               "tiered topology needs 2<=n_orgs<=64, 1<=per_org<=16")
    n = topology_size(topo)
    dur = sched.get("duration")
    _check(isinstance(dur, (int, float)) and 1.0 <= dur <= 600.0,
           "duration must be 1..600 virtual seconds")
    ct = sched.get("converge_timeout", 120.0)
    _check(isinstance(ct, (int, float)) and 1.0 <= ct <= 600.0,
           "converge_timeout must be 1..600 virtual seconds")

    def _idx(v, what):
        _check(isinstance(v, int) and 0 <= v < n,
               f"{what}: node index {v!r} out of range 0..{n - 1}")

    events = sched.get("events", [])
    _check(isinstance(events, list) and len(events) <= 64,
           "events must be a list of at most 64 entries")
    for ev in events:
        _check(isinstance(ev, dict), "event must be an object")
        _check(ev.get("kind") in EVENT_KINDS,
               f"unknown event kind {ev.get('kind')!r}")
        t = ev.get("t")
        _check(isinstance(t, (int, float)) and 0.0 <= t <= dur,
               f"event time {t!r} outside 0..duration")
        kind = ev["kind"]
        if kind == "partition":
            groups = ev.get("groups")
            _check(isinstance(groups, list) and len(groups) >= 2,
                   "partition needs >= 2 groups")
            seen: set = set()
            for g in groups:
                _check(isinstance(g, list) and g, "empty partition group")
                for v in g:
                    _idx(v, "partition")
                    _check(v not in seen,
                           f"node {v} in two partition groups")
                    seen.add(v)
        elif kind == "flaky":
            for v in ev.get("victims", []):
                _idx(v, "flaky")
            for p in ("drop", "damage", "duplicate"):
                x = ev.get(p, 0.0)
                _check(isinstance(x, (int, float)) and 0.0 <= x <= 1.0,
                       f"flaky {p} must be a probability")
        elif kind in ("lag", "unlag", "crash", "restore", "equivocate",
                      "silence", "capture_scp"):
            _idx(ev.get("victim"), kind)
            if kind == "lag":
                lat = ev.get("latency", 1.0)
                _check(isinstance(lat, (int, float))
                       and 0.0 <= lat <= 30.0,
                       "lag latency must be 0..30s")
        elif kind == "replay_stale":
            _idx(ev.get("attacker"), kind)
            age = ev.get("age", 2)
            _check(isinstance(age, int) and 1 <= age <= 1000,
                   "replay_stale age must be 1..1000 slots")

    traffic = sched.get("traffic", [])
    _check(isinstance(traffic, list) and len(traffic) <= 8,
           "traffic must be a list of at most 8 phases")
    prev_end = None
    for p in sorted(traffic, key=lambda p: p.get("t", 0.0)):
        _check(isinstance(p, dict), "traffic phase must be an object")
        _check(p.get("mode", "pay") in TRAFFIC_MODES,
               f"unknown traffic mode {p.get('mode')!r}")
        t, d = p.get("t"), p.get("duration")
        _check(isinstance(t, (int, float)) and 0.0 <= t <= dur,
               "traffic phase start outside 0..duration")
        _check(isinstance(d, (int, float)) and 0.5 <= d <= dur,
               "traffic phase duration out of range")
        rate = p.get("rate")
        _check(isinstance(rate, (int, float)) and 0.0 < rate <= 1000.0,
               "traffic rate must be 0..1000 tx/s")
        if prev_end is not None:
            _check(t >= prev_end, "overlapping traffic phases")
        prev_end = t + d


# ---------------------------------------------------------------------------
# topology resolution
# ---------------------------------------------------------------------------

def node_ids(topo: dict) -> List[bytes]:
    """The topology's node ids WITHOUT building a sim (ids are a pure
    function of the node index — simulation._seeds)."""
    from ..simulation import _ids, _seeds

    return _ids(_seeds(topology_size(topo)))


def topology_factory(topo: dict,
                     persist_dir: Optional[str]) -> Callable:
    """make_sim() for one schedule's topology.  Consensus must free-run
    (MANUAL_CLOSE=False) for schedules to mean anything."""
    from ..simulation import core, hierarchical_quorum

    if topo["kind"] == "core":
        return lambda: core(
            int(topo["n"]), threshold=topo.get("threshold"),
            persist_dir=persist_dir, MANUAL_CLOSE=False)
    return lambda: hierarchical_quorum(
        int(topo["n_orgs"]), int(topo["per_org"]),
        persist_dir=persist_dir, MANUAL_CLOSE=False)


# ---------------------------------------------------------------------------
# the seeded generator
# ---------------------------------------------------------------------------

def _rng_for(seed: int) -> random.Random:
    return random.Random(int.from_bytes(
        sha256(b"fuzz-schedule-%d" % seed), "big"))


def generate_schedule(seed: int, profile: str = "default") -> dict:
    """One schedule, pure in ``seed``: every choice below draws from a
    seed-derived RNG and nothing else.  Generated schedules are meant
    to PASS on healthy topologies — the fuzzer's job is to find the
    interleaving where the implementation breaks its own oracles, not
    to script guaranteed forks (that's ``known_bad_schedule``)."""
    prof = PROFILES[profile]
    rng = _rng_for(seed)
    topo = dict(rng.choice(prof["topologies"]))
    n = topology_size(topo)
    duration = round(rng.uniform(*prof["duration"]), 1)
    ids = list(range(n))

    events: List[dict] = []
    crashed: set = set()
    n_events = rng.randint(1, prof["max_events"])
    # event times leave the first 2s for the network to start closing
    # and the last 3s for late faults to bite before the heal epilogue
    times = sorted(round(rng.uniform(2.0, max(3.0, duration - 3.0)), 1)
                   for _ in range(n_events))
    for t in times:
        kind = rng.choice(
            ("partition", "flaky", "lag", "crash", "equivocate",
             "replay_chain", "clear_links", "heal"))
        if kind == "partition":
            cut = rng.sample(ids, max(1, n // 3))
            keep = [i for i in ids if i not in cut]
            events.append({"t": t, "kind": "partition",
                           "groups": [keep, cut]})
            if rng.random() < 0.7:
                events.append({
                    "t": round(min(duration,
                                   t + rng.uniform(3.0, 8.0)), 1),
                    "kind": "heal"})
        elif kind == "flaky":
            events.append({
                "t": t, "kind": "flaky",
                "victims": sorted(rng.sample(ids, max(1, n // 4))),
                "drop": round(rng.uniform(0.01, 0.05), 3),
                "damage": round(rng.uniform(0.0, 0.02), 3),
                "duplicate": round(rng.uniform(0.0, 0.02), 3)})
            if rng.random() < 0.7:
                events.append({
                    "t": round(min(duration,
                                   t + rng.uniform(3.0, 8.0)), 1),
                    "kind": "clear_links"})
        elif kind == "lag":
            v = rng.choice(ids)
            events.append({"t": t, "kind": "lag", "victim": v,
                           "latency": round(rng.uniform(0.5, 3.0), 2)})
            if rng.random() < 0.7:
                events.append({
                    "t": round(min(duration,
                                   t + rng.uniform(3.0, 8.0)), 1),
                    "kind": "unlag", "victim": v})
        elif kind == "crash" and len(crashed) < max(1, (n - 1) // 3):
            v = rng.choice([i for i in ids if i not in crashed])
            crashed.add(v)
            events.append({"t": t, "kind": "crash", "victim": v})
            if rng.random() < 0.7:
                events.append({
                    "t": round(min(duration,
                                   t + rng.uniform(4.0, 9.0)), 1),
                    "kind": "restore", "victim": v})
                crashed.discard(v)
        elif kind == "equivocate":
            # Byzantine minority only: the generator probes the honest
            # majority's tolerance, never scripts an unsafe quorum
            byz = rng.sample(ids, max(1, (n - 1) // 4))
            for v in byz:
                events.append({"t": t, "kind": "equivocate",
                               "victim": v})
        elif kind == "replay_chain":
            a = rng.choice(ids)
            events.append({"t": min(t, 1.0), "kind": "capture_scp",
                           "victim": a})
            events.append({
                "t": round(max(t, min(duration - 1.0, 14.0)), 1),
                "kind": "replay_stale", "attacker": a,
                "age": rng.randint(2, 4),
                "limit": rng.randint(16, 64)})
        elif kind == "clear_links":
            events.append({"t": t, "kind": "clear_links"})
        elif kind == "heal":
            events.append({"t": t, "kind": "heal"})

    traffic: List[dict] = []
    if prof["traffic_max"] and rng.random() < 0.75:
        t_cursor = round(rng.uniform(0.5, 2.0), 1)
        for _ in range(rng.randint(1, prof["traffic_max"])):
            d = round(rng.uniform(4.0, min(8.0, duration - 2.0)), 1)
            if t_cursor + d > duration:
                break
            traffic.append({
                "t": t_cursor, "duration": d,
                "mode": rng.choice(("pay", "pay", "pretend", "mixed")),
                "rate": round(rng.uniform(2.0, 8.0), 1),
                "dex_percent": rng.choice((30, 50))})
            t_cursor = round(t_cursor + d + rng.uniform(0.5, 2.0), 1)

    # canonical event order: by time, then kind (stable across reruns)
    events.sort(key=lambda e: (e["t"], e["kind"]))
    sched = {
        "fuzz_schema": SCHEMA_VERSION,
        "seed": seed,
        "profile": profile,
        "topology": topo,
        "duration": duration,
        "converge_timeout": 150.0 if topology_size(topo) >= 50 else 90.0,
        "events": events,
        "traffic": traffic,
    }
    validate_schedule(sched)
    return sched


def known_bad_schedule(seed: int = 14, noise: bool = True) -> dict:
    """The injected known-bad: a deliberately-unsafe core-4 (threshold
    2 — sub-intersecting quorums, the ``run_induced_fork`` recipe as
    IR) where one node equivocates, relays nothing (silence), and the
    honest nodes are partitioned around it.  Those three events
    deterministically fork the network; the ``noise`` events are
    harmless chaff the ddmin minimizer must strip away."""
    essential = [
        {"t": 2.0, "kind": "equivocate", "victim": 1},
        {"t": 2.0, "kind": "silence", "victim": 1},
        {"t": 3.0, "kind": "partition", "groups": [[2], [0, 3]]},
    ]
    chaff = [
        {"t": 4.0, "kind": "lag", "victim": 3, "latency": 0.4},
        {"t": 6.0, "kind": "unlag", "victim": 3},
        {"t": 5.0, "kind": "flaky", "victims": [0], "drop": 0.01,
         "damage": 0.0, "duplicate": 0.01},
        {"t": 7.0, "kind": "clear_links"},
    ] if noise else []
    events = sorted(essential + chaff,
                    key=lambda e: (e["t"], e["kind"]))
    sched = {
        "fuzz_schema": SCHEMA_VERSION,
        "seed": seed,
        "profile": "known-bad",
        "topology": {"kind": "core", "n": 4, "threshold": 2},
        "duration": 16.0,
        "converge_timeout": 30.0,
        "events": events,
        "traffic": [],
    }
    validate_schedule(sched)
    return sched
