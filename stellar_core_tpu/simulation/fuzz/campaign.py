"""The fuzz campaign loop: generate -> run -> retain novel -> minimize
failures -> persist repros.

Corpus retention is novelty-driven: every run's quantized behavior
signature (``executor.novelty_signature``) is checked against the
signatures already seen; only runs that did something NEW keep their
schedule in the corpus.  That spends the budget on unexplored
interleavings instead of re-proving the same partition/heal shape
forever, and the retained set doubles as the bench's corpus stats.

Failures are minimized with ddmin (budget-capped) and persisted to
``traces/`` as runnable repro artifacts; a failure whose minimized
schedule does NOT reproduce its class is itself a campaign error
(``non_reproducing``) — the red-flag the verify_green fuzz smoke
gates on.
"""
from __future__ import annotations

import os
import time as _wall
from typing import Callable, Dict, List, Optional

from . import schedule as S
from .executor import run_schedule
from .minimize import minimize_schedule, write_repro


class FuzzCampaign:
    def __init__(self, seed0: int, profile: str = "default",
                 schedules: int = 10,
                 wall_budget_s: Optional[float] = None,
                 corpus_dir: Optional[str] = None,
                 traces_dir: str = "traces",
                 minimize_budget: int = 32,
                 run: Callable[[dict], dict] = run_schedule,
                 log: Optional[Callable[[str], None]] = None):
        self.seed0 = int(seed0)
        self.profile = profile
        self.schedules = int(schedules)
        self.wall_budget_s = wall_budget_s
        self.corpus_dir = corpus_dir
        self.traces_dir = traces_dir
        self.minimize_budget = int(minimize_budget)
        self._run = run
        self._log = log or (lambda s: None)
        self.novelty_seen: Dict[str, int] = {}
        self.results: List[dict] = []
        self.failures: List[dict] = []

    def run(self) -> dict:
        # the campaign LOOP runs on wall time by design: its budget and
        # schedules/hour stats are operator-facing harness numbers.
        # Nothing here feeds a schedule or a verdict — each run's
        # replay identity is a pure function of the schedule's seed.
        # detlint: allow(det-wallclock)
        t0 = _wall.monotonic()
        executed = 0
        retained = 0
        for i in range(self.schedules):
            if self.wall_budget_s is not None and \
                    _wall.monotonic() - t0 > self.wall_budget_s:  # detlint: allow(det-wallclock)
                self._log(f"[campaign] wall budget exhausted after "
                          f"{executed} schedules")
                break
            seed = self.seed0 + i
            sched = S.generate_schedule(seed, self.profile)
            res = self._run(sched)
            executed += 1
            self.results.append(res)
            novel = res["novelty"] not in self.novelty_seen
            self.novelty_seen[res["novelty"]] = \
                self.novelty_seen.get(res["novelty"], 0) + 1
            status = "FAIL" if not res["ok"] else \
                ("new" if novel else "seen")
            self._log(f"[campaign] seed {seed} "
                      f"{S.schedule_id(sched)}: {status} "
                      f"({res.get('failure_class') or 'pass'})")
            if novel and self.corpus_dir:
                retained += 1
                S.save_schedule(sched, os.path.join(
                    self.corpus_dir,
                    f"corpus_{S.schedule_id(sched)}.json"))
            if not res["ok"]:
                self._handle_failure(seed, sched, res)
        wall = _wall.monotonic() - t0  # detlint: allow(det-wallclock)
        return {
            "profile": self.profile,
            "seed0": self.seed0,
            "schedules_requested": self.schedules,
            "schedules_executed": executed,
            "wall_s": round(wall, 2),
            "schedules_per_hour": round(executed / wall * 3600.0, 1)
            if wall > 0 else None,
            "unique_novelty": len(self.novelty_seen),
            "retained": retained,
            "failures": self.failures,
            "failure_count": len(self.failures),
        }

    def _handle_failure(self, seed: int, sched: dict,
                        res: dict) -> None:
        self._log(f"[campaign] minimizing seed {seed} "
                  f"({res['failure_class']})")
        entry = {
            "seed": seed,
            "schedule_id": res["schedule_id"],
            "failure_class": res["failure_class"],
            "failure_fingerprint": res["failure_fingerprint"],
        }
        try:
            mini, stats = minimize_schedule(
                sched, target_class=res["failure_class"],
                run=self._run, max_runs=self.minimize_budget,
                log=self._log)
            entry["minimized"] = {
                "schedule_id": S.schedule_id(mini),
                "atoms_before": stats["atoms_before"],
                "atoms_after": stats["atoms_after"],
                "oracle_runs": stats["oracle_runs"],
                "reproduces": stats["reproduces"],
            }
            if stats["reproduces"]:
                entry["repro_path"] = write_repro(
                    mini, stats["final_result"] | {"ok": False},
                    out_dir=self.traces_dir,
                    minimized_from=res["schedule_id"])
            else:
                entry["non_reproducing"] = True
        except Exception as e:  # minimizer bugs must not kill the run
            entry["minimize_error"] = f"{type(e).__name__}: {e}"
            entry["non_reproducing"] = True
        self.failures.append(entry)
