"""Seeded fault-schedule fuzzer over the chaos engine (ISSUE 20).

The subsystem has four parts, one module each:

- ``schedule``  — the serializable schedule IR (JSON), its validator,
  canonical byte form, the pure-function-of-one-seed generator, and
  the hand-built known-bad schedule the minimizer tests shrink.
- ``executor``  — compile a schedule against the chaos engine and run
  it under the full oracle stack (no-fork, convergence, time-to-heal,
  invariants, traffic accounting), returning a classified result with
  a deterministic failure fingerprint and a novelty signature.
- ``minimize``  — event-subset ddmin then parameter shrinking over a
  failing schedule, re-running the oracles at every step, plus the
  repro artifact format ``tools/fuzz_repro`` replays.
- ``campaign``  — the corpus loop: generate, run, retain novel
  schedules, minimize + persist failures, aggregate stats.
"""
from .schedule import (  # noqa: F401
    SCHEMA_VERSION, ScheduleError, canonical_bytes, generate_schedule,
    known_bad_schedule, load_schedule, save_schedule, schedule_id,
    validate_schedule,
)
from .executor import run_schedule  # noqa: F401
from .minimize import minimize_schedule, write_repro  # noqa: F401
from .campaign import FuzzCampaign  # noqa: F401
