"""Chaos engine: deterministic fault injection over simulated networks.

Everything here is driven by ONE chaos seed: per-link fault RNGs derive
from it (sha256(seed || endpoints || epoch)), scenario event times are
virtual-clock timers, and Byzantine behavior is scripted — so a chaos
run is a pure function of (topology, scenario, seed) and re-running it
reproduces the exact same per-node ledger-hash sequences.  That
determinism is itself asserted (``fingerprint`` + the seed-determinism
tests): a heisen-failure under chaos would be worthless evidence.

Fault taxonomy (ref the reference's LoopbackPeer damage knobs +
Simulation-based HerderTests, scaled into scripted scenarios):

- **link faults** — per-direction drop/damage/duplicate probabilities
  and latency on loopback links (``LinkChaos`` in overlay/peer.py).
  Drop/damage/duplicate break the authenticated MAC sequence exactly
  like a torn TCP stream, so connections die and the engine's link
  maintenance re-dials them (connection churn is part of the chaos).
- **partitions** — ``partition(groups)`` cuts every link crossing group
  boundaries (total deterministic loss, counted ``overlay.chaos.cut``);
  ``heal()`` restores wiring and starts the time-to-heal stopwatch.
- **crash / kill-restore** — ``crash(node)`` tears the Application down
  mid-flight (shared clock survives, on-disk state survives);
  ``restore(node)`` rebuilds from disk via the restart-from-state path
  and re-wires its topology links.
- **laggards** — ``lag(node, seconds)`` adds symmetric latency to every
  link of one node.
- **Byzantine actors** — ``equivocate(node)`` wraps a captured
  validator's broadcast so every SCP emission is accompanied by a
  conflicting variant (same slot, same txSetHash, bumped closeTime)
  signed with the node's real key, sent to disjoint halves of its
  peers; ``replay_stale(attacker, ...)`` re-floods envelopes captured
  rounds ago (honest nodes must discard them via the herder's slot
  bracket, not re-grow SCP state).

Safety oracle after every scenario: zero forks among honest survivors
(header-chain AND bucket-hash agreement via ``Simulation.assert_no_forks``),
no invariant violations (sim nodes run ``INVARIANT_CHECKS=[".*"]`` — a
violation crashes the close and therefore the scenario), and liveness:
the surviving quorum kept closing and the network converged after the
faults cleared (``time_to_heal``).
"""
from __future__ import annotations

import json
import os
import random
import time as _wall
from typing import Callable, Dict, List, Optional, Tuple

from ..crypto import sha256
from ..overlay.peer import LinkChaos, PeerState
from ..utils.clock import VirtualTimer
from ..xdr import overlay_types as O
from ..xdr import types as T
from .simulation import Simulation


class ScenarioFailure(AssertionError):
    """Typed oracle failure raised by ``run_scenario``.

    ``failure_class`` names WHICH oracle tripped (``fork``,
    ``convergence-timeout``, ``unfired-script``, ``traffic``) — the
    fuzzer's ddmin minimizer shrinks against the CLASS (same oracle
    keeps failing) while the full failure fingerprint stays the
    replay-identity check for the persisted repro."""

    def __init__(self, failure_class: str, message: str,
                 forensics_path: Optional[str] = None):
        super().__init__(message)
        self.failure_class = failure_class
        self.forensics_path = forensics_path


class LinkPolicy:
    """The engine's intended fault state for one (a, b) link; re-applied
    whenever link maintenance re-dials the pair."""

    __slots__ = ("drop", "damage", "duplicate", "latency", "cut")

    def __init__(self):
        self.drop = 0.0
        self.damage = 0.0
        self.duplicate = 0.0
        self.latency = 0.0
        self.cut = False

    def active(self) -> bool:
        return bool(self.cut or self.drop or self.damage
                    or self.duplicate or self.latency)


class ChaosEngine:
    """Seeded fault scheduler over one ``Simulation``."""

    MAINTENANCE_PERIOD = 1.0  # virtual seconds between re-dial sweeps

    def __init__(self, sim: Simulation, seed: int):
        self.sim = sim
        self.seed = int(seed)
        self.rng = random.Random(self.seed)
        self.byzantine: set = set()
        self.policies: Dict[Tuple[bytes, bytes], LinkPolicy] = {}
        self._link_epoch: Dict[Tuple[bytes, bytes], int] = {}
        self.reconnects = 0
        self.equivocations = 0
        self.replayed = 0
        self.events: List[Tuple[float, str]] = []
        # node -> seq -> (virtual time, wall time) of local externalize
        self.extern_times: Dict[bytes, Dict[int, Tuple[float, float]]] = {}
        # node -> seq -> header hash at externalize (the live fork/
        # determinism record; the DB header chain is the post-hoc oracle)
        self.extern_hashes: Dict[bytes, Dict[int, bytes]] = {}
        self._capture: List = []  # (slot, envelope) log for stale replay
        self._timers: List[VirtualTimer] = []
        self._maint_timer: Optional[VirtualTimer] = None
        # virtual time the last fault was cleared (heal/unlag/restore) —
        # the time-to-heal stopwatch's zero
        self.last_clear_time: float = sim.clock.now()
        for nid in sim.nodes:
            self._hook_node(nid)

    # -- bookkeeping ---------------------------------------------------------

    def log_event(self, what: str) -> None:
        self.events.append((round(self.sim.clock.now(), 3), what))

    def _hook_node(self, nid: bytes) -> None:
        app = self.sim.nodes[nid]
        times = self.extern_times.setdefault(nid, {})
        hashes = self.extern_hashes.setdefault(nid, {})

        def on_ext(slot, sv, app=app, times=times, hashes=hashes):
            lm = app.ledger_manager
            if lm.last_closed_seq() >= slot:
                times.setdefault(
                    slot, (self.sim.clock.now(), _wall.monotonic()))
                if lm.last_closed_seq() == slot:
                    hashes.setdefault(slot, lm.last_closed_hash())

        app.herder.on_externalized.append(on_ext)

    def _link_rng(self, a: bytes, b: bytes) -> random.Random:
        epoch = self._link_epoch.get((a, b), 0)
        self._link_epoch[(a, b)] = epoch + 1
        material = sha256(b"chaos-link-%d-%d" % (self.seed, epoch) + a + b)
        return random.Random(int.from_bytes(material, "big"))

    def _key(self, a: bytes, b: bytes) -> Tuple[bytes, bytes]:
        """Canonical (a, b) orientation: the one the topology recorded."""
        return (b, a) if (b, a) in self.sim.topology else (a, b)

    def _policy(self, a: bytes, b: bytes) -> LinkPolicy:
        return self.policies.setdefault(self._key(a, b), LinkPolicy())

    def _apply_policy(self, key: Tuple[bytes, bytes]) -> None:
        policy = self.policies.get(key)
        for peer in self.sim.link_peers(*key):
            if policy is None or not policy.active():
                peer.set_chaos(None)
                continue
            a, b = key
            peer.set_chaos(LinkChaos(
                self._link_rng(a, b), drop=policy.drop,
                damage=policy.damage, duplicate=policy.duplicate,
                latency=policy.latency, cut=policy.cut))

    # -- link faults ---------------------------------------------------------

    def set_link(self, a: bytes, b: bytes, drop: float = 0.0,
                 damage: float = 0.0, duplicate: float = 0.0,
                 latency: float = 0.0, cut: Optional[bool] = None) -> None:
        policy = self._policy(a, b)
        policy.drop = drop
        policy.damage = damage
        policy.duplicate = duplicate
        policy.latency = latency
        if cut is not None:
            policy.cut = cut
        self._apply_policy(self._key(a, b))

    def clear_links(self) -> None:
        """Drop every probabilistic fault and latency; cuts (partitions)
        persist until ``heal``."""
        for key, policy in self.policies.items():
            policy.drop = policy.damage = policy.duplicate = 0.0
            policy.latency = 0.0
            self._apply_policy(key)
        self.last_clear_time = self.sim.clock.now()
        self.log_event("links cleared")

    def partition(self, groups: List[List[bytes]]) -> None:
        """Cut every link whose endpoints land in different groups (nodes
        in no group keep all their links)."""
        side = {}
        for gi, group in enumerate(groups):
            for nid in group:
                side[nid] = gi
        n_cut = 0
        for a, b in self.sim.topology:
            if a in side and b in side and side[a] != side[b]:
                policy = self._policy(a, b)
                if not policy.cut:
                    policy.cut = True
                    n_cut += 1
                self._apply_policy(self._key(a, b))
        self.log_event(f"partition: {len(groups)} groups, {n_cut} links cut")

    def heal(self) -> None:
        for key, policy in self.policies.items():
            policy.cut = False
            self._apply_policy(key)
        self.last_clear_time = self.sim.clock.now()
        self.log_event("heal")

    def lag(self, nid: bytes, latency: float) -> None:
        for a, b in self.sim.topology:
            if nid in (a, b):
                policy = self._policy(a, b)
                policy.latency = latency
                self._apply_policy(self._key(a, b))
        if latency:
            self.log_event(f"lag {nid.hex()[:8]} by {latency}s")
        else:
            self.last_clear_time = self.sim.clock.now()
            self.log_event(f"unlag {nid.hex()[:8]}")

    # -- crash / restore -----------------------------------------------------

    def crash(self, nid: bytes) -> None:
        self.sim.crash_node(nid)
        self.log_event(f"crash {nid.hex()[:8]}")

    def restore(self, nid: bytes) -> None:
        self.sim.restart_node(nid)
        self._hook_node(nid)
        for key in self.policies:
            if nid in key:
                self._apply_policy(key)
        self.last_clear_time = self.sim.clock.now()
        self.log_event(f"restore {nid.hex()[:8]}")

    # -- link maintenance (reconnect churn) ---------------------------------

    def start_maintenance(self) -> None:
        """Periodically re-dial topology links whose connection died —
        drop/damage/duplicate faults break the authenticated MAC stream
        by design, so sustained probabilistic chaos NEEDS reconnection
        for the network to stay live (the churn is part of the test)."""
        if self._maint_timer is None:
            self._maint_timer = VirtualTimer(self.sim.clock, owner=self)
        self._arm_maintenance()

    def _arm_maintenance(self) -> None:
        t = self._maint_timer
        t.cancel()
        t.expires_from_now(self.MAINTENANCE_PERIOD)
        t.async_wait(self._maintain_links)

    def _maintain_links(self) -> None:
        self.maintain_links_once()
        self._arm_maintenance()

    def maintain_links_once(self) -> int:
        sim = self.sim
        redialed = 0
        for a, b in sim.topology:
            if sim.crashed.get(a) or sim.crashed.get(b):
                continue
            peers = sim.link_peers(a, b)
            dead = not peers or any(
                p.state == PeerState.CLOSING for p in peers)
            if not dead:
                continue
            for p in peers:
                if p.state != PeerState.CLOSING:
                    p.close("chaos re-dial")
            sim.links.pop((a, b), None)
            sim.links.pop((b, a), None)
            sim._wire(a, b)
            key = (a, b) if (a, b) in self.policies else (b, a)
            if key in self.policies:
                self._apply_policy(key)
            redialed += 1
        self.reconnects += redialed
        return redialed

    def stop(self) -> None:
        if self._maint_timer is not None:
            self._maint_timer.cancel()
        for t in self._timers:
            t.cancel()
        self.sim.clock.cancel_owner(self)

    # -- Byzantine actors ----------------------------------------------------

    def equivocate(self, nid: bytes) -> None:
        """Turn ``nid`` into an equivocator: every SCP emission goes out
        in two conflicting variants (original + closeTime-bumped value,
        both properly signed) to disjoint halves of its peers, bypassing
        the floodgate so the halves really do see different statements.
        Honest forwarding then spreads both network-wide."""
        app = self.sim.nodes[nid]
        self.byzantine.add(nid)
        engine = self

        def equivocating_broadcast(env, app=app):
            alt = engine._perturb_envelope(app, env)
            peers = sorted(app.overlay_manager.authenticated.values(),
                           key=lambda p: p.peer_id or b"")
            if alt is not None:
                engine.equivocations += 1
            for i, p in enumerate(peers):
                send = env if (alt is None or i % 2 == 0) else alt
                p.send_message(O.StellarMessage.make(
                    O.MessageType.SCP_MESSAGE, send))

        app.broadcast_scp_message = equivocating_broadcast
        self.log_event(f"equivocator {nid.hex()[:8]}")

    def _sign_statement(self, app, st):
        """Properly-signed envelope for a forged statement — through the
        node's OWN driver (the equivocator holds its real key), so the
        signed-body format lives in exactly one place."""
        env = T.SCPEnvelope.make(statement=st, signature=b"")
        app.herder.driver.sign_envelope(env)
        return env

    @staticmethod
    def _bump_value(value: bytes) -> Optional[bytes]:
        """A conflicting-but-valid variant of one consensus value: same
        tx set, closeTime+1 — passes every honest validity check while
        differing as a ballot/nomination value."""
        try:
            sv = T.StellarValue.decode(value)
        except Exception:
            return None
        return T.StellarValue.encode(sv._replace(closeTime=sv.closeTime + 1))

    def _perturb_envelope(self, app, env):
        """Build the conflicting twin of one emitted envelope (fresh
        statement + fresh signature; the original is never mutated)."""
        st = env.statement
        ST = T.SCPStatementType
        p = st.pledges
        try:
            if p.type == ST.SCP_ST_NOMINATE:
                nom = p.value
                votes = [self._bump_value(v) or v for v in nom.votes]
                accepted = [self._bump_value(v) or v
                            for v in nom.accepted]
                if votes == list(nom.votes) and \
                        accepted == list(nom.accepted):
                    return None
                pledges = T.SCPStatement.fields[2][1].make(
                    ST.SCP_ST_NOMINATE,
                    nom._replace(votes=votes, accepted=accepted))
            elif p.type == ST.SCP_ST_PREPARE:
                prep = p.value
                alt = self._bump_value(prep.ballot.value)
                if alt is None:
                    return None
                pledges = T.SCPStatement.fields[2][1].make(
                    ST.SCP_ST_PREPARE,
                    prep._replace(ballot=prep.ballot._replace(value=alt)))
            else:
                # CONFIRM/EXTERNALIZE equivocation would require the
                # node to have (claimed to have) accepted two commits —
                # emit a conflicting PREPARE-stage history instead by
                # leaving these untouched; nomination/prepare
                # equivocation is where split views are seeded
                return None
        except Exception:
            return None
        return self._sign_statement(
            app, st._replace(pledges=pledges))

    # -- stale replay --------------------------------------------------------

    def capture_scp(self, nid: bytes) -> None:
        """Record every envelope ``nid`` broadcasts (still delivering it
        normally) as future stale-replay ammunition."""
        app = self.sim.nodes[nid]
        orig = app.broadcast_scp_message
        engine = self

        def capturing_broadcast(env, orig=orig):
            engine._capture.append((env.statement.slotIndex, env))
            orig(env)

        app.broadcast_scp_message = capturing_broadcast

    def replay_stale(self, attacker: bytes, max_age_slot: int,
                     limit: int = 64) -> int:
        """Re-flood captured envelopes for slots <= ``max_age_slot`` from
        ``attacker``'s connections.  Honest nodes must shed them (herder
        slot bracket / floodgate) without re-growing SCP slot state."""
        app = self.sim.nodes[attacker]
        peers = sorted(app.overlay_manager.authenticated.values(),
                       key=lambda p: p.peer_id or b"")
        sent = 0
        for slot, env in self._capture:
            if slot > max_age_slot or sent >= limit:
                continue
            for p in peers:
                p.send_message(O.StellarMessage.make(
                    O.MessageType.SCP_MESSAGE, env))
            sent += 1
        self.replayed += sent
        self.log_event(f"stale replay: {sent} envelopes from "
                       f"{attacker.hex()[:8]}")
        return sent

    # -- aggregate counters --------------------------------------------------

    def chaos_counters(self) -> Dict[str, int]:
        out = {"dropped": 0, "damaged": 0, "duplicated": 0, "delayed": 0,
               "cut": 0}
        for app in self.sim.alive_nodes().values():
            for k in out:
                out[k] += app.metrics.counter(f"overlay.chaos.{k}").count
        out["reconnects"] = self.reconnects
        out["equivocations"] = self.equivocations
        out["stale_replayed"] = self.replayed
        out["stale_discarded"] = sum(
            app.metrics.counter("herder.scp.discarded").count
            for app in self.sim.alive_nodes().values())
        return out

    def honest_alive(self) -> List[bytes]:
        return [nid for nid in self.sim.alive_nodes()
                if nid not in self.byzantine]

    def fingerprint(self) -> str:
        """One hash over every honest node's externalized (seq, header
        hash) sequence — the chaos-seed determinism contract: the same
        (topology, scenario, seed) must reproduce this byte-for-byte."""
        h = sha256(b"".join(
            nid + seq.to_bytes(8, "big") + self.extern_hashes[nid][seq]
            for nid in sorted(self.honest_alive())
            for seq in sorted(self.extern_hashes.get(nid, {}))))
        return h.hex()


# ---------------------------------------------------------------------------
# network-wide forensic aggregator (ISSUE 14 tentpole part 4)
# ---------------------------------------------------------------------------

def first_hash_divergence(chaos: ChaosEngine) -> Optional[dict]:
    """First slot where two honest nodes externalized different header
    hashes — the fork's ground zero (None while no divergence)."""
    honest = sorted(chaos.honest_alive())
    seqs = sorted({s for n in honest
                   for s in chaos.extern_hashes.get(n, {})})
    for s in seqs:
        by_hash: Dict[str, List[str]] = {}
        for n in honest:
            h = chaos.extern_hashes.get(n, {}).get(s)
            if h is not None:
                by_hash.setdefault(h.hex()[:16], []).append(n.hex()[:8])
        if len(by_hash) > 1:
            return {"slot": s, "nodes": dict(sorted(by_hash.items()))}
    return None


def collect_forensics(sim: Simulation, chaos: ChaosEngine, label: str,
                      seed: int, reason: str) -> dict:
    """Merge every alive node's SCP timeline into one cross-node
    forensic record with first-divergence attribution: which node,
    which slot, which message.

    Attribution order: equivocation evidence (two mutually-unordered
    statements from one node for one slot, found by
    scp/timeline.find_equivocations over the merged exports) beats the
    raw externalized-hash divergence — the hash split is the SYMPTOM,
    the conflicting statement pair is the CAUSE and names its emitter.
    Everything here is a pure function of sim state and virtual time,
    so a same-seed rerun reproduces the dump byte-for-byte."""
    from ..scp.timeline import find_equivocations

    timelines = {}
    for nid in sorted(sim.alive_nodes()):
        app = sim.nodes[nid]
        timelines[nid.hex()[:8]] = app.herder.scp.timeline.export()
    extern = {
        nid.hex()[:8]: {str(s): h.hex()
                        for s, h in sorted(
                            chaos.extern_hashes.get(nid, {}).items())}
        for nid in sorted(chaos.extern_hashes)}
    divergence = first_hash_divergence(chaos)
    equivocations = find_equivocations(timelines)
    first: Optional[dict] = None
    if equivocations:
        e = equivocations[0]  # already sorted by (slot, node)
        first = {"via": "equivocation", "slot": e["slot"],
                 "node": e["node"],
                 "message": {"proto": e["proto"],
                             "statements": e["statements"]}}
    elif divergence is not None:
        first = {"via": "externalized-hash",
                 "slot": divergence["slot"],
                 "node": divergence["nodes"], "message": None}
    return {
        "forensics_schema": 1,
        "scenario": label,
        "seed": seed,
        "reason": reason,
        "nodes": {
            "honest": sorted(n.hex()[:8] for n in chaos.honest_alive()),
            "byzantine": sorted(n.hex()[:8] for n in chaos.byzantine),
            "crashed": sorted(n.hex()[:8] for n, dead
                              in sim.crashed.items() if dead)},
        "first_divergence": first,
        "divergence": divergence,
        "equivocations": equivocations,
        "per_node_externalized": extern,
        "chaos_events": [list(e) for e in chaos.events],
        "timelines": timelines,
    }


def dump_forensics(report: dict, out_dir: Optional[str] = None) -> str:
    """Persist one forensic record as FORENSICS_<scenario>_seed<N>.json
    (sorted keys, trailing newline — byte-identical across same-seed
    reruns)."""
    out_dir = out_dir or os.getcwd()
    os.makedirs(out_dir, exist_ok=True)
    path = os.path.join(
        out_dir, f"FORENSICS_{report['scenario']}_seed"
                 f"{report['seed']}.json")
    with open(path, "w", encoding="utf-8") as f:
        json.dump(report, f, indent=1, sort_keys=True)
        f.write("\n")
    return path


def run_induced_fork(make_sim: Callable[[], Simulation], seed: int,
                     duration: float = 60.0,
                     forensics_dir: Optional[str] = None) -> tuple:
    """Deliberately fork a deliberately-unsafe network and prove the
    forensics name the culprit: one equivocating Byzantine node on a
    sub-intersecting quorum (e.g. core-4 at threshold 2, where
    {victim, byzantine} is a full quorum) splits honest nodes onto
    conflicting values.  EXPECTS the fork: raises if none happens
    within ``duration`` virtual seconds; otherwise dumps the merged
    forensic record and returns (report, dump_path).

    This is the verify_green forensic smoke's engine: the dump's
    first_divergence must identify the equivocator and the forked
    slot, and a same-seed rerun must reproduce the dump bytes."""
    sim = make_sim()
    chaos = ChaosEngine(sim, seed)
    sim.start_all_nodes()
    while sim.crank():
        pass
    chaos.start_maintenance()
    rng = random.Random(int.from_bytes(
        sha256(b"induced-fork-%d" % seed), "big"))
    ids = sorted(sim.nodes)
    byz = rng.choice(ids)
    honest = [n for n in ids if n != byz]
    # the full Byzantine bridge: the node equivocates to its peers,
    # relays NOTHING across them (selective forwarding), and the
    # honest nodes are partitioned around it — each side can only
    # reach quorum WITH the bridge, on the bridge's conflicting values
    chaos.equivocate(byz)
    sim.nodes[byz].overlay_manager.broadcast_message = \
        lambda *a, **kw: None
    chaos.partition([[honest[0]], honest[1:]])
    clock = sim.clock
    t_end = clock.now() + duration
    div = None
    seen_externs = -1
    while clock.now() < t_end:
        if clock.crank(block=True) == 0 and \
                clock.next_deadline() is None:
            break
        n_ext = sum(len(v) for v in chaos.extern_hashes.values())
        if n_ext != seen_externs:
            seen_externs = n_ext
            div = first_hash_divergence(chaos)
            if div is not None:
                break
    chaos.stop()
    try:
        if div is None:
            raise AssertionError(
                f"induced-fork seed {seed}: no honest divergence within "
                f"{duration}s virtual — the unsafe quorum never split")
        rep = collect_forensics(
            sim, chaos, "induced_fork", seed,
            reason=f"scripted fork probe: header divergence at slot "
                   f"{div['slot']}")
        path = dump_forensics(rep, forensics_dir)
    finally:
        for nid in list(sim.alive_nodes()):
            sim.nodes[nid].stop_node()
    return rep, path


# ---------------------------------------------------------------------------
# scenario runner
# ---------------------------------------------------------------------------

def _percentiles(values: List[float]) -> Dict[str, float]:
    if not values:
        return {"p50": 0.0, "p99": 0.0, "max": 0.0}
    vs = sorted(values)

    def pct(p: float) -> float:
        i = min(len(vs) - 1, int(p * (len(vs) - 1) + 0.5))
        return vs[i]

    return {"p50": round(pct(0.50), 3), "p99": round(pct(0.99), 3),
            "max": round(vs[-1], 3)}


def _arm_traffic(sim: Simulation, chaos: ChaosEngine, traffic: List[dict],
                 events: List[tuple], phase_reports: List[dict],
                 label: str) -> tuple:
    """Seed loadgen accounts THROUGH consensus, then append each
    traffic phase to the scenario's event script (so phases share the
    unfired-script oracle and the chaos event log with every other
    fault).  Returns (LoadGenerator, merged events)."""
    from ..ledger.ledger_txn import LedgerTxn
    from .load_generator import LoadGenerator

    phases = sorted(traffic, key=lambda p: p["t"])
    prev_end = None
    for p in phases:
        assert p.get("mode", "pay") in ("pay", "pretend", "mixed"), p
        if prev_end is not None:
            assert p["t"] >= prev_end, \
                f"[{label}] overlapping traffic phases: {phases}"
        prev_end = p["t"] + p["duration"]

    assert sim.crank_until(lambda: sim.have_all_externalized(2), 120.0), \
        f"[{label}] network never started closing before traffic seeding"
    app0 = sim.nodes[sorted(sim.nodes)[0]]
    lg = LoadGenerator(app0)
    n_accounts = max(8, max(int(p.get("accounts", 0)) for p in phases))
    for env in lg.create_account_envelopes(n_accounts):
        assert app0.herder.recv_transaction(env) == 0

    def _applied(pub):
        def probe():
            with LedgerTxn(app0.ledger_manager.root) as ltx:
                e = ltx.load_account(pub)
                ltx.rollback()
            return e is not None
        return probe

    assert sim.crank_until(
        _applied(lg.accounts[-1].public_key().raw), 120.0), \
        f"[{label}] loadgen account seeding stalled"

    if any(p.get("mode") == "mixed" for p in phases):
        # staged DEX seeding through consensus: issuer create in its
        # own close, then trustlines, then funding (apply order inside
        # one ledger is hash-shuffled, so each stage must land first)
        for env in lg.create_dex_issuer_envelope():
            assert app0.herder.recv_transaction(env) == 0
        assert sim.crank_until(
            _applied(lg.dex_issuer.public_key().raw), 120.0), \
            f"[{label}] DEX issuer seeding stalled"
        for env in lg.setup_dex_envelopes() + lg.fund_dex_envelopes():
            assert app0.herder.recv_transaction(env) == 0
        target = max(a.ledger_manager.last_closed_seq()
                     for a in sim.alive_nodes().values()) + 2
        assert sim.crank_until(
            lambda: sim.have_all_externalized(target), 120.0), \
            f"[{label}] DEX trustline seeding stalled"

    # rate-mode generation batches land on the generator app's fair
    # scheduler; a single-node rig drains it in Application.crank, but
    # sim rigs crank the SHARED clock directly and never touch per-app
    # schedulers — so traffic needs its own deterministic pump (a
    # virtual timer, like every other scheduled piece of the scenario)
    pump_timer = VirtualTimer(sim.clock, owner=chaos)

    def pump():
        if not sim.crashed.get(sorted(sim.nodes)[0], False):
            while app0.scheduler.run_one():
                pass
        pump_timer.expires_from_now(0.5)
        pump_timer.async_wait(pump)

    pump()
    chaos._timers.append(pump_timer)

    def start_phase(c, p):
        _flush_phase_report(lg, phase_reports)
        lg.start_rate_run(
            mode=p.get("mode", "pay"), rate=float(p["rate"]),
            duration=float(p["duration"]),
            dex_percent=int(p.get("dex_percent", 50)))

    merged = list(events)
    for p in phases:
        elabel = (f"traffic {p.get('mode', 'pay')}@{p['rate']}tx/s "
                  f"for {p['duration']}s")
        merged.append((float(p["t"]), elabel,
                       lambda c, p=p: start_phase(c, p)))
    return lg, merged


def _flush_phase_report(lg, phase_reports: List[dict]) -> None:
    """Snapshot the finished rate run's accounting (one dict per
    completed phase; resets the generator so the next flush can never
    double-count)."""
    st = lg.rate_status()
    if "mode" in st:
        phase_reports.append({
            "mode": st["mode"], "rate": st["rate"],
            "ticks": st["ticks"], "submitted": st["submitted"],
            "status_counts": dict(sorted(st["status_counts"].items()))})
    lg._rate_state = None


def _traffic_oracle(sim: Simulation, traffic: List[dict],
                    phase_reports: List[dict], label: str) -> dict:
    """Traffic accounting contract: every phase started, and every
    submitted tx carries a recorded admission status (the submit and
    status counters increment together or the generator lost track of
    a tx).  Returns the report's ``traffic`` section, including the
    tx-queue overload counters — ban-set depth and aging/surge
    admission statuses (TRY_AGAIN_LATER=3, BANNED=4) — that overload
    scenarios assert against."""
    assert len(phase_reports) == len(traffic), \
        (f"[{label}] only {len(phase_reports)}/{len(traffic)} traffic "
         f"phases ran — phase timers must fire inside the duration")
    for rep in phase_reports:
        assert rep["submitted"] == sum(rep["status_counts"].values()), \
            f"[{label}] traffic accounting leak: {rep}"
    submitted_total = sum(r["submitted"] for r in phase_reports)
    expected = sum(float(p["rate"]) * float(p["duration"])
                   for p in traffic)
    if expected >= 2.0:
        assert submitted_total > 0, \
            (f"[{label}] traffic oracle: {expected:.0f} txs expected "
             f"but none submitted")
    queue = {"pending": 0, "banned": 0}
    for app in sim.alive_nodes().values():
        tq = app.herder.tx_queue
        queue["pending"] += app.metrics.counter(
            "herder.pending-txs.count").count
        queue["banned"] += len(set().union(*tq.banned)) if tq.banned \
            else 0
    statuses: Dict[str, int] = {}
    for rep in phase_reports:
        for k, v in rep["status_counts"].items():
            statuses[k] = statuses.get(k, 0) + v
    return {"phases": phase_reports,
            "submitted_total": submitted_total,
            "status_totals": dict(sorted(statuses.items())),
            "queue": queue}


def run_scenario(make_sim: Callable[[], Simulation], seed: int,
                 events: List[Tuple[float, str,
                                    Callable[[ChaosEngine], None]]],
                 duration: float, label: str,
                 converge_timeout: float = 120.0,
                 forensics_dir: Optional[str] = None,
                 traffic: Optional[List[dict]] = None) -> dict:
    """Run one scripted chaos scenario end to end and return its report.

    ``events`` is a list of (virtual-time offset, label, fn(chaos));
    after ``duration`` virtual seconds of free-running consensus under
    those faults the runner clears every remaining fault (heal + link
    clear + restore of still-crashed nodes), waits for the honest
    survivors to converge two more ledgers, and asserts the safety
    contract: no forks (header chain + bucket hash), convergence within
    ``converge_timeout`` virtual seconds.  An invariant violation or a
    crash anywhere in a close raises out of the crank and fails the
    scenario — those are P0s, not statistics.

    ``traffic`` makes loadgen rate mode a first-class scenario phase
    (ROADMAP item 6: load running THROUGH the faults): a list of
    ``{"t": offset, "duration": s, "mode": "pay"|"pretend"|"mixed",
    "rate": tx/s, "dex_percent": int}`` dicts.  Before the fault window
    the runner seeds generator accounts through real consensus (a
    direct ledger write on a live network would itself be a fork), arms
    each phase as a scripted event on one node's LoadGenerator, and the
    report gains a ``traffic`` section: per-phase submit/status
    accounting (asserted consistent: every submit has a recorded
    admission status) plus the tx-queue overload counters (pending
    depth, ban-set size, TRY_AGAIN_LATER/BANNED statuses — the aging
    and surge-lane evidence).  Phases must not overlap: one generator
    drives one rate run at a time.

    When any oracle FAILS (fork, convergence/heal timeout, unfired
    script, traffic accounting), the runner dumps the merged cross-node
    slot timeline with first-divergence attribution to
    ``FORENSICS_*.json`` under ``forensics_dir`` (cwd by default) and
    raises ``ScenarioFailure`` with the oracle's ``failure_class`` and
    the artifact path — a failing schedule becomes a readable timeline,
    not a rerun-and-guess.
    """
    sim = make_sim()
    chaos = ChaosEngine(sim, seed)
    sim.start_all_nodes()
    while sim.crank():
        pass  # handshakes settle at t=0
    chaos.start_maintenance()
    clock = sim.clock

    lg = None
    phase_reports: List[dict] = []
    if traffic:
        lg, events = _arm_traffic(sim, chaos, list(traffic),
                                  list(events), phase_reports, label)

    t0 = clock.now()
    for offset, elabel, fn in events:
        t = VirtualTimer(clock, owner=chaos)
        t.expires_from_now(max(0.0, (t0 + offset) - clock.now()))
        t.async_wait(lambda fn=fn, elabel=elabel: (
            chaos.log_event(f"event: {elabel}"), fn(chaos)))
        chaos._timers.append(t)

    t_end = t0 + duration
    while clock.now() < t_end:
        if clock.crank(block=True) == 0 and \
                clock.next_deadline() is None:
            break

    def _oracle_failed(err: AssertionError,
                       failure_class: str = "oracle") -> None:
        """Any failed oracle dumps the merged forensic timeline and
        re-raises with the artifact path attached."""
        chaos.stop()
        try:
            path = dump_forensics(
                collect_forensics(sim, chaos, label, seed,
                                  reason=str(err)), forensics_dir)
        finally:
            for nid in list(sim.alive_nodes()):
                sim.nodes[nid].stop_node()
        raise ScenarioFailure(
            failure_class, f"{err}\n[forensics] {path}",
            forensics_path=path) from None

    # every scripted event must have fired inside the fault window — a
    # scenario whose script outlives its duration silently tests
    # nothing (the tiered stale_replay caught this: its replay timer
    # was cancelled before firing and the run reported a clean pass)
    fired = sum(1 for _, what in chaos.events
                if what.startswith("event: "))
    if fired != len(events):
        _oracle_failed(AssertionError(
            f"[{label}] only {fired}/{len(events)} scripted events fired "
            f"within duration {duration}s — extend the duration to cover "
            f"the script"), "unfired-script")

    # clear every remaining fault and start the heal stopwatch
    for nid in sorted(n for n, dead in sim.crashed.items() if dead):
        chaos.restore(nid)
    chaos.heal()
    chaos.clear_links()
    chaos.maintain_links_once()
    heal_start = max(chaos.last_clear_time, clock.now())
    honest = chaos.honest_alive()
    target = max(sim.nodes[n].ledger_manager.last_closed_seq()
                 for n in honest) + 2

    def converged_slot() -> Optional[int]:
        """First slot >= target that EVERY honest survivor externalized
        with one hash.  Any common slot counts, not just the target: a
        node that rejoined through out-of-sync recovery catches up PAST
        the target without re-externalizing it — a recording gap, not a
        safety problem."""
        recs = [chaos.extern_hashes.get(nid, {}) for nid in honest]
        common = set(recs[0]) if recs else set()
        for rec in recs[1:]:
            common &= set(rec)
        for s in sorted(x for x in common if x >= target):
            if len({rec[s] for rec in recs}) == 1:
                return s
        return None

    deadline = heal_start + converge_timeout
    while clock.now() < deadline and converged_slot() is None:
        if clock.crank(block=True) == 0 and \
                clock.next_deadline() is None:
            break
    conv = converged_slot()
    if conv is None:
        # a convergence timeout CAUSED by divergent histories is a
        # fork, not a liveness problem — classify by the symptom's
        # root so the fuzzer's ddmin matcher sees one stable class
        div = first_hash_divergence(chaos)
        _oracle_failed(AssertionError(
            f"[{label}] honest survivors failed to converge on seq "
            f"{target} within {converge_timeout}s virtual: "
            f"{[(n.hex()[:8], sim.nodes[n].ledger_manager.last_closed_seq()) for n in honest]}"
            + (f" (diverged at slot {div['slot']})" if div else "")),
            "fork" if div else "convergence-timeout")
    # healed when the LAST honest node externalized the agreed slot
    time_to_heal = round(
        max(0.0, max(
            chaos.extern_times[n][conv][0] for n in honest
            if conv in chaos.extern_times.get(n, {})) - heal_start), 3)
    chaos.stop()

    # safety: full header-chain + bucket-hash agreement, all honest pairs
    try:
        fork_comparisons = sim.assert_no_forks(honest)
    except AssertionError as e:
        _oracle_failed(e, "fork")

    # traffic accounting oracle: every phase must have started and
    # every submitted tx must carry a recorded admission status
    traffic_report = None
    if traffic:
        lg.stop_rate_run()
        _flush_phase_report(lg, phase_reports)
        try:
            traffic_report = _traffic_oracle(
                sim, traffic, phase_reports, label)
        except AssertionError as e:
            _oracle_failed(e, "traffic")

    # close-latency statistics over the whole run
    spread_ms: List[float] = []
    wall_ms: List[float] = []
    cadence_s: List[float] = []
    all_seqs = sorted({s for nid in honest
                       for s in chaos.extern_times.get(nid, {})})
    prev_wall_end = None
    for s in all_seqs:
        vts = [chaos.extern_times[nid][s][0] for nid in honest
               if s in chaos.extern_times.get(nid, {})]
        wts = [chaos.extern_times[nid][s][1] for nid in honest
               if s in chaos.extern_times.get(nid, {})]
        if len(vts) >= 2:
            spread_ms.append((max(vts) - min(vts)) * 1000.0)
        if prev_wall_end is not None:
            wall_ms.append((max(wts) - prev_wall_end) * 1000.0)
        prev_wall_end = max(wts)
        cadence_s.append(max(vts))
    cadence_diffs = [b - a for a, b in zip(cadence_s, cadence_s[1:])]

    report = {
        "scenario": label,
        "seed": seed,
        "nodes": len(sim.nodes),
        "byzantine": len(chaos.byzantine),
        "ledgers_closed": len(all_seqs),
        "close_spread_virtual_ms": _percentiles(spread_ms),
        "round_wall_ms": _percentiles(wall_ms),
        "cadence_virtual_s": _percentiles(cadence_diffs),
        "virtual_elapsed_s": round(clock.now() - t0, 3),
        "time_to_heal_s": time_to_heal,
        "counters": chaos.chaos_counters(),
        "fork_check": "pass",
        "fork_comparisons": fork_comparisons,
        "fingerprint": chaos.fingerprint(),
        "events": chaos.events,
        # raw per-node externalize record (hash prefixes): the
        # rerun-mismatch oracle's forensic material — chaos_bench
        # diffs two runs' maps to name the first (node, seq) that
        # diverged between reruns
        "per_node_externalized": {
            nid.hex()[:8]: {str(s): h.hex()[:16]
                            for s, h in sorted(
                                chaos.extern_hashes[nid].items())}
            for nid in sorted(chaos.extern_hashes)},
    }
    if traffic_report is not None:
        report["traffic"] = traffic_report
    # release node resources (DB handles, pools) without stopping the
    # clock mid-assert; the sim object dies with this frame
    for nid in list(sim.alive_nodes()):
        sim.nodes[nid].stop_node()
    return report


# ---------------------------------------------------------------------------
# the canned scenario suite (tests + tools/chaos_bench.py share these)
# ---------------------------------------------------------------------------

def scenario_events(sim_ids: List[bytes], scenario: str,
                    rng: random.Random) -> List[tuple]:
    """Build the event script for one named scenario over the given node
    ids (callers pass the topology's node order; victim choices draw
    from ``rng`` so they derive from the chaos seed)."""
    n = len(sim_ids)
    if scenario == "partition_heal":
        # cut off a minority third for a while, then heal
        cut = rng.sample(sim_ids, max(1, n // 3))
        keep = [x for x in sim_ids if x not in cut]
        return [
            (3.0, "partition minority",
             lambda c, g=[keep, cut]: c.partition(g)),
            (13.0, "heal", lambda c: c.heal()),
        ]
    if scenario == "crash_restore":
        victim = rng.choice(sim_ids)
        return [
            (3.4, "crash mid-close",
             lambda c, v=victim: c.crash(v)),
            (9.0, "restore from state",
             lambda c, v=victim: c.restore(v)),
        ]
    if scenario == "laggard":
        victim = rng.choice(sim_ids)
        return [
            (2.0, "laggard +2.5s",
             lambda c, v=victim: c.lag(v, 2.5)),
            (12.0, "unlag", lambda c, v=victim: c.lag(v, 0.0)),
        ]
    if scenario == "flaky_links":
        victims = rng.sample(sim_ids, max(2, n // 4))

        def flake(c, vs=victims):
            for v in vs:
                for a, b in c.sim.topology:
                    if v in (a, b):
                        c.set_link(a, b, drop=0.02, duplicate=0.01,
                                   damage=0.01)
        return [
            (2.0, "flaky links on", flake),
            (12.0, "links clean", lambda c: c.clear_links()),
        ]
    if scenario == "stale_replay":
        attacker = rng.choice(sim_ids)
        # replay late enough that the earliest captured slots are BOTH
        # past the floodgate's dedup TTL (so the replay isn't absorbed
        # as a duplicate) and below the herder's slot bracket (so the
        # discard path, not SCP, sheds them)
        return [
            (0.5, "capture scp",
             lambda c, a=attacker: c.capture_scp(a)),
            (16.0, "replay stale envelopes",
             lambda c, a=attacker: c.replay_stale(
                 a, max_age_slot=c.sim.nodes[a].ledger_manager
                 .last_closed_seq() - 2)),
        ]
    if scenario == "equivocator":
        # a Byzantine minority equivocates from the start
        byz = rng.sample(sim_ids, max(1, (n - 1) // 4))
        return [(1.0, f"equivocate x{len(byz)}",
                 lambda c, bs=byz: [c.equivocate(b) for b in bs])]
    raise ValueError(f"unknown scenario {scenario!r}")


STANDARD_SCENARIOS = ("partition_heal", "crash_restore", "laggard",
                      "flaky_links", "stale_replay", "equivocator")


def run_standard_scenario(make_sim: Callable[[], Simulation],
                          scenario: str, seed: int, n_nodes: int,
                          duration: float = 20.0,
                          converge_timeout: float = 120.0,
                          forensics_dir: Optional[str] = None) -> dict:
    """Resolve a named scenario against the canned topologies' node
    order (node ids are a pure function of the node index, so no sim
    needs building to know them) and run it.  The victim-choosing RNG
    derives from (seed, scenario) so every scenario of a bench run is
    independently deterministic."""
    from .simulation import _ids, _seeds

    ids = _ids(_seeds(n_nodes))
    rng = random.Random(int.from_bytes(
        sha256(b"chaos-scenario-%d-" % seed + scenario.encode()), "big"))
    events = scenario_events(ids, scenario, rng)
    # the fault window must cover the whole event script (plus slack
    # for the last fault to bite) — otherwise late events like
    # stale_replay's t=16 injection never fire on short-duration tiers
    duration = max(duration, max(t for t, _, _ in events) + 2.0)
    return run_scenario(make_sim, seed, events, duration, scenario,
                        converge_timeout=converge_timeout,
                        forensics_dir=forensics_dir)
