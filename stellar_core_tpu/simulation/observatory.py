"""NetworkObservatory: the fleet-merged view of a Simulation's telemetry.

Single-node observability (flight recorder, tx lifecycle, vitals, the
r19 flood hop records) answers "what did THIS node see"; the observatory
joins every sim node's hop records and registries into the network-level
questions the ROADMAP's multi-validator rungs need answered:

- propagation: per flood item, which nodes saw it and when → time to
  50%/90% node coverage, time-to-first-delivery off the origin;
- redundancy: per directed link, unique vs duplicate arrivals → how much
  of the flood fan-out is wasted bytes;
- cadence: per-node close skew — who is lagging the network head.

Everything is computed from virtual-clock stamps and deterministic
counters, keys sorted and floats rounded, so a same-seed sim rerun
yields a byte-identical ``json.dumps(snapshot(), sort_keys=True)`` —
pinned by tests/test_observatory.py.

Blind spot by design: hop records live under the flood tracker's stride
gate, so under decimation an item's coverage is computed from the nodes
that SAMPLED it, not all nodes that saw it (coverage counts are exact
only while stride == 1).  Real-TCP fleets aggregate via
tools/fleet_scrape.py instead — the observatory needs in-process access.
"""
from __future__ import annotations

import math
from typing import Dict, List, Optional


def _p(xs: List[float], q: float) -> Optional[float]:
    """Nearest-rank percentile of an unsorted list; None when empty."""
    if not xs:
        return None
    s = sorted(xs)
    return s[min(len(s) - 1, int(q * len(s)))]


def _summary(xs: List[float]) -> Optional[dict]:
    if not xs:
        return None
    return {
        "n": len(xs),
        "p50": round(_p(xs, 0.50), 6),
        "p90": round(_p(xs, 0.90), 6),
        "max": round(max(xs), 6),
    }


class NetworkObservatory:
    """Merges every sim node's flood hop records + registries into one
    network view, served by the ``network-observatory`` admin endpoint
    (the Simulation attaches ``app._observatory = self`` on every node,
    restarts included)."""

    def __init__(self, sim):
        self.sim = sim

    # -- merge ---------------------------------------------------------------

    def _merged_items(self, alive: dict) -> Dict[str, dict]:
        """hexhash -> merged per-item record across all alive nodes."""
        items: Dict[str, dict] = {}
        for nid, app in alive.items():
            n8 = nid.hex()[:8]
            for hexhash, rec in app.floodtracer.export().items():
                it = items.setdefault(hexhash, {
                    "kind": rec["kind"], "origin": None,
                    "deliveries": [], "dups_total": 0})
                if rec["origin"]:
                    it["origin"] = n8
                else:
                    it["deliveries"].append(
                        {"node": n8, "t": rec["first_t"],
                         "from": rec["from"]})
                it["dups_total"] += rec["dups"]
                if rec["origin"]:
                    # the origin "sees" the item at its own stamp too —
                    # coverage counts it as node zero
                    it["deliveries"].append(
                        {"node": n8, "t": rec["first_t"], "from": None})
        for it in items.values():
            it["deliveries"].sort(key=lambda d: (d["t"], d["node"]))
            for d in it["deliveries"]:
                d["t"] = round(d["t"], 6)
        return items

    @staticmethod
    def _coverage_times(it: dict, n_alive: int) -> dict:
        """time-to-50%/90% node coverage + first-delivery lag for one
        merged item, measured from the earliest stamp (the origin's when
        the origin is known — it is always the earliest)."""
        deliveries = it["deliveries"]
        out = {"coverage": round(len(deliveries) / n_alive, 4)
               if n_alive else 0.0, "t50": None, "t90": None, "ttfd": None}
        if not deliveries:
            return out
        t0 = deliveries[0]["t"]
        need50 = max(1, math.ceil(0.5 * n_alive))
        need90 = max(1, math.ceil(0.9 * n_alive))
        if len(deliveries) >= need50:
            out["t50"] = round(deliveries[need50 - 1]["t"] - t0, 6)
        if len(deliveries) >= need90:
            out["t90"] = round(deliveries[need90 - 1]["t"] - t0, 6)
        if it["origin"] is not None and len(deliveries) >= 2:
            out["ttfd"] = round(deliveries[1]["t"] - t0, 6)
        return out

    # -- views ---------------------------------------------------------------

    def snapshot(self) -> dict:
        sim = self.sim
        alive = sim.alive_nodes()
        n_alive = len(alive)
        items = self._merged_items(alive)

        t50s, t90s, ttfds = [], [], []
        item_docs = {}
        for hexhash in sorted(items):
            it = items[hexhash]
            cov = self._coverage_times(it, n_alive)
            if cov["t50"] is not None:
                t50s.append(cov["t50"])
            if cov["t90"] is not None:
                t90s.append(cov["t90"])
            if cov["ttfd"] is not None:
                ttfds.append(cov["ttfd"])
            item_docs[hexhash] = {
                "kind": it["kind"], "origin": it["origin"],
                "dups_total": it["dups_total"],
                "deliveries": it["deliveries"], **cov}

        links = {}
        for nid in sorted(alive):
            n8 = nid.hex()[:8]
            for pid8, row in alive[nid].floodtracer.report(
                    last=0)["links"].items():
                links[f"{n8}<-{pid8}"] = {
                    "unique": row["unique"],
                    "duplicate": row["duplicate"],
                    "redundancy": row["dup_ratio"],
                }

        lcls = {nid: app.ledger_manager.last_closed_seq()
                for nid, app in alive.items()}
        head = max(lcls.values()) if lcls else 0
        cadence = {nid.hex()[:8]: {"lcl": seq, "lag": head - seq}
                   for nid, seq in sorted(lcls.items())}

        return {
            "nodes": sorted(nid.hex()[:8] for nid in alive),
            "n_items": len(item_docs),
            "items": item_docs,
            "propagation": {
                "time_to_50pct": _summary(t50s),
                "time_to_90pct": _summary(t90s),
                "ttfd": _summary(ttfds),
            },
            "links": links,
            "close_cadence": cadence,
        }

    def summary(self) -> dict:
        """snapshot() minus the per-item detail — what benches persist."""
        doc = self.snapshot()
        del doc["items"]
        return doc
