"""Stellar protocol schema (protocol 19) declared over the XDR runtime.

Equivalent of the reference's generated codecs for the protocol ``.x`` files
(ref src/protocol-curr/xdr/Stellar-{types,ledger-entries,transaction,ledger,
SCP}.x; codegen ref src/Makefile.am:42-47).  Declarations follow the wire
format exactly — field order and discriminant values are the protocol spec —
but the runtime/object model is this framework's own (combinators +
generic records, see runtime.py).

Naming: type objects are UpperCamel like the protocol; enums expose their
members as attributes (``OperationType.PAYMENT``).
"""
from __future__ import annotations

from .runtime import (
    Bool, Enum, FixedArray, Hyper, Int, Lazy, Opaque, Option, Struct, Uhyper,
    Uint, Union, VarArray, VarOpaque, XdrStr,
)

# ---------------------------------------------------------------------------
# Stellar-types.x
# ---------------------------------------------------------------------------

Hash = Opaque(32)
Uint256 = Opaque(32)
Signature = VarOpaque(64)
SignatureHint = Opaque(4)

ExtensionPoint = Union("ExtensionPoint", Int, {0: ("v0", None)})

CryptoKeyType = Enum("CryptoKeyType", {
    "KEY_TYPE_ED25519": 0,
    "KEY_TYPE_PRE_AUTH_TX": 1,
    "KEY_TYPE_HASH_X": 2,
    "KEY_TYPE_ED25519_SIGNED_PAYLOAD": 3,
    "KEY_TYPE_MUXED_ED25519": 0x100,
})

PublicKeyType = Enum("PublicKeyType", {"PUBLIC_KEY_TYPE_ED25519": 0})

SignerKeyType = Enum("SignerKeyType", {
    "SIGNER_KEY_TYPE_ED25519": 0,
    "SIGNER_KEY_TYPE_PRE_AUTH_TX": 1,
    "SIGNER_KEY_TYPE_HASH_X": 2,
    "SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD": 3,
})

PublicKey = Union("PublicKey", PublicKeyType, {
    PublicKeyType.PUBLIC_KEY_TYPE_ED25519: ("ed25519", Uint256),
})

_Ed25519SignedPayload = Struct("Ed25519SignedPayload", [
    ("ed25519", Uint256),
    ("payload", VarOpaque(64)),
])

SignerKey = Union("SignerKey", SignerKeyType, {
    SignerKeyType.SIGNER_KEY_TYPE_ED25519: ("ed25519", Uint256),
    SignerKeyType.SIGNER_KEY_TYPE_PRE_AUTH_TX: ("preAuthTx", Uint256),
    SignerKeyType.SIGNER_KEY_TYPE_HASH_X: ("hashX", Uint256),
    SignerKeyType.SIGNER_KEY_TYPE_ED25519_SIGNED_PAYLOAD:
        ("ed25519SignedPayload", _Ed25519SignedPayload),
})

NodeID = PublicKey
AccountID = PublicKey

Curve25519Public = Struct("Curve25519Public", [("key", Opaque(32))])
Curve25519Secret = Struct("Curve25519Secret", [("key", Opaque(32))])
HmacSha256Key = Struct("HmacSha256Key", [("key", Opaque(32))])
HmacSha256Mac = Struct("HmacSha256Mac", [("mac", Opaque(32))])


def account_id(ed25519_bytes: bytes):
    """Convenience: raw 32-byte key -> AccountID value."""
    return PublicKey.make(PublicKeyType.PUBLIC_KEY_TYPE_ED25519, ed25519_bytes)


# ---------------------------------------------------------------------------
# Stellar-ledger-entries.x
# ---------------------------------------------------------------------------

Thresholds = Opaque(4)
String32 = XdrStr(32)
String64 = XdrStr(64)
DataValue = VarOpaque(64)
PoolID = Hash
AssetCode4 = Opaque(4)
AssetCode12 = Opaque(12)

AssetType = Enum("AssetType", {
    "ASSET_TYPE_NATIVE": 0,
    "ASSET_TYPE_CREDIT_ALPHANUM4": 1,
    "ASSET_TYPE_CREDIT_ALPHANUM12": 2,
    "ASSET_TYPE_POOL_SHARE": 3,
})

AssetCode = Union("AssetCode", AssetType, {
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("assetCode4", AssetCode4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("assetCode12", AssetCode12),
})

AlphaNum4 = Struct("AlphaNum4", [
    ("assetCode", AssetCode4), ("issuer", AccountID),
])
AlphaNum12 = Struct("AlphaNum12", [
    ("assetCode", AssetCode12), ("issuer", AccountID),
])

Asset = Union("Asset", AssetType, {
    AssetType.ASSET_TYPE_NATIVE: ("native", None),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
})

Price = Struct("Price", [("n", Int), ("d", Int)])
Liabilities = Struct("Liabilities", [("buying", Hyper), ("selling", Hyper)])

ThresholdIndexes = Enum("ThresholdIndexes", {
    "THRESHOLD_MASTER_WEIGHT": 0,
    "THRESHOLD_LOW": 1,
    "THRESHOLD_MED": 2,
    "THRESHOLD_HIGH": 3,
})

LedgerEntryType = Enum("LedgerEntryType", {
    "ACCOUNT": 0,
    "TRUSTLINE": 1,
    "OFFER": 2,
    "DATA": 3,
    "CLAIMABLE_BALANCE": 4,
    "LIQUIDITY_POOL": 5,
})

Signer = Struct("Signer", [("key", SignerKey), ("weight", Uint)])

AUTH_REQUIRED_FLAG = 0x1
AUTH_REVOCABLE_FLAG = 0x2
AUTH_IMMUTABLE_FLAG = 0x4
AUTH_CLAWBACK_ENABLED_FLAG = 0x8
MASK_ACCOUNT_FLAGS = 0x7
MASK_ACCOUNT_FLAGS_V17 = 0xF
MAX_SIGNERS = 20

SponsorshipDescriptor = Option(AccountID)

AccountEntryExtensionV3 = Struct("AccountEntryExtensionV3", [
    ("ext", ExtensionPoint),
    ("seqLedger", Uint),
    ("seqTime", Uhyper),
])

AccountEntryExtensionV2 = Struct("AccountEntryExtensionV2", [
    ("numSponsored", Uint),
    ("numSponsoring", Uint),
    ("signerSponsoringIDs", VarArray(SponsorshipDescriptor, MAX_SIGNERS)),
    ("ext", Union("AccountEntryExtensionV2Ext", Int, {
        0: ("v0", None),
        3: ("v3", AccountEntryExtensionV3),
    })),
])

AccountEntryExtensionV1 = Struct("AccountEntryExtensionV1", [
    ("liabilities", Liabilities),
    ("ext", Union("AccountEntryExtensionV1Ext", Int, {
        0: ("v0", None),
        2: ("v2", AccountEntryExtensionV2),
    })),
])

AccountEntry = Struct("AccountEntry", [
    ("accountID", AccountID),
    ("balance", Hyper),
    ("seqNum", Hyper),
    ("numSubEntries", Uint),
    ("inflationDest", Option(AccountID)),
    ("flags", Uint),
    ("homeDomain", String32),
    ("thresholds", Thresholds),
    ("signers", VarArray(Signer, MAX_SIGNERS)),
    ("ext", Union("AccountEntryExt", Int, {
        0: ("v0", None),
        1: ("v1", AccountEntryExtensionV1),
    })),
])

AUTHORIZED_FLAG = 1
AUTHORIZED_TO_MAINTAIN_LIABILITIES_FLAG = 2
TRUSTLINE_CLAWBACK_ENABLED_FLAG = 4
MASK_TRUSTLINE_FLAGS = 1
MASK_TRUSTLINE_FLAGS_V13 = 3
MASK_TRUSTLINE_FLAGS_V17 = 7

LiquidityPoolType = Enum("LiquidityPoolType", {
    "LIQUIDITY_POOL_CONSTANT_PRODUCT": 0,
})

TrustLineAsset = Union("TrustLineAsset", AssetType, {
    AssetType.ASSET_TYPE_NATIVE: ("native", None),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
    AssetType.ASSET_TYPE_POOL_SHARE: ("liquidityPoolID", PoolID),
})

TrustLineEntryExtensionV2 = Struct("TrustLineEntryExtensionV2", [
    ("liquidityPoolUseCount", Int),
    ("ext", Union("TrustLineEntryExtensionV2Ext", Int, {0: ("v0", None)})),
])

_TrustLineEntryV1 = Struct("TrustLineEntryV1", [
    ("liabilities", Liabilities),
    ("ext", Union("TrustLineEntryV1Ext", Int, {
        0: ("v0", None),
        2: ("v2", TrustLineEntryExtensionV2),
    })),
])

TrustLineEntry = Struct("TrustLineEntry", [
    ("accountID", AccountID),
    ("asset", TrustLineAsset),
    ("balance", Hyper),
    ("limit", Hyper),
    ("flags", Uint),
    ("ext", Union("TrustLineEntryExt", Int, {
        0: ("v0", None),
        1: ("v1", _TrustLineEntryV1),
    })),
])

PASSIVE_FLAG = 1
MASK_OFFERENTRY_FLAGS = 1

OfferEntry = Struct("OfferEntry", [
    ("sellerID", AccountID),
    ("offerID", Hyper),
    ("selling", Asset),
    ("buying", Asset),
    ("amount", Hyper),
    ("price", Price),
    ("flags", Uint),
    ("ext", Union("OfferEntryExt", Int, {0: ("v0", None)})),
])

DataEntry = Struct("DataEntry", [
    ("accountID", AccountID),
    ("dataName", String64),
    ("dataValue", DataValue),
    ("ext", Union("DataEntryExt", Int, {0: ("v0", None)})),
])

ClaimPredicateType = Enum("ClaimPredicateType", {
    "CLAIM_PREDICATE_UNCONDITIONAL": 0,
    "CLAIM_PREDICATE_AND": 1,
    "CLAIM_PREDICATE_OR": 2,
    "CLAIM_PREDICATE_NOT": 3,
    "CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME": 4,
    "CLAIM_PREDICATE_BEFORE_RELATIVE_TIME": 5,
})

ClaimPredicate = Union("ClaimPredicate", ClaimPredicateType, {
    ClaimPredicateType.CLAIM_PREDICATE_UNCONDITIONAL: ("unconditional", None),
    ClaimPredicateType.CLAIM_PREDICATE_AND:
        ("andPredicates", VarArray(Lazy(lambda: ClaimPredicate), 2)),
    ClaimPredicateType.CLAIM_PREDICATE_OR:
        ("orPredicates", VarArray(Lazy(lambda: ClaimPredicate), 2)),
    ClaimPredicateType.CLAIM_PREDICATE_NOT:
        ("notPredicate", Option(Lazy(lambda: ClaimPredicate))),
    ClaimPredicateType.CLAIM_PREDICATE_BEFORE_ABSOLUTE_TIME:
        ("absBefore", Hyper),
    ClaimPredicateType.CLAIM_PREDICATE_BEFORE_RELATIVE_TIME:
        ("relBefore", Hyper),
})

ClaimantType = Enum("ClaimantType", {"CLAIMANT_TYPE_V0": 0})

_ClaimantV0 = Struct("ClaimantV0", [
    ("destination", AccountID),
    ("predicate", ClaimPredicate),
])

Claimant = Union("Claimant", ClaimantType, {
    ClaimantType.CLAIMANT_TYPE_V0: ("v0", _ClaimantV0),
})

ClaimableBalanceIDType = Enum("ClaimableBalanceIDType", {
    "CLAIMABLE_BALANCE_ID_TYPE_V0": 0,
})

ClaimableBalanceID = Union("ClaimableBalanceID", ClaimableBalanceIDType, {
    ClaimableBalanceIDType.CLAIMABLE_BALANCE_ID_TYPE_V0: ("v0", Hash),
})

CLAIMABLE_BALANCE_CLAWBACK_ENABLED_FLAG = 0x1
MASK_CLAIMABLE_BALANCE_FLAGS = 0x1

ClaimableBalanceEntryExtensionV1 = Struct("ClaimableBalanceEntryExtensionV1", [
    ("ext", Union("ClaimableBalanceEntryExtensionV1Ext", Int,
                  {0: ("v0", None)})),
    ("flags", Uint),
])

ClaimableBalanceEntry = Struct("ClaimableBalanceEntry", [
    ("balanceID", ClaimableBalanceID),
    ("claimants", VarArray(Claimant, 10)),
    ("asset", Asset),
    ("amount", Hyper),
    ("ext", Union("ClaimableBalanceEntryExt", Int, {
        0: ("v0", None),
        1: ("v1", ClaimableBalanceEntryExtensionV1),
    })),
])

LiquidityPoolConstantProductParameters = Struct(
    "LiquidityPoolConstantProductParameters", [
        ("assetA", Asset),
        ("assetB", Asset),
        ("fee", Int),
    ])

LIQUIDITY_POOL_FEE_V18 = 30

_LPConstantProduct = Struct("LiquidityPoolEntryConstantProduct", [
    ("params", LiquidityPoolConstantProductParameters),
    ("reserveA", Hyper),
    ("reserveB", Hyper),
    ("totalPoolShares", Hyper),
    ("poolSharesTrustLineCount", Hyper),
])

LiquidityPoolEntry = Struct("LiquidityPoolEntry", [
    ("liquidityPoolID", PoolID),
    ("body", Union("LiquidityPoolEntryBody", LiquidityPoolType, {
        LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT:
            ("constantProduct", _LPConstantProduct),
    })),
])

LedgerEntryExtensionV1 = Struct("LedgerEntryExtensionV1", [
    ("sponsoringID", SponsorshipDescriptor),
    ("ext", Union("LedgerEntryExtensionV1Ext", Int, {0: ("v0", None)})),
])

LedgerEntryData = Union("LedgerEntryData", LedgerEntryType, {
    LedgerEntryType.ACCOUNT: ("account", AccountEntry),
    LedgerEntryType.TRUSTLINE: ("trustLine", TrustLineEntry),
    LedgerEntryType.OFFER: ("offer", OfferEntry),
    LedgerEntryType.DATA: ("data", DataEntry),
    LedgerEntryType.CLAIMABLE_BALANCE:
        ("claimableBalance", ClaimableBalanceEntry),
    LedgerEntryType.LIQUIDITY_POOL: ("liquidityPool", LiquidityPoolEntry),
})

LedgerEntry = Struct("LedgerEntry", [
    ("lastModifiedLedgerSeq", Uint),
    ("data", LedgerEntryData),
    ("ext", Union("LedgerEntryExt", Int, {
        0: ("v0", None),
        1: ("v1", LedgerEntryExtensionV1),
    })),
])
# one LedgerEntry flows through tx meta + bucket list + SQL commit per
# close; memoized encoding collapses those to a single pack (values are
# immutable-by-convention: all mutation goes through _replace)
LedgerEntry.memoize = True

_LKAccount = Struct("LedgerKeyAccount", [("accountID", AccountID)])
_LKTrustLine = Struct("LedgerKeyTrustLine", [
    ("accountID", AccountID), ("asset", TrustLineAsset),
])
_LKOffer = Struct("LedgerKeyOffer", [
    ("sellerID", AccountID), ("offerID", Hyper),
])
_LKData = Struct("LedgerKeyData", [
    ("accountID", AccountID), ("dataName", String64),
])
_LKClaimableBalance = Struct("LedgerKeyClaimableBalance", [
    ("balanceID", ClaimableBalanceID),
])
_LKLiquidityPool = Struct("LedgerKeyLiquidityPool", [
    ("liquidityPoolID", PoolID),
])

LedgerKey = Union("LedgerKey", LedgerEntryType, {
    LedgerEntryType.ACCOUNT: ("account", _LKAccount),
    LedgerEntryType.TRUSTLINE: ("trustLine", _LKTrustLine),
    LedgerEntryType.OFFER: ("offer", _LKOffer),
    LedgerEntryType.DATA: ("data", _LKData),
    LedgerEntryType.CLAIMABLE_BALANCE:
        ("claimableBalance", _LKClaimableBalance),
    LedgerEntryType.LIQUIDITY_POOL: ("liquidityPool", _LKLiquidityPool),
})

EnvelopeType = Enum("EnvelopeType", {
    "ENVELOPE_TYPE_TX_V0": 0,
    "ENVELOPE_TYPE_SCP": 1,
    "ENVELOPE_TYPE_TX": 2,
    "ENVELOPE_TYPE_AUTH": 3,
    "ENVELOPE_TYPE_SCPVALUE": 4,
    "ENVELOPE_TYPE_TX_FEE_BUMP": 5,
    "ENVELOPE_TYPE_OP_ID": 6,
    "ENVELOPE_TYPE_POOL_REVOKE_OP_ID": 7,
})

# ---------------------------------------------------------------------------
# Stellar-transaction.x — operations
# ---------------------------------------------------------------------------

LiquidityPoolParameters = Union(
    "LiquidityPoolParameters", LiquidityPoolType, {
        LiquidityPoolType.LIQUIDITY_POOL_CONSTANT_PRODUCT:
            ("constantProduct", LiquidityPoolConstantProductParameters),
    })

_MuxedEd25519 = Struct("MuxedEd25519", [
    ("id", Uhyper), ("ed25519", Uint256),
])

MuxedAccount = Union("MuxedAccount", CryptoKeyType, {
    CryptoKeyType.KEY_TYPE_ED25519: ("ed25519", Uint256),
    CryptoKeyType.KEY_TYPE_MUXED_ED25519: ("med25519", _MuxedEd25519),
})


def muxed_account(ed25519_bytes: bytes):
    return MuxedAccount.make(CryptoKeyType.KEY_TYPE_ED25519, ed25519_bytes)


DecoratedSignature = Struct("DecoratedSignature", [
    ("hint", SignatureHint),
    ("signature", Signature),
])

OperationType = Enum("OperationType", {
    "CREATE_ACCOUNT": 0,
    "PAYMENT": 1,
    "PATH_PAYMENT_STRICT_RECEIVE": 2,
    "MANAGE_SELL_OFFER": 3,
    "CREATE_PASSIVE_SELL_OFFER": 4,
    "SET_OPTIONS": 5,
    "CHANGE_TRUST": 6,
    "ALLOW_TRUST": 7,
    "ACCOUNT_MERGE": 8,
    "INFLATION": 9,
    "MANAGE_DATA": 10,
    "BUMP_SEQUENCE": 11,
    "MANAGE_BUY_OFFER": 12,
    "PATH_PAYMENT_STRICT_SEND": 13,
    "CREATE_CLAIMABLE_BALANCE": 14,
    "CLAIM_CLAIMABLE_BALANCE": 15,
    "BEGIN_SPONSORING_FUTURE_RESERVES": 16,
    "END_SPONSORING_FUTURE_RESERVES": 17,
    "REVOKE_SPONSORSHIP": 18,
    "CLAWBACK": 19,
    "CLAWBACK_CLAIMABLE_BALANCE": 20,
    "SET_TRUST_LINE_FLAGS": 21,
    "LIQUIDITY_POOL_DEPOSIT": 22,
    "LIQUIDITY_POOL_WITHDRAW": 23,
})

CreateAccountOp = Struct("CreateAccountOp", [
    ("destination", AccountID),
    ("startingBalance", Hyper),
])

PaymentOp = Struct("PaymentOp", [
    ("destination", MuxedAccount),
    ("asset", Asset),
    ("amount", Hyper),
])

PathPaymentStrictReceiveOp = Struct("PathPaymentStrictReceiveOp", [
    ("sendAsset", Asset),
    ("sendMax", Hyper),
    ("destination", MuxedAccount),
    ("destAsset", Asset),
    ("destAmount", Hyper),
    ("path", VarArray(Asset, 5)),
])

PathPaymentStrictSendOp = Struct("PathPaymentStrictSendOp", [
    ("sendAsset", Asset),
    ("sendAmount", Hyper),
    ("destination", MuxedAccount),
    ("destAsset", Asset),
    ("destMin", Hyper),
    ("path", VarArray(Asset, 5)),
])

ManageSellOfferOp = Struct("ManageSellOfferOp", [
    ("selling", Asset),
    ("buying", Asset),
    ("amount", Hyper),
    ("price", Price),
    ("offerID", Hyper),
])

ManageBuyOfferOp = Struct("ManageBuyOfferOp", [
    ("selling", Asset),
    ("buying", Asset),
    ("buyAmount", Hyper),
    ("price", Price),
    ("offerID", Hyper),
])

CreatePassiveSellOfferOp = Struct("CreatePassiveSellOfferOp", [
    ("selling", Asset),
    ("buying", Asset),
    ("amount", Hyper),
    ("price", Price),
])

SetOptionsOp = Struct("SetOptionsOp", [
    ("inflationDest", Option(AccountID)),
    ("clearFlags", Option(Uint)),
    ("setFlags", Option(Uint)),
    ("masterWeight", Option(Uint)),
    ("lowThreshold", Option(Uint)),
    ("medThreshold", Option(Uint)),
    ("highThreshold", Option(Uint)),
    ("homeDomain", Option(String32)),
    ("signer", Option(Signer)),
])

ChangeTrustAsset = Union("ChangeTrustAsset", AssetType, {
    AssetType.ASSET_TYPE_NATIVE: ("native", None),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM4: ("alphaNum4", AlphaNum4),
    AssetType.ASSET_TYPE_CREDIT_ALPHANUM12: ("alphaNum12", AlphaNum12),
    AssetType.ASSET_TYPE_POOL_SHARE:
        ("liquidityPool", LiquidityPoolParameters),
})

ChangeTrustOp = Struct("ChangeTrustOp", [
    ("line", ChangeTrustAsset),
    ("limit", Hyper),
])

AllowTrustOp = Struct("AllowTrustOp", [
    ("trustor", AccountID),
    ("asset", AssetCode),
    ("authorize", Uint),
])

ManageDataOp = Struct("ManageDataOp", [
    ("dataName", String64),
    ("dataValue", Option(DataValue)),
])

BumpSequenceOp = Struct("BumpSequenceOp", [("bumpTo", Hyper)])

CreateClaimableBalanceOp = Struct("CreateClaimableBalanceOp", [
    ("asset", Asset),
    ("amount", Hyper),
    ("claimants", VarArray(Claimant, 10)),
])

ClaimClaimableBalanceOp = Struct("ClaimClaimableBalanceOp", [
    ("balanceID", ClaimableBalanceID),
])

BeginSponsoringFutureReservesOp = Struct(
    "BeginSponsoringFutureReservesOp", [("sponsoredID", AccountID)])

RevokeSponsorshipType = Enum("RevokeSponsorshipType", {
    "REVOKE_SPONSORSHIP_LEDGER_ENTRY": 0,
    "REVOKE_SPONSORSHIP_SIGNER": 1,
})

_RevokeSponsorshipSigner = Struct("RevokeSponsorshipSigner", [
    ("accountID", AccountID),
    ("signerKey", SignerKey),
])

RevokeSponsorshipOp = Union("RevokeSponsorshipOp", RevokeSponsorshipType, {
    RevokeSponsorshipType.REVOKE_SPONSORSHIP_LEDGER_ENTRY:
        ("ledgerKey", LedgerKey),
    RevokeSponsorshipType.REVOKE_SPONSORSHIP_SIGNER:
        ("signer", _RevokeSponsorshipSigner),
})

ClawbackOp = Struct("ClawbackOp", [
    ("asset", Asset),
    ("from_", MuxedAccount),
    ("amount", Hyper),
])

ClawbackClaimableBalanceOp = Struct("ClawbackClaimableBalanceOp", [
    ("balanceID", ClaimableBalanceID),
])

SetTrustLineFlagsOp = Struct("SetTrustLineFlagsOp", [
    ("trustor", AccountID),
    ("asset", Asset),
    ("clearFlags", Uint),
    ("setFlags", Uint),
])

LiquidityPoolDepositOp = Struct("LiquidityPoolDepositOp", [
    ("liquidityPoolID", PoolID),
    ("maxAmountA", Hyper),
    ("maxAmountB", Hyper),
    ("minPrice", Price),
    ("maxPrice", Price),
])

LiquidityPoolWithdrawOp = Struct("LiquidityPoolWithdrawOp", [
    ("liquidityPoolID", PoolID),
    ("amount", Hyper),
    ("minAmountA", Hyper),
    ("minAmountB", Hyper),
])

OperationBody = Union("OperationBody", OperationType, {
    OperationType.CREATE_ACCOUNT: ("createAccountOp", CreateAccountOp),
    OperationType.PAYMENT: ("paymentOp", PaymentOp),
    OperationType.PATH_PAYMENT_STRICT_RECEIVE:
        ("pathPaymentStrictReceiveOp", PathPaymentStrictReceiveOp),
    OperationType.MANAGE_SELL_OFFER:
        ("manageSellOfferOp", ManageSellOfferOp),
    OperationType.CREATE_PASSIVE_SELL_OFFER:
        ("createPassiveSellOfferOp", CreatePassiveSellOfferOp),
    OperationType.SET_OPTIONS: ("setOptionsOp", SetOptionsOp),
    OperationType.CHANGE_TRUST: ("changeTrustOp", ChangeTrustOp),
    OperationType.ALLOW_TRUST: ("allowTrustOp", AllowTrustOp),
    OperationType.ACCOUNT_MERGE: ("destination", MuxedAccount),
    OperationType.INFLATION: ("inflation", None),
    OperationType.MANAGE_DATA: ("manageDataOp", ManageDataOp),
    OperationType.BUMP_SEQUENCE: ("bumpSequenceOp", BumpSequenceOp),
    OperationType.MANAGE_BUY_OFFER: ("manageBuyOfferOp", ManageBuyOfferOp),
    OperationType.PATH_PAYMENT_STRICT_SEND:
        ("pathPaymentStrictSendOp", PathPaymentStrictSendOp),
    OperationType.CREATE_CLAIMABLE_BALANCE:
        ("createClaimableBalanceOp", CreateClaimableBalanceOp),
    OperationType.CLAIM_CLAIMABLE_BALANCE:
        ("claimClaimableBalanceOp", ClaimClaimableBalanceOp),
    OperationType.BEGIN_SPONSORING_FUTURE_RESERVES:
        ("beginSponsoringFutureReservesOp", BeginSponsoringFutureReservesOp),
    OperationType.END_SPONSORING_FUTURE_RESERVES:
        ("endSponsoringFutureReserves", None),
    OperationType.REVOKE_SPONSORSHIP:
        ("revokeSponsorshipOp", RevokeSponsorshipOp),
    OperationType.CLAWBACK: ("clawbackOp", ClawbackOp),
    OperationType.CLAWBACK_CLAIMABLE_BALANCE:
        ("clawbackClaimableBalanceOp", ClawbackClaimableBalanceOp),
    OperationType.SET_TRUST_LINE_FLAGS:
        ("setTrustLineFlagsOp", SetTrustLineFlagsOp),
    OperationType.LIQUIDITY_POOL_DEPOSIT:
        ("liquidityPoolDepositOp", LiquidityPoolDepositOp),
    OperationType.LIQUIDITY_POOL_WITHDRAW:
        ("liquidityPoolWithdrawOp", LiquidityPoolWithdrawOp),
})

Operation = Struct("Operation", [
    ("sourceAccount", Option(MuxedAccount)),
    ("body", OperationBody),
])

_HashIDPreimageOperationID = Struct("HashIDPreimageOperationID", [
    ("sourceAccount", AccountID),
    ("seqNum", Hyper),
    ("opNum", Uint),
])

_HashIDPreimageRevokeID = Struct("HashIDPreimageRevokeID", [
    ("sourceAccount", AccountID),
    ("seqNum", Hyper),
    ("opNum", Uint),
    ("liquidityPoolID", PoolID),
    ("asset", Asset),
])

HashIDPreimage = Union("HashIDPreimage", EnvelopeType, {
    EnvelopeType.ENVELOPE_TYPE_OP_ID:
        ("operationID", _HashIDPreimageOperationID),
    EnvelopeType.ENVELOPE_TYPE_POOL_REVOKE_OP_ID:
        ("revokeID", _HashIDPreimageRevokeID),
})

MemoType = Enum("MemoType", {
    "MEMO_NONE": 0,
    "MEMO_TEXT": 1,
    "MEMO_ID": 2,
    "MEMO_HASH": 3,
    "MEMO_RETURN": 4,
})

Memo = Union("Memo", MemoType, {
    MemoType.MEMO_NONE: ("none", None),
    MemoType.MEMO_TEXT: ("text", XdrStr(28)),
    MemoType.MEMO_ID: ("id", Uhyper),
    MemoType.MEMO_HASH: ("hash", Hash),
    MemoType.MEMO_RETURN: ("retHash", Hash),
})

MEMO_NONE_VALUE = Memo.make(MemoType.MEMO_NONE)

TimeBounds = Struct("TimeBounds", [
    ("minTime", Uhyper),
    ("maxTime", Uhyper),
])

LedgerBounds = Struct("LedgerBounds", [
    ("minLedger", Uint),
    ("maxLedger", Uint),
])

PreconditionsV2 = Struct("PreconditionsV2", [
    ("timeBounds", Option(TimeBounds)),
    ("ledgerBounds", Option(LedgerBounds)),
    ("minSeqNum", Option(Hyper)),
    ("minSeqAge", Uhyper),
    ("minSeqLedgerGap", Uint),
    ("extraSigners", VarArray(SignerKey, 2)),
])

PreconditionType = Enum("PreconditionType", {
    "PRECOND_NONE": 0,
    "PRECOND_TIME": 1,
    "PRECOND_V2": 2,
})

Preconditions = Union("Preconditions", PreconditionType, {
    PreconditionType.PRECOND_NONE: ("none", None),
    PreconditionType.PRECOND_TIME: ("timeBounds", TimeBounds),
    PreconditionType.PRECOND_V2: ("v2", PreconditionsV2),
})

MAX_OPS_PER_TX = 100

TransactionV0 = Struct("TransactionV0", [
    ("sourceAccountEd25519", Uint256),
    ("fee", Uint),
    ("seqNum", Hyper),
    ("timeBounds", Option(TimeBounds)),
    ("memo", Memo),
    ("operations", VarArray(Operation, MAX_OPS_PER_TX)),
    ("ext", Union("TransactionV0Ext", Int, {0: ("v0", None)})),
])

TransactionV0Envelope = Struct("TransactionV0Envelope", [
    ("tx", TransactionV0),
    ("signatures", VarArray(DecoratedSignature, 20)),
])

Transaction = Struct("Transaction", [
    ("sourceAccount", MuxedAccount),
    ("fee", Uint),
    ("seqNum", Hyper),
    ("cond", Preconditions),
    ("memo", Memo),
    ("operations", VarArray(Operation, MAX_OPS_PER_TX)),
    ("ext", Union("TransactionExt", Int, {0: ("v0", None)})),
])

TransactionV1Envelope = Struct("TransactionV1Envelope", [
    ("tx", Transaction),
    ("signatures", VarArray(DecoratedSignature, 20)),
])

FeeBumpTransaction = Struct("FeeBumpTransaction", [
    ("feeSource", MuxedAccount),
    ("fee", Hyper),
    ("innerTx", Union("FeeBumpInnerTx", EnvelopeType, {
        EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope),
    })),
    ("ext", Union("FeeBumpTransactionExt", Int, {0: ("v0", None)})),
])

FeeBumpTransactionEnvelope = Struct("FeeBumpTransactionEnvelope", [
    ("tx", FeeBumpTransaction),
    ("signatures", VarArray(DecoratedSignature, 20)),
])

TransactionEnvelope = Union("TransactionEnvelope", EnvelopeType, {
    EnvelopeType.ENVELOPE_TYPE_TX_V0: ("v0", TransactionV0Envelope),
    EnvelopeType.ENVELOPE_TYPE_TX: ("v1", TransactionV1Envelope),
    EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
        ("feeBump", FeeBumpTransactionEnvelope),
})
# an envelope is encoded at admission (hash), flood, tx-set hashing, and
# tx-history persistence — memoize like LedgerEntry
TransactionEnvelope.memoize = True

TransactionSignaturePayload = Struct("TransactionSignaturePayload", [
    ("networkId", Hash),
    ("taggedTransaction",
     Union("TaggedTransaction", EnvelopeType, {
         EnvelopeType.ENVELOPE_TYPE_TX: ("tx", Transaction),
         EnvelopeType.ENVELOPE_TYPE_TX_FEE_BUMP:
             ("feeBump", FeeBumpTransaction),
     })),
])

# ---------------------------------------------------------------------------
# Stellar-transaction.x — results
# ---------------------------------------------------------------------------

ClaimAtomType = Enum("ClaimAtomType", {
    "CLAIM_ATOM_TYPE_V0": 0,
    "CLAIM_ATOM_TYPE_ORDER_BOOK": 1,
    "CLAIM_ATOM_TYPE_LIQUIDITY_POOL": 2,
})

ClaimOfferAtomV0 = Struct("ClaimOfferAtomV0", [
    ("sellerEd25519", Uint256),
    ("offerID", Hyper),
    ("assetSold", Asset),
    ("amountSold", Hyper),
    ("assetBought", Asset),
    ("amountBought", Hyper),
])

ClaimOfferAtom = Struct("ClaimOfferAtom", [
    ("sellerID", AccountID),
    ("offerID", Hyper),
    ("assetSold", Asset),
    ("amountSold", Hyper),
    ("assetBought", Asset),
    ("amountBought", Hyper),
])

ClaimLiquidityAtom = Struct("ClaimLiquidityAtom", [
    ("liquidityPoolID", PoolID),
    ("assetSold", Asset),
    ("amountSold", Hyper),
    ("assetBought", Asset),
    ("amountBought", Hyper),
])

ClaimAtom = Union("ClaimAtom", ClaimAtomType, {
    ClaimAtomType.CLAIM_ATOM_TYPE_V0: ("v0", ClaimOfferAtomV0),
    ClaimAtomType.CLAIM_ATOM_TYPE_ORDER_BOOK: ("orderBook", ClaimOfferAtom),
    ClaimAtomType.CLAIM_ATOM_TYPE_LIQUIDITY_POOL:
        ("liquidityPool", ClaimLiquidityAtom),
})


def _result_enum(name: str, success_names, failure_names):
    """Result-code enum: successes from 0 up-order as listed with their
    index semantics (first success = 0, second = 1 only for tx codes);
    failures numbered -1, -2, ... in listed order."""
    values = {}
    for i, n in enumerate(success_names):
        values[n] = i
    for i, n in enumerate(failure_names):
        values[n] = -(i + 1)
    return Enum(name, values)


def _simple_result(name: str, code_enum: Enum,
                   special: dict = None) -> Union:
    """Result union where most arms are void; ``special`` maps code->arm."""
    arms = {}
    for code_name, v in code_enum.by_name.items():
        if special and v in special:
            arms[v] = special[v]
        else:
            arms[v] = (code_name.lower(), None)
    return Union(name, code_enum, arms)


CreateAccountResultCode = _result_enum(
    "CreateAccountResultCode",
    ["CREATE_ACCOUNT_SUCCESS"],
    ["CREATE_ACCOUNT_MALFORMED", "CREATE_ACCOUNT_UNDERFUNDED",
     "CREATE_ACCOUNT_LOW_RESERVE", "CREATE_ACCOUNT_ALREADY_EXIST"])
CreateAccountResult = _simple_result(
    "CreateAccountResult", CreateAccountResultCode)

PaymentResultCode = _result_enum(
    "PaymentResultCode",
    ["PAYMENT_SUCCESS"],
    ["PAYMENT_MALFORMED", "PAYMENT_UNDERFUNDED", "PAYMENT_SRC_NO_TRUST",
     "PAYMENT_SRC_NOT_AUTHORIZED", "PAYMENT_NO_DESTINATION",
     "PAYMENT_NO_TRUST", "PAYMENT_NOT_AUTHORIZED", "PAYMENT_LINE_FULL",
     "PAYMENT_NO_ISSUER"])
PaymentResult = _simple_result("PaymentResult", PaymentResultCode)

SimplePaymentResult = Struct("SimplePaymentResult", [
    ("destination", AccountID),
    ("asset", Asset),
    ("amount", Hyper),
])

_PathPaymentSuccess = Struct("PathPaymentStrictReceiveSuccess", [
    ("offers", VarArray(ClaimAtom)),
    ("last", SimplePaymentResult),
])

PathPaymentStrictReceiveResultCode = _result_enum(
    "PathPaymentStrictReceiveResultCode",
    ["PATH_PAYMENT_STRICT_RECEIVE_SUCCESS"],
    ["PATH_PAYMENT_STRICT_RECEIVE_MALFORMED",
     "PATH_PAYMENT_STRICT_RECEIVE_UNDERFUNDED",
     "PATH_PAYMENT_STRICT_RECEIVE_SRC_NO_TRUST",
     "PATH_PAYMENT_STRICT_RECEIVE_SRC_NOT_AUTHORIZED",
     "PATH_PAYMENT_STRICT_RECEIVE_NO_DESTINATION",
     "PATH_PAYMENT_STRICT_RECEIVE_NO_TRUST",
     "PATH_PAYMENT_STRICT_RECEIVE_NOT_AUTHORIZED",
     "PATH_PAYMENT_STRICT_RECEIVE_LINE_FULL",
     "PATH_PAYMENT_STRICT_RECEIVE_NO_ISSUER",
     "PATH_PAYMENT_STRICT_RECEIVE_TOO_FEW_OFFERS",
     "PATH_PAYMENT_STRICT_RECEIVE_OFFER_CROSS_SELF",
     "PATH_PAYMENT_STRICT_RECEIVE_OVER_SENDMAX"])
PathPaymentStrictReceiveResult = _simple_result(
    "PathPaymentStrictReceiveResult", PathPaymentStrictReceiveResultCode,
    {0: ("success", _PathPaymentSuccess),
     -9: ("noIssuer", Asset)})

_PathPaymentSendSuccess = Struct("PathPaymentStrictSendSuccess", [
    ("offers", VarArray(ClaimAtom)),
    ("last", SimplePaymentResult),
])

PathPaymentStrictSendResultCode = _result_enum(
    "PathPaymentStrictSendResultCode",
    ["PATH_PAYMENT_STRICT_SEND_SUCCESS"],
    ["PATH_PAYMENT_STRICT_SEND_MALFORMED",
     "PATH_PAYMENT_STRICT_SEND_UNDERFUNDED",
     "PATH_PAYMENT_STRICT_SEND_SRC_NO_TRUST",
     "PATH_PAYMENT_STRICT_SEND_SRC_NOT_AUTHORIZED",
     "PATH_PAYMENT_STRICT_SEND_NO_DESTINATION",
     "PATH_PAYMENT_STRICT_SEND_NO_TRUST",
     "PATH_PAYMENT_STRICT_SEND_NOT_AUTHORIZED",
     "PATH_PAYMENT_STRICT_SEND_LINE_FULL",
     "PATH_PAYMENT_STRICT_SEND_NO_ISSUER",
     "PATH_PAYMENT_STRICT_SEND_TOO_FEW_OFFERS",
     "PATH_PAYMENT_STRICT_SEND_OFFER_CROSS_SELF",
     "PATH_PAYMENT_STRICT_SEND_UNDER_DESTMIN"])
PathPaymentStrictSendResult = _simple_result(
    "PathPaymentStrictSendResult", PathPaymentStrictSendResultCode,
    {0: ("success", _PathPaymentSendSuccess),
     -9: ("noIssuer", Asset)})

ManageSellOfferResultCode = _result_enum(
    "ManageSellOfferResultCode",
    ["MANAGE_SELL_OFFER_SUCCESS"],
    ["MANAGE_SELL_OFFER_MALFORMED", "MANAGE_SELL_OFFER_SELL_NO_TRUST",
     "MANAGE_SELL_OFFER_BUY_NO_TRUST",
     "MANAGE_SELL_OFFER_SELL_NOT_AUTHORIZED",
     "MANAGE_SELL_OFFER_BUY_NOT_AUTHORIZED", "MANAGE_SELL_OFFER_LINE_FULL",
     "MANAGE_SELL_OFFER_UNDERFUNDED", "MANAGE_SELL_OFFER_CROSS_SELF",
     "MANAGE_SELL_OFFER_SELL_NO_ISSUER", "MANAGE_SELL_OFFER_BUY_NO_ISSUER",
     "MANAGE_SELL_OFFER_NOT_FOUND", "MANAGE_SELL_OFFER_LOW_RESERVE"])

ManageOfferEffect = Enum("ManageOfferEffect", {
    "MANAGE_OFFER_CREATED": 0,
    "MANAGE_OFFER_UPDATED": 1,
    "MANAGE_OFFER_DELETED": 2,
})

ManageOfferSuccessResult = Struct("ManageOfferSuccessResult", [
    ("offersClaimed", VarArray(ClaimAtom)),
    ("offer", Union("ManageOfferSuccessResultOffer", ManageOfferEffect, {
        ManageOfferEffect.MANAGE_OFFER_CREATED: ("offer", OfferEntry),
        ManageOfferEffect.MANAGE_OFFER_UPDATED: ("offer", OfferEntry),
        ManageOfferEffect.MANAGE_OFFER_DELETED: ("deleted", None),
    })),
])

ManageSellOfferResult = _simple_result(
    "ManageSellOfferResult", ManageSellOfferResultCode,
    {0: ("success", ManageOfferSuccessResult)})

ManageBuyOfferResultCode = _result_enum(
    "ManageBuyOfferResultCode",
    ["MANAGE_BUY_OFFER_SUCCESS"],
    ["MANAGE_BUY_OFFER_MALFORMED", "MANAGE_BUY_OFFER_SELL_NO_TRUST",
     "MANAGE_BUY_OFFER_BUY_NO_TRUST", "MANAGE_BUY_OFFER_SELL_NOT_AUTHORIZED",
     "MANAGE_BUY_OFFER_BUY_NOT_AUTHORIZED", "MANAGE_BUY_OFFER_LINE_FULL",
     "MANAGE_BUY_OFFER_UNDERFUNDED", "MANAGE_BUY_OFFER_CROSS_SELF",
     "MANAGE_BUY_OFFER_SELL_NO_ISSUER", "MANAGE_BUY_OFFER_BUY_NO_ISSUER",
     "MANAGE_BUY_OFFER_NOT_FOUND", "MANAGE_BUY_OFFER_LOW_RESERVE"])
ManageBuyOfferResult = _simple_result(
    "ManageBuyOfferResult", ManageBuyOfferResultCode,
    {0: ("success", ManageOfferSuccessResult)})

SetOptionsResultCode = _result_enum(
    "SetOptionsResultCode",
    ["SET_OPTIONS_SUCCESS"],
    ["SET_OPTIONS_LOW_RESERVE", "SET_OPTIONS_TOO_MANY_SIGNERS",
     "SET_OPTIONS_BAD_FLAGS", "SET_OPTIONS_INVALID_INFLATION",
     "SET_OPTIONS_CANT_CHANGE", "SET_OPTIONS_UNKNOWN_FLAG",
     "SET_OPTIONS_THRESHOLD_OUT_OF_RANGE", "SET_OPTIONS_BAD_SIGNER",
     "SET_OPTIONS_INVALID_HOME_DOMAIN",
     "SET_OPTIONS_AUTH_REVOCABLE_REQUIRED"])
SetOptionsResult = _simple_result("SetOptionsResult", SetOptionsResultCode)

ChangeTrustResultCode = _result_enum(
    "ChangeTrustResultCode",
    ["CHANGE_TRUST_SUCCESS"],
    ["CHANGE_TRUST_MALFORMED", "CHANGE_TRUST_NO_ISSUER",
     "CHANGE_TRUST_INVALID_LIMIT", "CHANGE_TRUST_LOW_RESERVE",
     "CHANGE_TRUST_SELF_NOT_ALLOWED", "CHANGE_TRUST_TRUST_LINE_MISSING",
     "CHANGE_TRUST_CANNOT_DELETE",
     "CHANGE_TRUST_NOT_AUTH_MAINTAIN_LIABILITIES"])
ChangeTrustResult = _simple_result("ChangeTrustResult", ChangeTrustResultCode)

AllowTrustResultCode = _result_enum(
    "AllowTrustResultCode",
    ["ALLOW_TRUST_SUCCESS"],
    ["ALLOW_TRUST_MALFORMED", "ALLOW_TRUST_NO_TRUST_LINE",
     "ALLOW_TRUST_TRUST_NOT_REQUIRED", "ALLOW_TRUST_CANT_REVOKE",
     "ALLOW_TRUST_SELF_NOT_ALLOWED", "ALLOW_TRUST_LOW_RESERVE"])
AllowTrustResult = _simple_result("AllowTrustResult", AllowTrustResultCode)

AccountMergeResultCode = _result_enum(
    "AccountMergeResultCode",
    ["ACCOUNT_MERGE_SUCCESS"],
    ["ACCOUNT_MERGE_MALFORMED", "ACCOUNT_MERGE_NO_ACCOUNT",
     "ACCOUNT_MERGE_IMMUTABLE_SET", "ACCOUNT_MERGE_HAS_SUB_ENTRIES",
     "ACCOUNT_MERGE_SEQNUM_TOO_FAR", "ACCOUNT_MERGE_DEST_FULL",
     "ACCOUNT_MERGE_IS_SPONSOR"])
AccountMergeResult = _simple_result(
    "AccountMergeResult", AccountMergeResultCode,
    {0: ("sourceAccountBalance", Hyper)})

InflationResultCode = _result_enum(
    "InflationResultCode", ["INFLATION_SUCCESS"], ["INFLATION_NOT_TIME"])

InflationPayout = Struct("InflationPayout", [
    ("destination", AccountID),
    ("amount", Hyper),
])

InflationResult = _simple_result(
    "InflationResult", InflationResultCode,
    {0: ("payouts", VarArray(InflationPayout))})

ManageDataResultCode = _result_enum(
    "ManageDataResultCode",
    ["MANAGE_DATA_SUCCESS"],
    ["MANAGE_DATA_NOT_SUPPORTED_YET", "MANAGE_DATA_NAME_NOT_FOUND",
     "MANAGE_DATA_LOW_RESERVE", "MANAGE_DATA_INVALID_NAME"])
ManageDataResult = _simple_result("ManageDataResult", ManageDataResultCode)

BumpSequenceResultCode = _result_enum(
    "BumpSequenceResultCode",
    ["BUMP_SEQUENCE_SUCCESS"], ["BUMP_SEQUENCE_BAD_SEQ"])
BumpSequenceResult = _simple_result(
    "BumpSequenceResult", BumpSequenceResultCode)

CreateClaimableBalanceResultCode = _result_enum(
    "CreateClaimableBalanceResultCode",
    ["CREATE_CLAIMABLE_BALANCE_SUCCESS"],
    ["CREATE_CLAIMABLE_BALANCE_MALFORMED",
     "CREATE_CLAIMABLE_BALANCE_LOW_RESERVE",
     "CREATE_CLAIMABLE_BALANCE_NO_TRUST",
     "CREATE_CLAIMABLE_BALANCE_NOT_AUTHORIZED",
     "CREATE_CLAIMABLE_BALANCE_UNDERFUNDED"])
CreateClaimableBalanceResult = _simple_result(
    "CreateClaimableBalanceResult", CreateClaimableBalanceResultCode,
    {0: ("balanceID", ClaimableBalanceID)})

ClaimClaimableBalanceResultCode = _result_enum(
    "ClaimClaimableBalanceResultCode",
    ["CLAIM_CLAIMABLE_BALANCE_SUCCESS"],
    ["CLAIM_CLAIMABLE_BALANCE_DOES_NOT_EXIST",
     "CLAIM_CLAIMABLE_BALANCE_CANNOT_CLAIM",
     "CLAIM_CLAIMABLE_BALANCE_LINE_FULL",
     "CLAIM_CLAIMABLE_BALANCE_NO_TRUST",
     "CLAIM_CLAIMABLE_BALANCE_NOT_AUTHORIZED"])
ClaimClaimableBalanceResult = _simple_result(
    "ClaimClaimableBalanceResult", ClaimClaimableBalanceResultCode)

BeginSponsoringFutureReservesResultCode = _result_enum(
    "BeginSponsoringFutureReservesResultCode",
    ["BEGIN_SPONSORING_FUTURE_RESERVES_SUCCESS"],
    ["BEGIN_SPONSORING_FUTURE_RESERVES_MALFORMED",
     "BEGIN_SPONSORING_FUTURE_RESERVES_ALREADY_SPONSORED",
     "BEGIN_SPONSORING_FUTURE_RESERVES_RECURSIVE"])
BeginSponsoringFutureReservesResult = _simple_result(
    "BeginSponsoringFutureReservesResult",
    BeginSponsoringFutureReservesResultCode)

EndSponsoringFutureReservesResultCode = _result_enum(
    "EndSponsoringFutureReservesResultCode",
    ["END_SPONSORING_FUTURE_RESERVES_SUCCESS"],
    ["END_SPONSORING_FUTURE_RESERVES_NOT_SPONSORED"])
EndSponsoringFutureReservesResult = _simple_result(
    "EndSponsoringFutureReservesResult",
    EndSponsoringFutureReservesResultCode)

RevokeSponsorshipResultCode = _result_enum(
    "RevokeSponsorshipResultCode",
    ["REVOKE_SPONSORSHIP_SUCCESS"],
    ["REVOKE_SPONSORSHIP_DOES_NOT_EXIST", "REVOKE_SPONSORSHIP_NOT_SPONSOR",
     "REVOKE_SPONSORSHIP_LOW_RESERVE",
     "REVOKE_SPONSORSHIP_ONLY_TRANSFERABLE", "REVOKE_SPONSORSHIP_MALFORMED"])
RevokeSponsorshipResult = _simple_result(
    "RevokeSponsorshipResult", RevokeSponsorshipResultCode)

ClawbackResultCode = _result_enum(
    "ClawbackResultCode",
    ["CLAWBACK_SUCCESS"],
    ["CLAWBACK_MALFORMED", "CLAWBACK_NOT_CLAWBACK_ENABLED",
     "CLAWBACK_NO_TRUST", "CLAWBACK_UNDERFUNDED"])
ClawbackResult = _simple_result("ClawbackResult", ClawbackResultCode)

ClawbackClaimableBalanceResultCode = _result_enum(
    "ClawbackClaimableBalanceResultCode",
    ["CLAWBACK_CLAIMABLE_BALANCE_SUCCESS"],
    ["CLAWBACK_CLAIMABLE_BALANCE_DOES_NOT_EXIST",
     "CLAWBACK_CLAIMABLE_BALANCE_NOT_ISSUER",
     "CLAWBACK_CLAIMABLE_BALANCE_NOT_CLAWBACK_ENABLED"])
ClawbackClaimableBalanceResult = _simple_result(
    "ClawbackClaimableBalanceResult", ClawbackClaimableBalanceResultCode)

SetTrustLineFlagsResultCode = _result_enum(
    "SetTrustLineFlagsResultCode",
    ["SET_TRUST_LINE_FLAGS_SUCCESS"],
    ["SET_TRUST_LINE_FLAGS_MALFORMED",
     "SET_TRUST_LINE_FLAGS_NO_TRUST_LINE",
     "SET_TRUST_LINE_FLAGS_CANT_REVOKE",
     "SET_TRUST_LINE_FLAGS_INVALID_STATE",
     "SET_TRUST_LINE_FLAGS_LOW_RESERVE"])
SetTrustLineFlagsResult = _simple_result(
    "SetTrustLineFlagsResult", SetTrustLineFlagsResultCode)

LiquidityPoolDepositResultCode = _result_enum(
    "LiquidityPoolDepositResultCode",
    ["LIQUIDITY_POOL_DEPOSIT_SUCCESS"],
    ["LIQUIDITY_POOL_DEPOSIT_MALFORMED", "LIQUIDITY_POOL_DEPOSIT_NO_TRUST",
     "LIQUIDITY_POOL_DEPOSIT_NOT_AUTHORIZED",
     "LIQUIDITY_POOL_DEPOSIT_UNDERFUNDED",
     "LIQUIDITY_POOL_DEPOSIT_LINE_FULL", "LIQUIDITY_POOL_DEPOSIT_BAD_PRICE",
     "LIQUIDITY_POOL_DEPOSIT_POOL_FULL"])
LiquidityPoolDepositResult = _simple_result(
    "LiquidityPoolDepositResult", LiquidityPoolDepositResultCode)

LiquidityPoolWithdrawResultCode = _result_enum(
    "LiquidityPoolWithdrawResultCode",
    ["LIQUIDITY_POOL_WITHDRAW_SUCCESS"],
    ["LIQUIDITY_POOL_WITHDRAW_MALFORMED",
     "LIQUIDITY_POOL_WITHDRAW_NO_TRUST",
     "LIQUIDITY_POOL_WITHDRAW_UNDERFUNDED",
     "LIQUIDITY_POOL_WITHDRAW_LINE_FULL",
     "LIQUIDITY_POOL_WITHDRAW_UNDER_MINIMUM"])
LiquidityPoolWithdrawResult = _simple_result(
    "LiquidityPoolWithdrawResult", LiquidityPoolWithdrawResultCode)

OperationResultCode = Enum("OperationResultCode", {
    "opINNER": 0,
    "opBAD_AUTH": -1,
    "opNO_ACCOUNT": -2,
    "opNOT_SUPPORTED": -3,
    "opTOO_MANY_SUBENTRIES": -4,
    "opEXCEEDED_WORK_LIMIT": -5,
    "opTOO_MANY_SPONSORING": -6,
})

OperationResultTr = Union("OperationResultTr", OperationType, {
    OperationType.CREATE_ACCOUNT:
        ("createAccountResult", CreateAccountResult),
    OperationType.PAYMENT: ("paymentResult", PaymentResult),
    OperationType.PATH_PAYMENT_STRICT_RECEIVE:
        ("pathPaymentStrictReceiveResult", PathPaymentStrictReceiveResult),
    OperationType.MANAGE_SELL_OFFER:
        ("manageSellOfferResult", ManageSellOfferResult),
    OperationType.CREATE_PASSIVE_SELL_OFFER:
        ("createPassiveSellOfferResult", ManageSellOfferResult),
    OperationType.SET_OPTIONS: ("setOptionsResult", SetOptionsResult),
    OperationType.CHANGE_TRUST: ("changeTrustResult", ChangeTrustResult),
    OperationType.ALLOW_TRUST: ("allowTrustResult", AllowTrustResult),
    OperationType.ACCOUNT_MERGE: ("accountMergeResult", AccountMergeResult),
    OperationType.INFLATION: ("inflationResult", InflationResult),
    OperationType.MANAGE_DATA: ("manageDataResult", ManageDataResult),
    OperationType.BUMP_SEQUENCE: ("bumpSeqResult", BumpSequenceResult),
    OperationType.MANAGE_BUY_OFFER:
        ("manageBuyOfferResult", ManageBuyOfferResult),
    OperationType.PATH_PAYMENT_STRICT_SEND:
        ("pathPaymentStrictSendResult", PathPaymentStrictSendResult),
    OperationType.CREATE_CLAIMABLE_BALANCE:
        ("createClaimableBalanceResult", CreateClaimableBalanceResult),
    OperationType.CLAIM_CLAIMABLE_BALANCE:
        ("claimClaimableBalanceResult", ClaimClaimableBalanceResult),
    OperationType.BEGIN_SPONSORING_FUTURE_RESERVES:
        ("beginSponsoringFutureReservesResult",
         BeginSponsoringFutureReservesResult),
    OperationType.END_SPONSORING_FUTURE_RESERVES:
        ("endSponsoringFutureReservesResult",
         EndSponsoringFutureReservesResult),
    OperationType.REVOKE_SPONSORSHIP:
        ("revokeSponsorshipResult", RevokeSponsorshipResult),
    OperationType.CLAWBACK: ("clawbackResult", ClawbackResult),
    OperationType.CLAWBACK_CLAIMABLE_BALANCE:
        ("clawbackClaimableBalanceResult", ClawbackClaimableBalanceResult),
    OperationType.SET_TRUST_LINE_FLAGS:
        ("setTrustLineFlagsResult", SetTrustLineFlagsResult),
    OperationType.LIQUIDITY_POOL_DEPOSIT:
        ("liquidityPoolDepositResult", LiquidityPoolDepositResult),
    OperationType.LIQUIDITY_POOL_WITHDRAW:
        ("liquidityPoolWithdrawResult", LiquidityPoolWithdrawResult),
})

OperationResult = Union("OperationResult", OperationResultCode, {
    OperationResultCode.opINNER: ("tr", OperationResultTr),
    OperationResultCode.opBAD_AUTH: ("opBAD_AUTH", None),
    OperationResultCode.opNO_ACCOUNT: ("opNO_ACCOUNT", None),
    OperationResultCode.opNOT_SUPPORTED: ("opNOT_SUPPORTED", None),
    OperationResultCode.opTOO_MANY_SUBENTRIES:
        ("opTOO_MANY_SUBENTRIES", None),
    OperationResultCode.opEXCEEDED_WORK_LIMIT:
        ("opEXCEEDED_WORK_LIMIT", None),
    OperationResultCode.opTOO_MANY_SPONSORING:
        ("opTOO_MANY_SPONSORING", None),
})

TransactionResultCode = Enum("TransactionResultCode", {
    "txFEE_BUMP_INNER_SUCCESS": 1,
    "txSUCCESS": 0,
    "txFAILED": -1,
    "txTOO_EARLY": -2,
    "txTOO_LATE": -3,
    "txMISSING_OPERATION": -4,
    "txBAD_SEQ": -5,
    "txBAD_AUTH": -6,
    "txINSUFFICIENT_BALANCE": -7,
    "txNO_ACCOUNT": -8,
    "txINSUFFICIENT_FEE": -9,
    "txBAD_AUTH_EXTRA": -10,
    "txINTERNAL_ERROR": -11,
    "txNOT_SUPPORTED": -12,
    "txFEE_BUMP_INNER_FAILED": -13,
    "txBAD_SPONSORSHIP": -14,
    "txBAD_MIN_SEQ_AGE_OR_GAP": -15,
    "txMALFORMED": -16,
})

# txFEE_BUMP_INNER_SUCCESS / txFEE_BUMP_INNER_FAILED are NOT valid inside an
# inner result — enumerate the void arms instead of a catch-all default so
# decode rejects them like the reference's generated codec.
_inner_tx_arms = {
    TransactionResultCode.txSUCCESS: ("results", VarArray(OperationResult)),
    TransactionResultCode.txFAILED: ("results", VarArray(OperationResult)),
}
_inner_tx_arms.update({
    code: (name.lower(), None)
    for name, code in TransactionResultCode.by_name.items()
    if code not in (1, 0, -1, -13)
})
_InnerTxResultResult = Union(
    "InnerTransactionResultResult", TransactionResultCode, _inner_tx_arms)

InnerTransactionResult = Struct("InnerTransactionResult", [
    ("feeCharged", Hyper),
    ("result", _InnerTxResultResult),
    ("ext", Union("InnerTransactionResultExt", Int, {0: ("v0", None)})),
])

InnerTransactionResultPair = Struct("InnerTransactionResultPair", [
    ("transactionHash", Hash),
    ("result", InnerTransactionResult),
])

_TxResultResult = Union(
    "TransactionResultResult", TransactionResultCode,
    {
        TransactionResultCode.txFEE_BUMP_INNER_SUCCESS:
            ("innerResultPair", InnerTransactionResultPair),
        TransactionResultCode.txFEE_BUMP_INNER_FAILED:
            ("innerResultPair", InnerTransactionResultPair),
        TransactionResultCode.txSUCCESS:
            ("results", VarArray(OperationResult)),
        TransactionResultCode.txFAILED:
            ("results", VarArray(OperationResult)),
    },
    default=("void", None))

TransactionResult = Struct("TransactionResult", [
    ("feeCharged", Hyper),
    ("result", _TxResultResult),
    ("ext", Union("TransactionResultExt", Int, {0: ("v0", None)})),
])

# ---------------------------------------------------------------------------
# Stellar-SCP.x
# ---------------------------------------------------------------------------

Value = VarOpaque()

SCPBallot = Struct("SCPBallot", [
    ("counter", Uint),
    ("value", Value),
])

SCPStatementType = Enum("SCPStatementType", {
    "SCP_ST_PREPARE": 0,
    "SCP_ST_CONFIRM": 1,
    "SCP_ST_EXTERNALIZE": 2,
    "SCP_ST_NOMINATE": 3,
})

SCPNomination = Struct("SCPNomination", [
    ("quorumSetHash", Hash),
    ("votes", VarArray(Value)),
    ("accepted", VarArray(Value)),
])

_SCPPrepare = Struct("SCPStatementPrepare", [
    ("quorumSetHash", Hash),
    ("ballot", SCPBallot),
    ("prepared", Option(SCPBallot)),
    ("preparedPrime", Option(SCPBallot)),
    ("nC", Uint),
    ("nH", Uint),
])

_SCPConfirm = Struct("SCPStatementConfirm", [
    ("ballot", SCPBallot),
    ("nPrepared", Uint),
    ("nCommit", Uint),
    ("nH", Uint),
    ("quorumSetHash", Hash),
])

_SCPExternalize = Struct("SCPStatementExternalize", [
    ("commit", SCPBallot),
    ("nH", Uint),
    ("commitQuorumSetHash", Hash),
])

SCPStatementPledges = Union("SCPStatementPledges", SCPStatementType, {
    SCPStatementType.SCP_ST_PREPARE: ("prepare", _SCPPrepare),
    SCPStatementType.SCP_ST_CONFIRM: ("confirm", _SCPConfirm),
    SCPStatementType.SCP_ST_EXTERNALIZE: ("externalize", _SCPExternalize),
    SCPStatementType.SCP_ST_NOMINATE: ("nominate", SCPNomination),
})

SCPStatement = Struct("SCPStatement", [
    ("nodeID", NodeID),
    ("slotIndex", Uhyper),
    ("pledges", SCPStatementPledges),
])

SCPEnvelope = Struct("SCPEnvelope", [
    ("statement", SCPStatement),
    ("signature", Signature),
])
# statements/envelopes re-encode constantly on the flood path (MAC per
# peer send, floodgate dedup id, signature body at every receiving
# node); both are construct-once values — the single post-construction
# mutation site (HerderSCPDriver.sign_envelope setting .signature)
# drops the envelope memo explicitly
SCPStatement.memoize = True
SCPEnvelope.memoize = True

SCPQuorumSet = Struct("SCPQuorumSet", [
    ("threshold", Uint),
    ("validators", VarArray(NodeID)),
    ("innerSets", VarArray(Lazy(lambda: SCPQuorumSet))),
])

# ---------------------------------------------------------------------------
# Stellar-ledger.x
# ---------------------------------------------------------------------------

UpgradeType = VarOpaque(128)

StellarValueType = Enum("StellarValueType", {
    "STELLAR_VALUE_BASIC": 0,
    "STELLAR_VALUE_SIGNED": 1,
})

LedgerCloseValueSignature = Struct("LedgerCloseValueSignature", [
    ("nodeID", NodeID),
    ("signature", Signature),
])

StellarValue = Struct("StellarValue", [
    ("txSetHash", Hash),
    ("closeTime", Uhyper),
    ("upgrades", VarArray(UpgradeType, 6)),
    ("ext", Union("StellarValueExt", StellarValueType, {
        StellarValueType.STELLAR_VALUE_BASIC: ("basic", None),
        StellarValueType.STELLAR_VALUE_SIGNED:
            ("lcValueSignature", LedgerCloseValueSignature),
    })),
])

MASK_LEDGER_HEADER_FLAGS = 0x7

LedgerHeaderFlags = Enum("LedgerHeaderFlags", {
    "DISABLE_LIQUIDITY_POOL_TRADING_FLAG": 0x1,
    "DISABLE_LIQUIDITY_POOL_DEPOSIT_FLAG": 0x2,
    "DISABLE_LIQUIDITY_POOL_WITHDRAWAL_FLAG": 0x4,
})

LedgerHeaderExtensionV1 = Struct("LedgerHeaderExtensionV1", [
    ("flags", Uint),
    ("ext", Union("LedgerHeaderExtensionV1Ext", Int, {0: ("v0", None)})),
])

LedgerHeader = Struct("LedgerHeader", [
    ("ledgerVersion", Uint),
    ("previousLedgerHash", Hash),
    ("scpValue", StellarValue),
    ("txSetResultHash", Hash),
    ("bucketListHash", Hash),
    ("ledgerSeq", Uint),
    ("totalCoins", Hyper),
    ("feePool", Hyper),
    ("inflationSeq", Uint),
    ("idPool", Uhyper),
    ("baseFee", Uint),
    ("baseReserve", Uint),
    ("maxTxSetSize", Uint),
    ("skipList", FixedArray(Hash, 4)),
    ("ext", Union("LedgerHeaderExt", Int, {
        0: ("v0", None),
        1: ("v1", LedgerHeaderExtensionV1),
    })),
])

LedgerUpgradeType = Enum("LedgerUpgradeType", {
    "LEDGER_UPGRADE_VERSION": 1,
    "LEDGER_UPGRADE_BASE_FEE": 2,
    "LEDGER_UPGRADE_MAX_TX_SET_SIZE": 3,
    "LEDGER_UPGRADE_BASE_RESERVE": 4,
    "LEDGER_UPGRADE_FLAGS": 5,
})

LedgerUpgrade = Union("LedgerUpgrade", LedgerUpgradeType, {
    LedgerUpgradeType.LEDGER_UPGRADE_VERSION: ("newLedgerVersion", Uint),
    LedgerUpgradeType.LEDGER_UPGRADE_BASE_FEE: ("newBaseFee", Uint),
    LedgerUpgradeType.LEDGER_UPGRADE_MAX_TX_SET_SIZE:
        ("newMaxTxSetSize", Uint),
    LedgerUpgradeType.LEDGER_UPGRADE_BASE_RESERVE: ("newBaseReserve", Uint),
    LedgerUpgradeType.LEDGER_UPGRADE_FLAGS: ("newFlags", Uint),
})

BucketEntryType = Enum("BucketEntryType", {
    "METAENTRY": -1,
    "LIVEENTRY": 0,
    "DEADENTRY": 1,
    "INITENTRY": 2,
})

BucketMetadata = Struct("BucketMetadata", [
    ("ledgerVersion", Uint),
    ("ext", Union("BucketMetadataExt", Int, {0: ("v0", None)})),
])

BucketEntry = Union("BucketEntry", BucketEntryType, {
    BucketEntryType.LIVEENTRY: ("liveEntry", LedgerEntry),
    BucketEntryType.INITENTRY: ("liveEntry", LedgerEntry),
    BucketEntryType.DEADENTRY: ("deadEntry", LedgerKey),
    BucketEntryType.METAENTRY: ("metaEntry", BucketMetadata),
})

TxSetComponentType = Enum("TxSetComponentType", {
    "TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE": 0,
})

_TxsMaybeDiscountedFee = Struct("TxsMaybeDiscountedFee", [
    ("baseFee", Option(Hyper)),
    ("txs", VarArray(TransactionEnvelope)),
])

TxSetComponent = Union("TxSetComponent", TxSetComponentType, {
    TxSetComponentType.TXSET_COMP_TXS_MAYBE_DISCOUNTED_FEE:
        ("txsMaybeDiscountedFee", _TxsMaybeDiscountedFee),
})

TransactionPhase = Union("TransactionPhase", Int, {
    0: ("v0Components", VarArray(TxSetComponent)),
})

TransactionSet = Struct("TransactionSet", [
    ("previousLedgerHash", Hash),
    ("txs", VarArray(TransactionEnvelope)),
])

TransactionSetV1 = Struct("TransactionSetV1", [
    ("previousLedgerHash", Hash),
    ("phases", VarArray(TransactionPhase)),
])

GeneralizedTransactionSet = Union("GeneralizedTransactionSet", Int, {
    1: ("v1TxSet", TransactionSetV1),
})

TransactionResultPair = Struct("TransactionResultPair", [
    ("transactionHash", Hash),
    ("result", TransactionResult),
])

TransactionResultSet = Struct("TransactionResultSet", [
    ("results", VarArray(TransactionResultPair)),
])

TransactionHistoryEntry = Struct("TransactionHistoryEntry", [
    ("ledgerSeq", Uint),
    ("txSet", TransactionSet),
    ("ext", Union("TransactionHistoryEntryExt", Int, {
        0: ("v0", None),
        1: ("generalizedTxSet", GeneralizedTransactionSet),
    })),
])

TransactionHistoryResultEntry = Struct("TransactionHistoryResultEntry", [
    ("ledgerSeq", Uint),
    ("txResultSet", TransactionResultSet),
    ("ext", Union("TransactionHistoryResultEntryExt", Int,
                  {0: ("v0", None)})),
])

LedgerHeaderHistoryEntry = Struct("LedgerHeaderHistoryEntry", [
    ("hash", Hash),
    ("header", LedgerHeader),
    ("ext", Union("LedgerHeaderHistoryEntryExt", Int, {0: ("v0", None)})),
])

LedgerSCPMessages = Struct("LedgerSCPMessages", [
    ("ledgerSeq", Uint),
    ("messages", VarArray(SCPEnvelope)),
])

SCPHistoryEntryV0 = Struct("SCPHistoryEntryV0", [
    ("quorumSets", VarArray(SCPQuorumSet)),
    ("ledgerMessages", LedgerSCPMessages),
])

SCPHistoryEntry = Union("SCPHistoryEntry", Int, {
    0: ("v0", SCPHistoryEntryV0),
})

LedgerEntryChangeType = Enum("LedgerEntryChangeType", {
    "LEDGER_ENTRY_CREATED": 0,
    "LEDGER_ENTRY_UPDATED": 1,
    "LEDGER_ENTRY_REMOVED": 2,
    "LEDGER_ENTRY_STATE": 3,
})

LedgerEntryChange = Union("LedgerEntryChange", LedgerEntryChangeType, {
    LedgerEntryChangeType.LEDGER_ENTRY_CREATED: ("created", LedgerEntry),
    LedgerEntryChangeType.LEDGER_ENTRY_UPDATED: ("updated", LedgerEntry),
    LedgerEntryChangeType.LEDGER_ENTRY_REMOVED: ("removed", LedgerKey),
    LedgerEntryChangeType.LEDGER_ENTRY_STATE: ("state", LedgerEntry),
})

LedgerEntryChanges = VarArray(LedgerEntryChange)

OperationMeta = Struct("OperationMeta", [
    ("changes", LedgerEntryChanges),
])

TransactionMetaV1 = Struct("TransactionMetaV1", [
    ("txChanges", LedgerEntryChanges),
    ("operations", VarArray(OperationMeta)),
])

TransactionMetaV2 = Struct("TransactionMetaV2", [
    ("txChangesBefore", LedgerEntryChanges),
    ("operations", VarArray(OperationMeta)),
    ("txChangesAfter", LedgerEntryChanges),
])

TransactionMeta = Union("TransactionMeta", Int, {
    0: ("operations", VarArray(OperationMeta)),
    1: ("v1", TransactionMetaV1),
    2: ("v2", TransactionMetaV2),
})

TransactionResultMeta = Struct("TransactionResultMeta", [
    ("result", TransactionResultPair),
    ("feeProcessing", LedgerEntryChanges),
    ("txApplyProcessing", TransactionMeta),
])

UpgradeEntryMeta = Struct("UpgradeEntryMeta", [
    ("upgrade", LedgerUpgrade),
    ("changes", LedgerEntryChanges),
])

LedgerCloseMetaV0 = Struct("LedgerCloseMetaV0", [
    ("ledgerHeader", LedgerHeaderHistoryEntry),
    ("txSet", TransactionSet),
    ("txProcessing", VarArray(TransactionResultMeta)),
    ("upgradesProcessing", VarArray(UpgradeEntryMeta)),
    ("scpInfo", VarArray(SCPHistoryEntry)),
])

LedgerCloseMetaV1 = Struct("LedgerCloseMetaV1", [
    ("ledgerHeader", LedgerHeaderHistoryEntry),
    ("txSet", GeneralizedTransactionSet),
    ("txProcessing", VarArray(TransactionResultMeta)),
    ("upgradesProcessing", VarArray(UpgradeEntryMeta)),
    ("scpInfo", VarArray(SCPHistoryEntry)),
])

LedgerCloseMeta = Union("LedgerCloseMeta", Int, {
    0: ("v0", LedgerCloseMetaV0),
    1: ("v1", LedgerCloseMetaV1),
})

# results + metas are encoded 2-3x per close (result-set hash, txhistory
# row, ledger-close meta stream) — cache the first encoding on the value
TransactionResultPair.memoize = True
TransactionMeta.memoize = True
# the batched fee kernel returns feeProcessing changes pre-encoded; the
# memo slot lets LazyUnion carry those bytes straight into the meta
LedgerEntryChange.memoize = True

# route encode() through the native schema-VM packer when the toolchain
# can build it (native/xdr_pack.c); wire-identical, Python pack remains
# the oracle and fallback
import sys as _sys

from .runtime import enable_native_encode as _enable_native_encode

# import stays cheap: only an already-built extension is used here; node
# startup (Application.start) retries with build=True and flips this on
NATIVE_ENCODE = _enable_native_encode(_sys.modules[__name__], build=False)


def ensure_native_encode() -> bool:
    """Build + enable the native encoder (idempotent; called from
    Application.start so every node process gets it)."""
    global NATIVE_ENCODE
    if not NATIVE_ENCODE:
        NATIVE_ENCODE = _enable_native_encode(
            _sys.modules[__name__], build=True)
    return NATIVE_ENCODE
