"""XDR (RFC 4506) runtime: declarative type combinators.

The reference generates C++ codecs from the protocol ``.x`` files with
xdrpp's ``xdrc`` (ref src/Makefile.am:42-47); XDR is the wire *and*
canonical-hash format for everything (ref docs/architecture.md:52-54).
This module is the equivalent runtime, redesigned for Python: declarative
combinator objects with ``pack``/``unpack``, over which
``stellar_core_tpu.xdr.types`` declares the protocol schema.

Canonicality matters: every codec here round-trips to the unique canonical
byte form (big-endian, 4-byte alignment, zero padding), so
``sha256(pack(x))`` is usable as an object id exactly like the reference's
``xdrSha256`` (ref src/crypto/SHA.h).
"""
from __future__ import annotations

import struct
from dataclasses import dataclass
from typing import Any, Callable, Dict, List, Optional as Opt, Sequence, Tuple


class XdrError(Exception):
    pass


# Wire-facing decode depth bound.  Legitimate protocol structures nest
# single digits deep (quorum sets are validity-bounded at 4); the guard
# exists to turn adversarial nesting into XdrError.  It must trip well
# before CPython's recursion limit does — each XDR level costs ~6
# interpreter frames, so 100 levels stays comfortably inside the default
# 1000-frame limit even under pytest's extra stack.
MAX_DECODE_DEPTH = 100


class Reader:
    __slots__ = ("data", "pos", "depth")

    def __init__(self, data: bytes):
        self.data = data
        self.pos = 0
        self.depth = 0

    def take(self, n: int) -> bytes:
        if self.pos + n > len(self.data):
            raise XdrError("short read")
        out = self.data[self.pos:self.pos + n]
        self.pos += n
        return out

    def enter(self) -> None:
        """Depth guard for recursive types: adversarial deeply-nested
        payloads (e.g. a 400-level SCPQuorumSet) must fail with XdrError,
        not escape as RecursionError."""
        self.depth += 1
        if self.depth > MAX_DECODE_DEPTH:
            raise XdrError("max decode depth exceeded")

    def leave(self) -> None:
        self.depth -= 1

    def done(self) -> bool:
        return self.pos == len(self.data)


class XdrType:
    """Base combinator. pack(value) -> bytes parts appended to out list."""

    def pack(self, v, out: List[bytes]) -> None:
        raise NotImplementedError

    def unpack(self, r: Reader):
        raise NotImplementedError

    def encode(self, v) -> bytes:
        out: List[bytes] = []
        self.pack(v, out)
        return b"".join(out)

    def decode(self, data: bytes, allow_trailing: bool = False):
        r = Reader(data)
        v = self.unpack(r)
        if not allow_trailing and not r.done():
            raise XdrError("trailing bytes")
        return v

    def default(self):
        """The C++ default-constructed value of this type (ints/enums 0,
        arrays empty, unions on arm 0) — what the reference's XDR result
        fields hold before anything assigns them."""
        raise NotImplementedError(type(self).__name__)


def _pad(n: int) -> bytes:
    return b"\x00" * ((4 - n % 4) % 4)


class _IntBase(XdrType):
    fmt = ">i"
    lo, hi = -(2**31), 2**31 - 1

    def pack(self, v, out):
        if not (self.lo <= v <= self.hi):
            raise XdrError(f"{v} out of range for {type(self).__name__}")
        out.append(struct.pack(self.fmt, v))

    def unpack(self, r):
        return struct.unpack(self.fmt, r.take(struct.calcsize(self.fmt)))[0]

    def default(self):
        return 0


class IntType(_IntBase):
    pass


class UintType(_IntBase):
    fmt = ">I"
    lo, hi = 0, 2**32 - 1


class HyperType(_IntBase):
    fmt = ">q"
    lo, hi = -(2**63), 2**63 - 1


class UhyperType(_IntBase):
    fmt = ">Q"
    lo, hi = 0, 2**64 - 1


Int = IntType()
Uint = UintType()
Hyper = HyperType()
Uhyper = UhyperType()


class BoolType(XdrType):
    def pack(self, v, out):
        out.append(struct.pack(">I", 1 if v else 0))

    def unpack(self, r):
        x = struct.unpack(">I", r.take(4))[0]
        if x not in (0, 1):
            raise XdrError("bad bool")
        return bool(x)

    def default(self):
        return False


Bool = BoolType()


class Opaque(XdrType):
    """Fixed-length opaque[n]."""

    def __init__(self, n: int):
        self.n = n
        self._padding = _pad(n)  # precomputed; b"" when n % 4 == 0

    def pack(self, v, out):
        if len(v) != self.n:
            raise XdrError(f"opaque[{self.n}] got {len(v)} bytes")
        out.append(v if type(v) is bytes else bytes(v))
        if self._padding:
            out.append(self._padding)

    def unpack(self, r):
        v = r.take(self.n)
        pad = r.take((4 - self.n % 4) % 4)
        if pad.strip(b"\x00"):
            raise XdrError("nonzero padding")
        return v

    def default(self):
        return b"\x00" * self.n


class VarOpaque(XdrType):
    """opaque<max>."""

    def __init__(self, max_len: int = 2**32 - 1):
        self.max_len = max_len

    def pack(self, v, out):
        if len(v) > self.max_len:
            raise XdrError("opaque too long")
        out.append(struct.pack(">I", len(v)))
        out.append(bytes(v))
        out.append(_pad(len(v)))

    def unpack(self, r):
        n = struct.unpack(">I", r.take(4))[0]
        if n > self.max_len:
            raise XdrError("opaque too long")
        v = r.take(n)
        pad = r.take((4 - n % 4) % 4)
        if pad.strip(b"\x00"):
            raise XdrError("nonzero padding")
        return v

    def default(self):
        return b""


class XdrStr(VarOpaque):
    """string<max> — kept as bytes (stellar strings are byte-exact)."""


class FixedArray(XdrType):
    def __init__(self, elem: XdrType, n: int):
        self.elem, self.n = elem, n

    def pack(self, v, out):
        if len(v) != self.n:
            raise XdrError("bad array length")
        for e in v:
            self.elem.pack(e, out)

    def unpack(self, r):
        return [self.elem.unpack(r) for _ in range(self.n)]

    def default(self):
        return [self.elem.default() for _ in range(self.n)]


class VarArray(XdrType):
    def __init__(self, elem: XdrType, max_len: int = 2**32 - 1):
        self.elem, self.max_len = elem, max_len

    def pack(self, v, out):
        if len(v) > self.max_len:
            raise XdrError("array too long")
        out.append(struct.pack(">I", len(v)))
        for e in v:
            self.elem.pack(e, out)

    def unpack(self, r):
        n = struct.unpack(">I", r.take(4))[0]
        if n > self.max_len:
            raise XdrError("array too long")
        return [self.elem.unpack(r) for _ in range(n)]

    def default(self):
        return []


class Option(XdrType):
    """T* — XDR optional (bool + value)."""

    def __init__(self, elem: XdrType):
        self.elem = elem

    def pack(self, v, out):
        if v is None:
            out.append(struct.pack(">I", 0))
        else:
            out.append(struct.pack(">I", 1))
            self.elem.pack(v, out)

    def unpack(self, r):
        flag = struct.unpack(">I", r.take(4))[0]
        if flag not in (0, 1):
            raise XdrError("bad optional flag")
        return self.elem.unpack(r) if flag else None

    def default(self):
        return None


class Enum(XdrType):
    """Named int32 with a closed value set."""

    def __init__(self, name: str, values: Dict[str, int]):
        self.name = name
        self.by_name = dict(values)
        self.by_value = {v: k for k, v in values.items()}
        # enum wire bytes precomputed per value (hot: every union disc)
        self._enc = {v: struct.pack(">i", v) for v in self.by_value}
        for k, v in values.items():
            setattr(self, k, v)

    def pack(self, v, out):
        b = self._enc.get(v)
        if b is None:
            raise XdrError(f"bad {self.name} value {v}")
        out.append(b)

    def unpack(self, r):
        v = struct.unpack(">i", r.take(4))[0]
        if v not in self.by_value:
            raise XdrError(f"bad {self.name} value {v}")
        return v

    def nameof(self, v) -> str:
        return self.by_value[v]

    def default(self):
        return 0 if 0 in self.by_value else min(self.by_value)


class _StructValue:
    """Generic record: attribute access + equality + repr."""

    __slots__ = ("_fields", "__dict__")

    def __init__(self, _fields: Sequence[str], **kw):
        self._fields = _fields if type(_fields) is tuple else tuple(_fields)
        d = self.__dict__
        d.update(kw)
        # fast path: fully-specified construction (the hot case — every
        # decode and most make() calls) skips the default-fill scan
        if len(d) != len(self._fields):
            for f in self._fields:
                if f not in d:
                    d[f] = None

    def __eq__(self, other):
        return (
            isinstance(other, _StructValue)
            and self._fields == other._fields
            and all(
                getattr(self, f) == getattr(other, f) for f in self._fields
            )
        )

    def __repr__(self):
        body = ", ".join(f"{f}={getattr(self, f)!r}" for f in self._fields)
        return f"({body})"

    def _replace(self, **kw):
        new = _StructValue.__new__(_StructValue)
        new._fields = self._fields
        new.__dict__.update(self.__dict__)
        new.__dict__.pop("_xdr_enc", None)  # drop any memoized encoding
        new.__dict__.update(kw)
        return new


class Struct(XdrType):
    # memoize=True caches the encoding on the value object itself (under
    # "_xdr_enc" in its __dict__; _replace drops it).  Only safe for types
    # whose values are immutable-by-convention AND reused across encodes —
    # a LedgerEntry flows through tx meta, the bucket list, and the SQL
    # commit in one close, which otherwise encodes it three times.
    memoize = False

    def __init__(self, name: str, fields: Sequence[Tuple[str, XdrType]]):
        self.name = name
        self.fields = list(fields)
        # a shared tuple: _StructValue keeps a reference instead of copying
        self.field_names = tuple(f for f, _ in fields)
        # bound pack methods: the encode hot loop skips attribute dispatch
        self._packers = [(f, t.pack) for f, t in fields]
        self._packfn = None  # compiled on first pack (fields may be
        #                      patched during schema construction)

    def _compile_packfn(self):
        """exec-compile a packer: unrolled field sequence with runs of
        primitive leaves FUSED into single struct.pack calls.  Encoding
        is the close path's hottest loop (meta + bucket + SQL all encode
        LedgerEntries); the fused packer cuts interpreter dispatch ~3x.
        Wire layout identical by construction — struct formats map
        int->'>i', uint->'>I', hyper->'>q', uhyper->'>Q', bool->'>I'."""
        fmt_of = {IntType: "i", UintType: "I", HyperType: "q",
                  UhyperType: "Q", BoolType: "I"}
        ns = {"_sp": struct.pack}
        lines = ["def _packfn(d, out):"]
        run_fmt, run_args = "", []

        def flush():
            nonlocal run_fmt, run_args
            if run_fmt:
                lines.append(
                    f"    out.append(_sp('>{run_fmt}', "
                    f"{', '.join(run_args)}))")
                run_fmt, run_args = "", []

        for i, (fname, ftype) in enumerate(self.fields):
            code = fmt_of.get(type(ftype))
            if code is not None:
                run_fmt += code
                run_args.append(f"d[{fname!r}]")
                continue
            flush()
            ns[f"_p{i}"] = ftype.pack
            lines.append(f"    _p{i}(d[{fname!r}], out)")
        flush()
        if len(lines) == 1:
            lines.append("    pass")
        exec("\n".join(lines), ns)
        self._packfn = ns["_packfn"]
        return self._packfn

    def make(self, **kw):
        unknown = set(kw) - set(self.field_names)
        if unknown:
            raise XdrError(f"{self.name}: unknown fields {unknown}")
        return _StructValue(self.field_names, **kw)

    def default(self):
        return _StructValue(self.field_names,
                            **{f: t.default() for f, t in self.fields})

    def _pack_slow(self, v, out):
        """Per-field fallback with precise error attribution (also covers
        namedtuple-like stand-ins without __dict__)."""
        d = getattr(v, "__dict__", None)
        for fname, fpack in self._packers:
            try:
                fpack(d[fname] if d is not None else getattr(v, fname),
                      out)
            except (KeyError, AttributeError, TypeError, XdrError) as e:
                raise XdrError(f"{self.name}.{fname}: {e}") from e

    def pack(self, v, out):
        d = getattr(v, "__dict__", None)
        if d is None:  # e.g. a namedtuple-like stand-in
            return self._pack_slow(v, out)
        packfn = self._packfn or self._compile_packfn()
        if self.memoize:
            hit = d.get("_xdr_enc")
            if hit is not None and hit[0] is self:
                out.append(hit[1])
                return
            sub: List[bytes] = []
            try:
                packfn(d, sub)
            except Exception:
                sub = []
                self._pack_slow(v, sub)  # re-raise with field context
            enc = b"".join(sub)
            d["_xdr_enc"] = (self, enc)
            out.append(enc)
            return
        n = len(out)
        try:
            packfn(d, out)
        except Exception as e:
            if isinstance(e, XdrError):
                raise
            del out[n:]  # drop partial output before the diagnosing retry
            self._pack_slow(v, out)  # re-raises with field context

    def unpack(self, r):
        kw = {fname: ftype.unpack(r) for fname, ftype in self.fields}
        return _StructValue(self.field_names, **kw)


class _UnionValue:
    __slots__ = ("type", "value", "arm", "_enc")

    def __init__(self, type_, value=None, arm: str = ""):
        self.type = type_
        self.value = value
        self.arm = arm
        self._enc = None  # (union_type, bytes) memo for memoize unions

    def __eq__(self, other):
        return (
            isinstance(other, _UnionValue)
            and self.type == other.type
            and self.value == other.value
        )

    def __repr__(self):
        return f"Union(type={self.type}, {self.arm}={self.value!r})"


class Union(XdrType):
    """Discriminated union.  arms: disc-value -> (arm_name, type|None).

    ``default`` (arm_name, type|None) catches unlisted discriminants.
    """

    def __init__(
        self,
        name: str,
        disc: XdrType,
        arms: Dict[int, Tuple[str, Opt[XdrType]]],
        default: Opt[Tuple[str, Opt[XdrType]]] = None,
    ):
        self.name = name
        self.disc = disc
        self.arms = dict(arms)
        self._default_arm = default

    def _arm(self, d):
        if d in self.arms:
            return self.arms[d]
        if self._default_arm is not None:
            return self._default_arm
        raise XdrError(f"{self.name}: no arm for discriminant {d}")

    def make(self, d, value=None):
        arm_name, _ = self._arm(d)
        return _UnionValue(d, value, arm_name)

    def default_for(self, d):
        """Union set to discriminant ``d`` with a default-constructed arm
        (the reference's ``u.type(d)`` on a fresh XDR union)."""
        arm_name, arm_type = self._arm(d)
        return _UnionValue(
            d, arm_type.default() if arm_type is not None else None,
            arm_name)

    def default(self):
        d = 0 if (0 in self.arms or self._default_arm is not None) else \
            min(self.arms)
        return self.default_for(d)

    memoize = False  # see Struct.memoize

    def pack(self, v, out):
        if self.memoize:
            hit = v._enc
            if hit is not None and hit[0] is self:
                out.append(hit[1])
                return
            sub: List[bytes] = []
            self._pack_inner(v, sub)
            enc = b"".join(sub)
            v._enc = (self, enc)
            out.append(enc)
            return
        self._pack_inner(v, out)

    def _pack_inner(self, v, out):
        self.disc.pack(v.type, out)
        arm_name, arm_type = self._arm(v.type)
        if arm_type is not None:
            try:
                arm_type.pack(v.value, out)
            except XdrError as e:
                raise XdrError(f"{self.name}.{arm_name}: {e}") from e
        elif v.value is not None:
            raise XdrError(f"{self.name}: void arm carries a value")

    def unpack(self, r):
        d = self.disc.unpack(r)
        arm_name, arm_type = self._arm(d)
        value = arm_type.unpack(r) if arm_type is not None else None
        return _UnionValue(d, value, arm_name)


class Lazy(XdrType):
    """Forward reference for recursive types (e.g. SCPQuorumSet)."""

    def __init__(self, thunk: Callable[[], XdrType]):
        self._thunk = thunk
        self._resolved: Opt[XdrType] = None

    def _get(self) -> XdrType:
        if self._resolved is None:
            self._resolved = self._thunk()
        return self._resolved

    def pack(self, v, out):
        self._get().pack(v, out)

    def unpack(self, r):
        r.enter()
        try:
            return self._get().unpack(r)
        finally:
            r.leave()


# -- native encoder wiring (see native/xdr_pack.c) ---------------------------

_native_pack = None
_native_pack_many = None


def _compile_native_schema(roots, build: bool = True) -> None:
    """Flatten every reachable XdrType into the C node table and install
    it.  Each compiled type gets ``_nidx`` (its node index); ``encode``
    then routes through the C packer.  Wire bytes are identical by
    construction; the Python pack tree remains the fallback/oracle."""
    global _native_pack, _native_pack_many
    from ..native import get_xdrpack

    mod = get_xdrpack(build=build)
    if mod is None:
        return
    import sys

    nodes: List[tuple] = []
    index: Dict[int, Tuple[int, XdrType]] = {}

    def compile_type(t) -> int:
        key = id(t)
        if key in index:
            return index[key][0]
        if isinstance(t, Lazy):
            # forward reference: compile the resolved target; shares its
            # node (recursion terminates because the target reserves its
            # slot before compiling children)
            idx = compile_type(t._get())
            index[key] = (idx, t)
            return idx
        idx = len(nodes)
        index[key] = (idx, t)
        nodes.append(None)  # reserve (recursive types)
        memo = None
        if isinstance(t, Struct):
            if t.memoize:
                memo = t
            fields = tuple(
                (sys.intern(f), compile_type(ft)) for f, ft in t.fields)
            nodes[idx] = (7, 0, fields, None, None, -1, None, memo)
        elif isinstance(t, Union):
            if t.memoize:
                memo = t
            arm_map = {}
            for d, (an, at) in t.arms.items():
                arm_map[d] = (1, compile_type(at)) if at is not None \
                    else (0, -1)
            default = None
            if t._default_arm is not None:
                an, at = t._default_arm
                default = (1, compile_type(at)) if at is not None \
                    else (0, -1)
            valid = (frozenset(t.disc.by_value)
                     if isinstance(t.disc, Enum) else None)
            nodes[idx] = (8, 0, None, arm_map, default, -1, valid, memo)
        elif isinstance(t, Enum):
            nodes[idx] = (12, 0, None, None, None, -1,
                          frozenset(t.by_value), None)
        elif isinstance(t, Opaque):
            nodes[idx] = (5, t.n, None, None, None, -1, None, None)
        elif isinstance(t, VarOpaque):  # includes XdrStr
            nodes[idx] = (6, t.max_len, None, None, None, -1, None, None)
        elif isinstance(t, FixedArray):
            nodes[idx] = (9, t.n, None, None, None,
                          compile_type(t.elem), None, None)
        elif isinstance(t, VarArray):
            nodes[idx] = (10, t.max_len, None, None, None,
                          compile_type(t.elem), None, None)
        elif isinstance(t, Option):
            nodes[idx] = (11, 0, None, None, None,
                          compile_type(t.elem), None, None)
        elif isinstance(t, BoolType):
            nodes[idx] = (4, 0, None, None, None, -1, None, None)
        elif isinstance(t, UintType):
            nodes[idx] = (1, 0, None, None, None, -1, None, None)
        elif isinstance(t, UhyperType):
            nodes[idx] = (3, 0, None, None, None, -1, None, None)
        elif isinstance(t, HyperType):
            nodes[idx] = (2, 0, None, None, None, -1, None, None)
        elif isinstance(t, IntType):
            nodes[idx] = (0, 0, None, None, None, -1, None, None)
        else:
            raise TypeError(f"uncompilable XdrType {type(t).__name__}")
        return idx

    for t in roots:
        compile_type(t)
    mod.init_schema(nodes, XdrError)
    for idx, t in index.values():
        t._nidx = idx
    _native_pack = mod.pack
    # older prebuilt .so without the batch entry: encode_many degrades
    _native_pack_many = getattr(mod, "pack_many", None)


def encode_many(pairs):
    """Batch encode ``[(XdrType, value), ...]`` -> ``[bytes, ...]`` in
    ONE native call (xdr_pack.c pack_many: shared arena, GIL-released
    copy-out), or None when the native packer is unavailable — callers
    fall back to per-value ``encode``.  Bytes are identical either way
    (same node table, same packer)."""
    if _native_pack_many is None:
        return None
    items = []
    for t, v in pairs:
        idx = getattr(t, "_nidx", -1)
        if idx < 0:
            return None
        items.append((idx, v))
    return _native_pack_many(items)


def _encode_native(self, v):
    idx = getattr(self, "_nidx", -1)
    if idx >= 0 and _native_pack is not None:
        return _native_pack(idx, v)
    out: List[bytes] = []
    self.pack(v, out)
    return b"".join(out)


def enable_native_encode(module, build: bool = True) -> bool:
    """Compile every XdrType bound in ``module`` (the schema module) into
    the native packer and reroute ``encode``.  ``build=False`` only uses
    an already-built extension (imports stay cheap; Application.start
    retries with build=True).  Safe no-op when unavailable."""
    global _native_pack
    if _native_pack is not None:
        return True
    # vars() order is module definition order (same every process);
    # node indices are process-local and wire bytes are canonical by
    # construction
    # detlint: allow(det-unsorted-iter)
    roots = [t for t in vars(module).values() if isinstance(t, XdrType)]
    try:
        _compile_native_schema(roots, build)
    except Exception:
        _native_pack = None
        return False
    if _native_pack is None:
        return False
    XdrType.encode = _encode_native
    return True
