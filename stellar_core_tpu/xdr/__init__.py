"""XDR wire/canonical format (ref src/protocol-curr/xdr + xdrpp runtime;
codegen ref src/Makefile.am:42-47).

``runtime`` holds the combinator engine; ``types`` the protocol-19 schema.
"""
from . import runtime, types  # noqa: F401
from .runtime import XdrError  # noqa: F401


def xdr_sha256(xdr_type, value) -> bytes:
    """sha256 of the canonical encoding (ref src/crypto/SHA.h xdrSha256)."""
    from ..crypto import sha256

    return sha256(xdr_type.encode(value))
