"""Overlay wire schema (ref src/protocol-curr/xdr/Stellar-overlay.x).

Separate module from types.py: these are transport-layer messages, only the
overlay imports them.
"""
from __future__ import annotations

from .runtime import (
    Enum, FixedArray, Hyper, Int, Opaque, Struct, Uhyper, Uint, Union,
    VarArray, VarOpaque, XdrStr,
)
from .types import (
    Curve25519Public, GeneralizedTransactionSet, Hash, HmacSha256Mac, NodeID,
    SCPEnvelope, SCPQuorumSet, Signature, TransactionEnvelope,
    TransactionSet, Uint256,
)

ErrorCode = Enum("ErrorCode", {
    "ERR_MISC": 0,
    "ERR_DATA": 1,
    "ERR_CONF": 2,
    "ERR_AUTH": 3,
    "ERR_LOAD": 4,
})

Error = Struct("Error", [
    ("code", ErrorCode),
    ("msg", XdrStr(100)),
])

SendMore = Struct("SendMore", [("numMessages", Uint)])
SendMoreExtended = Struct("SendMoreExtended", [
    ("numMessages", Uint),
    ("numBytes", Uint),
])

AuthCert = Struct("AuthCert", [
    ("pubkey", Curve25519Public),
    ("expiration", Uhyper),
    ("sig", Signature),
])

Hello = Struct("Hello", [
    ("ledgerVersion", Uint),
    ("overlayVersion", Uint),
    ("overlayMinVersion", Uint),
    ("networkID", Hash),
    ("versionStr", XdrStr(100)),
    ("listeningPort", Int),
    ("peerID", NodeID),
    ("cert", AuthCert),
    ("nonce", Uint256),
])

AUTH_MSG_FLAG_FLOW_CONTROL_BYTES_REQUESTED = 200

Auth = Struct("Auth", [("flags", Int)])

IPAddrType = Enum("IPAddrType", {"IPv4": 0, "IPv6": 1})

_PeerAddressIp = Union("PeerAddressIp", IPAddrType, {
    IPAddrType.IPv4: ("ipv4", Opaque(4)),
    IPAddrType.IPv6: ("ipv6", Opaque(16)),
})

PeerAddress = Struct("PeerAddress", [
    ("ip", _PeerAddressIp),
    ("port", Uint),
    ("numFailures", Uint),
])

MessageType = Enum("MessageType", {
    "ERROR_MSG": 0,
    "AUTH": 2,
    "DONT_HAVE": 3,
    "GET_PEERS": 4,
    "PEERS": 5,
    "GET_TX_SET": 6,
    "TX_SET": 7,
    "GENERALIZED_TX_SET": 17,
    "TRANSACTION": 8,
    "GET_SCP_QUORUMSET": 9,
    "SCP_QUORUMSET": 10,
    "SCP_MESSAGE": 11,
    "GET_SCP_STATE": 12,
    "HELLO": 13,
    "SURVEY_REQUEST": 14,
    "SURVEY_RESPONSE": 15,
    "SEND_MORE": 16,
    "SEND_MORE_EXTENDED": 20,
    "FLOOD_ADVERT": 18,
    "FLOOD_DEMAND": 19,
})

DontHave = Struct("DontHave", [
    ("type", MessageType),
    ("reqHash", Uint256),
])

SurveyMessageCommandType = Enum("SurveyMessageCommandType", {
    "SURVEY_TOPOLOGY": 0,
})

SurveyMessageResponseType = Enum("SurveyMessageResponseType", {
    "SURVEY_TOPOLOGY_RESPONSE_V0": 0,
    "SURVEY_TOPOLOGY_RESPONSE_V1": 1,
})

SurveyRequestMessage = Struct("SurveyRequestMessage", [
    ("surveyorPeerID", NodeID),
    ("surveyedPeerID", NodeID),
    ("ledgerNum", Uint),
    ("encryptionKey", Curve25519Public),
    ("commandType", SurveyMessageCommandType),
])

SignedSurveyRequestMessage = Struct("SignedSurveyRequestMessage", [
    ("requestSignature", Signature),
    ("request", SurveyRequestMessage),
])

EncryptedBody = VarOpaque(64000)

SurveyResponseMessage = Struct("SurveyResponseMessage", [
    ("surveyorPeerID", NodeID),
    ("surveyedPeerID", NodeID),
    ("ledgerNum", Uint),
    ("commandType", SurveyMessageCommandType),
    ("encryptedBody", EncryptedBody),
])

SignedSurveyResponseMessage = Struct("SignedSurveyResponseMessage", [
    ("responseSignature", Signature),
    ("response", SurveyResponseMessage),
])

PeerStats = Struct("PeerStats", [
    ("id", NodeID),
    ("versionStr", XdrStr(100)),
    ("messagesRead", Uhyper),
    ("messagesWritten", Uhyper),
    ("bytesRead", Uhyper),
    ("bytesWritten", Uhyper),
    ("secondsConnected", Uhyper),
    ("uniqueFloodBytesRecv", Uhyper),
    ("duplicateFloodBytesRecv", Uhyper),
    ("uniqueFetchBytesRecv", Uhyper),
    ("duplicateFetchBytesRecv", Uhyper),
    ("uniqueFloodMessageRecv", Uhyper),
    ("duplicateFloodMessageRecv", Uhyper),
    ("uniqueFetchMessageRecv", Uhyper),
    ("duplicateFetchMessageRecv", Uhyper),
])

PeerStatList = VarArray(PeerStats, 25)

TopologyResponseBodyV0 = Struct("TopologyResponseBodyV0", [
    ("inboundPeers", PeerStatList),
    ("outboundPeers", PeerStatList),
    ("totalInboundPeerCount", Uint),
    ("totalOutboundPeerCount", Uint),
])

TopologyResponseBodyV1 = Struct("TopologyResponseBodyV1", [
    ("inboundPeers", PeerStatList),
    ("outboundPeers", PeerStatList),
    ("totalInboundPeerCount", Uint),
    ("totalOutboundPeerCount", Uint),
    ("maxInboundPeerCount", Uint),
    ("maxOutboundPeerCount", Uint),
])

SurveyResponseBody = Union(
    "SurveyResponseBody", SurveyMessageResponseType, {
        SurveyMessageResponseType.SURVEY_TOPOLOGY_RESPONSE_V0:
            ("topologyResponseBodyV0", TopologyResponseBodyV0),
        SurveyMessageResponseType.SURVEY_TOPOLOGY_RESPONSE_V1:
            ("topologyResponseBodyV1", TopologyResponseBodyV1),
    })

TX_ADVERT_VECTOR_MAX_SIZE = 1000
TX_DEMAND_VECTOR_MAX_SIZE = 1000

FloodAdvert = Struct("FloodAdvert", [
    ("txHashes", VarArray(Hash, TX_ADVERT_VECTOR_MAX_SIZE)),
])

FloodDemand = Struct("FloodDemand", [
    ("txHashes", VarArray(Hash, TX_DEMAND_VECTOR_MAX_SIZE)),
])

StellarMessage = Union("StellarMessage", MessageType, {
    MessageType.ERROR_MSG: ("error", Error),
    MessageType.HELLO: ("hello", Hello),
    MessageType.AUTH: ("auth", Auth),
    MessageType.DONT_HAVE: ("dontHave", DontHave),
    MessageType.GET_PEERS: ("getPeers", None),
    MessageType.PEERS: ("peers", VarArray(PeerAddress, 100)),
    MessageType.GET_TX_SET: ("txSetHash", Uint256),
    MessageType.TX_SET: ("txSet", TransactionSet),
    MessageType.GENERALIZED_TX_SET:
        ("generalizedTxSet", GeneralizedTransactionSet),
    MessageType.TRANSACTION: ("transaction", TransactionEnvelope),
    MessageType.SURVEY_REQUEST:
        ("signedSurveyRequestMessage", SignedSurveyRequestMessage),
    MessageType.SURVEY_RESPONSE:
        ("signedSurveyResponseMessage", SignedSurveyResponseMessage),
    MessageType.GET_SCP_QUORUMSET: ("qSetHash", Uint256),
    MessageType.SCP_QUORUMSET: ("qSet", SCPQuorumSet),
    MessageType.SCP_MESSAGE: ("envelope", SCPEnvelope),
    MessageType.GET_SCP_STATE: ("getSCPLedgerSeq", Uint),
    MessageType.SEND_MORE: ("sendMoreMessage", SendMore),
    MessageType.SEND_MORE_EXTENDED:
        ("sendMoreExtendedMessage", SendMoreExtended),
    MessageType.FLOOD_ADVERT: ("floodAdvert", FloodAdvert),
    MessageType.FLOOD_DEMAND: ("floodDemand", FloodDemand),
})
# one flood message encodes (2 + fan-out) times per hop today (MAC
# verify, floodgate id, then once per forwarded peer); messages are
# construct-once values, so memoize the encoding on the value
StellarMessage.memoize = True

_AuthenticatedMessageV0 = Struct("AuthenticatedMessageV0", [
    ("sequence", Uhyper),
    ("message", StellarMessage),
    ("mac", HmacSha256Mac),
])

AuthenticatedMessage = Union("AuthenticatedMessage", Uint, {
    0: ("v0", _AuthenticatedMessageV0),
})
