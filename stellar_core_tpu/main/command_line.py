"""Command line: the operator entry point
(ref src/main/CommandLine.cpp:1825-1891 subcommand table; clara parsing
collapses to argparse).

Subcommands: run, new-db, catchup, publish, http-command, version,
self-check.  `python -m stellar_core_tpu --conf node.toml run` runs a node
as an OS process: real TCP overlay (PEER_PORT), admin HTTP (HTTP_PORT),
SCP cadence on the real-time clock.
"""
from __future__ import annotations

import argparse
import json
import sys
from typing import Optional

from ..utils.clock import ClockMode, VirtualClock
from .application import Application
from .config import Config


def load_config(path: Optional[str], overrides: dict) -> Config:
    if path:
        cfg = Config.from_toml(path)
        for k, v in overrides.items():
            setattr(cfg, k, v)
        return cfg
    return Config(**overrides)


def cmd_run(cfg: Config) -> int:
    """ref run(): boot + crank the real-time main loop forever."""
    app = Application(VirtualClock(ClockMode.REAL_TIME), cfg)
    app.enable_tcp()
    app.start()
    info = app.get_json_info()
    print(json.dumps({"starting": info}), flush=True)
    import time

    try:
        last_gc = time.monotonic()
        while True:
            if app.crank(block=False) == 0:
                # idle: nap briefly, then poll sockets/timers again (the
                # asio run-loop equivalent)
                time.sleep(0.005)
                # DEFERRED_GC residual: a node that is idle (not closing
                # ledgers — out of sync, or serving HTTP only) must
                # still collect now and then, else cyclic garbage grows
                # unboundedly with automatic GC off
                if cfg.DEFERRED_GC and \
                        time.monotonic() - last_gc > 30.0:
                    import gc

                    gc.collect(1)
                    last_gc = time.monotonic()
    except KeyboardInterrupt:
        app.graceful_stop()
    return 0


def cmd_new_db(cfg: Config) -> int:
    """ref newDB(): initialize the database + genesis ledger."""
    app = Application(VirtualClock(ClockMode.REAL_TIME), cfg)
    app.ledger_manager.start_new_ledger()
    print(json.dumps({
        "ledger": app.ledger_manager.last_closed_seq(),
        "hash": app.ledger_manager.last_closed_hash().hex()}))
    return 0


def cmd_catchup(cfg: Config, to_ledger: int, mode: str) -> int:
    from ..catchup import CatchupConfiguration, CatchupWork
    from ..work.work import State

    app = Application(VirtualClock(ClockMode.REAL_TIME), cfg)
    app.start()
    if not app.history_manager.archives:
        print(json.dumps({"error": "no HISTORY_ARCHIVES configured"}))
        return 1
    archive = app.history_manager.archives[0]
    work = CatchupWork(app, archive, CatchupConfiguration(
        to_ledger,
        CatchupConfiguration.COMPLETE if mode == "complete"
        else CatchupConfiguration.MINIMAL))
    work.start()
    for _ in range(100000):
        work.crank()
        if work.state not in (State.RUNNING, State.WAITING):
            break
    print(json.dumps({
        "state": work.state.name,
        "ledger": app.ledger_manager.last_closed_seq(),
        "hash": app.ledger_manager.last_closed_hash().hex()}))
    return 0 if work.state == State.SUCCESS else 1


def cmd_publish(cfg: Config) -> int:
    app = Application(VirtualClock(ClockMode.REAL_TIME), cfg)
    app.start()
    app.history_manager.publish_queued_history()
    print(json.dumps(
        {"published": app.history_manager.published_checkpoints}))
    return 0


def cmd_self_check(cfg: Config) -> int:
    """ref selfCheck(): verify local state consistency."""
    from ..xdr import types as T
    from ..xdr import xdr_sha256

    app = Application(VirtualClock(ClockMode.REAL_TIME), cfg)
    app.start()
    checks = {}
    lm = app.ledger_manager
    hdr = lm.last_closed_header()
    checks["header_hash"] = (
        lm.last_closed_hash() == xdr_sha256(T.LedgerHeader, hdr))
    checks["bucket_list"] = (
        app.bucket_manager.get_bucket_list_hash() == hdr.bucketListHash
        or hdr.bucketListHash == b"\x00" * 32)
    # BucketListIsConsistentWithDatabase (ref src/invariant/
    # BucketListIsConsistentWithDatabase.cpp, run here as the reference's
    # self-check does): the SQL entry store must hold exactly the bucket
    # list's live entries
    if app.bucket_manager.get_bucket_list_hash() != b"\x00" * 32 and \
            app.bucket_manager.bucket_list.levels[0].curr.entries:
        live = app.bucket_manager.bucket_list.all_live_entries()
        db_count = app.ledger_manager.root.count_entries()
        consistent = len(live) == db_count
        if consistent:
            for kb, entry in live.items():
                # straight SQL, NOT root.get: in BucketListDB mode the
                # root serves from the buckets, which would make this
                # cross-tier invariant compare the buckets to themselves
                row = app.database.execute(
                    "SELECT entry FROM ledgerentries WHERE key = ?",
                    (kb,)).fetchone()
                if row is None or row[0] != T.LedgerEntry.encode(entry):
                    consistent = False
                    break
        checks["bucketlist_consistent_with_database"] = consistent
    qic = app.herder.check_quorum_intersection()
    checks["quorum_intersection"] = qic.ok  # None = budget hit: unknown
    ok = all(v is not False for v in checks.values())
    print(json.dumps({"ok": ok, "checks": checks}))
    return 0 if ok else 1


def cmd_version() -> int:
    print(json.dumps({"version": "stellar-core-tpu 0.3.0",
                      "protocol": Config.CURRENT_LEDGER_PROTOCOL_VERSION}))
    return 0


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(prog="stellar-core-tpu")
    ap.add_argument("--conf", help="TOML config file")
    sub = ap.add_subparsers(dest="cmd", required=True)
    sub.add_parser("run")
    sub.add_parser("new-db")
    cu = sub.add_parser("catchup")
    cu.add_argument("to_ledger", type=int)
    cu.add_argument("--mode", choices=["minimal", "complete"],
                    default="minimal")
    sub.add_parser("publish")
    sub.add_parser("self-check")
    sub.add_parser("version")
    args = ap.parse_args(argv)

    if args.cmd == "version":
        return cmd_version()
    cfg = load_config(args.conf, {})
    if args.cmd == "run":
        return cmd_run(cfg)
    if args.cmd == "new-db":
        return cmd_new_db(cfg)
    if args.cmd == "catchup":
        return cmd_catchup(cfg, args.to_ledger, args.mode)
    if args.cmd == "publish":
        return cmd_publish(cfg)
    if args.cmd == "self-check":
        return cmd_self_check(cfg)
    return 2


if __name__ == "__main__":
    sys.exit(main())
