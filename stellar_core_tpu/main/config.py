"""Config: the node's knob surface (ref src/main/Config.h — a 607-line
header of ~200 TOML-loaded fields; this port keeps the same names for the
load-bearing ones and loads from TOML via tomllib or from kwargs).

Like the reference's Config::load, ``from_toml`` rejects unknown keys and
``validate()`` runs the sanity pass (quorum safety incl. FAILURE_SAFETY /
UNSAFE_QUORUM, port/time ranges, regex compilation) before a node boots.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..crypto import SecretKey, sha256


class ConfigError(Exception):
    """Invalid node configuration (ref std::invalid_argument throws from
    Config::load/validateConfig)."""


class Config:
    CURRENT_LEDGER_PROTOCOL_VERSION = 19

    def __init__(self, **kw):
        # identity / network
        self.NETWORK_PASSPHRASE: str = kw.get(
            "NETWORK_PASSPHRASE", "Test SDF Network ; September 2015")
        self.NODE_SEED: Optional[bytes] = kw.get("NODE_SEED")
        self.NODE_IS_VALIDATOR: bool = kw.get("NODE_IS_VALIDATOR", True)
        self.QUORUM_SET: Optional[dict] = kw.get("QUORUM_SET")

        # mode
        self.RUN_STANDALONE: bool = kw.get("RUN_STANDALONE", False)
        self.MANUAL_CLOSE: bool = kw.get("MANUAL_CLOSE", False)
        self.FORCE_SCP: bool = kw.get("FORCE_SCP", False)

        # protocol / testing knobs
        self.LEDGER_PROTOCOL_VERSION: int = kw.get(
            "LEDGER_PROTOCOL_VERSION",
            self.CURRENT_LEDGER_PROTOCOL_VERSION)
        self.TESTING_UPGRADE_DESIRED_FEE: int = kw.get(
            "TESTING_UPGRADE_DESIRED_FEE", 100)
        self.TESTING_UPGRADE_RESERVE: int = kw.get(
            "TESTING_UPGRADE_RESERVE", 5000000)
        self.TESTING_UPGRADE_MAX_TX_SET_SIZE: int = kw.get(
            "TESTING_UPGRADE_MAX_TX_SET_SIZE", 100)
        self.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING: bool = kw.get(
            "ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING", False)

        # storage
        self.DATABASE: str = kw.get("DATABASE", ":memory:")
        self.BUCKET_DIR_PATH: str = kw.get("BUCKET_DIR_PATH", "buckets")
        # set to a real directory to persist bucket files (restart support)
        self.BUCKET_DIR_PATH_REAL: Optional[str] = kw.get(
            "BUCKET_DIR_PATH_REAL")
        # history archives to publish to / catch up from (ref HISTORY
        # config blocks, src/history/readme.md:8-30).  Two entry forms:
        #   [name, local-directory-path]            — direct file I/O
        #   {name=..., get=..., put=..., mkdir=...} — shell command
        #     templates run as subprocesses ({0}=local file, {1}=remote
        #     path), e.g. get = "curl -sf http://host/{1} -o {0}"
        self.HISTORY_ARCHIVES: List[object] = kw.get("HISTORY_ARCHIVES", [])
        # file path receiving length-framed LedgerCloseMeta XDR per close
        # (ref METADATA_OUTPUT_STREAM, Config.h)
        self.METADATA_OUTPUT_STREAM: Optional[str] = kw.get(
            "METADATA_OUTPUT_STREAM")

        # upgrades this node votes for when nominating (ref Upgrades::
        # UpgradeParameters; None = don't propose)
        self.UPGRADE_DESIRED_PROTOCOL_VERSION: Optional[int] = kw.get(
            "UPGRADE_DESIRED_PROTOCOL_VERSION")
        self.UPGRADE_DESIRED_BASE_FEE: Optional[int] = kw.get(
            "UPGRADE_DESIRED_BASE_FEE")
        self.UPGRADE_DESIRED_MAX_TX_SET_SIZE: Optional[int] = kw.get(
            "UPGRADE_DESIRED_MAX_TX_SET_SIZE")
        self.UPGRADE_DESIRED_BASE_RESERVE: Optional[int] = kw.get(
            "UPGRADE_DESIRED_BASE_RESERVE")

        # SCP federated-tally backend: "host" (exact python), "tensor"
        # (batched device kernels, ops/quorum.py), or "both" (tensor with
        # the host oracle asserting equality — differential testing)
        # "auto" resolves at Application construction: "tensor" when a
        # device probe succeeds, "host" otherwise (utils/device.py)
        self.SCP_TALLY_BACKEND: str = kw.get("SCP_TALLY_BACKEND", "auto")

        # quorum-intersection scan budget for synchronous callers (admin
        # HTTP, self-check): the branch-and-bound is NP-hard over network-
        # supplied qsets, so cap it; the scan reports "unknown" (aborted)
        # past the budget instead of hanging the handler.  ~1M calls/s in
        # the native tier => default caps a scan at roughly 30 s.
        self.QUORUM_INTERSECTION_MAX_CALLS: int = kw.get(
            "QUORUM_INTERSECTION_MAX_CALLS", 30_000_000)
        # wall-clock ceiling for one scan — the call cap alone is
        # calibrated to the native tier and would let the slower Python
        # tiers (deep qsets, no g++) run orders of magnitude longer
        self.QUORUM_INTERSECTION_TIMEOUT_SECONDS: float = kw.get(
            "QUORUM_INTERSECTION_TIMEOUT_SECONDS", 30.0)

        # quorum safety (ref Config.h FAILURE_SAFETY / UNSAFE_QUORUM:
        # -1 = auto-derive f from the top-level quorum set size)
        self.FAILURE_SAFETY: int = kw.get("FAILURE_SAFETY", -1)
        self.UNSAFE_QUORUM: bool = kw.get("UNSAFE_QUORUM", False)

        # consensus cadence (ref Herder.cpp:7-18)
        self.EXP_LEDGER_TIMESPAN_SECONDS: float = kw.get(
            "EXP_LEDGER_TIMESPAN_SECONDS",
            1.0 if kw.get("ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING")
            else 5.0)
        self.MAX_SCP_TIMEOUT_SECONDS: float = kw.get(
            "MAX_SCP_TIMEOUT_SECONDS", 240.0)
        self.CONSENSUS_STUCK_TIMEOUT_SECONDS: float = kw.get(
            "CONSENSUS_STUCK_TIMEOUT_SECONDS", 35.0)
        # closed-slot retention for SCP state (ref MAX_SLOTS_TO_REMEMBER)
        self.MAX_SLOTS_TO_REMEMBER: int = kw.get(
            "MAX_SLOTS_TO_REMEMBER", 12)
        # mempool capacity = multiplier x ledger maxTxSetSize ops (ref
        # TRANSACTION_QUEUE_SIZE_MULTIPLIER feeding TxQueueLimiter)
        self.TRANSACTION_QUEUE_SIZE_MULTIPLIER: int = kw.get(
            "TRANSACTION_QUEUE_SIZE_MULTIPLIER", 4)

        # catchup (ref CATCHUP_COMPLETE: replay every ledger instead of
        # assuming bucket state at the anchor checkpoint)
        self.CATCHUP_COMPLETE: bool = kw.get("CATCHUP_COMPLETE", False)
        # ledgers behind live before archive catchup triggers
        self.CATCHUP_TRIGGER_GAP: int = kw.get("CATCHUP_TRIGGER_GAP", 2)
        # base of the exponential retry backoff (clock-seconds) for
        # archive download works; 0 = immediate retries
        self.CATCHUP_RETRY_BACKOFF: float = kw.get(
            "CATCHUP_RETRY_BACKOFF", 0.1)
        # worker threads behind the WorkScheduler's pool (parallel
        # archive fetch/verify; threads spawn lazily, idle nodes pay
        # nothing); 0 = no pool, every work cranks inline
        self.WORK_POOL_WORKERS: int = kw.get("WORK_POOL_WORKERS", 4)

        # overlay
        self.PEER_PORT: int = kw.get("PEER_PORT", 11625)
        self.HTTP_PORT: int = kw.get("HTTP_PORT", 11626)
        self.TARGET_PEER_CONNECTIONS: int = kw.get(
            "TARGET_PEER_CONNECTIONS", 8)
        self.MAX_ADDITIONAL_PEER_CONNECTIONS: int = kw.get(
            "MAX_ADDITIONAL_PEER_CONNECTIONS", 64)
        self.KNOWN_PEERS: List[str] = kw.get("KNOWN_PEERS", [])
        # always-reconnect peers, tried before KNOWN_PEERS (ref
        # PREFERRED_PEERS)
        self.PREFERRED_PEERS: List[str] = kw.get("PREFERRED_PEERS", [])

        # cross-peer SCP signature-batch admission: flooded envelopes
        # received within one crank verify as a single padded batch
        # (SIG_BATCH_BUCKETS) instead of per-envelope inside SCP —
        # verdicts identical either way, the device just sees one
        # dispatch (ROADMAP 4 companion)
        self.OVERLAY_SIG_BATCH: bool = kw.get("OVERLAY_SIG_BATCH", True)

        # work/process subsystem (ref MAX_CONCURRENT_SUBPROCESSES)
        self.MAX_CONCURRENT_SUBPROCESSES: int = kw.get(
            "MAX_CONCURRENT_SUBPROCESSES", 16)

        # device tier
        # "auto" resolves at Application construction: "tpu" when a
        # device probe succeeds, "cpu" otherwise — a TPU-native node must
        # not need env flags to use the TPU (VERDICT r3 weak #3)
        self.CRYPTO_BACKEND: str = kw.get("CRYPTO_BACKEND", "auto")

        # run spill-merges on worker threads between spills (FutureBucket,
        # ref src/bucket/FutureBucket.cpp).  Results are bitwise identical
        # to synchronous merges — this only moves latency off the close
        # path — so the knob exists for debugging, not determinism.
        self.BACKGROUND_BUCKET_MERGES: bool = kw.get(
            "BACKGROUND_BUCKET_MERGES", True)
        # first bucket level stored as sparse-indexed files instead of in
        # memory (ref BucketListDB; levels 0-3 hold <= 4^4 ledgers of
        # deltas and stay hot)
        self.DISK_BUCKET_LEVEL: int = kw.get("DISK_BUCKET_LEVEL", 4)
        # serve point reads / apply-loop prefetch from the bucket tier's
        # bloom-filtered per-bucket indexes instead of SQL (ref
        # BucketListDB / EXPERIMENTAL_BUCKETLIST_DB — default on; SQL
        # keeps only the offer-book range scans).  Activation still
        # requires a fresh start or a hash-verified bucket restore
        # (Application.start) so a node with a missing/stale bucket store
        # never serves wrong reads.
        self.BUCKETLIST_DB: bool = kw.get("BUCKETLIST_DB", True)
        # run GC between closes instead of wherever allocation counters
        # trip (a mid-close gen2 cycle costs >1s at 1000-tx closes)
        self.DEFERRED_GC: bool = kw.get("DEFERRED_GC", True)
        # after each FULL post-close collection (checkpoint cadence),
        # gc.freeze() the survivors — adopted buckets/indexes — so the
        # next gen-2 pass traverses only the delta since the last
        # checkpoint instead of the whole heap (the SOAK_BENCH_r13
        # 427ms-p99 fix).  Kill switch for leak hunts: frozen objects
        # are invisible to the cyclic collector (refcounting still
        # frees them)
        self.GC_FREEZE_LONG_LIVED: bool = kw.get(
            "GC_FREEZE_LONG_LIVED", True)

        # parallel transaction apply (stellar_core_tpu/apply/): footprint
        # planner + conflict-cluster scheduler + bit-identical concurrent
        # executor.  PARALLEL_APPLY is the kill switch (env
        # PARALLEL_APPLY=0 also disables); WORKERS <= 1 disables too.
        # Env reads live HERE on purpose: main/ is outside detlint's
        # consensus det-wallclock scope, and the env only gates WHETHER
        # the parallel path runs — results are bit-identical either way.
        import os as _os

        self.PARALLEL_APPLY: bool = kw.get(
            "PARALLEL_APPLY",
            _os.environ.get("PARALLEL_APPLY", "1") != "0")
        self.PARALLEL_APPLY_WORKERS: int = kw.get(
            "PARALLEL_APPLY_WORKERS",
            int(_os.environ.get("PARALLEL_APPLY_WORKERS", "2") or 0))
        # native GIL-free apply kernel (native/apply_kernel.cpp) for
        # kernel-eligible clusters; NATIVE_APPLY=0 is the kill switch —
        # every cluster then runs the Python reference apply
        # (bit-identical either way, enforced by test_native_apply.py).
        # Note: INVARIANT_CHECKS run per-op on Python-applied clusters
        # only; kernel-applied clusters rely on the kernel's own
        # exact-shape parse + bounds guards (set NATIVE_APPLY=0 to run
        # every configured checker on every tx).
        self.NATIVE_APPLY: bool = kw.get(
            "NATIVE_APPLY",
            _os.environ.get("NATIVE_APPLY", "1") != "0")
        # engage the planner+kernel WITHOUT a worker pool (workers 0/1):
        # the kernel beats Python even applying clusters sequentially on
        # the close thread.  Off by default so workers=0 keeps meaning
        # "plain sequential apply" unless explicitly opted in.
        self.NATIVE_APPLY_INLINE: bool = kw.get(
            "NATIVE_APPLY_INLINE",
            _os.environ.get("NATIVE_APPLY_INLINE", "0") == "1")
        # batched fee/seqnum phase (apply_kernel.cpp charge_fees): one
        # GIL-released call replaces the per-tx process_fee_seq_num
        # loop.  NATIVE_FEE=0 is the kill switch; bytes are identical
        # either way (tests/test_native_fee.py).  Follows NATIVE_APPLY:
        # the fee batch never engages with the apply kernel killed.
        self.NATIVE_FEE: bool = kw.get(
            "NATIVE_FEE",
            _os.environ.get("NATIVE_FEE", "1") != "0")
        # in-kernel constant-product pool quoting on path-payment hops;
        # NATIVE_POOL_QUOTE=0 restores the decline-if-live-pool host
        # screen (pool hops then always run the Python reference).
        self.NATIVE_POOL_QUOTE: bool = kw.get(
            "NATIVE_POOL_QUOTE",
            _os.environ.get("NATIVE_POOL_QUOTE", "1") != "0")
        # native tail encode (xdr_pack.c pack_many): the commit tail's
        # tx-history row encodes collapse into one native crossing.
        # NATIVE_TAIL_ENCODE=0 falls back to per-value encode() — same
        # packer, same bytes.
        self.NATIVE_TAIL_ENCODE: bool = kw.get(
            "NATIVE_TAIL_ENCODE",
            _os.environ.get("NATIVE_TAIL_ENCODE", "1") != "0")
        # one JSON line of session apply stats appended at shutdown —
        # tools/verify_green.py's parallel smoke aggregates these to
        # report aborts observed across the suite
        self.PARALLEL_APPLY_STATS_FILE: Optional[str] = kw.get(
            "PARALLEL_APPLY_STATS_FILE",
            _os.environ.get("PARALLEL_APPLY_STATS_FILE"))

        # pipelined ledger close (ledger/close_pipeline.py): after the
        # header seals, the commit/meta/tx-history/gc tail runs on a
        # worker while the herder triggers the next ledger, with a
        # write-ahead read overlay and a strict depth-1 barrier (the
        # next close's seal waits for the previous tail's durable
        # commit).  PIPELINED_CLOSE=0 (env or config) is the kill
        # switch: the fully synchronous close path, bit-identical
        # results either way (tests/test_pipelined_close.py).
        self.PIPELINED_CLOSE: bool = kw.get(
            "PIPELINED_CLOSE",
            _os.environ.get("PIPELINED_CLOSE", "1") != "0")
        # drain the tail before close_ledger returns.  None resolves to
        # MANUAL_CLOSE: test/standalone rigs keep sequential read
        # semantics, real nodes overlap.  Benches and overlap tests set
        # False explicitly.
        self.PIPELINED_CLOSE_EAGER_DRAIN: Optional[bool] = kw.get(
            "PIPELINED_CLOSE_EAGER_DRAIN")
        # one JSON line of pipeline session stats at shutdown —
        # tools/verify_green.py's pipelined smoke aggregates these
        self.PIPELINED_CLOSE_STATS_FILE: Optional[str] = kw.get(
            "PIPELINED_CLOSE_STATS_FILE",
            _os.environ.get("PIPELINED_CLOSE_STATS_FILE"))

        # surge-pricing DEX lane: ops from DEX transactions (offers +
        # path payments) admitted per ledger, on top of the total
        # maxTxSetSize cap (ref SurgePricingUtils.h lane config /
        # MAX_DEX_TX_OPERATIONS).  None = no DEX lane limit.
        self.MAX_DEX_TX_OPERATIONS: Optional[int] = kw.get(
            "MAX_DEX_TX_OPERATIONS")

        # flight recorder (utils/tracing.py): hierarchical span tracing
        # over the close path.  Disabled tracing still measures the
        # per-phase close breakdown; it just records no spans.
        self.TRACING_ENABLED: bool = kw.get("TRACING_ENABLED", True)
        # how many whole closes the span ring retains (/trace?ledger=N)
        self.TRACE_RING_CLOSES: int = kw.get("TRACE_RING_CLOSES", 8)
        # slow-close watchdog: a close slower than this persists its full
        # span tree as Chrome trace_event JSON into TRACE_DIR and logs a
        # one-line summary (<= 0 disables the watchdog)
        self.SLOW_CLOSE_THRESHOLD_SECONDS: float = kw.get(
            "SLOW_CLOSE_THRESHOLD_SECONDS", 2.0)
        self.TRACE_DIR: str = kw.get("TRACE_DIR", "traces")
        # test hook: sleep this long inside every close (span
        # "ledger.close.test_delay") so the watchdog path is testable
        # without a genuinely pathological workload
        self.ARTIFICIALLY_SLEEP_IN_CLOSE_FOR_TESTING: float = kw.get(
            "ARTIFICIALLY_SLEEP_IN_CLOSE_FOR_TESTING", 0.0)

        # transaction-lifecycle telemetry (utils/txtrace.py): sampled
        # per-tx stage stamps (overlay recv -> admit -> txset ->
        # nominate -> externalize -> apply -> durable commit) rolled up
        # into txtrace.* histograms and the HTTP tx/latency endpoint.
        # Observational only — hashes/meta are bit-identical on or off
        # (tests/test_txtrace.py) and the disabled cost is one attribute
        # check per stamp site.
        self.TX_LIFECYCLE_TRACKING: bool = kw.get(
            "TX_LIFECYCLE_TRACKING", True)
        # completed-lifecycle records retained for tx/latency
        self.TX_LIFECYCLE_RING: int = kw.get("TX_LIFECYCLE_RING", 256)
        # in-flight tracked txs before deterministic decimation halves
        # the live map and doubles the sampling stride
        self.TX_LIFECYCLE_MAX_LIVE: int = kw.get(
            "TX_LIFECYCLE_MAX_LIVE", 512)

        # flood-propagation telemetry (utils/floodtrace.py): sampled
        # per-item hop records across the overlay flood (origin vs
        # relayed, sending peer, duplicate attribution, fan-out),
        # rolled into floodtrace.* metrics and the HTTP flood endpoint;
        # simulation/observatory.py merges them network-wide.
        # Observational only — hashes/meta are bit-identical on or off
        # and the disabled cost is one attribute check per flood site.
        self.FLOOD_TRACE_ENABLED: bool = kw.get(
            "FLOOD_TRACE_ENABLED", True)
        # retired hop records retained for flood / the observatory
        self.FLOOD_TRACE_RING: int = kw.get("FLOOD_TRACE_RING", 256)
        # in-flight tracked items before deterministic decimation
        # halves the live map and doubles the sampling stride
        self.FLOOD_TRACE_MAX_LIVE: int = kw.get(
            "FLOOD_TRACE_MAX_LIVE", 512)

        # continuous node-vitals sampler (utils/vitals.py): periodic
        # RSS/fd/thread/queue/bucket/GC gauges in a bounded ring with
        # per-gauge slope estimation, vitals.* Prometheus gauges, the
        # HTTP vitals endpoint, and the SLO watchdog.  Suites and sims
        # keep it off (one timer per node); real/TOML nodes default on.
        self.VITALS_ENABLED: bool = kw.get("VITALS_ENABLED", True)
        self.VITALS_PERIOD_SECONDS: float = kw.get(
            "VITALS_PERIOD_SECONDS", 1.0)
        self.VITALS_RING_SAMPLES: int = kw.get("VITALS_RING_SAMPLES", 900)
        # append one JSON line per sample (offline soak analysis)
        self.VITALS_JSONL: Optional[str] = kw.get("VITALS_JSONL")
        # SLO ceilings the watchdog enforces (structured WARN per breach
        # episode + slo.breach.* counters); each 0 disables that check
        self.SLO_MAX_MEMORY_SLOPE_MB_S: float = kw.get(
            "SLO_MAX_MEMORY_SLOPE_MB_S", 16.0)
        self.SLO_MAX_CLOSE_P99_SECONDS: float = kw.get(
            "SLO_MAX_CLOSE_P99_SECONDS", 5.0)
        self.SLO_MAX_QUEUE_AGE: int = kw.get("SLO_MAX_QUEUE_AGE", 3)
        # vitals sample taken while the local quorum slice is
        # unsatisfiable from recently-heard nodes = a breach episode
        # (fed by the quorum-health monitor's gauges; False disables)
        self.SLO_QUORUM_AVAILABILITY: bool = kw.get(
            "SLO_QUORUM_AVAILABILITY", True)

        # consensus forensics (scp/timeline.py): per-slot SCP timeline
        # ring — state-machine transitions, envelopes with verdicts,
        # timer arms/fires — behind the scp?slot=N endpoint and the
        # chaos engine's cross-node forensic dumps.  Recording is
        # provably inert (telemetry on/off closes bit-identical,
        # tests + detlint det-telemetry-readback).
        self.SCP_TIMELINE_ENABLED: bool = kw.get(
            "SCP_TIMELINE_ENABLED", True)
        self.SCP_TIMELINE_SLOTS: int = kw.get("SCP_TIMELINE_SLOTS", 32)
        self.SCP_TIMELINE_EVENTS_PER_SLOT: int = kw.get(
            "SCP_TIMELINE_EVENTS_PER_SLOT", 256)

        # quorum-health monitor (herder/quorum_health.py): one cheap
        # qset-graph evaluation per close (heard/available/criticality
        # gauges), plus an optional budget-capped intersection scan
        # every PERIOD closes (0 = on demand only via the
        # quorum-health?intersection=true endpoint)
        self.QUORUM_HEALTH_ENABLED: bool = kw.get(
            "QUORUM_HEALTH_ENABLED", True)
        self.QUORUM_HEALTH_INTERSECTION_PERIOD: int = kw.get(
            "QUORUM_HEALTH_INTERSECTION_PERIOD", 0)
        self.QUORUM_HEALTH_INTERSECTION_MAX_CALLS: int = kw.get(
            "QUORUM_HEALTH_INTERSECTION_MAX_CALLS", 200_000)
        self.QUORUM_HEALTH_INTERSECTION_TIMEOUT_SECONDS: float = kw.get(
            "QUORUM_HEALTH_INTERSECTION_TIMEOUT_SECONDS", 1.0)

        # invariants
        self.INVARIANT_CHECKS: List[str] = kw.get("INVARIANT_CHECKS", [])

        # history
        self.HISTORY: Dict[str, dict] = kw.get("HISTORY", {})
        self.CHECKPOINT_FREQUENCY: int = kw.get(
            "CHECKPOINT_FREQUENCY",
            8 if self.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING else 64)

        if self.NODE_SEED is None:
            self.NODE_SEED = sha256(b"default-node-seed")

    def validate(self) -> None:
        """Sanity pass run before a node boots (ref Config::load's
        validation + validateConfig's quorum-safety rules).  Raises
        ConfigError with an operator-actionable message."""
        import re

        if not self.NETWORK_PASSPHRASE:
            raise ConfigError("NETWORK_PASSPHRASE must be non-empty")
        if len(self.NODE_SEED) != 32:
            raise ConfigError("NODE_SEED must be a 32-byte seed")
        # 0 / None disable the respective listener (enable_tcp honors
        # both sentinels); ranges only apply when enabled
        for name in ("PEER_PORT", "HTTP_PORT"):
            v = getattr(self, name)
            if v and not (0 < v < 65536):
                raise ConfigError(f"{name} out of range: {v}")
        if self.PEER_PORT and self.HTTP_PORT and \
                self.PEER_PORT == self.HTTP_PORT:
            raise ConfigError("PEER_PORT and HTTP_PORT must differ")
        if self.EXP_LEDGER_TIMESPAN_SECONDS <= 0:
            raise ConfigError("EXP_LEDGER_TIMESPAN_SECONDS must be > 0")
        if self.MAX_SLOTS_TO_REMEMBER < 1:
            raise ConfigError("MAX_SLOTS_TO_REMEMBER must be >= 1")
        if self.MAX_CONCURRENT_SUBPROCESSES < 1:
            raise ConfigError("MAX_CONCURRENT_SUBPROCESSES must be >= 1")
        if self.TRACE_RING_CLOSES < 1:
            raise ConfigError("TRACE_RING_CLOSES must be >= 1")
        if self.ARTIFICIALLY_SLEEP_IN_CLOSE_FOR_TESTING < 0:
            raise ConfigError(
                "ARTIFICIALLY_SLEEP_IN_CLOSE_FOR_TESTING must be >= 0")
        if self.VITALS_PERIOD_SECONDS <= 0:
            raise ConfigError("VITALS_PERIOD_SECONDS must be > 0")
        if self.VITALS_RING_SAMPLES < 2:
            raise ConfigError("VITALS_RING_SAMPLES must be >= 2")
        if self.TX_LIFECYCLE_RING < 1 or self.TX_LIFECYCLE_MAX_LIVE < 2:
            raise ConfigError(
                "TX_LIFECYCLE_RING must be >= 1 and "
                "TX_LIFECYCLE_MAX_LIVE >= 2")
        if self.FLOOD_TRACE_RING < 1 or self.FLOOD_TRACE_MAX_LIVE < 2:
            raise ConfigError(
                "FLOOD_TRACE_RING must be >= 1 and "
                "FLOOD_TRACE_MAX_LIVE >= 2")
        if self.SCP_TIMELINE_SLOTS < 1 or \
                self.SCP_TIMELINE_EVENTS_PER_SLOT < 8:
            raise ConfigError(
                "SCP_TIMELINE_SLOTS must be >= 1 and "
                "SCP_TIMELINE_EVENTS_PER_SLOT >= 8")
        if self.QUORUM_HEALTH_INTERSECTION_PERIOD < 0:
            raise ConfigError(
                "QUORUM_HEALTH_INTERSECTION_PERIOD must be >= 0")
        if self.PARALLEL_APPLY_WORKERS < 0:
            raise ConfigError("PARALLEL_APPLY_WORKERS must be >= 0")
        if self.MAX_DEX_TX_OPERATIONS is not None and \
                self.MAX_DEX_TX_OPERATIONS < 0:
            raise ConfigError("MAX_DEX_TX_OPERATIONS must be >= 0")
        if self.CRYPTO_BACKEND not in ("cpu", "tpu", "auto"):
            raise ConfigError(
                f"unknown CRYPTO_BACKEND {self.CRYPTO_BACKEND!r}")
        if self.SCP_TALLY_BACKEND not in ("host", "tensor", "both",
                                         "auto"):
            raise ConfigError(
                f"unknown SCP_TALLY_BACKEND {self.SCP_TALLY_BACKEND!r}")
        for pat in self.INVARIANT_CHECKS:
            try:
                re.compile(pat)
            except re.error as e:
                raise ConfigError(
                    f"INVARIANT_CHECKS pattern {pat!r}: {e}") from e
        for a in self.HISTORY_ARCHIVES:
            if isinstance(a, dict):
                if "name" not in a or not ("get" in a or "put" in a):
                    raise ConfigError(
                        "command-template HISTORY_ARCHIVES entries need "
                        "'name' and at least one of 'get'/'put'")
                unknown = set(a) - {"name", "get", "put", "mkdir"}
                if unknown:
                    raise ConfigError(
                        f"unknown archive keys: {sorted(unknown)}")
            elif len(a) != 2:
                raise ConfigError(
                    "HISTORY_ARCHIVES entries must be [name, path] pairs "
                    "or {name, get, put, mkdir} command tables")
        if self.QUORUM_SET is not None:
            self._validate_qset(self.QUORUM_SET, depth=0)
        elif self.NODE_IS_VALIDATOR and not self.RUN_STANDALONE:
            raise ConfigError("validator nodes need a QUORUM_SET")

    def _validate_qset(self, qs: dict, depth: int) -> None:
        """Structure + byzantine-safety of a quorum-set spec (ref
        validateConfig: threshold >= n - f with f = (n-1)/3 unless
        UNSAFE_QUORUM; FAILURE_SAFETY overrides f at the top level)."""
        if depth > 2:
            raise ConfigError("quorum set nested deeper than 2 levels")
        validators = qs.get("validators", [])
        inner = qs.get("inner_sets", [])
        n = len(validators) + len(inner)
        thr = qs.get("threshold", 0)
        if n == 0:
            raise ConfigError("empty quorum set")
        if not (1 <= thr <= n):
            raise ConfigError(
                f"quorum threshold {thr} out of range 1..{n}")
        if len(set(validators)) != len(validators):
            raise ConfigError("duplicate validator in quorum set")
        if depth == 0 and not self.UNSAFE_QUORUM:
            max_f = (n - 1) // 3
            f = max_f if self.FAILURE_SAFETY < 0 else self.FAILURE_SAFETY
            if f > max_f:
                # tolerating more than (n-1)/3 byzantine failures is
                # impossible; a larger f would also weaken the threshold
                # bound below into a liveness-only check
                raise ConfigError(
                    f"FAILURE_SAFETY {f} exceeds the {max_f} byzantine "
                    f"failures a {n}-member quorum set can tolerate")
            if thr < n - f:
                raise ConfigError(
                    f"quorum threshold {thr} < {n - f} is unsafe for "
                    f"{n} members tolerating {f} failures; raise the "
                    "threshold or set UNSAFE_QUORUM = true")
        for s in inner:
            self._validate_qset(s, depth + 1)

    def network_id(self) -> bytes:
        return sha256(self.NETWORK_PASSPHRASE.encode())

    def node_secret(self) -> SecretKey:
        return SecretKey(self.NODE_SEED)

    def node_id(self) -> bytes:
        return self.node_secret().public_key().raw

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        try:
            import tomllib
        except ImportError:  # Python < 3.11: the bundled subset parser
            from ..utils import minitoml as tomllib

        with open(path, "rb") as f:
            data = tomllib.load(f)
        kw = {}
        known = set(vars(cls()))
        for k, v in data.items():
            if k.upper() not in known:
                raise ConfigError(f"unknown configuration key: {k}")
            kw[k.upper()] = v
        if "NODE_SEED" in kw and isinstance(kw["NODE_SEED"], str):
            from ..crypto.strkey import decode_ed25519_seed

            kw["NODE_SEED"] = decode_ed25519_seed(kw["NODE_SEED"])
        qs = kw.get("QUORUM_SET")
        if qs:
            kw["QUORUM_SET"] = cls._decode_qset_spec(qs)
        if "HISTORY_ARCHIVES" in kw:
            kw["HISTORY_ARCHIVES"] = [
                a if isinstance(a, dict) else tuple(a)
                for a in kw["HISTORY_ARCHIVES"]]
        cfg = cls(**kw)
        # a file-configured node persists buckets next to its database by
        # default (BUCKET_DIR_PATH resolved relative to the config file,
        # like the reference's BUCKET_DIR_PATH); in-memory-DB nodes stay
        # storeless unless an explicit real path is given
        if cfg.BUCKET_DIR_PATH_REAL is None and cfg.DATABASE != ":memory:":
            import os

            base = os.path.dirname(os.path.abspath(path))
            cfg.BUCKET_DIR_PATH_REAL = (
                cfg.BUCKET_DIR_PATH
                if os.path.isabs(cfg.BUCKET_DIR_PATH)
                else os.path.join(base, cfg.BUCKET_DIR_PATH))
        cfg.validate()
        return cfg

    @staticmethod
    def _decode_qset_spec(qs: dict) -> dict:
        """TOML quorum sets name validators by strkey (G...); decode to
        raw keys recursively."""
        from ..crypto.strkey import decode_ed25519_public_key

        def conv(v):
            return (decode_ed25519_public_key(v)
                    if isinstance(v, str) else v)

        out = {"threshold": qs["threshold"],
               "validators": [conv(v) for v in qs.get("validators", [])]}
        if qs.get("inner_sets"):
            out["inner_sets"] = [Config._decode_qset_spec(s)
                                 for s in qs["inner_sets"]]
        return out


def test_config(n: int = 0, **kw) -> Config:
    """getTestConfig equivalent (ref src/test/TestUtils): standalone,
    manual close, in-memory DB, accelerated time."""
    import os

    defaults = dict(
        NODE_SEED=sha256(b"test-node-%d" % n),
        RUN_STANDALONE=True,
        MANUAL_CLOSE=True,
        ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True,
        DATABASE=":memory:",
        INVARIANT_CHECKS=[".*"],
        # test quorums (2-of-3 etc.) are below the byzantine-safety bar
        # on purpose (ref getTestConfig setting UNSAFE_QUORUM)
        UNSAFE_QUORUM=True,
        # suites/simulations keep normal GC: the deferred policy is
        # process-global and one multi-app pytest process must not have
        # collection disabled by the first test app
        DEFERRED_GC=False,
        # the slow-close watchdog stays off in suites (a loaded CI worker
        # crossing the threshold would litter trace files in the cwd);
        # watchdog tests opt in with an explicit threshold + TRACE_DIR
        SLOW_CLOSE_THRESHOLD_SECONDS=0.0,
        # tests pin the host tiers: "auto" would spawn one device-probe
        # subprocess per process, and the suite runs on CPU anyway;
        # device-path tests opt in explicitly
        CRYPTO_BACKEND="cpu",
        SCP_TALLY_BACKEND="host",
        # parallel apply stays opt-in for suites: the default tier-1
        # pass exercises the sequential path; tools/verify_green.py's
        # parallel smoke re-runs the suite with PARALLEL_APPLY_WORKERS=2
        # exported, which flips every test Application to parallel
        PARALLEL_APPLY_WORKERS=int(
            os.environ.get("PARALLEL_APPLY_WORKERS", "0") or 0),
        # same discipline for the pipelined close: off in the default
        # tier-1 pass, flipped on suite-wide by verify_green's
        # PIPELINED_CLOSE=1 smoke (MANUAL_CLOSE rigs then eager-drain
        # per close, so post-close reads keep sequential semantics)
        PIPELINED_CLOSE=os.environ.get("PIPELINED_CLOSE", "0") == "1",
        # the vitals timer stays off in suites (a per-app 1 Hz timer
        # would perturb crank_until-driven rigs and add 50 timers/s at
        # simulation scale); vitals/soak tests opt in explicitly.  The
        # tx-lifecycle tracker stays ON — it owns no timers and every
        # suite close then exercises the stamp sites.
        VITALS_ENABLED=False,
    )
    defaults.update(kw)
    return Config(**defaults)
