"""Config: the node's knob surface (ref src/main/Config.h — a 607-line
header of ~200 TOML-loaded fields; this port keeps the same names for the
load-bearing ones and loads from TOML via tomllib or from kwargs).
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..crypto import SecretKey, sha256


class Config:
    CURRENT_LEDGER_PROTOCOL_VERSION = 19

    def __init__(self, **kw):
        # identity / network
        self.NETWORK_PASSPHRASE: str = kw.get(
            "NETWORK_PASSPHRASE", "Test SDF Network ; September 2015")
        self.NODE_SEED: Optional[bytes] = kw.get("NODE_SEED")
        self.NODE_IS_VALIDATOR: bool = kw.get("NODE_IS_VALIDATOR", True)
        self.QUORUM_SET: Optional[dict] = kw.get("QUORUM_SET")

        # mode
        self.RUN_STANDALONE: bool = kw.get("RUN_STANDALONE", False)
        self.MANUAL_CLOSE: bool = kw.get("MANUAL_CLOSE", False)
        self.FORCE_SCP: bool = kw.get("FORCE_SCP", False)

        # protocol / testing knobs
        self.LEDGER_PROTOCOL_VERSION: int = kw.get(
            "LEDGER_PROTOCOL_VERSION",
            self.CURRENT_LEDGER_PROTOCOL_VERSION)
        self.TESTING_UPGRADE_DESIRED_FEE: int = kw.get(
            "TESTING_UPGRADE_DESIRED_FEE", 100)
        self.TESTING_UPGRADE_RESERVE: int = kw.get(
            "TESTING_UPGRADE_RESERVE", 5000000)
        self.TESTING_UPGRADE_MAX_TX_SET_SIZE: int = kw.get(
            "TESTING_UPGRADE_MAX_TX_SET_SIZE", 100)
        self.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING: bool = kw.get(
            "ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING", False)

        # storage
        self.DATABASE: str = kw.get("DATABASE", ":memory:")
        self.BUCKET_DIR_PATH: str = kw.get("BUCKET_DIR_PATH", "buckets")
        # set to a real directory to persist bucket files (restart support)
        self.BUCKET_DIR_PATH_REAL: Optional[str] = kw.get(
            "BUCKET_DIR_PATH_REAL")
        # [(name, local-directory-path)] history archives to publish
        # to / catch up from (ref HISTORY config blocks)
        self.HISTORY_ARCHIVES: List[tuple] = kw.get("HISTORY_ARCHIVES", [])
        # file path receiving length-framed LedgerCloseMeta XDR per close
        # (ref METADATA_OUTPUT_STREAM, Config.h)
        self.METADATA_OUTPUT_STREAM: Optional[str] = kw.get(
            "METADATA_OUTPUT_STREAM")

        # upgrades this node votes for when nominating (ref Upgrades::
        # UpgradeParameters; None = don't propose)
        self.UPGRADE_DESIRED_PROTOCOL_VERSION: Optional[int] = kw.get(
            "UPGRADE_DESIRED_PROTOCOL_VERSION")
        self.UPGRADE_DESIRED_BASE_FEE: Optional[int] = kw.get(
            "UPGRADE_DESIRED_BASE_FEE")
        self.UPGRADE_DESIRED_MAX_TX_SET_SIZE: Optional[int] = kw.get(
            "UPGRADE_DESIRED_MAX_TX_SET_SIZE")
        self.UPGRADE_DESIRED_BASE_RESERVE: Optional[int] = kw.get(
            "UPGRADE_DESIRED_BASE_RESERVE")

        # SCP federated-tally backend: "host" (exact python), "tensor"
        # (batched device kernels, ops/quorum.py), or "both" (tensor with
        # the host oracle asserting equality — differential testing)
        self.SCP_TALLY_BACKEND: str = kw.get("SCP_TALLY_BACKEND", "host")

        # consensus cadence (ref Herder.cpp:7-18)
        self.EXP_LEDGER_TIMESPAN_SECONDS: float = kw.get(
            "EXP_LEDGER_TIMESPAN_SECONDS",
            1.0 if kw.get("ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING")
            else 5.0)
        self.MAX_SCP_TIMEOUT_SECONDS: float = 240.0
        self.CONSENSUS_STUCK_TIMEOUT_SECONDS: float = 35.0

        # overlay
        self.PEER_PORT: int = kw.get("PEER_PORT", 11625)
        self.HTTP_PORT: int = kw.get("HTTP_PORT", 11626)
        self.TARGET_PEER_CONNECTIONS: int = kw.get(
            "TARGET_PEER_CONNECTIONS", 8)
        self.MAX_ADDITIONAL_PEER_CONNECTIONS: int = kw.get(
            "MAX_ADDITIONAL_PEER_CONNECTIONS", 64)
        self.KNOWN_PEERS: List[str] = kw.get("KNOWN_PEERS", [])

        # device tier
        self.CRYPTO_BACKEND: str = kw.get("CRYPTO_BACKEND", "cpu")

        # invariants
        self.INVARIANT_CHECKS: List[str] = kw.get("INVARIANT_CHECKS", [])

        # history
        self.HISTORY: Dict[str, dict] = kw.get("HISTORY", {})
        self.CHECKPOINT_FREQUENCY: int = (
            8 if self.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING else 64)

        if self.NODE_SEED is None:
            self.NODE_SEED = sha256(b"default-node-seed")

    def network_id(self) -> bytes:
        return sha256(self.NETWORK_PASSPHRASE.encode())

    def node_secret(self) -> SecretKey:
        return SecretKey(self.NODE_SEED)

    def node_id(self) -> bytes:
        return self.node_secret().public_key().raw

    @classmethod
    def from_toml(cls, path: str) -> "Config":
        import tomllib

        with open(path, "rb") as f:
            data = tomllib.load(f)
        kw = {}
        for k, v in data.items():
            kw[k.upper()] = v
        if "NODE_SEED" in kw and isinstance(kw["NODE_SEED"], str):
            from ..crypto.strkey import decode_ed25519_seed

            kw["NODE_SEED"] = decode_ed25519_seed(kw["NODE_SEED"])
        qs = kw.get("QUORUM_SET")
        if qs:
            kw["QUORUM_SET"] = cls._decode_qset_spec(qs)
        if "HISTORY_ARCHIVES" in kw:
            kw["HISTORY_ARCHIVES"] = [
                tuple(a) for a in kw["HISTORY_ARCHIVES"]]
        return cls(**kw)

    @staticmethod
    def _decode_qset_spec(qs: dict) -> dict:
        """TOML quorum sets name validators by strkey (G...); decode to
        raw keys recursively."""
        from ..crypto.strkey import decode_ed25519_public_key

        def conv(v):
            return (decode_ed25519_public_key(v)
                    if isinstance(v, str) else v)

        out = {"threshold": qs["threshold"],
               "validators": [conv(v) for v in qs.get("validators", [])]}
        if qs.get("inner_sets"):
            out["inner_sets"] = [Config._decode_qset_spec(s)
                                 for s in qs["inner_sets"]]
        return out


def test_config(n: int = 0, **kw) -> Config:
    """getTestConfig equivalent (ref src/test/TestUtils): standalone,
    manual close, in-memory DB, accelerated time."""
    defaults = dict(
        NODE_SEED=sha256(b"test-node-%d" % n),
        RUN_STANDALONE=True,
        MANUAL_CLOSE=True,
        ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING=True,
        DATABASE=":memory:",
        INVARIANT_CHECKS=[".*"],
    )
    defaults.update(kw)
    return Config(**defaults)
