"""Application: the container that owns and wires every subsystem
(ref src/main/Application.h:132-318, ApplicationImpl.cpp — SURVEY.md §2.10).

Construction wires: clock -> metrics -> database -> bucket manager ->
ledger manager -> invariants -> herder -> (overlay, history when
configured).  ``start()`` mirrors ApplicationImpl::start() :772-821:
load-or-create ledger -> herder start -> overlay start.
"""
from __future__ import annotations

from typing import List, Optional

from ..bucket.bucket_list import BucketManager
from ..herder.herder import Herder
from ..invariant.manager import InvariantManager
from ..ledger.ledger_manager import LedgerManager
from ..utils.clock import ClockMode, VirtualClock
from ..utils.metrics import MetricsRegistry
from ..utils.scheduler import Scheduler
from ..work.work import WorkScheduler
from ..xdr import types as T
from .config import Config

# process-global one-shot flag for the deferred-GC policy
_GC_DEFERRED = False


class Application:
    def __init__(self, clock: VirtualClock, config: Config):
        self.clock = clock
        self.config = config
        # resolve "auto" device backends once, before any subsystem reads
        # them: default-on TPU when a device answers the (never-killed,
        # bounded-wait) subprocess probe, CPU tiers otherwise
        if "auto" in (config.CRYPTO_BACKEND, config.SCP_TALLY_BACKEND):
            from ..utils.device import device_available

            alive = device_available()
            if config.CRYPTO_BACKEND == "auto":
                config.CRYPTO_BACKEND = "tpu" if alive else "cpu"
            if config.SCP_TALLY_BACKEND == "auto":
                config.SCP_TALLY_BACKEND = "tensor" if alive else "host"
        self.metrics = MetricsRegistry(clock)
        # flight recorder: span ring + slow-close watchdog (utils/tracing)
        from ..utils.tracing import Tracer

        self.tracer = Tracer(
            enabled=config.TRACING_ENABLED,
            ring_closes=config.TRACE_RING_CLOSES,
            slow_close_threshold=(
                config.SLOW_CLOSE_THRESHOLD_SECONDS
                if config.SLOW_CLOSE_THRESHOLD_SECONDS > 0 else None),
            trace_dir=config.TRACE_DIR,
            metrics=self.metrics)
        # tx-lifecycle telemetry: sampled per-tx stage stamps across
        # overlay/herder/ledger, rolled into txtrace.* histograms and
        # the tx/latency endpoint (utils/txtrace.py)
        from ..utils.txtrace import TxLifecycleTracker

        self.txtracer = TxLifecycleTracker(
            metrics=self.metrics,
            enabled=config.TX_LIFECYCLE_TRACKING,
            max_live=config.TX_LIFECYCLE_MAX_LIVE,
            ring=config.TX_LIFECYCLE_RING)
        # flood-propagation telemetry: per-item hop records across the
        # overlay flood, stamped on the shared clock so a simulation's
        # nodes produce cross-comparable (and deterministic) timelines
        # (utils/floodtrace.py; merged by simulation/observatory.py)
        from ..utils.floodtrace import FloodPropagationTracker

        self.floodtracer = FloodPropagationTracker(
            metrics=self.metrics,
            enabled=config.FLOOD_TRACE_ENABLED,
            now=clock.now,
            max_live=config.FLOOD_TRACE_MAX_LIVE,
            ring=config.FLOOD_TRACE_RING)
        self.scheduler = Scheduler(clock)
        from ..database import Database

        self.database = Database(config.DATABASE, metrics=self.metrics)
        self.bucket_manager = BucketManager(
            self, bucket_dir=getattr(config, "BUCKET_DIR_PATH_REAL", None))
        self.invariants = InvariantManager(config.INVARIANT_CHECKS)
        self.ledger_manager = LedgerManager(self)
        # parallel transaction apply: footprint planner + conflict
        # clusters + bit-identical concurrent executor (apply/), with
        # its own PR-1-style worker pool when enabled
        from ..apply import ParallelApplyManager

        self.parallel_apply = ParallelApplyManager(self)
        from ..work.work import WorkerPool

        self.work_scheduler = WorkScheduler(
            clock,
            worker_pool=(WorkerPool(config.WORK_POOL_WORKERS)
                         if getattr(config, "WORK_POOL_WORKERS", 4) > 0
                         else None))
        self.herder = Herder(self)
        self.overlay_manager = None   # wired by overlay.setup (optional)
        from ..process import ProcessManager

        # before HistoryManager: command-template archives transfer
        # through the process manager
        self.process_manager = ProcessManager(
            self, config.MAX_CONCURRENT_SUBPROCESSES)
        from ..history import HistoryManager

        self.history_manager = HistoryManager(self)
        from ..catchup import CatchupManager

        self.catchup_manager = CatchupManager(self)
        # continuous node-vitals sampler + SLO watchdog (utils/vitals):
        # constructed always (endpoints/report work either way), the
        # periodic timer + gc callback only engage via start()
        from ..utils.vitals import VitalsSampler

        self.vitals = VitalsSampler(self)
        import threading

        # LedgerCloseMeta ring: appended by whichever thread runs the
        # close path (main sequential, close tail pipelined — detlint
        # conc-unguarded-shared); reads (tests, forensics) are lock-free
        # list snapshots
        from ..utils.lockdep import register_lock

        self._meta_lock = register_lock(threading.Lock(), "app.meta")
        self._meta_stream: List = []  # guarded-by: _meta_lock
        self._started = False
        # real-socket mode (enable_tcp): io service + listeners
        self.tcp_io = None
        self.peer_door = None
        self.http_server = None

    # -- lifecycle (ref ApplicationImpl::start :772) ------------------------

    @classmethod
    def create(cls, clock: Optional[VirtualClock] = None,
               config: Optional[Config] = None) -> "Application":
        return cls(clock or VirtualClock(ClockMode.REAL_TIME),
                   config or Config())

    def start(self) -> None:
        self.config.validate()
        T.ensure_native_encode()  # build once per checkout, cached .so
        if self.config.DEFERRED_GC:
            # low-latency close discipline: a gen-2 cycle collection can
            # stall the single-threaded close loop for >1s (measured:
            # p99 1.45s vs p50 0.3s purely from GC).  Freeze the startup
            # arena, stop automatic collection, and collect explicitly
            # AFTER each close (LedgerManager._post_close_gc) where the
            # 5s cadence has idle room.  Process-global and one-shot: a
            # second Application in the same process must not re-freeze
            # (that would pin earlier apps' dead cycles forever).
            global _GC_DEFERRED
            if not _GC_DEFERRED:
                _GC_DEFERRED = True
                import gc

                gc.freeze()
                gc.disable()
        if self.ledger_manager.load_last_known_ledger():
            restored = self._restore_bucket_state()
            # BucketListDB reads only activate when the bucket list
            # provably matches the last closed header; a node without a
            # (verified) bucket store keeps serving reads from SQL
            if restored and self.config.BUCKETLIST_DB:
                self.ledger_manager.root.enable_bucket_reads()
                self._restore_sql_ahead()
        else:
            if self.config.BUCKETLIST_DB:
                # fresh start: the bucket list begins empty and every
                # close folds its delta in, so it stays authoritative
                # from genesis (direct writes ride the sql-ahead overlay)
                self.ledger_manager.root.enable_bucket_reads()
            self.ledger_manager.start_new_ledger()
        self.herder.start()
        if self.overlay_manager is not None:
            self.overlay_manager.start()
        if self.tcp_io is not None:
            self.connect_known_peers()
            # periodic connection top-up (ref OverlayManagerImpl::tick):
            # a one-shot dial would leave the node isolated forever when
            # it races a peer's listener coming up
            from ..utils.clock import VirtualTimer

            self._overlay_tick_timer = VirtualTimer(self.clock,
                                                    owner=self)
            self._arm_overlay_tick()
        self.history_manager.publish_queued_history()
        self.vitals.start()
        self._started = True

    def _restore_bucket_state(self) -> bool:
        """Reassume the bucket list from the persisted level hashes + the
        on-disk bucket files (ref ApplicationImpl::start :788 ->
        loadLastKnownLedger -> AssumeStateWork).  True when the restored
        list hash-matches the last closed header (the gate for
        BucketListDB reads)."""
        import json

        if self.bucket_manager.bucket_dir is None:
            # no on-disk bucket store configured: nothing to restore from
            # (state hashes can't be rebuilt; catchup from an archive is
            # the rejoin path for such nodes)
            return False
        row = self.database.execute(
            "SELECT state FROM persistentstate WHERE "
            "statename='bucketlist'").fetchone()
        if row is None:
            return False
        level_hashes = [tuple(p) for p in json.loads(row[0])]
        self.bucket_manager.restore_from_level_hashes(level_hashes)
        hdr = self.ledger_manager.last_closed_header()
        if self.bucket_manager.get_bucket_list_hash() != \
                hdr.bucketListHash:
            raise RuntimeError(
                "restored bucket list does not match the last closed "
                "header's bucketListHash")
        if self.config.BUCKETLIST_DB:
            # build/load every bucket's index NOW (persisted sidecar
            # blooms make this a memmap open; legacy pre-index sidecars
            # upgrade here, at boot) — never as a multi-second stall
            # inside the first point read of the apply path
            self.bucket_manager.bucket_list.ensure_indexes()
        return True

    def _restore_sql_ahead(self) -> None:
        """Reload the sql-ahead overlay's persisted key list (stored
        alongside the bucket state): entries that only ever lived in SQL
        must stay visible to BucketListDB-mode reads across restarts."""
        import json

        row = self.database.execute(
            "SELECT state FROM persistentstate WHERE "
            "statename='sqlahead'").fetchone()
        if row is None:
            return
        self.ledger_manager.root.load_sql_ahead(
            bytes.fromhex(h) for h in json.loads(row[0]))

    def crank(self, block: bool = False) -> int:
        n = self.clock.crank(block)
        while self.scheduler.run_one():
            n += 1
        self.work_scheduler.crank()
        n += self.process_manager.poll()
        if self.tcp_io is not None:
            n += self.tcp_io.poll()
        return n

    def enable_tcp(self) -> None:
        """Real-socket mode: TCP overlay transport + PeerDoor + admin HTTP
        (ref ApplicationImpl start wiring OverlayManager/PeerDoor/
        CommandHandler).  Outbound connections go to KNOWN_PEERS."""
        from ..overlay.manager import OverlayManager
        from ..overlay.tcp_peer import PeerDoor, TCPIOService
        from .http_server import AdminHttpServer

        self.tcp_io = TCPIOService()
        if self.overlay_manager is None:
            self.overlay_manager = OverlayManager(self)
        if self.config.PEER_PORT:
            self.peer_door = PeerDoor(self, self.config.PEER_PORT)
            self.tcp_io.register(self.peer_door.sock,
                                 self.peer_door.on_acceptable)
        if self.config.HTTP_PORT is not None:
            self.http_server = AdminHttpServer(self,
                                               self.config.HTTP_PORT)

    def connect_known_peers(self) -> None:
        from ..overlay.tcp_peer import connect_to

        from ..overlay.peer_manager import OUTBOUND, PREFERRED

        pm = self.overlay_manager.peer_manager
        known = []
        for plist, ptype in ((self.config.PREFERRED_PEERS, PREFERRED),
                             (self.config.KNOWN_PEERS, OUTBOUND)):
            for addr in plist:
                host, _, port = addr.partition(":")
                known.append((host or "127.0.0.1", int(port or 11625),
                              ptype))
        if pm is not None:
            for host, port, ptype in known:
                pm.ensure_exists(host, port, ptype)
            targets = pm.peers_to_try(
                self.config.TARGET_PEER_CONNECTIONS)
        else:
            targets = [(h, p) for h, p, _ in known]
        # never re-dial an address we're already connected (or mid-
        # handshake) to — the periodic tick would otherwise churn a new
        # socket to the same peer every 2s
        connected = set()
        for p in list(self.overlay_manager.authenticated.values()) + \
                list(self.overlay_manager.pending_peers):
            addr = getattr(p, "remote_addr", None)
            if addr is not None:
                connected.add(addr)
        for host, port in targets:
            if (host, port) in connected:
                continue
            peer = connect_to(self, host, port)
            if peer is None and pm is not None:
                pm.on_connect_failure(host, port)

    def _arm_overlay_tick(self) -> None:
        t = self._overlay_tick_timer
        t.cancel()
        t.expires_from_now(2.0)
        t.async_wait(self._overlay_tick)

    def _overlay_tick(self) -> None:
        om = self.overlay_manager
        if om is not None and \
                len(om.authenticated) < self.config.TARGET_PEER_CONNECTIONS:
            self.connect_known_peers()
        self._arm_overlay_tick()

    def stop_node(self) -> None:
        """Tear down THIS node's subsystems without touching the clock —
        the clock may be shared by a whole simulated network (chaos
        crash-restore kills one validator while the rest keep cranking).
        Every timer tagged with this app is swept so no callback fires
        into freed subsystems; on-disk state (DATABASE file + bucket
        store) survives for a restart-from-state rebuild."""
        # vitals first: its gc callback is PROCESS-global (gc.callbacks)
        # and must never keep timing collections for a dead node
        self.vitals.stop()
        # then the close pipeline: its tail worker holds the database
        # and bucket store, both torn down below (drains the in-flight
        # tail; an abandoned tail — the chaos pipeline-window crash —
        # was already discarded via crash_abandon)
        self.ledger_manager.pipeline.shutdown()
        # abort in-flight works (a mid-catchup teardown re-attaches the
        # ledger root) and stop the worker pool before the stores they
        # write to go away below
        self.work_scheduler.shutdown()
        self.process_manager.shutdown()
        self.parallel_apply.shutdown()
        self.bucket_manager.shutdown()
        if self.overlay_manager is not None:
            self.overlay_manager.shutdown()
        if self.peer_door is not None:
            self.peer_door.close()
        if self.http_server is not None:
            self.http_server.close()
        self.clock.cancel_owner(self)
        self.database.close()
        self._started = False

    def graceful_stop(self) -> None:
        self.stop_node()
        self.clock.stop()

    # -- cross-subsystem plumbing ------------------------------------------

    def broadcast_transaction(self, env) -> None:
        if self.overlay_manager is not None:
            self.overlay_manager.broadcast_transaction(env)

    def broadcast_scp_message(self, env) -> None:
        if self.overlay_manager is not None:
            self.overlay_manager.broadcast_scp(env)

    def request_scp_items(self, hashes: List[bytes]) -> None:
        if self.overlay_manager is not None:
            self.overlay_manager.fetch_items(hashes)

    def emit_ledger_close_meta(self, header, tx_set, tx_metas,
                               upgrade_metas) -> None:
        """METADATA_OUTPUT_STREAM equivalent: in-memory ring of
        LedgerCloseMeta (ref LedgerManagerImpl.cpp:738-757)."""
        from ..xdr import xdr_sha256

        meta = T.LedgerCloseMeta.make(0, T.LedgerCloseMetaV0.make(
            ledgerHeader=T.LedgerHeaderHistoryEntry.make(
                hash=xdr_sha256(T.LedgerHeader, header),
                header=header,
                ext=T.LedgerHeaderHistoryEntry.fields[2][1].make(0)),
            txSet=tx_set.to_xdr(),
            txProcessing=tx_metas,
            upgradesProcessing=upgrade_metas,
            scpInfo=[]))
        with self._meta_lock:
            self._meta_stream.append(meta)
            if len(self._meta_stream) > 64:
                self._meta_stream.pop(0)
        # METADATA_OUTPUT_STREAM: append framed XDR to a file for
        # downstream consumers (ref LedgerManagerImpl.cpp:738-757; the
        # reference writes to a configured fd/file)
        path = getattr(self.config, "METADATA_OUTPUT_STREAM", None)
        if path:
            data = T.LedgerCloseMeta.encode(meta)
            with open(path, "ab") as f:
                f.write(len(data).to_bytes(4, "big") + data)
        self.metrics.meter("ledger.close.frame").mark()

    # -- status (ref getJsonInfo / 'info' endpoint) -------------------------

    def get_json_info(self) -> dict:
        lm = self.ledger_manager
        try:
            header = lm.last_closed_header()
            ledger_info = {
                "num": header.ledgerSeq,
                "hash": lm.last_closed_hash().hex(),
                "closeTime": header.scpValue.closeTime,
                "baseFee": header.baseFee,
                "baseReserve": header.baseReserve,
                "maxTxSetSize": header.maxTxSetSize,
                "version": header.ledgerVersion,
            }
        except Exception:
            ledger_info = {}
        return {
            "build": "stellar-core-tpu",
            "ledger": ledger_info,
            "state": ("Synced!" if self._started else "Booting"),
            "network": self.config.NETWORK_PASSPHRASE,
            "protocol_version": self.config.LEDGER_PROTOCOL_VERSION,
            "peers": (self.overlay_manager.connection_count()
                      if self.overlay_manager else 0),
            "pending_txs": self.herder.tx_queue.size(),
            "crypto_backend": self.config.CRYPTO_BACKEND,
        }


