"""App container + config (ref src/main — SURVEY.md §2.10)."""
from .application import Application  # noqa: F401
from .config import Config, test_config  # noqa: F401
