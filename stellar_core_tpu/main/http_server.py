"""Admin HTTP API: the operator/Horizon-facing command endpoints
(ref src/main/CommandHandler.cpp:89-129 route table; lib/http's tiny
embedded server).

Single-threaded like the reference: a non-blocking listener on the app's
TCPIOService, parsed with a minimal GET handler.  Routes: info, metrics,
peers, quorum (?intersection=true), scp, tx?blob=<base64-xdr>,
manualclose, ll?level=..., bans, trace[/summary], tx/latency, vitals.
"""
from __future__ import annotations

import base64
import json
import socket
import urllib.parse
from typing import Callable, Dict, Optional


def _jsonable(x):
    """Recursively hex raw bytes (node ids, consensus values) so
    protocol-state bodies survive json.dumps — SCP internals hold
    values as bytes, not display strings."""
    if isinstance(x, bytes):
        return x.hex()
    if isinstance(x, dict):
        return {(k.hex() if isinstance(k, bytes) else k): _jsonable(v)
                for k, v in x.items()}
    if isinstance(x, (list, tuple, set)):
        return [_jsonable(v) for v in x]
    return x


class RawBody:
    """A non-JSON response body (Prometheus text exposition, trace JSON
    downloads): handlers return one in place of a dict and _respond
    sends it verbatim with its content type."""

    def __init__(self, data: bytes, content_type: str):
        self.data = data
        self.content_type = content_type


class CommandHandler:
    """Route registry + implementations (ref CommandHandler::CommandHandler
    registering handlers :89-129)."""

    def __init__(self, app):
        self.app = app
        self.routes: Dict[str, Callable] = {
            "info": self.info,
            "metrics": self.metrics,
            "peers": self.peers,
            "quorum": self.quorum,
            "quorum-health": self.quorum_health,
            "scp": self.scp,
            "tx": self.tx,
            "manualclose": self.manualclose,
            "ll": self.log_level,
            "surveytopology": self.survey_topology,
            "getsurveyresult": self.get_survey_result,
            "bans": self.bans,
            "unban": self.unban,
            "generateload": self.generateload,
            "trace": self.trace,
            "trace/summary": self.trace_summary,
            "tx/latency": self.tx_latency,
            "vitals": self.vitals,
            "catchup-status": self.catchup_status,
            "flood": self.flood,
            "network-observatory": self.network_observatory,
        }

    def handle(self, path: str, params: Dict[str, str]) -> tuple:
        """-> (status, json-serializable body)."""
        fn = self.routes.get(path.strip("/"))
        if fn is None:
            return 404, {"error": f"unknown command {path!r}"}
        try:
            return fn(params)
        except Exception as e:  # operator endpoint: report, don't crash
            return 500, {"error": f"{type(e).__name__}: {e}"}

    # -- endpoints ----------------------------------------------------------

    def info(self, params):
        return 200, {"info": self.app.get_json_info()}

    def catchup_status(self, params):
        return 200, self.app.catchup_manager.status()

    def metrics(self, params):
        # derived metrics registered IN the registry so the Prometheus
        # exposition carries them too (they were JSON-side-table-only
        # before): the root prefetch hit rate, the PR-9 footprint-
        # prefetch hit rate, and the batched-kernel counter, which is
        # pinned present from boot instead of appearing only after the
        # first batched crossing
        m = self.app.metrics
        root = self.app.ledger_manager.root
        pstats = self.app.ledger_manager.pipeline.stats
        m.gauge("ledger.prefetch.hit-rate").set(root.prefetch_hit_rate())
        m.gauge("ledger.close.prefetch.hit-rate").set(
            pstats["prefetch_adopted"] / pstats["prefetch_keys"]
            if pstats["prefetch_keys"] else 0.0)
        m.counter("apply.native.batched_clusters")
        # aggregate hit/decline counters pinned present from boot; the
        # per-op-type breakout (apply.native.hit.<op> and
        # apply.native.decline.<op>.<reason>) registers on first event
        m.counter("apply.native.hit")
        m.counter("apply.native.decline")
        # fee-phase kernel accounting (r16): pinned from boot so the
        # scrape never misses them; the decline-reason breakout
        # (apply.native.fee.decline.<code>) registers on first decline
        m.counter("apply.native.fee.hit")
        m.counter("apply.native.fee.decline")
        # catchup progress counters pinned from boot (a node that never
        # fell behind should still scrape zeros, not absences)
        m.counter("catchup.chain.verified")
        m.counter("catchup.bucket.downloaded-bytes")
        m.counter("catchup.bucket.applied-bytes")
        m.gauge("catchup.buffered-ledgers")
        m.counter("apply.native.tail_encode.hit")
        # bounded per-peer overlay vitals mirrored into the registry
        # (Prometheus rides the registry; the JSON body also carries
        # the full structured form below)
        om = self.app.overlay_manager
        if om is not None:
            om.export_peer_gauges()
        # ?format=prometheus: text exposition of the registry (plus the
        # flight recorder's span-derived timers, which live in the
        # registry as span.* Timers).  The default JSON body below is
        # untouched — existing consumers see identical bytes.
        if params.get("format") == "prometheus":
            from ..utils.metrics import render_prometheus

            return 200, RawBody(
                render_prometheus(self.app.metrics).encode(),
                "text/plain; version=0.0.4; charset=utf-8")
        snap = self.app.metrics.snapshot()
        snap["ledger.prefetch.hit-rate"] = round(
            root.prefetch_hit_rate(), 4)
        # per-peer overlay vitals (bounded: first N peers + an "other"
        # roll-up; overlay/manager.py peer_vitals)
        if om is not None:
            snap["overlay.peer.vitals"] = om.peer_vitals()
        # the close pipeline's session counters (tails, barrier wait,
        # prefetch staging) at a glance, like bucket.merge.pipeline
        snap["ledger.close.pipeline"] = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in pstats.items()}
        # the async merge pipeline's health at a glance: per-phase ms of
        # the last close + cumulative staging counters (sync_fallback
        # _merges must read 0 in steady state)
        snap["ledger.close.phases"] = \
            self.app.ledger_manager.last_close_phases
        bl = self.app.bucket_manager.bucket_list
        snap["bucket.merge.pipeline"] = {
            k: (round(v, 4) if isinstance(v, float) else v)
            for k, v in bl.stats.items()}
        # BucketListDB read path at a glance: which tier answered point
        # reads, probes per read (the bloom filters' whole point), FP
        # rate, and the indexes' resident cost
        reads = bl.stats["point_reads"]
        probes = bl.stats["bucket_probes"]
        checks = bl.stats["bloom_checks"]
        snap["bucket.read.path"] = {
            "enabled": root.bucket_reads_enabled,
            "served_by": {"bucket": root.reads_from_buckets,
                          "overlay": root.reads_from_overlay,
                          "sql": root.reads_from_sql},
            "point_reads": reads,
            "probes_per_read": round(probes / reads, 4) if reads else 0.0,
            "bloom_false_positive_rate": round(
                bl.stats["bloom_false_positives"] / checks, 6)
            if checks else 0.0,
            "index_memory_bytes": bl.index_memory_bytes(),
            "index_build_s": round(bl.stats["index_build_s"], 4),
        }
        return 200, {"metrics": snap}

    def peers(self, params):
        om = self.app.overlay_manager
        if om is None:
            return 200, {"authenticated_peers": []}
        return 200, {"authenticated_peers": [
            {"id": pid.hex(), **p.get_stats()}
            for pid, p in om.authenticated.items()]}

    def quorum(self, params):
        if params.get("intersection") == "true":
            res = self.app.herder.check_quorum_intersection()
            body = {"intersection": res.ok,  # null = scan budget hit
                    "scanned_subsets": res.scanned,
                    "scc_size": res.scc_size,
                    "tier": res.tier}
            if res.aborted:
                body["aborted"] = True
            if res.split:
                body["split"] = [[n.hex() for n in side]
                                 for side in res.split]
            return 200, body
        qset = self.app.herder.scp.local_node.qset
        qt = self.app.herder.quorum_tracker
        return 200, {"qset": {
            "threshold": qset.threshold,
            "validators": [v.value.hex() for v in qset.validators],
            "inner_sets": len(qset.innerSets)},
            "transitive": {
                "node_count": len(qt.quorum),
                "missing_qsets": [n.hex()[:8]
                                  for n in qt.nodes_missing_qsets()]}}

    def quorum_health(self, params):
        """quorum-health[?intersection=true][&evaluate=true] — the
        quorum-health monitor's report (herder/quorum_health.py):
        heard/available/criticality of the local qset per close, the
        last budget-capped intersection verdict, transitive-quorum
        bookkeeping.  ?intersection=true runs one capped scan now;
        ?evaluate=true forces a fresh evaluation of the current LCL."""
        qh = self.app.herder.quorum_health
        if params.get("evaluate") == "true":
            qh.evaluate(self.app.ledger_manager.last_closed_seq())
        if params.get("intersection") == "true":
            qh.check_intersection()
        return 200, {"quorum_health": qh.report()}

    def scp(self, params):
        """scp[?slot=N][&limit=K] — per-slot consensus state PLUS the
        forensic timeline (scp/timeline.py).  Without ?slot: the last
        two slots' protocol state and a timeline summary.  With
        ?slot=N: that slot's full state and every recorded timeline
        event (nomination rounds, ballot transitions, timers, inbound
        envelopes with verdicts) — render with
        tools/trace_view.py --slots."""
        scp = self.app.herder.scp
        tl = scp.timeline
        if "slot" in params:
            try:
                idx = int(params["slot"])
            except ValueError:
                return 400, {"error": "bad slot param"}
            slot = scp.get_slot(idx, create=False)
            return 200, {
                "slot": idx,
                "state": _jsonable(slot.get_entire_state())
                if slot is not None else None,
                "timeline": tl.export(idx)}
        out = {}
        try:
            limit = int(params.get("limit", "2"))
        except ValueError:
            return 400, {"error": "bad limit param"}
        if limit <= 0:
            # [-0:] would be the WHOLE list, the opposite of the bound
            return 400, {"error": "bad limit param"}
        for idx in sorted(scp.slots)[-limit:]:
            out[str(idx)] = _jsonable(scp.slots[idx].get_entire_state())
        return 200, {"slots": out,
                     "timeline": {"enabled": tl.enabled,
                                  "slots": tl.slots(),
                                  "dropped_slots": tl.dropped_slots}}

    def tx(self, params):
        """Submit a transaction: tx?blob=<base64 TransactionEnvelope XDR>
        (ref CommandHandler::tx :117)."""
        from ..herder.tx_queue import TransactionQueue
        from ..xdr import types as T

        blob = params.get("blob")
        if not blob:
            return 400, {"error": "missing blob"}
        try:
            env = T.TransactionEnvelope.decode(
                base64.b64decode(blob.encode()))
        except Exception:
            return 400, {"status": "ERROR", "error": "malformed envelope"}
        res = self.app.herder.recv_transaction(env)
        names = {TransactionQueue.ADD_STATUS_PENDING: "PENDING",
                 TransactionQueue.ADD_STATUS_DUPLICATE: "DUPLICATE",
                 TransactionQueue.ADD_STATUS_BANNED: "TRY_AGAIN_LATER",
                 TransactionQueue.ADD_STATUS_TRY_AGAIN_LATER:
                 "TRY_AGAIN_LATER",
                 TransactionQueue.ADD_STATUS_ERROR: "ERROR"}
        return 200, {"status": names.get(res, "ERROR")}

    def manualclose(self, params):
        if not self.app.config.MANUAL_CLOSE:
            return 400, {"error": "manual close not enabled"}
        seq = self.app.herder.manual_close()
        return 200, {"ledger": seq}

    def generateload(self, params):
        """generateload?mode=create|pay|pretend|mixed|credit|pathpay|pool
        &accounts=N&txs=N [&dexpct=N&opcount=N&trustpct=N&hops=N] —
        drives the LoadGenerator through the real tx queue (ref
        CommandHandler.cpp:125; the reference registers this only in
        test builds, here it requires the standalone/testing
        accelerators to be on).  ``credit`` and ``pathpay`` seed
        themselves over real transactions in stages — call the mode
        repeatedly with a manualclose between calls until the note
        stops asking for another stage."""
        cfg = self.app.config
        if not (cfg.RUN_STANDALONE
                or cfg.ARTIFICIALLY_ACCELERATE_TIME_FOR_TESTING):
            return 400, {"error": "generateload requires standalone/"
                                  "testing mode"}
        from ..simulation.load_generator import LoadGenerator

        lg = getattr(self.app, "_load_generator", None)
        if lg is None:
            lg = self.app._load_generator = LoadGenerator(self.app)
        mode = params.get("mode", "pay")
        n_accounts = int(params.get("accounts", "100"))
        n_txs = int(params.get("txs", "100"))

        # rate mode: generateload?mode=pay&rate=N&duration=S starts a
        # timer-driven tx/s run (ref LoadGenerator.h:28-36); mode=status
        # polls it, mode=stop cancels it
        if mode == "status":
            return 200, {"rate_run": lg.rate_status()}
        if mode == "stop":
            lg.stop_rate_run()
            return 200, {"rate_run": lg.rate_status()}
        if "rate" in params:
            if mode not in ("pay", "pretend", "mixed"):
                return 400, {"error": f"rate mode needs pay/pretend/"
                                      f"mixed, got {mode!r}"}
            if not lg.accounts:
                lg.restore_accounts()
            if not lg.accounts:
                return 400, {"error": "run mode=create (and close) first"}
            status = lg.start_rate_run(
                mode, rate=float(params["rate"]),
                duration=float(params.get("duration", "10")),
                dex_percent=int(params.get("dexpct", "50")),
                op_count=int(params.get("opcount", "1")))
            return 200, {"rate_run": status}

        def submit(envs, note=None, on_all_pending=None):
            statuses: dict = {}
            for env in envs:
                r = self.app.herder.recv_transaction(env)
                statuses[r] = statuses.get(r, 0) + 1
            if statuses == {0: len(envs)} and on_all_pending:
                on_all_pending()
            body = {"mode": mode, "submitted": len(envs),
                    "status_counts": statuses}
            if note:
                body["note"] = note
            return 200, body

        # all seeding is TRANSACTION-based so the bucket-list commitment
        # stays consistent with the SQL tier (self-check-clean); each
        # seeding stage needs a ledger close before the next call
        if mode == "create":
            return submit(lg.create_account_envelopes(n_accounts),
                          "accounts exist after the next close")
        if not lg.accounts:
            # restarted node: the pool is a deterministic function of the
            # account ordinal (ref LoadGenerator::findAccount), so probe
            # the ledger for previously-created accounts before giving up
            lg.restore_accounts()
        if not lg.accounts:
            return 400, {"error": "run mode=create (and close) first"}
        if mode == "pay":
            envs = lg.generate_payments(n_txs)
        elif mode == "pretend":
            envs = lg.generate_pretend(
                n_txs, op_count=int(params.get("opcount", "1")))
        elif mode == "mixed":
            # stages advance ONLY when every stage tx was admitted, so a
            # mis-sequenced call (e.g. before the seeding close) can be
            # retried instead of wedging the DEX setup
            stage = getattr(lg, "_dex_stage", 0)
            if stage == 0:
                return submit(lg.create_dex_issuer_envelope(),
                              "dex issuer submitted; close a ledger "
                              "and call mode=mixed again",
                              lambda: setattr(lg, "_dex_stage", 1))
            if stage == 1:
                return submit(lg.setup_dex_envelopes(),
                              "dex trustlines submitted; close a "
                              "ledger and call mode=mixed again",
                              lambda: setattr(lg, "_dex_stage", 2))
            if stage == 2:
                return submit(lg.fund_dex_envelopes(),
                              "dex funding submitted; close a ledger "
                              "and call mode=mixed again",
                              lambda: setattr(lg, "_dex_stage", 3))
            envs = lg.generate_mixed(
                n_txs, dex_percent=int(params.get("dexpct", "50")))
        elif mode == "credit":
            # credit-heavy mix (ISSUE 13): LOAD payments over
            # trustlines + changeTrust salt on CRD2.  Staged like
            # mode=mixed: issuers -> trustlines -> funding, one close
            # between calls
            stage = getattr(lg, "_credit_stage", 0)
            if stage == 0:
                return submit(lg.create_credit_issuer_envelopes(),
                              "credit issuers submitted; close a "
                              "ledger and call mode=credit again",
                              lambda: setattr(lg, "_credit_stage", 1))
            if stage == 1:
                return submit(lg.setup_dex_envelopes(),
                              "trustlines submitted; close a ledger "
                              "and call mode=credit again",
                              lambda: setattr(lg, "_credit_stage", 2))
            if stage == 2:
                return submit(lg.fund_dex_envelopes(),
                              "funding submitted; close a ledger and "
                              "call mode=credit again",
                              lambda: setattr(lg, "_credit_stage", 3))
            envs = lg.generate_credit_mix(
                n_txs, trust_pct=int(params.get("trustpct", "10")))
        elif mode == "pathpay":
            # multi-hop path payments over seeded books (ISSUE 13):
            # four tx-based seeding stages (issuers+makers, trustlines,
            # funding, maker offers), then the workload
            hops = int(params.get("hops", "2"))
            stage = getattr(lg, "_path_stage", 0)
            if stage < 4:
                return submit(
                    lg.path_stage_envelopes(stage, hops=hops),
                    f"path seeding stage {stage} submitted; close a "
                    f"ledger and call mode=pathpay again",
                    lambda: setattr(lg, "_path_stage", stage + 1))
            envs = lg.generate_path_payments(n_txs)
        elif mode == "pool":
            # path payments routed through LIVE constant-product pools
            # (ISSUE 16): pools bulk-seed on first call (perf-rig
            # style, no staged closes needed), then the workload is the
            # same alternating strict-send/receive mix as mode=pathpay
            # with the pools as the only crossing venue
            if getattr(lg, "pool_ids", None) is None:
                lg.setup_pool(hops=int(params.get("hops", "2")))
            envs = lg.generate_pool_payments(n_txs)
        else:
            return 400, {"error": f"unknown mode {mode!r}"}
        return submit(envs)

    def survey_topology(self, params):
        """surveytopology?node=<hex-or-strkey> (ref CommandHandler
        surveytopology)."""
        om = self.app.overlay_manager
        if om is None:
            return 400, {"error": "no overlay"}
        node = params.get("node", "")
        try:
            if node.startswith("G"):
                from ..crypto.strkey import decode_ed25519_public_key

                nid = decode_ed25519_public_key(node)
            else:
                nid = bytes.fromhex(node)
        except Exception:
            return 400, {"error": "bad node id"}
        ok = om.survey_manager.start_survey(nid)
        return 200, {"submitted": ok}

    def get_survey_result(self, params):
        om = self.app.overlay_manager
        if om is None:
            return 400, {"error": "no overlay"}
        return 200, {"results": {
            k.hex()[:8]: v
            for k, v in om.survey_manager.results.items()}}

    def bans(self, params):
        om = self.app.overlay_manager
        if om is None:
            return 200, {"bans": []}
        return 200, {"bans": [b.hex() for b in sorted(om.banned_peers)]}

    def unban(self, params):
        om = self.app.overlay_manager
        node = params.get("node", "")
        if om is None or not node:
            return 400, {"error": "no overlay / missing node"}
        try:
            om.unban_peer(bytes.fromhex(node))
        except ValueError:
            return 400, {"error": "bad node id"}
        return 200, {"unbanned": node}

    def log_level(self, params):
        """ll?level=debug[&partition=SCP] — runtime per-partition log
        control (ref CommandHandler.cpp:113).  Unknown partitions/levels
        are a 400, not a silent fallback to the Default partition."""
        from ..utils import logging as L

        level = params.get("level")
        partition = params.get("partition")
        if partition is not None and partition not in L.PARTITIONS:
            return 400, {"error": f"unknown log partition {partition!r}",
                         "partitions": list(L.PARTITIONS)}
        if level:
            try:
                L.set_log_level(level, partition)
            except ValueError as e:
                return 400, {"error": str(e)}
        return 200, {"levels": L.get_log_levels()}

    # -- flight recorder (utils/tracing) ------------------------------------

    def trace(self, params):
        """trace?ledger=N — Chrome trace_event JSON of one retained
        close (the latest when ledger is omitted); load it in
        chrome://tracing / Perfetto or tools/trace_view.py."""
        from ..utils.tracing import chrome_trace

        tracer = self.app.tracer
        if not tracer.enabled:
            return 400, {"error": "tracing disabled (TRACING_ENABLED)"}
        seq = None
        if "ledger" in params:
            try:
                seq = int(params["ledger"])
            except ValueError:
                return 400, {"error": "bad ledger param"}
        rec = tracer.get_close(seq)
        if rec is None:
            retained = [r.seq for r in tracer.closes()]
            return 404, {"error": f"no trace for ledger {seq}",
                         "retained_closes": retained}
        return 200, RawBody(
            json.dumps(chrome_trace(rec), indent=1).encode(),
            "application/json")

    def tx_latency(self, params):
        """tx/latency?last=N — the transaction-lifecycle tracker's
        report: per-stage + end-to-end latency summaries (ms) over the
        sampled txs, tracker stats, and the last N completed
        lifecycles (utils/txtrace.py)."""
        last = int(params.get("last", "16"))
        return 200, {"tx_latency": self.app.txtracer.report(last=last)}

    def vitals(self, params):
        """vitals — the node-vitals sampler's report: latest gauge
        sample, per-gauge slopes over the ring, SLO watchdog state and
        the GC pause histogram (utils/vitals.py).  ?sample=true takes
        one sample on demand (works even when the periodic timer is
        disabled — suites, sims)."""
        if params.get("sample") == "true":
            self.app.vitals.sample_once()
        return 200, {"vitals": self.app.vitals.report()}

    def flood(self, params):
        """flood?hash=<hex> — this node's hop record for one flood item
        (origin/relayed, first-seen link, duplicate arrivals, forward
        fan-out).  Without ?hash: tracker stats + registry rollups +
        per-link dedup ratios + the most recent hop records
        (?last=N, default 16)."""
        ft = self.app.floodtracer
        if "hash" in params:
            try:
                h = bytes.fromhex(params["hash"])
            except ValueError:
                return 400, {"error": "bad hash param (want hex)"}
            rec = ft.lookup(h)
            if rec is None:
                return 404, {"error": f"no hop record for {params['hash']}"
                             " (untracked, sampled out, or evicted)",
                             "stats": ft.stats()}
            return 200, {"flood": rec}
        return 200, {"flood": ft.report(last=int(params.get("last", "16")))}

    def network_observatory(self, params):
        """network-observatory — fleet-merged propagation/close-cadence
        view.  Only live on sim rigs, where the Simulation attached a
        NetworkObservatory to every node; real nodes aggregate via
        tools/fleet_scrape.py instead."""
        obs = getattr(self.app, "_observatory", None)
        if obs is None:
            return 400, {"error": "no observatory attached "
                         "(sim rigs only; real fleets: tools/fleet_scrape.py)"}
        return 200, {"observatory": obs.snapshot()}

    def trace_summary(self, params):
        """trace/summary?k=N — top-k self-time spans aggregated over the
        whole retained close ring."""
        from ..utils.tracing import summarize_ring

        tracer = self.app.tracer
        recs = tracer.closes()
        k = int(params.get("k", "10"))
        return 200, {
            "closes_retained": [r.seq for r in recs],
            "slow_close_traces": [
                {"ledger": seq, "path": path}
                for seq, path in tracer.slow_close_traces],
            "top_spans_by_self_time": summarize_ring(recs, k=k),
        }


class AdminHttpServer:
    """Non-blocking single-request-per-connection HTTP/1.0 server on the
    app's TCPIOService."""

    def __init__(self, app, port: int = 0):
        self.app = app
        self.handler = CommandHandler(app)
        self.sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self.sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self.sock.bind(("127.0.0.1", port))
        self.port = self.sock.getsockname()[1]
        self.sock.listen(16)
        self.sock.setblocking(False)
        app.tcp_io.register(self.sock, self._on_acceptable)

    def _on_acceptable(self) -> None:
        while True:
            try:
                conn, _ = self.sock.accept()
            except (BlockingIOError, InterruptedError, OSError):
                return
            conn.setblocking(False)
            buf = bytearray()

            def on_readable(conn=conn, buf=buf):
                try:
                    chunk = conn.recv(65536)
                except (BlockingIOError, InterruptedError):
                    return
                except OSError:
                    self.app.tcp_io.unregister(conn)
                    conn.close()
                    return
                if chunk:
                    buf.extend(chunk)
                if b"\r\n\r\n" in buf or not chunk:
                    self._respond(conn, bytes(buf))

            self.app.tcp_io.register(conn, on_readable)

    def _respond(self, conn, request: bytes) -> None:
        self.app.tcp_io.unregister(conn)
        try:
            line = request.split(b"\r\n", 1)[0].decode("latin-1")
            _method, target, *_ = line.split(" ")
            parsed = urllib.parse.urlparse(target)
            params = {k: v[0] for k, v in
                      urllib.parse.parse_qs(parsed.query).items()}
            status, body = self.handler.handle(parsed.path, params)
        except Exception as e:
            status, body = 400, {"error": str(e)}
        if isinstance(body, RawBody):
            payload = body.data
            content_type = body.content_type
        else:
            payload = json.dumps(body, indent=1).encode()
            content_type = "application/json"
        reasons = {200: "OK", 400: "Bad Request", 404: "Not Found",
                   500: "Internal Server Error"}
        head = (f"HTTP/1.0 {status} {reasons.get(status, 'OK')}\r\n"
                f"Content-Type: {content_type}\r\n"
                f"Content-Length: {len(payload)}\r\n\r\n").encode()
        try:
            conn.sendall(head + payload)
        except OSError:
            pass
        conn.close()

    def close(self) -> None:
        self.app.tcp_io.unregister(self.sock)
        self.sock.close()
