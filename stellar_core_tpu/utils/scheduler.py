"""Fair multi-queue action scheduler with latency-based load shedding
(ref src/util/Scheduler.h:24-140).

Actions are enqueued into named queues; dispatch round-robins by accumulated
runtime (the queue that has consumed the least runs next).  Queues whose
oldest action exceeds the latency window shed DROPPABLE actions.
"""
from __future__ import annotations

import time
from collections import deque
from enum import Enum
from typing import Callable, Deque, Dict, Optional, Tuple


class ActionType(Enum):
    NORMAL = 0
    DROPPABLE = 1


class _Queue:
    __slots__ = ("name", "actions", "total_service_time")

    def __init__(self, name: str):
        self.name = name
        self.actions: Deque[Tuple[float, ActionType, Callable]] = deque()
        self.total_service_time = 0.0


class Scheduler:
    def __init__(self, clock, latency_window: float = 5.0):
        self.clock = clock
        self.latency_window = latency_window
        self.queues: Dict[str, _Queue] = {}
        self.stats_dropped = 0
        self.stats_ran = 0

    def enqueue(self, queue_name: str, action: Callable[[], None],
                action_type: ActionType = ActionType.NORMAL) -> None:
        q = self.queues.get(queue_name)
        if q is None:
            q = self.queues[queue_name] = _Queue(queue_name)
        q.actions.append((self.clock.now(), action_type, action))

    def _shed(self, q: _Queue) -> None:
        now = self.clock.now()
        kept: Deque = deque()
        while q.actions:
            ts, typ, act = q.actions.popleft()
            if (typ == ActionType.DROPPABLE
                    and now - ts > self.latency_window):
                self.stats_dropped += 1
            else:
                kept.append((ts, typ, act))
        q.actions = kept

    def run_one(self) -> bool:
        """Run the next action from the least-served non-empty queue."""
        best: Optional[_Queue] = None
        for q in self.queues.values():
            self._shed(q)
            if q.actions and (best is None
                              or q.total_service_time
                              < best.total_service_time):
                best = q
        if best is None:
            return False
        _, _, action = best.actions.popleft()
        t0 = time.perf_counter()
        try:
            action()
        finally:
            best.total_service_time += time.perf_counter() - t0
            self.stats_ran += 1
        return True

    def size(self) -> int:
        return sum(len(q.actions) for q in self.queues.values())
