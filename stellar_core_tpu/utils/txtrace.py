"""Transaction-lifecycle telemetry: follow ONE transaction across
subsystems (the axis the PR-4 flight recorder cannot see — spans are
per-close and per-subsystem, a tx's journey crosses both).

Every sampled transaction gets monotonic stage stamps as it moves
through the node:

    recv         overlay socket receive (timestamp token captured by the
                 overlay, before admission work starts)
    admit        TransactionQueue.try_add -> PENDING (the sampling gate)
    txset        included in a nominated TxSetFrame
    nominate     the herder handed that proposal to SCP
    externalize  consensus externalized a value carrying the tx
    apply        the close's apply phase finished the tx
    commit       the tx's ledger became DURABLE (SQL committed).  Under
                 the pipelined close this happens on the tail worker
                 DURING ledger N+1 — the stamp carries the originating
                 ledger seq (the PR-9 cross-close token discipline, same
                 reason deferred spans carry ``close_seq``)

Design constraints, in order:

- **Zero consensus surface.**  Stamps are observational; nothing here
  feeds a hash, a tally or an apply decision.  The wallclock reads live
  in THIS module (utils/ is outside detlint's consensus scan and the
  module is sanctioned like utils/tracing.py), so consensus modules
  stamp through ``app.txtracer`` without det-wallclock findings.
- **Bounded memory, deterministic sampling.**  The live map admits
  every ``stride``-th first-seen transaction; when it fills, every
  other tracked tx (insertion order) is dropped and the stride doubles
  — the PR-4 Histogram reservoir discipline applied to in-flight
  tracking.  Which txs get tracked is a pure function of the admission
  sequence, never of hash order or a PRNG.
- **Near-zero disabled cost.**  A disabled tracker's stamp is one
  attribute check; an enabled tracker's stamp for an untracked tx is
  one dict probe.  The soak bench measures the enabled cost A/B
  (SOAK_BENCH ``disabled_cost``: must stay <1% of close p50).

Rollups land in the owning registry as ``txtrace.stage.<a>_to_<b>``
and ``txtrace.e2e.*`` histograms (seconds), so `/metrics` carries them
in both JSON and Prometheus form; the HTTP ``tx/latency`` endpoint
serves the full report (per-stage summaries in ms + the completed-tx
ring).
"""
from __future__ import annotations

import threading
from collections import deque
from time import perf_counter
from typing import Dict, Iterable, List, Optional

from .lockdep import guard_fields, register_lock

#: lifecycle stages in pipeline order ("fee" = the close's fee/seqnum
#: charge phase — stamped per tx whether the batched fee kernel or the
#: per-tx reference loop charged it, so batching keeps attribution)
STAGES = ("recv", "admit", "txset", "nominate", "externalize", "fee",
          "apply", "commit")
_STAGE_INDEX = {s: i for i, s in enumerate(STAGES)}
#: precomputed histogram names for every ordered stage pair — string
#: building per completed tx was the dominant rollup cost
_PAIR_NAME = {(a, b): f"txtrace.stage.{a}_to_{b}"
              for i, a in enumerate(STAGES)
              for b in STAGES[i + 1:]}

#: in-flight tracked txs before decimation halves the map (each entry
#: is one small dict of <= 7 floats)
DEFAULT_MAX_LIVE = 512
#: completed lifecycle records retained for the tx/latency endpoint
DEFAULT_RING = 256


class TxLifecycleTracker:
    """One per Application; all stamping funnels through here."""

    def __init__(self, metrics=None, enabled: bool = True,
                 max_live: int = DEFAULT_MAX_LIVE,
                 ring: int = DEFAULT_RING):
        if metrics is None:
            from .metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.enabled = enabled
        self.metrics = metrics
        self.max_live = max(2, int(max_live))
        self._lock = register_lock(threading.Lock(), "txtrace")
        # tx hash -> {stage: perf_counter seconds}
        self._live: Dict[bytes, dict] = {}  # guarded-by: _lock
        # completed lifecycle records
        self._ring: deque = deque(maxlen=max(1, int(ring)))  # guarded-by: _lock
        self._stride = 1          # guarded-by: _lock
        self._seen = 0            # guarded-by: _lock
        self._tracked = 0         # guarded-by: _lock
        self._completed = 0       # guarded-by: _lock
        self._decimations = 0     # guarded-by: _lock
        # Histogram objects resolved once per name: the registry's
        # name->metric lookup per completed tx would dominate _finish
        self._hists: Dict[str, object] = {}  # guarded-by: _lock
        guard_fields(self)

    # -- stamping ----------------------------------------------------------

    def note_recv(self) -> Optional[float]:
        """Overlay-receive timestamp token: captured by the overlay
        BEFORE admission work, handed into ``try_add(recv_ts=...)`` so
        the recv->admit delta covers decode + validity + signature
        cost.  None when disabled (callers pass it through blindly)."""
        if not self.enabled:
            return None
        return perf_counter()

    def on_admit(self, tx_hash: bytes,
                 recv_ts: Optional[float] = None) -> None:
        """The sampling gate, at queue admission (PENDING verdicts
        only).  Accepts every ``stride``-th candidate; a full live map
        decimates deterministically (keep every other entry in
        insertion order, double the stride)."""
        if not self.enabled:
            return
        t = perf_counter()
        with self._lock:
            self._seen += 1
            if (self._seen - 1) % self._stride:
                return
            if tx_hash in self._live:
                return
            rec = {"admit": t}
            if recv_ts is not None:
                rec["recv"] = recv_ts
            self._live[tx_hash] = rec
            self._tracked += 1
            if len(self._live) >= self.max_live:
                # keep the ODD insertion indices: a phase-shifted
                # systematic sample of the doubled stride that retains
                # the just-admitted tx (even indices would drop the
                # newcomer the moment it was counted as tracked)
                self._live = dict(list(self._live.items())[1::2])
                self._stride *= 2
                self._decimations += 1

    def stamp_frames(self, frames: Iterable, stage: str,
                     seq: Optional[int] = None) -> None:
        """Stamp ``stage`` for every TRACKED frame in ``frames`` (one
        shared timestamp — the stages are close-level events).  The
        ``commit`` stage finalizes the record: per-stage deltas roll
        into the registry histograms and the record (tagged with the
        ORIGINATING ledger ``seq``, even when the pipelined tail runs
        this during ledger N+1) moves to the completed ring."""
        if not self.enabled:
            return
        idx = _STAGE_INDEX[stage]  # KeyError = caller bug, stay loud
        with self._lock:
            if not self._live:
                return
            t = perf_counter()
            final = idx == len(STAGES) - 1
            for frame in frames:
                h = frame.full_hash()
                rec = self._live.get(h)
                if rec is None or stage in rec:
                    continue
                rec[stage] = t
                if final:
                    del self._live[h]
                    self._finish(rec, seq)

    def _hist(self, name: str):
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = self.metrics.histogram(name)
        return h

    def _finish(self, rec: dict, seq: Optional[int]) -> None:
        """guarded-by: _lock — fold one completed lifecycle into the
        per-stage + end-to-end histograms and the completed ring."""
        order = [s for s in STAGES if s in rec]
        prev = None
        for s in order:
            if prev is not None:
                self._hist(_PAIR_NAME[prev, s]).update(
                    rec[s] - rec[prev])
            prev = s
        self._hist("txtrace.e2e.admit_to_commit").update(
            rec["commit"] - rec["admit"])
        if "recv" in rec:
            self._hist("txtrace.e2e.recv_to_commit").update(
                rec["commit"] - rec["recv"])
        self._completed += 1
        # raw stamps only — formatting happens at report time, not on
        # the close/tail thread
        self._ring.append((seq, rec))

    # -- reporting ---------------------------------------------------------

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "stride": self._stride,
                "seen": self._seen,
                "tracked": self._tracked,
                "live": len(self._live),
                "completed": self._completed,
                "decimations": self._decimations,
            }

    def report(self, last: int = 16) -> dict:
        """The tx/latency endpoint body: tracker stats, per-stage and
        end-to-end latency summaries (ms), and the most recent
        completed lifecycles."""
        out = self.stats()
        stages: Dict[str, dict] = {}
        for name in sorted(self.metrics._metrics):
            if not name.startswith("txtrace."):
                continue
            h = self.metrics._metrics[name]
            s = h.summary()
            stages[name] = {
                "count": s["count"],
                "p50_ms": round(s["p50"] * 1000.0, 3),
                "p99_ms": round(s["p99"] * 1000.0, 3),
                "mean_ms": round(s["mean"] * 1000.0, 3),
                "max_ms": round(s["max"] * 1000.0, 3),
            }
        with self._lock:
            raw = list(self._ring)[-last:]
        recent: List[dict] = []
        for seq, rec in raw:
            order = [s for s in STAGES if s in rec]
            first = rec[order[0]]
            recent.append({
                "ledger": seq,
                "stages_ms": {s: round((rec[s] - first) * 1000.0, 3)
                              for s in order},
            })
        out["latency"] = stages
        out["recent"] = recent
        return out
