"""Flight recorder: hierarchical span tracing for the ledger-close path
(ref the reference node's Tracy zones + LogSlowExecution + libmedida
timers; here one subsystem feeds all three surfaces).

Design
------
- ``tracer.span("ledger.apply.dex")`` is a nestable context manager.
  Nesting is tracked per thread; cross-thread parenting (the bucket
  merge worker pool) passes an explicit ``parent=`` token captured on
  the submitting thread via ``tracer.current_id()``.
- Spans ALWAYS measure (two perf_counter reads — the measurement also
  feeds the per-phase close breakdown, which must work regardless of
  recording); they are only RECORDED into the pending ring when the
  tracer is enabled.  A disabled tracer's span costs ~1µs: no
  allocation beyond one small object, no locks.
- Finished spans land in a bounded pending deque; at every ledger close
  ``commit_close(seq)`` drains it into a CloseRecord, so background
  spans (overlay receive, SCP rounds, bucket merges finishing late)
  attach to the next close.  The ring keeps the last N closes WHOLE.
- The slow-close watchdog fires inside commit_close: a close whose root
  span exceeds the threshold is persisted as Chrome ``trace_event``
  JSON (load in chrome://tracing / Perfetto, or tools/trace_view.py)
  and logged as a one-line summary on the Perf partition.
- ``stopwatch()`` is the sanctioned raw-duration helper for consensus
  modules: the perf_counter reads live HERE (utils/ is outside
  detlint's consensus scan), so instrumentation never needs
  det-wallclock baseline entries.

Per-op-type apply attribution: the close's apply loop installs an op
cost collector (``collect_op_costs``); ``transactions/frame.py`` feeds
it per-operation durations via ``op_collector()`` — a single
thread-local read when inactive.
"""
from __future__ import annotations

import json
import threading
from collections import deque
from time import perf_counter
from typing import Dict, List, Optional, Tuple

from .lockdep import register_lock

# pending spans kept between closes; eviction-bounded so a node that
# never closes (or a test hammering spans from many threads) cannot
# grow memory without bound
MAX_PENDING_SPANS = 32768
# spans kept per committed close record (1000-tx closes emit ~1k
# admission spans + phases + aggregates)
MAX_SPANS_PER_CLOSE = 16384
DEFAULT_RING_CLOSES = 8


class Span:
    """One finished (or in-flight) span.  ``seconds`` is valid after
    __exit__ even when the tracer is disabled.

    ``close_seq``: cross-CLOSE parenting for the pipelined close tail —
    a span that runs during ledger N+1 but belongs to ledger N's close
    (deferred commit/meta/gc) carries N here and is routed into N's
    already-committed ring record instead of the pending deque."""

    __slots__ = ("name", "span_id", "parent_id", "tid", "thread_name",
                 "t0", "t1", "args", "close_seq", "_tracer")

    def __init__(self, tracer, name: str, parent_id: Optional[int],
                 args: Optional[dict], close_seq: Optional[int] = None):
        self._tracer = tracer
        self.name = name
        self.parent_id = parent_id
        self.span_id = 0
        self.tid = 0
        self.thread_name = ""
        self.t0 = 0.0
        self.t1 = 0.0
        self.args = args
        self.close_seq = close_seq

    @property
    def seconds(self) -> float:
        return self.t1 - self.t0

    def __enter__(self) -> "Span":
        tr = self._tracer
        if tr.enabled:
            self.span_id = tr._next_id()
            th = threading.current_thread()
            self.tid = th.ident or 0
            self.thread_name = th.name
            stack = tr._stack()
            if self.parent_id is None and stack:
                self.parent_id = stack[-1]
            stack.append(self.span_id)
        self.t0 = perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.t1 = perf_counter()
        tr = self._tracer
        # pop on span_id alone: a tracer disabled BETWEEN enter and exit
        # (bench's A/B toggle, with worker-pool spans still in flight)
        # must not leak this id onto the thread's stack forever
        if self.span_id:
            stack = tr._stack()
            if stack and stack[-1] == self.span_id:
                stack.pop()
            if tr.enabled:
                tr._record(self)
        return False

    def to_dict(self) -> dict:
        d = {"name": self.name, "id": self.span_id,
             "parent": self.parent_id, "tid": self.tid,
             "thread": self.thread_name,
             "t0": self.t0, "dur_ms": round(self.seconds * 1000.0, 6)}
        if self.args:
            d["args"] = dict(self.args)
        return d


class _Stopwatch:
    """Minimal always-on duration scope: the sanctioned timing primitive
    consensus modules use instead of raw perf_counter reads."""

    __slots__ = ("t0", "seconds")

    def __enter__(self) -> "_Stopwatch":
        self.seconds = 0.0
        self.t0 = perf_counter()
        return self

    def __exit__(self, *exc) -> bool:
        self.seconds = perf_counter() - self.t0
        return False


def stopwatch() -> _Stopwatch:
    return _Stopwatch()


# -- per-op-type apply cost collection --------------------------------------

_op_tls = threading.local()


class OpCostCollector:
    """Accumulates (total seconds, count) per operation-type name."""

    def __init__(self):
        self.costs: Dict[str, List[float]] = {}

    def add(self, type_name: str, seconds: float) -> None:
        slot = self.costs.get(type_name)
        if slot is None:
            self.costs[type_name] = [seconds, 1]
        else:
            slot[0] += seconds
            slot[1] += 1

    def add_many(self, type_name: str, seconds: float, count: int) -> None:
        """Fold a pre-aggregated (seconds, count) bucket in — the
        parallel-apply executor merges per-cluster collectors into the
        close's collector this way."""
        slot = self.costs.get(type_name)
        if slot is None:
            self.costs[type_name] = [seconds, count]
        else:
            slot[0] += seconds
            slot[1] += count


def op_collector() -> Optional[OpCostCollector]:
    """The active collector for THIS thread (None almost always — the
    single getattr is the whole disabled-path cost in the op loop)."""
    return getattr(_op_tls, "collector", None)


class _CollectScope:
    def __init__(self, collector: OpCostCollector):
        self.collector = collector

    def __enter__(self) -> OpCostCollector:
        _op_tls.collector = self.collector
        return self.collector

    def __exit__(self, *exc) -> bool:
        _op_tls.collector = None
        return False


def collect_op_costs() -> _CollectScope:
    return _CollectScope(OpCostCollector())


# -- the tracer --------------------------------------------------------------

class CloseRecord:
    __slots__ = ("seq", "root_id", "duration_s", "spans", "truncated")

    def __init__(self, seq: int, root_id: int, duration_s: float,
                 spans: List[Span], truncated: int):
        self.seq = seq
        self.root_id = root_id
        self.duration_s = duration_s
        self.spans = spans
        self.truncated = truncated


class Tracer:
    def __init__(self, enabled: bool = True,
                 ring_closes: int = DEFAULT_RING_CLOSES,
                 slow_close_threshold: Optional[float] = None,
                 trace_dir: Optional[str] = None,
                 metrics=None,
                 max_pending: int = MAX_PENDING_SPANS):
        self.enabled = enabled
        self.slow_close_threshold = slow_close_threshold
        self.trace_dir = trace_dir
        self.metrics = metrics
        self._lock = register_lock(threading.Lock(), "tracer")
        self._pending: deque = deque(maxlen=max_pending)  # guarded-by: _lock
        self._ring: deque = deque(maxlen=max(1, ring_closes))
        self._id_counter = 0
        self._tls = threading.local()
        # persisted watchdog traces this process wrote: (seq, path)
        self.slow_close_traces: List[Tuple[int, str]] = []

    # -- span plumbing -------------------------------------------------------

    def _stack(self) -> List[int]:
        stack = getattr(self._tls, "stack", None)
        if stack is None:
            stack = self._tls.stack = []
        return stack

    def _next_id(self) -> int:
        with self._lock:
            self._id_counter += 1
            return self._id_counter

    def _record(self, sp: Span) -> None:
        if sp.close_seq is not None and self._route_late(sp):
            return
        with self._lock:
            self._pending.append(sp)

    def _route_late(self, sp: Span) -> bool:
        """Append a close-tagged span to its (already committed) close
        record in the ring — the pipelined tail's spans finish during
        the NEXT close but belong to ledger ``close_seq``.  False when
        that record does not exist yet (the span finished before
        commit_close ran): the pending drain then files it correctly."""
        with self._lock:
            for rec in reversed(self._ring):
                if rec.seq == sp.close_seq:
                    if len(rec.spans) >= MAX_SPANS_PER_CLOSE:
                        rec.truncated += 1
                    else:
                        rec.spans.append(sp)
                    break
            else:
                return False
        if self.metrics is not None:
            self.metrics.timer(f"span.{sp.name}").update(sp.seconds)
        return True

    def span(self, name: str, parent: Optional[int] = None,
             close_seq: Optional[int] = None, **args) -> Span:
        """Nestable span context manager.  ``parent`` overrides the
        thread-local nesting (cross-thread parenting); ``close_seq``
        routes the finished span into that ledger's close record even
        when it outlives the close (cross-close parenting — the
        pipelined tail)."""
        return Span(self, name, parent, args or None,
                    close_seq=close_seq)

    def current_id(self) -> Optional[int]:
        """Token for cross-thread parenting: the innermost open span on
        THIS thread (None when disabled or at top level)."""
        if not self.enabled:
            return None
        stack = getattr(self._tls, "stack", None)
        return stack[-1] if stack else None

    def aggregate_span(self, name: str, parent: Optional[int],
                       t0: float, seconds: float, **args) -> None:
        """Emit a synthetic (already-measured) span — the per-op-type
        apply aggregates."""
        if not self.enabled:
            return
        sp = Span(self, name, parent, args or None)
        sp.span_id = self._next_id()
        th = threading.current_thread()
        sp.tid = th.ident or 0
        sp.thread_name = th.name
        sp.t0 = t0
        sp.t1 = t0 + seconds
        self._record(sp)

    def pending_count(self) -> int:
        with self._lock:
            return len(self._pending)

    # -- close records -------------------------------------------------------

    def commit_close(self, seq: int, root: Span) -> Optional[CloseRecord]:
        """Drain pending spans into the ring as one close record; run the
        slow-close watchdog.  Called by LedgerManager after every close
        (the root span must already be closed)."""
        if not self.enabled:
            return None
        # drain + ring-append under one lock hold: a tail span finishing
        # concurrently either lands in the pending drain (filed here) or
        # sees the new record and routes itself (_route_late) — never
        # neither, never both
        with self._lock:
            spans = list(self._pending)
            self._pending.clear()
            truncated = 0
            if len(spans) > MAX_SPANS_PER_CLOSE:
                truncated = len(spans) - MAX_SPANS_PER_CLOSE
                spans = spans[-MAX_SPANS_PER_CLOSE:]
            rec = CloseRecord(seq, root.span_id, root.seconds, spans,
                              truncated)
            self._ring.append(rec)
            if self.metrics is not None:
                # inside the lock on purpose: a tail span routed into
                # this record by _route_late after the append updates
                # its own timer there — counting it here too would
                # double-sample it
                self._update_span_timers(rec)
        thr = self.slow_close_threshold
        if thr is not None and thr > 0 and root.seconds > thr:
            self._watchdog_fire(rec)
        return rec

    def _update_span_timers(self, rec: CloseRecord) -> None:
        """Span-derived timers in the metrics registry: per close, one
        Timer update per span name with that close's total seconds (the
        Prometheus exposition's ``span.*`` series)."""
        totals: Dict[str, float] = {}
        for sp in rec.spans:
            totals[sp.name] = totals.get(sp.name, 0.0) + sp.seconds
        for name in sorted(totals):
            self.metrics.timer(f"span.{name}").update(totals[name])

    def closes(self) -> List[CloseRecord]:
        with self._lock:
            return list(self._ring)

    def get_close(self, seq: Optional[int] = None) -> Optional[CloseRecord]:
        """The ring record for ledger ``seq`` (latest when None)."""
        recs = self.closes()
        if not recs:
            return None
        if seq is None:
            return recs[-1]
        for rec in reversed(recs):
            if rec.seq == seq:
                return rec
        return None

    # -- the slow-close watchdog ---------------------------------------------

    def _watchdog_fire(self, rec: CloseRecord) -> None:
        from .logging import get_logger

        path = None
        if self.trace_dir is not None:
            import os

            try:
                os.makedirs(self.trace_dir, exist_ok=True)
                path = os.path.join(self.trace_dir,
                                    f"slow-close-{rec.seq}.trace.json")
                tmp = f"{path}.{os.getpid()}.tmp"
                with open(tmp, "w", encoding="utf-8") as f:
                    json.dump(chrome_trace(rec), f)
                os.replace(tmp, path)
                self.slow_close_traces.append((rec.seq, path))
            except OSError:
                path = None
        top = top_spans(rec, k=3)
        summary = ", ".join(f"{name} {ms:.1f}ms" for name, ms, _ in top)
        get_logger("Perf").warning(
            "slow close: ledger %d took %.3fs (threshold %.3fs); "
            "top self-time: %s%s", rec.seq, rec.duration_s,
            self.slow_close_threshold, summary,
            f"; trace persisted to {path}" if path else "")


# -- analysis / export -------------------------------------------------------

def self_times(rec: CloseRecord) -> Dict[int, float]:
    """span_id -> self time (duration minus SAME-THREAD children's).
    Cross-thread children run concurrently with their parent (the
    bucket worker merges routinely outlive the staging bucket phase),
    so subtracting them would drive the parent's self time negative."""
    by_id = {sp.span_id: sp for sp in rec.spans}
    selfs = {sp.span_id: sp.seconds for sp in rec.spans}
    for sp in rec.spans:
        parent = by_id.get(sp.parent_id) if sp.parent_id else None
        if parent is not None and parent.tid == sp.tid:
            selfs[parent.span_id] -= sp.seconds
    return selfs


def top_spans(rec: CloseRecord, k: int = 10
              ) -> List[Tuple[str, float, int]]:
    """Top-k (name, self_ms, count) aggregated by span name."""
    selfs = self_times(rec)
    by_name: Dict[str, List[float]] = {}
    for sp in rec.spans:
        slot = by_name.setdefault(sp.name, [0.0, 0])
        slot[0] += selfs.get(sp.span_id, 0.0)
        slot[1] += 1
    ranked = sorted(by_name.items(),
                    key=lambda kv: (-kv[1][0], kv[0]))[:k]
    return [(name, v[0] * 1000.0, int(v[1])) for name, v in ranked]


def summarize_ring(records: List[CloseRecord], k: int = 10) -> List[dict]:
    """Top-k self-time spans aggregated across a list of close records
    (the /trace/summary endpoint body)."""
    by_name: Dict[str, List[float]] = {}
    for rec in records:
        selfs = self_times(rec)
        for sp in rec.spans:
            slot = by_name.setdefault(sp.name, [0.0, 0])
            slot[0] += selfs.get(sp.span_id, 0.0)
            slot[1] += 1
    ranked = sorted(by_name.items(),
                    key=lambda kv: (-kv[1][0], kv[0]))[:k]
    return [{"name": name, "self_ms": round(v[0] * 1000.0, 3),
             "count": int(v[1])} for name, v in ranked]


def chrome_trace(rec: CloseRecord) -> dict:
    """Chrome ``trace_event`` JSON (the "X" complete-event form), with
    span/parent ids in args so cross-thread parenting survives export.
    Timestamps are µs relative to the record's earliest span."""
    if rec.spans:
        base = min(sp.t0 for sp in rec.spans)
    else:
        base = 0.0
    events = []
    for sp in rec.spans:
        ev = {"name": sp.name, "ph": "X", "pid": 1, "tid": sp.tid,
              "ts": round((sp.t0 - base) * 1e6, 3),
              "dur": round(sp.seconds * 1e6, 3),
              "args": {"span_id": sp.span_id,
                       "parent_id": sp.parent_id,
                       "thread": sp.thread_name}}
        if sp.args:
            ev["args"].update({k: v for k, v in sp.args.items()
                               if isinstance(v, (int, float, str, bool))})
        events.append(ev)
    return {"traceEvents": events,
            "metadata": {"ledger": rec.seq,
                         "duration_ms": round(rec.duration_s * 1000.0, 3),
                         "root_span_id": rec.root_id,
                         "truncated_spans": rec.truncated}}


# -- access helpers ----------------------------------------------------------

#: shared no-op tracer for components constructed without an Application
NULL_TRACER = Tracer(enabled=False)


def tracer_of(obj) -> Tracer:
    """The tracer owned by ``obj``'s Application, else the null tracer —
    lets deep modules (SCP protocols via their driver) instrument
    without new constructor plumbing."""
    app = getattr(obj, "app", None)
    tr = getattr(app, "tracer", None)
    return tr if tr is not None else NULL_TRACER
