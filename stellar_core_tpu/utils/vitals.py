"""Continuous node vitals: the gauges only LONG runs make meaningful.

Every per-close surface so far (flight-recorder spans, close-phase
dicts, bench A/Bs) answers "how fast was that close"; none answers
"is this node drifting" — RSS creeping, fds leaking, the tx queue
aging toward mass bans, GC pauses stretching.  This sampler records a
fixed-size time series of node-health gauges on a periodic timer and
derives a least-squares slope per gauge, so a soak run can assert
"memory slope ≈ 0" instead of eyeballing two RSS numbers.

Per sample (one dict in a bounded ring):
  rss_bytes / open_fds / threads        process health (/proc-backed)
  tx_queue_depth / tx_queue_age_max     admission pressure + aging
  pipeline_tail_depth                   pipelined-close tail in flight
  bucket_entries / bucket_disk_bytes    state-store growth
  verify_cache_hit_rate                 crypto verify-cache efficacy
  prefetch_hit_rate                     root entry-cache prefetch efficacy
  gc_pending                            allocation-counter pressure

GC pauses are recorded via ``gc.callbacks`` (start/stop bracket around
every collection, including the deferred post-close collections) into
the ``vitals.gc.pause`` histogram + per-generation counters.

Surfaces: every numeric gauge mirrors into the metrics registry as a
``vitals.*`` Gauge (JSON `/metrics` + Prometheus exposition), the HTTP
``vitals`` endpoint serves the full report (latest sample, slopes,
SLO state), and ``VITALS_JSONL`` appends one JSON line per sample for
offline analysis of a whole soak.

SLO watchdog (config ``SLO_MAX_*``, each 0 = disabled): memory slope,
close-latency p99 and tx-queue age are checked per sample once the
ring has warmup depth; a breach increments ``slo.breach.<name>`` and
logs ONE structured WARN per breach episode (level transitions, not
per sample — a soak in breach must not drown the log).

Like utils/tracing.py, the wallclock reads live HERE: the module is
detlint-sanctioned (observation-only), consensus code never imports it.
"""
from __future__ import annotations

import os
import threading
from collections import deque
from time import perf_counter
from typing import Dict, List, Optional, Tuple

#: warmup before slope-based SLOs evaluate (a 2-point "slope" is noise)
SLO_WARMUP_SAMPLES = 8

#: sample keys whose drift a slope is computed for
SLOPE_GAUGES = ("rss_bytes", "open_fds", "threads", "tx_queue_depth",
                "bucket_entries", "bucket_disk_bytes")


def rss_bytes() -> int:
    """Current resident set size.  /proc/self/statm is the live value;
    the resource fallback (non-Linux) is the peak, which still bounds a
    leak check from above."""
    try:
        with open("/proc/self/statm") as f:
            return int(f.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError):
        import resource

        return resource.getrusage(resource.RUSAGE_SELF).ru_maxrss * 1024


def open_fds() -> int:
    try:
        return len(os.listdir("/proc/self/fd"))
    except OSError:
        return -1


def least_squares_slope(points: List[Tuple[float, float]]) -> float:
    """dv/dt of (t, v) samples by ordinary least squares; 0.0 below two
    points or with a degenerate time axis."""
    n = len(points)
    if n < 2:
        return 0.0
    mt = sum(t for t, _ in points) / n
    mv = sum(v for _, v in points) / n
    denom = sum((t - mt) ** 2 for t, _ in points)
    if denom <= 0.0:
        return 0.0
    num = sum((t - mt) * (v - mv) for t, v in points)
    return num / denom


class VitalsSampler:
    """One per Application.  ``start()`` arms the periodic timer and
    registers the GC callback; ``stop()`` reverses both (the callback
    MUST come off ``gc.callbacks`` — it is process-global and a dead
    node's callback would keep timing other nodes' collections)."""

    def __init__(self, app):
        cfg = app.config
        self.app = app
        self.enabled = bool(getattr(cfg, "VITALS_ENABLED", False))
        self.period = float(getattr(cfg, "VITALS_PERIOD_SECONDS", 1.0))
        self.ring: deque = deque(
            maxlen=int(getattr(cfg, "VITALS_RING_SAMPLES", 900)))
        self.jsonl_path = getattr(cfg, "VITALS_JSONL", None)
        self.samples_taken = 0
        self._timer = None
        self._gc_registered = False
        self._gc_tls = threading.local()  # per-thread collection t0
        # SLO name -> currently-in-breach (episode edge detection)
        self._slo_active: Dict[str, bool] = {}

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        if not self.enabled or self._timer is not None:
            return
        self._register_gc()
        from .clock import VirtualTimer

        self._timer = VirtualTimer(self.app.clock, owner=self.app)
        self._arm()

    def stop(self) -> None:
        if self._timer is not None:
            self._timer.cancel()
            self._timer = None
        self._unregister_gc()

    def _arm(self) -> None:
        self._timer.expires_from_now(self.period)
        self._timer.async_wait(self._tick)

    def _tick(self) -> None:
        self.sample_once()
        if self._timer is not None:
            self._arm()

    # -- gc pause accounting (gc.callbacks) --------------------------------

    def _register_gc(self) -> None:
        if self._gc_registered:
            return
        import gc

        gc.callbacks.append(self._on_gc)
        self._gc_registered = True

    def _unregister_gc(self) -> None:
        if not self._gc_registered:
            return
        import gc

        try:
            gc.callbacks.remove(self._on_gc)
        except ValueError:
            pass  # already gone (interpreter teardown ordering)
        self._gc_registered = False

    def _on_gc(self, phase: str, info: dict) -> None:
        """Bracket every collection — including the deferred post-close
        ones the pipelined tail runs on its worker, hence the
        per-THREAD t0 (two threads' collections must not cross-time)."""
        if phase == "start":
            self._gc_tls.t0 = perf_counter()
        elif phase == "stop":
            t0 = getattr(self._gc_tls, "t0", None)
            if t0 is None:
                return
            self._gc_tls.t0 = None
            m = self.app.metrics
            m.histogram("vitals.gc.pause").update(perf_counter() - t0)
            m.counter("vitals.gc.gen%d.collections"
                      % info.get("generation", 0)).inc()

    # -- sampling ----------------------------------------------------------

    def collect(self) -> dict:
        """One gauge sweep.  Everything here must stay cheap enough to
        run at 1 Hz forever — no heap walks, no SQL."""
        import gc

        app = self.app
        q = app.herder.tx_queue
        lm = app.ledger_manager
        bl = app.bucket_manager.bucket_list
        from ..crypto.ed25519 import verify_cache_stats

        hits, misses = verify_cache_stats()
        entries = disk_bytes = 0
        for lv in bl.levels:
            for b in (lv.curr, lv.snap):
                entries += len(b)
                disk_bytes += getattr(b, "size_bytes", 0)
        return {
            "t": round(perf_counter(), 6),
            "rss_bytes": rss_bytes(),
            "open_fds": open_fds(),
            "threads": threading.active_count(),
            "tx_queue_depth": q.size(),
            "tx_queue_age_max": max(
                (a.age for a in q.accounts.values()), default=0),
            "pipeline_tail_depth": lm.pipeline.tail_depth(),
            "bucket_entries": entries,
            "bucket_disk_bytes": disk_bytes,
            "verify_cache_hit_rate": (
                round(hits / (hits + misses), 4)
                if hits + misses else 0.0),
            "prefetch_hit_rate": round(lm.root.prefetch_hit_rate(), 4),
            "gc_pending": sum(gc.get_count()),
        }

    def sample_once(self) -> dict:
        sample = self.collect()
        self.ring.append(sample)
        self.samples_taken += 1
        m = self.app.metrics
        for k, v in sample.items():
            if k != "t" and isinstance(v, (int, float)):
                m.gauge(f"vitals.{k}").set(v)
        if self.jsonl_path:
            self._persist(sample)
        self._check_slos(sample)
        return sample

    def _persist(self, sample: dict) -> None:
        import json

        try:
            with open(self.jsonl_path, "a", encoding="utf-8") as f:
                f.write(json.dumps(sample, sort_keys=True) + "\n")
        except OSError:
            self.jsonl_path = None  # disk gone: stop retrying per sample

    def slope(self, gauge: str, last_fraction: float = 1.0) -> float:
        """Least-squares drift of one gauge in units per second.
        ``last_fraction`` < 1 fits only the newest part of the ring —
        the steady-state view, which startup transients (caches and
        bounded rings still filling toward their caps) would otherwise
        dominate."""
        pts = [(s["t"], float(s[gauge])) for s in self.ring
               if isinstance(s.get(gauge), (int, float))]
        if last_fraction < 1.0 and len(pts) > 2:
            pts = pts[-max(2, int(len(pts) * last_fraction)):]
        return least_squares_slope(pts)

    def slopes(self, last_fraction: float = 1.0) -> Dict[str, float]:
        return {g: round(self.slope(g, last_fraction), 6)
                for g in SLOPE_GAUGES}

    # -- SLO watchdog ------------------------------------------------------

    def _check_slos(self, sample: dict) -> None:
        cfg = self.app.config
        breaches: List[Tuple[str, str]] = []
        slope_cap = getattr(cfg, "SLO_MAX_MEMORY_SLOPE_MB_S", 0.0)
        if slope_cap and len(self.ring) >= 2 * SLO_WARMUP_SAMPLES:
            # newest-half fit with a doubled warmup: the full-ring fit
            # would count the startup transient (caches and bounded
            # rings filling toward their caps) as a leak and flake the
            # soak gate
            sl = self.slope("rss_bytes", last_fraction=0.5)
            if sl > slope_cap * 1e6:
                breaches.append((
                    "memory-slope",
                    f"rss slope {sl / 1e6:.2f} MB/s > {slope_cap} MB/s "
                    f"(tail fit over {len(self.ring) // 2} samples)"))
        p99_cap = getattr(cfg, "SLO_MAX_CLOSE_P99_SECONDS", 0.0)
        if p99_cap:
            t = self.app.metrics._metrics.get("ledger.ledger.close")
            if t is not None and t.count >= SLO_WARMUP_SAMPLES:
                p99 = t.percentile(0.99)
                if p99 > p99_cap:
                    breaches.append((
                        "close-p99",
                        f"close p99 {p99:.3f}s > {p99_cap}s"))
        age_cap = getattr(cfg, "SLO_MAX_QUEUE_AGE", 0)
        if age_cap and sample["tx_queue_age_max"] > age_cap:
            breaches.append((
                "queue-age",
                f"tx queue age {sample['tx_queue_age_max']} ledgers > "
                f"{age_cap}"))
        if getattr(cfg, "SLO_QUORUM_AVAILABILITY", True):
            # fed by the quorum-health monitor (herder/quorum_health.py):
            # a sample taken while the local quorum slice is
            # unsatisfiable from recently-heard nodes is a breach —
            # only once the monitor has actually evaluated
            qh = getattr(getattr(self.app, "herder", None),
                         "quorum_health", None)
            if qh is not None and qh.enabled and qh.evaluations > 0 \
                    and not getattr(cfg, "MANUAL_CLOSE", False):
                # per-close evaluation freezes during a total stall —
                # the primary failure this SLO exists to catch — so
                # once closes are overdue, re-evaluate against the
                # LIVE slot, where the silence actually is
                stale_after = max(
                    4 * getattr(cfg, "EXP_LEDGER_TIMESPAN_SECONDS", 5.0),
                    2 * self.period)
                if self.app.clock.now() - qh.last_eval_time > stale_after:
                    qh.evaluate(
                        self.app.ledger_manager.last_closed_seq() + 1)
            mm = self.app.metrics._metrics
            avail = mm.get("quorum.health.available")
            evals = mm.get("quorum.health.evaluations")
            if avail is not None and evals is not None and \
                    evals.count > 0 and avail.value < 1.0:
                breaches.append((
                    "quorum-availability",
                    "local quorum slice unsatisfiable from "
                    "recently-heard nodes"))
        breached_now = set()
        for name, msg in breaches:
            breached_now.add(name)
            self.app.metrics.counter(f"slo.breach.{name}").inc()
            if not self._slo_active.get(name):
                from .logging import get_logger

                get_logger("Perf").warning("SLO breach [%s]: %s",
                                           name, msg)
            self._slo_active[name] = True
        for name in self._slo_active:
            if name not in breached_now:
                self._slo_active[name] = False

    def breach_counts(self) -> Dict[str, int]:
        out = {}
        for name, metric in sorted(self.app.metrics._metrics.items()):
            if name.startswith("slo.breach."):
                out[name[len("slo.breach."):]] = metric.count
        return out

    # -- reporting ---------------------------------------------------------

    def report(self) -> dict:
        """The vitals endpoint body."""
        gc_pause = self.app.metrics._metrics.get("vitals.gc.pause")
        gp = gc_pause.summary() if gc_pause is not None else None
        if gp is not None:
            gp = {"count": gp["count"],
                  "p50_ms": round(gp["p50"] * 1000.0, 3),
                  "p99_ms": round(gp["p99"] * 1000.0, 3),
                  "max_ms": round(gp["max"] * 1000.0, 3)}
        return {
            "enabled": self.enabled,
            "period_s": self.period,
            "samples": len(self.ring),
            "samples_taken": self.samples_taken,
            "latest": dict(self.ring[-1]) if self.ring else None,
            "slopes_per_s": self.slopes(),
            # newest-half fit: steady-state drift with startup
            # transients (rings/caches filling to their caps) excluded
            "slopes_tail_per_s": self.slopes(last_fraction=0.5),
            "slo": {"active": dict(self._slo_active),
                    "breaches": self.breach_counts()},
            "gc_pause": gp,
        }
