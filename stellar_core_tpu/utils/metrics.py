"""Metrics registry: counters, meters, timers, histograms
(ref lib/libmedida + docs/metrics.md; exposed via the admin `metrics`
endpoint like ref src/main/CommandHandler.cpp:116).

Names are dotted triples like the reference's catalog
("ledger.ledger.close", "scp.envelope.receive").
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional


class Counter:
    """Lock-free by design: metric updates are observability-only — a
    lost increment under a GIL-preempted ``+=`` costs one sample, never
    consensus state (COVERAGE.md "Concurrency analysis")."""

    def __init__(self):
        self.count = 0

    def inc(self, n: int = 1):
        self.count += n  # detlint: allow(conc-unguarded-shared)

    def dec(self, n: int = 1):
        self.count -= n  # detlint: allow(conc-unguarded-shared)

    def set_count(self, n: int):
        self.count = n  # detlint: allow(conc-unguarded-shared)


class Gauge:
    """Last-write instantaneous value (vitals samples, derived rates).
    Unlike medida's callback gauges this is push-style: the owner sets
    it when it samples, so reading a snapshot never runs foreign code."""

    def __init__(self):
        self.value = 0.0

    def set(self, v: float):
        self.value = float(v)


class Meter:
    """Event rate tracker (1m EWMA + total count).

    ``one_minute_rate`` decays on READ: an idle meter's rate tends to 0
    with the time since its last mark instead of freezing at the last
    instantaneous value (medida's tickIfNecessary, folded into the
    getter so there is no tick thread)."""

    def __init__(self, clock=None):
        self.count = 0
        self._rate = 0.0
        self._last: Optional[float] = None
        self._clock = clock

    def _now(self) -> float:
        return self._clock.now() if self._clock else time.monotonic()

    def mark(self, n: int = 1):
        # lock-free like Counter: a racing mark can lose one EWMA step
        # or count — an observability sample, never consensus state
        now = self._now()
        if self._last is not None:
            dt = max(now - self._last, 1e-9)
            inst = n / dt
            alpha = 1 - math.exp(-dt / 60.0)
            self._rate += alpha * (inst - self._rate)  # detlint: allow(conc-unguarded-shared)
        self._last = now  # detlint: allow(conc-unguarded-shared)
        self.count += n  # detlint: allow(conc-unguarded-shared)

    @property
    def one_minute_rate(self) -> float:
        if self._last is None:
            return 0.0
        idle = self._now() - self._last
        if idle <= 0:
            return self._rate
        return self._rate * math.exp(-idle / 60.0)


class Histogram:
    """Streaming histogram (count/min/max/mean/percentiles) over a
    DETERMINISTIC stride-decimation reservoir.

    Medida keeps a uniform random sample; the randomness made two
    identically-driven registries produce different snapshots (and
    tripped the spirit of detlint's determinism discipline).  Instead:
    accept every ``stride``-th update; when the buffer fills, drop every
    other retained sample and double the stride.  The reservoir is a
    uniform systematic sample of the whole update history, bounded to
    [MAX_SAMPLES/2, MAX_SAMPLES], and a pure function of the update
    sequence."""

    MAX_SAMPLES = 1028

    def __init__(self):
        self.count = 0
        self._samples: List[float] = []
        self._stride = 1
        self.min = math.inf
        self.max = -math.inf
        self._sum = 0.0

    def update(self, v: float):
        self.count += 1
        self._sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if (self.count - 1) % self._stride == 0:
            self._samples.append(v)
            if len(self._samples) >= self.MAX_SAMPLES:
                # keep even positions: exactly the samples a doubled
                # stride would have accepted from the start
                del self._samples[1::2]
                self._stride *= 2

    @property
    def mean(self) -> float:
        return self._sum / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        k = min(int(p * len(s)), len(s) - 1)
        return s[k]

    def summary(self) -> dict:
        return {
            "count": self.count,
            "min": 0.0 if self.count == 0 else self.min,
            "max": 0.0 if self.count == 0 else self.max,
            "mean": self.mean,
            "p50": self.percentile(0.50),
            "p75": self.percentile(0.75),
            "p99": self.percentile(0.99),
        }


class Timer(Histogram):
    """Histogram of durations (seconds) + rate."""

    def __init__(self, clock=None):
        super().__init__()
        self.meter = Meter(clock)

    def update(self, v: float):
        super().update(v)
        self.meter.mark()

    def time_scope(self):
        return _TimeScope(self)


class _TimeScope:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    def __init__(self, clock=None):
        import threading

        from .lockdep import register_lock

        self._clock = clock
        self._metrics: Dict[str, object] = {}  # guarded-by: _reg_lock
        # bounded-cardinality metric families (bounded_name): family ->
        # admitted member suffixes.  Guarded by _reg_lock.
        self._families: Dict[str, set] = {}
        # registration is the one cross-thread mutation (the pipelined
        # close's tail worker and gc callbacks both register lazily):
        # without the lock, two threads racing the get-then-insert
        # below could each create the metric and one would silently
        # lose its updates.  Reads stay lock-free: iteration always
        # goes through sorted(...) whose list materialization is
        # GIL-atomic.
        self._reg_lock = register_lock(threading.Lock(), "metrics.registry")

    def _get(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            with self._reg_lock:
                m = self._metrics.get(name)
                if m is None:
                    m = self._metrics[name] = cls(*args)
        assert isinstance(m, cls), f"{name} registered as {type(m).__name__}"
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter, self._clock)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer, self._clock)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def gauge(self, name: str) -> Gauge:
        return self._get(name, Gauge)

    def bounded_name(self, family: str, member: str,
                     cap: int = 32) -> str:
        """Bounded-cardinality guard for label-shaped metric families
        (``apply.native.decline.<op>.<why>``, ``overlay.peer.*.<id>``):
        the first ``cap`` DISTINCT members keep their own metric name
        (``family.member``); every later member collapses into
        ``family.other``, so an adversarial label mix (hostile op
        shapes, peer churn) cannot grow the registry — and the
        /metrics payload — without bound.  Admission is deterministic
        first-come.  Member strings are sanitized (dots allowed, other
        separators collapse) so a hostile slug cannot fork families."""
        member = member.replace("\n", "_").replace(" ", "_") or "unknown"
        members = self._families.get(family)
        if members is not None and member in members:
            return f"{family}.{member}"
        with self._reg_lock:
            members = self._families.setdefault(family, set())
            if member in members or len(members) < cap:
                members.add(member)
                return f"{family}.{member}"
        return f"{family}.other"

    def snapshot(self) -> dict:
        out = {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Counter):
                out[name] = {"type": "counter", "count": m.count}
            elif isinstance(m, Gauge):
                out[name] = {"type": "gauge", "value": m.value}
            elif isinstance(m, Timer):
                out[name] = {"type": "timer", **m.summary(),
                             "rate1m": m.meter.one_minute_rate}
            elif isinstance(m, Meter):
                out[name] = {"type": "meter", "count": m.count,
                             "rate1m": m.one_minute_rate}
            elif isinstance(m, Histogram):
                out[name] = {"type": "histogram", **m.summary()}
        return out

    def reset(self) -> None:
        """MetricResetter equivalent for tests."""
        with self._reg_lock:
            self._metrics.clear()
            self._families.clear()


# -- Prometheus exposition ---------------------------------------------------

def _prom_name(name: str) -> str:
    """Dotted medida-style names -> a legal Prometheus metric name."""
    import re

    out = re.sub(r"[^a-zA-Z0-9_:]", "_", name)
    if not re.match(r"[a-zA-Z_:]", out):
        out = "_" + out
    return out


#: registry families whose last dotted segment is a peer id (pid8 or the
#: bounded_name "other" roll-up) — exposed as a {peer="..."} label
#: instead of a per-peer metric name
_PEER_FAMILIES = ("overlay.peer.", "floodtrace.link.")


def _peer_split(name: str):
    """'overlay.peer.queue_depth.ab12cd34' -> (family, member), else
    None for names outside the per-peer families."""
    for pref in _PEER_FAMILIES:
        if name.startswith(pref):
            fam, _, member = name.rpartition(".")
            if fam != pref.rstrip(".") and member:
                return fam, member
    return None


def render_prometheus(registry: MetricsRegistry) -> str:
    """Text exposition format (version 0.0.4) of the registry: counters
    as ``counter``, meters as count + 1m-rate gauge, timers/histograms
    as ``summary`` with quantile labels — the shape Prometheus's
    text-format parser and promtool both accept.  Span-derived timers
    (``span.*``, fed per close by the flight recorder) ride along as
    ordinary registry timers.

    Per-peer families (overlay.peer.*, floodtrace.link.*) emit one
    metric per family with a ``{peer="..."}`` label rather than
    name-mangling the peer id — sorted iteration keeps a family's
    members adjacent, so each family gets exactly one # TYPE line.  The
    JSON snapshot() form is unchanged."""
    lines: List[str] = []
    typed = set()
    for name, m in sorted(registry._metrics.items()):
        ps = _peer_split(name) if isinstance(m, (Counter, Gauge)) else None
        if ps is not None:
            fam, member = ps
            pname = _prom_name(fam)
            if pname not in typed:
                typed.add(pname)
                lines.append(
                    f"# TYPE {pname} "
                    f"{'counter' if isinstance(m, Counter) else 'gauge'}")
            val = m.count if isinstance(m, Counter) else f"{m.value:.6g}"
            lines.append(f'{pname}{{peer="{member}"}} {val}')
            continue
        pname = _prom_name(name)
        if isinstance(m, Counter):
            lines.append(f"# TYPE {pname} counter")
            lines.append(f"{pname} {m.count}")
        elif isinstance(m, Gauge):
            lines.append(f"# TYPE {pname} gauge")
            lines.append(f"{pname} {m.value:.6g}")
        elif isinstance(m, Timer):
            _render_summary(lines, pname + "_seconds", m)
            rname = pname + "_rate1m"
            lines.append(f"# TYPE {rname} gauge")
            lines.append(f"{rname} {m.meter.one_minute_rate:.6g}")
        elif isinstance(m, Meter):
            lines.append(f"# TYPE {pname}_total counter")
            lines.append(f"{pname}_total {m.count}")
            rname = pname + "_rate1m"
            lines.append(f"# TYPE {rname} gauge")
            lines.append(f"{rname} {m.one_minute_rate:.6g}")
        elif isinstance(m, Histogram):
            _render_summary(lines, pname, m)
    return "\n".join(lines) + "\n"


def _render_summary(lines: List[str], pname: str, h: Histogram) -> None:
    s = h.summary()
    lines.append(f"# TYPE {pname} summary")
    for q, key in (("0.5", "p50"), ("0.75", "p75"), ("0.99", "p99")):
        lines.append(f'{pname}{{quantile="{q}"}} {s[key]:.6g}')
    lines.append(f"{pname}_sum {h.mean * h.count:.6g}")
    lines.append(f"{pname}_count {h.count}")
