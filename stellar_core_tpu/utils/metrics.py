"""Metrics registry: counters, meters, timers, histograms
(ref lib/libmedida + docs/metrics.md; exposed via the admin `metrics`
endpoint like ref src/main/CommandHandler.cpp:116).

Names are dotted triples like the reference's catalog
("ledger.ledger.close", "scp.envelope.receive").
"""
from __future__ import annotations

import math
import time
from typing import Dict, List, Optional


class Counter:
    def __init__(self):
        self.count = 0

    def inc(self, n: int = 1):
        self.count += n

    def dec(self, n: int = 1):
        self.count -= n

    def set_count(self, n: int):
        self.count = n


class Meter:
    """Event rate tracker (1m EWMA + total count)."""

    def __init__(self, clock=None):
        self.count = 0
        self._rate = 0.0
        self._last: Optional[float] = None
        self._clock = clock

    def _now(self) -> float:
        return self._clock.now() if self._clock else time.monotonic()

    def mark(self, n: int = 1):
        now = self._now()
        if self._last is not None:
            dt = max(now - self._last, 1e-9)
            inst = n / dt
            alpha = 1 - math.exp(-dt / 60.0)
            self._rate += alpha * (inst - self._rate)
        self._last = now
        self.count += n

    @property
    def one_minute_rate(self) -> float:
        return self._rate


class Histogram:
    """Reservoir-free streaming histogram (count/min/max/mean/percentiles
    over a sliding sample of 1028 like medida's uniform sample)."""

    MAX_SAMPLES = 1028

    def __init__(self):
        self.count = 0
        self._samples: List[float] = []
        self.min = math.inf
        self.max = -math.inf
        self._sum = 0.0

    def update(self, v: float):
        self.count += 1
        self._sum += v
        self.min = min(self.min, v)
        self.max = max(self.max, v)
        if len(self._samples) < self.MAX_SAMPLES:
            self._samples.append(v)
        else:
            import random

            i = random.randrange(self.count)
            if i < self.MAX_SAMPLES:
                self._samples[i] = v

    @property
    def mean(self) -> float:
        return self._sum / self.count if self.count else 0.0

    def percentile(self, p: float) -> float:
        if not self._samples:
            return 0.0
        s = sorted(self._samples)
        k = min(int(p * len(s)), len(s) - 1)
        return s[k]

    def summary(self) -> dict:
        return {
            "count": self.count,
            "min": 0.0 if self.count == 0 else self.min,
            "max": 0.0 if self.count == 0 else self.max,
            "mean": self.mean,
            "p50": self.percentile(0.50),
            "p75": self.percentile(0.75),
            "p99": self.percentile(0.99),
        }


class Timer(Histogram):
    """Histogram of durations (seconds) + rate."""

    def __init__(self, clock=None):
        super().__init__()
        self.meter = Meter(clock)

    def update(self, v: float):
        super().update(v)
        self.meter.mark()

    def time_scope(self):
        return _TimeScope(self)


class _TimeScope:
    def __init__(self, timer: Timer):
        self.timer = timer

    def __enter__(self):
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        self.timer.update(time.perf_counter() - self._t0)
        return False


class MetricsRegistry:
    def __init__(self, clock=None):
        self._clock = clock
        self._metrics: Dict[str, object] = {}

    def _get(self, name: str, cls, *args):
        m = self._metrics.get(name)
        if m is None:
            m = self._metrics[name] = cls(*args)
        assert isinstance(m, cls), f"{name} registered as {type(m).__name__}"
        return m

    def counter(self, name: str) -> Counter:
        return self._get(name, Counter)

    def meter(self, name: str) -> Meter:
        return self._get(name, Meter, self._clock)

    def timer(self, name: str) -> Timer:
        return self._get(name, Timer, self._clock)

    def histogram(self, name: str) -> Histogram:
        return self._get(name, Histogram)

    def snapshot(self) -> dict:
        out = {}
        for name, m in sorted(self._metrics.items()):
            if isinstance(m, Counter):
                out[name] = {"type": "counter", "count": m.count}
            elif isinstance(m, Timer):
                out[name] = {"type": "timer", **m.summary(),
                             "rate1m": m.meter.one_minute_rate}
            elif isinstance(m, Meter):
                out[name] = {"type": "meter", "count": m.count,
                             "rate1m": m.one_minute_rate}
            elif isinstance(m, Histogram):
                out[name] = {"type": "histogram", **m.summary()}
        return out

    def reset(self) -> None:
        """MetricResetter equivalent for tests."""
        self._metrics.clear()
