"""xdrquery: a small filter language over decoded XDR values
(ref src/util/xdrquery/ — the reference's flex/bison grammar collapses to
a recursive-descent parser over the same surface: dotted field paths,
comparisons, && / || / !, parentheses, int/string literals).

Used for operator-side inspection (`dumpxdr`-style filtering of ledger
entries, e.g. ``data.account.balance > 1000000000``).  Paths traverse
namedtuple fields; union arms deref by arm name (``data.account`` selects
the ACCOUNT arm's value and fails the row when the union holds another
arm); ``.type`` reads a union discriminant; 32-byte values compare against
hex strings.
"""
from __future__ import annotations

import re
from typing import Any, List, Optional, Tuple

TOKEN_RE = re.compile(r"""
    \s*(?:
      (?P<num>-?\d+)
    | (?P<str>'[^']*'|"[^"]*")
    | (?P<op>&&|\|\||==|!=|<=|>=|<|>|!|\(|\))
    | (?P<name>[A-Za-z_][A-Za-z0-9_]*(?:\.[A-Za-z0-9_]+)*)
    )""", re.VERBOSE)


class QueryError(Exception):
    pass


def _tokenize(src: str) -> List[Tuple[str, str]]:
    out = []
    pos = 0
    while pos < len(src):
        m = TOKEN_RE.match(src, pos)
        if m is None or m.end() == pos:
            if src[pos:].strip() == "":
                break
            raise QueryError(f"bad token at {src[pos:pos + 12]!r}")
        pos = m.end()
        for kind in ("num", "str", "op", "name"):
            v = m.group(kind)
            if v is not None:
                out.append((kind, v))
                break
    return out


class _Missing:
    """Path didn't resolve (wrong union arm / absent option): the row
    fails every comparison, like the reference's NULL semantics."""


MISSING = _Missing()


def resolve_path(value: Any, path: str) -> Any:
    for part in path.split("."):
        if value is MISSING or value is None:
            return MISSING
        if part == "type" and hasattr(value, "type"):
            value = value.type
            continue
        if hasattr(value, part):
            value = getattr(value, part)
            continue
        # union arm deref: .value carries the arm; arm name must match
        # the declared arm for the current discriminant
        inner = getattr(value, "value", MISSING)
        if inner is not MISSING and hasattr(inner, part):
            value = getattr(inner, part)
            continue
        if inner is not MISSING and _arm_matches(value, part):
            value = inner
            continue
        return MISSING
    return value


def _arm_matches(union_val, name: str) -> bool:
    """Does the union currently hold the arm called ``name``?
    (_UnionValue carries its arm name; matched case-insensitively so
    ``data.account.balance`` selects the ACCOUNT arm.)"""
    arm = getattr(union_val, "arm", None)
    return arm is not None and arm.lower() == name.lower()


class _Parser:
    def __init__(self, tokens: List[Tuple[str, str]]):
        self.toks = tokens
        self.i = 0

    def peek(self) -> Optional[Tuple[str, str]]:
        return self.toks[self.i] if self.i < len(self.toks) else None

    def take(self) -> Tuple[str, str]:
        t = self.peek()
        if t is None:
            raise QueryError("unexpected end of query")
        self.i += 1
        return t

    def parse(self):
        node = self.parse_or()
        if self.peek() is not None:
            raise QueryError(f"trailing tokens: {self.toks[self.i:]}")
        return node

    def parse_or(self):
        left = self.parse_and()
        while self.peek() == ("op", "||"):
            self.take()
            right = self.parse_and()
            left = ("or", left, right)
        return left

    def parse_and(self):
        left = self.parse_not()
        while self.peek() == ("op", "&&"):
            self.take()
            right = self.parse_not()
            left = ("and", left, right)
        return left

    def parse_not(self):
        if self.peek() == ("op", "!"):
            self.take()
            return ("not", self.parse_not())
        return self.parse_cmp()

    def parse_cmp(self):
        left = self.parse_atom()
        t = self.peek()
        if t is not None and t[0] == "op" and t[1] in (
                "==", "!=", "<", "<=", ">", ">="):
            self.take()
            right = self.parse_atom()
            return ("cmp", t[1], left, right)
        return left

    def parse_atom(self):
        t = self.take()
        if t == ("op", "("):
            node = self.parse_or()
            if self.take() != ("op", ")"):
                raise QueryError("expected )")
            return node
        kind, v = t
        if kind == "num":
            return ("lit", int(v))
        if kind == "str":
            return ("lit", v[1:-1])
        if kind == "name":
            if v in ("true", "false"):
                return ("lit", v == "true")
            return ("path", v)
        raise QueryError(f"unexpected token {t}")


def compile_query(src: str):
    """Compile to a predicate over decoded XDR values."""
    ast = _Parser(_tokenize(src)).parse()

    def truthy(x) -> bool:
        # an unresolved path is NULL-ish: false in any boolean context
        return x is not MISSING and bool(x)

    def evaluate(node, value):
        kind = node[0]
        if kind == "lit":
            return node[1]
        if kind == "path":
            return resolve_path(value, node[1])
        if kind == "and":
            return truthy(evaluate(node[1], value)) and \
                truthy(evaluate(node[2], value))
        if kind == "or":
            return truthy(evaluate(node[1], value)) or \
                truthy(evaluate(node[2], value))
        if kind == "not":
            return not truthy(evaluate(node[1], value))
        if kind == "cmp":
            _, op, ln, rn = node
            lv = evaluate(ln, value)
            rv = evaluate(rn, value)
            if lv is MISSING or rv is MISSING:
                return False
            lv, rv = _coerce(lv, rv)
            if op == "==":
                return lv == rv
            if op == "!=":
                return lv != rv
            if op == "<":
                return lv < rv
            if op == "<=":
                return lv <= rv
            if op == ">":
                return lv > rv
            if op == ">=":
                return lv >= rv
        raise QueryError(f"bad node {node}")

    def predicate(value) -> bool:
        out = evaluate(ast, value)
        if out is MISSING:
            return False
        return bool(out)

    return predicate


def _coerce(lv, rv):
    """bytes vs hex-string comparisons; enum ints vs ints are already
    compatible."""
    if isinstance(lv, bytes) and isinstance(rv, str):
        try:
            rv = bytes.fromhex(rv)
        except ValueError:
            rv = rv.encode()
    elif isinstance(rv, bytes) and isinstance(lv, str):
        try:
            lv = bytes.fromhex(lv)
        except ValueError:
            lv = lv.encode()
    return lv, rv


def query_entries(entries, src: str):
    """Filter an iterable of decoded XDR values by a query string."""
    pred = compile_query(src)
    return [e for e in entries if pred(e)]
