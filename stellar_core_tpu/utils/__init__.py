"""Infrastructure: clock, scheduler, metrics, logging
(ref src/util — SURVEY.md §2.15)."""
from .clock import ClockMode, VirtualClock, VirtualTimer  # noqa: F401
from .metrics import MetricsRegistry  # noqa: F401
from .scheduler import ActionType, Scheduler  # noqa: F401
