"""Minimal TOML-subset parser — fallback for Python < 3.11 hosts with no
``tomllib`` (the node's config loader must work on the bare container).

Supports exactly what node config files use: ``#`` comments, bare
``key = value`` pairs, ``[table]`` / ``[table.sub]`` headers,
``[[array-of-tables]]``, and values that are strings, booleans, integers,
floats, or (possibly multi-line) arrays of those.  Unsupported syntax
raises ValueError rather than mis-parsing.
"""
from __future__ import annotations

from typing import Any, Dict, List, Tuple


def load(f) -> Dict[str, Any]:
    data = f.read()
    if isinstance(data, bytes):
        data = data.decode("utf-8")
    return loads(data)


def loads(text: str) -> Dict[str, Any]:
    root: Dict[str, Any] = {}
    cur = root
    lines = text.splitlines()
    i = 0
    while i < len(lines):
        line = _strip_comment(lines[i]).strip()
        i += 1
        if not line:
            continue
        if line.startswith("[["):
            if not line.endswith("]]"):
                raise ValueError(f"bad table header: {line}")
            tbl = _descend(root, line[2:-2].strip())
            parent, leaf = tbl
            arr = parent.setdefault(leaf, [])
            if not isinstance(arr, list):
                raise ValueError(f"conflicting table {line}")
            cur = {}
            arr.append(cur)
        elif line.startswith("["):
            if not line.endswith("]"):
                raise ValueError(f"bad table header: {line}")
            parent, leaf = _descend(root, line[1:-1].strip())
            cur = parent.setdefault(leaf, {})
            if not isinstance(cur, dict):
                raise ValueError(f"conflicting table {line}")
        else:
            if "=" not in line:
                raise ValueError(f"bad line: {line}")
            key, _, rest = line.partition("=")
            key = key.strip().strip('"')
            rest = rest.strip()
            # multi-line arrays: keep consuming until brackets balance
            while rest.startswith("[") and not _balanced(rest):
                if i >= len(lines):
                    raise ValueError(f"unterminated array for {key}")
                rest += " " + _strip_comment(lines[i]).strip()
                i += 1
            cur[key] = _value(rest)
    return root


def _scan(s: str):
    """Yield (index, char, in_string) with backslash escapes honored
    inside strings — the one quote-state walker every helper shares, so
    '\\"' inside a string can never flip the state."""
    in_str = False
    escaped = False
    for i, ch in enumerate(s):
        if in_str and escaped:
            escaped = False
            yield i, ch, True
            continue
        if in_str and ch == "\\":
            escaped = True
            yield i, ch, True
            continue
        if ch == '"':
            yield i, ch, in_str  # the quote itself reports the old state
            in_str = not in_str
            continue
        yield i, ch, in_str


def _strip_comment(line: str) -> str:
    for i, ch, in_str in _scan(line):
        if ch == "#" and not in_str:
            return line[:i]
    return line


def _balanced(s: str) -> bool:
    depth = 0
    for _, ch, in_str in _scan(s):
        if not in_str:
            if ch == "[":
                depth += 1
            elif ch == "]":
                depth -= 1
    return depth == 0


def _descend(root: Dict[str, Any],
             dotted: str) -> Tuple[Dict[str, Any], str]:
    parts = [p.strip().strip('"') for p in dotted.split(".")]
    node = root
    for p in parts[:-1]:
        nxt = node.setdefault(p, {})
        if isinstance(nxt, list):
            nxt = nxt[-1]
        node = nxt
    return node, parts[-1]


def _value(s: str) -> Any:
    s = s.strip()
    if not s:
        raise ValueError("empty value")
    if s.startswith('"'):
        body = []
        escapes = {"\\": "\\", '"': '"', "n": "\n", "t": "\t", "r": "\r"}
        escaped = False
        closed_at = None
        for i in range(1, len(s)):
            ch = s[i]
            if escaped:
                body.append(escapes.get(ch, ch))
                escaped = False
            elif ch == "\\":
                escaped = True
            elif ch == '"':
                closed_at = i
                break
            else:
                body.append(ch)
        if closed_at != len(s) - 1:
            raise ValueError(f"bad string: {s}")
        return "".join(body)
    if s.startswith("["):
        if not s.endswith("]"):
            raise ValueError(f"bad array: {s}")
        return [_value(el) for el in _split_array(s[1:-1])]
    if s == "true":
        return True
    if s == "false":
        return False
    try:
        return int(s)
    except ValueError:
        pass
    try:
        return float(s)
    except ValueError:
        raise ValueError(f"unsupported TOML value: {s}")


def _split_array(body: str) -> List[str]:
    out = []
    depth = 0
    cur = []
    for _, ch, in_str in _scan(body):
        if not in_str and ch == "[":
            depth += 1
            cur.append(ch)
        elif not in_str and ch == "]":
            depth -= 1
            cur.append(ch)
        elif not in_str and ch == "," and depth == 0:
            out.append("".join(cur))
            cur = []
        else:
            cur.append(ch)
    tail = "".join(cur).strip()
    if tail:
        out.append(tail)
    return [x.strip() for x in out if x.strip()]
