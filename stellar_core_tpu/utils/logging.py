"""Partitioned logging (ref src/util/Logging.h + LogPartitions.def).

15 partitions with independently settable levels, runtime-adjustable via
the admin ``ll`` endpoint like the reference (ref CommandHandler.cpp:113).
Built over the stdlib logging module.
"""
from __future__ import annotations

import logging
from typing import Dict, Optional

PARTITIONS = [
    "Fs", "SCP", "Bucket", "Database", "History", "Process", "Ledger",
    "Overlay", "Herder", "Tx", "LoadGen", "Work", "Invariant", "Perf",
    "Default",
]

_loggers: Dict[str, logging.Logger] = {}


def get_logger(partition: str) -> logging.Logger:
    if partition not in PARTITIONS:
        partition = "Default"
    lg = _loggers.get(partition)
    if lg is None:
        lg = logging.getLogger(f"stellar_core_tpu.{partition}")
        _loggers[partition] = lg
    return lg


def set_log_level(level: str, partition: Optional[str] = None) -> None:
    """Set one partition's level, or all when partition is None.
    Raises ValueError on an unknown level or partition — the runtime
    ``ll`` endpoint must not silently retarget the Default partition."""
    lvl = getattr(logging, level.upper(), None)
    if not isinstance(lvl, int):
        raise ValueError(f"unknown log level {level!r}")
    if partition is not None and partition not in PARTITIONS:
        raise ValueError(f"unknown log partition {partition!r}")
    targets = [partition] if partition else PARTITIONS
    for p in targets:
        get_logger(p).setLevel(lvl)


def get_log_levels() -> Dict[str, str]:
    return {
        p: logging.getLevelName(get_logger(p).getEffectiveLevel())
        for p in PARTITIONS
    }


def init(level: str = "INFO") -> None:
    logging.basicConfig(
        format="%(asctime)s %(name)s [%(levelname)s] %(message)s")
    set_log_level(level)


class LogSlowExecution:
    """Scope timer that logs when a step exceeds a threshold
    (ref src/util/LogSlowExecution.h — used around closeLedger,
    LedgerManagerImpl.cpp:673)."""

    def __init__(self, name: str, threshold_seconds: float = 1.0,
                 partition: str = "Perf"):
        import time as _time

        self.name = name
        self.threshold = threshold_seconds
        self.partition = partition
        self._time = _time
        self._t0 = None

    def __enter__(self):
        self._t0 = self._time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt = self._time.perf_counter() - self._t0
        if dt > self.threshold:
            get_logger(self.partition).warning(
                "slow execution: %s took %.3fs (threshold %.3fs)",
                self.name, dt, self.threshold)
        return False
