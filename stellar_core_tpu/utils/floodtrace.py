"""Flood-propagation telemetry: per-item hop records across the overlay
(the network axis PR 10's tx-lifecycle tracker cannot see — it follows
one tx through ONE node's subsystems; this follows one flood item
across the gossip fan-out, hop by hop).

Every sampled flood item (the keys Floodgate already dedups on: a
TRANSACTION or SCP_MESSAGE StellarMessage hash) gets a bounded hop
record at each node that tracks it:

    origin       True when this node first broadcast the item itself
                 (loadgen/HTTP tx submit, own SCP emission); False when
                 it arrived from a peer
    from         pid8 of the peer the FIRST copy arrived from (None at
                 the origin)
    first_t      clock stamp of first sight (sim nodes share one
                 VirtualClock, so cross-node deltas are meaningful AND
                 deterministic — the observatory merges on these)
    dups         redundant copies received after the first, with
                 bounded per-link attribution (``dup_links``) and the
                 first-duplicate lag (how far behind the winning path
                 the best redundant path ran)
    forwards     (t, n_peers) per broadcast fan-out event, bounded;
                 ``fanout`` totals the peers this node relayed to

Design constraints, in order (the PR-10 discipline):

- **Zero consensus surface.**  Stamps are observational; nothing here
  feeds a hash, a message send, or an admission verdict.  Clock reads
  live in THIS module (utils/ is outside detlint's consensus scan),
  consensus modules stamp through ``app.floodtracer``.
- **Bounded memory, deterministic sampling.**  The live map admits
  every ``stride``-th first-seen item; when it fills, every other
  tracked item (insertion order) is dropped and the stride doubles.
  Which items get tracked is a pure function of the first-sight
  sequence, never of hash order or a PRNG.  Floodgate GC retires
  tracked records into a bounded completed ring (``on_clear``).
- **Near-zero disabled cost.**  A disabled tracker costs one attribute
  check per flood site; an enabled tracker's stamp for an untracked
  item is one dict probe.

Rollups land in the owning registry so `/metrics` carries them in JSON
and Prometheus form:

    floodtrace.item.dup_lag         seconds each duplicate arrived
                                    behind the first delivery (the
                                    first sample per item is the
                                    first-delivery margin)
    floodtrace.item.fanout          peers relayed to per fan-out event
    floodtrace.item.relay_latency   first-sight -> first-forward
                                    seconds for RELAYED items (this
                                    node's contribution to hop latency)
    floodtrace.link.unique.<pid8>   per-link first-delivery counter
    floodtrace.link.duplicate.<pid8>  per-link redundant-copy counter

The HTTP ``flood`` endpoint serves one hop record (``?hash=``) or the
tracker report; simulation/observatory.py merges every node's records
into network views (coverage percentiles, per-link redundancy).
"""
from __future__ import annotations

import threading
import time as _time
from collections import deque
from typing import Callable, Dict, List, Optional

from .lockdep import guard_fields, register_lock

#: in-flight tracked items before decimation halves the map
DEFAULT_MAX_LIVE = 512
#: retired hop records retained for the flood endpoint / observatory
DEFAULT_RING = 256
#: distinct peers attributed per item's dup_links before "other"
DUP_LINK_CAP = 16
#: forward fan-out events recorded per item
FORWARD_CAP = 8
#: distinct per-link counter families (floodtrace.link.*) per node
LINK_CAP = 16


class FloodPropagationTracker:
    """One per Application; every flood stamp funnels through here."""

    def __init__(self, metrics=None, enabled: bool = True,
                 now: Optional[Callable[[], float]] = None,
                 max_live: int = DEFAULT_MAX_LIVE,
                 ring: int = DEFAULT_RING):
        if metrics is None:
            from .metrics import MetricsRegistry

            metrics = MetricsRegistry()
        self.enabled = enabled
        self.metrics = metrics
        # clock injection: sims pass the shared VirtualClock's now so
        # hop stamps are deterministic and cross-node comparable
        self._now = now if now is not None else _time.monotonic
        self.max_live = max(2, int(max_live))
        self._lock = register_lock(threading.Lock(), "floodtrace")
        # msg hash -> hop record dict
        self._live: Dict[bytes, dict] = {}  # guarded-by: _lock
        # retired hop records: (hash, record)
        self._ring: deque = deque(maxlen=max(1, int(ring)))  # guarded-by: _lock
        self._stride = 1          # guarded-by: _lock
        self._seen = 0            # guarded-by: _lock
        self._tracked = 0         # guarded-by: _lock
        self._retired = 0         # guarded-by: _lock
        self._decimations = 0     # guarded-by: _lock
        # metric objects resolved once per name (registry lookup per
        # flood event would dominate the stamp cost)
        self._hists: Dict[str, object] = {}     # guarded-by: _lock
        self._link_counters: Dict[tuple, object] = {}  # guarded-by: _lock
        guard_fields(self)

    # -- stamping ----------------------------------------------------------

    def _admit(self, h: bytes, rec: dict) -> bool:
        """guarded-by: _lock — the first-sight sampling gate.  Accepts
        every ``stride``-th new item; a full live map decimates
        deterministically (keep every other entry in insertion order,
        double the stride)."""
        self._seen += 1
        if (self._seen - 1) % self._stride:
            return False
        if h in self._live:
            return False
        self._live[h] = rec
        self._tracked += 1
        if len(self._live) >= self.max_live:
            # keep the ODD insertion indices: a phase-shifted
            # systematic sample of the doubled stride that retains the
            # just-admitted item
            self._live = dict(list(self._live.items())[1::2])
            self._stride *= 2
            self._decimations += 1
        return True

    def _link_counter(self, pid8: str, new: bool):
        """guarded-by: _lock — cached per-link flood counter, bounded
        through ONE bounded_name family per direction."""
        c = self._link_counters.get((pid8, new))
        if c is None:
            kind = "unique" if new else "duplicate"
            name = self.metrics.bounded_name(
                f"floodtrace.link.{kind}", pid8, cap=LINK_CAP)
            c = self._link_counters[(pid8, new)] = \
                self.metrics.counter(name)
        return c

    def note_recv(self, h: bytes, pid8: str, new: bool, kind: str,
                  seq: int) -> None:
        """One inbound flood copy: ``new`` is the Floodgate verdict.
        First deliveries pass the sampling gate; duplicates stamp only
        already-tracked items (one dict probe otherwise)."""
        if not self.enabled:
            return
        t = self._now()
        with self._lock:
            self._link_counter(pid8, new).inc()
            if new:
                self._admit(h, {
                    "kind": kind, "origin": False, "from": pid8,
                    "seq": seq, "first_t": t, "dups": 0,
                    "dup_links": {}, "dup_first_lag": None,
                    "forwards": [], "fanout": 0})
                return
            rec = self._live.get(h)
            if rec is None:
                return
            rec["dups"] += 1
            lag = t - rec["first_t"]
            if rec["dup_first_lag"] is None:
                rec["dup_first_lag"] = lag
            links = rec["dup_links"]
            key = pid8 if pid8 in links or len(links) < DUP_LINK_CAP \
                else "other"
            links[key] = links.get(key, 0) + 1
            self._hist("floodtrace.item.dup_lag").update(lag)

    def note_origin(self, h: bytes, kind: str, seq: int) -> None:
        """A locally-originated broadcast (own tx submit / own SCP
        emission) — the item's first sight anywhere, gate applies."""
        if not self.enabled:
            return
        t = self._now()
        with self._lock:
            self._admit(h, {
                "kind": kind, "origin": True, "from": None,
                "seq": seq, "first_t": t, "dups": 0, "dup_links": {},
                "dup_first_lag": None, "forwards": [], "fanout": 0})

    def note_forward(self, h: bytes, n_peers: int) -> None:
        """One broadcast fan-out event for a tracked item."""
        if not self.enabled:
            return
        t = self._now()
        with self._lock:
            rec = self._live.get(h)
            if rec is None:
                return
            if not rec["forwards"] and not rec["origin"]:
                self._hist("floodtrace.item.relay_latency").update(
                    t - rec["first_t"])
            rec["fanout"] += n_peers
            if len(rec["forwards"]) < FORWARD_CAP:
                rec["forwards"].append((t, n_peers))
            self._hist("floodtrace.item.fanout").update(n_peers)

    def retire(self, hashes) -> None:
        """Floodgate GC dropped these records (clear_below's on_clear
        hook): move any tracked ones to the completed ring."""
        if not self.enabled:
            return
        with self._lock:
            if not self._live:
                return
            for h in hashes:
                rec = self._live.pop(h, None)
                if rec is not None:
                    self._retired += 1
                    self._ring.append((h, rec))

    def forget_link(self, pid8: str) -> None:
        """Per-connection attribution reset on peer disconnect (the
        reconnect-churn fix): the link's unique/duplicate counters
        restart at zero with the next connection, so dup-rate gauges
        describe the CURRENT link, not every connection that ever
        carried the peer id."""
        with self._lock:
            for new in (True, False):
                c = self._link_counters.get((pid8, new))
                if c is not None:
                    c.set_count(0)

    def _hist(self, name: str):
        h = self._hists.get(name)
        if h is None:
            h = self._hists[name] = self.metrics.histogram(name)
        return h

    # -- reporting ---------------------------------------------------------

    @staticmethod
    def _fmt(h: bytes, rec: dict) -> dict:
        """One hop record as a deterministic, jsonable dict."""
        return {
            "hash": h.hex(),
            "kind": rec["kind"],
            "origin": rec["origin"],
            "from": rec["from"],
            "seq": rec["seq"],
            "first_t": round(rec["first_t"], 6),
            "dups": rec["dups"],
            "dup_links": {k: rec["dup_links"][k]
                          for k in sorted(rec["dup_links"])},
            "dup_first_lag": (round(rec["dup_first_lag"], 6)
                              if rec["dup_first_lag"] is not None
                              else None),
            "forwards": [{"t": round(t, 6), "n": n}
                         for t, n in rec["forwards"]],
            "fanout": rec["fanout"],
        }

    def lookup(self, h: bytes) -> Optional[dict]:
        """The flood?hash= body: live map first, then the ring."""
        with self._lock:
            rec = self._live.get(h)
            if rec is not None:
                return self._fmt(h, rec)
            for rh, rec in reversed(self._ring):
                if rh == h:
                    return self._fmt(h, rec)
        return None

    def export(self) -> Dict[str, dict]:
        """Every retained hop record (live + ring), hash-hex keyed and
        sorted — the observatory's per-node raw material."""
        with self._lock:
            items = [(h, rec) for h, rec in self._ring]
            items += list(self._live.items())
        return {h.hex(): self._fmt(h, rec)
                for h, rec in sorted(items, key=lambda kv: kv[0])}

    def stats(self) -> dict:
        with self._lock:
            return {
                "enabled": self.enabled,
                "stride": self._stride,
                "seen": self._seen,
                "tracked": self._tracked,
                "live": len(self._live),
                "retired": self._retired,
                "decimations": self._decimations,
            }

    def report(self, last: int = 16) -> dict:
        """The flood endpoint body (no ?hash=): tracker stats, the
        floodtrace.* rollup summaries (ms), per-link counters, and the
        most recent hop records."""
        out = self.stats()
        rollups: Dict[str, dict] = {}
        links: Dict[str, dict] = {}
        for name in sorted(self.metrics._metrics):
            if name.startswith("floodtrace.link."):
                parts = name.split(".")  # floodtrace.link.<kind>.<pid8>
                links.setdefault(parts[3], {})[parts[2]] = \
                    self.metrics._metrics[name].count
                continue
            if not name.startswith("floodtrace."):
                continue
            s = self.metrics._metrics[name].summary()
            rollups[name] = {
                "count": s["count"],
                "p50_ms": round(s["p50"] * 1000.0, 3),
                "p99_ms": round(s["p99"] * 1000.0, 3),
                "mean_ms": round(s["mean"] * 1000.0, 3),
                "max_ms": round(s["max"] * 1000.0, 3),
            }
        for pid8, st in links.items():
            uniq = st.get("unique", 0)
            dup = st.get("duplicate", 0)
            st["dup_ratio"] = round(dup / (uniq + dup), 4) \
                if uniq + dup else 0.0
        with self._lock:
            raw = ([(h, rec) for h, rec in self._ring]
                   + list(self._live.items()))[-last:] if last > 0 else []
        out["rollups"] = rollups
        out["links"] = {k: links[k] for k in sorted(links)}
        out["recent"] = [self._fmt(h, rec) for h, rec in raw]
        return out
