"""VirtualClock + VirtualTimer: the event loop of every node.

Two modes like the reference (ref src/util/Timer.h:64-223):
- REAL_TIME: now() is wall-clock; crank() dispatches due work.
- VIRTUAL_TIME: now() only advances when cranked and jumps straight to the
  next scheduled event — whole multi-node networks simulate deterministically
  at accelerated time in one process (ref docs/architecture.md:33-36).

The host event loop stays single-threaded by design (ref
docs/architecture.md:24-27; SURVEY.md §2.17 P1): consensus/state mutation
all happens on the crank thread, while TPU work is dispatched
asynchronously through jax and joined at batch boundaries.
"""
from __future__ import annotations

import heapq
import itertools
import time as _time
from enum import Enum
from typing import Callable, List, Optional, Tuple


class ClockMode(Enum):
    REAL_TIME = 0
    VIRTUAL_TIME = 1


class VirtualClock:
    def __init__(self, mode: ClockMode = ClockMode.VIRTUAL_TIME):
        self.mode = mode
        self._virtual_now = 0.0
        self._timers: List[Tuple[float, int, "VirtualTimer"]] = []
        self._seq = itertools.count()
        self._actions: List[Callable[[], None]] = []
        self._stopped = False

    # -- time --------------------------------------------------------------

    def now(self) -> float:
        if self.mode == ClockMode.REAL_TIME:
            return _time.monotonic()
        return self._virtual_now

    def system_now(self) -> float:
        """Wall-clock (unix) time; virtual mode derives it from the virtual
        offset so close times stay deterministic in simulation."""
        if self.mode == ClockMode.REAL_TIME:
            return _time.time()
        return self._virtual_now

    def set_current_virtual_time(self, t: float) -> None:
        assert self.mode == ClockMode.VIRTUAL_TIME
        assert t >= self._virtual_now
        self._virtual_now = t

    # -- scheduling --------------------------------------------------------

    def post_action(self, action: Callable[[], None]) -> None:
        """Queue work for the next crank (ref postOnMainThread)."""
        self._actions.append(action)

    def _enqueue_timer(self, deadline: float, timer: "VirtualTimer",
                       gen: int) -> None:
        heapq.heappush(self._timers, (deadline, next(self._seq), timer, gen))

    def next_deadline(self) -> Optional[float]:
        while self._timers and not self._timers[0][2]._live(
                self._timers[0][3]):
            heapq.heappop(self._timers)
        return self._timers[0][0] if self._timers else None

    # -- crank -------------------------------------------------------------

    def crank(self, block: bool = False) -> int:
        """Dispatch queued actions + due timers; returns #events dispatched.

        VIRTUAL_TIME: if nothing is due and ``block``, jump time to the next
        deadline.  REAL_TIME: if nothing is due and ``block``, sleep until
        the next deadline.
        """
        if self._stopped:
            return 0
        progress = 0

        actions, self._actions = self._actions, []
        for a in actions:
            a()
            progress += 1

        while True:
            nd = self.next_deadline()
            if nd is None:
                break
            if nd > self.now():
                if progress == 0 and block:
                    if self.mode == ClockMode.VIRTUAL_TIME:
                        self._virtual_now = nd
                    else:
                        _time.sleep(nd - self.now())
                    continue
                break
            _, _, timer, gen = heapq.heappop(self._timers)
            if not timer._live(gen):
                continue
            timer._fire()
            progress += 1
            # actions posted by timer callbacks run this crank too
            actions, self._actions = self._actions, []
            for a in actions:
                a()
                progress += 1
        return progress

    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 100.0) -> bool:
        """Crank until pred() or the (virtual/real) deadline passes —
        the test-harness workhorse (ref Simulation::crankUntil)."""
        deadline = self.now() + timeout
        while self.now() <= deadline:
            if pred():
                return True
            if self.crank(block=True) == 0 and self.next_deadline() is None:
                # fully idle: nothing will ever change
                return pred()
        return pred()

    def cancel_owner(self, owner: object) -> int:
        """Cancel every armed timer tagged with ``owner`` — the teardown
        path for one node on a SHARED clock (chaos crash-restore): a dead
        Application's timers must never fire into freed subsystems while
        the rest of the simulated network keeps cranking.  Returns the
        number of timers cancelled."""
        n = 0
        for entry in list(self._timers):
            timer = entry[2]
            if timer.owner is owner and timer._live(entry[3]):
                timer.cancel()
                n += 1
        return n

    def stop(self) -> None:
        self._stopped = True


class VirtualTimer:
    """One-shot timer owned by a subsystem (ref VirtualTimer).

    expires_from_now/expires_at + async_wait(cb, on_cancel=None); cancel()
    invokes the cancel handler like asio's operation_aborted path.
    Cancel-and-rearm is safe: heap entries carry the arming generation, so
    a stale entry from before a cancel() can never fire a later callback.

    ``owner`` tags the timer with the object (typically the Application)
    whose lifetime bounds it, so ``VirtualClock.cancel_owner`` can sweep
    every timer of one node off a shared simulation clock.
    """

    def __init__(self, clock: VirtualClock, owner: object = None):
        self.clock = clock
        self.owner = owner
        self.cancelled = False
        self._cb: Optional[Callable[[], None]] = None
        self._on_cancel: Optional[Callable[[], None]] = None
        self._armed = False
        self._gen = 0  # bumped on every arm/cancel; heap entries snapshot it

    def expires_from_now(self, delay: float) -> None:
        self._deadline = self.clock.now() + delay

    def expires_at(self, deadline: float) -> None:
        self._deadline = deadline

    def async_wait(self, cb: Callable[[], None],
                   on_cancel: Optional[Callable[[], None]] = None) -> None:
        assert not self._armed, "timer already armed"
        self.cancelled = False
        self._cb = cb
        self._on_cancel = on_cancel
        self._armed = True
        self._gen += 1
        self.clock._enqueue_timer(self._deadline, self, self._gen)

    def cancel(self) -> None:
        if self._armed and not self.cancelled:
            self.cancelled = True
            self._armed = False
            self._gen += 1  # invalidate the outstanding heap entry
            if self._on_cancel is not None:
                cb = self._on_cancel
                self._on_cancel = None
                self.clock.post_action(cb)

    def _live(self, gen: int) -> bool:
        return not self.cancelled and self._armed and gen == self._gen

    def _fire(self) -> None:
        self._armed = False
        cb = self._cb
        self._cb = None
        if cb is not None:
            cb()
