"""VirtualClock + VirtualTimer: the event loop of every node.

Two modes like the reference (ref src/util/Timer.h:64-223):
- REAL_TIME: now() is wall-clock; crank() dispatches due work.
- VIRTUAL_TIME: now() only advances when cranked and jumps straight to the
  next scheduled event — whole multi-node networks simulate deterministically
  at accelerated time in one process (ref docs/architecture.md:33-36).

The host event loop stays single-threaded by design (ref
docs/architecture.md:24-27; SURVEY.md §2.17 P1): consensus/state mutation
all happens on the crank thread, while TPU work is dispatched
asynchronously through jax and joined at batch boundaries.
"""
from __future__ import annotations

import heapq
import itertools
import time as _time
from enum import Enum
from typing import Callable, List, Optional, Tuple


class ClockMode(Enum):
    REAL_TIME = 0
    VIRTUAL_TIME = 1


class CrankProfiler:
    """Attributes the real wall time spent inside ``VirtualClock.crank``
    dispatch to subsystem buckets, keyed by the callback's defining class
    (the first ``__qualname__`` segment — closures armed inside a method
    still carry the class).  Purely observational: it wraps each dispatch
    in perf_counter stamps and never touches virtual time, so same-seed
    sim runs stay bit-identical with the profiler on or off.

    The ``crank`` bucket is the crank loop's own overhead (heap pops,
    deadline scans, REAL_TIME idle sleeps) — whole-crank wall minus the
    dispatched-callback wall — so the report's ``attributed_pct`` covers
    everything spent inside crank(); the remainder of measured wall is
    time outside the event loop (test harness, bench bookkeeping).
    """

    # first __qualname__ segment -> bucket; unlisted classes and
    # module-level functions fall into "other"
    _CLASS_BUCKETS = {
        # quorum-slice evaluation + consensus state machines
        "Herder": "consensus", "HerderSCPDriver": "consensus",
        "PendingEnvelopes": "consensus", "SCP": "consensus",
        "Slot": "consensus", "BallotProtocol": "consensus",
        "NominationProtocol": "consensus", "LocalNode": "consensus",
        "TallyEngine": "consensus", "QuorumTracker": "consensus",
        "QuorumHealthMonitor": "consensus",
        "TransactionQueue": "consensus",
        # per-node close phases + state
        "LedgerManager": "ledger", "ClosePipeline": "ledger",
        "LedgerCloseData": "ledger", "BucketManager": "ledger",
        "BucketList": "ledger", "Bucket": "ledger",
        "HistoryManager": "ledger", "PublishWork": "ledger",
        # overlay delivery
        "OverlayManager": "overlay", "Peer": "overlay",
        "LoopbackPeer": "overlay", "TCPPeer": "overlay",
        "PeerDoor": "overlay", "TCPIOService": "overlay",
        "Floodgate": "overlay", "SurveyManager": "overlay",
        "PeerManager": "overlay", "AdminHttpServer": "overlay",
        # chaos bookkeeping
        "ChaosEngine": "chaos", "LinkChaos": "chaos",
        "LinkPolicy": "chaos",
        # rig machinery
        "LoadGenerator": "loadgen",
        "VitalsSampler": "vitals",
        "Simulation": "sim",
        "Application": "app",
    }

    def __init__(self):
        self.buckets = {}  # bucket -> dispatched wall seconds
        self.events = {}   # bucket -> dispatch count
        self.crank_wall_s = 0.0
        self.cranks = 0
        self._t0 = _time.perf_counter()
        self._qn_cache = {}  # qualname head -> bucket
        # wall charged to nested scopes inside the current dispatch —
        # subtracted from the enclosing charge so every wall second
        # lands in exactly one bucket (self-time attribution)
        self._nested = 0.0

    def _bucket_of(self, cb) -> str:
        qn = getattr(cb, "__qualname__", None)
        if qn is None:  # functools.partial etc.
            qn = getattr(getattr(cb, "func", None), "__qualname__", "")
        head = qn.split(".", 1)[0]
        b = self._qn_cache.get(head)
        if b is None:
            b = self._qn_cache[head] = self._CLASS_BUCKETS.get(
                head, "other")
        return b

    def _charge_bucket(self, b: str, dt: float) -> None:
        self.buckets[b] = self.buckets.get(b, 0.0) + dt
        self.events[b] = self.events.get(b, 0) + 1

    def run(self, cb: Callable[[], None]) -> None:
        saved, self._nested = self._nested, 0.0
        t0 = _time.perf_counter()
        try:
            cb()
        finally:
            dt = _time.perf_counter() - t0
            self._charge_bucket(self._bucket_of(cb),
                                max(0.0, dt - self._nested))
            self._nested = saved

    def run_timer(self, timer: "VirtualTimer") -> None:
        cb = timer._cb  # snapshot: _fire() clears it
        saved, self._nested = self._nested, 0.0
        t0 = _time.perf_counter()
        try:
            timer._fire()
        finally:
            dt = _time.perf_counter() - t0
            self._charge_bucket(self._bucket_of(cb),
                                max(0.0, dt - self._nested))
            self._nested = saved

    # -- nested scopes (subsystem hooks) ------------------------------------
    # Deep subsystems (ledger close, SCP envelope processing) run INSIDE
    # overlay delivery callbacks, so entry-point attribution alone would
    # lump them into "overlay".  scope_begin/scope_end carve their wall
    # out of the enclosing dispatch; hook sites cost one is-None check
    # when profiling is off and never read the wallclock themselves.

    def scope_begin(self, bucket: str) -> tuple:
        tok = (bucket, _time.perf_counter(), self._nested)
        self._nested = 0.0
        return tok

    def scope_end(self, tok: tuple) -> None:
        bucket, t0, saved = tok
        dt = _time.perf_counter() - t0
        self._charge_bucket(bucket, max(0.0, dt - self._nested))
        self._nested = saved + dt

    def note_crank(self, dt: float) -> None:
        self.crank_wall_s += dt
        self.cranks += 1

    def report(self, virtual_elapsed: Optional[float] = None) -> dict:
        measured = _time.perf_counter() - self._t0
        dispatched = sum(self.buckets.values())
        buckets = {k: round(v, 6) for k, v in sorted(self.buckets.items())}
        buckets["crank"] = round(max(0.0, self.crank_wall_s - dispatched),
                                 6)
        attributed = dispatched + buckets["crank"]
        doc = {
            "buckets_s": buckets,
            "events": {k: v for k, v in sorted(self.events.items())},
            "cranks": self.cranks,
            "measured_wall_s": round(measured, 6),
            "attributed_wall_s": round(attributed, 6),
            "attributed_pct": round(100.0 * attributed / measured, 2)
            if measured > 0 else 0.0,
        }
        if virtual_elapsed is not None and virtual_elapsed > 0:
            doc["virtual_s"] = round(virtual_elapsed, 6)
            doc["wall_per_virtual_s"] = round(measured / virtual_elapsed, 6)
        return doc


class VirtualClock:
    def __init__(self, mode: ClockMode = ClockMode.VIRTUAL_TIME):
        self.mode = mode
        self._virtual_now = 0.0
        self._timers: List[Tuple[float, int, "VirtualTimer"]] = []
        self._seq = itertools.count()
        self._actions: List[Callable[[], None]] = []
        self._stopped = False
        # crank wall-attribution hook (CrankProfiler); None keeps the
        # dispatch loop at one is-None check per event
        self.profiler: Optional[CrankProfiler] = None

    # -- time --------------------------------------------------------------

    def now(self) -> float:
        if self.mode == ClockMode.REAL_TIME:
            return _time.monotonic()
        return self._virtual_now

    def system_now(self) -> float:
        """Wall-clock (unix) time; virtual mode derives it from the virtual
        offset so close times stay deterministic in simulation."""
        if self.mode == ClockMode.REAL_TIME:
            return _time.time()
        return self._virtual_now

    def set_current_virtual_time(self, t: float) -> None:
        assert self.mode == ClockMode.VIRTUAL_TIME
        assert t >= self._virtual_now
        self._virtual_now = t

    # -- scheduling --------------------------------------------------------

    def post_action(self, action: Callable[[], None]) -> None:
        """Queue work for the next crank (ref postOnMainThread)."""
        self._actions.append(action)

    def _enqueue_timer(self, deadline: float, timer: "VirtualTimer",
                       gen: int) -> None:
        heapq.heappush(self._timers, (deadline, next(self._seq), timer, gen))

    def next_deadline(self) -> Optional[float]:
        while self._timers and not self._timers[0][2]._live(
                self._timers[0][3]):
            heapq.heappop(self._timers)
        return self._timers[0][0] if self._timers else None

    # -- crank -------------------------------------------------------------

    def crank(self, block: bool = False) -> int:
        """Dispatch queued actions + due timers; returns #events dispatched.

        VIRTUAL_TIME: if nothing is due and ``block``, jump time to the next
        deadline.  REAL_TIME: if nothing is due and ``block``, sleep until
        the next deadline.
        """
        if self._stopped:
            return 0
        prof = self.profiler
        t_start = _time.perf_counter() if prof is not None else 0.0
        progress = 0

        actions, self._actions = self._actions, []
        for a in actions:
            if prof is None:
                a()
            else:
                prof.run(a)
            progress += 1

        while True:
            nd = self.next_deadline()
            if nd is None:
                break
            if nd > self.now():
                if progress == 0 and block:
                    if self.mode == ClockMode.VIRTUAL_TIME:
                        self._virtual_now = nd
                    else:
                        _time.sleep(nd - self.now())
                    continue
                break
            _, _, timer, gen = heapq.heappop(self._timers)
            if not timer._live(gen):
                continue
            if prof is None:
                timer._fire()
            else:
                prof.run_timer(timer)
            progress += 1
            # actions posted by timer callbacks run this crank too
            actions, self._actions = self._actions, []
            for a in actions:
                if prof is None:
                    a()
                else:
                    prof.run(a)
                progress += 1
        if prof is not None:
            prof.note_crank(_time.perf_counter() - t_start)
        return progress

    def crank_until(self, pred: Callable[[], bool],
                    timeout: float = 100.0) -> bool:
        """Crank until pred() or the (virtual/real) deadline passes —
        the test-harness workhorse (ref Simulation::crankUntil)."""
        deadline = self.now() + timeout
        while self.now() <= deadline:
            if pred():
                return True
            if self.crank(block=True) == 0 and self.next_deadline() is None:
                # fully idle: nothing will ever change
                return pred()
        return pred()

    def cancel_owner(self, owner: object) -> int:
        """Cancel every armed timer tagged with ``owner`` — the teardown
        path for one node on a SHARED clock (chaos crash-restore): a dead
        Application's timers must never fire into freed subsystems while
        the rest of the simulated network keeps cranking.  Returns the
        number of timers cancelled."""
        n = 0
        for entry in list(self._timers):
            timer = entry[2]
            if timer.owner is owner and timer._live(entry[3]):
                timer.cancel()
                n += 1
        return n

    def stop(self) -> None:
        self._stopped = True


class VirtualTimer:
    """One-shot timer owned by a subsystem (ref VirtualTimer).

    expires_from_now/expires_at + async_wait(cb, on_cancel=None); cancel()
    invokes the cancel handler like asio's operation_aborted path.
    Cancel-and-rearm is safe: heap entries carry the arming generation, so
    a stale entry from before a cancel() can never fire a later callback.

    ``owner`` tags the timer with the object (typically the Application)
    whose lifetime bounds it, so ``VirtualClock.cancel_owner`` can sweep
    every timer of one node off a shared simulation clock.
    """

    def __init__(self, clock: VirtualClock, owner: object = None):
        self.clock = clock
        self.owner = owner
        self.cancelled = False
        self._cb: Optional[Callable[[], None]] = None
        self._on_cancel: Optional[Callable[[], None]] = None
        self._armed = False
        self._gen = 0  # bumped on every arm/cancel; heap entries snapshot it

    def expires_from_now(self, delay: float) -> None:
        self._deadline = self.clock.now() + delay

    def expires_at(self, deadline: float) -> None:
        self._deadline = deadline

    def async_wait(self, cb: Callable[[], None],
                   on_cancel: Optional[Callable[[], None]] = None) -> None:
        assert not self._armed, "timer already armed"
        self.cancelled = False
        self._cb = cb
        self._on_cancel = on_cancel
        self._armed = True
        self._gen += 1
        self.clock._enqueue_timer(self._deadline, self, self._gen)

    def cancel(self) -> None:
        if self._armed and not self.cancelled:
            self.cancelled = True
            self._armed = False
            self._gen += 1  # invalidate the outstanding heap entry
            if self._on_cancel is not None:
                cb = self._on_cancel
                self._on_cancel = None
                self.clock.post_action(cb)

    def _live(self, gen: int) -> bool:
        return not self.cancelled and self._armed and gen == self._gen

    def _fire(self) -> None:
        self._armed = False
        cb = self._cb
        self._cb = None
        if cb is not None:
            cb()
