"""Runtime lockdep witness — the dynamic half of detlint v3's
concurrency layer (tools/lint/concurrency.py is the static half).

``LOCKDEP=1`` in the environment turns every ``register_lock``-ed lock
into a witness wrapper that records the process-wide lock acquisition
ORDER graph and fails fast (raises) the moment two locks are ever taken
in opposite orders — the runtime analogue of ``conc-lock-cycle``, but
over the orders that actually happened instead of the orders the call
graph can prove possible.  ``guard_fields(obj)`` additionally installs
assert-held write hooks generated from the SAME ``# guarded-by:``
annotations the static rule reads: a guarded field assigned without its
annotated lock held by the current thread raises ``GuardViolation``.

Cost model
----------
Disabled (the default): ``register_lock`` returns the RAW lock object
and ``guard_fields`` is a no-op — zero per-acquire cost, better than
the one-attr-check budget.  Enabled: one thread-local stack push/pop
plus a set lookup per acquire on known orders; graph mutation only on
the FIRST occurrence of a new (outer, inner) pair.  The overhead gate
lives in tests/test_lockdep.py and tools/verify_green.py
--lockdep-smoke.

Known relaxations (mirrored in COVERAGE.md):
- reads of guarded fields are UNCHECKED — the close pipeline reads
  ``_hold``/``stats`` lock-free by design (benign-stale);
- module-level guarded globals (native/__init__.py ``_lib``) cannot be
  descriptor-wrapped — only their lock ORDER is witnessed;
- interior mutation (``d[k] = v`` on a guarded dict) does not pass
  through ``__set__`` — only rebinding the attribute is checked.
"""
from __future__ import annotations

import os
import threading
from typing import Dict, List, Optional, Set, Tuple

LOCKDEP_ENABLED = os.environ.get("LOCKDEP", "0") == "1"

_GUARD_COMMENT = "# guarded-by:"


class LockOrderInversion(AssertionError):
    """Two witnessed locks were acquired in opposite orders."""


class GuardViolation(AssertionError):
    """A guarded field was written without its annotated lock held."""


_tls = threading.local()
_graph_lock = threading.Lock()
# outer lock name -> set of lock names acquired while holding it
_edges: Dict[str, Set[str]] = {}           # guarded-by: _graph_lock
# (outer, inner) -> (thread name, held-stack snapshot) first witness
_witness: Dict[Tuple[str, str], tuple] = {}  # guarded-by: _graph_lock
_stats = {
    "locks": 0, "acquires": 0, "edges": 0,
    "inversions": 0, "guard_checks": 0, "guard_violations": 0,
}


def _stack() -> List[str]:
    st = getattr(_tls, "stack", None)
    if st is None:
        st = _tls.stack = []
    return st


def _reachable(src: str, dst: str) -> Optional[List[str]]:
    """A path src -> ... -> dst in the edge graph (holding _graph_lock),
    or None."""
    seen = {src}
    frontier = [(src, [src])]
    while frontier:
        node, path = frontier.pop()
        for nxt in _edges.get(node, ()):
            if nxt == dst:
                return path + [dst]
            if nxt not in seen:
                seen.add(nxt)
                frontier.append((nxt, path + [nxt]))
    return None


class WitnessLock:
    """Order-witnessing wrapper around a threading.Lock/RLock.

    Re-entrant acquires of the SAME witness name push/pop the held
    stack without re-recording edges, so wrapped RLocks keep their
    semantics and self-edges never appear in the graph."""

    __slots__ = ("_lock", "name")

    def __init__(self, lock, name: str):
        self._lock = lock
        self.name = name

    def held_by_me(self) -> bool:
        return self.name in _stack()

    def _note_acquired(self) -> None:
        # the slow-path twin of __enter__'s inline bookkeeping (plain
        # acquire() calls and the edge-recording loop both land here)
        st = _stack()
        _stats["acquires"] += 1
        if self.name not in st:
            for outer in st:
                self._note_edge(outer, self.name)
        st.append(self.name)

    def _note_edge(self, outer: str, inner: str) -> None:
        if outer == inner:
            return
        succ = _edges.get(outer)
        if succ is not None and inner in succ:
            return  # known-good order: the per-acquire fast path
        with _graph_lock:
            succ = _edges.setdefault(outer, set())
            if inner in succ:
                return
            back = _reachable(inner, outer)
            here = (threading.current_thread().name, list(_stack()))
            if back is not None:
                _stats["inversions"] += 1
                prior = " -> ".join(back)
                wit = _witness.get((back[0], back[1]))
                prior_at = f" (first witnessed on thread " \
                           f"{wit[0]!r}, held {wit[1]})" if wit else ""
                raise LockOrderInversion(
                    f"lock order inversion: acquiring {inner!r} while "
                    f"holding {outer!r}, but the established order is "
                    f"{prior}{prior_at}; current thread "
                    f"{here[0]!r} holds {here[1]}")
            succ.add(inner)
            _witness[(outer, inner)] = here
            _stats["edges"] += 1

    # -- lock protocol -------------------------------------------------------

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        got = self._lock.acquire(blocking, timeout)
        if got:
            self._note_acquired()
        return got

    def release(self) -> None:
        st = _stack()
        # pop the most recent occurrence (re-entrant releases unwind in
        # LIFO order); a foreign release order still unwinds correctly
        # because release() precedes the underlying lock's own error
        for i in range(len(st) - 1, -1, -1):
            if st[i] == self.name:
                del st[i]
                break
        self._lock.release()

    def __enter__(self) -> "WitnessLock":
        # the hot path: inlined bookkeeping, no helper frames.  The
        # common case (outermost acquire, empty held stack) touches one
        # thread-local attribute, one counter and one list append
        self._lock.acquire()
        try:
            st = _tls.stack
        except AttributeError:
            st = _tls.stack = []
        _stats["acquires"] += 1
        if st and self.name not in st:
            for outer in st:
                self._note_edge(outer, self.name)
        st.append(self.name)
        return self

    def __exit__(self, exc_type, exc, tb) -> None:
        st = _tls.stack
        if st and st[-1] == self.name:
            st.pop()  # LIFO release: the overwhelmingly common case
        else:
            for i in range(len(st) - 1, -1, -1):
                if st[i] == self.name:
                    del st[i]
                    break
        self._lock.release()


def register_lock(lock, name: str):
    """Wrap ``lock`` for order witnessing under LOCKDEP=1; return it
    untouched otherwise (zero steady-state cost when disabled)."""
    if not LOCKDEP_ENABLED:
        return lock
    _stats["locks"] += 1
    return WitnessLock(lock, name)


# ---------------------------------------------------------------------------
# assert-held write hooks from # guarded-by: annotations
# ---------------------------------------------------------------------------

class _GuardedField:
    """Class-level data descriptor enforcing the annotated lock on
    WRITES (reads are unchecked — see the module docstring).  Values
    live in the instance ``__dict__`` under the field's own name, so
    instances created before installation keep working and ``vars()``
    stays truthful."""

    __slots__ = ("field", "lock_attr")

    def __init__(self, field: str, lock_attr: str):
        self.field = field
        self.lock_attr = lock_attr

    def __get__(self, obj, objtype=None):
        if obj is None:
            return self
        try:
            return obj.__dict__[self.field]
        except KeyError:
            raise AttributeError(self.field) from None

    def __set__(self, obj, value) -> None:
        # hot path: instance-dict probes only, no getattr chains (this
        # runs on every post-init write to a guarded field)
        d = obj.__dict__
        if "_lockdep_enforced" in d:
            lock = d.get(self.lock_attr)
            if type(lock) is WitnessLock:
                _stats["guard_checks"] += 1
                try:
                    st = _tls.stack
                except AttributeError:
                    st = _tls.stack = []
                if lock.name not in st:
                    _stats["guard_violations"] += 1
                    raise GuardViolation(
                        f"write to {type(obj).__name__}."
                        f"{self.field} (guarded-by: "
                        f"{self.lock_attr}) without {lock.name!r} "
                        f"held by thread "
                        f"{threading.current_thread().name!r}")
        d[self.field] = value

    def __delete__(self, obj) -> None:
        self.__set__(obj, None)
        del obj.__dict__[self.field]


def _guard_table(cls) -> Dict[str, str]:
    """field -> lock attr parsed from the class source's
    ``# guarded-by:`` trailing annotations (the same lines detlint
    reads)."""
    import ast
    import inspect
    import textwrap

    try:
        src = textwrap.dedent(inspect.getsource(cls))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError, IndentationError):
        return {}
    lines = src.splitlines()
    table: Dict[str, str] = {}
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign):
            targets = [node.target]
        else:
            continue
        lock = None
        for ln in range(node.lineno,
                        (getattr(node, "end_lineno", node.lineno)
                         or node.lineno) + 1):
            if 1 <= ln <= len(lines) and _GUARD_COMMENT in lines[ln - 1]:
                lock = lines[ln - 1].split(_GUARD_COMMENT, 1)[1] \
                    .strip().split()[0]
                break
        if lock is None:
            continue
        for t in targets:
            if isinstance(t, ast.Attribute) and \
                    isinstance(t.value, ast.Name) and \
                    t.value.id == "self" and "lock" not in t.attr.lower():
                table[t.attr] = lock
    return table


_installed: Set[type] = set()


def guard_fields(obj) -> None:
    """Arm assert-held write hooks for ``obj``'s annotated fields.

    Call at the END of ``__init__`` (after the lock and every guarded
    field exist): descriptors install once per class, and enforcement
    for THIS instance starts only now — construction writes before the
    call are exempt (happens-before sharing).  No-op unless LOCKDEP=1.
    """
    if not LOCKDEP_ENABLED:
        return
    cls = type(obj)
    if cls not in _installed:
        with _graph_lock:
            if cls not in _installed:
                for fieldname, lock_attr in sorted(
                        _guard_table(cls).items()):
                    setattr(cls, fieldname,
                            _GuardedField(fieldname, lock_attr))
                _installed.add(cls)
    obj.__dict__["_lockdep_enforced"] = True


def stats() -> dict:
    """Witness counters snapshot (the lockdep smoke's zero-violation
    gate reads this)."""
    with _graph_lock:
        out = dict(_stats)
        out["enabled"] = LOCKDEP_ENABLED
        return out


def reset() -> None:
    """Tests only: drop the order graph and counters (NOT the installed
    descriptors — enforcement state is per-instance)."""
    with _graph_lock:
        _edges.clear()
        _witness.clear()
        for k in _stats:
            _stats[k] = 0
