"""Device auto-detection for the default-on TPU paths (VERDICT r3 #2).

A TPU-native node should use the TPU without flags: configs default the
crypto / SCP-tally backends to "auto", and the Application resolves them
here at construction.  The probe runs in a SUBPROCESS because a wedged
TPU relay blocks ``jax.devices()`` indefinitely and cannot be interrupted
in-process — and the probe child is NEVER killed: killing a client
mid-handshake re-wedges the exclusive relay for every later client
(round-3 postmortem, .claude/skills/verify/SKILL.md).  On timeout the
child is left to finish on its own and the node boots on the CPU tier.

The result is cached process-wide: one probe per process no matter how
many Applications are constructed (the in-process Simulation harness
builds dozens).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Optional

from .lockdep import register_lock

_lock = register_lock(threading.Lock(), "device.probe")
_result: Optional[bool] = None  # guarded-by: _lock


class DeviceProbe:
    """ONE probe subprocess, never killed.  ``wait`` returns True (an
    accelerator answered), False (probe exited without one), or None
    (still pending — the child is left running, NOT killed)."""

    def __init__(self):
        env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
        self.started = time.monotonic()
        try:
            self.proc: Optional[subprocess.Popen] = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; assert jax.devices()[0].platform != 'cpu'"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env)
        except OSError:
            self.proc = None

    def wait(self, budget: float) -> Optional[bool]:
        if self.proc is None:
            return False
        try:
            return self.proc.wait(budget) == 0
        except subprocess.TimeoutExpired:
            return None  # leave the probe running; do NOT kill it


def device_available(timeout: float = 10.0) -> bool:
    """True iff a JAX accelerator backend initializes within ``timeout``
    seconds (probed once per process; the probe child is never killed)."""
    global _result
    with _lock:
        if _result is not None:
            return _result
        _result = DeviceProbe().wait(timeout) is True
        return _result


def _reset_for_tests() -> None:
    global _result
    with _lock:
        _result = None


# -- compile-time hygiene (VERDICT r5 weak #1: 26-minute device compiles) ----

def enable_compilation_cache(cache_dir: Optional[str] = None
                             ) -> Optional[str]:
    """Point JAX at a persistent compilation cache so a second capture
    window (or a recompile after a tunnel drop) skips lowering+compile
    entirely.  Returns the cache dir, or None when it could not be set
    (old jax, read-only filesystem) — callers proceed uncached."""
    cache_dir = (cache_dir
                 or os.environ.get("JAX_COMPILATION_CACHE_DIR")
                 or os.path.join(os.path.expanduser("~"), ".cache",
                                 "stellar_core_tpu", "jax_cache"))
    try:
        import jax

        os.makedirs(cache_dir, exist_ok=True)
        jax.config.update("jax_compilation_cache_dir", cache_dir)
        # cache even fast compiles: the failure mode being bounded is a
        # 26-minute device compile, but re-warming hundreds of small
        # programs through a flaky tunnel adds up too
        try:
            jax.config.update(
                "jax_persistent_cache_min_compile_time_secs", 0.0)
        except Exception:
            pass  # knob name varies across jax versions; best effort
        return cache_dir
    except Exception:
        return None


# Fixed signature-batch sizes: every device verify pads its batch up to
# one of these, so admission traffic cannot present a new shape per close
# and trigger a recompile mid-capture.  Shapes are MXU-friendly powers of
# two; beyond the largest bucket, batches round up to its multiple.
SIG_BATCH_BUCKETS = (256, 512, 1024, 2048, 4096, 8192, 16384, 32768,
                     65536, 131072)


def pad_signature_batch(n: int) -> int:
    """Smallest allowed batch size >= n."""
    if n <= 0:
        return SIG_BATCH_BUCKETS[0]
    for b in SIG_BATCH_BUCKETS:
        if n <= b:
            return b
    top = SIG_BATCH_BUCKETS[-1]
    return ((n + top - 1) // top) * top
