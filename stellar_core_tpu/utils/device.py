"""Device auto-detection for the default-on TPU paths (VERDICT r3 #2).

A TPU-native node should use the TPU without flags: configs default the
crypto / SCP-tally backends to "auto", and the Application resolves them
here at construction.  The probe runs in a SUBPROCESS because a wedged
TPU relay blocks ``jax.devices()`` indefinitely and cannot be interrupted
in-process — and the probe child is NEVER killed: killing a client
mid-handshake re-wedges the exclusive relay for every later client
(round-3 postmortem, .claude/skills/verify/SKILL.md).  On timeout the
child is left to finish on its own and the node boots on the CPU tier.

The result is cached process-wide: one probe per process no matter how
many Applications are constructed (the in-process Simulation harness
builds dozens).
"""
from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from typing import Optional

_lock = threading.Lock()
_result: Optional[bool] = None


class DeviceProbe:
    """ONE probe subprocess, never killed.  ``wait`` returns True (an
    accelerator answered), False (probe exited without one), or None
    (still pending — the child is left running, NOT killed)."""

    def __init__(self):
        env = {k: v for k, v in os.environ.items() if k != "JAX_PLATFORMS"}
        self.started = time.monotonic()
        try:
            self.proc: Optional[subprocess.Popen] = subprocess.Popen(
                [sys.executable, "-c",
                 "import jax; assert jax.devices()[0].platform != 'cpu'"],
                stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
                env=env)
        except OSError:
            self.proc = None

    def wait(self, budget: float) -> Optional[bool]:
        if self.proc is None:
            return False
        try:
            return self.proc.wait(budget) == 0
        except subprocess.TimeoutExpired:
            return None  # leave the probe running; do NOT kill it


def device_available(timeout: float = 10.0) -> bool:
    """True iff a JAX accelerator backend initializes within ``timeout``
    seconds (probed once per process; the probe child is never killed)."""
    global _result
    with _lock:
        if _result is not None:
            return _result
        _result = DeviceProbe().wait(timeout) is True
        return _result


def _reset_for_tests() -> None:
    global _result
    with _lock:
        _result = None
