"""Native (C++) runtime components behind ctypes seams
(the reference's C++ runtime tier — SURVEY.md §7 architecture stance:
host-side merge/scan compute stays native; JAX/Pallas is the device
tier).

The library builds on first use with g++ (baked into the image) and
caches the .so next to the sources; every caller has a pure-Python
fallback, so a missing toolchain degrades gracefully.
"""
from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Optional

from ..utils.lockdep import register_lock

_DIR = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_DIR, "_native.so")
_SRCS = [os.path.join(_DIR, f) for f in ("bucket_merge.cpp",
                                         "quorum_enum.cpp")]

_lock = register_lock(threading.Lock(), "native.lib")
_lib: Optional[ctypes.CDLL] = None  # guarded-by: _lock
_tried = False  # guarded-by: _lock


def _src_digest(srcs) -> str:
    import hashlib

    h = hashlib.sha256()
    for s in srcs:
        with open(s, "rb") as f:
            h.update(f.read())
    return h.hexdigest()


def _write_srchash(so: str, srcs) -> None:
    tmp = f"{so}.srchash.{os.getpid()}.tmp"
    with open(tmp, "w") as f:
        f.write(_src_digest(srcs))
    os.replace(tmp, so + ".srchash")


def _stale(srcs, so: str) -> bool:
    """Content-hash staleness: each built .so carries a ``.srchash``
    sidecar recording its sources' digest.  mtimes are useless for the
    prebuilt kernels shipped in the tree — git writes checkout files in
    arbitrary order, so a source edit without a rebuild could win the
    mtime race and load an outdated consensus kernel silently."""
    if not os.path.exists(so):
        return True
    try:
        with open(so + ".srchash") as f:
            return f.read().strip() != _src_digest(srcs)
    except OSError:
        return True


def _build() -> bool:
    try:
        r = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-o", _SO + ".tmp"] + _SRCS,
            capture_output=True, timeout=120)
        if r.returncode != 0:
            return False
        os.replace(_SO + ".tmp", _SO)
        _write_srchash(_SO, _SRCS)
        return True
    except Exception:
        return False


def get_lib() -> Optional[ctypes.CDLL]:
    """The native library, building it on first use; None when
    unavailable (callers fall back to Python)."""
    global _lib, _tried
    with _lock:
        if _lib is not None or _tried:
            return _lib
        _tried = True
        if _stale(_SRCS, _SO):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError:
            return None
        lib.bucket_merge.restype = ctypes.c_int64
        lib.bucket_merge.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int32),
            ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32),
        ]
        if hasattr(lib, "bucket_merge_stream"):
            p64 = ctypes.POINTER(ctypes.c_int64)
            p32 = ctypes.POINTER(ctypes.c_int32)
            pu8 = ctypes.POINTER(ctypes.c_uint8)
            lib.bucket_merge_stream.restype = ctypes.c_int64
            lib.bucket_merge_stream.argtypes = [
                ctypes.c_char_p, p64, p32,        # new stream/eoff/elen
                ctypes.c_char_p, p64, p32, p32,   # new keys/koff/klen/types
                ctypes.c_int64,                   # n_new
                ctypes.c_char_p, p64, p32,        # old stream/eoff/elen
                ctypes.c_char_p, p64, p32, p32,   # old keys/koff/klen/types
                ctypes.c_int64,                   # n_old
                ctypes.c_char_p,                  # out_path (NULL = no file)
                p64, p32, p32,                    # out eoff/elen/types
                pu8, p64, p32,                    # out keys/koff/klen
                pu8, p64,                         # out_hash32, out_bytes
            ]
        if not hasattr(lib, "quorum_enum_check"):
            # stale prebuilt .so (mtime newer than sources but missing
            # newer symbols): degrade to the Python tiers rather than
            # crash callers that only need the older entry points
            _lib = lib
            return _lib
        lib.quorum_enum_check.restype = ctypes.c_int64
        lib.quorum_enum_check.argtypes = [
            ctypes.c_int32,                      # n_nodes
            ctypes.POINTER(ctypes.c_int32),      # top_thr [n]
            ctypes.POINTER(ctypes.c_uint64),     # top_mem [n*W]
            ctypes.POINTER(ctypes.c_int32),      # inner_off [n+1]
            ctypes.POINTER(ctypes.c_int32),      # inner_thr [total]
            ctypes.POINTER(ctypes.c_uint64),     # inner_mem [total*W]
            ctypes.POINTER(ctypes.c_int32),      # interrupt flag (polled)
            ctypes.c_int64,                      # max_calls (0 = unlimited)
            ctypes.POINTER(ctypes.c_uint64),     # out_q1 [W]
            ctypes.POINTER(ctypes.c_uint64),     # out_q2 [W]
            ctypes.POINTER(ctypes.c_int64),      # out_calls
        ]
        lib.bucket_lower_bound.restype = None
        lib.bucket_lower_bound.argtypes = [
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
            ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
            ctypes.POINTER(ctypes.c_int64),
        ]
        if hasattr(lib, "bloom_fill"):
            pu64 = ctypes.POINTER(ctypes.c_uint64)
            lib.bloom_fill.restype = None
            lib.bloom_fill.argtypes = [
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                pu64, ctypes.c_int64,
            ]
            lib.bloom_check.restype = None
            lib.bloom_check.argtypes = [
                pu64, ctypes.c_int64,
                ctypes.c_char_p, ctypes.POINTER(ctypes.c_int64),
                ctypes.POINTER(ctypes.c_int32), ctypes.c_int64,
                ctypes.POINTER(ctypes.c_int32),
            ]
        _lib = lib
        return _lib


# -- native XDR packer (CPython extension) -------------------------------

_XDRPACK_SRC = os.path.join(_DIR, "xdr_pack.c")
_XDRPACK_SO = os.path.join(_DIR, "_xdrpack.so")
_xdrpack_mod = None  # guarded-by: _lock
_xdrpack_tried = False  # guarded-by: _lock


def _build_extension(src: str, so: str) -> bool:
    """Compile one CPython extension source to ``so``; pid-unique tmp +
    atomic replace so concurrent first-builds can never interleave into
    one file and install a torn .so.  Shared by the xdrpack encoder and
    the apply kernel."""
    import sysconfig

    inc = sysconfig.get_paths()["include"]
    tmp = f"{so}.{os.getpid()}.tmp"
    try:
        r = subprocess.run(
            ["g++", "-O2", "-shared", "-fPIC", "-I", inc, "-o", tmp, src],
            capture_output=True, timeout=180)
        if r.returncode != 0:
            return False
        os.replace(tmp, so)
        _write_srchash(so, [src])
        return True
    except Exception:
        return False


def _load_extension(name: str, so: str):
    import importlib.util

    spec = importlib.util.spec_from_file_location(name, so)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ext_cached(name: str, src: str, so: str, mod, tried, build: bool):
    """The one caching contract for the CPython-extension kernels
    (caller holds ``_lock`` and passes/stores its module-level handle
    pair): one-shot ``tried`` semantics, the ``build=False`` early
    return that leaves a later ``build=True`` caller free to succeed,
    and content-hash staleness.  Returns the updated ``(mod, tried)``
    pair — keeping this logic in one place so a fix to the contract
    cannot drift between the extensions."""
    if mod is not None or tried:
        return mod, tried
    try:
        if _stale([src], so):
            if not build:
                return None, False  # not tried: build=True may succeed
            tried = True
            if not _build_extension(src, so):
                return None, True
        else:
            tried = True
        mod = _load_extension(name, so)
    except Exception:
        return None, True
    return mod, tried


# -- native apply kernel (CPython extension; see apply_kernel.cpp) -------

_APPLY_SRC = os.path.join(_DIR, "apply_kernel.cpp")
_APPLY_SO = os.path.join(_DIR, "_applykernel.so")
_applykernel_mod = None  # guarded-by: _lock
_applykernel_tried = False  # guarded-by: _lock


def get_apply_kernel(build: bool = True):
    """The _applykernel extension (GIL-free transaction-apply kernel);
    builds on first use, None when unavailable — callers fall back to
    the Python reference apply."""
    global _applykernel_mod, _applykernel_tried
    with _lock:
        _applykernel_mod, _applykernel_tried = _ext_cached(
            "_applykernel", _APPLY_SRC, _APPLY_SO,
            _applykernel_mod, _applykernel_tried, build)
        return _applykernel_mod


def get_xdrpack(build: bool = True):
    """The _xdrpack extension module (schema-driven XDR encoder); with
    ``build=False`` only an already-built fresh .so is loaded (imports
    stay cheap — node startup triggers the build).  None when
    unavailable."""
    global _xdrpack_mod, _xdrpack_tried
    with _lock:
        _xdrpack_mod, _xdrpack_tried = _ext_cached(
            "_xdrpack", _XDRPACK_SRC, _XDRPACK_SO,
            _xdrpack_mod, _xdrpack_tried, build)
        return _xdrpack_mod
