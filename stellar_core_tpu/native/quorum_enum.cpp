// Pruned min-quorum enumeration over word-packed node sets — the native
// host tier of the quorum-intersection checker (BASELINE config #3).
//
// Behavioral spec: the reference's MinQuorumEnumerator branch-and-bound
// (ref src/herder/QuorumIntersectionCheckerImpl.cpp:124 — early exits X1
// committed > |SCC|/2, X2 perimeter quorum must extend committed, X3
// committed contracts to a quorum: terminal, minimal ones examined for a
// disjoint complement quorum; split node by in-degree heuristic :59).
// This file is a fresh implementation against that spec: the search is an
// explicit stack (no recursion), the quorum cache is a capped hash map,
// the split heuristic is deterministic (ties -> highest index) so the
// Python/device enumerator in herder/quorum_intersection.py walks the
// *identical* tree and can be differential-tested call-for-call.
//
// Scope: 2-level quorum sets (the production org shape; matches the
// QSetTensor form in ops/quorum.py).  Deeper nesting stays on the Python
// path.  The caller passes node sets restricted to the scan SCC.

#include <cstdint>
#include <cstring>
#include <unordered_map>
#include <vector>

namespace {

using u64 = uint64_t;

struct Ctx {
    int n = 0;
    int W = 0;  // words per mask
    const int32_t* top_thr = nullptr;
    const u64* top_mem = nullptr;      // n * W
    const int32_t* inner_off = nullptr;  // n + 1
    const int32_t* inner_thr = nullptr;
    const u64* inner_mem = nullptr;    // total * W
    std::vector<u64> succ;             // n * W: all nodes i's qset references
    volatile int32_t* interrupt = nullptr;
    int64_t calls = 0;
    int64_t max_calls = 0;
    // isAQuorum cache keyed by mask words (ref mCachedQuorums :391)
    struct VecHash {
        size_t operator()(const std::vector<u64>& v) const {
            size_t h = 1469598103934665603ull;
            for (u64 w : v) {
                h ^= (size_t)w;
                h *= 1099511628211ull;
            }
            return h;
        }
    };
    std::unordered_map<std::vector<u64>, bool, VecHash> quorum_cache;
};

inline int popcount_and(const u64* a, const u64* b, int W) {
    int c = 0;
    for (int w = 0; w < W; ++w) c += __builtin_popcountll(a[w] & b[w]);
    return c;
}

inline bool get_bit(const u64* m, int i) {
    return (m[i >> 6] >> (i & 63)) & 1;
}

inline void set_bit(u64* m, int i) { m[i >> 6] |= (u64)1 << (i & 63); }
inline void clear_bit(u64* m, int i) { m[i >> 6] &= ~((u64)1 << (i & 63)); }

inline bool any(const u64* m, int W) {
    for (int w = 0; w < W; ++w)
        if (m[w]) return true;
    return false;
}

inline int popcount(const u64* m, int W) {
    int c = 0;
    for (int w = 0; w < W; ++w) c += __builtin_popcountll(m[w]);
    return c;
}

// Does `bs` satisfy node i's quorum slice?  Top-level member count plus
// satisfied inner sets must reach the threshold (2-level only; success /
// fail short-circuits like the reference's containsQuorumSlice :318).
bool contains_slice(const Ctx& c, const u64* bs, int node) {
    int thr = c.top_thr[node];
    if (thr <= 0) return false;
    int hits = popcount_and(bs, c.top_mem + (size_t)node * c.W, c.W);
    if (hits >= thr) return true;
    int lo = c.inner_off[node], hi = c.inner_off[node + 1];
    int need = thr - hits;
    if (need > hi - lo) return false;
    int fail_budget = (hi - lo) - need + 1;
    for (int k = lo; k < hi; ++k) {
        int ithr = c.inner_thr[k];
        bool ok = ithr > 0 &&
                  popcount_and(bs, c.inner_mem + (size_t)k * c.W, c.W) >= ithr;
        if (ok) {
            if (--need == 0) return true;
        } else {
            if (--fail_budget == 0) return false;
        }
    }
    return false;
}

// Greatest fixpoint of f(X) = {i in X | contains_slice(X, i)}
// (ref contractToMaximalQuorum :407).  In-place.
void contract(const Ctx& c, u64* m) {
    bool changed = true;
    while (changed) {
        changed = false;
        for (int i = 0; i < c.n; ++i) {
            if (get_bit(m, i) && !contains_slice(c, m, i)) {
                clear_bit(m, i);
                changed = true;
            }
        }
    }
}

bool is_a_quorum(Ctx& c, const u64* m) {
    std::vector<u64> key(m, m + c.W);
    auto it = c.quorum_cache.find(key);
    if (it != c.quorum_cache.end()) return it->second;
    std::vector<u64> t = key;
    contract(c, t.data());
    bool res = any(t.data(), c.W);
    if (c.quorum_cache.size() < (1u << 20)) c.quorum_cache.emplace(key, res);
    return res;
}

// No single-node removal leaves a subquorum (ref isMinimalQuorum :449).
bool is_minimal_quorum(Ctx& c, const u64* q) {
    std::vector<u64> probe(q, q + c.W);
    for (int i = 0; i < c.n; ++i) {
        if (!get_bit(q, i)) continue;
        clear_bit(probe.data(), i);
        if (is_a_quorum(c, probe.data())) return false;
        set_bit(probe.data(), i);
    }
    return true;
}

// Deterministic in-degree split heuristic (ref pickSplitNode :59,
// derandomized: ties resolve to the highest node index so the Python
// enumerator explores the same tree).
int pick_split(const Ctx& c, const u64* remaining,
               std::vector<int32_t>& indeg) {
    indeg.assign(c.n, 0);
    int max_node = -1;
    for (int i = c.n - 1; i >= 0; --i)
        if (get_bit(remaining, i)) {
            max_node = i;
            break;
        }
    for (int i = 0; i < c.n; ++i) {
        if (!get_bit(remaining, i)) continue;
        const u64* s = c.succ.data() + (size_t)i * c.W;
        for (int w = 0; w < c.W; ++w) {
            u64 bits = s[w] & remaining[w];
            while (bits) {
                int j = (w << 6) + __builtin_ctzll(bits);
                bits &= bits - 1;
                ++indeg[j];
            }
        }
    }
    int best = max_node, best_deg = 0;
    for (int j = 0; j < c.n; ++j) {
        if (!get_bit(remaining, j)) continue;
        if (indeg[j] >= best_deg && indeg[j] > 0) {
            best_deg = indeg[j];
            best = j;  // later index wins ties
        }
    }
    return best;
}

// Search frame.  `extq` carries the maximal quorum of this frame's
// perimeter (committed|remaining), computed incrementally: every quorum
// inside a set is a subset of the set's maximal quorum, so
//   - the include-child's perimeter is unchanged -> extq is inherited;
//   - the exclude-child only re-contracts when the split node was in extq,
//     and then seeds the fixpoint from extq\{split} instead of the whole
//     perimeter;
//   - contract(committed) (exit X3) seeds from committed&extq, and is only
//     re-evaluated on include-children (committed unchanged on exclude).
// This does at most ONE seeded contraction per call where the reference
// does two full ones (ref :159-225) — same tree, same exits.
//
// Frames are POD (fixed-width word arrays): the stack is bounded by tree
// depth (~2n frames), and pushing a child is a memcpy, not three heap
// allocations.
constexpr int W_MAX = 16;  // 1024-node scan ceiling (pubnet SCC is ~100)

struct Frame {
    u64 committed[W_MAX], remaining[W_MAX], extq[W_MAX];
    bool check_committed;
};

}  // namespace

extern "C" {

// Returns 1 = disjoint quorums found (out_q1/out_q2 filled),
//         0 = intersection holds, -1 = interrupted, -2 = call budget hit.
int64_t quorum_enum_check(int32_t n_nodes, const int32_t* top_thr,
                          const u64* top_mem, const int32_t* inner_off,
                          const int32_t* inner_thr, const u64* inner_mem,
                          volatile int32_t* interrupt, int64_t max_calls,
                          u64* out_q1, u64* out_q2, int64_t* out_calls) {
    Ctx c;
    c.n = n_nodes;
    c.W = (n_nodes + 63) / 64;
    c.top_thr = top_thr;
    c.top_mem = top_mem;
    c.inner_off = inner_off;
    c.inner_thr = inner_thr;
    c.inner_mem = inner_mem;
    c.interrupt = interrupt;
    c.max_calls = max_calls;

    // allSuccessors per node (ref QBitSet::getSuccessors)
    c.succ.assign((size_t)c.n * c.W, 0);
    for (int i = 0; i < c.n; ++i) {
        u64* s = c.succ.data() + (size_t)i * c.W;
        const u64* t = c.top_mem + (size_t)i * c.W;
        for (int w = 0; w < c.W; ++w) s[w] |= t[w];
        for (int k = c.inner_off[i]; k < c.inner_off[i + 1]; ++k) {
            const u64* im = c.inner_mem + (size_t)k * c.W;
            for (int w = 0; w < c.W; ++w) s[w] |= im[w];
        }
    }

    if (c.W > W_MAX) {
        *out_calls = 0;
        return -3;  // too many nodes for the native tier; Python handles
    }
    u64 scc[W_MAX] = {0};
    for (int i = 0; i < c.n; ++i) set_bit(scc, i);
    int max_commit = c.n / 2;

    std::vector<Frame> stack;
    stack.reserve(4 * c.n + 8);
    stack.emplace_back();
    {
        Frame& root = stack.back();
        std::memset(&root, 0, sizeof(Frame));
        std::memcpy(root.remaining, scc, c.W * 8);
        // root extq = maximal quorum of the whole SCC (caller guarantees
        // non-empty); committed = {} contracts empty by definition
        std::memcpy(root.extq, scc, c.W * 8);
        contract(c, root.extq);
        root.check_committed = false;
    }
    std::vector<int32_t> indeg;
    u64 tmp[W_MAX];

    while (!stack.empty()) {
        if (interrupt && *interrupt) {
            *out_calls = c.calls;
            return -1;
        }
        if (max_calls > 0 && c.calls >= max_calls) {
            *out_calls = c.calls;
            return -2;
        }
        Frame f = stack.back();
        stack.pop_back();
        ++c.calls;

        // X1: over half committed — complementary branches cover it
        if (popcount(f.committed, c.W) > max_commit) continue;

        // X3: committed contains a quorum — terminal either way.  Only
        // include-children re-evaluate (committed unchanged otherwise),
        // seeding the fixpoint from committed&extq (every quorum inside
        // committed lies inside the perimeter's maximal quorum).
        if (f.check_committed) {
            for (int w = 0; w < c.W; ++w)
                tmp[w] = f.committed[w] & f.extq[w];
            contract(c, tmp);
            if (any(tmp, c.W)) {
                if (is_minimal_quorum(c, tmp)) {
                    u64 comp[W_MAX];
                    for (int w = 0; w < c.W; ++w)
                        comp[w] = scc[w] & ~tmp[w];
                    contract(c, comp);
                    if (any(comp, c.W)) {
                        std::memcpy(out_q1, tmp, c.W * 8);
                        std::memcpy(out_q2, comp, c.W * 8);
                        *out_calls = c.calls;
                        return 1;
                    }
                }
                continue;
            }
        }

        // X2 invariants hold on arrival: extq is this frame's perimeter
        // quorum, already known non-empty and ⊇ committed (checked at
        // push time / for the root above).
        if (!any(f.remaining, c.W)) continue;  // exhausted

        int split = pick_split(c, f.remaining, indeg);

        // exclude child: perimeter loses `split`
        bool excl_ok = true;
        Frame excl = f;
        excl.check_committed = false;
        clear_bit(excl.remaining, split);
        if (get_bit(f.extq, split)) {
            // re-contract seeded from extq\{split}
            clear_bit(excl.extq, split);
            contract(c, excl.extq);
            if (!any(excl.extq, c.W)) {
                excl_ok = false;  // X2.1
            } else {
                for (int w = 0; w < c.W; ++w)
                    if (excl.committed[w] & ~excl.extq[w]) {
                        excl_ok = false;  // X2.2
                        break;
                    }
            }
        }

        // include child: perimeter (and extq) unchanged; committed grows,
        // so X2.2 reduces to `split ∈ extq`
        bool incl_ok = get_bit(f.extq, split);

        // stack order: include-branch popped first (matches the Python
        // enumerator's LIFO expansion).  Pruned children still count a
        // call, mirroring the reference recursing then exiting.
        if (excl_ok)
            stack.push_back(excl);
        else
            ++c.calls;
        if (incl_ok) {
            stack.push_back(f);
            Frame& incl = stack.back();
            std::memcpy(incl.remaining, excl.remaining, c.W * 8);
            set_bit(incl.committed, split);
            incl.check_committed = true;
        } else {
            ++c.calls;
        }
    }
    *out_calls = c.calls;
    return 0;
}

}  // extern "C"
