/* Native XDR encoder: a schema-driven packer for the combinator runtime
 * (stellar_core_tpu/xdr/runtime.py).  The Python side compiles each
 * XdrType tree into a flat node table (see runtime._compile_native_schema)
 * and hands it over once; pack(idx, value) then walks plain Python
 * objects (_StructValue.__dict__ / _UnionValue slots) in C, emitting the
 * canonical big-endian stream.
 *
 * This is the host-runtime analog of the reference's xdrpp codegen tier:
 * encoding dominates the ledger-close profile (meta + result + bucket +
 * SQL all serialize XDR), and the interpreted combinator walk was ~40%
 * of a 1000-tx close.  Wire bytes are identical by construction; the
 * Python packer stays as the differential oracle and fallback.
 *
 * Node kinds mirror the runtime combinators:
 *   0 INT32  1 UINT32  2 INT64  3 UINT64  4 BOOL
 *   5 OPAQUE_FIX(n)    6 OPAQUE_VAR(max)
 *   7 STRUCT(fields)   8 UNION(arms)      9 ARR_FIX(n, elem)
 *  10 ARR_VAR(max, elem)  11 OPTION(elem)  12 ENUM(valid-set)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

enum {
    K_INT32 = 0, K_UINT32, K_INT64, K_UINT64, K_BOOL,
    K_OPAQUE_FIX, K_OPAQUE_VAR, K_STRUCT, K_UNION, K_ARR_FIX,
    K_ARR_VAR, K_OPTION, K_ENUM
};

typedef struct {
    PyObject *name;   /* interned field name */
    int32_t type_idx;
} Field;

typedef struct {
    int32_t has_arm;  /* 0 = void */
    int32_t type_idx;
} Arm;

typedef struct {
    int kind;
    int64_t n;            /* fixed len / max len / field count */
    Field *fields;        /* K_STRUCT */
    PyObject *arm_map;    /* K_UNION: dict disc -> (has_arm, idx) or None */
    Arm default_arm;      /* K_UNION: used when arm_map misses */
    int has_default;
    int32_t elem;         /* arrays / option */
    PyObject *valid;      /* K_ENUM: frozenset of valid values */
    PyObject *memo_key;   /* the Python XdrType object for memo identity,
                             or NULL when not memoized */
} Node;

typedef struct {
    char *buf;
    Py_ssize_t len, cap;
} Out;

static PyObject *XdrErrorCls;   /* set at init_schema */
static Node *g_nodes;
static Py_ssize_t g_count;

static int
out_reserve(Out *o, Py_ssize_t extra)
{
    if (o->len + extra <= o->cap)
        return 0;
    Py_ssize_t ncap = o->cap ? o->cap * 2 : 512;
    while (ncap < o->len + extra)
        ncap *= 2;
    char *nb = (char *)PyMem_Realloc(o->buf, ncap);
    if (!nb) {
        PyErr_NoMemory();
        return -1;
    }
    o->buf = nb;
    o->cap = ncap;
    return 0;
}

static inline int
emit_u32(Out *o, uint32_t v)
{
    if (out_reserve(o, 4) < 0)
        return -1;
    o->buf[o->len++] = (char)(v >> 24);
    o->buf[o->len++] = (char)(v >> 16);
    o->buf[o->len++] = (char)(v >> 8);
    o->buf[o->len++] = (char)v;
    return 0;
}

static inline int
emit_u64(Out *o, uint64_t v)
{
    if (emit_u32(o, (uint32_t)(v >> 32)) < 0)
        return -1;
    return emit_u32(o, (uint32_t)v);
}

static int
emit_bytes(Out *o, const char *p, Py_ssize_t n, int pad)
{
    Py_ssize_t padded = pad ? (n + 3) & ~(Py_ssize_t)3 : n;
    if (out_reserve(o, padded) < 0)
        return -1;
    memcpy(o->buf + o->len, p, n);
    if (padded > n)
        memset(o->buf + o->len + n, 0, padded - n);
    o->len += padded;
    return 0;
}

static int pack_node(int32_t idx, PyObject *v, Out *o);

static int
err(const char *msg)
{
    PyErr_SetString(XdrErrorCls, msg);
    return -1;
}

static int
pack_long_checked(PyObject *v, int64_t lo_is_min64, uint64_t hi, int is64,
                  int is_signed, Out *o)
{
    int overflow = 0;
    long long x;
    if (!PyLong_Check(v)) {
        if (PyBool_Check(v))
            x = (v == Py_True);
        else
            return err("expected int");
        overflow = 0;
    } else {
        x = PyLong_AsLongLongAndOverflow(v, &overflow);
        if (x == -1 && PyErr_Occurred())
            return -1;
    }
    if (is_signed) {
        if (overflow)
            return err("int out of range");
        if (!is64 && (x < INT32_MIN || x > INT32_MAX))
            return err("int out of range");
        if (is64)
            return emit_u64(o, (uint64_t)x);
        return emit_u32(o, (uint32_t)(int32_t)x);
    }
    /* unsigned */
    if (overflow > 0 || x < 0) {
        if (overflow > 0 && is64) {
            /* 2^63..2^64-1: retake as unsigned */
            unsigned long long ux = PyLong_AsUnsignedLongLong(v);
            if (ux == (unsigned long long)-1 && PyErr_Occurred()) {
                PyErr_Clear();
                return err("int out of range");
            }
            return emit_u64(o, (uint64_t)ux);
        }
        return err("int out of range");
    }
    if (overflow)
        return err("int out of range");
    if (!is64 && (uint64_t)x > hi)
        return err("int out of range");
    if (is64)
        return emit_u64(o, (uint64_t)x);
    return emit_u32(o, (uint32_t)x);
}

static int
pack_struct(Node *nd, PyObject *v, Out *o)
{
    PyObject *d = PyObject_GetAttrString(v, "__dict__");
    if (!d)
        return -1;
    if (!PyDict_Check(d)) {
        Py_DECREF(d);
        return err("struct value has no dict");
    }
    for (int64_t i = 0; i < nd->n; i++) {
        PyObject *fv = PyDict_GetItemWithError(d, nd->fields[i].name);
        if (!fv) {
            Py_DECREF(d);
            if (!PyErr_Occurred())
                PyErr_Format(XdrErrorCls, "missing struct field %U",
                             nd->fields[i].name);
            return -1;
        }
        if (pack_node(nd->fields[i].type_idx, fv, o) < 0) {
            Py_DECREF(d);
            return -1;
        }
    }
    Py_DECREF(d);
    return 0;
}

static int
pack_union(Node *nd, PyObject *v, Out *o)
{
    PyObject *disc = PyObject_GetAttrString(v, "type");
    if (!disc)
        return -1;
    long long dv = PyLong_AsLongLong(disc);
    if (dv == -1 && PyErr_Occurred()) {
        Py_DECREF(disc);
        return -1;
    }
    if (nd->valid) {
        int c = PySet_Contains(nd->valid, disc);
        if (c < 0) {
            Py_DECREF(disc);
            return -1;
        }
        if (!c) {
            Py_DECREF(disc);
            return err("bad enum value for union discriminant");
        }
    }
    int has_arm;
    int32_t arm_idx;
    PyObject *ent = PyDict_GetItemWithError(nd->arm_map, disc);
    Py_DECREF(disc);
    if (ent) {
        has_arm = PyLong_AsLong(PyTuple_GET_ITEM(ent, 0));
        arm_idx = (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(ent, 1));
    } else if (PyErr_Occurred()) {
        return -1;
    } else if (nd->has_default) {
        has_arm = nd->default_arm.has_arm;
        arm_idx = nd->default_arm.type_idx;
    } else {
        return err("no union arm for discriminant");
    }
    if (dv < INT32_MIN || dv > INT32_MAX)
        return err("union discriminant out of range");
    if (emit_u32(o, (uint32_t)(int32_t)dv) < 0)
        return -1;
    if (has_arm) {
        PyObject *av = PyObject_GetAttrString(v, "value");
        if (!av)
            return -1;
        int r = pack_node(arm_idx, av, o);
        Py_DECREF(av);
        return r;
    } else {
        PyObject *av = PyObject_GetAttrString(v, "value");
        if (!av)
            return -1;
        int bad = (av != Py_None);
        Py_DECREF(av);
        if (bad)
            return err("void arm carries a value");
    }
    return 0;
}

static int
pack_node(int32_t idx, PyObject *v, Out *o)
{
    Node *nd = &g_nodes[idx];
    switch (nd->kind) {
    case K_INT32:
        return pack_long_checked(v, 0, 0, 0, 1, o);
    case K_UINT32:
        return pack_long_checked(v, 0, UINT32_MAX, 0, 0, o);
    case K_INT64:
        return pack_long_checked(v, 0, 0, 1, 1, o);
    case K_UINT64:
        return pack_long_checked(v, 0, UINT64_MAX, 1, 0, o);
    case K_BOOL: {
        int t = PyObject_IsTrue(v);
        if (t < 0)
            return -1;
        return emit_u32(o, (uint32_t)t);
    }
    case K_ENUM: {
        int c = PySet_Contains(nd->valid, v);
        if (c < 0)
            return -1;
        if (!c)
            return err("bad enum value");
        long long x = PyLong_AsLongLong(v);
        if (x == -1 && PyErr_Occurred())
            return -1;
        return emit_u32(o, (uint32_t)(int32_t)x);
    }
    case K_OPAQUE_FIX: {
        /* mirror Opaque.pack: len(v) first, then bytes(v) coercion
         * (bytearray/memoryview accepted; int rejected by len()) */
        Py_ssize_t n = PyObject_Length(v);
        if (n < 0) {
            PyErr_Clear();
            return err("opaque expects a bytes-like value");
        }
        if (n != nd->n)
            return err("opaque length mismatch");
        PyObject *b = PyBytes_Check(v) ? Py_NewRef(v)
                                       : PyBytes_FromObject(v);
        if (!b) {
            PyErr_Clear();
            return err("opaque expects a bytes-like value");
        }
        int r = emit_bytes(o, PyBytes_AS_STRING(b),
                           PyBytes_GET_SIZE(b), 1);
        Py_DECREF(b);
        return r;
    }
    case K_OPAQUE_VAR: {
        Py_ssize_t n = PyObject_Length(v);
        if (n < 0) {
            PyErr_Clear();
            return err("opaque expects a bytes-like value");
        }
        if ((uint64_t)n > (uint64_t)nd->n)
            return err("opaque too long");
        PyObject *b = PyBytes_Check(v) ? Py_NewRef(v)
                                       : PyBytes_FromObject(v);
        if (!b) {
            PyErr_Clear();
            return err("opaque expects a bytes-like value");
        }
        if (emit_u32(o, (uint32_t)n) < 0) {
            Py_DECREF(b);
            return -1;
        }
        int r = emit_bytes(o, PyBytes_AS_STRING(b),
                           PyBytes_GET_SIZE(b), 1);
        Py_DECREF(b);
        return r;
    }
    case K_STRUCT: {
        if (nd->memo_key) {
            /* memoized: reuse / populate the value-side cache exactly
             * like Struct.pack does ('_xdr_enc' dict entry) */
            PyObject *d = PyObject_GetAttrString(v, "__dict__");
            if (!d)
                return -1;
            PyObject *hit = PyDict_GetItemString(d, "_xdr_enc");
            if (hit && PyTuple_Check(hit) &&
                PyTuple_GET_ITEM(hit, 0) == nd->memo_key) {
                PyObject *enc = PyTuple_GET_ITEM(hit, 1);
                int r = emit_bytes(o, PyBytes_AS_STRING(enc),
                                   PyBytes_GET_SIZE(enc), 0);
                Py_DECREF(d);
                return r;
            }
            Py_ssize_t start = o->len;
            if (pack_struct(nd, v, o) < 0) {
                Py_DECREF(d);
                return -1;
            }
            PyObject *enc = PyBytes_FromStringAndSize(o->buf + start,
                                                      o->len - start);
            if (enc) {
                PyObject *tup = PyTuple_Pack(2, nd->memo_key, enc);
                if (tup) {
                    PyDict_SetItemString(d, "_xdr_enc", tup);
                    Py_DECREF(tup);
                }
                Py_DECREF(enc);
            } else {
                PyErr_Clear();
            }
            Py_DECREF(d);
            return 0;
        }
        return pack_struct(nd, v, o);
    }
    case K_UNION: {
        if (nd->memo_key) {
            PyObject *hit = PyObject_GetAttrString(v, "_enc");
            if (!hit)
                return -1;
            if (PyTuple_Check(hit) &&
                PyTuple_GET_ITEM(hit, 0) == nd->memo_key) {
                PyObject *enc = PyTuple_GET_ITEM(hit, 1);
                int r = emit_bytes(o, PyBytes_AS_STRING(enc),
                                   PyBytes_GET_SIZE(enc), 0);
                Py_DECREF(hit);
                return r;
            }
            Py_DECREF(hit);
            Py_ssize_t start = o->len;
            if (pack_union(nd, v, o) < 0)
                return -1;
            PyObject *enc = PyBytes_FromStringAndSize(o->buf + start,
                                                      o->len - start);
            if (enc) {
                PyObject *tup = PyTuple_Pack(2, nd->memo_key, enc);
                if (tup) {
                    if (PyObject_SetAttrString(v, "_enc", tup) < 0)
                        PyErr_Clear();
                    Py_DECREF(tup);
                }
                Py_DECREF(enc);
            } else {
                PyErr_Clear();
            }
            return 0;
        }
        return pack_union(nd, v, o);
    }
    case K_ARR_FIX: {
        PyObject *seq = PySequence_Fast(v, "array expects a sequence");
        if (!seq)
            return -1;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
        if (n != nd->n) {
            Py_DECREF(seq);
            return err("bad array length");
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            if (pack_node(nd->elem, PySequence_Fast_GET_ITEM(seq, i),
                          o) < 0) {
                Py_DECREF(seq);
                return -1;
            }
        }
        Py_DECREF(seq);
        return 0;
    }
    case K_ARR_VAR: {
        PyObject *seq = PySequence_Fast(v, "array expects a sequence");
        if (!seq)
            return -1;
        Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);
        if ((uint64_t)n > (uint64_t)nd->n) {
            Py_DECREF(seq);
            return err("array too long");
        }
        if (emit_u32(o, (uint32_t)n) < 0) {
            Py_DECREF(seq);
            return -1;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            if (pack_node(nd->elem, PySequence_Fast_GET_ITEM(seq, i),
                          o) < 0) {
                Py_DECREF(seq);
                return -1;
            }
        }
        Py_DECREF(seq);
        return 0;
    }
    case K_OPTION: {
        if (v == Py_None)
            return emit_u32(o, 0);
        if (emit_u32(o, 1) < 0)
            return -1;
        return pack_node(nd->elem, v, o);
    }
    }
    return err("corrupt schema node");
}

/* init_schema(nodes, xdr_error_cls)
 * nodes: list of tuples
 *   (kind, n, fields, arm_map, default_arm, elem, valid, memo_key)
 *   fields: tuple of (name, idx) or None
 *   arm_map: dict {disc: (has_arm, idx)} or None
 *   default_arm: (has_arm, idx) or None
 */
/* release a partially-built node table (nodes 0..upto inclusive) when
 * init aborts mid-loop; field name slots are calloc-zeroed, so XDECREF
 * is safe for the node whose fields were still being filled */
static void
free_partial_tab(Node *tab, Py_ssize_t upto)
{
    for (Py_ssize_t k = 0; k <= upto; k++) {
        Node *nd = &tab[k];
        if (nd->fields) {
            for (Py_ssize_t j = 0; j < nd->n; j++)
                Py_XDECREF(nd->fields[j].name);
            PyMem_Free(nd->fields);
        }
        Py_XDECREF(nd->arm_map);
        Py_XDECREF(nd->valid);
        Py_XDECREF(nd->memo_key);
    }
    PyMem_Free(tab);
}

static PyObject *
py_init_schema(PyObject *self, PyObject *args)
{
    PyObject *nodes, *errcls;
    if (!PyArg_ParseTuple(args, "OO", &nodes, &errcls))
        return NULL;
    if (g_nodes) {
        PyErr_SetString(PyExc_RuntimeError,
                        "xdr_pack schema already initialized");
        return NULL;
    }
    Py_ssize_t count = PyList_GET_SIZE(nodes);
    Node *tab = (Node *)PyMem_Calloc(count, sizeof(Node));
    if (!tab)
        return PyErr_NoMemory();
    for (Py_ssize_t i = 0; i < count; i++) {
        PyObject *t = PyList_GET_ITEM(nodes, i);
        Node *nd = &tab[i];
        nd->kind = (int)PyLong_AsLong(PyTuple_GET_ITEM(t, 0));
        nd->n = PyLong_AsLongLong(PyTuple_GET_ITEM(t, 1));
        PyObject *fields = PyTuple_GET_ITEM(t, 2);
        if (fields != Py_None) {
            Py_ssize_t nf = PyTuple_GET_SIZE(fields);
            nd->n = nf;
            nd->fields = (Field *)PyMem_Calloc(nf, sizeof(Field));
            if (!nd->fields) {
                free_partial_tab(tab, i);
                return PyErr_NoMemory();
            }
            for (Py_ssize_t j = 0; j < nf; j++) {
                PyObject *f = PyTuple_GET_ITEM(fields, j);
                PyObject *nm = PyTuple_GET_ITEM(f, 0);
                Py_INCREF(nm);
                nd->fields[j].name = nm;
                nd->fields[j].type_idx =
                    (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(f, 1));
            }
        }
        PyObject *arm_map = PyTuple_GET_ITEM(t, 3);
        if (arm_map != Py_None) {
            Py_INCREF(arm_map);
            nd->arm_map = arm_map;
        }
        PyObject *defarm = PyTuple_GET_ITEM(t, 4);
        if (defarm != Py_None) {
            nd->has_default = 1;
            nd->default_arm.has_arm =
                (int)PyLong_AsLong(PyTuple_GET_ITEM(defarm, 0));
            nd->default_arm.type_idx =
                (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(defarm, 1));
        }
        nd->elem = (int32_t)PyLong_AsLong(PyTuple_GET_ITEM(t, 5));
        PyObject *valid = PyTuple_GET_ITEM(t, 6);
        if (valid != Py_None) {
            Py_INCREF(valid);
            nd->valid = valid;
        }
        PyObject *memo = PyTuple_GET_ITEM(t, 7);
        if (memo != Py_None) {
            Py_INCREF(memo);
            nd->memo_key = memo;
        }
    }
    Py_INCREF(errcls);
    XdrErrorCls = errcls;
    g_nodes = tab;
    g_count = count;
    Py_RETURN_NONE;
}

static PyObject *
py_pack(PyObject *self, PyObject *args)
{
    Py_ssize_t idx;
    PyObject *v;
    if (!PyArg_ParseTuple(args, "nO", &idx, &v))
        return NULL;
    if (!g_nodes || idx < 0 || idx >= g_count) {
        PyErr_SetString(PyExc_RuntimeError, "schema not initialized");
        return NULL;
    }
    Out o = {NULL, 0, 0};
    if (pack_node((int32_t)idx, v, &o) < 0) {
        PyMem_Free(o.buf);
        return NULL;
    }
    PyObject *res = PyBytes_FromStringAndSize(o.buf, o.len);
    PyMem_Free(o.buf);
    return res;
}

/* pack_many(items): one C call for a whole batch of encodes.
 * items = sequence of (type_index, value); returns [bytes, ...].
 * Every value is packed into one shared arena first (the object walk
 * needs the interpreter, but memoized structs/unions resolve to a
 * single lookup + arena append), then the per-row copy-out into the
 * preallocated bytes objects runs with the GIL RELEASED — on the
 * pipelined tail worker that is the window the next close's fee/apply
 * phases reclaim. */
static PyObject *
py_pack_many(PyObject *self, PyObject *args)
{
    PyObject *items;
    if (!PyArg_ParseTuple(args, "O", &items))
        return NULL;
    if (!g_nodes) {
        PyErr_SetString(PyExc_RuntimeError, "schema not initialized");
        return NULL;
    }
    PyObject *seq = PySequence_Fast(items, "pack_many expects a sequence");
    if (!seq)
        return NULL;
    Py_ssize_t n = PySequence_Fast_GET_SIZE(seq);

    Out o = {NULL, 0, 0};
    size_t *offs = (size_t *)PyMem_Malloc(sizeof(size_t) * (size_t)(n + 1));
    if (!offs) {
        Py_DECREF(seq);
        return PyErr_NoMemory();
    }
    for (Py_ssize_t i = 0; i < n; i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *io = PyTuple_GetItem(it, 0);
        PyObject *v = PyTuple_GetItem(it, 1);
        Py_ssize_t idx = io ? PyLong_AsSsize_t(io) : -1;
        if (!io || !v || (idx == -1 && PyErr_Occurred()) ||
            idx < 0 || idx >= g_count) {
            if (!PyErr_Occurred())
                PyErr_SetString(PyExc_RuntimeError,
                                "pack_many: bad (index, value) item");
            goto fail;
        }
        offs[i] = o.len;
        if (pack_node((int32_t)idx, v, &o) < 0)
            goto fail;
    }
    offs[n] = o.len;

    {
        /* snapshot every destination buffer pointer WITH the GIL held;
         * the GIL-released region below touches only raw memory */
        char **dsts = (char **)PyMem_Malloc(sizeof(char *) * (size_t)n);
        PyObject *res = PyList_New(n);
        if (!res || !dsts) {
            Py_XDECREF(res);
            PyMem_Free(dsts);
            if (!PyErr_Occurred())
                PyErr_NoMemory();
            goto fail;
        }
        for (Py_ssize_t i = 0; i < n; i++) {
            PyObject *b = PyBytes_FromStringAndSize(
                NULL, (Py_ssize_t)(offs[i + 1] - offs[i]));
            if (!b) {
                Py_DECREF(res);
                PyMem_Free(dsts);
                goto fail;
            }
            dsts[i] = PyBytes_AS_STRING(b);
            PyList_SET_ITEM(res, i, b);
        }
        Py_BEGIN_ALLOW_THREADS;
        for (Py_ssize_t i = 0; i < n; i++)
            memcpy(dsts[i], o.buf + offs[i], offs[i + 1] - offs[i]);
        Py_END_ALLOW_THREADS;
        PyMem_Free(dsts);
        PyMem_Free(offs);
        PyMem_Free(o.buf);
        Py_DECREF(seq);
        return res;
    }

fail:
    PyMem_Free(offs);
    PyMem_Free(o.buf);
    Py_DECREF(seq);
    return NULL;
}

static PyMethodDef methods[] = {
    {"init_schema", py_init_schema, METH_VARARGS,
     "Install the compiled node table (one-shot)."},
    {"pack", py_pack, METH_VARARGS,
     "pack(type_index, value) -> canonical XDR bytes."},
    {"pack_many", py_pack_many, METH_VARARGS,
     "pack_many([(type_index, value), ...]) -> [bytes, ...] in one "
     "native call (copy-out phase GIL-released)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_xdrpack", NULL, -1, methods,
};

PyMODINIT_FUNC
PyInit__xdrpack(void)
{
    return PyModule_Create(&moduledef);
}
