/* Native GIL-free transaction-apply kernel.
 *
 * PR-5's footprint->cluster->executor stack proved bit-identical
 * parallel apply but lost wall clock to the GIL: cluster workers
 * time-slice one interpreter.  This kernel cashes that machinery in —
 * a cluster whose transactions are all kernel-eligible hands over
 * packed XDR (entries, materialized order-book rows, per-tx
 * descriptors), the kernel applies the whole strip with exact
 * 64/128-bit integer arithmetic while the GIL is RELEASED, and returns
 * entry deltas plus pre-encoded TransactionMeta / TransactionResult
 * bytes that the merge/hash/commit phases consume exactly as they
 * consume the Python workers' output today.
 *
 * Covered op strip (kernel-complete for the op types dominating real
 * Stellar traffic — ISSUE 13 closes the credit/path/modify gap PR 6
 * left open):
 *   - PAYMENT, native AND credit assets (trustline balance edges,
 *     AUTHORIZED gate, issuer-source / issuer-dest mint-burn cases);
 *   - CHANGE_TRUST, classic assets: trustline create (issuer flag
 *     derivation, subentry reserve), limit update, and delete;
 *   - MANAGE_SELL_OFFER, offerID=0 (create) AND offerID!=0
 *     (modify/delete): load the resting offer from the packed
 *     snapshot, release old liabilities, re-run the crossing loop,
 *     re-post or delete; full exchangeV10 crossing mirroring
 *     transactions/offer_exchange.py (adjustOffer, liabilities
 *     acquire/release, price-error thresholds, claim atoms);
 *   - PATH_PAYMENT_STRICT_SEND / _RECEIVE over declared hop pairs:
 *     the multi-hop chain walk with per-hop send/receive propagation,
 *     the strict-send/strict-receive rounding modes, max-path-length
 *     and self-crossing guards.  A LIVE constant-product pool on a hop
 *     is QUOTED in-kernel (book-vs-pool arbitration mirroring
 *     convert_with_offers_and_pools); pool deposit/withdraw stay
 *     host-side.
 *
 * Beyond apply_cluster, charge_fees() batches the whole fee/seqnum
 * phase: one GIL-released call charges every tx's fee against the
 * packed source-account snapshot and returns per-tx pre-encoded
 * feeProcessing LedgerEntryChanges plus final account images.
 *
 * Parity discipline: the kernel implements ONLY the success paths.
 * Any ineligible shape, unexpected entry state, failing check, or
 * arithmetic-path divergence raises KernelDecline and the WHOLE
 * cluster falls back to the Python reference apply — which remains the
 * bit-identical oracle.  Every parsed entry is round-trip re-encoded
 * and compared against its input bytes, so a shape the encoder does
 * not model exactly can never silently produce divergent meta.
 *
 * Interface (dispatch layer: stellar_core_tpu/apply/native_apply.py):
 *   apply_cluster(params, entries, books, txs)
 *     params  = (ledger_seq, close_time, base_fee, base_reserve,
 *                idpool0)
 *     entries = [(key_bytes, entry_bytes|None), ...]
 *     books   = [(selling_asset, buying_asset, [key_bytes, ...]), ...]
 *     txs     = per-tx tuples, see parse_txs()
 *   -> (True, [(key, entry_bytes|None)...], [(meta, result)...], idpool)
 *    | (False, reason, tx_index)
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#include <algorithm>
#include <map>
#include <string>
#include <vector>

typedef __int128 i128;
static const int64_t INT64_MAX_ = 9223372036854775807LL;
static const uint32_t ACCOUNT_SUBENTRY_LIMIT = 1000;
static const int MAX_OFFERS_TO_CROSS = 1000;
/* longest effective conversion chain: 5 path entries + send + dest
 * assets = 6 hops (xdr/types.py VarArray(Asset, 5) path bound) */
static const int MAX_PATH_HOPS = 6;

/* OperationType values (xdr/types.py) */
enum {
    OP_PAYMENT = 1,
    OP_PATH_PAYMENT_STRICT_RECEIVE = 2,
    OP_MANAGE_SELL_OFFER = 3,
    OP_CHANGE_TRUST = 6,
    OP_PATH_PAYMENT_STRICT_SEND = 13,
};
/* LedgerEntryType */
enum { LE_ACCOUNT = 0, LE_TRUSTLINE = 1, LE_OFFER = 2,
       LE_LIQUIDITY_POOL = 5 };
/* LedgerEntryChangeType */
enum { CH_CREATED = 0, CH_UPDATED = 1, CH_REMOVED = 2, CH_STATE = 3 };
/* trustline flags */
static const uint32_t AUTHORIZED_FLAG = 1;
static const uint32_t MAINTAIN_LIABILITIES_FLAG = 2;
static const uint32_t TL_CLAWBACK_FLAG = 4;
/* account flags consulted by changeTrust's new-trustline derivation */
static const uint32_t ACC_AUTH_REQUIRED_FLAG = 1;
static const uint32_t ACC_AUTH_CLAWBACK_FLAG = 8;
/* offer flags */
static const uint32_t PASSIVE_FLAG = 1;
/* TrustLineEntry extension discriminants (liability XDR tags) */
enum { TL_EXT_V1 = 1, TL_V1_EXT_V2 = 2 };
/* AccountEntry extension discriminants (the v1/v2/v3 seqnum chain) */
enum { ACC_EXT_V1 = 1, ACC_EXT_V2 = 2, ACC_EXT_V3 = 3 };
/* liquidity pools: constant-product only; the quote math denominates
 * fees in basis points and the protocol pins the pool fee at 30 bps
 * (types.py LIQUIDITY_POOL_FEE_V18) */
static const int32_t POOL_FEE_V18 = 30;
static const int32_t POOL_MAX_BPS = 10000;
/* fee charge: base_fee scales with max(FEE_OPS_FLOOR, numOperations)
 * (frame.process_fee_seq_num) */
static const int64_t FEE_OPS_FLOOR = 1;
/* ManageOfferEffect */
enum { EFF_CREATED = 0, EFF_UPDATED = 1, EFF_DELETED = 2 };
/* offer_exchange.RoundingType */
enum { ROUND_NORMAL = 0, ROUND_PP_STRICT_RECEIVE = 1,
       ROUND_PP_STRICT_SEND = 2 };

struct Decline {
    std::string reason;
    Decline(const std::string &r) : reason(r) {}
};

static void need(bool ok, const char *why) {
    if (!ok)
        throw Decline(why);
}

/* ---------------------------------------------------------------- xdr io */

struct Rd {
    const uint8_t *p;
    size_t n, pos;
    Rd(const std::string &s)
        : p((const uint8_t *)s.data()), n(s.size()), pos(0) {}
    uint32_t u32() {
        need(pos + 4 <= n, "entry parse: short read");
        uint32_t v = ((uint32_t)p[pos] << 24) | ((uint32_t)p[pos + 1] << 16) |
                     ((uint32_t)p[pos + 2] << 8) | (uint32_t)p[pos + 3];
        pos += 4;
        return v;
    }
    int32_t i32() { return (int32_t)u32(); }
    uint64_t u64() {
        uint64_t hi = u32();
        return (hi << 32) | u32();
    }
    int64_t i64() { return (int64_t)u64(); }
    std::string take(size_t k) {
        need(pos + k <= n, "entry parse: short read");
        std::string out((const char *)p + pos, k);
        pos += k;
        return out;
    }
    std::string opaque_var(size_t maxlen) {
        uint32_t len = u32();
        need(len <= maxlen, "entry parse: opaque too long");
        std::string body = take(len);
        size_t pad = (4 - len % 4) % 4;
        for (size_t i = 0; i < pad; i++)
            need(take(1)[0] == 0, "entry parse: nonzero pad");
        return body;
    }
    bool done() const { return pos == n; }
};

struct Wr {
    std::string out;
    void u32(uint32_t v) {
        char b[4] = {(char)(v >> 24), (char)(v >> 16), (char)(v >> 8),
                     (char)v};
        out.append(b, 4);
    }
    void i32(int32_t v) { u32((uint32_t)v); }
    void u64(uint64_t v) {
        u32((uint32_t)(v >> 32));
        u32((uint32_t)v);
    }
    void i64(int64_t v) { u64((uint64_t)v); }
    void raw(const std::string &s) { out.append(s); }
    void opaque_var(const std::string &s) {
        u32((uint32_t)s.size());
        out.append(s);
        size_t pad = (4 - s.size() % 4) % 4;
        out.append(pad, '\0');
    }
};

/* --------------------------------------------------------------- assets */

static bool asset_is_native(const std::string &a) { return a.size() == 4; }

/* raw 32-byte issuer id of a credit asset (encoding places it last) */
static std::string asset_issuer(const std::string &a) {
    need(a.size() >= 36, "asset parse");
    return a.substr(a.size() - 32);
}

static bool asset_code_char_ok(uint8_t c) {
    return (c >= '0' && c <= '9') || (c >= 'A' && c <= 'Z') ||
           (c >= 'a' && c <= 'z');
}

/* mirror transactions/utils.py is_asset_valid */
static bool asset_valid(const std::string &a) {
    if (a.size() < 4)
        return false;
    uint32_t t = ((uint32_t)(uint8_t)a[0] << 24) |
                 ((uint32_t)(uint8_t)a[1] << 16) |
                 ((uint32_t)(uint8_t)a[2] << 8) | (uint32_t)(uint8_t)a[3];
    if (t == 0)
        return a.size() == 4;
    size_t code_len = (t == 1) ? 4 : 12;
    if ((t != 1 && t != 2) || a.size() != 4 + code_len + 4 + 32)
        return false;
    const uint8_t *code = (const uint8_t *)a.data() + 4;
    size_t body = code_len;
    while (body > 0 && code[body - 1] == 0)
        body--;
    if (body == 0)
        return false;
    for (size_t i = 0; i < body; i++)
        if (!asset_code_char_ok(code[i]))
            return false;
    if (t == 1)
        return body >= 1 && body <= 4;
    return body >= 5 && body <= 12;
}

/* ------------------------------------------------------- entry states */

struct AcctState {
    std::string id; /* raw 32 */
    int64_t balance = 0, seqNum = 0;
    uint32_t numSubEntries = 0, flags = 0;
    std::string homeDomain;
    uint8_t thresholds[4] = {0, 0, 0, 0};
    bool has_v1 = false, has_v2 = false, has_v3 = false;
    int64_t liab_buying = 0, liab_selling = 0;
    uint32_t numSponsored = 0, numSponsoring = 0;
    uint32_t seqLedger = 0;
    uint64_t seqTime = 0;
};

struct TlState {
    std::string account; /* raw 32 */
    std::string asset;   /* TrustLineAsset == Asset bytes */
    int64_t balance = 0, limit = 0;
    uint32_t flags = 0;
    bool has_v1 = false, has_v2 = false;
    int64_t liab_buying = 0, liab_selling = 0;
    int32_t pool_use_count = 0; /* ext v2 liquidityPoolUseCount */
};

struct OfferState {
    std::string seller; /* raw 32 */
    int64_t offerID = 0;
    std::string selling, buying; /* Asset bytes */
    int64_t amount = 0;
    int32_t price_n = 0, price_d = 0;
    uint32_t flags = 0;
};

struct PoolState {
    /* constant-product pool (the only pool body the protocol defines);
     * params are canonical (assetA < assetB, fee = POOL_FEE_V18) */
    std::string pool_id; /* raw 32 */
    std::string assetA, assetB;
    int32_t fee = 0;
    int64_t reserveA = 0, reserveB = 0;
    int64_t totalPoolShares = 0, poolSharesTrustLineCount = 0;
};

enum { K_OTHER = 0, K_ACCT = 1, K_TL = 2, K_OFFER = 3, K_POOL = 4 };

struct Entry {
    int kind = K_OTHER;
    bool exists = false;
    bool supported = false; /* parsed into a state the encoder models */
    bool dirty = false;     /* written/erased by this cluster */
    uint32_t lastModified = 0;
    AcctState acct;
    TlState tl;
    OfferState offer;
    PoolState pool;
    std::string raw; /* original input bytes */
};

struct BookDir {
    /* static materialized rows for one (selling, buying) direction, in
     * (price, offerID) order — mirrors ApplySnapshot.books */
    std::vector<std::string> rows; /* offer key bytes */
};

struct Hop {
    /* one effective conversion step (equal-adjacent assets already
     * collapsed host-side): sell ``selling`` for ``buying``; pool_key
     * is the hop pair's liquidity-pool LedgerKey — declared by the
     * footprint, probed for the decline-if-live guard */
    std::string selling, buying, pool_key;
};

struct Tx {
    int op = 0;
    std::string hash, src; /* raw 32 */
    int64_t seq = 0, fee = 0, fee_charged = 0;
    /* payment / path payments */
    std::string dest;
    int64_t amount = 0;
    std::string asset; /* payment asset / changeTrust line */
    /* offer */
    std::string selling, buying;
    int32_t price_n = 0, price_d = 0;
    int64_t offer_id = 0;
    /* change_trust */
    int64_t limit = 0;
    /* path payments: amount carries sendAmount (strict send) or
     * sendMax (strict receive); amount2 carries destMin / destAmount */
    int64_t amount2 = 0;
    std::string dest_asset;
    std::vector<Hop> hops;
};

struct Ctx {
    uint32_t ledger_seq = 0;
    uint64_t close_time = 0;
    int64_t base_fee = 0, base_reserve = 0;
    int64_t idpool = 0; /* running; idpool0 on entry */
    std::map<std::string, Entry> store;
    std::map<std::pair<std::string, std::string>, BookDir> books;
    std::vector<Tx> txs;
    /* outputs */
    std::vector<std::pair<std::string, std::string>> records;
    /* per-tx touched-state tracking (meta STATE values) */
    std::map<std::string, std::pair<bool, std::string>> pre_touched;
    std::map<std::string, std::pair<bool, std::string>> op_touched;
};

/* ------------------------------------------------------ key encoding */

static std::string account_key(const std::string &id) {
    Wr w;
    w.u32(LE_ACCOUNT);
    w.u32(0);
    w.raw(id);
    return w.out;
}

static std::string trustline_key(const std::string &id,
                                 const std::string &asset) {
    Wr w;
    w.u32(LE_TRUSTLINE);
    w.u32(0);
    w.raw(id);
    w.raw(asset);
    return w.out;
}

static std::string offer_key(const std::string &seller, int64_t offer_id) {
    Wr w;
    w.u32(LE_OFFER);
    w.u32(0);
    w.raw(seller);
    w.i64(offer_id);
    return w.out;
}

/* --------------------------------------------------- entry en/decoding */

static void encode_account(const Entry &e, Wr &w) {
    const AcctState &a = e.acct;
    w.u32(e.lastModified);
    w.u32(LE_ACCOUNT);
    w.u32(0);
    w.raw(a.id);
    w.i64(a.balance);
    w.i64(a.seqNum);
    w.u32(a.numSubEntries);
    w.u32(0); /* inflationDest: absent (eligibility) */
    w.u32(a.flags);
    w.opaque_var(a.homeDomain);
    w.out.append((const char *)a.thresholds, 4);
    w.u32(0); /* signers: none (eligibility) */
    if (!a.has_v1) {
        w.u32(0);
    } else {
        w.u32(ACC_EXT_V1);
        w.i64(a.liab_buying);
        w.i64(a.liab_selling);
        if (!a.has_v2) {
            w.u32(0);
        } else {
            w.u32(ACC_EXT_V2);
            w.u32(a.numSponsored);
            w.u32(a.numSponsoring);
            w.u32(0); /* signerSponsoringIDs: [] */
            if (!a.has_v3) {
                w.u32(0);
            } else {
                w.u32(ACC_EXT_V3);
                w.u32(0); /* ExtensionPoint v0 */
                w.u32(a.seqLedger);
                w.u64(a.seqTime);
            }
        }
    }
    w.u32(0); /* LedgerEntry ext v0 (unsponsored: eligibility) */
}

static void encode_trustline(const Entry &e, Wr &w) {
    const TlState &t = e.tl;
    w.u32(e.lastModified);
    w.u32(LE_TRUSTLINE);
    w.u32(0);
    w.raw(t.account);
    w.raw(t.asset);
    w.i64(t.balance);
    w.i64(t.limit);
    w.u32(t.flags);
    if (!t.has_v1) {
        w.u32(0);
    } else {
        w.u32(TL_EXT_V1);
        w.i64(t.liab_buying);
        w.i64(t.liab_selling);
        if (!t.has_v2) {
            w.u32(0); /* TrustLineEntryV1 ext v0 */
        } else {
            w.u32(TL_V1_EXT_V2);
            w.i32(t.pool_use_count);
            w.u32(0); /* TrustLineEntryExtensionV2 ext v0 */
        }
    }
    w.u32(0); /* LedgerEntry ext v0 */
}

static void encode_offer_value(const OfferState &o, Wr &w) {
    w.u32(0); /* sellerID pk disc */
    w.raw(o.seller);
    w.i64(o.offerID);
    w.raw(o.selling);
    w.raw(o.buying);
    w.i64(o.amount);
    w.i32(o.price_n);
    w.i32(o.price_d);
    w.u32(o.flags);
    w.u32(0); /* OfferEntry ext v0 */
}

static void encode_offer(const Entry &e, Wr &w) {
    w.u32(e.lastModified);
    w.u32(LE_OFFER);
    encode_offer_value(e.offer, w);
    w.u32(0); /* LedgerEntry ext v0 */
}

static void encode_pool(const Entry &e, Wr &w) {
    const PoolState &p = e.pool;
    w.u32(e.lastModified);
    w.u32(LE_LIQUIDITY_POOL);
    w.raw(p.pool_id);
    w.u32(0); /* LIQUIDITY_POOL_CONSTANT_PRODUCT */
    w.raw(p.assetA);
    w.raw(p.assetB);
    w.i32(p.fee);
    w.i64(p.reserveA);
    w.i64(p.reserveB);
    w.i64(p.totalPoolShares);
    w.i64(p.poolSharesTrustLineCount);
    w.u32(0); /* LedgerEntry ext v0 */
}

static std::string encode_entry(const Entry &e) {
    Wr w;
    switch (e.kind) {
    case K_ACCT:
        encode_account(e, w);
        break;
    case K_TL:
        encode_trustline(e, w);
        break;
    case K_OFFER:
        encode_offer(e, w);
        break;
    case K_POOL:
        encode_pool(e, w);
        break;
    default:
        /* untouched passthrough: callers never re-encode K_OTHER */
        return e.raw;
    }
    return w.out;
}

static std::string read_asset(Rd &r) {
    uint32_t t = r.u32();
    Wr w;
    w.u32(t);
    if (t == 0)
        return w.out;
    if (t == 1) {
        w.raw(r.take(4));
    } else if (t == 2) {
        w.raw(r.take(12));
    } else {
        throw Decline("unsupported asset type");
    }
    need(r.u32() == 0, "asset issuer key type");
    w.u32(0);
    w.raw(r.take(32));
    return w.out;
}

/* parse + round-trip verify; unsupported shapes leave supported=false
 * (a decline fires only if a tx actually touches the entry) */
static void parse_entry(Entry &e) {
    e.supported = false;
    try {
        Rd r(e.raw);
        e.lastModified = r.u32();
        uint32_t t = r.u32();
        if (t == LE_ACCOUNT) {
            AcctState a;
            need(r.u32() == 0, "pk type");
            a.id = r.take(32);
            a.balance = r.i64();
            a.seqNum = r.i64();
            a.numSubEntries = r.u32();
            need(r.u32() == 0, "inflationDest present");
            a.flags = r.u32();
            a.homeDomain = r.opaque_var(32);
            std::string th = r.take(4);
            memcpy(a.thresholds, th.data(), 4);
            need(r.u32() == 0, "account has signers");
            uint32_t ext = r.u32();
            if (ext == ACC_EXT_V1) {
                a.has_v1 = true;
                a.liab_buying = r.i64();
                a.liab_selling = r.i64();
                uint32_t e1 = r.u32();
                if (e1 == ACC_EXT_V2) {
                    a.has_v2 = true;
                    a.numSponsored = r.u32();
                    a.numSponsoring = r.u32();
                    need(r.u32() == 0, "signerSponsoringIDs present");
                    uint32_t e2 = r.u32();
                    if (e2 == ACC_EXT_V3) {
                        a.has_v3 = true;
                        need(r.u32() == 0, "extension point");
                        a.seqLedger = r.u32();
                        a.seqTime = r.u64();
                    } else {
                        need(e2 == 0, "account ext v2 arm");
                    }
                } else {
                    need(e1 == 0, "account ext v1 arm");
                }
            } else {
                need(ext == 0, "account ext arm");
            }
            need(r.u32() == 0, "entry sponsored");
            need(r.done(), "trailing entry bytes");
            e.kind = K_ACCT;
            e.acct = a;
        } else if (t == LE_TRUSTLINE) {
            TlState tl;
            need(r.u32() == 0, "pk type");
            tl.account = r.take(32);
            tl.asset = read_asset(r);
            need(!asset_is_native(tl.asset), "native trustline");
            tl.balance = r.i64();
            tl.limit = r.i64();
            tl.flags = r.u32();
            /* AUTHORIZED and MAINTAIN_LIABILITIES are mutually
             * exclusive states; an entry carrying both is corrupt and
             * must never apply natively */
            need(!((tl.flags & AUTHORIZED_FLAG) &&
                   (tl.flags & MAINTAIN_LIABILITIES_FLAG)),
                 "invalid trustline auth state");
            uint32_t ext = r.u32();
            if (ext == TL_EXT_V1) {
                tl.has_v1 = true;
                tl.liab_buying = r.i64();
                tl.liab_selling = r.i64();
                uint32_t e1 = r.u32();
                if (e1 == TL_V1_EXT_V2) {
                    tl.has_v2 = true;
                    tl.pool_use_count = r.i32();
                    need(r.u32() == 0, "trustline ext v2 arm");
                } else {
                    need(e1 == 0, "trustline v1 ext arm");
                }
            } else {
                need(ext == 0, "trustline ext arm");
            }
            need(r.u32() == 0, "entry sponsored");
            need(r.done(), "trailing entry bytes");
            e.kind = K_TL;
            e.tl = tl;
        } else if (t == LE_OFFER) {
            OfferState o;
            need(r.u32() == 0, "pk type");
            o.seller = r.take(32);
            o.offerID = r.i64();
            o.selling = read_asset(r);
            o.buying = read_asset(r);
            o.amount = r.i64();
            o.price_n = r.i32();
            o.price_d = r.i32();
            o.flags = r.u32();
            need(r.u32() == 0, "offer ext arm");
            need(r.u32() == 0, "entry sponsored");
            need(r.done(), "trailing entry bytes");
            e.kind = K_OFFER;
            e.offer = o;
        } else if (t == LE_LIQUIDITY_POOL) {
            PoolState p;
            p.pool_id = r.take(32);
            need(r.u32() == 0, "pool body type");
            p.assetA = read_asset(r);
            p.assetB = read_asset(r);
            p.fee = r.i32();
            p.reserveA = r.i64();
            p.reserveB = r.i64();
            p.totalPoolShares = r.i64();
            p.poolSharesTrustLineCount = r.i64();
            need(r.u32() == 0, "entry sponsored");
            need(r.done(), "trailing entry bytes");
            e.kind = K_POOL;
            e.pool = p;
        } else {
            e.kind = K_OTHER;
            return; /* carried verbatim; touching it declines */
        }
        /* round-trip guard: the encoder must reproduce the input bytes
         * EXACTLY or later STATE/UPDATED meta could silently diverge */
        if (encode_entry(e) != e.raw) {
            e.kind = K_OTHER;
            return;
        }
        e.supported = true;
    } catch (Decline &) {
        e.kind = K_OTHER; /* shape outside the model */
    }
}

/* ------------------------------------------------------ store access */

static Entry *find_entry(Ctx &c, const std::string &key) {
    auto it = c.store.find(key);
    return it == c.store.end() ? nullptr : &it->second;
}

/* The store holds EVERY declared key (absent ones as exists=false), so
 * a map miss provably means an UNDECLARED access.  The Python path
 * raises FootprintEscape there; the kernel must decline, never treat
 * it as "entry missing" — that would apply against wrong state. */
static Entry *declared(Ctx &c, const std::string &key) {
    Entry *e = find_entry(c, key);
    need(e != nullptr, "undeclared key access");
    return e;
}

static Entry &load_acct(Ctx &c, const std::string &id, const char *who) {
    Entry *e = declared(c, account_key(id));
    need(e->exists, who);
    need(e->kind == K_ACCT && e->supported, "unsupported account shape");
    return *e;
}

static Entry *load_acct_opt(Ctx &c, const std::string &id) {
    Entry *e = declared(c, account_key(id));
    if (!e->exists)
        return nullptr;
    need(e->kind == K_ACCT && e->supported, "unsupported account shape");
    return e;
}

static Entry *load_tl_opt(Ctx &c, const std::string &id,
                          const std::string &asset) {
    Entry *e = declared(c, trustline_key(id, asset));
    if (!e->exists)
        return nullptr;
    need(e->kind == K_TL && e->supported, "unsupported trustline shape");
    return e;
}

/* record the pre-image of a key the OP phase is about to write */
static void op_touch(Ctx &c, const std::string &key) {
    if (c.op_touched.count(key))
        return;
    Entry *e = find_entry(c, key);
    if (e != nullptr && e->exists)
        c.op_touched[key] = {true, encode_entry(*e)};
    else
        c.op_touched[key] = {false, std::string()};
}

static void mark_put(Ctx &c, Entry &e, const std::string &key) {
    op_touch(c, key);
    e.lastModified = c.ledger_seq; /* LedgerTxn.put stamps every write */
    e.exists = true;
    e.dirty = true;
}

/* ---------------------------------------------------- account helpers */

static int64_t min_balance(const Ctx &c, const AcctState &a) {
    /* (2 + numSubEntries + numSponsoring - numSponsored) * baseReserve */
    int64_t count = 2 + (int64_t)a.numSubEntries + (int64_t)a.numSponsoring -
                    (int64_t)a.numSponsored;
    return count * c.base_reserve;
}

static int64_t available_balance(const Ctx &c, const AcctState &a) {
    int64_t v = a.balance - min_balance(c, a) - a.liab_selling;
    return v > 0 ? v : 0;
}

static int64_t max_receive(const AcctState &a) {
    return INT64_MAX_ - a.balance - a.liab_buying;
}

/* transactions/utils.py _ensure_v3 (called by set_seq_info and, when
 * ext is v0, by set_account_liabilities) */
static void ensure_v3(AcctState &a) {
    a.has_v1 = true;
    a.has_v2 = true;
    a.has_v3 = true;
}

static void set_seq_info(Ctx &c, AcctState &a, int64_t seq) {
    ensure_v3(a);
    a.seqNum = seq;
    a.seqLedger = c.ledger_seq;
    a.seqTime = c.close_time;
}

static void set_account_liabilities(AcctState &a, int64_t b, int64_t s) {
    if (!a.has_v1)
        ensure_v3(a); /* mirror: _ensure_v3 when ext was v0 */
    a.liab_buying = b;
    a.liab_selling = s;
}

static void set_trustline_liabilities(TlState &t, int64_t b, int64_t s) {
    t.has_v1 = true;
    t.liab_buying = b;
    t.liab_selling = s;
}

static bool tl_authorized(const TlState &t) {
    return (t.flags & AUTHORIZED_FLAG) != 0;
}

/* ----------------------------------------------- exchangeV10 (exact) */

struct ExchRes {
    int64_t wheat_receive = 0, sheep_send = 0;
    bool wheat_stays = false;
};

static int64_t div128(i128 x, i128 c, bool round_up) {
    /* x >= 0, c > 0 in every call site; C++ division truncates toward
     * zero, so ceil needs the explicit additive form */
    i128 res = round_up ? (x + c - 1) / c : x / c;
    need(res >= 0 && res <= (i128)INT64_MAX_, "int64 overflow in division");
    return (int64_t)res;
}

static int64_t big_divide(int64_t a, int64_t b, int64_t c, bool round_up) {
    return div128((i128)a * b, (i128)c, round_up);
}

static i128 offer_value(int64_t pn, int64_t pd, int64_t max_send,
                        int64_t max_receive_) {
    i128 lhs = (i128)max_send * pn;
    i128 rhs = (i128)max_receive_ * pd;
    return lhs < rhs ? lhs : rhs;
}

static ExchRes exchange_v10_wt(int32_t pn, int32_t pd, int64_t mws,
                               int64_t mwr, int64_t mss, int64_t msr,
                               int round_) {
    /* exchangeV10WithoutPriceErrorThresholds — all three rounding
     * modes (offer_exchange.py:78) */
    i128 wheat_value = offer_value(pn, pd, mws, msr);
    i128 sheep_value = offer_value(pd, pn, mss, mwr);
    ExchRes res;
    res.wheat_stays = wheat_value > sheep_value;
    int64_t wheat_receive, sheep_send;
    if (res.wheat_stays) {
        if (round_ == ROUND_PP_STRICT_SEND) {
            wheat_receive = div128(sheep_value, pn, false);
            sheep_send = mss < msr ? mss : msr;
        } else if (pn > pd || round_ == ROUND_PP_STRICT_RECEIVE) {
            wheat_receive = div128(sheep_value, pn, false);
            sheep_send = big_divide(wheat_receive, pn, pd, true);
        } else {
            sheep_send = div128(sheep_value, pd, false);
            wheat_receive = big_divide(sheep_send, pd, pn, false);
        }
    } else {
        if (pn > pd) {
            wheat_receive = div128(wheat_value, pn, false);
            sheep_send = big_divide(wheat_receive, pn, pd, false);
        } else {
            sheep_send = div128(wheat_value, pd, false);
            wheat_receive = big_divide(sheep_send, pd, pn, true);
        }
    }
    int64_t wcap = mwr < mws ? mwr : mws;
    int64_t scap = msr < mss ? msr : mss;
    need(wheat_receive >= 0 && wheat_receive <= wcap,
         "wheatReceive out of bounds");
    need(sheep_send >= 0 && sheep_send <= scap, "sheepSend out of bounds");
    res.wheat_receive = wheat_receive;
    res.sheep_send = sheep_send;
    return res;
}

static bool price_error_ok(int32_t pn, int32_t pd, int64_t wr, int64_t ss,
                           bool can_favor_wheat) {
    /* checkPriceErrorBound */
    i128 lhs = (i128)100 * pn * wr;
    i128 rhs = (i128)100 * pd * ss;
    if (can_favor_wheat && rhs > lhs)
        return true;
    i128 diff = lhs > rhs ? lhs - rhs : rhs - lhs;
    i128 cap = (i128)pn * wr;
    return diff <= cap;
}

static ExchRes exchange_v10(int32_t pn, int32_t pd, int64_t mws, int64_t mwr,
                            int64_t mss, int64_t msr,
                            int round_ = ROUND_NORMAL) {
    ExchRes r = exchange_v10_wt(pn, pd, mws, mwr, mss, msr, round_);
    /* applyPriceErrorThresholds */
    if (r.wheat_receive > 0 && r.sheep_send > 0) {
        i128 wrv = (i128)r.wheat_receive * pn;
        i128 ssv = (i128)r.sheep_send * pd;
        need(!(r.wheat_stays && ssv < wrv), "favored sheep when wheat stays");
        need(!(!r.wheat_stays && ssv > wrv), "favored wheat when sheep stays");
        if (round_ == ROUND_NORMAL) {
            if (!price_error_ok(pn, pd, r.wheat_receive, r.sheep_send,
                                false)) {
                r.wheat_receive = 0;
                r.sheep_send = 0;
            }
        } else {
            need(price_error_ok(pn, pd, r.wheat_receive, r.sheep_send,
                                true),
                 "exceeded price error bound");
        }
    } else {
        if (round_ == ROUND_PP_STRICT_SEND) {
            need(r.sheep_send != 0, "invalid amount of sheep sent");
        } else {
            r.wheat_receive = 0;
            r.sheep_send = 0;
        }
    }
    return r;
}

static int64_t adjust_offer_amount(int32_t pn, int32_t pd, int64_t mws,
                                   int64_t msr) {
    ExchRes r = exchange_v10(pn, pd, mws, INT64_MAX_, INT64_MAX_, msr,
                             ROUND_NORMAL);
    return r.wheat_receive;
}

static int64_t offer_selling_liab(int32_t pn, int32_t pd, int64_t amount) {
    return exchange_v10_wt(pn, pd, amount, INT64_MAX_, INT64_MAX_,
                           INT64_MAX_, ROUND_NORMAL)
        .wheat_receive;
}

static int64_t offer_buying_liab(int32_t pn, int32_t pd, int64_t amount) {
    return exchange_v10_wt(pn, pd, amount, INT64_MAX_, INT64_MAX_,
                           INT64_MAX_, ROUND_NORMAL)
        .sheep_send;
}

/* ------------------------------------------- capacities / transfers */

static int64_t can_sell_at_most(Ctx &c, const std::string &id,
                                const std::string &asset) {
    if (asset_is_native(asset)) {
        Entry *e = load_acct_opt(c, id);
        return e == nullptr ? 0 : available_balance(c, e->acct);
    }
    if (asset_issuer(asset) == id)
        return INT64_MAX_;
    Entry *t = load_tl_opt(c, id, asset);
    if (t == nullptr || !tl_authorized(t->tl))
        return 0;
    int64_t v = t->tl.balance - t->tl.liab_selling;
    return v > 0 ? v : 0;
}

static int64_t can_buy_at_most(Ctx &c, const std::string &id,
                               const std::string &asset) {
    if (asset_is_native(asset)) {
        Entry *e = load_acct_opt(c, id);
        if (e == nullptr)
            return 0;
        int64_t v = max_receive(e->acct);
        return v > 0 ? v : 0;
    }
    if (asset_issuer(asset) == id)
        return INT64_MAX_;
    Entry *t = load_tl_opt(c, id, asset);
    if (t == nullptr || !tl_authorized(t->tl))
        return 0;
    int64_t v = t->tl.limit - t->tl.balance - t->tl.liab_buying;
    return v > 0 ? v : 0;
}

/* offer_exchange._credit (liabilities-aware; reserve NOT checked) */
static void credit(Ctx &c, const std::string &id, const std::string &asset,
                   int64_t delta) {
    if (asset_is_native(asset)) {
        Entry &e = load_acct(c, id, "credit target missing");
        AcctState &a = e.acct;
        int64_t nb = a.balance + delta;
        need(nb >= a.liab_selling && nb <= INT64_MAX_ - a.liab_buying,
             "balance transfer failed");
        mark_put(c, e, account_key(id));
        a.balance = nb;
        return;
    }
    if (asset_issuer(asset) == id)
        return; /* issuers mint/burn freely */
    Entry *t = load_tl_opt(c, id, asset);
    need(t != nullptr, "trustline transfer target missing");
    TlState &tl = t->tl;
    int64_t nb = tl.balance + delta;
    need(nb >= tl.liab_selling && nb <= tl.limit - tl.liab_buying,
         "trustline transfer failed");
    mark_put(c, *t, trustline_key(id, asset));
    tl.balance = nb;
}

/* apply_offer_liabilities(oe, sign): acquire(+1)/release(-1); any bound
 * violation declines (the Python path would fail or raise there) */
static void offer_liabilities(Ctx &c, const OfferState &oe, int sign) {
    for (int leg = 0; leg < 2; leg++) {
        bool is_buy = (leg == 1);
        const std::string &asset = is_buy ? oe.buying : oe.selling;
        int64_t liab = is_buy
                           ? offer_buying_liab(oe.price_n, oe.price_d,
                                               oe.amount)
                           : offer_selling_liab(oe.price_n, oe.price_d,
                                                oe.amount);
        int64_t delta = sign * liab;
        if (delta == 0)
            continue;
        if (asset_is_native(asset)) {
            Entry &e = load_acct(c, oe.seller, "offer owner missing");
            AcctState &a = e.acct;
            int64_t b = a.liab_buying, s = a.liab_selling;
            if (is_buy) {
                b += delta;
                need(b >= 0 && !(sign > 0 && b > INT64_MAX_ - a.balance),
                     "buying liabilities out of bounds");
            } else {
                s += delta;
                need(s >= 0 &&
                         !(sign > 0 && s > a.balance - min_balance(c, a)),
                     "selling liabilities out of bounds");
            }
            mark_put(c, e, account_key(oe.seller));
            set_account_liabilities(a, b, s);
        } else if (asset_issuer(asset) == oe.seller) {
            continue;
        } else {
            Entry *t = load_tl_opt(c, oe.seller, asset);
            need(t != nullptr, "offer owner trustline missing");
            TlState &tl = t->tl;
            int64_t b = tl.liab_buying, s = tl.liab_selling;
            if (is_buy) {
                b += delta;
                need(b >= 0 && !(sign > 0 && b > tl.limit - tl.balance),
                     "buying liabilities out of bounds");
            } else {
                s += delta;
                need(s >= 0 && !(sign > 0 && s > tl.balance),
                     "selling liabilities out of bounds");
            }
            mark_put(c, *t, trustline_key(oe.seller, asset));
            set_trustline_liabilities(tl, b, s);
        }
    }
}

/* _erase_offer: liabilities already released; subentry refund on owner */
static void erase_offer(Ctx &c, Entry &oe_entry, const std::string &key) {
    op_touch(c, key);
    oe_entry.exists = false;
    oe_entry.dirty = true;
    const std::string seller = oe_entry.offer.seller;
    Entry &owner = load_acct(c, seller, "offer owner missing on erase");
    need(owner.acct.numSubEntries >= 1, "invalid account state");
    mark_put(c, owner, account_key(seller));
    owner.acct.numSubEntries -= 1;
}

/* ---------------------------------------------------- best offer scan */

static bool price_less(int32_t an, int32_t ad, int64_t aid, int32_t bn,
                       int32_t bd, int64_t bid) {
    i128 l = (i128)an * bd, r = (i128)bn * ad;
    if (l != r)
        return l < r;
    return aid < bid;
}

/* ClusterView._best_offer: first unshadowed materialized row, then all
 * cluster-dirty offers of the direction; exact-rational min wins */
static Entry *best_offer(Ctx &c, const std::string &wheat,
                         const std::string &sheep, std::string *key_out) {
    auto bit = c.books.find({wheat, sheep});
    need(bit != c.books.end(), "undeclared order-book direction");
    Entry *best = nullptr;
    std::string best_key;
    for (const std::string &kb : bit->second.rows) {
        Entry *e = declared(c, kb); /* book rows ride the cluster keys */
        if (e->dirty)
            continue; /* shadowed by the cluster's own writes */
        need(e->exists && e->kind == K_OFFER && e->supported,
             "unsupported book offer");
        best = e;
        best_key = kb;
        break; /* rows are sorted: first unshadowed row wins... */
    }
    /* ...but a dirty (override) offer may still beat it.  Offer keys
     * all start with the big-endian LE_OFFER discriminant, and the
     * store is byte-ordered — scan only that contiguous range, not the
     * whole cluster (accounts/trustlines dominate large clusters and
     * this runs once per crossing iteration) */
    std::string opfx(4, '\0');
    opfx[3] = (char)LE_OFFER;
    for (auto sit = c.store.lower_bound(opfx);
         sit != c.store.end() && sit->first.compare(0, 4, opfx) == 0;
         ++sit) {
        Entry &e = sit->second;
        if (!e.dirty || !e.exists || e.kind != K_OFFER)
            continue;
        if (e.offer.selling != wheat || e.offer.buying != sheep)
            continue;
        if (best == nullptr ||
            price_less(e.offer.price_n, e.offer.price_d, e.offer.offerID,
                       best->offer.price_n, best->offer.price_d,
                       best->offer.offerID)) {
            best = &e;
            best_key = sit->first;
        }
    }
    if (best != nullptr)
        *key_out = best_key;
    return best;
}

/* --------------------------------------------------- meta assembly */

static void emit_change_entry(Wr &w, uint32_t kind, const std::string &enc) {
    w.u32(kind);
    w.raw(enc);
}

static void emit_changes(
    Wr &w, Ctx &c,
    const std::map<std::string, std::pair<bool, std::string>> &touched) {
    /* LedgerTxn.changes(): sorted by key; STATE(prev)+UPDATED/REMOVED,
     * or CREATED; created+erased in-layer is a no-op */
    uint32_t count = 0;
    Wr body;
    for (auto &kv : touched) {
        const std::string &key = kv.first;
        bool existed = kv.second.first;
        Entry *e = find_entry(c, key);
        bool exists_now = (e != nullptr && e->exists);
        if (existed) {
            emit_change_entry(body, CH_STATE, kv.second.second);
            count++;
            if (exists_now) {
                emit_change_entry(body, CH_UPDATED, encode_entry(*e));
            } else {
                /* REMOVED carries the LedgerKey — the key bytes ARE its
                 * canonical encoding */
                emit_change_entry(body, CH_REMOVED, key);
            }
            count++;
        } else {
            if (!exists_now)
                continue;
            emit_change_entry(body, CH_CREATED, encode_entry(*e));
            count++;
        }
    }
    w.u32(count);
    w.raw(body.out);
}

/* ------------------------------------------------------- validity */

static void common_checks(Ctx &c, const Tx &tx, Entry &src) {
    need(tx.fee >= 0, "negative fee");
    need(tx.fee >= c.base_fee, "insufficient fee");
    AcctState &a = src.acct;
    /* master-only auth: tx LOW + op MEDIUM thresholds met by the master
     * weight alone (signature verdicts pre-checked by the dispatcher) */
    uint8_t mw = a.thresholds[0];
    uint8_t low = a.thresholds[1], med = a.thresholds[2];
    need(mw > 0, "master key disabled");
    need(mw >= (low > 1 ? low : 1), "low threshold unmet");
    need(mw >= (med > 1 ? med : 1), "medium threshold unmet");
    /* sequence: acc.seqNum + 1 == tx.seqNum, not the starting seq */
    need(tx.seq >= 0, "negative seqnum");
    need(a.seqNum < INT64_MAX_, "seqnum saturated");
    need(a.seqNum + 1 == tx.seq, "bad seqnum");
    need(tx.seq != ((int64_t)c.ledger_seq << 32), "starting seqnum");
    /* balance above reserve+liabilities (fee already charged) */
    need(a.balance - a.liab_selling - min_balance(c, a) >= 0,
         "insufficient balance");
}

/* ------------------------------------------------------- payment op */

static void apply_payment(Ctx &c, const Tx &tx) {
    need(tx.amount > 0, "payment amount non-positive");
    need(asset_valid(tx.asset), "payment asset invalid");
    if (asset_is_native(tx.asset)) {
        /* credit destination first (ref updateDestBalance order) */
        Entry *de = load_acct_opt(c, tx.dest);
        need(de != nullptr, "payment destination missing");
        need(max_receive(de->acct) >= tx.amount, "payment line full");
        mark_put(c, *de, account_key(tx.dest));
        de->acct.balance += tx.amount;
        /* debit source, re-reading (self-payment nets to zero) */
        Entry &se = load_acct(c, tx.src, "payment source missing");
        need(tx.amount <= available_balance(c, se.acct),
             "payment underfunded");
        int64_t nb = se.acct.balance - tx.amount;
        need(nb >= 0 && nb <= INT64_MAX_, "payment balance overflow");
        mark_put(c, se, account_key(tx.src));
        se.acct.balance = nb;
        return;
    }
    /* credit asset (ref PaymentOpFrame::doApply via the strict-receive
     * core with an empty path): issuer sides mint/burn freely, the
     * dest-existence check is bypassed when paying the issuer itself */
    std::string issuer = asset_issuer(tx.asset);
    bool bypass_issuer_check = tx.dest == issuer;
    if (!bypass_issuer_check)
        need(load_acct_opt(c, tx.dest) != nullptr,
             "payment destination missing");
    /* -- 1) credit the destination ------------------------------------ */
    if (tx.dest != issuer) {
        Entry *dt = load_tl_opt(c, tx.dest, tx.asset);
        need(dt != nullptr, "payment no trust");
        need(tl_authorized(dt->tl), "payment not authorized");
        TlState &dtl = dt->tl;
        /* trustline_max_receive: limit - balance - buying */
        need(dtl.limit - dtl.balance - dtl.liab_buying >= tx.amount,
             "payment line full");
        mark_put(c, *dt, trustline_key(tx.dest, tx.asset));
        dtl.balance += tx.amount;
    }
    /* -- 2) debit the source (re-read: may be the same trustline) ----- */
    if (tx.src != issuer) {
        Entry *st = load_tl_opt(c, tx.src, tx.asset);
        need(st != nullptr, "payment src no trust");
        need(tl_authorized(st->tl), "payment src not authorized");
        TlState &stl = st->tl;
        int64_t avail = stl.balance - stl.liab_selling;
        need((avail > 0 ? avail : 0) >= tx.amount, "payment underfunded");
        mark_put(c, *st, trustline_key(tx.src, tx.asset));
        stl.balance -= tx.amount;
    }
}

/* opINNER(PAYMENT, PAYMENT_SUCCESS) */
static void payment_result(Wr &w) {
    w.u32(0);          /* opINNER */
    w.u32(OP_PAYMENT); /* OperationResultTr disc */
    w.u32(0);          /* PAYMENT_SUCCESS (void arm) */
}

/* ------------------------------------------------ manage_sell_offer */

struct Atom {
    bool is_pool = false;  /* liquidity-pool atom: pool_id set, no seller */
    std::string pool_id;   /* raw 32 (pool atoms) */
    std::string seller;    /* raw 32 (order-book atoms) */
    int64_t offer_id = 0;
    std::string asset_sold;
    int64_t amount_sold = 0;
    std::string asset_bought;
    int64_t amount_bought = 0;
};

static bool crosses(int32_t book_n, int32_t book_d, int32_t own_n,
                    int32_t own_d, bool own_passive, bool book_passive) {
    i128 lhs = (i128)book_n * own_n;
    i128 rhs = (i128)book_d * own_d;
    if (lhs < rhs)
        return true;
    if (lhs == rhs)
        return !(own_passive || book_passive);
    return false;
}

static void emit_claim_atoms(Wr &w, const std::vector<Atom> &atoms) {
    w.u32((uint32_t)atoms.size());
    for (const Atom &at : atoms) {
        if (at.is_pool) {
            w.u32(2); /* CLAIM_ATOM_TYPE_LIQUIDITY_POOL */
            w.raw(at.pool_id);
            w.raw(at.asset_sold);
            w.i64(at.amount_sold);
            w.raw(at.asset_bought);
            w.i64(at.amount_bought);
            continue;
        }
        w.u32(1); /* CLAIM_ATOM_TYPE_ORDER_BOOK */
        w.u32(0); /* sellerID pk disc */
        w.raw(at.seller);
        w.i64(at.offer_id);
        w.raw(at.asset_sold);
        w.i64(at.amount_sold);
        w.raw(at.asset_bought);
        w.i64(at.amount_bought);
    }
}

struct ConvertOut {
    int64_t sheep_sent = 0, wheat_received = 0;
    std::vector<Atom> atoms;
};

/* convert_with_offers (offer_exchange.py:340): cross book offers
 * selling ``wheat`` for ``sheep`` until limits are exhausted.  Book
 * sellers settle here; the taker's side is the caller's.  The
 * manage-offer own-price filter engages when filter_pn > 0 (its stop
 * is a normal outcome); CROSSED_SELF / TOO_MANY_OFFERS / exchange
 * errors decline — every one is a failure result host-side, and the
 * kernel owns success paths only. */
static ConvertOut convert_with_offers(Ctx &c, const std::string &src,
                                      const std::string &sheep,
                                      int64_t max_sheep_send,
                                      const std::string &wheat,
                                      int64_t max_wheat_receive,
                                      int round_, int32_t filter_pn,
                                      int32_t filter_pd) {
    ConvertOut out;
    int crossed = 0;
    while (max_sheep_send - out.sheep_sent > 0 &&
           max_wheat_receive - out.wheat_received > 0) {
        std::string okey;
        Entry *oe_e = best_offer(c, wheat, sheep, &okey);
        if (oe_e == nullptr)
            break;
        need(crossed < MAX_OFFERS_TO_CROSS, "too many offers crossed");
        OfferState &oe = oe_e->offer;
        if (filter_pn > 0 &&
            !crosses(oe.price_n, oe.price_d, filter_pn, filter_pd, false,
                     (oe.flags & PASSIVE_FLAG) != 0))
            break; /* price filter stop */
        need(oe.seller != src, "crossed self");

        offer_liabilities(c, oe, -1); /* release before measuring */

        int64_t seller_cap = can_sell_at_most(c, oe.seller, wheat);
        int64_t mwso = oe.amount < seller_cap ? oe.amount : seller_cap;
        int64_t msro = can_buy_at_most(c, oe.seller, sheep);
        int64_t adjusted =
            adjust_offer_amount(oe.price_n, oe.price_d, mwso, msro);
        if (adjusted == 0) {
            erase_offer(c, *oe_e, okey);
            crossed++;
            continue;
        }

        ExchRes res = exchange_v10(oe.price_n, oe.price_d, adjusted,
                                   max_wheat_receive - out.wheat_received,
                                   max_sheep_send - out.sheep_sent,
                                   INT64_MAX_, round_);
        crossed++;

        if (res.wheat_receive > 0) {
            credit(c, oe.seller, wheat, -res.wheat_receive);
            credit(c, oe.seller, sheep, res.sheep_send);
            Atom at;
            at.seller = oe.seller;
            at.offer_id = oe.offerID;
            at.asset_sold = wheat;
            at.amount_sold = res.wheat_receive;
            at.asset_bought = sheep;
            at.amount_bought = res.sheep_send;
            out.atoms.push_back(at);
            out.sheep_sent += res.sheep_send;
            out.wheat_received += res.wheat_receive;
        }

        if (res.wheat_stays) {
            int64_t rem = oe.amount - res.wheat_receive;
            int64_t cap2 = can_sell_at_most(c, oe.seller, wheat);
            int64_t new_amount = adjust_offer_amount(
                oe.price_n, oe.price_d, rem < cap2 ? rem : cap2,
                can_buy_at_most(c, oe.seller, sheep));
            if (new_amount == 0) {
                erase_offer(c, *oe_e, okey);
            } else {
                mark_put(c, *oe_e, okey);
                oe.amount = new_amount;
                offer_liabilities(c, oe, 1);
            }
            break; /* taker exhausted */
        }
        erase_offer(c, *oe_e, okey);
    }
    return out;
}

static void apply_manage_sell_offer(Ctx &c, const Tx &tx, Wr &result) {
    const std::string &selling = tx.selling, &buying = tx.buying;
    need(asset_valid(selling) && asset_valid(buying), "invalid asset");
    need(selling != buying, "selling == buying");
    need(tx.price_n > 0 && tx.price_d > 0, "invalid price");
    need(tx.amount >= 0 && tx.offer_id >= 0, "malformed offer");
    need(tx.amount > 0 || tx.offer_id != 0, "malformed offer");

    if (tx.amount == 0) {
        /* delete: no trustline prerequisites (ref checkOfferValid:38
         * "don't bother loading trust lines as we're deleting") */
        std::string okey = offer_key(tx.src, tx.offer_id);
        Entry *oe_e = declared(c, okey);
        need(oe_e->exists, "offer not found");
        need(oe_e->kind == K_OFFER && oe_e->supported,
             "unsupported offer shape");
        offer_liabilities(c, oe_e->offer, -1);
        erase_offer(c, *oe_e, okey);
        result.u32(0);                    /* opINNER */
        result.u32(OP_MANAGE_SELL_OFFER); /* tr disc */
        result.u32(0);                    /* MANAGE_SELL_OFFER_SUCCESS */
        result.u32(0);                    /* offersClaimed: [] */
        result.u32(EFF_DELETED);          /* (void) */
        return;
    }

    /* trustline prerequisites (ref checkOfferValid order) */
    if (!asset_is_native(selling) && asset_issuer(selling) != tx.src) {
        Entry *tl = load_tl_opt(c, tx.src, selling);
        need(load_acct_opt(c, asset_issuer(selling)) != nullptr,
             "sell no issuer");
        need(tl != nullptr, "sell no trust");
        need(tl_authorized(tl->tl), "sell not authorized");
    }
    if (!asset_is_native(buying) && asset_issuer(buying) != tx.src) {
        Entry *tl = load_tl_opt(c, tx.src, buying);
        need(load_acct_opt(c, asset_issuer(buying)) != nullptr,
             "buy no issuer");
        need(tl != nullptr, "buy no trust");
        need(tl_authorized(tl->tl), "buy not authorized");
    }

    bool modify = tx.offer_id != 0;
    uint32_t existing_flags = 0;
    if (modify) {
        /* modify: release old liabilities + erase, but KEEP the
         * subentry reservation (ref doApply v14+: "sellSheepOffer is
         * deleted but sourceAccount is not updated"); the rebuilt
         * offer keeps the loaded offer's flags — sponsored offers
         * decline at entry parse, so no sponsor survives here */
        std::string exkey = offer_key(tx.src, tx.offer_id);
        Entry *ex = declared(c, exkey);
        need(ex->exists, "offer not found");
        need(ex->kind == K_OFFER && ex->supported,
             "unsupported offer shape");
        existing_flags = ex->offer.flags;
        offer_liabilities(c, ex->offer, -1);
        op_touch(c, exkey);
        ex->exists = false;
        ex->dirty = true;
    } else {
        /* new offer: up-front subentry reservation (0-amount dummy
         * through create_entry_with_possible_sponsorship, unsponsored
         * branch) */
        Entry &se = load_acct(c, tx.src, "offer source missing");
        AcctState &a = se.acct;
        need(a.numSubEntries + 1 <= ACCOUNT_SUBENTRY_LIMIT,
             "too many subentries");
        need(available_balance(c, a) >= c.base_reserve, "low reserve");
        mark_put(c, se, account_key(tx.src));
        a.numSubEntries += 1;
    }

    /* full-offer liabilities must fit capacity up front */
    int64_t sell_cap = can_sell_at_most(c, tx.src, selling);
    int64_t buy_cap = can_buy_at_most(c, tx.src, buying);
    need(buy_cap >= offer_buying_liab(tx.price_n, tx.price_d, tx.amount),
         "offer line full");
    need(sell_cap >= offer_selling_liab(tx.price_n, tx.price_d, tx.amount),
         "offer underfunded");

    int64_t max_sheep_send = tx.amount < sell_cap ? tx.amount : sell_cap;
    int64_t max_wheat_receive = buy_cap;

    /* crossing loop (sheep=selling, wheat=buying; own offer is never
     * passive here — CREATE_PASSIVE_SELL_OFFER stays host-side) */
    ConvertOut cv = convert_with_offers(c, tx.src, selling, max_sheep_send,
                                        buying, max_wheat_receive,
                                        ROUND_NORMAL, tx.price_n,
                                        tx.price_d);

    /* settle the taker's side */
    if (cv.sheep_sent > 0)
        credit(c, tx.src, selling, -cv.sheep_sent);
    if (cv.wheat_received > 0)
        credit(c, tx.src, buying, cv.wheat_received);

    /* residual resting amount, re-adjusted post-settle */
    int64_t rem = tx.amount - cv.sheep_sent;
    int64_t cap = can_sell_at_most(c, tx.src, selling);
    int64_t sheep_limit = rem < cap ? rem : cap;
    int64_t wheat_limit = can_buy_at_most(c, tx.src, buying);
    int64_t amount_left =
        adjust_offer_amount(tx.price_n, tx.price_d, sheep_limit, wheat_limit);

    /* result: opINNER(MANAGE_SELL_OFFER, SUCCESS, ManageOfferSuccess) */
    result.u32(0);                    /* opINNER */
    result.u32(OP_MANAGE_SELL_OFFER); /* tr disc */
    result.u32(0);                    /* MANAGE_SELL_OFFER_SUCCESS */
    emit_claim_atoms(result, cv.atoms);

    if (amount_left <= 0) {
        /* nothing rests: give back the subentry reservation — for a
         * modify too (the ghost remove_entry_with_possible_sponsorship
         * on the 0-amount offer) */
        Entry &se = load_acct(c, tx.src, "offer source missing");
        need(se.acct.numSubEntries >= 1, "invalid account state");
        mark_put(c, se, account_key(tx.src));
        se.acct.numSubEntries -= 1;
        result.u32(EFF_DELETED); /* (void) */
        return;
    }

    /* write the resting offer: a modify keeps its id and flags, a
     * create allocates from the id pool */
    int64_t new_id;
    uint32_t flags;
    if (modify) {
        new_id = tx.offer_id;
        flags = existing_flags;
    } else {
        need(c.idpool < INT64_MAX_, "id pool saturated");
        new_id = c.idpool + 1;
        c.idpool = new_id;
        flags = 0;
    }
    OfferState no;
    no.seller = tx.src;
    no.offerID = new_id;
    no.selling = selling;
    no.buying = buying;
    no.amount = amount_left;
    no.price_n = tx.price_n;
    no.price_d = tx.price_d;
    no.flags = flags;
    std::string nkey = offer_key(tx.src, new_id);
    need(find_entry(c, nkey) == nullptr || !c.store[nkey].exists,
         "fresh offer key collision");
    Entry &ne = c.store[nkey];
    ne.kind = K_OFFER;
    ne.supported = true;
    ne.offer = no;
    mark_put(c, ne, nkey);
    offer_liabilities(c, ne.offer, 1);
    result.u32(modify ? EFF_UPDATED : EFF_CREATED);
    encode_offer_value(ne.offer, result);
}

/* ------------------------------------------------------ change_trust */

static void apply_change_trust(Ctx &c, const Tx &tx) {
    const std::string &line = tx.asset;
    need(asset_valid(line) && !asset_is_native(line),
         "change trust malformed");
    std::string issuer = asset_issuer(line);
    need(issuer != tx.src, "change trust self not allowed");
    need(tx.limit >= 0, "change trust malformed");

    std::string tlkey = trustline_key(tx.src, line);
    Entry *t = declared(c, tlkey);
    if (t->exists) {
        need(t->kind == K_TL && t->supported,
             "unsupported trustline shape");
        TlState &tl = t->tl;
        if (tx.limit != 0) {
            /* limit update (ref ChangeTrustOpFrame::doApply) */
            need(tx.limit >= tl.balance + tl.liab_buying,
                 "change trust invalid limit");
            need(load_acct_opt(c, issuer) != nullptr,
                 "change trust no issuer");
            mark_put(c, *t, tlkey);
            tl.limit = tx.limit;
            return;
        }
        /* delete: only an empty, liability-free, pool-free line goes */
        need(tl.balance == 0, "change trust invalid limit");
        need(tl.liab_buying == 0 && tl.liab_selling == 0,
             "change trust cannot delete");
        need(tl.pool_use_count == 0, "change trust cannot delete");
        op_touch(c, tlkey);
        t->exists = false;
        t->dirty = true;
        /* unsponsored remove: the owner's subentry reserve returns */
        Entry &owner = load_acct(c, tx.src, "trust source missing");
        need(owner.acct.numSubEntries >= 1, "invalid account state");
        mark_put(c, owner, account_key(tx.src));
        owner.acct.numSubEntries -= 1;
        return;
    }

    /* new trustline: flags derive from the issuer's account flags */
    need(tx.limit != 0, "change trust invalid limit");
    Entry *ie = load_acct_opt(c, issuer);
    need(ie != nullptr, "change trust no issuer");
    uint32_t flags = 0;
    if (!(ie->acct.flags & ACC_AUTH_REQUIRED_FLAG))
        flags |= AUTHORIZED_FLAG;
    if (ie->acct.flags & ACC_AUTH_CLAWBACK_FLAG)
        flags |= TL_CLAWBACK_FLAG;
    /* unsponsored create: the owner pays the subentry reserve */
    {
        Entry &owner = load_acct(c, tx.src, "trust source missing");
        AcctState &a = owner.acct;
        need(a.numSubEntries + 1 <= ACCOUNT_SUBENTRY_LIMIT,
             "too many subentries");
        need(available_balance(c, a) >= c.base_reserve, "low reserve");
        mark_put(c, owner, account_key(tx.src));
        a.numSubEntries += 1;
    }
    TlState tl;
    tl.account = tx.src;
    tl.asset = line;
    tl.balance = 0;
    tl.limit = tx.limit;
    tl.flags = flags;
    t->kind = K_TL;
    t->supported = true;
    t->tl = tl;
    mark_put(c, *t, tlkey);
}

/* opINNER(CHANGE_TRUST, CHANGE_TRUST_SUCCESS) */
static void change_trust_result(Wr &w) {
    w.u32(0);               /* opINNER */
    w.u32(OP_CHANGE_TRUST); /* OperationResultTr disc */
    w.u32(0);               /* CHANGE_TRUST_SUCCESS (void arm) */
}

/* ---------------------------------------------------- path payments */

/* Constant-product quote twins (transactions/liquidity_pool.py).  The
 * Python reference computes in unbounded ints; the kernel works in
 * i128, and any product that could exceed it DECLINES so the bignum
 * reference adjudicates — it never wraps. */
static const i128 I128_MAX = (i128)(((unsigned __int128)1 << 127) - 1);

/* floor((f*rout*in) / (10000*rin + f*in)), f = 10000 - fee_bps; false
 * mirrors the reference returning None (caller falls back to the book) */
static bool pool_swap_out_given_in(int64_t rin, int64_t rout, int64_t in,
                                   int32_t fee_bps, int64_t *out) {
    if (in <= 0 || rin <= 0 || rout <= 0)
        return false;
    if (in > INT64_MAX_ - rin)
        return false;
    i128 f = POOL_MAX_BPS - fee_bps;
    i128 prod = f * (i128)rout;
    need(prod == 0 || (i128)in <= I128_MAX / prod, "pool math overflow");
    i128 num = prod * (i128)in;
    i128 den = (i128)POOL_MAX_BPS * rin + f * (i128)in;
    i128 o = num / den; /* non-negative operands: trunc == floor */
    if (o == 0)
        return false;
    *out = (int64_t)o; /* o < rout, so it fits */
    return true;
}

/* ceil((10000*rin*out) / ((rout-out)*f)); false mirrors None */
static bool pool_swap_in_given_out(int64_t rin, int64_t rout, int64_t outv,
                                   int32_t fee_bps, int64_t *in) {
    if (outv <= 0 || rin <= 0 || rout <= 0)
        return false;
    if (outv >= rout)
        return false;
    i128 f = POOL_MAX_BPS - fee_bps;
    i128 a = (i128)POOL_MAX_BPS * rin;
    need((i128)outv <= I128_MAX / a, "pool math overflow");
    i128 num = a * (i128)outv;
    i128 den = ((i128)rout - outv) * f; /* > 0 */
    need(num <= I128_MAX - den, "pool math overflow");
    i128 amt = (num + den - 1) / den; /* ceil */
    if (amt > (i128)INT64_MAX_ - rin)
        return false;
    *in = (int64_t)amt;
    return true;
}

/* convert_with_offers_and_pools (offer_exchange.py): quote the hop's
 * declared pool, attempt the book in a child frame, keep whichever
 * side wins — the book only on a strictly better price.  The pool key
 * rides the footprint's book materialization, so it is always
 * declared; an absent pool degrades to the plain book crossing. */
static ConvertOut convert_hop(Ctx &c, const std::string &src,
                              const Hop &hop, int64_t max_sheep_send,
                              int64_t max_wheat_receive, int round_) {
    const std::string &sheep = hop.selling, &wheat = hop.buying;
    Entry *pe = declared(c, hop.pool_key);
    bool have_quote = false;
    bool sheep_is_a = false;
    int64_t to_pool = 0, from_pool = 0;
    if (pe->exists) {
        need(pe->kind == K_POOL && pe->supported,
             "unsupported pool shape");
        const PoolState &p = pe->pool;
        /* compare_assets' total order equals lexicographic order of the
         * canonical asset encodings, so byte compare decides A/B */
        sheep_is_a = sheep < wheat;
        const std::string &ca = sheep_is_a ? sheep : wheat;
        const std::string &cb = sheep_is_a ? wheat : sheep;
        /* the footprint derived this key from (min, max, fee=30); an
         * entry disagreeing with its own key is outside the model */
        need(p.assetA == ca && p.assetB == cb && p.fee == POOL_FEE_V18,
             "pool params mismatch");
        int64_t rin = sheep_is_a ? p.reserveA : p.reserveB;
        int64_t rout = sheep_is_a ? p.reserveB : p.reserveA;
        if (rin > 0 && rout > 0) {
            if (round_ == ROUND_PP_STRICT_SEND) {
                to_pool = max_sheep_send;
                have_quote = pool_swap_out_given_in(rin, rout, to_pool,
                                                    p.fee, &from_pool);
            } else if (round_ == ROUND_PP_STRICT_RECEIVE) {
                from_pool = max_wheat_receive;
                have_quote = pool_swap_in_given_out(rin, rout, from_pool,
                                                    p.fee, &to_pool);
            }
        }
    }
    if (!have_quote)
        return convert_with_offers(c, src, sheep, max_sheep_send, wheat,
                                   max_wheat_receive, round_, 0, 0);

    /* EMPTY book: convert_with_offers would cross nothing (both limits
     * stay slack -> ConvertResult.PARTIAL -> book loses), so the child
     * frame is provably a no-op.  Skip the whole-store snapshot — it is
     * O(cluster) per hop, and a pool-only workload collapses to ONE
     * conflict cluster, so snapshotting would make the close O(n^2).
     * best_offer is a pure read (the store is fully pre-materialized). */
    std::string probe_key;
    if (best_offer(c, wheat, sheep, &probe_key) == nullptr) {
        Entry *pe2 = declared(c, hop.pool_key);
        mark_put(c, *pe2, hop.pool_key);
        PoolState &p = pe2->pool;
        if (sheep_is_a) {
            p.reserveA += to_pool;
            p.reserveB -= from_pool;
        } else {
            p.reserveB += to_pool;
            p.reserveA -= from_pool;
        }
        Atom at;
        at.is_pool = true;
        at.pool_id = p.pool_id;
        at.asset_sold = wheat;
        at.amount_sold = from_pool;
        at.asset_bought = sheep;
        at.amount_bought = to_pool;
        ConvertOut out;
        out.sheep_sent = to_pool;
        out.wheat_received = from_pool;
        out.atoms.push_back(at);
        return out;
    }

    /* book attempt in a child frame (the reference's child LedgerTxn):
     * snapshot the mutable tx-visible state, roll back if the pool wins */
    std::map<std::string, Entry> store_snap = c.store;
    std::map<std::string, std::pair<bool, std::string>> touched_snap =
        c.op_touched;
    int64_t idpool_snap = c.idpool;
    ConvertOut cv = convert_with_offers(c, src, sheep, max_sheep_send,
                                        wheat, max_wheat_receive, round_,
                                        0, 0);
    /* ConvertResult.OK unless BOTH limits kept slack (PARTIAL) */
    bool book_ok = !(max_wheat_receive - cv.wheat_received > 0 &&
                     max_sheep_send - cv.sheep_sent > 0);
    bool use_book =
        book_ok && (i128)to_pool * cv.wheat_received >
                       (i128)from_pool * cv.sheep_sent;
    if (use_book)
        return cv;

    /* pool wins: restore, then trade against the pool */
    c.store = std::move(store_snap);
    c.op_touched = std::move(touched_snap);
    c.idpool = idpool_snap;
    Entry *pe2 = declared(c, hop.pool_key); /* re-locate after restore */
    mark_put(c, *pe2, hop.pool_key);
    PoolState &p = pe2->pool;
    if (sheep_is_a) {
        p.reserveA += to_pool;
        p.reserveB -= from_pool;
    } else {
        p.reserveB += to_pool;
        p.reserveA -= from_pool;
    }
    Atom at;
    at.is_pool = true;
    at.pool_id = p.pool_id;
    at.asset_sold = wheat;
    at.amount_sold = from_pool;
    at.asset_bought = sheep;
    at.amount_bought = to_pool;
    ConvertOut out;
    out.sheep_sent = to_pool;
    out.wheat_received = from_pool;
    out.atoms.push_back(at);
    return out;
}

static void apply_path_payment(Ctx &c, const Tx &tx, Wr &result) {
    bool strict_send = tx.op == OP_PATH_PAYMENT_STRICT_SEND;
    /* tx.amount = sendAmount | sendMax; tx.amount2 = destMin |
     * destAmount (strict send | strict receive) */
    need(tx.amount > 0 && tx.amount2 > 0, "path payment malformed");
    need(asset_valid(tx.asset) && asset_valid(tx.dest_asset),
         "path payment malformed");
    need((int)tx.hops.size() <= MAX_PATH_HOPS, "path too long");
    for (const Hop &h : tx.hops)
        need(asset_valid(h.selling) && asset_valid(h.buying),
             "path payment malformed");

    /* destination existence + dest/src trust gates (every failure is a
     * failure result host-side; the walk never touches the source's
     * own lines — sellers are never the taker — so check placement is
     * not state-visible on success paths) */
    need(load_acct_opt(c, tx.dest) != nullptr, "path no destination");
    if (!asset_is_native(tx.dest_asset) &&
        asset_issuer(tx.dest_asset) != tx.dest) {
        Entry *dt = load_tl_opt(c, tx.dest, tx.dest_asset);
        need(dt != nullptr, "path no trust");
        need(tl_authorized(dt->tl), "path not authorized");
    }
    if (!asset_is_native(tx.asset) && asset_issuer(tx.asset) != tx.src) {
        Entry *st = load_tl_opt(c, tx.src, tx.asset);
        need(st != nullptr, "path src no trust");
        need(tl_authorized(st->tl), "path src not authorized");
    }

    std::vector<Atom> atoms;
    int64_t send_amount, dest_amount;
    if (strict_send) {
        /* forward walk: propagate what each hop yields */
        int64_t have = tx.amount;
        for (size_t i = 0; i < tx.hops.size(); i++) {
            const Hop &hop = tx.hops[i];
            ConvertOut out = convert_hop(c, tx.src, hop, have,
                                         INT64_MAX_,
                                         ROUND_PP_STRICT_SEND);
            need(out.sheep_sent >= have, "too few offers");
            atoms.insert(atoms.end(), out.atoms.begin(),
                         out.atoms.end());
            have = out.wheat_received;
        }
        send_amount = tx.amount;
        dest_amount = have;
        need(dest_amount >= tx.amount2, "under destmin");
    } else {
        /* backward walk: compute what each hop requires */
        int64_t needed = tx.amount2;
        for (size_t i = tx.hops.size(); i-- > 0;) {
            const Hop &hop = tx.hops[i];
            ConvertOut out = convert_hop(c, tx.src, hop, INT64_MAX_,
                                         needed,
                                         ROUND_PP_STRICT_RECEIVE);
            need(out.wheat_received >= needed, "too few offers");
            atoms.insert(atoms.begin(), out.atoms.begin(),
                         out.atoms.end());
            needed = out.sheep_sent;
        }
        send_amount = needed;
        dest_amount = tx.amount2;
        need(send_amount <= tx.amount, "over sendmax");
    }

    if (asset_is_native(tx.asset)) {
        Entry &se = load_acct(c, tx.src, "path source missing");
        need(send_amount <= available_balance(c, se.acct),
             "path underfunded");
    }
    credit(c, tx.src, tx.asset, -send_amount);
    credit(c, tx.dest, tx.dest_asset, dest_amount);

    /* opINNER(type, SUCCESS, {offers, last: SimplePaymentResult}) */
    result.u32(0);                 /* opINNER */
    result.u32((uint32_t)tx.op);   /* OperationResultTr disc */
    result.u32(0);                 /* *_SUCCESS */
    emit_claim_atoms(result, atoms);
    result.u32(0); /* last.destination pk disc */
    result.raw(tx.dest);
    result.raw(tx.dest_asset);
    result.i64(dest_amount);
}

/* -------------------------------------------------------- tx driver */

static void run_tx(Ctx &c, size_t idx) {
    const Tx &tx = c.txs[idx];
    c.pre_touched.clear();
    c.op_touched.clear();

    Entry &src = load_acct(c, tx.src, "tx source missing");
    common_checks(c, tx, src);

    /* pre-ops phase: consume the sequence number (its delta is the
     * meta's txChangesBefore and commits before the op layer opens) */
    std::string src_key = account_key(tx.src);
    c.pre_touched[src_key] = {true, encode_entry(src)};
    need(src.acct.seqNum <= tx.seq, "unexpected sequence number");
    src.lastModified = c.ledger_seq;
    src.dirty = true;
    set_seq_info(c, src.acct, tx.seq);
    /* snapshot txChangesBefore NOW: its UPDATED values are the
     * post-seqnum PRE-op state (the pre layer commits before ops run) */
    Wr before;
    emit_changes(before, c, c.pre_touched);

    /* op phase */
    Wr opres;
    if (tx.op == OP_PAYMENT) {
        apply_payment(c, tx);
        payment_result(opres);
    } else if (tx.op == OP_MANAGE_SELL_OFFER) {
        apply_manage_sell_offer(c, tx, opres);
    } else if (tx.op == OP_CHANGE_TRUST) {
        apply_change_trust(c, tx);
        change_trust_result(opres);
    } else if (tx.op == OP_PATH_PAYMENT_STRICT_SEND ||
               tx.op == OP_PATH_PAYMENT_STRICT_RECEIVE) {
        apply_path_payment(c, tx, opres);
    } else {
        throw Decline("unsupported op type");
    }

    /* TransactionMeta: disc 2 + V2{before, [opmeta], after=[]} */
    Wr meta;
    meta.u32(2);
    meta.raw(before.out);
    meta.u32(1); /* one operation */
    emit_changes(meta, c, c.op_touched);
    meta.u32(0); /* txChangesAfter */

    /* TransactionResult: feeCharged + txSUCCESS[1 op result] + ext v0 */
    Wr result;
    result.i64(tx.fee_charged);
    result.u32(0); /* txSUCCESS */
    result.u32(1);
    result.raw(opres.out);
    result.u32(0); /* ext v0 */

    c.records.push_back({meta.out, result.out});
}

/* ------------------------------------------------------ python glue */

static PyObject *KernelError; /* module-level exception for bad calls */

static int parse_bytes(PyObject *o, std::string &out, const char *what) {
    char *buf;
    Py_ssize_t len;
    /* o may be NULL (short tuple from a caller regression): raise,
     * never hand NULL to PyBytes_AsStringAndSize (segfault) */
    if (!o || PyBytes_AsStringAndSize(o, &buf, &len) < 0) {
        PyErr_Format(KernelError, "%s: expected bytes", what);
        return -1;
    }
    out.assign(buf, (size_t)len);
    return 0;
}

static PyObject *apply_cluster(PyObject *self, PyObject *args) {
    PyObject *params, *entries, *books, *txs;
    if (!PyArg_ParseTuple(args, "OOOO", &params, &entries, &books, &txs))
        return NULL;

    Ctx c;
    {
        long long ls, ct, bf, br, ip;
        if (!PyArg_ParseTuple(params, "LLLLL", &ls, &ct, &bf, &br, &ip))
            return NULL;
        c.ledger_seq = (uint32_t)ls;
        c.close_time = (uint64_t)ct;
        c.base_fee = bf;
        c.base_reserve = br;
        c.idpool = ip;
    }

    /* entries */
    PyObject *seq = PySequence_Fast(entries, "entries must be a sequence");
    if (!seq)
        return NULL;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(seq, i);
        PyObject *kb = PyTuple_GetItem(it, 0);
        PyObject *eb = PyTuple_GetItem(it, 1);
        if (!kb || !eb) {
            Py_DECREF(seq);
            return NULL;
        }
        std::string key;
        if (parse_bytes(kb, key, "entry key") < 0) {
            Py_DECREF(seq);
            return NULL;
        }
        Entry &e = c.store[key];
        if (eb == Py_None) {
            e.exists = false;
        } else {
            if (parse_bytes(eb, e.raw, "entry bytes") < 0) {
                Py_DECREF(seq);
                return NULL;
            }
            e.exists = true;
        }
    }
    Py_DECREF(seq);

    /* books */
    seq = PySequence_Fast(books, "books must be a sequence");
    if (!seq)
        return NULL;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(seq, i);
        std::string sb, bb;
        if (parse_bytes(PyTuple_GetItem(it, 0), sb, "book selling") < 0 ||
            parse_bytes(PyTuple_GetItem(it, 1), bb, "book buying") < 0) {
            Py_DECREF(seq);
            return NULL;
        }
        PyObject *rows = PySequence_Fast(PyTuple_GetItem(it, 2),
                                         "book rows must be a sequence");
        if (!rows) {
            Py_DECREF(seq);
            return NULL;
        }
        BookDir &bd = c.books[{sb, bb}];
        for (Py_ssize_t j = 0; j < PySequence_Fast_GET_SIZE(rows); j++) {
            std::string kb;
            if (parse_bytes(PySequence_Fast_GET_ITEM(rows, j), kb,
                            "book row key") < 0) {
                Py_DECREF(rows);
                Py_DECREF(seq);
                return NULL;
            }
            bd.rows.push_back(kb);
        }
        Py_DECREF(rows);
    }
    Py_DECREF(seq);

    /* txs */
    seq = PySequence_Fast(txs, "txs must be a sequence");
    if (!seq)
        return NULL;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(seq, i);
        Tx tx;
        long op = PyLong_AsLong(PyTuple_GetItem(it, 0));
        tx.op = (int)op;
        if (parse_bytes(PyTuple_GetItem(it, 1), tx.hash, "tx hash") < 0 ||
            parse_bytes(PyTuple_GetItem(it, 2), tx.src, "tx source") < 0) {
            Py_DECREF(seq);
            return NULL;
        }
        tx.seq = PyLong_AsLongLong(PyTuple_GetItem(it, 3));
        tx.fee = PyLong_AsLongLong(PyTuple_GetItem(it, 4));
        tx.fee_charged = PyLong_AsLongLong(PyTuple_GetItem(it, 5));
        if (op == OP_PAYMENT) {
            if (parse_bytes(PyTuple_GetItem(it, 6), tx.dest,
                            "payment dest") < 0) {
                Py_DECREF(seq);
                return NULL;
            }
            tx.amount = PyLong_AsLongLong(PyTuple_GetItem(it, 7));
            if (parse_bytes(PyTuple_GetItem(it, 8), tx.asset,
                            "payment asset") < 0) {
                Py_DECREF(seq);
                return NULL;
            }
        } else if (op == OP_MANAGE_SELL_OFFER) {
            if (parse_bytes(PyTuple_GetItem(it, 6), tx.selling,
                            "offer selling") < 0 ||
                parse_bytes(PyTuple_GetItem(it, 7), tx.buying,
                            "offer buying") < 0) {
                Py_DECREF(seq);
                return NULL;
            }
            tx.amount = PyLong_AsLongLong(PyTuple_GetItem(it, 8));
            tx.price_n = (int32_t)PyLong_AsLong(PyTuple_GetItem(it, 9));
            tx.price_d = (int32_t)PyLong_AsLong(PyTuple_GetItem(it, 10));
            tx.offer_id = PyLong_AsLongLong(PyTuple_GetItem(it, 11));
        } else if (op == OP_CHANGE_TRUST) {
            if (parse_bytes(PyTuple_GetItem(it, 6), tx.asset,
                            "trust line asset") < 0) {
                Py_DECREF(seq);
                return NULL;
            }
            tx.limit = PyLong_AsLongLong(PyTuple_GetItem(it, 7));
        } else if (op == OP_PATH_PAYMENT_STRICT_SEND ||
                   op == OP_PATH_PAYMENT_STRICT_RECEIVE) {
            if (parse_bytes(PyTuple_GetItem(it, 6), tx.dest,
                            "path dest") < 0 ||
                parse_bytes(PyTuple_GetItem(it, 7), tx.asset,
                            "path send asset") < 0) {
                Py_DECREF(seq);
                return NULL;
            }
            tx.amount = PyLong_AsLongLong(PyTuple_GetItem(it, 8));
            if (parse_bytes(PyTuple_GetItem(it, 9), tx.dest_asset,
                            "path dest asset") < 0) {
                Py_DECREF(seq);
                return NULL;
            }
            tx.amount2 = PyLong_AsLongLong(PyTuple_GetItem(it, 10));
            PyObject *hops = PySequence_Fast(
                PyTuple_GetItem(it, 11), "path hops must be a sequence");
            if (!hops) {
                Py_DECREF(seq);
                return NULL;
            }
            for (Py_ssize_t j = 0; j < PySequence_Fast_GET_SIZE(hops);
                 j++) {
                PyObject *ht = PySequence_Fast_GET_ITEM(hops, j);
                Hop hop;
                if (parse_bytes(PyTuple_GetItem(ht, 0), hop.selling,
                                "hop selling") < 0 ||
                    parse_bytes(PyTuple_GetItem(ht, 1), hop.buying,
                                "hop buying") < 0 ||
                    parse_bytes(PyTuple_GetItem(ht, 2), hop.pool_key,
                                "hop pool key") < 0) {
                    Py_DECREF(hops);
                    Py_DECREF(seq);
                    return NULL;
                }
                tx.hops.push_back(hop);
            }
            Py_DECREF(hops);
        } else {
            Py_DECREF(seq);
            PyErr_SetString(KernelError, "unsupported op type in tx strip");
            return NULL;
        }
        if (PyErr_Occurred()) {
            Py_DECREF(seq);
            return NULL;
        }
        c.txs.push_back(tx);
    }
    Py_DECREF(seq);

    /* GIL-free strip apply: parse entries, run every tx, build deltas.
     * All state is kernel-local, so a Decline discards everything. */
    bool declined = false;
    std::string decline_reason;
    long decline_tx = -1;
    std::vector<std::pair<std::string, bool>> delta_keys;
    std::vector<std::string> delta_bytes;

    Py_BEGIN_ALLOW_THREADS;
    try {
        for (auto &kv : c.store)
            if (kv.second.exists)
                parse_entry(kv.second);
        for (size_t i = 0; i < c.txs.size(); i++) {
            try {
                run_tx(c, i);
            } catch (Decline &d) {
                decline_tx = (long)i;
                throw;
            }
        }
        for (auto &kv : c.store) {
            Entry &e = kv.second;
            if (!e.dirty)
                continue;
            delta_keys.push_back({kv.first, e.exists});
            delta_bytes.push_back(e.exists ? encode_entry(e)
                                           : std::string());
        }
    } catch (Decline &d) {
        declined = true;
        decline_reason = d.reason;
    }
    Py_END_ALLOW_THREADS;

    if (declined) {
        return Py_BuildValue("(Osl)", Py_False, decline_reason.c_str(),
                             decline_tx);
    }

    PyObject *deltas = PyList_New((Py_ssize_t)delta_keys.size());
    if (!deltas)
        return NULL;
    for (size_t i = 0; i < delta_keys.size(); i++) {
        PyObject *key = PyBytes_FromStringAndSize(
            delta_keys[i].first.data(),
            (Py_ssize_t)delta_keys[i].first.size());
        PyObject *val;
        if (delta_keys[i].second)
            val = PyBytes_FromStringAndSize(
                delta_bytes[i].data(), (Py_ssize_t)delta_bytes[i].size());
        else
            val = Py_NewRef(Py_None);
        if (!key || !val) {
            Py_XDECREF(key);
            Py_XDECREF(val);
            Py_DECREF(deltas);
            return NULL;
        }
        PyObject *tup = PyTuple_Pack(2, key, val);
        Py_DECREF(key);
        Py_DECREF(val);
        if (!tup) {
            Py_DECREF(deltas);
            return NULL;
        }
        PyList_SET_ITEM(deltas, (Py_ssize_t)i, tup);
    }

    PyObject *records = PyList_New((Py_ssize_t)c.records.size());
    if (!records) {
        Py_DECREF(deltas);
        return NULL;
    }
    for (size_t i = 0; i < c.records.size(); i++) {
        PyObject *tup = Py_BuildValue(
            "(y#y#)", c.records[i].first.data(),
            (Py_ssize_t)c.records[i].first.size(),
            c.records[i].second.data(),
            (Py_ssize_t)c.records[i].second.size());
        if (!tup) {
            Py_DECREF(deltas);
            Py_DECREF(records);
            return NULL;
        }
        PyList_SET_ITEM(records, (Py_ssize_t)i, tup);
    }

    return Py_BuildValue("(ONNL)", Py_True, deltas, records,
                         (long long)c.idpool);
}

/* charge_fees(params, accounts, txs): the whole fee/seqnum phase as
 * one GIL-released batch (frame.process_fee_seq_num's success path).
 *   params   = (ledger_seq, base_fee)
 *   accounts = [entry_bytes, ...] distinct fee sources, first-appearance
 *              order (every one must exist — the host screens absence)
 *   txs      = [(acct_idx, full_fee, num_ops), ...] in apply order
 * -> (True, [(charged, state_bytes, updated_bytes)...],
 *     [final_entry_bytes...], fee_pool_delta)
 *  | (False, reason)
 * The per-tx change pair mirrors LedgerTxn.changes(): STATE carries the
 * RUNNING pre-image (a repeat source sees the prior tx's post-image,
 * lastModified already restamped), UPDATED the post-charge image. */
static PyObject *charge_fees(PyObject *self, PyObject *args) {
    PyObject *params, *accounts, *txs;
    if (!PyArg_ParseTuple(args, "OOO", &params, &accounts, &txs))
        return NULL;
    long long ls, bf;
    if (!PyArg_ParseTuple(params, "LL", &ls, &bf))
        return NULL;

    std::vector<Entry> accts;
    PyObject *seq = PySequence_Fast(accounts, "accounts must be a sequence");
    if (!seq)
        return NULL;
    accts.resize((size_t)PySequence_Fast_GET_SIZE(seq));
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); i++) {
        if (parse_bytes(PySequence_Fast_GET_ITEM(seq, i),
                        accts[(size_t)i].raw, "fee account bytes") < 0) {
            Py_DECREF(seq);
            return NULL;
        }
        accts[(size_t)i].exists = true;
    }
    Py_DECREF(seq);

    struct FeeTx {
        long acct;
        int64_t full_fee;
        long num_ops;
    };
    std::vector<FeeTx> fts;
    seq = PySequence_Fast(txs, "fee txs must be a sequence");
    if (!seq)
        return NULL;
    for (Py_ssize_t i = 0; i < PySequence_Fast_GET_SIZE(seq); i++) {
        PyObject *it = PySequence_Fast_GET_ITEM(seq, i);
        FeeTx ft;
        ft.acct = PyLong_AsLong(PyTuple_GetItem(it, 0));
        ft.full_fee = PyLong_AsLongLong(PyTuple_GetItem(it, 1));
        ft.num_ops = PyLong_AsLong(PyTuple_GetItem(it, 2));
        if (PyErr_Occurred()) {
            Py_DECREF(seq);
            return NULL;
        }
        fts.push_back(ft);
    }
    Py_DECREF(seq);

    bool declined = false;
    std::string decline_reason;
    std::vector<int64_t> charged(fts.size(), 0);
    std::vector<std::string> state_b(fts.size()), upd_b(fts.size());
    std::vector<std::string> final_b(accts.size());
    int64_t fee_pool = 0;

    Py_BEGIN_ALLOW_THREADS;
    try {
        for (auto &e : accts) {
            parse_entry(e);
            need(e.kind == K_ACCT && e.supported,
                 "unsupported account shape");
        }
        for (size_t i = 0; i < fts.size(); i++) {
            FeeTx &ft = fts[i];
            need(ft.acct >= 0 && (size_t)ft.acct < accts.size(),
                 "fee account index out of range");
            Entry &e = accts[(size_t)ft.acct];
            /* fee = min(full_fee, base_fee * max(1, num_ops)); the
             * product is bounded in i128 and min() with an int64 */
            i128 per_ops = (i128)bf * (ft.num_ops > FEE_OPS_FLOOR
                                           ? ft.num_ops
                                           : FEE_OPS_FLOOR);
            i128 fee = (i128)ft.full_fee < per_ops ? (i128)ft.full_fee
                                                   : per_ops;
            int64_t ch = (int64_t)(fee < (i128)e.acct.balance
                                       ? fee
                                       : (i128)e.acct.balance);
            Wr st;
            st.u32(CH_STATE);
            st.raw(encode_entry(e));
            state_b[i] = st.out;
            e.acct.balance -= ch;
            e.lastModified = (uint32_t)ls;
            Wr up;
            up.u32(CH_UPDATED);
            up.raw(encode_entry(e));
            upd_b[i] = up.out;
            charged[i] = ch;
            need(fee_pool <= INT64_MAX_ - ch, "fee pool overflow");
            fee_pool += ch;
        }
        for (size_t i = 0; i < accts.size(); i++)
            final_b[i] = encode_entry(accts[i]);
    } catch (Decline &d) {
        declined = true;
        decline_reason = d.reason;
    }
    Py_END_ALLOW_THREADS;

    if (declined)
        return Py_BuildValue("(Os)", Py_False, decline_reason.c_str());

    PyObject *rows = PyList_New((Py_ssize_t)fts.size());
    if (!rows)
        return NULL;
    for (size_t i = 0; i < fts.size(); i++) {
        PyObject *tup = Py_BuildValue(
            "(Ly#y#)", (long long)charged[i], state_b[i].data(),
            (Py_ssize_t)state_b[i].size(), upd_b[i].data(),
            (Py_ssize_t)upd_b[i].size());
        if (!tup) {
            Py_DECREF(rows);
            return NULL;
        }
        PyList_SET_ITEM(rows, (Py_ssize_t)i, tup);
    }
    PyObject *finals = PyList_New((Py_ssize_t)accts.size());
    if (!finals) {
        Py_DECREF(rows);
        return NULL;
    }
    for (size_t i = 0; i < accts.size(); i++) {
        PyObject *b = PyBytes_FromStringAndSize(
            final_b[i].data(), (Py_ssize_t)final_b[i].size());
        if (!b) {
            Py_DECREF(rows);
            Py_DECREF(finals);
            return NULL;
        }
        PyList_SET_ITEM(finals, (Py_ssize_t)i, b);
    }
    return Py_BuildValue("(ONNL)", Py_True, rows, finals,
                         (long long)fee_pool);
}

static PyMethodDef Methods[] = {
    {"apply_cluster", apply_cluster, METH_VARARGS,
     "Apply one kernel-eligible cluster strip GIL-free; returns "
     "(True, deltas, records, idpool) or (False, reason, tx_index)."},
    {"charge_fees", charge_fees, METH_VARARGS,
     "Charge the whole fee phase GIL-free; returns (True, rows, "
     "finals, fee_pool_delta) or (False, reason)."},
    {NULL, NULL, 0, NULL},
};

static struct PyModuleDef moduledef = {
    PyModuleDef_HEAD_INIT, "_applykernel",
    "GIL-free native transaction-apply kernel", -1, Methods,
};

PyMODINIT_FUNC PyInit__applykernel(void) {
    PyObject *m = PyModule_Create(&moduledef);
    if (!m)
        return NULL;
    KernelError =
        PyErr_NewException("_applykernel.KernelError", NULL, NULL);
    if (!KernelError || PyModule_AddObject(m, "KernelError", KernelError) <
                            0) {
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
