// Native bucket-merge kernel: the sorted two-way merge with
// INIT/LIVE/DEAD shadowing semantics over serialized bucket entries
// (ref src/bucket/Bucket.cpp merge logic + BucketOutputIterator — the
// reference's background worker compute; SURVEY.md §2.7).
//
// The Python tier passes two entry tables as flat arrays:
//   keys:    concatenated key bytes
//   k_off/k_len: per-entry key slices (int64/int32)
//   types:   per-entry BucketEntryType (0=LIVE,1=DEAD,2=INIT per
//            protocol-11+ semantics, matching xdr types)
// and receives, for each surviving output slot, the source side
// (0=newer, 1=older), the source index, and a result type override
// (-1 = keep source entry unchanged; else re-tag to this type, which
// Python applies by rebuilding the entry with the same value).
//
// Merge-case table mirrors stellar_core_tpu/bucket/bucket_list.py
// _merge_entry (itself re-derived from Bucket::mergeCasesWithEqualKeys):
//   DEAD over INIT              -> annihilate
//   LIVE/INIT over INIT         -> INIT with newer value
//   INIT over DEAD              -> LIVE with newer value
//   otherwise                   -> newer entry unchanged
//
// Build: g++ -O2 -shared -fPIC -o _native.so bucket_merge.cpp

#include <cstdint>
#include <cstring>

namespace {

// lexicographic compare of two byte slices
int cmp_keys(const uint8_t* a, int32_t alen, const uint8_t* b,
             int32_t blen) {
  int32_t n = alen < blen ? alen : blen;
  int c = std::memcmp(a, b, static_cast<size_t>(n));
  if (c != 0) return c;
  if (alen == blen) return 0;
  return alen < blen ? -1 : 1;
}

constexpr int32_t kLive = 0;
constexpr int32_t kDead = 1;
constexpr int32_t kInit = 2;

}  // namespace

extern "C" {

// Returns the number of output entries written to out_side/out_idx/
// out_type (capacity must be >= n_new + n_old).
int64_t bucket_merge(
    const uint8_t* new_keys, const int64_t* new_off,
    const int32_t* new_len, const int32_t* new_types, int64_t n_new,
    const uint8_t* old_keys, const int64_t* old_off,
    const int32_t* old_len, const int32_t* old_types, int64_t n_old,
    int32_t* out_side, int64_t* out_idx, int32_t* out_type) {
  int64_t i = 0, j = 0, w = 0;
  while (i < n_new && j < n_old) {
    int c = cmp_keys(new_keys + new_off[i], new_len[i],
                     old_keys + old_off[j], old_len[j]);
    if (c < 0) {
      out_side[w] = 0; out_idx[w] = i; out_type[w] = -1;
      ++w; ++i;
    } else if (c > 0) {
      out_side[w] = 1; out_idx[w] = j; out_type[w] = -1;
      ++w; ++j;
    } else {
      int32_t nt = new_types[i];
      int32_t ot = old_types[j];
      if (nt == kDead && ot == kInit) {
        // annihilate: entry never existed at this level
      } else if ((nt == kLive || nt == kInit) && ot == kInit) {
        out_side[w] = 0; out_idx[w] = i; out_type[w] = kInit; ++w;
      } else if (nt == kInit && ot == kDead) {
        out_side[w] = 0; out_idx[w] = i; out_type[w] = kLive; ++w;
      } else {
        out_side[w] = 0; out_idx[w] = i; out_type[w] = -1; ++w;
      }
      ++i; ++j;
    }
  }
  for (; i < n_new; ++i) {
    out_side[w] = 0; out_idx[w] = i; out_type[w] = -1; ++w;
  }
  for (; j < n_old; ++j) {
    out_side[w] = 1; out_idx[w] = j; out_type[w] = -1; ++w;
  }
  return w;
}

// Batched lexicographic lower_bound over a sorted key table — the
// BucketIndex point-lookup core (ref src/bucket/BucketIndexImpl.cpp).
// Writes, per probe, the index of the first key >= probe (or n_keys).
void bucket_lower_bound(
    const uint8_t* keys, const int64_t* k_off, const int32_t* k_len,
    int64_t n_keys,
    const uint8_t* probes, const int64_t* p_off, const int32_t* p_len,
    int64_t n_probes, int64_t* out_pos) {
  for (int64_t p = 0; p < n_probes; ++p) {
    int64_t lo = 0, hi = n_keys;
    const uint8_t* pk = probes + p_off[p];
    int32_t pl = p_len[p];
    while (lo < hi) {
      int64_t mid = lo + (hi - lo) / 2;
      int c = cmp_keys(keys + k_off[mid], k_len[mid], pk, pl);
      if (c < 0) {
        lo = mid + 1;
      } else {
        hi = mid;
      }
    }
    out_pos[p] = lo;
  }
}

}  // extern "C"
