// Native bucket-merge kernel: the sorted two-way merge with
// INIT/LIVE/DEAD shadowing semantics over serialized bucket entries
// (ref src/bucket/Bucket.cpp merge logic + BucketOutputIterator — the
// reference's background worker compute; SURVEY.md §2.7).
//
// The Python tier passes two entry tables as flat arrays:
//   keys:    concatenated key bytes
//   k_off/k_len: per-entry key slices (int64/int32)
//   types:   per-entry BucketEntryType (0=LIVE,1=DEAD,2=INIT per
//            protocol-11+ semantics, matching xdr types)
// and receives, for each surviving output slot, the source side
// (0=newer, 1=older), the source index, and a result type override
// (-1 = keep source entry unchanged; else re-tag to this type, which
// Python applies by rebuilding the entry with the same value).
//
// Merge-case table mirrors stellar_core_tpu/bucket/bucket_list.py
// _merge_entry (itself re-derived from Bucket::mergeCasesWithEqualKeys):
//   DEAD over INIT              -> annihilate
//   LIVE/INIT over INIT         -> INIT with newer value
//   INIT over DEAD              -> LIVE with newer value
//   otherwise                   -> newer entry unchanged
//
// Build: g++ -O2 -shared -fPIC -o _native.so bucket_merge.cpp

#include <cstdint>
#include <cstdio>
#include <cstring>

namespace {

// lexicographic compare of two byte slices
int cmp_keys(const uint8_t* a, int32_t alen, const uint8_t* b,
             int32_t blen) {
  int32_t n = alen < blen ? alen : blen;
  int c = std::memcmp(a, b, static_cast<size_t>(n));
  if (c != 0) return c;
  if (alen == blen) return 0;
  return alen < blen ? -1 : 1;
}

constexpr int32_t kLive = 0;
constexpr int32_t kDead = 1;
constexpr int32_t kInit = 2;

// ---- CRC-32 (ISO-HDLC, reflected, poly 0xEDB88320) ------------------------
// Bit-identical to Python's zlib.crc32(data, start) so the bloom filters
// built here and the Python fallback tier (bucket/index.py) interoperate:
// a filter persisted by either side answers queries from the other.

struct Crc32Table {
  uint32_t t[256];
  Crc32Table() {
    for (uint32_t i = 0; i < 256; ++i) {
      uint32_t c = i;
      for (int k = 0; k < 8; ++k)
        c = (c & 1u) ? 0xEDB88320u ^ (c >> 1) : c >> 1;
      t[i] = c;
    }
  }
};

uint32_t crc32_update(uint32_t start, const uint8_t* data, int32_t len) {
  static const Crc32Table table;
  uint32_t crc = start ^ 0xFFFFFFFFu;
  for (int32_t i = 0; i < len; ++i)
    crc = table.t[(crc ^ data[i]) & 0xFFu] ^ (crc >> 8);
  return crc ^ 0xFFFFFFFFu;
}

// blocked-bloom probe layout shared with bucket/index.py: h1 selects the
// 64-bit block, four 6-bit slices of h2 select bits inside it
constexpr uint32_t kBloomSeed2 = 0x9E3779B9u;

uint64_t bloom_mask(uint32_t h2) {
  uint64_t m = 0;
  for (int shift = 0; shift < 24; shift += 6)
    m |= 1ull << ((h2 >> shift) & 63u);
  return m;
}

// ---- SHA-256 (FIPS 180-4), self-contained so the whole merge --------------
// (compare + copy + bucket hash) runs inside one GIL-free native call.

struct Sha256 {
  uint32_t h[8];
  uint64_t len = 0;
  uint8_t buf[64];
  size_t buf_used = 0;

  Sha256() {
    static const uint32_t init[8] = {
        0x6a09e667u, 0xbb67ae85u, 0x3c6ef372u, 0xa54ff53au,
        0x510e527fu, 0x9b05688cu, 0x1f83d9abu, 0x5be0cd19u};
    std::memcpy(h, init, sizeof(h));
  }

  static uint32_t rotr(uint32_t x, int n) {
    return (x >> n) | (x << (32 - n));
  }

  void block(const uint8_t* p) {
    static const uint32_t K[64] = {
        0x428a2f98u, 0x71374491u, 0xb5c0fbcfu, 0xe9b5dba5u, 0x3956c25bu,
        0x59f111f1u, 0x923f82a4u, 0xab1c5ed5u, 0xd807aa98u, 0x12835b01u,
        0x243185beu, 0x550c7dc3u, 0x72be5d74u, 0x80deb1feu, 0x9bdc06a7u,
        0xc19bf174u, 0xe49b69c1u, 0xefbe4786u, 0x0fc19dc6u, 0x240ca1ccu,
        0x2de92c6fu, 0x4a7484aau, 0x5cb0a9dcu, 0x76f988dau, 0x983e5152u,
        0xa831c66du, 0xb00327c8u, 0xbf597fc7u, 0xc6e00bf3u, 0xd5a79147u,
        0x06ca6351u, 0x14292967u, 0x27b70a85u, 0x2e1b2138u, 0x4d2c6dfcu,
        0x53380d13u, 0x650a7354u, 0x766a0abbu, 0x81c2c92eu, 0x92722c85u,
        0xa2bfe8a1u, 0xa81a664bu, 0xc24b8b70u, 0xc76c51a3u, 0xd192e819u,
        0xd6990624u, 0xf40e3585u, 0x106aa070u, 0x19a4c116u, 0x1e376c08u,
        0x2748774cu, 0x34b0bcb5u, 0x391c0cb3u, 0x4ed8aa4au, 0x5b9cca4fu,
        0x682e6ff3u, 0x748f82eeu, 0x78a5636fu, 0x84c87814u, 0x8cc70208u,
        0x90befffau, 0xa4506cebu, 0xbef9a3f7u, 0xc67178f2u};
    uint32_t w[64];
    for (int i = 0; i < 16; ++i) {
      w[i] = (uint32_t(p[4 * i]) << 24) | (uint32_t(p[4 * i + 1]) << 16) |
             (uint32_t(p[4 * i + 2]) << 8) | uint32_t(p[4 * i + 3]);
    }
    for (int i = 16; i < 64; ++i) {
      uint32_t s0 = rotr(w[i - 15], 7) ^ rotr(w[i - 15], 18) ^
                    (w[i - 15] >> 3);
      uint32_t s1 = rotr(w[i - 2], 17) ^ rotr(w[i - 2], 19) ^
                    (w[i - 2] >> 10);
      w[i] = w[i - 16] + s0 + w[i - 7] + s1;
    }
    uint32_t a = h[0], b = h[1], c = h[2], d = h[3];
    uint32_t e = h[4], f = h[5], g = h[6], hh = h[7];
    for (int i = 0; i < 64; ++i) {
      uint32_t S1 = rotr(e, 6) ^ rotr(e, 11) ^ rotr(e, 25);
      uint32_t ch = (e & f) ^ (~e & g);
      uint32_t t1 = hh + S1 + ch + K[i] + w[i];
      uint32_t S0 = rotr(a, 2) ^ rotr(a, 13) ^ rotr(a, 22);
      uint32_t maj = (a & b) ^ (a & c) ^ (b & c);
      uint32_t t2 = S0 + maj;
      hh = g; g = f; f = e; e = d + t1;
      d = c; c = b; b = a; a = t1 + t2;
    }
    h[0] += a; h[1] += b; h[2] += c; h[3] += d;
    h[4] += e; h[5] += f; h[6] += g; h[7] += hh;
  }

  void update(const uint8_t* p, size_t n) {
    len += n;
    if (buf_used) {
      size_t take = 64 - buf_used;
      if (take > n) take = n;
      std::memcpy(buf + buf_used, p, take);
      buf_used += take;
      p += take;
      n -= take;
      if (buf_used == 64) {
        block(buf);
        buf_used = 0;
      }
    }
    while (n >= 64) {
      block(p);
      p += 64;
      n -= 64;
    }
    if (n) {
      std::memcpy(buf, p, n);
      buf_used = n;
    }
  }

  void final(uint8_t out[32]) {
    uint64_t bits = len * 8;
    uint8_t pad = 0x80;
    update(&pad, 1);
    uint8_t zero = 0;
    while (buf_used != 56) update(&zero, 1);
    uint8_t lenb[8];
    for (int i = 0; i < 8; ++i) lenb[i] = uint8_t(bits >> (56 - 8 * i));
    // bypass the length accounting for the trailer
    std::memcpy(buf + 56, lenb, 8);
    block(buf);
    for (int i = 0; i < 8; ++i) {
      out[4 * i] = uint8_t(h[i] >> 24);
      out[4 * i + 1] = uint8_t(h[i] >> 16);
      out[4 * i + 2] = uint8_t(h[i] >> 8);
      out[4 * i + 3] = uint8_t(h[i]);
    }
  }
};

// one side of a streaming merge: serialized entry stream + flat tables
struct Side {
  const uint8_t* stream;
  const int64_t* eoff;
  const int32_t* elen;
  const uint8_t* keys;
  const int64_t* koff;
  const int32_t* klen;
  const int32_t* types;
  int64_t n;
};

// emit entry `idx` of `s` (re-tagged to `type` when >= 0) into the output
// file/hash and the output tables; returns false on I/O error
bool emit(const Side& s, int64_t idx, int32_t type, FILE* out, Sha256& sha,
          int64_t& wbytes, int64_t& kbytes, int64_t w, int64_t* out_eoff,
          int32_t* out_elen, int32_t* out_types, uint8_t* out_keys,
          int64_t* out_koff, int32_t* out_klen) {
  const uint8_t* e = s.stream + s.eoff[idx];
  int32_t n = s.elen[idx];
  int32_t ty = type >= 0 ? type : s.types[idx];
  out_eoff[w] = wbytes;
  out_elen[w] = n;
  out_types[w] = ty;
  out_koff[w] = kbytes;
  out_klen[w] = s.klen[idx];
  std::memcpy(out_keys + kbytes, s.keys + s.koff[idx],
              size_t(s.klen[idx]));
  kbytes += s.klen[idx];
  if (type >= 0 && type != s.types[idx]) {
    // XDR union discriminant: 4-byte big-endian tag, body unchanged
    // (re-tags only occur between LIVE and INIT, whose bodies are the
    // same LedgerEntry encoding)
    uint8_t tag[4] = {uint8_t(uint32_t(ty) >> 24), uint8_t(uint32_t(ty) >> 16),
                      uint8_t(uint32_t(ty) >> 8), uint8_t(uint32_t(ty))};
    sha.update(tag, 4);
    sha.update(e + 4, size_t(n - 4));
    if (out) {
      if (fwrite(tag, 1, 4, out) != 4) return false;
      if (fwrite(e + 4, 1, size_t(n - 4), out) != size_t(n - 4))
        return false;
    }
  } else {
    sha.update(e, size_t(n));
    if (out && fwrite(e, 1, size_t(n), out) != size_t(n)) return false;
  }
  wbytes += n;
  return true;
}

}  // namespace

extern "C" {

// Returns the number of output entries written to out_side/out_idx/
// out_type (capacity must be >= n_new + n_old).
int64_t bucket_merge(
    const uint8_t* new_keys, const int64_t* new_off,
    const int32_t* new_len, const int32_t* new_types, int64_t n_new,
    const uint8_t* old_keys, const int64_t* old_off,
    const int32_t* old_len, const int32_t* old_types, int64_t n_old,
    int32_t* out_side, int64_t* out_idx, int32_t* out_type) {
  int64_t i = 0, j = 0, w = 0;
  while (i < n_new && j < n_old) {
    int c = cmp_keys(new_keys + new_off[i], new_len[i],
                     old_keys + old_off[j], old_len[j]);
    if (c < 0) {
      out_side[w] = 0; out_idx[w] = i; out_type[w] = -1;
      ++w; ++i;
    } else if (c > 0) {
      out_side[w] = 1; out_idx[w] = j; out_type[w] = -1;
      ++w; ++j;
    } else {
      int32_t nt = new_types[i];
      int32_t ot = old_types[j];
      if (nt == kDead && ot == kInit) {
        // annihilate: entry never existed at this level
      } else if ((nt == kLive || nt == kInit) && ot == kInit) {
        out_side[w] = 0; out_idx[w] = i; out_type[w] = kInit; ++w;
      } else if (nt == kInit && ot == kDead) {
        out_side[w] = 0; out_idx[w] = i; out_type[w] = kLive; ++w;
      } else {
        out_side[w] = 0; out_idx[w] = i; out_type[w] = -1; ++w;
      }
      ++i; ++j;
    }
  }
  for (; i < n_new; ++i) {
    out_side[w] = 0; out_idx[w] = i; out_type[w] = -1; ++w;
  }
  for (; j < n_old; ++j) {
    out_side[w] = 1; out_idx[w] = j; out_type[w] = -1; ++w;
  }
  return w;
}

// Full streaming shadow-merge over two serialized BucketEntry streams —
// the FutureBucket worker's compute tier.  Unlike `bucket_merge` above
// (which only plans the merge and leaves copying/hashing to Python),
// this call does EVERYTHING natively: key compare, collision resolution,
// entry byte copy (with XDR discriminant re-tag), output stream write,
// and the bucket's sha256 — so a ctypes caller holds the GIL for none of
// it and background merges genuinely overlap the main thread.
//
// Inputs per side: the serialized stream, per-entry (offset, length)
// into it, the concatenated key bytes with per-entry (offset, length),
// and per-entry BucketEntryType tags.  `out_path` receives the merged
// XDR stream (NULL = hash/tables only).  Output tables (capacity
// n_new+n_old; out_keys capacity = total input key bytes) receive the
// surviving entries' offsets/lengths/types/keys.  out_hash32 gets the
// sha256 of the output stream; *out_bytes its length.
//
// Returns the number of surviving entries, or -1 on I/O error.
int64_t bucket_merge_stream(
    const uint8_t* new_stream, const int64_t* new_eoff,
    const int32_t* new_elen, const uint8_t* new_keys,
    const int64_t* new_koff, const int32_t* new_klen,
    const int32_t* new_types, int64_t n_new,
    const uint8_t* old_stream, const int64_t* old_eoff,
    const int32_t* old_elen, const uint8_t* old_keys,
    const int64_t* old_koff, const int32_t* old_klen,
    const int32_t* old_types, int64_t n_old,
    const char* out_path,
    int64_t* out_eoff, int32_t* out_elen, int32_t* out_types,
    uint8_t* out_keys, int64_t* out_koff, int32_t* out_klen,
    uint8_t* out_hash32, int64_t* out_bytes) {
  Side nw{new_stream, new_eoff, new_elen, new_keys, new_koff, new_klen,
          new_types, n_new};
  Side od{old_stream, old_eoff, old_elen, old_keys, old_koff, old_klen,
          old_types, n_old};
  FILE* out = nullptr;
  if (out_path != nullptr && out_path[0] != '\0') {
    out = fopen(out_path, "wb");
    if (out == nullptr) return -1;
  }
  Sha256 sha;
  int64_t i = 0, j = 0, w = 0, wbytes = 0, kbytes = 0;
  bool ok = true;
  while (ok && i < n_new && j < n_old) {
    int c = cmp_keys(nw.keys + nw.koff[i], nw.klen[i],
                     od.keys + od.koff[j], od.klen[j]);
    if (c < 0) {
      ok = emit(nw, i, -1, out, sha, wbytes, kbytes, w, out_eoff,
                out_elen, out_types, out_keys, out_koff, out_klen);
      ++w; ++i;
    } else if (c > 0) {
      ok = emit(od, j, -1, out, sha, wbytes, kbytes, w, out_eoff,
                out_elen, out_types, out_keys, out_koff, out_klen);
      ++w; ++j;
    } else {
      int32_t nt = nw.types[i];
      int32_t ot = od.types[j];
      if (nt == kDead && ot == kInit) {
        // annihilate
      } else if ((nt == kLive || nt == kInit) && ot == kInit) {
        ok = emit(nw, i, kInit, out, sha, wbytes, kbytes, w, out_eoff,
                  out_elen, out_types, out_keys, out_koff, out_klen);
        ++w;
      } else if (nt == kInit && ot == kDead) {
        ok = emit(nw, i, kLive, out, sha, wbytes, kbytes, w, out_eoff,
                  out_elen, out_types, out_keys, out_koff, out_klen);
        ++w;
      } else {
        ok = emit(nw, i, -1, out, sha, wbytes, kbytes, w, out_eoff,
                  out_elen, out_types, out_keys, out_koff, out_klen);
        ++w;
      }
      ++i; ++j;
    }
  }
  for (; ok && i < n_new; ++i, ++w) {
    ok = emit(nw, i, -1, out, sha, wbytes, kbytes, w, out_eoff, out_elen,
              out_types, out_keys, out_koff, out_klen);
  }
  for (; ok && j < n_old; ++j, ++w) {
    ok = emit(od, j, -1, out, sha, wbytes, kbytes, w, out_eoff, out_elen,
              out_types, out_keys, out_koff, out_klen);
  }
  if (out != nullptr) {
    if (fclose(out) != 0) ok = false;
  }
  if (!ok) return -1;
  sha.final(out_hash32);
  *out_bytes = wbytes;
  return w;
}

// Batched lexicographic lower_bound over a sorted key table — the
// BucketIndex point-lookup core (ref src/bucket/BucketIndexImpl.cpp).
// Writes, per probe, the index of the first key >= probe (or n_keys).
void bucket_lower_bound(
    const uint8_t* keys, const int64_t* k_off, const int32_t* k_len,
    int64_t n_keys,
    const uint8_t* probes, const int64_t* p_off, const int32_t* p_len,
    int64_t n_probes, int64_t* out_pos) {
  for (int64_t p = 0; p < n_probes; ++p) {
    int64_t lo = 0, hi = n_keys;
    const uint8_t* pk = probes + p_off[p];
    int32_t pl = p_len[p];
    while (lo < hi) {
      int64_t mid = lo + (hi - lo) / 2;
      int c = cmp_keys(keys + k_off[mid], k_len[mid], pk, pl);
      if (c < 0) {
        lo = mid + 1;
      } else {
        hi = mid;
      }
    }
    out_pos[p] = lo;
  }
}

// Fill a blocked bloom filter over a key table (the per-bucket
// BucketIndex filter, ref src/bucket/BucketIndexImpl.cpp's binary fuse /
// bloom layer).  words must be zeroed, n_blocks 64-bit blocks.
void bloom_fill(const uint8_t* keys, const int64_t* k_off,
                const int32_t* k_len, int64_t n_keys, uint64_t* words,
                int64_t n_blocks) {
  if (n_blocks <= 0) return;
  for (int64_t i = 0; i < n_keys; ++i) {
    const uint8_t* k = keys + k_off[i];
    uint32_t h1 = crc32_update(0, k, k_len[i]);
    uint32_t h2 = crc32_update(kBloomSeed2, k, k_len[i]);
    words[h1 % static_cast<uint64_t>(n_blocks)] |= bloom_mask(h2);
  }
}

// Batched membership check against a blocked bloom filter: out_hit[p]=1
// when the filter MAY contain probe p (0 = definitely absent).
void bloom_check(const uint64_t* words, int64_t n_blocks,
                 const uint8_t* probes, const int64_t* p_off,
                 const int32_t* p_len, int64_t n_probes,
                 int32_t* out_hit) {
  for (int64_t p = 0; p < n_probes; ++p) {
    if (n_blocks <= 0) {
      out_hit[p] = 0;
      continue;
    }
    const uint8_t* k = probes + p_off[p];
    uint32_t h1 = crc32_update(0, k, p_len[p]);
    uint32_t h2 = crc32_update(kBloomSeed2, k, p_len[p]);
    uint64_t m = bloom_mask(h2);
    out_hit[p] =
        (words[h1 % static_cast<uint64_t>(n_blocks)] & m) == m ? 1 : 0;
  }
}

}  // extern "C"
