"""Fuzzing harnesses: tx-mode and overlay-mode
(ref src/test/FuzzerImpl.{h,cpp} + docs/fuzzing.md — the reference's AFL
`fuzz`/`gen-fuzz` subcommands; here deterministic seeded generators usable
both from pytest and the CLI).

- TxFuzzer: builds structurally-random operations against a canned ledger
  and applies them through the full TransactionFrame path.  Any outcome is
  acceptable EXCEPT an uncontrolled exception (InvariantDoesNotHold or a
  raw crash) — mirroring the reference's "apply fuzzer-built ops against a
  canned ledger" mode.
- OverlayFuzzer: feeds mutated/garbage byte streams into Peer.recv_bytes —
  the peer must close cleanly, never throw.
- XdrFuzzer: random bytes through every registered XDR type: decode either
  raises XdrError or produces a value that re-encodes canonically.
"""
from __future__ import annotations

import random
from typing import List, Optional

from .crypto import SecretKey, sha256
from .xdr import types as T


class TxFuzzer:
    """ref FuzzerImpl tx mode: signature checks are bypassed (the
    reference compiles them out under FUZZING_BUILD_MODE...; here a
    constant-true verify callable) so the fuzz explores apply logic, not
    signature rejection."""

    NUM_ACCOUNTS = 8

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)
        from .ledger.ledger_txn import LedgerTxn, LedgerTxnRoot, \
            open_database
        from .transactions import utils as U

        self.db = open_database(":memory:")
        self.root = LedgerTxnRoot(self.db)
        self.network_id = sha256(b"fuzz network")
        self.keys = [SecretKey(sha256(b"fuzz-%d" % i))
                     for i in range(self.NUM_ACCOUNTS)]

        with LedgerTxn(self.root) as ltx:
            ltx.set_header(self._genesis_header())
            ltx.commit()
        with LedgerTxn(self.root) as ltx:
            for i, sk in enumerate(self.keys):
                ltx.put(U.make_account_entry(
                    sk.public_key().raw, 10**12, seq_num=0))
            ltx.commit()

    @staticmethod
    def _genesis_header():
        sv = T.StellarValue.make(
            txSetHash=b"\x00" * 32, closeTime=1000, upgrades=[],
            ext=T.StellarValue.fields[3][1].make(
                T.StellarValueType.STELLAR_VALUE_BASIC))
        return T.LedgerHeader.make(
            ledgerVersion=19, previousLedgerHash=b"\x00" * 32,
            scpValue=sv, txSetResultHash=b"\x00" * 32,
            bucketListHash=b"\x00" * 32, ledgerSeq=1,
            totalCoins=10**18, feePool=0, inflationSeq=0, idPool=0,
            baseFee=100, baseReserve=5000000, maxTxSetSize=100,
            skipList=[b"\x00" * 32] * 4,
            ext=T.LedgerHeader.fields[14][1].make(0))

    # -- random structure generators ----------------------------------------

    def _acct(self) -> bytes:
        return self.rng.choice(self.keys).public_key().raw

    def _amount(self) -> int:
        return self.rng.choice(
            [0, 1, -1, 100, 10**7, 2**63 - 1, -(2**63),
             self.rng.randrange(0, 10**10)])

    def _asset(self):
        from .transactions import utils as U

        if self.rng.random() < 0.5:
            return U.asset_native()
        code = bytes(self.rng.randrange(32, 127)
                     for _ in range(self.rng.randrange(1, 5)))
        return U.make_asset(code, self._acct())

    def _price(self):
        return T.Price.make(n=self.rng.randrange(-3, 1000),
                            d=self.rng.randrange(-3, 1000))

    def random_operation(self):
        OT = T.OperationType
        choice = self.rng.randrange(10)
        if choice == 0:
            body = T.OperationBody.make(OT.CREATE_ACCOUNT,
                                        T.CreateAccountOp.make(
                                            destination=T.account_id(
                                                self._acct()),
                                            startingBalance=self._amount()))
        elif choice == 1:
            body = T.OperationBody.make(OT.PAYMENT, T.PaymentOp.make(
                destination=T.muxed_account(self._acct()),
                asset=self._asset(), amount=self._amount()))
        elif choice == 2:
            body = T.OperationBody.make(
                OT.MANAGE_SELL_OFFER, T.ManageSellOfferOp.make(
                    selling=self._asset(), buying=self._asset(),
                    amount=self._amount(), price=self._price(),
                    offerID=self.rng.choice([0, 1, -5, 10**6])))
        elif choice == 3:
            a = self._asset()
            body = T.OperationBody.make(
                OT.CHANGE_TRUST, T.ChangeTrustOp.make(
                    line=T.ChangeTrustAsset.make(a.type, a.value),
                    limit=self._amount()))
        elif choice == 4:
            body = T.OperationBody.make(
                OT.CREATE_CLAIMABLE_BALANCE,
                T.CreateClaimableBalanceOp.make(
                    asset=self._asset(), amount=self._amount(),
                    claimants=[T.Claimant.make(
                        T.ClaimantType.CLAIMANT_TYPE_V0,
                        T.Claimant.arms[0][1].make(
                            destination=T.account_id(self._acct()),
                            predicate=T.ClaimPredicate.make(
                                T.ClaimPredicateType
                                .CLAIM_PREDICATE_UNCONDITIONAL)))]))
        elif choice == 5:
            body = T.OperationBody.make(
                OT.BEGIN_SPONSORING_FUTURE_RESERVES,
                T.BeginSponsoringFutureReservesOp.make(
                    sponsoredID=T.account_id(self._acct())))
        elif choice == 6:
            body = T.OperationBody.make(
                OT.END_SPONSORING_FUTURE_RESERVES, None)
        elif choice == 7:
            body = T.OperationBody.make(
                OT.ACCOUNT_MERGE, T.muxed_account(self._acct()))
        elif choice == 8:
            body = T.OperationBody.make(
                OT.BUMP_SEQUENCE, T.BumpSequenceOp.make(
                    bumpTo=self._amount()))
        else:
            body = T.OperationBody.make(
                OT.MANAGE_DATA, T.ManageDataOp.make(
                    dataName=bytes(self.rng.randrange(32, 127)
                                   for _ in range(
                                       self.rng.randrange(1, 10))),
                    dataValue=(None if self.rng.random() < 0.3 else
                               bytes(self.rng.randrange(256)
                                     for _ in range(8)))))
        src = None
        if self.rng.random() < 0.3:
            src = T.muxed_account(self._acct())
        return T.Operation.make(sourceAccount=src, body=body)

    def run_one(self) -> Optional[str]:
        """Build + apply one random tx.  Returns None (survived) or a
        crash description."""
        from .ledger.ledger_txn import LedgerTxn
        from .transactions import TransactionFrame

        sk = self.rng.choice(self.keys)
        n_ops = self.rng.randrange(1, 4)
        ops = [self.random_operation() for _ in range(n_ops)]
        with LedgerTxn(self.root) as probe:
            e = probe.load_account(sk.public_key().raw)
            seq = e.data.value.seqNum if e is not None else 0
            probe.rollback()
        tx = T.Transaction.make(
            sourceAccount=T.muxed_account(sk.public_key().raw),
            fee=self.rng.choice([0, 100, 10**6]),
            seqNum=seq + 1,
            cond=T.Preconditions.make(T.PreconditionType.PRECOND_NONE),
            memo=T.MEMO_NONE_VALUE,
            operations=ops,
            ext=T.Transaction.fields[6][1].make(0))
        env = T.TransactionEnvelope.make(
            T.EnvelopeType.ENVELOPE_TYPE_TX,
            T.TransactionV1Envelope.make(tx=tx, signatures=[]))
        try:
            frame = TransactionFrame(self.network_id, env)
            with LedgerTxn(self.root) as ltx:
                frame.process_fee_seq_num(ltx, base_fee=100)
                frame.apply(ltx, verify=lambda *a: True)
                ltx.commit()
        except Exception as e:  # noqa: BLE001 — the fuzz oracle
            return f"{type(e).__name__}: {e}"
        return None

    def run(self, iterations: int) -> List[str]:
        crashes = []
        for i in range(iterations):
            r = self.run_one()
            if r is not None:
                crashes.append(f"iter {i}: {r}")
        return crashes


class OverlayFuzzer:
    """ref FuzzerImpl overlay mode: bytes into the peer pipeline."""

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def _garbage(self) -> bytes:
        n = self.rng.randrange(0, 400)
        return bytes(self.rng.randrange(256) for _ in range(n))

    def _mutated_hello(self, app) -> bytes:
        """A real HELLO frame with random byte flips."""
        from .xdr import overlay_types as O

        hello = O.Hello.make(
            ledgerVersion=19, overlayVersion=28, overlayMinVersion=27,
            networkID=app.config.network_id(), versionStr=b"fuzz",
            listeningPort=11625, peerID=T.account_id(app.config.node_id()),
            cert=O.AuthCert.make(
                pubkey=T.Curve25519Public.make(key=b"\x01" * 32),
                expiration=2**40,
                sig=b"\x00" * 64),
            nonce=b"\x07" * 32)
        msg = O.StellarMessage.make(O.MessageType.HELLO, hello)
        am = O.AuthenticatedMessage.make(
            0, O.AuthenticatedMessage.arms[0][1].make(
                sequence=0, message=msg,
                mac=T.HmacSha256Mac.make(mac=b"\x00" * 32)))
        data = bytearray(O.AuthenticatedMessage.encode(am))
        for _ in range(self.rng.randrange(0, 8)):
            data[self.rng.randrange(len(data))] = self.rng.randrange(256)
        return bytes(data)

    def run(self, iterations: int) -> List[str]:
        from .main import Application, test_config
        from .overlay.manager import OverlayManager
        from .overlay.peer import Peer, PeerRole
        from .utils.clock import ClockMode, VirtualClock

        crashes = []
        app = Application(VirtualClock(ClockMode.VIRTUAL_TIME),
                          test_config())
        app.overlay_manager = OverlayManager(app)
        app.start()

        class SinkPeer(Peer):
            def transport_write(self, data: bytes) -> None:
                pass

        for i in range(iterations):
            peer = SinkPeer(app, PeerRole.ACCEPTOR)
            app.overlay_manager.add_pending_peer(peer)
            payload = (self._mutated_hello(app)
                       if self.rng.random() < 0.5 else self._garbage())
            try:
                peer.recv_bytes(payload)
                # follow-up garbage on whatever state it reached
                peer.recv_bytes(self._garbage())
            except Exception as e:  # noqa: BLE001
                crashes.append(f"iter {i}: {type(e).__name__}: {e}")
        return crashes


class XdrFuzzer:
    """Random bytes through the XDR codec: decode raises XdrError or the
    value re-encodes (no crashes, no infinite recursion)."""

    TYPES = ["TransactionEnvelope", "LedgerEntry", "LedgerHeader",
             "SCPEnvelope", "TransactionResult", "LedgerKey",
             "ClaimPredicate"]

    def __init__(self, seed: int = 0):
        self.rng = random.Random(seed)

    def run(self, iterations: int) -> List[str]:
        from .xdr.runtime import XdrError

        crashes = []
        for i in range(iterations):
            tname = self.rng.choice(self.TYPES)
            t = getattr(T, tname)
            data = bytes(self.rng.randrange(256)
                         for _ in range(self.rng.randrange(0, 300)))
            try:
                v = t.decode(data)
            except XdrError:
                continue
            except Exception as e:  # noqa: BLE001
                crashes.append(
                    f"iter {i} {tname}: {type(e).__name__}: {e}")
                continue
            try:
                t.encode(v)
            except Exception as e:  # noqa: BLE001
                crashes.append(
                    f"iter {i} {tname} re-encode: "
                    f"{type(e).__name__}: {e}")
        return crashes
