"""TransactionQueue: the pre-consensus mempool, per source account, with
age/ban/shift lifecycle (ref src/herder/TransactionQueue.h:34-139).

Each account holds a seq-ordered chain of pending txs; entries age with
each ledger (shift) and are dropped at age limit; invalid/banned txs are
rejected with try-again-later semantics.
"""
from __future__ import annotations

from typing import Dict, List, Optional

from ..ledger.ledger_txn import LedgerTxn
from ..transactions import TransactionFrame  # noqa: F401 (typing)
from ..transactions.frame import tx_frame_from_envelope
from ..transactions.frame import TC


class AccountTxs:
    __slots__ = ("frames", "age")

    def __init__(self):
        self.frames: List[TransactionFrame] = []
        self.age = 0


class TransactionQueue:
    ADD_STATUS_PENDING = 0
    ADD_STATUS_DUPLICATE = 1
    ADD_STATUS_ERROR = 2
    ADD_STATUS_TRY_AGAIN_LATER = 3
    ADD_STATUS_BANNED = 4

    PENDING_DEPTH = 4        # max age (ref pendingDepth)
    BAN_DEPTH = 10           # ledgers a banned tx stays banned
    MAX_PER_ACCOUNT = 112    # queue limit per account (v19 default ~)

    def __init__(self, app):
        self.app = app
        self.accounts: Dict[bytes, AccountTxs] = {}
        self.banned: List[set] = [set() for _ in range(self.BAN_DEPTH)]
        self.known: Dict[bytes, TransactionFrame] = {}
        self._ops_count = 0  # running total (capacity checks are O(1))

    # -- admission ---------------------------------------------------------

    def try_add(self, env, recv_ts=None) -> int:
        """ref tryAdd :130 — the north-star admission path.

        ``recv_ts``: overlay-receive timestamp token (a value from
        ``app.txtracer.note_recv()``) so the lifecycle tracker's
        recv->admit delta covers the decode/validity/signature cost."""
        network_id = self.app.config.network_id()
        try:
            frame = tx_frame_from_envelope(network_id, env)
        except Exception:
            return self.ADD_STATUS_ERROR
        h = frame.full_hash()
        if h in self.known:
            return self.ADD_STATUS_DUPLICATE
        if any(h in b for b in self.banned):
            return self.ADD_STATUS_BANNED

        src = frame.source_account_id()
        acct = self.accounts.get(src)
        # cheap capacity check BEFORE the expensive validity/signature work
        if acct is not None and len(acct.frames) >= self.MAX_PER_ACCOUNT:
            return self.ADD_STATUS_TRY_AGAIN_LATER
        lm = self.app.ledger_manager

        # seq continuity: must extend the chain (account seq + queued txs)
        with LedgerTxn(lm.root) as ltx:
            entry = ltx.load_account(src)
            base_seq = entry.data.value.seqNum if entry else None
            expected = base_seq
            if acct is not None and acct.frames:
                expected = acct.frames[-1].seq_num()
            if base_seq is None:
                ltx.rollback()
                return self.ADD_STATUS_ERROR
            if frame.seq_num() != expected + 1:
                ltx.rollback()
                return self.ADD_STATUS_TRY_AGAIN_LATER
            # full validity, treating queued predecessors as applied
            res = frame.check_valid(ltx, current_seq=expected)
            ltx.rollback()
        if not res.ok:
            return self.ADD_STATUS_ERROR
        # stamp the verdict: TxSetFrame.make_from_transactions skips a
        # full re-check for frames validated against this same LCL (the
        # reference pays the re-check in C++; here it would dominate the
        # close trigger)
        frame.checked_valid_lcl = lm.last_closed_seq()

        # global capacity: evict the cheapest tails, or reject the
        # newcomer if IT is the cheapest (ref TxQueueLimiter::canAddTx)
        if not self._make_room_for(frame):
            return self.ADD_STATUS_TRY_AGAIN_LATER

        if acct is None:
            acct = self.accounts[src] = AccountTxs()
        acct.frames.append(frame)
        self.known[h] = frame
        self._ops_count += frame.num_operations()
        self.app.metrics.counter("herder.pending-txs.count").inc()
        # lifecycle telemetry sampling gate (observational; the stamp's
        # wallclock read lives in utils/txtrace.py)
        self.app.txtracer.on_admit(h, recv_ts)
        return self.ADD_STATUS_PENDING

    # -- global size limiting (ref src/herder/TxQueueLimiter.h) ------------

    def _capacity_ops(self) -> int:
        return (self.app.config.TRANSACTION_QUEUE_SIZE_MULTIPLIER
                * self.app.ledger_manager.last_closed_header()
                .maxTxSetSize)

    @staticmethod
    def _fee_rate_lt(a, b) -> bool:
        """fee-per-op(a) < fee-per-op(b), exact cross-multiply."""
        return (a.fee_bid() * b.num_operations()
                < b.fee_bid() * a.num_operations())

    def _make_room_for(self, frame) -> bool:
        """Evict lowest-fee-rate account tails until the new tx fits;
        False (reject) when enough room cannot be freed from txs cheaper
        than the newcomer.  All-or-nothing: victims are only removed
        once the plan covers the shortfall, so a rejected newcomer never
        costs the queue anything.  The newcomer's own account chain is
        never broken.  Evicted txs are banned (BAN_DEPTH ledgers, same
        as age-outs) so their re-flood doesn't thrash the queue (ref
        TxQueueLimiter eviction + ban)."""
        cap = self._capacity_ops()
        shortfall = self._ops_count + frame.num_operations() - cap
        if shortfall <= 0:
            return True
        src = frame.source_account_id()
        tails = []  # planned victims, cheapest first, per-account tails
        depth: Dict[bytes, int] = {}
        # sorted once outside the planning loop (accounts don't change
        # until eviction below): victim ties must break by account id,
        # not by arrival/hash order (detlint det-unsorted-iter)
        accounts_by_id = sorted(self.accounts.items())
        while shortfall > 0:
            victim_src = None
            victim = None
            for vsrc, acct in accounts_by_id:
                if vsrc == src:
                    continue  # never break the newcomer's own chain
                idx = len(acct.frames) - 1 - depth.get(vsrc, 0)
                if idx < 0:
                    continue
                tail = acct.frames[idx]
                if victim is None or self._fee_rate_lt(tail, victim):
                    victim_src = vsrc
                    victim = tail
            if victim is None or not self._fee_rate_lt(victim, frame):
                return False  # can't free enough from cheaper txs
            tails.append((victim_src, victim))
            depth[victim_src] = depth.get(victim_src, 0) + 1
            shortfall -= victim.num_operations()
        for victim_src, victim in tails:
            self.accounts[victim_src].frames.pop()
            self.known.pop(victim.full_hash(), None)
            self.banned[0].add(victim.full_hash())
            self._ops_count -= victim.num_operations()
            self.app.metrics.counter("herder.pending-txs.count").dec()
        return True

    # -- harvesting --------------------------------------------------------

    def get_transactions(self) -> List[TransactionFrame]:
        out: List[TransactionFrame] = []
        for acct in self.accounts.values():
            out.extend(acct.frames)
        return out

    # -- lifecycle ---------------------------------------------------------

    def shift(self, ltx_root) -> None:
        """Post-close: drop applied/invalidated txs, age the rest, ban
        expired ones (ref shift :139 + removeApplied)."""
        self.banned.pop()
        self.banned.insert(0, set())
        with LedgerTxn(ltx_root) as ltx:
            for src in list(self.accounts):
                acct = self.accounts[src]
                entry = ltx.load_account(src)
                seq = entry.data.value.seqNum if entry else -1
                kept = [f for f in acct.frames if f.seq_num() > seq]
                dropped = [f for f in acct.frames if f.seq_num() <= seq]
                for f in dropped:
                    self.known.pop(f.full_hash(), None)
                acct.frames = kept
                if dropped:
                    acct.age = 0  # account made progress
                else:
                    acct.age += 1
                if acct.age >= self.PENDING_DEPTH:
                    for f in acct.frames:
                        self.known.pop(f.full_hash(), None)
                        self.banned[0].add(f.full_hash())
                    acct.frames = []
                if not acct.frames:
                    if acct.age >= self.PENDING_DEPTH or not kept:
                        self.accounts.pop(src, None)
            ltx.rollback()
        self._ops_count = sum(f.num_operations()
                              for acct in self.accounts.values()
                              for f in acct.frames)
        self.app.metrics.counter("herder.pending-txs.count").set_count(
            len(self.known))

    def is_banned(self, tx_hash: bytes) -> bool:
        return any(tx_hash in b for b in self.banned)

    def size(self) -> int:
        return len(self.known)
