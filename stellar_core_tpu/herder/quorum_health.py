"""Quorum-health monitor: continuous evaluation of the live qset graph.

PR 10's vitals answer "is this NODE drifting"; nothing answered "is
this node's QUORUM drifting" — validators silently dropping out of a
slice, a silent set growing v-blocking (one more loss and the node can
neither accept nor abort), or a network whose announced qsets stopped
enjoying intersection.  This monitor runs one cheap evaluation per
closed ledger over the slot's heard envelopes and the local quorum
set, plus an optional budget-capped quorum-intersection scan every N
ledgers, and exports everything as ``quorum.health.*`` gauges (JSON +
Prometheus ``/metrics``), the ``quorum-health`` admin endpoint, and an
SLO hook in the PR-10 vitals watchdog (``SLO_QUORUM_AVAILABILITY``:
a sample taken while the local slice is unsatisfiable from
recently-heard nodes is a breach episode).

Per close (all O(|qset|) with top-level-slice checks):
  heard / heard_fraction   local-qset members with envelopes this slot
  available                is_quorum_slice(qset, heard) — can my own
                           slice still be satisfied by live nodes
  silent_v_blocking        the SILENT set is v-blocking: every quorum
                           of mine needs at least one node that is not
                           talking — the stall precursor
  critical                 heard members whose single loss would flip
                           ``available`` off (node criticality)
  tracked / missing_qsets  transitive-quorum bookkeeping (QuorumTracker)

The monitor only READS consensus state and writes metrics/logs — it
feeds nothing back (same inertness contract as the SCP timeline
recorder, and the same telemetry-on/off bit-identity tests cover it).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set

from ..scp.local_node import is_quorum_slice, is_v_blocking, qset_nodes


class QuorumHealthMonitor:
    def __init__(self, herder):
        self.herder = herder
        self.app = herder.app
        cfg = self.app.config
        self.enabled = bool(getattr(cfg, "QUORUM_HEALTH_ENABLED", True))
        self.intersection_period = int(getattr(
            cfg, "QUORUM_HEALTH_INTERSECTION_PERIOD", 0))
        self.intersection_max_calls = int(getattr(
            cfg, "QUORUM_HEALTH_INTERSECTION_MAX_CALLS", 200_000))
        self.intersection_timeout = float(getattr(
            cfg, "QUORUM_HEALTH_INTERSECTION_TIMEOUT_SECONDS", 1.0))
        self.last: Optional[dict] = None
        self.last_intersection: Optional[dict] = None
        self.evaluations = 0
        self.last_eval_time = 0.0
        self._warned_unavailable = False

    # -- per-close evaluation ----------------------------------------------

    def on_ledger_closed(self, seq: int) -> None:
        if not self.enabled:
            return
        self.evaluate(seq)
        if self.intersection_period > 0 and \
                seq % self.intersection_period == 0:
            self.check_intersection(seq)

    def _heard_nodes(self, seq: int) -> Set[bytes]:
        """Nodes whose envelopes (either protocol) this node recorded
        for the slot, plus self — the 'recently live' set."""
        scp = self.herder.scp
        heard: Set[bytes] = {scp.local_node.node_id}
        slot = scp.get_slot(seq, create=False)
        if slot is not None:
            heard.update(slot.ballot.latest_envelopes)
            heard.update(slot.nomination.latest_nominations)
        return heard

    def evaluate(self, seq: int) -> dict:
        scp = self.herder.scp
        local_id = scp.local_node.node_id
        qset = scp.local_node.qset
        heard = self._heard_nodes(seq)
        members = sorted(qset_nodes(qset))
        heard_members = [n for n in members if n in heard]
        silent = [n for n in members if n not in heard]
        available = is_quorum_slice(qset, heard)
        blocked = is_v_blocking(qset, set(silent))
        critical: List[bytes] = []
        if available:
            for n in heard_members:
                if n == local_id:
                    continue
                if not is_quorum_slice(qset, heard - {n}):
                    critical.append(n)
        qt = self.herder.quorum_tracker
        missing = qt.nodes_missing_qsets()
        rep = {
            "seq": seq,
            "qset_members": len(members),
            "heard": len(heard_members),
            "heard_fraction": round(
                len(heard_members) / len(members), 4) if members else 0.0,
            "available": bool(available),
            "silent": [n.hex()[:8] for n in silent],
            "silent_v_blocking": bool(blocked),
            "critical": [n.hex()[:8] for n in critical],
            "tracked_nodes": len(qt.quorum),
            "missing_qsets": len(missing),
        }
        self.last = rep
        self.evaluations += 1
        self.last_eval_time = self.app.clock.now()
        m = self.app.metrics
        m.counter("quorum.health.evaluations").inc()
        m.gauge("quorum.health.qset-members").set(len(members))
        m.gauge("quorum.health.heard").set(len(heard_members))
        m.gauge("quorum.health.heard-fraction").set(rep["heard_fraction"])
        m.gauge("quorum.health.available").set(1.0 if available else 0.0)
        m.gauge("quorum.health.silent-v-blocking").set(
            1.0 if blocked else 0.0)
        m.gauge("quorum.health.critical-heard").set(len(critical))
        m.gauge("quorum.health.tracked-nodes").set(len(qt.quorum))
        m.gauge("quorum.health.missing-qsets").set(len(missing))
        if not available or blocked:
            if not self._warned_unavailable:
                from ..utils.logging import get_logger

                get_logger("Herder").warning(
                    "quorum health degraded at seq %d: available=%s "
                    "silent_v_blocking=%s silent=%s", seq, available,
                    blocked, ",".join(rep["silent"]) or "-")
            self._warned_unavailable = True
        else:
            self._warned_unavailable = False
        return rep

    # -- budget-capped intersection scan -----------------------------------

    def check_intersection(self, seq: Optional[int] = None) -> dict:
        """One quorum-intersection scan under the monitor's (small)
        budget — 'unknown' past the budget, never a stall.  The admin
        endpoint's full-budget scan stays at quorum?intersection=true."""
        res = self.herder.check_quorum_intersection(
            max_calls=self.intersection_max_calls,
            max_seconds=self.intersection_timeout)
        rep = {
            "seq": seq if seq is not None
            else self.app.ledger_manager.last_closed_seq(),
            "ok": res.ok,
            "aborted": bool(res.aborted),
            "scanned": res.scanned,
            "scc_size": res.scc_size,
            "tier": res.tier,
        }
        if res.split:
            rep["split"] = [[n.hex()[:8] for n in sorted(side)]
                            for side in res.split]
        self.last_intersection = rep
        m = self.app.metrics
        m.counter("quorum.health.intersection-checks").inc()
        # 1 = enjoys intersection, 0 = SPLIT FOUND, -1 = unknown
        m.gauge("quorum.health.intersection").set(
            -1.0 if res.ok is None else (1.0 if res.ok else 0.0))
        if res.ok is False:
            from ..utils.logging import get_logger

            get_logger("Herder").warning(
                "quorum intersection VIOLATED: disjoint quorums %s",
                rep.get("split"))
        return rep

    # -- reporting (the quorum-health endpoint body) -----------------------

    def report(self) -> dict:
        qt = self.herder.quorum_tracker
        return {
            "enabled": self.enabled,
            "evaluations": self.evaluations,
            "intersection_period": self.intersection_period,
            "last": self.last,
            "intersection": self.last_intersection,
            "transitive": {
                "node_count": len(qt.quorum),
                "missing_qsets": [n.hex()[:8] for n in
                                  sorted(qt.nodes_missing_qsets())],
            },
        }
