"""TxSetFrame: the consensus value — an ordered transaction set + hash
(ref src/herder/TxSetFrame.cpp — SURVEY.md §2.2).

Build from the local queue (``make_from_transactions``: sort-by-hash, surge
pricing, per-tx validity) or from the wire (``make_from_wire``: structural
re-validation).  ``txs_in_apply_order`` is the deterministic shuffle that
keeps per-account sequence order (ref getTxsInApplyOrder :503).

TPU batch hook: ``prevalidate_signatures`` collects every signature in the
set and verifies them as ONE device batch (ops/ed25519_kernel), feeding
per-signature verdicts into the frames' SignatureCheckers — the admission
hot path P5 (SURVEY.md §2.17).
"""
from __future__ import annotations

import hashlib
from fractions import Fraction
from typing import Dict, List, Optional, Sequence, Tuple

from ..crypto import sha256
from ..ledger.ledger_txn import LedgerTxn
from ..transactions import TransactionFrame
from ..transactions.frame import TC
from ..xdr import types as T, xdr_sha256


class TxSetFrame:
    def __init__(self, network_id: bytes, previous_ledger_hash: bytes,
                 frames: Sequence[TransactionFrame]):
        self.network_id = network_id
        self.previous_ledger_hash = previous_ledger_hash
        # canonical order: sorted by full hash (ref sortTxsInHashOrder)
        self.frames = sorted(frames, key=lambda f: f.full_hash())
        self._hash: Optional[bytes] = None
        self._valid_cache: Dict[bytes, bool] = {}

    # -- construction ------------------------------------------------------

    @classmethod
    def make_from_transactions(cls, network_id: bytes, lcl_hash: bytes,
                               frames: Sequence[TransactionFrame],
                               ltx_root, max_size: int,
                               base_fee: int,
                               max_dex_ops: Optional[int] = None
                               ) -> "TxSetFrame":
        """Filter invalid txs, trim to max_size by fee rate (surge pricing),
        keep per-account seq continuity (ref makeFromTransactions :234).
        ``max_dex_ops`` adds the DEX lane's per-lane op limit (config
        MAX_DEX_TX_OPERATIONS; ref SurgePricingUtils.h lane config)."""
        # per-source continuity: keep the longest valid prefix per account
        by_source: Dict[bytes, List[TransactionFrame]] = {}
        for f in frames:
            by_source.setdefault(f.source_account_id(), []).append(f)
        valid: List[TransactionFrame] = []
        with LedgerTxn(ltx_root) as ltx:
            lcl_seq = ltx.header().ledgerSeq
            for source, fs in by_source.items():
                fs.sort(key=lambda f: f.seq_num())
                entry = ltx.load_account(source)
                seq = entry.data.value.seqNum if entry else None
                for f in fs:
                    if seq is None or f.seq_num() != seq + 1:
                        break
                    # skip the full re-check for frames the queue already
                    # validated against this very LCL (admission stamps
                    # checked_valid_lcl); state can't have moved since
                    if getattr(f, "checked_valid_lcl", None) != lcl_seq:
                        res = f.check_valid(ltx, current_seq=seq)
                        if not res.ok:
                            break
                    valid.append(f)
                    seq = f.seq_num()
            ltx.rollback()
        valid = surge_pricing_filter(valid, max_size,
                                     max_dex_ops=max_dex_ops)
        return cls(network_id, lcl_hash, valid)

    @classmethod
    def make_from_wire(cls, network_id: bytes, xdr_tx_set) -> "TxSetFrame":
        from ..transactions.frame import tx_frame_from_envelope

        frames = [tx_frame_from_envelope(network_id, env)
                  for env in xdr_tx_set.txs]
        return cls(network_id, xdr_tx_set.previousLedgerHash, frames)

    # -- identity ----------------------------------------------------------

    def to_xdr(self):
        return T.TransactionSet.make(
            previousLedgerHash=self.previous_ledger_hash,
            txs=[f.envelope for f in self.frames])

    def contents_hash(self) -> bytes:
        if self._hash is None:
            self._hash = xdr_sha256(T.TransactionSet, self.to_xdr())
        return self._hash

    def size(self) -> int:
        return len(self.frames)

    def size_op(self) -> int:
        return sum(f.num_operations() for f in self.frames)

    # -- validity (wire sets) ----------------------------------------------

    def check_valid(self, ltx_root, lcl_hash: bytes,
                    verify=None) -> bool:
        """ref TxSetFrame::checkValid :562 — prev-hash linkage, size cap,
        hash order, per-source seq continuity, per-tx checkValid.

        The result is cached per LCL hash: SCP re-validates the same value
        once per envelope (every nomination/ballot message carrying it),
        and ledger state — the only input besides the set itself — cannot
        change without the LCL hash changing.  Without this a 1000-tx
        close re-runs the full per-tx chain ~8x (measured r4 profile)."""
        if self.previous_ledger_hash != lcl_hash:
            return False
        if verify is not None:
            # a custom verifier must actually run: bypass the cache both
            # ways (don't read a verdict it didn't produce, don't publish
            # one keyed only by lcl_hash)
            return self._check_valid_uncached(ltx_root, lcl_hash, verify)
        cached = self._valid_cache.get(lcl_hash)
        if cached is not None:
            return cached
        ok = self._check_valid_uncached(ltx_root, lcl_hash, verify)
        self._valid_cache = {lcl_hash: ok}
        return ok

    def _check_valid_uncached(self, ltx_root, lcl_hash: bytes,
                              verify=None) -> bool:
        with LedgerTxn(ltx_root) as _hltx:
            max_ops = _hltx.header().maxTxSetSize
            _hltx.rollback()
        if self.size_op() > max_ops:
            return False  # oversized set: reject like the reference
        hashes = [f.full_hash() for f in self.frames]
        if hashes != sorted(hashes):
            return False
        by_source: Dict[bytes, List[TransactionFrame]] = {}
        for f in self.frames:
            by_source.setdefault(f.source_account_id(), []).append(f)
        with LedgerTxn(ltx_root) as ltx:
            ok = True
            for source, fs in sorted(by_source.items()):
                fs.sort(key=lambda f: f.seq_num())
                entry = ltx.load_account(source)
                if entry is None:
                    ok = False
                    break
                seq = entry.data.value.seqNum
                for f in fs:
                    if f.seq_num() != seq + 1:
                        ok = False
                        break
                    res = f.check_valid(ltx, current_seq=seq,
                                        verify=verify)
                    if not res.ok:
                        ok = False
                        break
                    seq = f.seq_num()
                if not ok:
                    break
            ltx.rollback()
        return ok

    # -- apply order -------------------------------------------------------

    def txs_in_apply_order(self) -> List[TransactionFrame]:
        """Deterministic shuffle preserving per-account seq order: slot
        positions come from sha256(lcl || txhash) order; each account's txs
        fill its own positions in sequence order (ref ApplyTxSorter)."""
        def shuffle_key(f: TransactionFrame) -> bytes:
            return sha256(self.previous_ledger_hash + f.full_hash())

        shuffled = sorted(self.frames, key=shuffle_key)
        by_source: Dict[bytes, List[TransactionFrame]] = {}
        for f in self.frames:
            by_source.setdefault(f.source_account_id(), []).append(f)
        for _, fs in sorted(by_source.items()):
            fs.sort(key=lambda f: f.seq_num())
        iters = {src: iter(fs) for src, fs in by_source.items()}
        return [next(iters[f.source_account_id()]) for f in shuffled]

    # -- TPU batch pre-verification ----------------------------------------

    def collect_signature_batch(self) -> Tuple:
        """Gather (pubkey, sig, payload-hash) triples for every
        (signature x candidate-signer) pair whose hint matches — the batch
        the device kernel verifies in one shot."""
        import numpy as np

        from ..transactions.signature_checker import signature_hint

        triples = []
        index = []
        for fi, f in enumerate(self.frames):
            # a fee-bump contributes two signed payloads: the outer
            # envelope (fee source sigs over the fee-bump hash) and the
            # inner tx (its own hash + sigs)
            payloads = [(f.full_hash(), f.signatures)]
            inner = getattr(f, "inner_tx", None)
            if inner is not None:
                payloads.append((inner.full_hash(), inner.signatures))
            src = f.source_account_id()
            # candidate signer keys: tx source + fee source + op sources
            # (master keys); additional account signers resolve at check
            # time via cache misses falling back to CPU verify
            keys = {src}
            fee_src = getattr(f, "fee_source_id", None)
            if fee_src is not None:
                keys.add(fee_src())
            for opf in f.op_frames:
                keys.add(opf.source_account_id())
            for h, sigs in payloads:
                for i, ds in enumerate(sigs):
                    for pub in sorted(keys):
                        if ds.hint == signature_hint(pub):
                            triples.append((pub, ds.signature, h))
                            index.append((fi, i, pub))
        return triples, index

    def prevalidate_signatures(self, use_device: bool = True, tracer=None
                               ) -> Dict[Tuple[bytes, bytes, bytes], bool]:
        """Verify the whole set's signatures as one batch; returns a verdict
        cache keyed by (pubkey, signature, msg) for SignatureChecker.

        ``tracer`` (utils/tracing) splits the device leg into a dispatch
        span (batch assembly + async JAX dispatch) and a host-wait span
        (blocking on the device result) — the host-Python-vs-kernel-time
        attribution ROADMAP item 7 asks about."""
        if tracer is None:
            from ..utils.tracing import NULL_TRACER as tracer
        triples, _ = self.collect_signature_batch()
        if not triples:
            return {}
        verdicts: Dict[Tuple[bytes, bytes, bytes], bool] = {}
        if use_device:
            import os

            import numpy as np

            # kernel tier: the XLA kernel lowers on every backend and is
            # the safe default; CRYPTO_KERNEL=pallas opts the node into
            # the Pallas TPU kernel (bench.py probes pallas itself).
            # Kernel CHOICE is env-driven but both tiers return
            # bit-identical verdicts, so this read is consensus-neutral.
            # detlint: allow(det-wallclock)
            if os.environ.get("CRYPTO_KERNEL", "xla") == "pallas":
                from ..ops.ed25519_pallas import verify_batch
            else:
                from ..ops.ed25519_kernel import verify_batch

            from ..utils.device import pad_signature_batch

            with tracer.span("crypto.sigbatch.dispatch",
                             n_sigs=len(triples)):
                n = len(triples)
                pk = np.frombuffer(
                    b"".join(t[0] for t in triples),
                    np.uint8).reshape(n, 32)
                sg = np.frombuffer(
                    b"".join(t[1].ljust(64, b"\x00") for t in triples),
                    np.uint8).reshape(n, 64)
                mg = np.frombuffer(
                    b"".join(t[2] for t in triples),
                    np.uint8).reshape(n, 32)
                # pad to a fixed batch bucket (repeating real rows) so the
                # device sees a small closed set of shapes — per-close
                # batch sizes vary freely and would otherwise force a
                # recompile every time a new size shows up
                padded = pad_signature_batch(n)
                if padded != n:
                    idx = np.arange(padded) % n
                    pk, sg, mg = pk[idx], sg[idx], mg[idx]
                # JAX dispatch is async: this returns as soon as the
                # computation is enqueued on the device
                pending = verify_batch(pk, sg, mg)
            with tracer.span("crypto.sigbatch.host_wait"):
                # materializing blocks until the device result lands —
                # the dispatch/host-wait split is the JAX-overhead vs.
                # kernel-time attribution
                ok = np.asarray(pending)[:n]
            for t, v in zip(triples, ok):
                verdicts[(t[0], t[1], t[2])] = bool(v)
        else:
            from ..crypto import verify_sig

            with tracer.span("crypto.sigbatch.cpu",
                             n_sigs=len(triples)):
                for pub, sig, msg in triples:
                    verdicts[(pub, sig, msg)] = verify_sig(pub, sig, msg)
        return verdicts

    def make_cached_verify(self, verdicts):
        """verify callable for SignatureChecker: batch verdicts first,
        CPU fallback for pairs outside the batch (e.g. extra signers)."""
        from ..crypto import verify_sig

        def verify(pub: bytes, sig: bytes, msg: bytes) -> bool:
            key = (pub, sig, msg)
            if key in verdicts:
                return verdicts[key]
            return verify_sig(pub, sig, msg)

        return verify


#: op types riding the DEX lane (offers + path payments — everything
#: that can cross the order book; ref TxSetUtils hasDexOperations)
_DEX_OP_TYPES = frozenset((
    T.OperationType.MANAGE_SELL_OFFER,
    T.OperationType.MANAGE_BUY_OFFER,
    T.OperationType.CREATE_PASSIVE_SELL_OFFER,
    T.OperationType.PATH_PAYMENT_STRICT_RECEIVE,
    T.OperationType.PATH_PAYMENT_STRICT_SEND,
))


def is_dex_tx(f: TransactionFrame) -> bool:
    return any(opf.op.body.type in _DEX_OP_TYPES for opf in f.op_frames)


def surge_pricing_filter(frames: List[TransactionFrame],
                         max_ops: int,
                         max_dex_ops: Optional[int] = None
                         ) -> List[TransactionFrame]:
    """Trim to the ledger's op capacity by fee-per-op rate, highest first
    (ref applySurgePricing :1150 / SurgePricingUtils.h priority queue).
    Per-account seq chains are kept intact: dropping a tx drops its
    successors.

    Lanes (ref SurgePricingUtils.h DexLimitingLaneConfig): every tx
    counts against the generic ``max_ops`` capacity; txs containing DEX
    ops ALSO count against the ``max_dex_ops`` lane when set, so order-
    book traffic cannot crowd payments out of the whole ledger."""
    total_ops = sum(f.num_operations() for f in frames)
    # DEX classification scans every op of a frame — compute it once,
    # not per prefix sum inside the trim loop (same O(n^2) shape the
    # chain-position map below fixes)
    dex = ({id(f): is_dex_tx(f) for f in frames}
           if max_dex_ops is not None else {})
    dex_total = (sum(f.num_operations() for f in frames if dex[id(f)])
                 if max_dex_ops is not None else 0)
    if total_ops <= max_ops and \
            (max_dex_ops is None or dex_total <= max_dex_ops):
        return list(frames)

    def rate(f: TransactionFrame) -> Tuple:
        # fee-per-op as an EXACT rational (float division could tie or
        # flip near-equal rates after rounding — consensus-visible
        # ordering must be exact int math); tie-break by hash
        return (Fraction(-f.fee_bid(), max(1, f.num_operations())),
                f.full_hash())

    by_source: Dict[bytes, List[TransactionFrame]] = {}
    for f in frames:
        by_source.setdefault(f.source_account_id(), []).append(f)
    # chain position by identity, precomputed once — chain.index(f)
    # inside the trim loop was O(n^2) on long same-source chains
    chain_pos: Dict[int, int] = {}
    for _, fs in sorted(by_source.items()):
        fs.sort(key=lambda f: f.seq_num())
        for pos, c in enumerate(fs):
            chain_pos[id(c)] = pos

    kept: set = set()
    kept_order: List[TransactionFrame] = []
    ops = 0
    dex_ops = 0
    dropped_sources = set()
    for f in sorted(frames, key=rate):
        src = f.source_account_id()
        if src in dropped_sources or id(f) in kept:
            continue
        chain = by_source[src]
        pos = chain_pos[id(f)]
        # a high-fee successor pulls its not-yet-kept (cheaper)
        # predecessors in with it — seq chains stay intact
        prefix = [c for c in chain[:pos + 1] if id(c) not in kept]
        prefix_ops = sum(c.num_operations() for c in prefix)
        prefix_dex = (sum(c.num_operations() for c in prefix
                          if dex[id(c)])
                      if max_dex_ops is not None else 0)
        if ops + prefix_ops > max_ops or \
                (max_dex_ops is not None
                 and dex_ops + prefix_dex > max_dex_ops):
            dropped_sources.add(src)
            continue
        for c in prefix:
            kept.add(id(c))
            kept_order.append(c)
        ops += prefix_ops
        dex_ops += prefix_dex
    return kept_order
