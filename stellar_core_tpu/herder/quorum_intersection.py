"""QuorumIntersectionChecker: does every pair of quorums in the network
intersect?  (ref src/herder/QuorumIntersectionChecker.h:16,
QuorumIntersectionCheckerImpl.cpp — QBitSet graph :373, Tarjan SCC, the
MinQuorumEnumerator powerset scan :124/:391/:407.)

TPU-first redesign (BASELINE config #3): instead of the reference's
recursive single-subset scan over BitSets, candidate subsets are contracted
to their maximal quorums in device-sized batches
(ops/quorum.contract_batch — a boolean-matmul fixpoint).  Disjoint quorums
exist iff some subset S contracts to a non-empty quorum Q whose complement
also contracts non-empty: every quorum is its own contraction, so scanning
all subsets of the main SCC is exhaustive.

The subset space is 2^|SCC|; the scan caps at MAX_SCAN_NODES (the
reference similarly treats the checker as an offline/background tool with
an interrupt flag for big networks).
"""
from __future__ import annotations

from typing import Dict, List, Optional, Set, Tuple

import numpy as np

from ..scp import local_node as LN

MAX_SCAN_NODES = 20  # 2^20 subsets ~ 1M contractions, chunked on device
CHUNK = 1 << 14


class QuorumIntersectionResult:
    def __init__(self, ok: bool, split: Optional[Tuple[Set[bytes],
                                                       Set[bytes]]] = None,
                 scanned: int = 0, scc_size: int = 0):
        self.ok = ok
        self.split = split
        self.scanned = scanned
        self.scc_size = scc_size


def tarjan_scc(nodes: List[bytes],
               edges: Dict[bytes, Set[bytes]]) -> List[List[bytes]]:
    """Tarjan's strongly-connected components, iterative
    (ref src/util/TarjanSCCCalculator.h)."""
    index: Dict[bytes, int] = {}
    lowlink: Dict[bytes, int] = {}
    on_stack: Set[bytes] = set()
    stack: List[bytes] = []
    sccs: List[List[bytes]] = []
    counter = [0]

    for start in nodes:
        if start in index:
            continue
        work = [(start, iter(sorted(edges.get(start, ()))))]
        index[start] = lowlink[start] = counter[0]
        counter[0] += 1
        stack.append(start)
        on_stack.add(start)
        while work:
            v, it = work[-1]
            advanced = False
            for w in it:
                if w not in index:
                    index[w] = lowlink[w] = counter[0]
                    counter[0] += 1
                    stack.append(w)
                    on_stack.add(w)
                    work.append((w, iter(sorted(edges.get(w, ())))))
                    advanced = True
                    break
                elif w in on_stack:
                    lowlink[v] = min(lowlink[v], index[w])
            if advanced:
                continue
            work.pop()
            if work:
                parent = work[-1][0]
                lowlink[parent] = min(lowlink[parent], lowlink[v])
            if lowlink[v] == index[v]:
                comp = []
                while True:
                    w = stack.pop()
                    on_stack.discard(w)
                    comp.append(w)
                    if w == v:
                        break
                sccs.append(comp)
    return sccs


def check_quorum_intersection(qmap: Dict[bytes, object],
                              use_device: bool = True
                              ) -> QuorumIntersectionResult:
    """qmap: node id -> XDR SCPQuorumSet.  Nodes with unknown (None) qsets
    are excluded, like the reference's missing-qset handling."""
    qmap = {n: q for n, q in qmap.items() if q is not None}
    nodes = sorted(qmap)
    if not nodes:
        return QuorumIntersectionResult(True)

    # dependency graph: n -> nodes its qset references (ref buildGraph)
    edges = {n: (LN.qset_nodes(q) & set(nodes)) for n, q in qmap.items()}
    sccs = tarjan_scc(nodes, edges)
    # quorums in two different SCCs are disjoint by construction — the
    # reference fails fast in that case and otherwise restricts the scan
    # to the single quorum-bearing SCC (ref
    # networkEnjoysQuorumIntersection checking exactly one SCC has
    # quorums)
    quorum_sccs = []
    for comp in sorted(sccs, key=len, reverse=True):
        q = _contract_host(set(comp), qmap)
        if q:
            quorum_sccs.append((sorted(comp), q))
    if not quorum_sccs:
        return QuorumIntersectionResult(True, scc_size=0)
    if len(quorum_sccs) > 1:
        return QuorumIntersectionResult(
            False, (quorum_sccs[0][1], quorum_sccs[1][1]),
            0, len(quorum_sccs[0][0]))
    main_scc = quorum_sccs[0][0]
    if len(main_scc) > MAX_SCAN_NODES:
        raise ValueError(
            f"quorum intersection scan capped at {MAX_SCAN_NODES} nodes "
            f"(SCC has {len(main_scc)})")

    n = len(main_scc)
    universe = set(main_scc)
    plains = []
    for node in main_scc:
        p = LN.qset_to_plain(qmap[node])
        if p is None:
            use_device = False  # >2-level qsets: host contraction only
            break
        # restrict memberships to the SCC (outside nodes never vote here)
        thr, vals, inners = p
        plains.append((thr, [v for v in vals if v in universe],
                       [(t, [v for v in vs if v in universe])
                        for t, vs in inners]))

    scanned = 0
    if use_device:
        import jax.numpy as jnp

        from ..ops.quorum import build_qset_tensor, contract_batch

        qsets = build_qset_tensor(plains, main_scc)
        total = 1 << n
        for base in range(0, total, CHUNK):
            count = min(CHUNK, total - base)
            idx = np.arange(base, base + count, dtype=np.uint32)
            members = ((idx[:, None] >> np.arange(n)) & 1).astype(np.bool_)
            contracted = np.asarray(
                contract_batch(qsets, jnp.asarray(members)))
            scanned += count
            nonempty = contracted.any(axis=1)
            if not nonempty.any():
                continue
            # complements of the found quorums, contracted in turn
            quorums = np.unique(contracted[nonempty], axis=0)
            comp = ~quorums
            comp_contracted = np.asarray(
                contract_batch(qsets, jnp.asarray(comp)))
            bad = comp_contracted.any(axis=1)
            if bad.any():
                i = int(np.argmax(bad))
                q1 = {main_scc[j] for j in range(n) if quorums[i, j]}
                q2 = {main_scc[j] for j in range(n)
                      if comp_contracted[i, j]}
                return QuorumIntersectionResult(
                    False, (q1, q2), scanned, n)
        return QuorumIntersectionResult(True, None, scanned, n)

    # host path (exact, any nesting depth)
    total = 1 << n
    for mask in range(total):
        s = {main_scc[j] for j in range(n) if (mask >> j) & 1}
        q1 = _contract_host(s, qmap)
        scanned += 1
        if not q1:
            continue
        q2 = _contract_host(universe - q1, qmap)
        if q2:
            return QuorumIntersectionResult(False, (q1, q2), scanned, n)
    return QuorumIntersectionResult(True, None, scanned, n)


def _contract_host(members: Set[bytes],
                   qmap: Dict[bytes, object]) -> Set[bytes]:
    """Host contraction to the maximal quorum inside ``members``
    (ref contractToMaximalQuorum)."""
    cur = set(members)
    while True:
        nxt = {n for n in cur
               if n in qmap and LN.is_quorum_slice(qmap[n], cur)}
        if nxt == cur:
            return cur
        cur = nxt
